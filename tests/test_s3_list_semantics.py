"""The reference's full list-semantics corpus, ported onto the real API
server (ref src/garage/tests/s3/list.rs, all 615 LoC of pagination edge
cases): ListObjectsV2 (continuation × delimiter × prefix × start-after),
ListObjectsV1 (marker semantics, repeated common prefixes per AWS spec),
and ListMultipartUploads (key+upload-id marker pairs × delimiter).

VERDICT r3 #6: api/s3/list.py implements all four endpoints but only a
handful of edge cases were tested; this file is the matrix."""

import xml.etree.ElementTree as ET

import pytest

from test_s3_api import make_api_cluster, stop_all
from garage_tpu.api.signature import uri_encode

pytestmark = pytest.mark.asyncio

# ref list.rs:3-4
KEYS = ["a", "a/a", "a/b", "a/c", "a/d/a", "a/é", "b", "c"]
KEYS_MULTIPART = ["a", "a", "c", "c/a", "c/b"]

NS = "{http://s3.amazonaws.com/doc/2006-03-01/}"


def _strip_ns(root):
    for el in root.iter():
        if el.tag.startswith("{"):
            el.tag = el.tag.split("}", 1)[1]
    return root


def parse_list(body: bytes) -> dict:
    """Common fields of v1/v2/multipart list responses."""
    root = _strip_ns(ET.fromstring(body))
    out = {
        "keys": [c.findtext("Key") for c in root.findall("Contents")],
        "prefixes": [p.findtext("Prefix")
                     for p in root.findall("CommonPrefixes")],
        "uploads": [(u.findtext("Key"), u.findtext("UploadId"))
                    for u in root.findall("Upload")],
        "truncated": root.findtext("IsTruncated") == "true",
        "next_token": root.findtext("NextContinuationToken"),
        "next_marker": root.findtext("NextMarker"),
        "next_key_marker": root.findtext("NextKeyMarker"),
        "next_upload_id_marker": root.findtext("NextUploadIdMarker"),
    }
    return out


async def _fill_bucket(client, bucket):
    st, _h, _b = await client.req("PUT", f"/{bucket}")
    assert st == 200
    for k in KEYS:
        st, _h, _b = await client.req(
            "PUT", f"/{bucket}/{uri_encode(k, encode_slash=False)}",
            body=b"x")
        assert st == 200, k


async def _list_v2(client, bucket, **q):
    query = [(k.replace("_", "-"), v) for k, v in q.items()
             if v is not None]
    query.insert(0, ("list-type", "2"))
    st, _h, body = await client.req("GET", f"/{bucket}", query=query)
    assert st == 200, body[:300]
    return parse_list(body)


async def _list_v1(client, bucket, **q):
    query = [(k.replace("_", "-"), v) for k, v in q.items()
             if v is not None]
    st, _h, body = await client.req("GET", f"/{bucket}", query=query)
    assert st == 200, body[:300]
    return parse_list(body)


async def test_listobjectsv2_matrix(tmp_path):
    """ref list.rs:6-220 test_listobjectsv2."""
    garages, server, client, _key = await make_api_cluster(tmp_path)
    try:
        await _fill_bucket(client, "lv2")

        r = await _list_v2(client, "lv2")
        assert len(r["keys"]) == 8 and not r["prefixes"]

        # max-keys=2 truncates with a continuation token
        r = await _list_v2(client, "lv2", max_keys="2")
        assert len(r["keys"]) == 2 and not r["prefixes"]
        assert r["truncated"] and r["next_token"]

        # page through everything one key at a time
        cnt, nxt = 0, None
        for i in range(len(KEYS)):
            r = await _list_v2(client, "lv2", max_keys="1",
                               continuation_token=nxt)
            cnt += 1
            nxt = r["next_token"]
            assert len(r["keys"]) == 1 and not r["prefixes"]
            if i != len(KEYS) - 1:
                assert nxt
        assert cnt == len(KEYS)
        assert nxt is None

        # delimiter folds a/* into one common prefix
        r = await _list_v2(client, "lv2", delimiter="/")
        assert len(r["keys"]) == 3 and len(r["prefixes"]) == 1

        # delimiter × pagination: each page has exactly one key OR one
        # prefix; totals must match (ref list.rs:104-132)
        cnt_key = cnt_pfx = 0
        nxt = None
        for _ in range(len(KEYS)):
            r = await _list_v2(client, "lv2", delimiter="/", max_keys="1",
                               continuation_token=nxt)
            nxt = r["next_token"]
            if len(r["keys"]) == 1 and not r["prefixes"]:
                cnt_key += 1
            elif len(r["prefixes"]) == 1 and not r["keys"]:
                cnt_pfx += 1
            else:
                raise AssertionError((r["keys"], r["prefixes"]))
            if nxt is None:
                break
        assert cnt_key == 3 and cnt_pfx == 1

        # prefix alone
        r = await _list_v2(client, "lv2", prefix="a/")
        assert len(r["keys"]) == 5 and not r["prefixes"]

        # prefix + delimiter
        r = await _list_v2(client, "lv2", prefix="a/", delimiter="/")
        assert len(r["keys"]) == 4 and len(r["prefixes"]) == 1

        # prefix + delimiter + max-keys → exactly "a/a"
        r = await _list_v2(client, "lv2", prefix="a/", delimiter="/",
                           max_keys="1")
        assert r["keys"] == ["a/a"] and not r["prefixes"]

        # start-after before all keys → everything
        r = await _list_v2(client, "lv2", start_after="Z")
        assert len(r["keys"]) == 8 and not r["prefixes"]

        # start-after at the last key → empty
        r = await _list_v2(client, "lv2", start_after="c")
        assert not r["keys"] and not r["prefixes"]
    finally:
        await stop_all(garages, server)


async def test_listobjectsv1_matrix(tmp_path):
    """ref list.rs:222-433 test_listobjectsv1."""
    garages, server, client, _key = await make_api_cluster(tmp_path)
    try:
        await _fill_bucket(client, "lv1")

        r = await _list_v1(client, "lv1")
        assert len(r["keys"]) == 8 and not r["prefixes"]

        r = await _list_v1(client, "lv1", max_keys="2")
        assert len(r["keys"]) == 2 and not r["prefixes"]
        assert r["truncated"] and r["next_marker"]

        # pagination by marker
        cnt, nxt = 0, None
        for i in range(len(KEYS)):
            r = await _list_v1(client, "lv1", max_keys="1", marker=nxt)
            cnt += 1
            nxt = r["next_marker"]
            assert len(r["keys"]) == 1 and not r["prefixes"]
            if i != len(KEYS) - 1:
                assert nxt
        assert cnt == len(KEYS)

        r = await _list_v1(client, "lv1", delimiter="/")
        assert len(r["keys"]) == 3 and len(r["prefixes"]) == 1

        # delimiter × pagination.  The reference has no whole-prefix
        # skip on v1 and re-emits "a/" once per element inside it (5×,
        # clients dedup; ref list.rs:306-341 and its comment).  This
        # implementation rolls the prefix up ONCE and continues after it
        # — real AWS v1 semantics (NextMarker = the rolled-up prefix) —
        # so the invariants are: every page is exactly one key or one
        # prefix, all 3 top-level keys arrive, the a/ prefix arrives at
        # least once and dedups to exactly {a/}, and pagination
        # terminates.
        cnt_key = cnt_pfx = 0
        seen_pfx = set()
        nxt = None
        for _ in range(len(KEYS)):
            r = await _list_v1(client, "lv1", delimiter="/", max_keys="1",
                               marker=nxt)
            nxt = r["next_marker"]
            if len(r["keys"]) == 1 and not r["prefixes"]:
                cnt_key += 1
            elif len(r["prefixes"]) == 1 and not r["keys"]:
                cnt_pfx += 1
                seen_pfx.add(r["prefixes"][0])
            else:
                raise AssertionError((r["keys"], r["prefixes"]))
            if nxt is None:
                break
        assert cnt_key == 3 and cnt_pfx >= 1
        assert seen_pfx == {"a/"}

        r = await _list_v1(client, "lv1", prefix="a/")
        assert len(r["keys"]) == 5 and not r["prefixes"]

        r = await _list_v1(client, "lv1", prefix="a/", delimiter="/")
        assert len(r["keys"]) == 4 and len(r["prefixes"]) == 1

        r = await _list_v1(client, "lv1", prefix="a/", delimiter="/",
                           max_keys="1")
        assert r["keys"] == ["a/a"] and not r["prefixes"]

        r = await _list_v1(client, "lv1", marker="Z")
        assert len(r["keys"]) == 8 and not r["prefixes"]

        r = await _list_v1(client, "lv1", marker="c")
        assert not r["keys"] and not r["prefixes"]
    finally:
        await stop_all(garages, server)


async def test_listmultipart_matrix(tmp_path):
    """ref list.rs:435-615 test_listmultipart."""
    garages, server, client, _key = await make_api_cluster(tmp_path)
    try:
        st, _h, _b = await client.req("PUT", "/lmp")
        assert st == 200
        for k in KEYS_MULTIPART:
            st, _h, body = await client.req(
                "POST", f"/lmp/{uri_encode(k, encode_slash=False)}",
                query=[("uploads", "")])
            assert st == 200, body[:300]

        async def list_mpu(**q):
            query = [("uploads", "")] + [
                (k.replace("_", "-"), v) for k, v in q.items()
                if v is not None]
            st, _h, body = await client.req("GET", "/lmp", query=query)
            assert st == 200, body[:300]
            return parse_list(body)

        r = await list_mpu()
        assert len(r["uploads"]) == 5 and not r["prefixes"]

        # pagination by (key-marker, upload-id-marker)
        nxt = upnxt = None
        for i in range(len(KEYS_MULTIPART)):
            r = await list_mpu(max_uploads="1", key_marker=nxt,
                               upload_id_marker=upnxt)
            nxt = r["next_key_marker"]
            upnxt = r["next_upload_id_marker"]
            assert len(r["uploads"]) == 1 and not r["prefixes"]
            if i != len(KEYS_MULTIPART) - 1:
                assert nxt
        # delimiter folds c/* into one prefix
        r = await list_mpu(delimiter="/")
        assert len(r["uploads"]) == 3 and len(r["prefixes"]) == 1

        # delimiter × pagination: each page is one upload or one prefix
        nxt = upnxt = None
        upcnt = pfxcnt = loopcnt = 0
        while loopcnt < len(KEYS_MULTIPART):
            r = await list_mpu(delimiter="/", max_uploads="1",
                               key_marker=nxt, upload_id_marker=upnxt)
            nxt = r["next_key_marker"]
            upnxt = r["next_upload_id_marker"]
            loopcnt += 1
            upcnt += len(r["uploads"])
            pfxcnt += len(r["prefixes"])
            if nxt is None:
                break
        assert upcnt + pfxcnt == loopcnt
        assert upcnt == 3 and pfxcnt == 1

        r = await list_mpu(prefix="c")
        assert len(r["uploads"]) == 3 and not r["prefixes"]

        r = await list_mpu(prefix="c", delimiter="/")
        assert len(r["uploads"]) == 1 and len(r["prefixes"]) == 1

        r = await list_mpu(prefix="c", delimiter="/", max_uploads="1")
        assert len(r["uploads"]) == 1 and not r["prefixes"]

        # marker before / after everything
        r = await list_mpu(key_marker="ZZZZZ")
        assert len(r["uploads"]) == 5 and not r["prefixes"]

        r = await list_mpu(key_marker="d")
        assert not r["uploads"] and not r["prefixes"]
    finally:
        await stop_all(garages, server)
