"""ISSUE-7 acceptance: the 24-node / 4-zone SimCluster chaos drive.

One cluster (24 storage nodes round-robined over 4 zones + 1 S3
gateway, FaultyLink on every directed dial path) runs the three
cluster-scale drills back to back — exactly the code scripts/chaos.py
--phases zone_blackhole,rolling,zone_drain executes:

  1. one full zone blackholed: reads served local-zone-first from
     survivors, boundary breakers open then recover, zero client errors
  2. a one-zone-at-a-time rolling restart with a bumped version tag
     under live traffic (mixed versions visible in the handshake map)
  3. a zone drain rebalance under sustained PUT/GET load: the mover
     finishes on every node (rebalance_partitions_done == total), and
     every acked object reads back bit-identical even after the drained
     zone is partitioned away

Marked slow + cluster: ~25 in-process daemons and ~600 fault links are
deliberately NOT tier-1 material.  Run with:

    JAX_PLATFORMS=cpu python -m pytest tests/test_cluster_scale.py -m cluster
"""

import pytest

from garage_tpu.utils.promlint import lint_exposition

pytestmark = [pytest.mark.asyncio, pytest.mark.slow, pytest.mark.cluster]


async def test_24_node_4_zone_drills(tmp_path):
    import aiohttp

    from garage_tpu.testing.sim_cluster import (
        SimCluster,
        TrafficDriver,
        rolling_restart_drill,
        zone_blackhole_drill,
        zone_drain_drill,
    )

    cluster = SimCluster(tmp_path, n_storage=24, n_zones=4)
    await cluster.start()
    try:
        async with aiohttp.ClientSession() as session:
            # --- 1. one full zone dark -------------------------------
            t = TrafficDriver(cluster, session, bucket="dark")
            await t.make_bucket()
            st = await zone_blackhole_drill(cluster, t, secs=6.0,
                                            zone="z2")
            assert st["errors"] == 0, st
            assert st["breaker_opened"], st
            assert st["breaker_states_after"] == ["closed"], st
            assert st["puts"] > 0 and st["gets"] > 0, st

            # --- 2. rolling restart, one zone at a time --------------
            t = TrafficDriver(cluster, session, bucket="roll")
            await t.make_bucket()
            st = await rolling_restart_drill(cluster, t, secs=10.0)
            assert st["errors"] == 0, st
            assert st["mixed_versions_seen"], st
            assert st["verify_mismatches"] == 0, st
            assert len(st["zones"]) == 4, st

            # --- 3. zone drain under live load -----------------------
            t = TrafficDriver(cluster, session, bucket="drain")
            await t.make_bucket()
            st = await zone_drain_drill(cluster, t, secs=6.0, zone="z4",
                                        settle_secs=90.0)
            assert st["errors"] == 0, st
            assert st["rebalance_complete"], st
            assert st["verify_mismatches_zone_dark"] == 0, st
            assert st["drained_metric_seen"], st

            # the whole drive left the gateway's exposition lint-clean
            body = cluster.garages[0].system.metrics.render()
            assert "rebalance_partitions_done" in body
            assert lint_exposition(body) == []
    finally:
        await cluster.stop()
