"""Multi-chip codec sharding on the virtual 8-device CPU mesh
(xla_force_host_platform_device_count, see conftest.py) — validates the
mesh-sharded verify/encode path the driver also exercises via
__graft_entry__.dryrun_multichip."""

import hashlib

import jax
import numpy as np
import pytest
from jax.sharding import Mesh

from garage_tpu.ops import gf256
from garage_tpu.ops.tpu_codec import sharded_fns


@pytest.fixture(scope="module")
def mesh():
    devs = jax.devices()
    if len(devs) < 2:
        pytest.skip("need multi-device (virtual) platform")
    return Mesh(np.array(devs), ("data",))


def test_sharded_verify(mesh):
    bsz, nbytes = 16, 256
    rng = np.random.default_rng(0)
    data = rng.integers(0, 256, (bsz, nbytes), dtype=np.uint8)
    lengths = np.full((bsz,), nbytes, dtype=np.int32)
    expected = np.stack([
        np.frombuffer(
            hashlib.blake2s(data[i].tobytes(), digest_size=32).digest(), dtype="<u4"
        )
        for i in range(bsz)
    ]).astype(np.uint32)
    expected[3] ^= 1  # corrupt one expectation
    fns = sharded_fns(mesh)
    h, ok, bad = fns["verify"](data, lengths, expected)
    ok = np.asarray(ok)
    assert ok.sum() == bsz - 1 and not ok[3]
    assert int(bad) == 1


def test_sharded_encode_matches_numpy(mesh):
    k, m, s = 4, 2, 128
    rng = np.random.default_rng(1)
    data = rng.integers(0, 256, (16, k, s), dtype=np.uint8)
    pm = gf256.rs_parity_matrix(k, m)
    w = np.asarray(gf256.bitmatrix_of_gf_matrix(pm), dtype=np.int8)
    fns = sharded_fns(mesh)
    out = np.asarray(fns["rs_encode"](data, w))
    assert np.array_equal(out, gf256.gf_matmul_blocks(pm, data))


def test_codec_shard_mesh_from_config(mesh):
    """codec.shard_mesh wires a device mesh into the TpuCodec itself."""
    import hashlib as _h

    from garage_tpu.utils.config import config_from_dict

    cfg = config_from_dict({"codec": {"backend": "tpu", "shard_mesh": 8}})
    codec = cfg.codec.make(cfg.compression_level)
    assert codec.mesh is not None and codec.mesh.size == 8
    blocks = [bytes([i]) * (100 + i) for i in range(5)]  # odd batch, padded
    hashes = codec.batch_hash(blocks)
    assert [bytes(x) for x in hashes] == [
        _h.blake2s(b, digest_size=32).digest() for b in blocks
    ]
    assert codec.batch_verify(blocks, hashes).all()
    rng = np.random.default_rng(3)
    data = rng.integers(0, 256, (5, 8, 64), dtype=np.uint8)  # 5 % 8 != 0
    parity = codec.rs_encode(data)
    assert parity.shape == (5, 4, 64)
    from garage_tpu.ops import make_codec

    cpu = make_codec("cpu")
    assert np.array_equal(parity, cpu.rs_encode(data))


async def test_daemon_scrub_end_to_end_on_sharded_codec(tmp_path):
    """VERDICT r3 #4: codec.shard_mesh through the PRODUCT path, not the
    codec.  A full Garage daemon configured with backend="tpu" +
    shard_mesh=8 runs its real ScrubWorker over the virtual 8-device CPU
    mesh: the fused verify rides the mesh-jitted executable (corruption
    counts psum-reduced across devices), planted corruptions are found
    exactly, the sidecar parity written by the sharded pass reconstructs
    a lost block bit-identically, and the parity bytes equal the CPU
    codec's RS encode of the same codeword."""
    import asyncio
    import os

    from garage_tpu.block.repair import ScrubWorker
    from garage_tpu.model import Garage
    from garage_tpu.ops.codec import CodecParams
    from garage_tpu.ops.cpu_codec import CpuCodec
    from garage_tpu.rpc.layout import ClusterLayout, NodeRole
    from garage_tpu.utils.config import config_from_dict
    from garage_tpu.utils.data import Hash, blake2s_sum

    g = Garage(config_from_dict({
        "metadata_dir": str(tmp_path / "meta"),
        "data_dir": str(tmp_path / "data"),
        "replication_mode": "none",
        "rpc_bind_addr": "127.0.0.1:0",
        "rpc_secret": "shard-scrub",
        "db_engine": "memory",
        "bootstrap_peers": [],
        "codec": {
            "backend": "tpu", "shard_mesh": 8,
            "rs_data": 4, "rs_parity": 2,
            "store_parity": True, "batch_blocks": 16,
        },
    }))
    try:
        await g.system.netapp.listen("127.0.0.1:0")
        lay = g.system.layout
        lay.stage_role(bytes(g.system.id), NodeRole("dc1", 1000))
        lay.apply_staged_changes()
        g.system.layout = ClusterLayout.decode(lay.encode())
        g.system._rebuild_ring()

        codec = g.block_manager.codec
        # the daemon config actually sharded the codec over the mesh
        assert codec.mesh is not None and codec.mesh.devices.size == 8

        from garage_tpu.block.block import DataBlock

        # small blocks: XLA CPU compile time explodes on big graphs
        datas = [os.urandom(6_000 + 37 * i) for i in range(24)]
        hashes = [blake2s_sum(d) for d in datas]
        for h, d in zip(hashes, datas):
            await g.block_manager.write_block(h, DataBlock.plain(d))

        # silent corruption on 3 blocks
        for h in hashes[:3]:
            path, _ = g.block_manager.find_block(h)
            with open(path, "r+b") as f:
                f.seek(16)
                f.write(b"\xde\xad\xbe\xef")

        scrub = ScrubWorker(g.block_manager)
        scrub.send_command("start")
        while (await scrub.work()).name in ("BUSY", "THROTTLED"):
            pass
        # psum-reduced corruption count, surfaced by the product worker
        assert scrub.state.corruptions == 3
        assert g.block_manager.resync.queue_len() >= 3

        # the sharded pass wrote RS(4,2) sidecars for the clean blocks:
        # lose one member entirely and reconstruct it locally
        store = g.block_manager.parity_store
        assert store is not None and store.stats()["indexed_blocks"] > 0
        victim = None
        for h in hashes[3:]:
            if not store.coverage(h):
                continue
            # the victim's codeword must have enough TRUSTWORTHY pieces
            # with the victim gone: a codeword that also contains
            # quarantined (planted-corruption) members can legitimately
            # fall under k survivors — which manifest a block lands in
            # is decided by the per-run random hashes, so picking such a
            # victim made this assert a coin flip, not a signal
            man_h = store._load_manifest(h)
            sibs = [Hash(x) for x in man_h["hashes"]
                    if bytes(x) != bytes(h)]
            if all(store._read_verified_member(mh) is not None
                   for mh in sibs):
                victim = h
                break
        assert victim is not None, "no cleanly-reconstructable victim"
        man = store._load_manifest(victim)
        path, _ = g.block_manager.find_block(victim)
        os.remove(path)
        rec = await asyncio.to_thread(store.try_reconstruct, victim)
        want = datas[hashes.index(victim)]
        assert rec == want, "sharded-pass parity failed to reconstruct"

        # bit-identity of the mesh parity vs the CPU codec, daemon data:
        # re-derive the victim's codeword from its manifest and compare
        cpu = CpuCodec(CodecParams(rs_data=4, rs_parity=2))
        import numpy as np

        members = []
        for mh in man["hashes"]:
            if bytes(mh) == bytes(victim):
                raw = want
            else:
                blk = await g.block_manager.read_block(Hash(bytes(mh)))
                raw = blk.decompressed()
            pad = np.zeros(man["maxlen"], dtype=np.uint8)
            pad[: len(raw)] = np.frombuffer(raw, dtype=np.uint8)
            members.append(pad)
        while len(members) < man["k"]:  # partial codeword: zero shards
            members.append(np.zeros(man["maxlen"], dtype=np.uint8))
        shards = np.stack(members)[None, :, :]
        expect_parity = cpu.rs_encode(shards)[0]
        got_parity = np.stack([
            np.frombuffer(s, dtype=np.uint8) for s in man["parity"]
        ])
        assert np.array_equal(got_parity, expect_parity), \
            "mesh-sharded parity differs from CPU RS encode"
    finally:
        await g.shutdown()
