"""Multi-chip codec sharding on the virtual 8-device CPU mesh
(xla_force_host_platform_device_count, see conftest.py) — validates the
mesh-sharded verify/encode path the driver also exercises via
__graft_entry__.dryrun_multichip."""

import hashlib

import jax
import numpy as np
import pytest
from jax.sharding import Mesh

from garage_tpu.ops import gf256
from garage_tpu.ops.tpu_codec import sharded_fns


@pytest.fixture(scope="module")
def mesh():
    devs = jax.devices()
    if len(devs) < 2:
        pytest.skip("need multi-device (virtual) platform")
    return Mesh(np.array(devs), ("data",))


def test_sharded_verify(mesh):
    bsz, nbytes = 16, 256
    rng = np.random.default_rng(0)
    data = rng.integers(0, 256, (bsz, nbytes), dtype=np.uint8)
    lengths = np.full((bsz,), nbytes, dtype=np.int32)
    expected = np.stack([
        np.frombuffer(
            hashlib.blake2s(data[i].tobytes(), digest_size=32).digest(), dtype="<u4"
        )
        for i in range(bsz)
    ]).astype(np.uint32)
    expected[3] ^= 1  # corrupt one expectation
    fns = sharded_fns(mesh)
    h, ok, bad = fns["verify"](data, lengths, expected)
    ok = np.asarray(ok)
    assert ok.sum() == bsz - 1 and not ok[3]
    assert int(bad) == 1


def test_sharded_encode_matches_numpy(mesh):
    k, m, s = 4, 2, 128
    rng = np.random.default_rng(1)
    data = rng.integers(0, 256, (16, k, s), dtype=np.uint8)
    pm = gf256.rs_parity_matrix(k, m)
    w = np.asarray(gf256.bitmatrix_of_gf_matrix(pm), dtype=np.int8)
    fns = sharded_fns(mesh)
    out = np.asarray(fns["rs_encode"](data, w))
    assert np.array_equal(out, gf256.gf_matmul_blocks(pm, data))


def test_codec_shard_mesh_from_config(mesh):
    """codec.shard_mesh wires a device mesh into the TpuCodec itself."""
    import hashlib as _h

    from garage_tpu.utils.config import config_from_dict

    cfg = config_from_dict({"codec": {"backend": "tpu", "shard_mesh": 8}})
    codec = cfg.codec.make(cfg.compression_level)
    assert codec.mesh is not None and codec.mesh.size == 8
    blocks = [bytes([i]) * (100 + i) for i in range(5)]  # odd batch, padded
    hashes = codec.batch_hash(blocks)
    assert [bytes(x) for x in hashes] == [
        _h.blake2s(b, digest_size=32).digest() for b in blocks
    ]
    assert codec.batch_verify(blocks, hashes).all()
    rng = np.random.default_rng(3)
    data = rng.integers(0, 256, (5, 8, 64), dtype=np.uint8)  # 5 % 8 != 0
    parity = codec.rs_encode(data)
    assert parity.shape == (5, 4, 64)
    from garage_tpu.ops import make_codec

    cpu = make_codec("cpu")
    assert np.array_equal(parity, cpu.rs_encode(data))
