"""RepairPlanner — exact-k fetch planning + partial-parallel repair.

Unit half: a stub-fetch planner over hand-built codewords proves the
plan never requests more than k pieces up front, ranks breaker-open and
cross-zone survivors last, hedges a ranked replacement when a fetch
stalls, replaces failed fetches, and XOR-accumulates PPR partial sums
bit-identically (including coefficient rescale after a survivor-set
change).

Cluster half: a real EC cluster drill proves the planned path and the
`ppr` block RPC reconstruct bit-identically end to end, and that a
mixed-version peer (gossiping a pre-PPR version) falls back to
whole-shard fetch without losing correctness.
"""

import asyncio
import os

import numpy as np
import pytest

from garage_tpu.block.repair_plan import (
    RAW,
    RepairPlanner,
    _Piece,
    parse_version,
)
from garage_tpu.ops import gf256
from garage_tpu.utils.data import Hash, blake2s_sum
from garage_tpu.utils.error import GarageError

pytestmark = pytest.mark.asyncio


# --- unit-half fakes ---------------------------------------------------------


class FakeRpc:
    def __init__(self, ranks=None):
        self.ranks = ranks or {}
        self.m_duration = None

    def peer_rank(self, n):
        return self.ranks.get(bytes(n), (1, 1, 0.0))

    def request_order(self, nodes):
        return sorted(nodes, key=self.peer_rank)


class FakeSystem:
    def __init__(self, ranks=None):
        self.rpc = FakeRpc(ranks)
        self.id = b"\x00" * 32

    def peer_version(self, nid):
        return None


class FakeReplication:
    def __init__(self, holders):
        self.holders = holders  # piece hash -> [node ids]

    def read_nodes(self, h):
        return self.holders.get(bytes(h), [b"\x01" * 32])


class FakeManager:
    def __init__(self, holders=None, ranks=None):
        self.system = FakeSystem(ranks)
        self.replication = FakeReplication(holders or {})
        self.codec = object()   # no decode_matrix → gf256 fallback
        self.feeder = None
        self.hash_algo = "blake2s"
        self.block_rpc_timeout = 1.0
        self.counters = {"fetch": {}, "repaired": 0, "overfetch": 0,
                         "hedges": 0, "ppr_fallbacks": 0}

    def is_block_present(self, h):
        return False

    def note_repair_fetch(self, mode, n):
        f = self.counters["fetch"]
        f[mode] = f.get(mode, 0) + n

    def note_repair_done(self, n):
        self.counters["repaired"] += n

    def note_repair_overfetch(self, n):
        self.counters["overfetch"] += n

    def note_repair_hedge(self):
        self.counters["hedges"] += 1

    def note_repair_ppr_fallback(self):
        self.counters["ppr_fallbacks"] += 1


class StubPlanner(RepairPlanner):
    """Planner with the network replaced by a shard dictionary; per-piece
    behavior ('stall' | 'fail') drives the hedging/replacement tests."""

    def __init__(self, mgr, shards, **kw):
        super().__init__(mgr, **kw)
        self.shards = shards          # piece hash -> unpacked shard bytes
        self.behavior = {}            # piece hash -> "stall" | "fail"
        self.fetch_log = []

    async def _maybe(self, piece):
        b = self.behavior.get(piece.hash)
        if b == "stall":
            await asyncio.sleep(30)
        if b == "fail":
            raise GarageError("injected piece failure")

    async def _fetch_whole(self, piece):
        self.fetch_log.append(("whole", piece.index))
        await self._maybe(piece)
        sh = self.shards[piece.hash]
        return sh, RAW, len(sh)

    async def _fetch_ppr(self, piece, coeff, want):
        self.fetch_log.append(("ppr", piece.index, coeff))
        await self._maybe(piece)
        body = gf256.gf_scale_bytes(coeff, self.shards[piece.hash], want)
        return body, int(coeff), len(body)


class Ent:
    def __init__(self, k, m, member_index, members, lengths, parity_hashes):
        self.k, self.m = k, m
        self.member_index = member_index
        self.members = members
        self.lengths = lengths
        self.parity_hashes = parity_hashes


def make_codeword(k=3, m=2, sizes=(900, 700, 500), seed=7):
    """A real RS(k, m) codeword over random members of varying length:
    (ent, shards{hash: bytes}, member bytes)."""
    rng = np.random.default_rng(seed)
    datas = [rng.integers(0, 256, s, dtype=np.uint8).tobytes()
             for s in sizes]
    maxlen = max(sizes)
    arr = np.zeros((k, maxlen), dtype=np.uint8)
    for i, d in enumerate(datas):
        arr[i, :len(d)] = np.frombuffer(d, dtype=np.uint8)
    parity = gf256.gf_matmul_blocks(gf256.rs_parity_matrix(k, m), arr[None])[0]
    mh = [bytes(blake2s_sum(d)) for d in datas]
    ph = [bytes(blake2s_sum(parity[j].tobytes())) for j in range(m)]
    shards = {h: d for h, d in zip(mh, datas)}
    shards.update({h: parity[j].tobytes() for j, h in enumerate(ph)})
    ent = Ent(k, m, 0, mh, list(sizes), ph)
    return ent, shards, datas


async def test_planner_requests_exactly_k_pieces_up_front():
    ent, shards, datas = make_codeword()
    target = Hash(ent.members[0])
    for use_ppr in (False, True):
        mgr = FakeManager()
        pl = StubPlanner(mgr, shards, use_ppr=use_ppr)
        out = await pl.reconstruct(target, ent)
        assert out == datas[0]
        # k = 3, no implicit zeros → exactly 3 fetches, never the 4th
        # candidate (4 = 2 surviving members + 2 parity)
        assert len(pl.fetch_log) == 3, pl.fetch_log
        kinds = {f[0] for f in pl.fetch_log}
        assert kinds == ({"ppr"} if use_ppr else {"whole"})
        assert mgr.counters["repaired"] == len(datas[0])
        assert mgr.counters["overfetch"] == 0
    # PPR moves only target-row-sized partials: ≤ whole-shard bytes
    ppr_bytes = sum(len(gf256.gf_scale_bytes(1, shards[h], ent.lengths[0]))
                    for h in list(shards)[:3])
    assert ppr_bytes <= sum(len(v) for v in list(shards.values())[:3])


async def test_ppr_moves_fewer_bytes_than_whole_shard():
    # target is the SHORT member: partials truncate to its length
    ent, shards, datas = make_codeword(sizes=(300, 1000, 1000))
    target = Hash(ent.members[0])
    mgr_p = FakeManager()
    out = await StubPlanner(mgr_p, shards, use_ppr=True).reconstruct(
        target, ent)
    assert out == datas[0]
    mgr_s = FakeManager()
    out2 = await StubPlanner(mgr_s, shards, use_ppr=False).reconstruct(
        target, ent)
    assert out2 == datas[0]
    ppr = sum(mgr_p.counters["fetch"].values())
    shard = sum(mgr_s.counters["fetch"].values())
    assert 0 < ppr < shard, (ppr, shard)
    # exact bound: k partials of ≤ target-length each
    assert ppr <= ent.k * ent.lengths[0]


async def test_breaker_open_and_cross_zone_rank_last():
    n_local, n_cross, n_open, n_par = (b"\x11" * 32, b"\x22" * 32,
                                       b"\x33" * 32, b"\x44" * 32)
    ranks = {
        n_local: (1, 0, 0.002),   # local zone, fast
        n_cross: (2, 0, 0.001),   # cross-zone (faster RTT, still later)
        n_open: (4, 0, 0.0),      # breaker open
        n_par: (1, 0, 0.005),     # healthy parity holder
    }
    pieces = [
        _Piece(0, b"A" * 32, "data"),   # held by open-breaker peer only
        _Piece(1, b"B" * 32, "data"),   # cross-zone
        _Piece(2, b"C" * 32, "data"),   # local zone
        _Piece(3, b"D" * 32, "parity"),  # healthy parity
    ]
    holders = {b"A" * 32: [n_open], b"B" * 32: [n_cross],
               b"C" * 32: [n_local], b"D" * 32: [n_par]}
    mgr = FakeManager(holders=holders, ranks=ranks)
    got = [p.index for p in RepairPlanner(mgr).rank_pieces(pieces)]
    # local data < cross-zone data < healthy parity < breaker-open data
    assert got == [2, 1, 3, 0], got


async def test_hedged_replacement_fires_on_stalled_fetch():
    ent, shards, datas = make_codeword(k=2, m=2, sizes=(640, 480))
    target = Hash(ent.members[0])
    for use_ppr in (False, True):
        mgr = FakeManager()
        pl = StubPlanner(mgr, shards, use_ppr=use_ppr, hedge_delay=0.05)
        pl.behavior[ent.members[1]] = "stall"  # the surviving data member
        out = await pl.reconstruct(target, ent)
        assert out == datas[0]
        assert pl.hedges >= 1
        assert mgr.counters["hedges"] >= 1
        # the stalled fetch was abandoned: decode came from the two
        # parity pieces (2 completed fetches + the stalled one launched)
        assert len(pl.fetch_log) == 3, pl.fetch_log


async def test_failed_fetch_launches_ranked_replacement():
    ent, shards, datas = make_codeword()
    target = Hash(ent.members[0])
    for use_ppr in (False, True):
        mgr = FakeManager()
        pl = StubPlanner(mgr, shards, use_ppr=use_ppr, hedge_delay=5.0)
        pl.behavior[ent.members[2]] = "fail"
        out = await pl.reconstruct(target, ent)
        assert out == datas[0]
        # 3 initial + 1 replacement for the failed piece
        assert len(pl.fetch_log) == 4, pl.fetch_log


async def test_ppr_rescale_after_set_change_is_bit_identical():
    """A failed fetch changes the survivor set AFTER partials were
    computed under the old coefficients — the coordinator must rescale
    them (c_new ⊗ c_old⁻¹) rather than refetch."""
    ent, shards, datas = make_codeword(k=4, m=2,
                                       sizes=(1000, 900, 800, 700))
    target = Hash(ent.members[0])
    mgr = FakeManager()
    pl = StubPlanner(mgr, shards, use_ppr=True, hedge_delay=5.0)
    pl.behavior[ent.members[3]] = "fail"  # forces parity replacement
    out = await pl.reconstruct(target, ent)
    assert out == datas[0]
    assert len(pl.fetch_log) == 5  # 4 planned + 1 replacement


async def test_ppr_whole_shard_fallback_is_raw_scaled():
    """A piece whose PPR fetch degrades to whole-shard (RAW sentinel)
    still lands in a bit-identical XOR accumulation."""
    ent, shards, datas = make_codeword()
    target = Hash(ent.members[0])

    class FallbackPlanner(StubPlanner):
        async def _fetch_ppr(self, piece, coeff, want):
            if piece.index == 1:  # this survivor "predates" the endpoint
                self.ppr_fallbacks += 1
                self.manager.note_repair_ppr_fallback()
                sh = self.shards[piece.hash]
                self.fetch_log.append(("whole", piece.index))
                return sh, RAW, len(sh)
            return await super()._fetch_ppr(piece, coeff, want)

    mgr = FakeManager()
    pl = FallbackPlanner(mgr, shards, use_ppr=True)
    out = await pl.reconstruct(target, ent)
    assert out == datas[0]
    assert pl.ppr_fallbacks == 1
    assert mgr.counters["ppr_fallbacks"] == 1


def test_parse_version():
    assert parse_version("0.9.0") == (0, 9, 0)
    assert parse_version("0.8.3-old") == (0, 8, 3)
    assert parse_version("1.2") == (1, 2, 0)
    assert parse_version(None) is None
    assert parse_version("devbuild") is None


# --- cluster half: real `ppr` RPC + mixed-version fallback -------------------


async def _wait_indexed(garages, hs, secs=25.0):
    """Wait until every member hash has a live parity-index entry."""
    deadline = asyncio.get_event_loop().time() + secs
    entries = {}
    while asyncio.get_event_loop().time() < deadline:
        entries = {}
        for h in hs:
            ents = await garages[0].parity_index_table.get_range(
                bytes(h), None)
            live = [e for e in ents if not e.is_tombstone()]
            entries[bytes(h)] = live[0] if live else None
        if all(entries.values()):
            return entries
        await asyncio.sleep(0.1)
    raise AssertionError("write-time parity never distributed")


async def test_cluster_ppr_drill_bit_identical_with_mixed_version(tmp_path):
    """EC cluster drill: the planned PPR path reconstructs bit-identical
    bytes over the real `ppr` RPC; with one peer gossiping a pre-PPR
    version, its pieces fall back to whole-shard fetch and the result is
    STILL bit-identical."""
    import sys

    sys.path.insert(0, os.path.dirname(__file__))
    from test_model import make_ec_cluster, shutdown

    garages = await make_ec_cluster(tmp_path, 5, rs=(2, 2))
    try:
        datas = [os.urandom(30_000 + 1013 * i) for i in range(8)]
        hs = [blake2s_sum(d) for d in datas]
        for h, d in zip(hs, datas):
            await garages[0].block_manager.rpc_put_block(h, d)
        for g in garages:
            await g.block_manager.ec_accumulator.drain()
        entries = await _wait_indexed(garages, hs)

        # gossip the status so peer versions are known cluster-wide
        for g in garages:
            await g.system.rpc.broadcast(
                g.system.endpoint,
                {"t": "advertise_status",
                 "status": g.system._local_status().pack(),
                 "peers": g.system._peer_book()},
                timeout=5.0)

        # coordinate from a node that holds NO piece of the first
        # block's codeword (placement is ring-random per run: a node
        # holding a surviving member/parity would serve that piece
        # LOCALLY, and an unlucky single-member codeword could then
        # reconstruct with zero wire bytes — the `ppr bytes moved`
        # assert below needs every fetched piece to be remote)
        def holder_of(bh):
            return bytes(garages[0].block_manager.replication.write_nodes(
                Hash(bh))[0])

        ent0 = entries[bytes(hs[0])]
        piece_holders = {
            holder_of(bytes(ph))
            for ph in list(ent0.members) + list(ent0.parity_hashes)
            if bytes(ph) != bytes(32)
        }
        coord = next(g for g in garages
                     if bytes(g.system.id) not in piece_holders
                     and bytes(g.system.id) != holder_of(hs[0]))
        planner = coord.block_manager.repair_planner
        assert planner is not None and planner.use_ppr

        before = dict(coord.block_manager.repair_fetch_bytes)
        out = await planner.reconstruct(Hash(hs[0]), entries[bytes(hs[0])])
        assert out == datas[0], "PPR reconstruction not bit-identical"
        after = coord.block_manager.repair_fetch_bytes
        # a tree-capable cluster aggregates the partials and lands the
        # bytes as coordinator "tree" ingress; demoted/flat edges land
        # as "ppr" — either way partial products moved on the wire
        moved = (after.get("ppr", 0) + after.get("tree", 0)
                 - before.get("ppr", 0) - before.get("tree", 0))
        assert moved > 0, "no partial products moved"

        # mixed-version: one OTHER node gossips a pre-PPR version; the
        # planner must stop sending it `ppr` and whole-shard its pieces.
        # Pick the old node among nodes that actually HOLD a test block
        # (placement is ring-random per run: an arbitrary node holds one
        # of the 8 blocks only ~83% of the time, and the capability-gate
        # check below needs a block whose sole holder is the old node —
        # the holder of hs[0] always qualifies and is never coord)
        old = next(g for g in garages
                   if bytes(g.system.id) != bytes(coord.system.id)
                   and any(holder_of(bytes(h)) == bytes(g.system.id)
                           for h in hs))
        old.system.version = "0.1.0"
        await old.system.rpc.broadcast(
            old.system.endpoint,
            {"t": "advertise_status",
             "status": old.system._local_status().pack(),
             "peers": old.system._peer_book()},
            timeout=5.0)
        assert parse_version(
            coord.system.peer_version(old.system.id)) == (0, 1, 0)

        # deterministic capability-gate check: fetch a piece whose SOLE
        # holder (data replication "none") is the old-version node — the
        # planner must refuse to send it `ppr` and fall back to a
        # whole-shard fetch (RAW), still moving the verified bytes
        from garage_tpu.block.repair_plan import _Piece

        old_piece = next(
            (bytes(h) for h in hs
             if holder_of(bytes(h)) == bytes(old.system.id)), None)
        assert old_piece is not None, "no block landed on the old node"
        c2 = next(g for g in garages
                  if bytes(g.system.id) != bytes(old.system.id)
                  and not g.block_manager.is_block_present(
                      Hash(old_piece)))
        pl2 = c2.block_manager.repair_planner
        fb_before = c2.block_manager.repair_ppr_fallbacks
        payload, c_app, moved = await pl2._fetch_ppr(
            _Piece(0, old_piece, "data"), 7, 4096)
        from garage_tpu.block.repair_plan import RAW as RAW_SENTINEL
        assert c_app == RAW_SENTINEL, "old-version peer was sent ppr"
        assert moved > 0 and payload, "fallback moved no bytes"
        assert c2.block_manager.repair_ppr_fallbacks == fb_before + 1

        # and end-to-end: every codeword touching the old node still
        # reconstructs bit-identically through the planner
        planner._row_cache.clear()
        hit = 0
        for h in hs:
            ent = entries[bytes(h)]
            piece_hashes = ([m for m in ent.members
                             if bytes(m) != bytes(h)]
                            + list(ent.parity_hashes))
            on_old = any(holder_of(p) == bytes(old.system.id)
                         for p in piece_hashes)
            if not on_old:
                continue
            c = next(g for g in garages
                     if bytes(g.system.id) != holder_of(bytes(h)))
            got = await c.block_manager.repair_planner.reconstruct(
                Hash(h), ent)
            assert got == datas[hs.index(h)], \
                "mixed-version reconstruction not bit-identical"
            hit += 1
        assert hit > 0, "no codeword touched the old-version node"
    finally:
        await shutdown(garages)
