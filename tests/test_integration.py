"""Full-daemon integration tests: spawn REAL `python -m garage_tpu server`
subprocesses on localhost, configure the cluster through the real CLI,
and drive the S3/admin APIs — the reference's test philosophy (execve a
compiled binary, no mocked IO; SURVEY.md §4, tests/common/garage.rs)."""

import asyncio
import json
import os
import pathlib
import signal
import subprocess
import sys
import time

import aiohttp
import pytest

sys.path.insert(0, os.path.dirname(__file__))
from test_s3_api import S3Client

pytestmark = pytest.mark.asyncio

REPO = pathlib.Path(__file__).resolve().parent.parent


def _free_ports(n):
    import socket

    socks, ports = [], []
    for _ in range(n):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        socks.append(s)
        ports.append(s.getsockname()[1])
    for s in socks:
        s.close()
    return ports


class Cluster:
    """3 daemon subprocesses + CLI helpers (ref tests/common/garage.rs)."""

    def __init__(self, base: pathlib.Path, n=3):
        self.base = base
        self.n = n
        self.procs = []
        self.configs = []
        ports = _free_ports(3 * n)
        self.rpc_ports = ports[:n]
        self.s3_ports = ports[n:2 * n]
        self.admin_ports = ports[2 * n:]
        peers = ", ".join(f'"127.0.0.1:{p}"' for p in self.rpc_ports)
        for i in range(n):
            d = base / f"node{i}"
            (d / "meta").mkdir(parents=True)
            (d / "data").mkdir(parents=True)
            cfg = d / "garage.toml"
            cfg.write_text(f'''
metadata_dir = "{d}/meta"
data_dir = "{d}/data"
db_engine = "sqlite"
replication_mode = "3"
rpc_bind_addr = "127.0.0.1:{self.rpc_ports[i]}"
rpc_public_addr = "127.0.0.1:{self.rpc_ports[i]}"
rpc_secret = "integration-test-secret"
bootstrap_peers = [{peers}]

[s3_api]
s3_region = "garage"
api_bind_addr = "127.0.0.1:{self.s3_ports[i]}"

[admin]
api_bind_addr = "127.0.0.1:{self.admin_ports[i]}"
admin_token = "test-admin-token"
''')
            self.configs.append(str(cfg))

    def start(self):
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env["PYTHONPATH"] = str(REPO)
        for i in range(self.n):
            self.procs.append(subprocess.Popen(
                [sys.executable, "-m", "garage_tpu", "-c", self.configs[i], "server"],
                cwd=str(REPO), env=env,
                stdout=subprocess.DEVNULL, stderr=subprocess.STDOUT,
            ))

    def cli(self, *args, config=None, check=True, timeout=60):
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env["PYTHONPATH"] = str(REPO)
        r = subprocess.run(
            [sys.executable, "-m", "garage_tpu",
             "-c", config or self.configs[0], *args],
            cwd=str(REPO), env=env, capture_output=True, text=True,
            timeout=timeout,
        )
        if check and r.returncode != 0:
            raise RuntimeError(f"cli {args} failed: {r.stdout}\n{r.stderr}")
        return r.stdout

    async def wait_up(self, timeout=60):
        """Poll /health until every node answers (boot detection —
        garage.rs polls `garage status`)."""
        deadline = time.monotonic() + timeout
        async with aiohttp.ClientSession() as s:
            for port in self.admin_ports:
                while True:
                    try:
                        async with s.get(
                            f"http://127.0.0.1:{port}/health",
                            timeout=aiohttp.ClientTimeout(total=2),
                        ) as r:
                            await r.read()
                            break
                    except Exception:
                        if time.monotonic() > deadline:
                            raise TimeoutError(f"node on {port} did not boot")
                        await asyncio.sleep(0.3)

    def configure_layout(self):
        """Assign all nodes + apply (ref garage.rs:138-153)."""
        ids = []
        for c in self.configs:
            out = self.cli("node-id", config=c)
            ids.append(out.strip().split("@")[0])
        # bootstrap peers connect automatically on discovery; wait until
        # the admin endpoint can resolve all ids unambiguously
        for nid in ids:
            self.cli("layout", "assign", nid, "-z", "dc1", "-c", "100M")
        self.cli("layout", "apply", "--version", "1")
        return ids

    def stop(self):
        for p in self.procs:
            p.send_signal(signal.SIGTERM)
        for p in self.procs:
            try:
                p.wait(timeout=15)
            except subprocess.TimeoutExpired:
                p.kill()
                p.wait()


@pytest.fixture
def cluster(tmp_path):
    c = Cluster(tmp_path)
    c.start()
    try:
        yield c
    finally:
        c.stop()


async def _boot(cluster):
    """Wait for boot + full mesh, then apply the layout."""
    await cluster.wait_up()
    for _ in range(60):
        out = cluster.cli("status")
        if "3/3 connected" in out:
            break
        await asyncio.sleep(0.5)
    cluster.configure_layout()


async def test_daemon_cluster_end_to_end(cluster):
    await _boot(cluster)
    out = cluster.cli("status")
    assert "healthy" in out

    # create a key + bucket via the CLI
    out = cluster.cli("key", "create", "it-key")
    key_id = [l for l in out.splitlines() if "Key ID" in l][0].split()[-1]
    secret = [l for l in out.splitlines() if "Secret" in l][0].split()[-1]
    cluster.cli("bucket", "create", "it-bucket")
    cluster.cli("bucket", "allow", "it-bucket", "--key", key_id,
                "--read", "--write", "--owner")

    # drive S3 against two different nodes
    c0 = S3Client(cluster.s3_ports[0], key_id, secret)
    c1 = S3Client(cluster.s3_ports[1], key_id, secret)
    data = os.urandom(1536 * 1024)
    status, _, _ = await c0.req("PUT", "/it-bucket/x.bin", body=data)
    assert status == 200
    status, _, got = await c1.req("GET", "/it-bucket/x.bin")  # other node!
    assert status == 200 and got == data

    # kill one node; quorum reads/writes must still work (rf=3, quorum 2)
    victim = cluster.procs[2]
    victim.send_signal(signal.SIGKILL)
    victim.wait()
    await asyncio.sleep(1)
    status, _, got = await c0.req("GET", "/it-bucket/x.bin")
    assert status == 200 and got == data
    data2 = os.urandom(200 * 1024)
    status, _, _ = await c1.req("PUT", "/it-bucket/y.bin", body=data2)
    assert status == 200
    status, _, got = await c0.req("GET", "/it-bucket/y.bin")
    assert status == 200 and got == data2

    # worker list over admin RPC still answers
    out = cluster.cli("worker", "list")
    assert "Merkle" in out or "merkle" in out

    # stats: local, then cluster-wide fan-out (one node is down -> its
    # entry reports the error instead of stats)
    out = cluster.cli("stats")
    assert "resync_queue" in out
    allstats = json.loads(cluster.cli("stats", "-a"))
    assert len(allstats["nodes"]) == 3
    ok_nodes = [v for v in allstats["nodes"].values() if "block" in v]
    err_nodes = [v for v in allstats["nodes"].values() if "err" in v]
    assert len(ok_nodes) == 2 and len(err_nodes) == 1, allstats

    # restart the killed node: the mesh heals (bootstrap peers + persisted
    # peer list + discovery loop), the cluster reports healthy again, and
    # the restarted node serves reads written while it was down
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = str(REPO)
    cluster.procs[2] = subprocess.Popen(
        [sys.executable, "-m", "garage_tpu", "-c", cluster.configs[2],
         "server"],
        cwd=str(REPO), env=env,
        stdout=subprocess.DEVNULL, stderr=subprocess.STDOUT,
    )
    # "healthy" + every partition all-ok means all 3 storage nodes are
    # back (the known/connected counts also include transient CLI peers,
    # as in the reference's get_known_nodes-based health)
    for _ in range(120):
        out = cluster.cli("status")
        if "healthy" in out and "256/256 all-ok" in out:
            break
        await asyncio.sleep(0.5)
    else:
        raise AssertionError(f"mesh did not heal: {out}")
    c2 = S3Client(cluster.s3_ports[2], key_id, secret)
    for _ in range(40):  # S3 bind may land moments after RPC heals
        try:
            status, _, got = await c2.req("GET", "/it-bucket/y.bin")
            break
        except (aiohttp.ClientError, OSError):
            await asyncio.sleep(0.5)
    else:
        raise AssertionError("restarted node's S3 API never came up")
    assert status == 200 and got == data2


async def test_admin_http_api(cluster):
    await _boot(cluster)

    hdrs = {"Authorization": "Bearer test-admin-token"}
    base = f"http://127.0.0.1:{cluster.admin_ports[0]}"
    async with aiohttp.ClientSession() as s:
        async with s.get(f"{base}/health") as r:
            assert r.status == 200
        async with s.get(f"{base}/v1/status") as r:
            assert r.status == 403  # no token
        async with s.get(f"{base}/v1/status", headers=hdrs) as r:
            assert r.status == 200
            st = await r.json()
            assert len(st["roles"]) == 3
        async with s.post(f"{base}/v1/key", headers=hdrs, json={"name": "k"}) as r:
            k = await r.json()
            assert k["accessKeyId"].startswith("GK")
        async with s.get(f"{base}/v1/key", headers=hdrs) as r:
            keys = await r.json()
            assert any(x["id"] == k["accessKeyId"] for x in keys)
        async with s.get(f"{base}/metrics") as r:
            body = await r.text()
            assert "cluster_healthy" in body
            # per-layer families (ref rpc/table/block/api metric structs);
            # the key insert above drove quorum RPCs + table writes on
            # this node, so the labelled samples must exist
            assert 'rpc_request_counter{endpoint="garage/table/key' in body
            assert "rpc_duration_seconds_bucket" in body
            assert 'table_put_request_counter{table_name="key"' in body
            assert "table_size{" in body
            assert "block_resync_queue_length" in body
            assert "api_request_counter" in body


async def test_gateway_get_survives_storage_node_kill(tmp_path):
    """Daemon-level mid-download failover (VERDICT r1 item 7 done-criteria):
    a gateway node streams a multi-block GetObject from storage replicas;
    the storage node actually serving is SIGKILLed mid-transfer and the
    client still receives the full, correct body (stream failover in
    rpc_get_block_streaming + ref manager.rs:231-345)."""
    import json as _json

    from test_s3_api import S3Client  # noqa: F811 (sys.path'd above)
    from garage_tpu.api.signature import sign_request

    c = Cluster(tmp_path, n=4)
    c.start()
    try:
        await c.wait_up()
        for _ in range(60):
            if "4/4 connected" in c.cli("status"):
                break
            await asyncio.sleep(0.5)
        # nodes 0-2 store data; node 3 is a pure API gateway (no capacity)
        ids = []
        for cfg in c.configs:
            ids.append(c.cli("node-id", config=cfg).strip().split("@")[0])
        for nid in ids[:3]:
            c.cli("layout", "assign", nid, "-z", "dc1", "-c", "100M")
        c.cli("layout", "assign", ids[3], "-z", "dc1")  # gateway
        c.cli("layout", "apply", "--version", "1")

        out = c.cli("key", "create", "gw-key")
        key_id = [l for l in out.splitlines() if "Key ID" in l][0].split()[-1]
        secret = [l for l in out.splitlines() if "Secret" in l][0].split()[-1]
        c.cli("bucket", "create", "gw-bucket")
        c.cli("bucket", "allow", "gw-bucket", "--key", key_id,
              "--read", "--write", "--owner")

        data = os.urandom(8 * 1024 * 1024)  # 8 × 1 MiB blocks
        put_client = S3Client(c.s3_ports[0], key_id, secret)
        status, _, _ = await put_client.req("PUT", "/gw-bucket/big.bin",
                                            body=data)
        assert status == 200

        def node_bytes_read(i):
            stats = _json.loads(c.cli("stats", config=c.configs[i]))
            return stats["block"]["bytes_read"]

        before = [node_bytes_read(i) for i in range(3)]

        # streaming GET through the GATEWAY (stores nothing itself)
        gw_port = c.s3_ports[3]
        path = "/gw-bucket/big.bin"
        headers = {"host": f"127.0.0.1:{gw_port}"}
        headers.update(sign_request(
            key_id, secret, "garage", "GET", path, [], headers, b"",
            path_is_raw=True,
        ))
        got = bytearray()
        async with aiohttp.ClientSession() as s:
            async with s.get(f"http://127.0.0.1:{gw_port}{path}",
                             headers=headers) as r:
                assert r.status == 200
                # consume the first ~1.5 MiB, then find + kill the serving
                # storage node (its read counter moved by ≥ a block)
                while len(got) < 1536 * 1024:
                    chunk = await r.content.read(64 * 1024)
                    assert chunk, "stream ended early"
                    got.extend(chunk)
                deltas = [node_bytes_read(i) - before[i] for i in range(3)]
                victim = deltas.index(max(deltas))
                assert deltas[victim] >= 1024 * 1024, deltas
                c.procs[victim].send_signal(signal.SIGKILL)
                c.procs[victim].wait()
                while True:
                    chunk = await r.content.read(256 * 1024)
                    if not chunk:
                        break
                    got.extend(chunk)
        assert len(got) == len(data) and bytes(got) == data
    finally:
        c.stop()


# --- cluster upgrade across a format version bump ---------------------------


async def test_cluster_upgrade_format_version_bump(tmp_path):
    """Flag-day upgrade (analog of the reference's test-upgrade.sh +
    src/model/migrate.rs): populate a persistent 3-node cluster whose
    table entries are encoded at format V1, stop every node, 'install
    the new binary' (the same table redefined with a V2 entry carrying
    an extra field and a Migrate step), restart on the SAME metadata
    dirs, and assert: every old row decodes + migrates, reads converge
    at quorum, mixed V1/V2 rows merge and re-encode as V2, and new
    writes land."""
    from garage_tpu.db import open_db
    from garage_tpu.rpc.replication_mode import parse_replication_mode
    from garage_tpu.table import Table, TableShardedReplication
    from garage_tpu.table.schema import Entry, TableSchema
    from garage_tpu.utils.crdt import now_msec

    sys.path.insert(0, os.path.dirname(__file__))
    from test_table import make_cluster, shutdown

    class RowV1(Entry):
        VERSION_MARKER = b"GT01upg"

        def __init__(self, key, ts, value):
            self.key, self.ts, self.value = key, ts, value

        @property
        def partition_key(self):
            return self.key

        @property
        def sort_key(self):
            return b""

        def merge(self, other):
            if other.ts > self.ts:
                self.ts, self.value = other.ts, other.value

        def fields(self):
            return [self.key, self.ts, self.value]

        @classmethod
        def from_fields(cls, b):
            return cls(str(b[0]), int(b[1]), str(b[2]))

    class RowV2(RowV1):
        VERSION_MARKER = b"GT02upg"
        PREVIOUS = RowV1

        def __init__(self, key, ts, value, tags=None):
            super().__init__(key, ts, value)
            self.tags = list(tags or [])

        def merge(self, other):
            super().merge(other)
            # new-field CRDT: union of tags
            self.tags = sorted(set(self.tags) | set(getattr(other, "tags", [])))

        def fields(self):
            return [self.key, self.ts, self.value, self.tags]

        @classmethod
        def from_fields(cls, b):
            return cls(str(b[0]), int(b[1]), str(b[2]),
                       [str(t) for t in b[3]])

        @classmethod
        def migrate(cls, old):
            return cls(old.key, old.ts, old.value, tags=[])

    def mk_schema(entry_cls):
        class S(TableSchema):
            TABLE_NAME = "upgradekv"
            ENTRY = entry_cls

            def updated(self, tx, old, new):
                pass

            def matches_filter(self, entry, filter):
                return True

        return S()

    def mk_tables(systems, entry_cls):
        m = parse_replication_mode("3")
        tables = []
        for i, s in enumerate(systems):
            db = open_db("sqlite",
                         str(tmp_path / f"n{i}" / "meta" / "upg.sqlite"))
            repl = TableShardedReplication(
                s, m.replication_factor, m.read_quorum, m.write_quorum)
            tables.append(Table(s, mk_schema(entry_cls), repl, db))
        return tables

    # --- generation 1: old binary, V1 rows ---
    systems = await make_cluster(tmp_path)
    tables = mk_tables(systems, RowV1)
    for i in range(20):
        await tables[0].insert(RowV1(f"key-{i:02d}", now_msec(), f"v{i}"))
    got = await tables[1].get(f"key-07", b"")
    assert got is not None and got.value == "v7"
    await shutdown(systems)

    # --- flag-day: every node restarts on the NEW binary (V2 schema),
    # same metadata dirs, same node identities ---
    systems2 = await make_cluster(tmp_path)
    tables2 = mk_tables(systems2, RowV2)

    # every V1 row decodes via the Migrate chain and reads at quorum
    for i in range(20):
        row = await tables2[2].get(f"key-{i:02d}", b"")
        assert row is not None, f"key-{i:02d} lost across upgrade"
        assert row.value == f"v{i}" and row.tags == []

    # updating an old row re-encodes it as V2 (mixed-version merge)
    upd = RowV2("key-03", now_msec() + 10, "v3-new", tags=["a"])
    await tables2[0].insert(upd)
    row = await tables2[1].get("key-03", b"")
    assert row.value == "v3-new" and row.tags == ["a"]
    # the quorum write may have node 1 as its background straggler and
    # read-repair lands asynchronously — poll for the re-encode
    for _ in range(100):
        raw = tables2[1].data.read_entry("key-03", b"")
        if raw is not None and raw.startswith(b"GT02upg"):
            break
        await asyncio.sleep(0.05)
    assert raw is not None and raw.startswith(b"GT02upg"), raw[:8]

    # new writes land normally post-upgrade
    await tables2[0].insert(RowV2("fresh", now_msec(), "new", tags=["x", "y"]))
    got = await tables2[2].get("fresh", b"")
    assert got.tags == ["x", "y"]
    await shutdown(systems2)


async def test_independent_client_interop(cluster):
    """Real-client smoke with a from-scratch SigV4 implementation
    (tests/independent_s3_client.py — zero garage_tpu imports, written
    from the AWS spec): header auth, presigned URLs, aws-chunked
    STREAMING signatures, multipart with out-of-order parts, retries.
    The role the reference gives aws-cli/s3cmd/mc/rclone
    (script/test-smoke.sh:11-60) — none of which ship in this image."""
    from independent_s3_client import IndependentS3Client

    await _boot(cluster)
    out = cluster.cli("key", "create", "indep-key")
    key_id = [l for l in out.splitlines() if "Key ID" in l][0].split()[-1]
    secret = [l for l in out.splitlines() if "Secret" in l][0].split()[-1]
    cluster.cli("bucket", "create", "indep")
    cluster.cli("bucket", "allow", "indep", "--key", key_id,
                "--read", "--write", "--owner")

    c = IndependentS3Client(
        f"http://127.0.0.1:{cluster.s3_ports[0]}", key_id, secret)

    # plain header-auth PUT/GET round trip
    body = os.urandom(300_000)
    st, _h, _b = await c.request("PUT", "/indep/plain.bin", body=body)
    assert st == 200
    st, _h, got = await c.request("GET", "/indep/plain.bin")
    assert st == 200 and got == body

    # aws-chunked streaming-signature PUT (what aws-cli does by default
    # over plain http), read back from ANOTHER node
    sbody = os.urandom(700_000)
    st, _h, resp = await c.put_streaming("/indep/streamed.bin", sbody)
    assert st == 200, resp[:300]
    c1 = IndependentS3Client(
        f"http://127.0.0.1:{cluster.s3_ports[1]}", key_id, secret)
    st, _h, got = await c1.request("GET", "/indep/streamed.bin")
    assert st == 200 and got == sbody

    # presigned URL GET — no headers beyond Host, query auth only
    url = c.presign("GET", "/indep/plain.bin")
    async with aiohttp.ClientSession() as sess:
        import yarl

        async with sess.get(yarl.URL(url, encoded=True)) as r:
            rb = await r.read()
            assert r.status == 200, rb[:300]
            assert rb == body

    # multipart with OUT-OF-ORDER parts (real tools upload concurrently)
    st, _h, resp = await c.request(
        "POST", "/indep/mp.bin", query=[("uploads", "")])
    assert st == 200, resp[:200]
    uid = resp.split(b"<UploadId>")[1].split(b"</UploadId>")[0].decode()
    parts = {1: os.urandom(5 << 20), 2: os.urandom(5 << 20),
             3: os.urandom(1 << 20)}
    etags = {}
    for pn in (2, 3, 1):  # deliberately out of order
        st, hdr, _b = await c.request(
            "PUT", "/indep/mp.bin", body=parts[pn],
            query=[("partNumber", str(pn)), ("uploadId", uid)])
        assert st == 200
        etags[pn] = hdr.get("ETag", hdr.get("Etag", "")).strip('"')
        assert etags[pn], f"no ETag header in {list(hdr)}"
    xml = ("<CompleteMultipartUpload>" + "".join(
        f"<Part><PartNumber>{pn}</PartNumber><ETag>{etags[pn]}</ETag></Part>"
        for pn in (1, 2, 3)) + "</CompleteMultipartUpload>").encode()
    st, _h, resp = await c.request(
        "POST", "/indep/mp.bin", body=xml, query=[("uploadId", uid)])
    assert st == 200, resp[:300]
    st, _h, got = await c.request("GET", "/indep/mp.bin")
    assert st == 200 and got == parts[1] + parts[2] + parts[3]


async def test_fault_injector_crash_corrupt_revive(tmp_path):
    """The reusable injector (garage_tpu/testing/faults.py): crash a
    node abruptly, corrupt + drop blocks behind the cluster's back,
    verify scrub-class machinery detects and repairs, then revive the
    node from its on-disk state and watch it rejoin."""
    import asyncio

    from garage_tpu.model.s3.version_table import Version
    from garage_tpu.testing.faults import FaultInjector
    from garage_tpu.utils.config import config_from_dict
    from garage_tpu.utils.data import Hash, blake2s_sum, gen_uuid

    from test_model import shutdown
    from garage_tpu.model import Garage
    from garage_tpu.rpc.layout import ClusterLayout, NodeRole

    garages, cfgs = [], []
    for i in range(3):
        cfg = config_from_dict({
            "metadata_dir": str(tmp_path / f"n{i}" / "meta"),
            "data_dir": str(tmp_path / f"n{i}" / "data"),
            "replication_mode": "3",
            "rpc_bind_addr": "127.0.0.1:0",
            "rpc_secret": "fault-test",
            "db_engine": "sqlite",       # revive needs persistence
            "bootstrap_peers": [],
        })
        cfgs.append(cfg)
        g = Garage(cfg)
        await g.system.netapp.listen("127.0.0.1:0")
        garages.append(g)
    ports = [g.system.netapp._server.sockets[0].getsockname()[1]
             for g in garages]
    for i, a in enumerate(garages):
        for j, b in enumerate(garages):
            if i < j:
                await a.system.netapp.connect(
                    f"127.0.0.1:{ports[j]}", expected_id=b.system.id)
            if i != j:
                a.system.peering.add_peer(
                    f"127.0.0.1:{ports[j]}", b.system.id)
        a.config.rpc_public_addr = f"127.0.0.1:{ports[i]}"
        a.system.peering.start()
    lay = garages[0].system.layout
    for g in garages:
        lay.stage_role(bytes(g.system.id), NodeRole("dc1", 1000))
    lay.apply_staged_changes()
    enc = lay.encode()
    for g in garages:
        g.system.layout = ClusterLayout.decode(enc)
        g.system._rebuild_ring()
        g.spawn_workers()

    inj = FaultInjector(garages, cfgs)
    try:
        import os as _os

        data = _os.urandom(300_000)
        h = blake2s_sum(data)
        await garages[0].block_manager.rpc_put_block(h, data)
        vu, bid = gen_uuid(), gen_uuid()
        ver = Version.new(vu, bytes(bid), "fobj")
        ver.add_block(0, 0, bytes(h), len(data))
        await garages[0].version_table.insert(ver)
        await asyncio.sleep(1.0)

        # every replica stores it (replication 3)
        holders = [i for i in range(3)
                   if inj._find(i, h) is not None]
        assert len(holders) == 3, holders

        # 1. silent corruption on node 1: read path must detect it and
        # serve from another replica, then resync repairs the file
        assert inj.corrupt_block(1, h)
        got = await garages[1].block_manager.rpc_get_block(Hash(bytes(h)))
        assert got == data, "corrupted replica served bad bytes"

        # 2. silent drop on node 2 + crash node 1: the only intact
        # replica is node 0; reads still work
        assert inj.drop_block(2, h)
        await inj.crash(1)
        got = await garages[2].block_manager.rpc_get_block(Hash(bytes(h)))
        assert got == data

        # 3. revive node 1 from disk: it rejoins, metadata intact
        g1 = await inj.revive(1)
        for _ in range(100):
            v = await g1.version_table.get(bytes(vu), "")
            if v is not None:
                break
            await asyncio.sleep(0.1)
        assert v is not None and not v.deleted.value

        # 4. node 2's dropped block heals via resync (repair trigger)
        garages[2].block_manager.resync.put_to_resync(Hash(bytes(h)), 0.0)
        healed = False
        for _ in range(200):
            if inj._find(2, h) is not None:
                healed = True
                break
            await asyncio.sleep(0.1)
        assert healed, "dropped block never resynced back"
    finally:
        await shutdown([g for i, g in enumerate(inj.garages)
                        if i not in inj.dead])
