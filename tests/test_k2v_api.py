"""K2V HTTP API + client library tests.

Mirrors the reference's K2V suites (tests/k2v/: item CRUD with causality
tokens, batch ops, long-poll with real concurrent tasks) against an
in-process node + K2VApiServer, driven through the K2VClient library
(ref k2v-client crate).  Includes regressions for the tombstone-
resurrect and tombstone-pagination bugs found in round 2.
"""

import asyncio

import pytest

from garage_tpu.api.k2v_server import K2VApiServer
from garage_tpu.k2v_client import K2VClient, K2VError
from garage_tpu.model import Garage
from garage_tpu.rpc.layout import ClusterLayout, NodeRole
from garage_tpu.utils.config import config_from_dict

pytestmark = pytest.mark.asyncio


async def make_k2v(tmp_path):
    g = Garage(config_from_dict({
        "metadata_dir": str(tmp_path / "meta"),
        "data_dir": str(tmp_path / "data"),
        "replication_mode": "none",
        "rpc_bind_addr": "127.0.0.1:0",
        "rpc_secret": "k2v",
        "db_engine": "memory",
        "bootstrap_peers": [],
    }))
    await g.system.netapp.listen("127.0.0.1:0")
    lay = g.system.layout
    lay.stage_role(bytes(g.system.id), NodeRole("dc1", 1000))
    lay.apply_staged_changes()
    g.system.layout = ClusterLayout.decode(lay.encode())
    g.system._rebuild_ring()
    g.spawn_workers()

    helper = g.helper()
    key = await helper.create_key("k2v-test")
    key.params().allow_create_bucket.update(True)
    await g.key_table.insert(key)
    bucket = await helper.create_bucket("kbkt")
    from garage_tpu.model import BucketKeyPerm

    await helper.set_bucket_key_permissions(
        bucket.id, key.key_id, BucketKeyPerm(True, True, False))

    srv = K2VApiServer(g)
    await srv.start("127.0.0.1:0")
    c = K2VClient(f"http://127.0.0.1:{srv.port}", "kbkt",
                  key.key_id, key.params().secret_key)
    return g, srv, c, key


async def test_item_crud_and_causality(tmp_path):
    g, srv, c, _k = await make_k2v(tmp_path)
    # missing item
    assert await c.read_item("p", "s") is None
    # insert + read round-trips value and token
    await c.insert_item("p", "s", b"v1")
    item = await c.read_item("p", "s")
    assert item.values == [b"v1"]
    tok = item.token
    # supersede with the token: single value remains
    await c.insert_item("p", "s", b"v2", token=str(tok))
    item = await c.read_item("p", "s")
    assert item.values == [b"v2"]
    # concurrent insert WITHOUT a token: two sibling values survive
    await c.insert_item("p", "s", b"v3")
    item = await c.read_item("p", "s")
    assert sorted(item.values) == [b"v2", b"v3"]
    # resolve the conflict with the merged token
    await c.insert_item("p", "s", b"merged", token=str(item.token))
    item = await c.read_item("p", "s")
    assert item.values == [b"merged"]
    # delete with the token → gone
    await c.delete_item("p", "s", token=str(item.token))
    assert await c.read_item("p", "s") is None
    await srv.stop()
    await g.shutdown()


async def test_batch_pagination_through_tombstones(tmp_path):
    """Regression: tombstones must not stop pagination early NOR be
    resurrected/recounted by range reads and range deletes."""
    g, srv, c, _k = await make_k2v(tmp_path)
    await c.insert_batch([("p", f"k{i:03d}", b"v", None) for i in range(30)])
    # tombstone the middle 20 (k005..k024)
    d = await c.delete_range("p", start="k005", end="k025")
    assert d["deletedItems"] == 20
    # re-deleting the same range deletes NOTHING (tombstones not recounted)
    d = await c.delete_range("p", start="k005", end="k025")
    assert d["deletedItems"] == 0
    # page through with limit 5: pages may be tombstone-heavy but `more`
    # keeps the walk going; exactly the 10 live items come back
    got = []
    start = None
    for _page in range(20):
        res = await c.read_range("p", start=start, limit=5)
        got += [i["sk"] for i in res["items"]]
        if not res["more"]:
            break
        start = res["nextStart"] + "\x00"
    assert got == [f"k{i:03d}" for i in list(range(5)) + list(range(25, 30))]
    # tombstones=true surfaces the dead ones too
    res = await c.read_batch([{"partitionKey": "p", "tombstones": True,
                               "limit": 1000}])
    assert len(res[0]["items"]) == 30
    # delete the whole partition in one call (walks past the page size)
    d = await c.delete_range("p")
    assert d["deletedItems"] == 10
    res = await c.read_range("p", limit=1000)
    assert res["items"] == []
    await srv.stop()
    await g.shutdown()


async def test_poll_item_longpoll(tmp_path):
    g, srv, c, _k = await make_k2v(tmp_path)
    await c.insert_item("pp", "ss", b"first")
    item = await c.read_item("pp", "ss")

    async def update_later():
        await asyncio.sleep(0.3)
        await c.insert_item("pp", "ss", b"second", token=str(item.token))

    upd = asyncio.ensure_future(update_later())
    got = await c.poll_item("pp", "ss", str(item.token), timeout=10.0)
    await upd
    assert got is not None and got.values == [b"second"]
    # timeout path: nothing changes → None after the (short) window
    got = await c.poll_item("pp", "ss", str(got.token), timeout=1.0)
    assert got is None
    await srv.stop()
    await g.shutdown()


async def test_read_index_counts(tmp_path):
    g, srv, c, _k = await make_k2v(tmp_path)
    await c.insert_batch([("pa", f"s{i}", b"x", None) for i in range(4)])
    await c.insert_batch([("pb", f"s{i}", b"x", None) for i in range(2)])

    async def entries():
        idx = await c.read_index()
        return {p["pk"]: p["entries"] for p in idx.get("partitionKeys", [])}

    for _ in range(80):  # counters propagate via the insert queue
        if await entries() == {"pa": 4, "pb": 2}:
            break
        await asyncio.sleep(0.05)
    assert await entries() == {"pa": 4, "pb": 2}
    await srv.stop()
    await g.shutdown()


async def test_read_only_key_permissions(tmp_path):
    g, srv, c, key = await make_k2v(tmp_path)
    await c.insert_item("p", "s", b"v")
    # drop to read-only
    from garage_tpu.model import BucketKeyPerm

    helper = g.helper()
    bid = await helper.resolve_global_bucket_name("kbkt")
    await helper.set_bucket_key_permissions(
        bid, key.key_id, BucketKeyPerm(True, False, False))
    # ReadBatch (POST ?search) is a READ — must pass for a read-only key
    res = await c.read_batch([{"partitionKey": "p"}])
    assert len(res[0]["items"]) == 1
    # mutation is rejected
    with pytest.raises(K2VError) as ei:
        await c.insert_item("p", "s2", b"nope")
    assert ei.value.status == 403
    await srv.stop()
    await g.shutdown()


async def test_poll_range_seen_marker_continuation(tmp_path):
    """PollRange with seen markers (ref api/k2v/range.rs + k2v/seen.rs):
    an unmarked poll returns current items + a marker; polling with that
    marker blocks until something NEW appears in the range, and the new
    marker suppresses everything already seen."""
    g, srv, c, _k = await make_k2v(tmp_path)
    await c.insert_item("pr", "a", b"va")
    await c.insert_item("pr", "b", b"vb")

    # first poll: everything is new
    out = await c.poll_range("pr", timeout=5.0)
    assert out is not None
    keys = {i["sk"] for i in out["items"]}
    assert keys == {"a", "b"}
    marker = out["seenMarker"]

    # nothing new → timeout (304 → None)
    out2 = await c.poll_range("pr", seen_marker=marker, timeout=1.0)
    assert out2 is None

    # a concurrent write wakes the poll; only the NEW item is delivered
    async def update_later():
        await asyncio.sleep(0.3)
        await c.insert_item("pr", "c", b"vc")

    upd = asyncio.ensure_future(update_later())
    out3 = await c.poll_range("pr", seen_marker=marker, timeout=10.0)
    await upd
    assert out3 is not None
    assert {i["sk"] for i in out3["items"]} == {"c"}

    # overwriting an already-seen key is ALSO new (causality advanced)
    item_a = await c.read_item("pr", "a")
    marker3 = out3["seenMarker"]

    async def update_a():
        await asyncio.sleep(0.3)
        await c.insert_item("pr", "a", b"va2", token=str(item_a.token))

    upd = asyncio.ensure_future(update_a())
    out4 = await c.poll_range("pr", seen_marker=marker3, timeout=10.0)
    await upd
    assert out4 is not None and {i["sk"] for i in out4["items"]} == {"a"}

    # prefix filter: a write outside the prefix does not wake the poll
    async def update_outside():
        await asyncio.sleep(0.3)
        await c.insert_item("pr", "zzz", b"zz")

    upd = asyncio.ensure_future(update_outside())
    out5 = await c.poll_range("pr", seen_marker=out4["seenMarker"],
                              prefix="a", timeout=1.2)
    await upd
    assert out5 is None
    await srv.stop()
    await g.shutdown()
