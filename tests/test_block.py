"""Block store tests: DataBlock codec, DataLayout, BlockManager RPC
get/put on a real 3-node loopback cluster, refcounting, resync
(missing-fetch and offload), and the batch-first scrub worker."""

import asyncio
import os

import pytest

from garage_tpu.block import (
    BlockManager,
    BlockResyncManager,
    DataBlock,
    DataLayout,
    ScrubWorker,
)
from garage_tpu.block.layout import DRIVE_NPART, drive_partition
from garage_tpu.block.repair import (
    BlockStoreIterator,
    RebalanceWorker,
    ScrubWorkerState,
)
from garage_tpu.block.resync import ResyncPersistedConfig
from garage_tpu.utils.persister import Persister
from garage_tpu.db import open_db
from garage_tpu.rpc.replication_mode import parse_replication_mode
from garage_tpu.table import TableShardedReplication
from garage_tpu.utils.data import Hash, blake2s_sum, gen_uuid
from garage_tpu.utils.error import CorruptData, GarageError

from tests.test_table import make_cluster, shutdown

pytestmark = pytest.mark.asyncio


# --- DataBlock ---


def test_datablock_plain_verify():
    data = os.urandom(4096)
    h = blake2s_sum(data)
    b = DataBlock.plain(data)
    b.verify(h)  # ok
    with pytest.raises(CorruptData):
        DataBlock.plain(data[:-1] + b"\x00").verify(h)


def test_datablock_compression_roundtrip():
    data = b"a" * 100_000  # compressible
    b = DataBlock.from_buffer(data, compression_level=3)
    assert b.compressed and len(b) < len(data)
    assert b.decompressed() == data
    b.verify(blake2s_sum(data))  # zstd checksum path
    # corrupted frame fails
    bad = DataBlock(b.inner[:-2] + b"\x00\x00", compressed=True)
    with pytest.raises(CorruptData):
        bad.verify(blake2s_sum(data))
    # incompressible data stays plain
    rnd = os.urandom(100_000)
    assert not DataBlock.from_buffer(rnd, compression_level=3).compressed
    assert not DataBlock.from_buffer(rnd, compression_level=None).compressed


# --- DataLayout ---


def test_data_layout_assignment(tmp_path):
    d1, d2 = str(tmp_path / "d1"), str(tmp_path / "d2")
    lay = DataLayout.initialize([{"path": d1, "capacity": 100}, {"path": d2, "capacity": 300}])
    counts = [0, 0]
    for p in lay.part_prim:
        counts[p] += 1
    assert sum(counts) == DRIVE_NPART
    assert abs(counts[1] - 3 * counts[0]) <= 4  # ∝ capacity
    # deterministic: same config → same assignment
    lay2 = DataLayout.initialize([{"path": d1, "capacity": 100}, {"path": d2, "capacity": 300}])
    assert lay.part_prim == lay2.part_prim
    # update: moved partitions keep old dir as secondary
    lay3 = lay.update([{"path": d1, "capacity": 300}, {"path": d2, "capacity": 100}])
    moved = [p for p in range(DRIVE_NPART) if lay3.part_prim[p] != lay.part_prim[p]]
    assert moved, "capacity flip must move partitions"
    for p in moved:
        assert lay.part_prim[p] in lay3.part_sec[p]
    # persistence roundtrip
    enc = lay3.encode()
    assert DataLayout.decode(enc).part_prim == lay3.part_prim


# --- cluster harness ---


async def make_block_cluster(tmp_path, n=3, mode="3"):
    systems = await make_cluster(tmp_path, n=n, mode=mode)
    m = parse_replication_mode(mode)
    managers = []
    for i, s in enumerate(systems):
        db = open_db("memory")
        repl = TableShardedReplication(s, m.replication_factor, 1, m.write_quorum)
        s.config.data_dir = [{"path": str(tmp_path / f"n{i}" / "data")}]
        mgr = BlockManager(s.config, db, s, repl)
        mgr.resync = BlockResyncManager(mgr, db)
        managers.append(mgr)
    return systems, managers


async def test_block_put_get_roundtrip(tmp_path):
    systems, managers = await make_block_cluster(tmp_path)
    data = os.urandom(200_000)
    h = blake2s_sum(data)
    await managers[0].rpc_put_block(h, data)
    await asyncio.sleep(0.1)  # straggler drain
    stored = sum(1 for m in managers if m.is_block_present(h))
    assert stored == 3
    for m in managers:
        got = await m.rpc_get_block(h)
        assert got == data
    await shutdown(systems)


async def test_block_get_tries_other_nodes(tmp_path):
    systems, managers = await make_block_cluster(tmp_path)
    data = os.urandom(50_000)
    h = blake2s_sum(data)
    await managers[0].rpc_put_block(h, data)
    await asyncio.sleep(0.1)
    # delete the local copy on one node; its reads must hit the network
    victim = managers[1]
    found = victim.find_block(h)
    if found:
        os.remove(found[0])
    got = await victim.rpc_get_block(h)
    assert got == data
    await shutdown(systems)


async def test_corrupt_block_detected_and_requeued(tmp_path):
    systems, managers = await make_block_cluster(tmp_path)
    data = os.urandom(150_000)
    h = blake2s_sum(data)
    await managers[0].rpc_put_block(h, data)
    await asyncio.sleep(0.1)
    m = next(m for m in managers if m.is_block_present(h))
    path, _ = m.find_block(h)
    with open(path, "r+b") as f:
        f.seek(100)
        f.write(b"\xff\xff\xff\xff")
    with pytest.raises(CorruptData):
        await m.read_block(h)
    assert not m.is_block_present(h)  # moved aside
    assert os.path.exists(path + ".corrupted")
    assert m.resync.queue_len() == 1  # requeued for re-fetch
    await shutdown(systems)


async def test_quarantine_resync_reserve_loop(tmp_path):
    """Corrupt a stored copy on disk (FaultInjector.corrupt_block): the
    client read still returns correct bytes (failover), the bad copy is
    quarantined, and after the queued resync runs a later read serves a
    healed LOCAL copy."""
    from garage_tpu.testing.faults import FaultInjector

    systems, managers = await make_block_cluster(tmp_path)
    data = os.urandom(120_000)
    h = blake2s_sum(data)
    await managers[0].rpc_put_block(h, data)
    await asyncio.sleep(0.1)
    inj = FaultInjector([], configs=[s.config for s in systems])
    i, m = next((i, m) for i, m in enumerate(managers)
                if m.is_block_present(h))
    path, _ = m.find_block(h)
    assert inj.corrupt_block(i, h)
    # the client gets correct bytes — the corrupt local copy fails
    # verify, is quarantined, and the read fails over to a replica
    assert await m.rpc_get_block(h) == data
    assert os.path.exists(path + ".corrupted")
    assert not m.is_block_present(h)
    assert m.quarantined == 1
    assert m.resync.enqueue_counts.get("corrupt_read") == 1
    # drive the queued refetch; a later read serves the healed copy
    m.db.transaction(lambda tx: m.rc.block_incref(tx, h))
    await m.resync.resync_block(h)
    assert m.is_block_present(h)
    assert (await m.read_block(h)).decompressed() == data
    await shutdown(systems)


async def test_resync_fetches_missing_block(tmp_path):
    systems, managers = await make_block_cluster(tmp_path)
    data = os.urandom(80_000)
    h = blake2s_sum(data)
    await managers[0].rpc_put_block(h, data)
    await asyncio.sleep(0.1)
    victim = managers[1]
    # mark needed (rc>0) then delete local file → resync must re-fetch
    victim.db.transaction(lambda tx: victim.rc.block_incref(tx, h))
    found = victim.find_block(h)
    if found:
        os.remove(found[0])
    assert await victim.need_block(h)
    await victim.resync.resync_block(h)
    assert victim.is_block_present(h)
    assert (await victim.rpc_get_block(h)) == data
    await shutdown(systems)


async def test_resync_offloads_and_deletes_unneeded(tmp_path):
    systems, managers = await make_block_cluster(tmp_path)
    data = os.urandom(60_000)
    h = blake2s_sum(data)
    # only node 0 has the block; rc=0 there (deletable immediately)
    await managers[0].write_block(h, DataBlock.plain(data))
    import garage_tpu.block.rc as rc_mod

    # force the deletion timer into the past
    managers[0].rc.tree.insert(
        bytes(h), rc_mod.pack([0, 1])
    )
    # other replicas need it: rc>0, no file
    for m in managers[1:]:
        m.db.transaction(lambda tx, m=m: m.rc.block_incref(tx, h))
    await managers[0].resync.resync_block(h)
    await asyncio.sleep(0.1)
    assert not managers[0].is_block_present(h)  # deleted locally
    for m in managers[1:]:
        if bytes(m.system.id) in [bytes(x) for x in managers[0].replication.write_nodes(h)]:
            assert m.is_block_present(h)
    await shutdown(systems)


async def test_drain_push_moves_block_before_refs_migrate(tmp_path):
    """A layout change can un-assign a node while its refs are still
    live (table sync lags the ring).  The draining holder must push to
    the new owners immediately — need_block's drain flag lets them
    accept on ring assignment alone — and must NOT drop its local copy
    while rc is nonzero (deletion belongs to the migrating branch once
    the refs leave)."""
    systems, managers = await make_block_cluster(tmp_path, n=4)
    data = os.urandom(70_000)
    h = blake2s_sum(data)
    owners = [bytes(x) for x in managers[0].replication.write_nodes(h)]
    victim = next(m for m in managers if bytes(m.system.id) not in owners)
    # the un-assigned node holds the block and still references it:
    # exactly the post-drain state before table sync migrates the refs
    await victim.write_block(h, DataBlock.plain(data))
    victim.db.transaction(lambda tx: victim.rc.block_incref(tx, h))
    assert not victim.is_assigned(h)
    holders = [m for m in managers if bytes(m.system.id) in owners]
    for m in holders:
        # their rc is as stale as the victim's assignment: without the
        # drain flag nobody would accept and the drain's bytes would
        # wait on metadata migration
        assert not await m.need_block(h)
        assert await m.need_block(h, drain=True)
    await victim.resync.resync_block(h)
    for m in holders:
        assert m.is_block_present(h)
    # refs still live → the local copy survives the push
    assert victim.is_block_present(h)
    await shutdown(systems)


# --- scrub ---


async def test_scrub_batch_detects_corruption(tmp_path):
    systems, managers = await make_block_cluster(tmp_path, n=1, mode="1")
    m = managers[0]
    datas = [os.urandom(30_000) for _ in range(20)]
    hashes = [blake2s_sum(d) for d in datas]
    for h, d in zip(hashes, datas):
        await m.write_block(h, DataBlock.plain(d))
    # corrupt 3 of them on disk
    for h in hashes[:3]:
        path, _ = m.find_block(h)
        with open(path, "r+b") as f:
            f.seek(10)
            f.write(b"\x00\x01\x02\x03")
    scrub = ScrubWorker(m)
    scrub.send_command("start")
    while (await scrub.work()).name in ("BUSY", "THROTTLED"):
        pass
    assert scrub.state.corruptions == 3
    assert m.resync.queue_len() == 3
    present = sum(1 for h in hashes if m.is_block_present(h))
    assert present == 17
    await shutdown(systems)


async def test_scrub_checkpoint_and_resume(tmp_path):
    """Kill mid-scrub (drop the worker), restart from the same persister:
    the new worker resumes running from the checkpointed position
    (ref repair.rs:185-229 persisted scrub state)."""
    systems, managers = await make_block_cluster(tmp_path, n=1, mode="1")
    m = managers[0]
    for _ in range(40):
        d = os.urandom(5_000)
        await m.write_block(blake2s_sum(d), DataBlock.plain(d))
    pers = Persister(str(tmp_path / "meta"), "scrub_info", ScrubWorkerState)
    w = ScrubWorker(m, persister=pers)
    w.send_command("start")
    await w.work()   # applies start (checkpoints), scrubs the first prefix
    w._checkpoint(force=True)
    # persisted position = VERIFIED position; the iterator runs one
    # prefix ahead (read-ahead), so resume re-verifies the in-flight one
    pos = w.state.position
    assert w.state.running and pos > 0
    assert w.iterator.position >= pos
    w._drop_read_ahead()

    # "kill -9": drop w without any shutdown; restart from disk
    w2 = ScrubWorker(m, persister=pers)
    assert w2.state.running
    assert w2.iterator is not None and w2.iterator.position == pos
    while (await w2.work()).name in ("BUSY", "THROTTLED"):
        pass
    assert not w2.state.running and w2.state.time_last_complete > 0
    # completion checkpointed: a third restart schedules the next run
    w3 = ScrubWorker(m, persister=pers)
    assert not w3.state.running and w3.iterator is None
    assert w3.state.time_next_run > 0
    await shutdown(systems)


async def test_resync_config_persists(tmp_path):
    systems, managers = await make_block_cluster(tmp_path, n=1, mode="1")
    m = managers[0]
    pers = Persister(str(tmp_path / "meta"), "resync_cfg", ResyncPersistedConfig)
    r = BlockResyncManager(m, open_db("memory"), persister=pers)
    r.set_n_workers(4)
    r.set_tranquility(7)
    with pytest.raises(ValueError):
        r.set_n_workers(0)
    with pytest.raises(ValueError):
        r.set_n_workers(99)
    r2 = BlockResyncManager(m, open_db("memory"), persister=pers)
    assert r2.n_workers == 4 and r2.tranquility == 7
    await shutdown(systems)


async def test_block_store_iterator_resumable(tmp_path):
    systems, managers = await make_block_cluster(tmp_path, n=1, mode="1")
    m = managers[0]
    hashes = []
    for _ in range(30):
        d = os.urandom(1000)
        h = blake2s_sum(d)
        hashes.append(h)
        await m.write_block(h, DataBlock.plain(d))
    roots = [dd.path for dd in m.data_layout.data_dirs]
    it = BlockStoreIterator(roots)
    seen = []
    # stop midway, then resume from the persisted position
    while len(seen) < 15:
        batch = it.next_prefix()
        assert batch is not None
        seen.extend(h for h, _p, _c in batch)
    it2 = BlockStoreIterator(roots, position=it.position)
    while (batch := it2.next_prefix()) is not None:
        seen.extend(h for h, _p, _c in batch)
    assert sorted(bytes(h) for h in seen) == sorted(bytes(h) for h in hashes)
    await shutdown(systems)


async def test_streaming_get_midstream_failover(tmp_path):
    """A node dying mid-stream must not kill the read: the stream resumes
    on the next replica, skipping already-delivered bytes (ref
    manager.rs:231-345; VERDICT r1 weak #5)."""
    for payload in (os.urandom(600_000), b"A" * 600_000):  # plain + zstd
        systems, managers = await make_block_cluster(tmp_path / str(len(payload)))
        h = blake2s_sum(payload)
        await managers[0].rpc_put_block(h, payload)
        await asyncio.sleep(0.2)
        assert sum(1 for m in managers if m.is_block_present(h)) == 3

        # poison node0 (= self, first in request_order): its get_block
        # stream dies after ~200 KB on the wire
        m0 = managers[0]
        orig = m0._handle

        async def poison(remote, msg, body, _orig=orig):
            resp, stream = await _orig(remote, msg, body)
            if msg.get("t") == "get_block" and stream is not None:
                async def dying(_s=stream):
                    sent = 0
                    async for c in _s:
                        yield c
                        sent += len(c)
                        if sent >= 200_000:
                            raise RuntimeError("simulated node crash")
                return resp, dying()
            return resp, stream

        m0.endpoint.set_handler(poison)
        got = bytearray()
        async for chunk in m0.rpc_get_block_streaming(h):
            got.extend(chunk)
        assert bytes(got) == payload

        # the RAW fetch path (resync/repair) rides the SAME failover:
        # a storable DataBlock comes back whole despite the mid-stream
        # death, re-compressed when that pays (for_storage)
        block = await m0.rpc_get_raw_block(h, for_storage=True)
        assert block.decompressed() == payload
        assert bytes(blake2s_sum(block.decompressed())) == bytes(h)
        await shutdown(systems)


async def test_scrub_with_hybrid_codec(tmp_path):
    """The production scrub worker runs with codec backend='hybrid'
    (config-selected): corruption detection works identically while the
    work-stealing engine splits batches between CPU and the device
    backend (JAX CPU platform here)."""
    systems, managers = await make_block_cluster(tmp_path, n=1, mode="1")
    m = managers[0]
    from garage_tpu.ops import make_codec

    m.codec = make_codec("hybrid", rs_data=4, rs_parity=2,
                         hybrid_group_blocks=8)
    datas = [os.urandom(20_000) for _ in range(24)]
    hashes = [blake2s_sum(d) for d in datas]
    for h, d in zip(hashes, datas):
        await m.write_block(h, DataBlock.plain(d))
    for h in hashes[:2]:
        path, _ = m.find_block(h)
        with open(path, "r+b") as f:
            f.seek(5)
            f.write(b"\xba\xad")
    scrub = ScrubWorker(m)
    scrub.send_command("start")
    while (await scrub.work()).name in ("BUSY", "THROTTLED"):
        pass
    assert scrub.state.corruptions == 2
    assert sum(1 for h in hashes if m.is_block_present(h)) == 22
    await shutdown(systems)


async def test_rebalance_moves_blocks_to_primary_dir(tmp_path):
    """Multi-drive rebalance (ref repair.rs:531-626): after a drive-layout
    change, RebalanceWorker moves each block file into its new primary
    directory and the manager still finds/reads every block."""
    systems, managers = await make_block_cluster(tmp_path, n=1, mode="1")
    m = managers[0]
    hashes = []
    for _ in range(24):
        d = os.urandom(2000)
        h = blake2s_sum(d)
        hashes.append((h, d))
        await m.write_block(h, DataBlock.plain(d))

    # add a second, much larger drive: most partitions move primary
    d1 = m.data_layout.data_dirs[0].path
    d2 = str(tmp_path / "drive2")
    new_dirs = [{"path": d1, "capacity": 100},
                {"path": d2, "capacity": 900}]
    m.data_layout = m.data_layout.update(new_dirs)
    os.makedirs(d2, exist_ok=True)

    moved_expected = [
        h for h, _d in hashes
        if m.data_layout.primary_dir(h) != d1
    ]
    assert moved_expected, "bigger drive must take over some partitions"

    w = RebalanceWorker(m)
    while (await w.work()).name != "DONE":
        pass
    assert w.moved >= len(moved_expected)

    for h, d in hashes:
        path, compressed = m.find_block(h)
        assert path.startswith(m.data_layout.primary_dir(h))
        assert not compressed
        block = await m.read_block(h)
        assert block.inner == d
    await shutdown(systems)


async def test_parity_sidecar_local_reconstruction(tmp_path):
    """RS decode-repair with every replica unreachable (BASELINE config
    #4): scrub persists parity sidecars; a corrupted block is rebuilt
    LOCALLY from its codeword's surviving pieces — zero network — and a
    lost block resyncs from parity before trying the (dead) replicas."""
    from garage_tpu.block.parity import ParityStore
    from garage_tpu.block.repair import ScrubWorker

    systems, managers = await make_block_cluster(tmp_path, n=1, mode="1")
    m = managers[0]
    m.blocks_reconstructed = 0
    db = open_db("memory")
    m.parity_store = ParityStore(m, db, m.codec)

    # 16 blocks = 2 full RS(8,4) codewords, varying sizes; one of them
    # stored COMPRESSED (must be covered by parity too)
    blocks = {}
    for i in range(16):
        if i == 3:
            d = b"compressible " * 900 + os.urandom(50)
        else:
            d = os.urandom(9000 + 137 * i)
        h = blake2s_sum(d)
        blocks[bytes(h)] = d
        await m.write_block(h, DataBlock.from_buffer(d, 3))
    assert any(c for _p, c in map(m.find_block, map(Hash, blocks))), \
        "expected at least one compressed block"

    # scrub pass persists the parity sidecars
    w = ScrubWorker(m)
    w.send_command("start")
    while (await w.work()).name in ("BUSY", "THROTTLED"):
        pass
    assert m.parity_store.stats()["indexed_blocks"] == 16
    assert w.state.corruptions == 0

    # corrupt one block on disk; scrub detects it and repairs it from
    # LOCAL parity (no resync entry — the network path was never needed)
    victim = next(iter(blocks))
    vh = Hash(victim)
    path, _ = m.find_block(vh)
    data = bytearray(blocks[victim])
    data[100] ^= 0xFF
    with open(path, "wb") as f:
        f.write(bytes(data))

    w2 = ScrubWorker(m)
    w2.send_command("start")
    while (await w2.work()).name in ("BUSY", "THROTTLED"):
        pass
    assert w2.state.corruptions == 1
    assert m.blocks_reconstructed == 1
    assert m.resync.queue_len() == 0, "local repair must not hit the network"
    block = await m.read_block(vh)
    assert block.inner == blocks[victim]

    # a DELETED block also reconstructs via the resync path, again with
    # zero replicas available (single-node cluster: there are none)
    victim2 = list(blocks)[5]
    vh2 = Hash(victim2)
    path2, _ = m.find_block(vh2)
    os.remove(path2)
    rebuilt = m.parity_store.try_reconstruct(vh2)
    assert rebuilt == blocks[victim2]

    # churn + GC: remove a block so its codeword can never re-form, then
    # run TWO more passes on the SAME worker (the purge grace is one
    # pass); the orphaned sidecar is deleted and its index entries pruned
    import time as _time

    removed_h = list(blocks)[10]
    rf = m.find_block(Hash(removed_h))
    os.remove(rf[0])
    files_before = sum(
        len(fs) for _d, _s, fs in os.walk(m.parity_store.dir))
    _time.sleep(0.05)
    for _pass in range(2):
        w2.send_command("start")
        while (await w2.work()).name in ("BUSY", "THROTTLED"):
            pass
        _time.sleep(0.05)
    files_after = sum(
        len(fs) for _d, _s, fs in os.walk(m.parity_store.dir))
    assert files_after < files_before, "orphaned sidecar never purged"
    # 15 surviving blocks = 1 full codeword; the other 7 lose coverage
    assert m.parity_store.stats()["indexed_blocks"] == 8
    assert not m.parity_store.coverage(Hash(removed_h))

    # fewer than k surviving pieces → reconstruction refuses
    for i, hb in enumerate(list(blocks)):
        if hb in (victim, victim2):
            continue
        found = m.find_block(Hash(hb))
        if found:
            os.remove(found[0])
    assert m.parity_store.try_reconstruct(vh2) is None
    await shutdown(systems)


async def test_write_time_parity(tmp_path):
    """BASELINE config #3: parity exists from FIRST WRITE (no scrub pass
    needed).  Full codewords flush at k blocks; a partial codeword
    (object smaller than k blocks) flushes on drain and reconstructs
    against implicit zero shards."""
    from garage_tpu.block.parity import ParityStore, WriteParityAccumulator

    systems, managers = await make_block_cluster(tmp_path, n=1, mode="1")
    m = managers[0]
    m.blocks_reconstructed = 0
    db = open_db("memory")
    m.parity_store = ParityStore(m, db, m.codec)
    m.write_parity = WriteParityAccumulator(m.parity_store, m.codec,
                                            flush_after=0.2)

    k = m.codec.params.rs_data
    # one full codeword: k blocks, varying sizes, one compressible
    datas = [os.urandom(8000 + 321 * i) for i in range(k - 1)]
    datas.append(b"compressible " * 700)
    hs = [blake2s_sum(d) for d in datas]
    for h, d in zip(hs, datas):
        await m.write_block(h, DataBlock.from_buffer(d, 3))
    # k-th write triggers the flush; encode runs async — wait for it
    for _ in range(100):
        if m.parity_store.coverage(hs[0]):
            break
        await asyncio.sleep(0.02)
    assert all(m.parity_store.coverage(h) for h in hs), \
        "full codeword must be covered right after the k-th write, no scrub"

    # corrupt one member on disk; read path detects, resync repairs from
    # the WRITE-TIME sidecar (zero network: single-node cluster)
    victim = hs[2]
    path, _ = m.find_block(victim)
    raw = bytearray(open(path, "rb").read())
    raw[50] ^= 0xFF
    open(path, "wb").write(bytes(raw))
    assert m.parity_store.try_reconstruct(victim) == datas[2]

    # partial codeword: 2 more blocks (< k), flushed by the timer
    small = [os.urandom(5000), os.urandom(6000)]
    sh = [blake2s_sum(d) for d in small]
    for h, d in zip(sh, small):
        await m.write_block(h, DataBlock.plain(d))
    for _ in range(200):
        if m.parity_store.coverage(sh[0]):
            break
        await asyncio.sleep(0.02)
    assert m.parity_store.coverage(sh[0]) and m.parity_store.coverage(sh[1])
    # delete one member: reconstruction uses the survivor + zero shards
    p2, _ = m.find_block(sh[1])
    os.remove(p2)
    assert m.parity_store.try_reconstruct(sh[1]) == small[1]

    # dedupe: re-writing an existing block must not enter a new codeword
    before = len(m.write_parity._pending)
    await m.write_block(hs[0], DataBlock.from_buffer(datas[0], 3))
    assert len(m.write_parity._pending) == before
    await shutdown(systems)


async def test_write_time_parity_drain_flushes_tail(tmp_path):
    """Shutdown with a partial codeword pending: drain() must flush and
    persist it (clean stop loses nothing)."""
    from garage_tpu.block.parity import ParityStore, WriteParityAccumulator

    systems, managers = await make_block_cluster(tmp_path, n=1, mode="1")
    m = managers[0]
    db = open_db("memory")
    m.parity_store = ParityStore(m, db, m.codec)
    m.write_parity = WriteParityAccumulator(m.parity_store, m.codec,
                                            flush_after=60.0)  # never fires
    d = os.urandom(7000)
    h = blake2s_sum(d)
    await m.write_block(h, DataBlock.plain(d))
    assert not m.parity_store.coverage(h)
    await m.write_parity.drain()
    assert m.parity_store.coverage(h)
    await shutdown(systems)


async def test_parity_geometry_change_recovers_coverage(tmp_path):
    """Regression: the sidecar group id must include the (k, m) codec
    geometry.  With member-hashes-only gids, changing rs_parity made
    put_codeword mtime-touch the OLD-geometry file every pass (so the
    purge never removed it) while _load_manifest rejected it on its
    (k, m) check — local-repair coverage was silently and permanently
    lost for the codeword."""
    from garage_tpu.block.parity import ParityStore
    from garage_tpu.ops import make_codec

    systems, managers = await make_block_cluster(tmp_path, n=1, mode="1")
    m = managers[0]
    db = open_db("memory")
    m.codec = make_codec("cpu", rs_data=4, rs_parity=2, batch_blocks=64)
    store = ParityStore(m, db, m.codec)

    datas = [os.urandom(5000 + i) for i in range(4)]
    hs = [blake2s_sum(d) for d in datas]
    for h, d in zip(hs, datas):
        await m.write_block(h, DataBlock.plain(d))
    parity = m.codec.rs_encode_blocks(datas)[0]
    store.put_codeword(hs, [len(d) for d in datas], parity)
    assert store.coverage(hs[0])

    # operator changes rs_parity 2 → 3; same members re-encode
    m.codec = make_codec("cpu", rs_data=4, rs_parity=3, batch_blocks=64)
    store2 = ParityStore(m, db, m.codec)
    assert not store2.coverage(hs[0]), "old-geometry sidecar must not count"
    parity3 = m.codec.rs_encode_blocks(datas)[0]
    store2.put_codeword(hs, [len(d) for d in datas], parity3)
    # the new-geometry sidecar must be a NEW file (not a touch of the old
    # one), loadable, and able to reconstruct
    assert store2.coverage(hs[0])
    found = m.find_block(hs[1])
    os.remove(found[0])
    assert store2.try_reconstruct(hs[1]) == datas[1]
    await shutdown(systems)


async def test_resync_prefers_local_parity_over_network(tmp_path):
    """The resync missing-block path reconstructs from the local parity
    sidecar BEFORE trying any replica — on a 1-node cluster there are no
    replicas at all, so success proves zero network was needed."""
    from garage_tpu.block.parity import ParityStore
    from garage_tpu.block.repair import ScrubWorker

    systems, managers = await make_block_cluster(tmp_path, n=1, mode="1")
    m = managers[0]
    m.blocks_reconstructed = 0
    m.parity_store = ParityStore(m, open_db("memory"), m.codec)

    datas = [os.urandom(12_000 + i) for i in range(8)]  # one full codeword
    hs = [blake2s_sum(d) for d in datas]
    for h, d in zip(hs, datas):
        await m.write_block(h, DataBlock.plain(d))
    w = ScrubWorker(m)
    w.send_command("start")
    while (await w.work()).name in ("BUSY", "THROTTLED"):
        pass
    assert m.parity_store.stats()["indexed_blocks"] == 8

    # rc>0 + file gone → resync_block must restore it locally
    victim = hs[3]
    m.db.transaction(lambda tx: m.rc.block_incref(tx, victim))
    os.remove(m.find_block(victim)[0])
    assert await m.need_block(victim)
    await m.resync.resync_block(victim)
    assert m.is_block_present(victim)
    assert (await m.read_block(victim)).inner == datas[3]
    assert m.blocks_reconstructed == 1
    await shutdown(systems)
