"""Daemon assembly — the `garage_tpu server` entry point.

Equivalent of reference src/garage/server.rs:30-192 (SURVEY.md §2.9):
read config → build Garage → spawn background workers → start RPC
listener + membership loops → start S3/Admin/Web API servers → wait for
SIGINT/SIGTERM → graceful shutdown in reverse order (API servers first,
then workers with their 8s deadline, then the RPC system, then the DB).
"""

from __future__ import annotations

import asyncio
import faulthandler
import logging
import signal
from typing import Optional

from .admin import AdminRpcHandler
from .api.admin_server import AdminApiServer
from .api.s3.api_server import S3ApiServer
from .model import Garage
from .utils.config import Config, read_config
from .web import WebServer

logger = logging.getLogger("garage_tpu.server")


class Server:
    """A running node with all its services (also used in-process by the
    integration test harness)."""

    def __init__(self, config: Config):
        self.config = config
        self.garage = Garage(config)
        self.admin_rpc = AdminRpcHandler(self.garage)
        self.s3: Optional[S3ApiServer] = None
        self.admin: Optional[AdminApiServer] = None
        self.web: Optional[WebServer] = None
        self.k2v = None

    async def start(self) -> None:
        g = self.garage
        g.spawn_workers()
        await g.system.run()  # binds the RPC socket + starts gossip loops
        if self.config.s3_api_bind_addr:
            self.s3 = S3ApiServer(g)
            await self.s3.start(self.config.s3_api_bind_addr)
        if self.config.admin_api_bind_addr:
            self.admin = AdminApiServer(g)
            await self.admin.start(self.config.admin_api_bind_addr)
        if self.config.web_bind_addr:
            self.web = WebServer(g)
            await self.web.start(self.config.web_bind_addr)
        if self.config.k2v_api_bind_addr:
            from .api.k2v_server import K2VApiServer

            self.k2v = K2VApiServer(g)
            await self.k2v.start(self.config.k2v_api_bind_addr)
        logger.info(
            "node %s up (rpc %s)",
            bytes(g.system.id).hex()[:16],
            self.config.rpc_bind_addr,
        )

    async def stop(self) -> None:
        # reverse order of start (ref server.rs:135-171).  The S3 front
        # door drains first: new requests shed typed while the in-flight
        # set finishes inside api.drain_timeout, with the drain state
        # gossiped so sibling gateways absorb load before the socket
        # closes (docs/ROBUSTNESS.md "Geo-WAN & gateway failover")
        if self.s3 is not None:
            try:
                await self.s3.drain()
            except Exception:
                logger.exception("S3 drain failed; closing hard")
        for srv in (self.k2v, self.web, self.admin, self.s3):
            if srv is not None:
                await srv.stop()
        await self.garage.shutdown()


async def run_server(config_path: str) -> None:
    # SIGUSR1 → dump all thread stacks to stderr (live-debug a stuck node)
    faulthandler.register(signal.SIGUSR1, all_threads=True)
    config = read_config(config_path)
    server = Server(config)
    await server.start()

    stop = asyncio.Event()
    loop = asyncio.get_running_loop()
    for sig in (signal.SIGINT, signal.SIGTERM):
        try:
            loop.add_signal_handler(sig, stop.set)
        except NotImplementedError:  # pragma: no cover
            pass
    await stop.wait()
    logger.info("shutting down…")
    await server.stop()
