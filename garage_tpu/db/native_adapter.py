"""Native log-structured engine adapter — the default metadata engine.

Binds garage_tpu/native/logdb.cpp over ctypes.  Fills the role of the
reference's LMDB default engine (ref db/lmdb_adapter.rs:1-354): a fast
native ordered KV store behind the Db/Tree/Transaction facade.  (LMDB
itself is not present in this environment; logdb is an original
bitcask-style design — append-only CRC'd log with commit records, in-RAM
ordered key index, values pread on demand.  See logdb.cpp.)

Transactions use a Python-side overlay (reads see uncommitted writes,
ordered iteration merges the overlay) applied atomically through one
`ldb_apply` batch — a single commit record, so a crash never exposes a
partial transaction.  Serializability comes from the adapter lock held
for the closure, the same contract as the other engines.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Callable, Dict, Iterator, List, Optional, Tuple

from ..utils.error import DbError
from . import IDb, Transaction, TxAbort

_NATIVE_DIR = os.path.join(
    os.path.dirname(os.path.dirname(__file__)), "native"
)
# GARAGE_NATIVE_SUFFIX=.asan/.tsan → sanitizer-instrumented variant
# (make asan/tsan in native/; run under the matching LD_PRELOAD)
_SO_NAME = "liblogdb{}.so".format(
    os.environ.get("GARAGE_NATIVE_SUFFIX", ""))
_SO_PATH = os.path.join(_NATIVE_DIR, _SO_NAME)

_lib = None
_lib_err: Optional[str] = None


def _load() -> ctypes.CDLL:
    global _lib, _lib_err
    if _lib is not None:
        return _lib
    if _lib_err is not None:
        raise DbError(f"native logdb unavailable: {_lib_err}")
    try:
        lib = ctypes.CDLL(_SO_PATH)
    except OSError:
        # stale or missing binary (e.g. built on another host with
        # -march=native): one rebuild attempt
        try:
            target = ("asan" if _SO_NAME.endswith(".asan.so")
                      else "tsan" if _SO_NAME.endswith(".tsan.so")
                      else "liblogdb.so")
            subprocess.run(
                ["make", "-C", _NATIVE_DIR, "-s", target],
                check=True, capture_output=True, timeout=120,
            )
            lib = ctypes.CDLL(_SO_PATH)
        except Exception as e:  # noqa: BLE001
            _lib_err = str(e)
            raise DbError(f"native logdb unavailable: {e}")
    c = ctypes
    lib.ldb_open.restype = c.c_void_p
    lib.ldb_open.argtypes = [c.c_char_p, c.c_int]
    lib.ldb_open_tree.restype = c.c_int
    lib.ldb_open_tree.argtypes = [c.c_void_p, c.c_char_p, c.c_uint32]
    lib.ldb_tree_count.restype = c.c_int
    lib.ldb_tree_count.argtypes = [c.c_void_p]
    lib.ldb_tree_name.restype = c.c_int
    lib.ldb_tree_name.argtypes = [c.c_void_p, c.c_int, c.c_char_p, c.c_uint32]
    lib.ldb_get.restype = c.c_long
    lib.ldb_get.argtypes = [c.c_void_p, c.c_int, c.c_char_p, c.c_uint32,
                            c.c_void_p, c.c_uint32]
    lib.ldb_len.restype = c.c_long
    lib.ldb_len.argtypes = [c.c_void_p, c.c_int]
    lib.ldb_apply.restype = c.c_int
    lib.ldb_apply.argtypes = [c.c_void_p, c.c_char_p, c.c_uint64]
    lib.ldb_iter_new.restype = c.c_void_p
    lib.ldb_iter_new.argtypes = [c.c_void_p, c.c_int, c.c_char_p, c.c_uint32,
                                 c.c_int, c.c_char_p, c.c_uint32, c.c_int,
                                 c.c_int]
    lib.ldb_iter_next.restype = c.c_int
    lib.ldb_iter_next.argtypes = [
        c.c_void_p, c.POINTER(c.POINTER(c.c_uint8)), c.POINTER(c.c_uint32),
        c.POINTER(c.POINTER(c.c_uint8)), c.POINTER(c.c_uint32),
    ]
    lib.ldb_iter_free.argtypes = [c.c_void_p]
    lib.ldb_sync.restype = c.c_int
    lib.ldb_sync.argtypes = [c.c_void_p]
    lib.ldb_compact.restype = c.c_int
    lib.ldb_compact.argtypes = [c.c_void_p]
    lib.ldb_snapshot.restype = c.c_int
    lib.ldb_snapshot.argtypes = [c.c_void_p, c.c_char_p]
    lib.ldb_close.argtypes = [c.c_void_p]
    _lib = lib
    return lib


def _pack_op(op: int, tree: int, key: bytes, value: bytes) -> bytes:
    import struct

    return struct.pack("<BIII", op, tree, len(key), len(value)) + key + value


class NativeDb(IDb):
    engine = "native"

    def __init__(self, path: str, fsync: bool = False):
        self._lib = _load()
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self._h = self._lib.ldb_open(path.encode(), 1 if fsync else 0)
        if not self._h:
            raise DbError(f"cannot open native db at {path}")
        self._lock = threading.RLock()
        self._names: Dict[str, int] = {}
        n = self._lib.ldb_tree_count(self._h)
        buf = ctypes.create_string_buffer(4096)
        for i in range(n):
            ln = self._lib.ldb_tree_name(self._h, i, buf, 4096)
            if 0 <= ln <= 4096:
                self._names[buf.raw[:ln].decode()] = i

    # --- engine interface ---

    def open_tree(self, name: str) -> int:
        with self._lock:
            i = self._names.get(name)
            if i is None:
                i = self._lib.ldb_open_tree(self._h, name.encode(),
                                            len(name.encode()))
                if i < 0:
                    raise DbError(f"cannot open tree {name!r}")
                self._names[name] = i
            return i

    def list_trees(self) -> List[str]:
        with self._lock:
            return sorted(self._names, key=self._names.get)

    def get(self, tree: int, key: bytes) -> Optional[bytes]:
        with self._lock:
            key = bytes(key)
            n = self._lib.ldb_get(self._h, tree, key, len(key), None, 0)
            if n == -1:
                return None
            if n < 0:
                raise DbError("native get failed")
            if n == 0:
                return b""
            buf = ctypes.create_string_buffer(int(n))
            n2 = self._lib.ldb_get(self._h, tree, key, len(key), buf, int(n))
            if n2 != n:
                raise DbError("native get raced")
            return buf.raw

    def len(self, tree: int) -> int:
        n = self._lib.ldb_len(self._h, tree)
        if n < 0:
            raise DbError("bad tree")
        return int(n)

    def insert(self, tree: int, key: bytes, value: bytes) -> Optional[bytes]:
        with self._lock:
            old = self.get(tree, key)
            self._apply(_pack_op(1, tree, bytes(key), bytes(value)))
            return old

    def remove(self, tree: int, key: bytes) -> Optional[bytes]:
        with self._lock:
            old = self.get(tree, key)
            if old is not None:
                self._apply(_pack_op(2, tree, bytes(key), b""))
            return old

    def clear(self, tree: int) -> None:
        with self._lock:
            self._apply(_pack_op(5, tree, b"", b""))

    def _apply(self, ops: bytes) -> None:
        rc = self._lib.ldb_apply(self._h, ops, len(ops))
        if rc != 0:
            raise DbError(f"native apply failed rc={rc}")

    def iter_range(
        self,
        tree: int,
        start: Optional[bytes],
        end: Optional[bytes],
        reverse: bool = False,
    ) -> Iterator[Tuple[bytes, bytes]]:
        it = self._lib.ldb_iter_new(
            self._h, tree,
            start or b"", len(start) if start else 0, 0 if start is None else 1,
            end or b"", len(end) if end else 0, 0 if end is None else 1,
            1 if reverse else 0,
        )
        if not it:
            raise DbError("bad tree for iteration")
        c = ctypes
        kp = c.POINTER(c.c_uint8)()
        vp = c.POINTER(c.c_uint8)()
        kl = c.c_uint32()
        vl = c.c_uint32()
        try:
            while True:
                rc = self._lib.ldb_iter_next(
                    it, c.byref(kp), c.byref(kl), c.byref(vp), c.byref(vl)
                )
                if rc == 0:
                    return
                if rc < 0:
                    raise DbError("native iteration failed")
                k = c.string_at(kp, kl.value)
                v = c.string_at(vp, vl.value)
                yield k, v
        finally:
            self._lib.ldb_iter_free(it)

    def range_scan(
        self,
        tree: int,
        start: Optional[bytes],
        end: Optional[bytes],
        limit: int,
        reverse: bool = False,
    ) -> List[Tuple[bytes, bytes]]:
        # one native iterator, freed after `limit` rows — the in-RAM
        # ordered index seeks once and preads values on demand
        if limit <= 0:
            return []
        out: List[Tuple[bytes, bytes]] = []
        for kv in self.iter_range(tree, start, end, reverse):
            out.append(kv)
            if len(out) >= limit:
                break
        return out

    def transaction(self, fn: Callable[[Transaction], object]):
        with self._lock:
            tx = _NativeTx(self)
            try:
                res = fn(tx)
            except TxAbort as a:
                return a.value
            ops = tx.serialize()
            if ops:
                self._apply(ops)
        for hook in tx._on_commit:
            hook()
        return res

    def snapshot(self, path: str) -> None:
        with self._lock:
            os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
            if self._lib.ldb_snapshot(self._h, path.encode()) != 0:
                raise DbError("snapshot failed")

    def compact(self) -> None:
        with self._lock:
            if self._lib.ldb_compact(self._h) != 0:
                raise DbError("compaction failed")

    def close(self) -> None:
        with self._lock:
            if self._h:
                self._lib.ldb_close(self._h)
                self._h = None


class _NativeTx(Transaction):
    """Overlay transaction: writes buffer in RAM (visible to reads within
    the txn), applied as one atomic ldb_apply batch on commit."""

    def __init__(self, db: NativeDb):
        super().__init__()
        self.db = db
        # tree -> {key: value | None(=delete)}
        self.overlay: Dict[int, Dict[bytes, Optional[bytes]]] = {}

    def _o(self, tree: "Tree") -> Dict[bytes, Optional[bytes]]:
        return self.overlay.setdefault(tree.idx, {})

    def get(self, tree, key: bytes) -> Optional[bytes]:
        key = bytes(key)
        o = self._o(tree)
        if key in o:
            return o[key]
        return self.db.get(tree.idx, key)

    def len(self, tree) -> int:
        base = self.db.len(tree.idx)
        for k, v in self.overlay.get(tree.idx, {}).items():
            existed = self.db.get(tree.idx, k) is not None
            if v is None and existed:
                base -= 1
            elif v is not None and not existed:
                base += 1
        return base

    def insert(self, tree, key: bytes, value: bytes) -> Optional[bytes]:
        key = bytes(key)
        old = self.get(tree, key)
        self._o(tree)[key] = bytes(value)
        return old

    def remove(self, tree, key: bytes) -> Optional[bytes]:
        key = bytes(key)
        old = self.get(tree, key)
        if old is not None:
            self._o(tree)[key] = None
        return old

    def iter_range(self, tree, start=None, end=None, reverse=False):
        o = self.overlay.get(tree.idx, {})
        base = self.db.iter_range(tree.idx, start, end, reverse)
        ov_keys = sorted(
            (k for k in o
             if (start is None or k >= start) and (end is None or k < end)),
            reverse=reverse,
        )
        # ordered merge of the engine iterator and the overlay
        oi = 0
        bnext: Optional[Tuple[bytes, bytes]] = next(base, None)

        def ahead(a: bytes, b: bytes) -> bool:
            return a < b if not reverse else a > b

        while bnext is not None or oi < len(ov_keys):
            if bnext is None:
                take_overlay = True
            elif oi >= len(ov_keys):
                take_overlay = False
            elif ov_keys[oi] == bnext[0]:
                bnext = next(base, None)  # overlay shadows the engine row
                continue
            else:
                take_overlay = ahead(ov_keys[oi], bnext[0])
            if take_overlay:
                k = ov_keys[oi]
                oi += 1
                v = o[k]
                if v is not None:
                    yield k, v
            else:
                k, v = bnext
                bnext = next(base, None)
                if k not in o:  # not shadowed
                    yield k, v

    def serialize(self) -> bytes:
        out = []
        for tree_idx, o in self.overlay.items():
            for k, v in o.items():
                if v is None:
                    out.append(_pack_op(2, tree_idx, k, b""))
                else:
                    out.append(_pack_op(1, tree_idx, k, v))
        return b"".join(out)
