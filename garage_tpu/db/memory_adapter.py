"""In-memory DB engine — sorted maps behind one lock, with optional
snapshot + write-ahead-log durability.

Without a path: the test/ephemeral engine.  With a path: the third
DURABLE metadata engine (the slot the reference fills with sled,
ref src/db/sled_adapter.rs:1-274 — an in-RAM-indexed store persisted to
disk).  Design, deliberately different from both sled and logdb:

  - the entire working set lives in RAM (this engine's point: metadata
    reads at dict speed);
  - every committed mutation appends ONE crc-framed redo record to
    `wal.log` (torn tails are detected by length/crc and truncated at
    recovery — a kill -9 mid-append loses nothing acknowledged);
  - when the WAL outgrows max(threshold, 2 x snapshot size) the engine
    writes a full crc-framed snapshot via tmp+fsync+rename and resets
    the WAL — recovery cost stays proportional to the working set, not
    history;
  - tree ids are assigned by open order, so open_tree is itself a
    logged operation (replay reproduces the id assignment).

Conforms to the same suite as sqlite/native (tests/test_db.py) and the
same kill -9 torture harness (tests/test_db_torture.py).
"""

from __future__ import annotations

import bisect
import logging
import os
import struct
import threading
import zlib
from typing import Callable, Iterator, List, Optional, Tuple

from . import DbError, IDb, Transaction, TxAbort

logger = logging.getLogger("garage_tpu.db.memory")

_OP_INSERT = 0
_OP_REMOVE = 1
_OP_CLEAR = 2
_OP_OPEN_TREE = 3

_SNAP_MAGIC = b"GTMSNAP1"
_WAL_MAGIC = b"GTMWAL01"


def _enc_ops(ops) -> bytes:
    parts = [struct.pack("<I", len(ops))]
    for op in ops:
        code = op[0]
        if code == _OP_OPEN_TREE:
            name = op[1].encode()
            parts.append(struct.pack("<BI", code, len(name)))
            parts.append(name)
        elif code == _OP_CLEAR:
            parts.append(struct.pack("<BI", code, op[1]))
        else:
            _c, tree, key, val = op
            parts.append(struct.pack("<BII", code, tree, len(key)))
            parts.append(key)
            if code == _OP_INSERT:
                parts.append(struct.pack("<I", len(val)))
                parts.append(val)
    return b"".join(parts)


def _dec_ops(body: bytes):
    (n,) = struct.unpack_from("<I", body, 0)
    off = 4
    out = []
    for _ in range(n):
        code = body[off]
        if code == _OP_OPEN_TREE:
            (ln,) = struct.unpack_from("<I", body, off + 1)
            off += 5
            out.append((code, body[off:off + ln].decode()))
            off += ln
        elif code == _OP_CLEAR:
            (tree,) = struct.unpack_from("<I", body, off + 1)
            out.append((code, tree))
            off += 5
        else:
            tree, klen = struct.unpack_from("<II", body, off + 1)
            off += 9
            key = body[off:off + klen]
            off += klen
            if code == _OP_INSERT:
                (vlen,) = struct.unpack_from("<I", body, off)
                off += 4
                val = body[off:off + vlen]
                off += vlen
                out.append((code, tree, key, val))
            else:
                out.append((code, tree, key, None))
    return out


class _MemTree:
    __slots__ = ("name", "data", "keys")

    def __init__(self, name: str):
        self.name = name
        self.data = {}
        self.keys: List[bytes] = []  # sorted

    def insert(self, key: bytes, value: bytes) -> Optional[bytes]:
        old = self.data.get(key)
        if old is None:
            bisect.insort(self.keys, key)
        self.data[key] = value
        return old

    def remove(self, key: bytes) -> Optional[bytes]:
        old = self.data.pop(key, None)
        if old is not None:
            i = bisect.bisect_left(self.keys, key)
            del self.keys[i]
        return old

    def range_keys(
        self, start: Optional[bytes], end: Optional[bytes], reverse: bool
    ) -> List[bytes]:
        lo = 0 if start is None else bisect.bisect_left(self.keys, start)
        hi = len(self.keys) if end is None else bisect.bisect_left(self.keys, end)
        ks = self.keys[lo:hi]
        return ks[::-1] if reverse else ks


class MemoryDb(IDb):
    engine = "memory"

    def __init__(self, path: Optional[str] = None, fsync: bool = False,
                 wal_snapshot_bytes: int = 64 << 20):
        self._lock = threading.RLock()
        self._trees: List[_MemTree] = []
        self._by_name = {}
        self._path = path
        self._fsync = fsync
        self._wal_snapshot_bytes = wal_snapshot_bytes
        self._wal = None
        self._wal_bytes = 0
        self._snap_bytes = 0
        if path is not None:
            os.makedirs(path, exist_ok=True)
            self._recover()
            self._open_wal()

    # --- durability machinery (no-ops when path is None) ---

    def _snap_path(self) -> str:
        return os.path.join(self._path, "snap.db")

    def _wal_path(self) -> str:
        return os.path.join(self._path, "wal.log")

    def _open_wal(self) -> None:
        f = open(self._wal_path(), "ab")
        if f.tell() == 0:
            f.write(_WAL_MAGIC)
            f.flush()
            if self._fsync:
                os.fsync(f.fileno())
        self._wal = f
        self._wal_bytes = f.tell()

    def _log(self, ops) -> None:
        """Append one committed mutation group; called under the lock."""
        if self._wal is None or not ops:
            return
        body = _enc_ops(ops)
        self._wal.write(struct.pack("<II", len(body),
                                    zlib.crc32(body)) + body)
        self._wal.flush()
        if self._fsync:
            os.fsync(self._wal.fileno())
        self._wal_bytes += 8 + len(body)
        if self._wal_bytes > max(self._wal_snapshot_bytes,
                                 2 * self._snap_bytes):
            self._write_snapshot()

    def _write_snapshot(self) -> None:
        """Full state to snap.tmp + fsync + rename, then reset the WAL.
        Called under the lock; crash anywhere leaves either the old
        snapshot + full WAL or the new snapshot (+ possibly the stale
        WAL, whose replay is idempotent re-application of state already
        in the snapshot — see _recover)."""
        parts = [struct.pack("<I", len(self._trees))]
        for t in self._trees:
            name = t.name.encode()
            parts.append(struct.pack("<II", len(name), len(t.data)))
            parts.append(name)
            for k in t.keys:
                v = t.data[k]
                parts.append(struct.pack("<II", len(k), len(v)))
                parts.append(k)
                parts.append(v)
        body = b"".join(parts)
        tmp = self._snap_path() + ".tmp"
        with open(tmp, "wb") as f:
            f.write(_SNAP_MAGIC + struct.pack(
                "<IQ", zlib.crc32(body), len(body)) + body)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self._snap_path())
        dirfd = os.open(self._path, os.O_RDONLY)
        try:
            os.fsync(dirfd)
        finally:
            os.close(dirfd)
        self._snap_bytes = len(body)
        # reset the WAL only after the snapshot is durable
        if self._wal is not None:
            self._wal.close()
        with open(self._wal_path(), "wb") as f:
            f.write(_WAL_MAGIC)
            f.flush()
            os.fsync(f.fileno())
        self._open_wal()

    def _recover(self) -> None:
        snap = self._snap_path()
        if os.path.exists(snap):
            with open(snap, "rb") as f:
                hdr = f.read(len(_SNAP_MAGIC) + 12)
                if hdr[:len(_SNAP_MAGIC)] != _SNAP_MAGIC:
                    raise DbError(f"bad snapshot magic in {snap}")
                crc, blen = struct.unpack_from("<IQ", hdr,
                                               len(_SNAP_MAGIC))
                body = f.read(blen)
            if len(body) != blen or zlib.crc32(body) != crc:
                raise DbError(f"corrupt snapshot {snap}")
            self._load_snapshot(body)
            self._snap_bytes = blen
        wal = self._wal_path()
        if not os.path.exists(wal):
            return
        with open(wal, "rb") as f:
            raw = f.read()
        if raw[:len(_WAL_MAGIC)] != _WAL_MAGIC:
            if raw:
                raise DbError(f"bad WAL magic in {wal}")
            return
        off = len(_WAL_MAGIC)
        good_end = off
        bad_reason = None
        while off + 8 <= len(raw):
            blen, crc = struct.unpack_from("<II", raw, off)
            body = raw[off + 8:off + 8 + blen]
            if len(body) != blen:
                bad_reason = "short_record"  # torn tail: never committed
                break
            if zlib.crc32(body) != crc:
                bad_reason = "crc_mismatch"
                break
            self._replay(_dec_ops(body))
            off += 8 + blen
            good_end = off
        if good_end < len(raw):
            dropped = len(raw) - good_end
            if bad_reason is None:
                bad_reason = "short_header"  # < 8 trailing bytes
            # A short final record/header is the EXPECTED kill -9 shape
            # (the record never committed — losing it loses nothing
            # acknowledged).  A CRC mismatch FOLLOWED by parseable
            # records is a different animal: mid-file corruption eating
            # commits that were acknowledged — scan ahead to tell the
            # two apart and log accordingly (round-5 ADVICE #2; the old
            # silent truncate hid both cases).
            later_records = 0
            if bad_reason == "crc_mismatch":
                scan = off + 8 + struct.unpack_from("<II", raw, off)[0]
                while scan + 8 <= len(raw):
                    blen2, crc2 = struct.unpack_from("<II", raw, scan)
                    body2 = raw[scan + 8:scan + 8 + blen2]
                    if len(body2) != blen2 or zlib.crc32(body2) != crc2:
                        break
                    later_records += 1
                    scan += 8 + blen2
            if later_records:
                logger.error(
                    "WAL %s: mid-file CRC mismatch at offset %d with %d "
                    "parseable record(s) after it — %d bytes of "
                    "ACKNOWLEDGED commits discarded (media corruption, "
                    "not a torn tail)",
                    wal, off, later_records, dropped)
            else:
                logger.warning(
                    "WAL %s: torn tail (%s at offset %d), truncating %d "
                    "uncommitted byte(s)", wal, bad_reason, off, dropped)
            with open(wal, "r+b") as f:
                f.truncate(good_end)

    def _load_snapshot(self, body: bytes) -> None:
        (ntrees,) = struct.unpack_from("<I", body, 0)
        off = 4
        for _ in range(ntrees):
            nlen, nkeys = struct.unpack_from("<II", body, off)
            off += 8
            name = body[off:off + nlen].decode()
            off += nlen
            t = _MemTree(name)
            for _ in range(nkeys):
                klen, vlen = struct.unpack_from("<II", body, off)
                off += 8
                k = body[off:off + klen]
                off += klen
                v = body[off:off + vlen]
                off += vlen
                t.data[k] = v
                t.keys.append(k)
            self._trees.append(t)
            self._by_name[name] = len(self._trees) - 1

    def _replay(self, ops) -> None:
        for op in ops:
            code = op[0]
            if code == _OP_OPEN_TREE:
                name = op[1]
                if name not in self._by_name:
                    self._trees.append(_MemTree(name))
                    self._by_name[name] = len(self._trees) - 1
            elif code == _OP_CLEAR:
                t = self._trees[op[1]]
                t.data.clear()
                t.keys.clear()
            elif code == _OP_INSERT:
                self._trees[op[1]].insert(op[2], op[3])
            else:
                self._trees[op[1]].remove(op[2])

    def snapshot(self, path: str) -> None:
        """Consistent copy for `garage meta snapshot` / convert-db.

        The copied snapshot, the stub WAL and the destination directory
        are all fsynced before returning, mirroring _write_snapshot — a
        snapshot whose caller archives/deletes the source right after
        must not evaporate in a crash (round-5 ADVICE #3)."""
        if self._path is None:
            raise DbError("snapshot requires a durable (path) memory db")
        with self._lock:
            self._write_snapshot()
            import shutil

            os.makedirs(path, exist_ok=True)
            dst_snap = os.path.join(path, "snap.db")
            shutil.copy2(self._snap_path(), dst_snap)
            with open(dst_snap, "rb") as f:
                os.fsync(f.fileno())
            with open(os.path.join(path, "wal.log"), "wb") as f:
                f.write(_WAL_MAGIC)
                f.flush()
                os.fsync(f.fileno())
            dirfd = os.open(path, os.O_RDONLY)
            try:
                os.fsync(dirfd)
            finally:
                os.close(dirfd)

    def close(self) -> None:
        with self._lock:
            if self._wal is not None:
                self._wal.close()
                self._wal = None

    def open_tree(self, name: str) -> int:
        with self._lock:
            if name in self._by_name:
                return self._by_name[name]
            self._trees.append(_MemTree(name))
            idx = len(self._trees) - 1
            self._by_name[name] = idx
            self._log([(_OP_OPEN_TREE, name)])
            return idx

    def list_trees(self) -> List[str]:
        with self._lock:
            return [t.name for t in self._trees]

    def get(self, tree: int, key: bytes) -> Optional[bytes]:
        with self._lock:
            return self._trees[tree].data.get(key)

    def len(self, tree: int) -> int:
        with self._lock:
            return len(self._trees[tree].data)

    def insert(self, tree: int, key: bytes, value: bytes) -> Optional[bytes]:
        with self._lock:
            old = self._trees[tree].insert(bytes(key), bytes(value))
            self._log([(_OP_INSERT, tree, bytes(key), bytes(value))])
            return old

    def remove(self, tree: int, key: bytes) -> Optional[bytes]:
        with self._lock:
            old = self._trees[tree].remove(bytes(key))
            if old is not None:
                self._log([(_OP_REMOVE, tree, bytes(key), None)])
            return old

    def clear(self, tree: int) -> None:
        with self._lock:
            t = self._trees[tree]
            t.data.clear()
            t.keys.clear()
            self._log([(_OP_CLEAR, tree)])

    def iter_range(
        self,
        tree: int,
        start: Optional[bytes],
        end: Optional[bytes],
        reverse: bool = False,
    ) -> Iterator[Tuple[bytes, bytes]]:
        # Snapshot the key range so concurrent mutation can't corrupt the
        # walk; values are read live (same behavior as a cursor walk).
        with self._lock:
            t = self._trees[tree]
            ks = t.range_keys(start, end, reverse)
        for k in ks:
            with self._lock:
                v = t.data.get(k)
            if v is not None:
                yield k, v

    def range_scan(
        self,
        tree: int,
        start: Optional[bytes],
        end: Optional[bytes],
        limit: int,
        reverse: bool = False,
    ) -> List[Tuple[bytes, bytes]]:
        # one slice + gather under a single lock hold: the per-key
        # lock round-trip of iter_range is what a page-sized scan pays
        # for a consistency it does not need
        if limit <= 0:
            return []
        with self._lock:
            t = self._trees[tree]
            lo = 0 if start is None else bisect.bisect_left(t.keys, start)
            hi = (len(t.keys) if end is None
                  else bisect.bisect_left(t.keys, end))
            if reverse:
                ks = t.keys[max(lo, hi - limit):hi][::-1]
            else:
                ks = t.keys[lo:min(hi, lo + limit)]
            return [(k, t.data[k]) for k in ks]

    def transaction(self, fn: Callable[[Transaction], object]):
        with self._lock:
            tx = _MemTx(self)
            try:
                res = fn(tx)
            except TxAbort as a:
                tx.rollback()
                return a.value
            except BaseException:
                tx.rollback()
                raise
            # ONE redo record for the whole transaction: recovery
            # replays it atomically or (torn tail) not at all
            self._log(tx._redo)
        for hook in tx._on_commit:
            hook()
        return res


class _MemTx(Transaction):
    """Undo-log transaction over the in-memory trees (lock held by caller)."""

    def __init__(self, db: MemoryDb):
        super().__init__()
        self.db = db
        self._undo: List[Tuple[int, bytes, Optional[bytes]]] = []
        self._redo: List[tuple] = []

    def get(self, tree, key):
        return self.db._trees[tree.idx].data.get(bytes(key))

    def len(self, tree):
        return len(self.db._trees[tree.idx].data)

    def insert(self, tree, key, value):
        old = self.db._trees[tree.idx].insert(bytes(key), bytes(value))
        self._undo.append((tree.idx, bytes(key), old))
        self._redo.append((_OP_INSERT, tree.idx, bytes(key), bytes(value)))
        return old

    def remove(self, tree, key):
        old = self.db._trees[tree.idx].remove(bytes(key))
        if old is not None:
            self._undo.append((tree.idx, bytes(key), old))
            self._redo.append((_OP_REMOVE, tree.idx, bytes(key), None))
        return old

    def iter_range(self, tree, start=None, end=None, reverse=False):
        t = self.db._trees[tree.idx]
        for k in t.range_keys(start, end, reverse):
            v = t.data.get(k)
            if v is not None:
                yield k, v

    def rollback(self):
        for tree_idx, key, old in reversed(self._undo):
            t = self.db._trees[tree_idx]
            if old is None:
                t.remove(key)
            else:
                t.insert(key, old)
