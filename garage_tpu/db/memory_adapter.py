"""In-memory DB engine — sorted maps behind one lock.

Test/ephemeral engine; conforms to the same suite as sqlite
(tests/test_db.py, mirroring ref db/test.rs run across engines).
"""

from __future__ import annotations

import bisect
import threading
from typing import Callable, Iterator, List, Optional, Tuple

from . import IDb, Transaction, TxAbort


class _MemTree:
    __slots__ = ("name", "data", "keys")

    def __init__(self, name: str):
        self.name = name
        self.data = {}
        self.keys: List[bytes] = []  # sorted

    def insert(self, key: bytes, value: bytes) -> Optional[bytes]:
        old = self.data.get(key)
        if old is None:
            bisect.insort(self.keys, key)
        self.data[key] = value
        return old

    def remove(self, key: bytes) -> Optional[bytes]:
        old = self.data.pop(key, None)
        if old is not None:
            i = bisect.bisect_left(self.keys, key)
            del self.keys[i]
        return old

    def range_keys(
        self, start: Optional[bytes], end: Optional[bytes], reverse: bool
    ) -> List[bytes]:
        lo = 0 if start is None else bisect.bisect_left(self.keys, start)
        hi = len(self.keys) if end is None else bisect.bisect_left(self.keys, end)
        ks = self.keys[lo:hi]
        return ks[::-1] if reverse else ks


class MemoryDb(IDb):
    engine = "memory"

    def __init__(self):
        self._lock = threading.RLock()
        self._trees: List[_MemTree] = []
        self._by_name = {}

    def open_tree(self, name: str) -> int:
        with self._lock:
            if name in self._by_name:
                return self._by_name[name]
            self._trees.append(_MemTree(name))
            idx = len(self._trees) - 1
            self._by_name[name] = idx
            return idx

    def list_trees(self) -> List[str]:
        with self._lock:
            return [t.name for t in self._trees]

    def get(self, tree: int, key: bytes) -> Optional[bytes]:
        with self._lock:
            return self._trees[tree].data.get(key)

    def len(self, tree: int) -> int:
        with self._lock:
            return len(self._trees[tree].data)

    def insert(self, tree: int, key: bytes, value: bytes) -> Optional[bytes]:
        with self._lock:
            return self._trees[tree].insert(bytes(key), bytes(value))

    def remove(self, tree: int, key: bytes) -> Optional[bytes]:
        with self._lock:
            return self._trees[tree].remove(bytes(key))

    def clear(self, tree: int) -> None:
        with self._lock:
            t = self._trees[tree]
            t.data.clear()
            t.keys.clear()

    def iter_range(
        self,
        tree: int,
        start: Optional[bytes],
        end: Optional[bytes],
        reverse: bool = False,
    ) -> Iterator[Tuple[bytes, bytes]]:
        # Snapshot the key range so concurrent mutation can't corrupt the
        # walk; values are read live (same behavior as a cursor walk).
        with self._lock:
            t = self._trees[tree]
            ks = t.range_keys(start, end, reverse)
        for k in ks:
            with self._lock:
                v = t.data.get(k)
            if v is not None:
                yield k, v

    def transaction(self, fn: Callable[[Transaction], object]):
        with self._lock:
            tx = _MemTx(self)
            try:
                res = fn(tx)
            except TxAbort as a:
                tx.rollback()
                return a.value
            except BaseException:
                tx.rollback()
                raise
        for hook in tx._on_commit:
            hook()
        return res


class _MemTx(Transaction):
    """Undo-log transaction over the in-memory trees (lock held by caller)."""

    def __init__(self, db: MemoryDb):
        super().__init__()
        self.db = db
        self._undo: List[Tuple[int, bytes, Optional[bytes]]] = []

    def get(self, tree, key):
        return self.db._trees[tree.idx].data.get(bytes(key))

    def len(self, tree):
        return len(self.db._trees[tree.idx].data)

    def insert(self, tree, key, value):
        old = self.db._trees[tree.idx].insert(bytes(key), bytes(value))
        self._undo.append((tree.idx, bytes(key), old))
        return old

    def remove(self, tree, key):
        old = self.db._trees[tree.idx].remove(bytes(key))
        if old is not None:
            self._undo.append((tree.idx, bytes(key), old))
        return old

    def iter_range(self, tree, start=None, end=None, reverse=False):
        t = self.db._trees[tree.idx]
        for k in t.range_keys(start, end, reverse):
            v = t.data.get(k)
            if v is not None:
                yield k, v

    def rollback(self):
        for tree_idx, key, old in reversed(self._undo):
            t = self.db._trees[tree_idx]
            if old is None:
                t.remove(key)
            else:
                t.insert(key, old)
