"""Metadata DB layer — engine-agnostic Db/Tree/Transaction facade.

Equivalent of reference src/db/lib.rs: a `Db` exposes named ordered
byte-key→byte-value `Tree`s and closure-based serializable transactions
(db/lib.rs:91-127, 175-254, 321-415).  Engines are selected at runtime
(ref model/garage.rs:114-213); here the engines are:

  - "sqlite": stdlib sqlite3 in WAL mode behind one process-wide lock
    (the reference's sqlite adapter likewise serializes through a global
    mutex, db/sqlite_adapter.rs).
  - "memory": in-process sorted maps — for tests and ephemeral nodes.

The shared conformance suite (tests/test_db.py) runs against every engine,
mirroring the reference's db/test.rs pattern.
"""

from __future__ import annotations

import threading
from typing import Callable, Iterator, List, Optional, Tuple, TypeVar

from ..utils.error import DbError

T = TypeVar("T")


class TxAbort(Exception):
    """Raised inside a transaction closure to roll back (ref db/lib.rs
    TxError::Abort).  The `value` is returned to the transaction caller."""

    def __init__(self, value=None):
        self.value = value
        super().__init__("transaction aborted")


class IDb:
    """Engine interface (ref db/lib.rs:321-353 trait IDb).

    Trees are addressed by integer index once opened; keys and values are
    `bytes`.  `range` bounds follow Python slice conventions: start
    inclusive, end exclusive; None = unbounded.
    """

    engine: str = "?"

    def open_tree(self, name: str) -> int:
        raise NotImplementedError

    def list_trees(self) -> List[str]:
        raise NotImplementedError

    def get(self, tree: int, key: bytes) -> Optional[bytes]:
        raise NotImplementedError

    def len(self, tree: int) -> int:
        raise NotImplementedError

    def insert(self, tree: int, key: bytes, value: bytes) -> Optional[bytes]:
        raise NotImplementedError

    def remove(self, tree: int, key: bytes) -> Optional[bytes]:
        raise NotImplementedError

    def clear(self, tree: int) -> None:
        raise NotImplementedError

    def iter_range(
        self,
        tree: int,
        start: Optional[bytes],
        end: Optional[bytes],
        reverse: bool = False,
    ) -> Iterator[Tuple[bytes, bytes]]:
        raise NotImplementedError

    def first(self, tree: int) -> Optional[Tuple[bytes, bytes]]:
        for kv in self.iter_range(tree, None, None):
            return kv
        return None

    def range_scan(
        self,
        tree: int,
        start: Optional[bytes],
        end: Optional[bytes],
        limit: int,
        reverse: bool = False,
    ) -> List[Tuple[bytes, bytes]]:
        """One materialized page of at most `limit` rows — the building
        block of paged/parallel scans: engines answer it in a single
        seek + bounded read (sqlite: one LIMIT query; memory: one slice
        under the lock) instead of a chunked cursor walk per row.
        Callers resume with start = last_key + b"\\x00" (forward) or
        end = last_key (reverse)."""
        out: List[Tuple[bytes, bytes]] = []
        if limit <= 0:
            return out
        for kv in self.iter_range(tree, start, end, reverse):
            out.append(kv)
            if len(out) >= limit:
                break
        return out

    def transaction(self, fn: Callable[["Transaction"], T]) -> T:
        raise NotImplementedError

    def snapshot(self, path: str) -> None:
        raise DbError(f"snapshot not supported by engine {self.engine}")

    def close(self) -> None:
        pass


class Transaction:
    """Transactional view handed to the closure (ref db/lib.rs ITx:355-377).

    Engines guarantee serializability by holding the engine write lock for
    the duration of the closure.  `on_commit` hooks run after a successful
    commit, outside the lock (used e.g. to notify merkle/GC workers)."""

    def __init__(self):
        self._on_commit: List[Callable[[], None]] = []

    def get(self, tree: "Tree", key: bytes) -> Optional[bytes]:
        raise NotImplementedError

    def len(self, tree: "Tree") -> int:
        raise NotImplementedError

    def insert(self, tree: "Tree", key: bytes, value: bytes) -> Optional[bytes]:
        raise NotImplementedError

    def remove(self, tree: "Tree", key: bytes) -> Optional[bytes]:
        raise NotImplementedError

    def iter_range(
        self,
        tree: "Tree",
        start: Optional[bytes] = None,
        end: Optional[bytes] = None,
        reverse: bool = False,
    ) -> Iterator[Tuple[bytes, bytes]]:
        raise NotImplementedError

    def on_commit(self, fn: Callable[[], None]) -> None:
        self._on_commit.append(fn)

    def abort(self, value=None) -> "NoReturn":  # noqa: F821
        raise TxAbort(value)


class Tree:
    """One named ordered keyspace (ref db/lib.rs:175-254)."""

    __slots__ = ("db", "name", "idx")

    def __init__(self, db: "Db", name: str, idx: int):
        self.db, self.name, self.idx = db, name, idx

    def get(self, key: bytes) -> Optional[bytes]:
        return self.db.backend.get(self.idx, key)

    def __len__(self) -> int:
        return self.db.backend.len(self.idx)

    def is_empty(self) -> bool:
        return len(self) == 0

    def insert(self, key: bytes, value: bytes) -> Optional[bytes]:
        return self.db.backend.insert(self.idx, key, value)

    def remove(self, key: bytes) -> Optional[bytes]:
        return self.db.backend.remove(self.idx, key)

    def clear(self) -> None:
        self.db.backend.clear(self.idx)

    def first(self) -> Optional[Tuple[bytes, bytes]]:
        return self.db.backend.first(self.idx)

    def items(
        self, start: Optional[bytes] = None, end: Optional[bytes] = None
    ) -> Iterator[Tuple[bytes, bytes]]:
        return self.db.backend.iter_range(self.idx, start, end)

    def items_rev(
        self, start: Optional[bytes] = None, end: Optional[bytes] = None
    ) -> Iterator[Tuple[bytes, bytes]]:
        return self.db.backend.iter_range(self.idx, start, end, reverse=True)

    def range_scan(
        self,
        start: Optional[bytes] = None,
        end: Optional[bytes] = None,
        limit: int = 1000,
        reverse: bool = False,
    ) -> List[Tuple[bytes, bytes]]:
        """One page of at most `limit` rows (see IDb.range_scan)."""
        return self.db.backend.range_scan(self.idx, start, end, limit, reverse)

    def get_gt(self, key: bytes) -> Optional[Tuple[bytes, bytes]]:
        """First entry with key strictly greater (cursor-style resumable
        iteration — the pattern the table/block workers use so concurrent
        mutation cannot invalidate an iterator)."""
        for kv in self.db.backend.iter_range(self.idx, key + b"\x00", None):
            return kv
        return None


class Db:
    """Engine-agnostic database handle (ref db/lib.rs:27-41)."""

    def __init__(self, backend: IDb):
        self.backend = backend
        self._trees = {}
        self._lock = threading.Lock()

    @property
    def engine(self) -> str:
        return self.backend.engine

    def open_tree(self, name: str) -> Tree:
        with self._lock:
            t = self._trees.get(name)
            if t is None:
                t = Tree(self, name, self.backend.open_tree(name))
                self._trees[name] = t
            return t

    def list_trees(self) -> List[str]:
        return self.backend.list_trees()

    def transaction(self, fn: Callable[[Transaction], T]) -> T:
        """Run `fn(tx)` serializably; commit on return, roll back on TxAbort
        (returning its value) or any exception (re-raised)."""
        return self.backend.transaction(fn)

    def snapshot(self, path: str) -> None:
        self.backend.snapshot(path)

    def close(self) -> None:
        self.backend.close()


def open_db(engine: str, path: Optional[str] = None, **kw) -> Db:
    """Open a metadata DB (ref model/garage.rs:114-213 engine dispatch)."""
    if engine in ("sqlite", "sqlite3"):
        from .sqlite_adapter import SqliteDb

        if path is None:
            raise DbError("sqlite engine requires a path")
        return Db(SqliteDb(path, **kw))
    if engine in ("memory", "mem"):
        from .memory_adapter import MemoryDb

        # with a path: the durable third engine (snapshot + WAL, the
        # reference's sled slot); without: RAM-only (tests/ephemeral)
        return Db(MemoryDb(path=path, **kw))
    if engine in ("native", "logdb"):
        from .native_adapter import NativeDb

        if path is None:
            raise DbError("native engine requires a path")
        return Db(NativeDb(path, **kw))
    raise DbError(f"unknown db engine {engine!r}")
