"""CountedTree — a Tree with an O(1) in-RAM length counter.

Equivalent of reference src/db/counted_tree_hack.rs:16-34: sqlite COUNT(*)
is O(n), but the resync queue/error trees and gc_todo need cheap `.len()`
for metrics and scheduling, so the count is kept in an atomic alongside the
tree and initialized from a real count at open.
"""

from __future__ import annotations

import itertools
import threading
from typing import Iterator, Optional, Tuple

from . import Tree


class CountedTree:
    def __init__(self, tree: Tree):
        self.tree = tree
        self._count = len(tree)
        self._lock = threading.Lock()

    def __len__(self) -> int:
        return self._count

    def is_empty(self) -> bool:
        return self._count == 0

    def get(self, key: bytes) -> Optional[bytes]:
        return self.tree.get(key)

    def insert(self, key: bytes, value: bytes) -> Optional[bytes]:
        old = self.tree.insert(key, value)
        if old is None:
            with self._lock:
                self._count += 1
        return old

    def remove(self, key: bytes) -> Optional[bytes]:
        old = self.tree.remove(key)
        if old is not None:
            with self._lock:
                self._count -= 1
        return old

    def first(self) -> Optional[Tuple[bytes, bytes]]:
        return self.tree.first()

    def items(self, start=None, end=None) -> Iterator[Tuple[bytes, bytes]]:
        return self.tree.items(start, end)

    def get_gt(self, key: bytes) -> Optional[Tuple[bytes, bytes]]:
        return self.tree.get_gt(key)
