"""CountedTree — a Tree with an O(1) in-RAM length counter.

Equivalent of reference src/db/counted_tree_hack.rs:16-34: sqlite COUNT(*)
is O(n), but the resync queue/error trees and gc_todo need cheap `.len()`
for metrics and scheduling, so the count is kept in an atomic alongside the
tree and initialized from a real count at open.
"""

from __future__ import annotations

import itertools
import threading
from typing import Iterator, Optional, Tuple

from . import Tree


class CountedTree:
    def __init__(self, tree: Tree):
        self.tree = tree
        self._count = len(tree)
        self._lock = threading.Lock()

    def __len__(self) -> int:
        return self._count

    def is_empty(self) -> bool:
        return self._count == 0

    def get(self, key: bytes) -> Optional[bytes]:
        return self.tree.get(key)

    def insert(self, key: bytes, value: bytes) -> Optional[bytes]:
        old = self.tree.insert(key, value)
        if old is None:
            with self._lock:
                self._count += 1
        return old

    def remove(self, key: bytes) -> Optional[bytes]:
        old = self.tree.remove(key)
        if old is not None:
            with self._lock:
                self._count -= 1
        return old

    def first(self) -> Optional[Tuple[bytes, bytes]]:
        return self.tree.first()

    def items(self, start=None, end=None) -> Iterator[Tuple[bytes, bytes]]:
        return self.tree.items(start, end)

    def get_gt(self, key: bytes) -> Optional[Tuple[bytes, bytes]]:
        return self.tree.get_gt(key)

    def range_scan(self, start=None, end=None, limit: int = 1000,
                   reverse: bool = False) -> list:
        return self.tree.range_scan(start, end, limit, reverse)

    def reconcile(self) -> int:
        """Re-count from the tree and return the drift the cached
        counter had accumulated (0 = exact).  The metadata-at-millions
        accuracy check: worker gauges and scheduling decisions read the
        cached count, so any drift under delete+reinsert churn is a
        first-class bug — bench/tests assert reconcile() == 0 after
        churn, and a production caller can use it as a self-repair."""
        # length read under the counter lock: every adapter runs
        # on_commit hooks (the only other _lock takers) AFTER releasing
        # its own lock, so this nesting cannot deadlock — and a length
        # read outside the lock could install a stale count, turning the
        # self-repair into the drift it is meant to fix
        with self._lock:
            real = len(self.tree)
            drift = self._count - real
            self._count = real
        return drift

    def _add(self, delta: int) -> None:
        with self._lock:
            self._count += delta

    def tx_insert(self, tx, key: bytes, value: bytes) -> Optional[bytes]:
        """Transactional insert that keeps the counter consistent: the
        count adjustment is applied via on_commit so aborts don't skew it."""
        old = tx.insert(self.tree, key, value)
        if old is None:
            tx.on_commit(lambda: self._add(1))
        return old

    def tx_remove(self, tx, key: bytes) -> Optional[bytes]:
        old = tx.remove(self.tree, key)
        if old is not None:
            tx.on_commit(lambda: self._add(-1))
        return old

    def compare_and_swap(
        self,
        key: bytes,
        expected: Optional[bytes],
        new: Optional[bytes],
    ) -> bool:
        """Atomically set key → new (None = delete) iff current == expected
        (ref db/counted_tree_hack.rs compare_and_swap, used by gc/resync to
        remove a todo entry only if unchanged since it was read)."""
        tree = self.tree

        def txn(tx):
            cur = tx.get(tree, key)
            if cur != expected:
                return False
            if new is None:
                if cur is not None:
                    tx.remove(tree, key)
            else:
                tx.insert(tree, key, new)
            return True

        ok = tree.db.transaction(txn)
        if ok:
            with self._lock:
                if expected is None and new is not None:
                    self._count += 1
                elif expected is not None and new is None:
                    self._count -= 1
        return ok
