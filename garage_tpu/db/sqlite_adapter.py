"""SQLite DB engine — WAL mode, one process-wide write lock.

Equivalent of reference src/db/sqlite_adapter.rs: every tree is one table
`tree_<n>(k BLOB PRIMARY KEY, v BLOB)`; a global mutex serializes access
(the reference notes this is the thread-safety worst case,
ref block/repair.rs:92-101).  BLOB primary keys give us ordered range scans
with memcmp semantics, matching the facade's contract.
"""

from __future__ import annotations

import sqlite3
import threading
from typing import Callable, Iterator, List, Optional, Tuple

from ..utils.error import DbError
from . import IDb, Transaction, TxAbort


class SqliteDb(IDb):
    engine = "sqlite"

    def __init__(self, path: str, synchronous: str = "NORMAL"):
        self.path = path
        self._lock = threading.RLock()
        self._conn = sqlite3.connect(path, check_same_thread=False, isolation_level=None)
        self._conn.execute("PRAGMA journal_mode=WAL")
        self._conn.execute(f"PRAGMA synchronous={synchronous}")
        self._conn.execute(
            "CREATE TABLE IF NOT EXISTS _trees (id INTEGER PRIMARY KEY, name TEXT UNIQUE)"
        )
        self._names: List[str] = []
        self._tree_ids: List[int] = []
        for tid, name in self._conn.execute("SELECT id, name FROM _trees ORDER BY id"):
            self._names.append(name)
            self._tree_ids.append(tid)

    def _table(self, tree: int) -> str:
        return f"tree_{self._tree_ids[tree]}"

    def open_tree(self, name: str) -> int:
        with self._lock:
            if name in self._names:
                return self._names.index(name)
            self._conn.execute(
                "INSERT OR IGNORE INTO _trees(name) VALUES (?)", (name,)
            )
            # lastrowid is stale when the INSERT was ignored; always re-read.
            tid = self._conn.execute(
                "SELECT id FROM _trees WHERE name=?", (name,)
            ).fetchone()[0]
            self._conn.execute(
                f"CREATE TABLE IF NOT EXISTS tree_{tid} "
                "(k BLOB PRIMARY KEY, v BLOB NOT NULL) WITHOUT ROWID"
            )
            self._names.append(name)
            self._tree_ids.append(tid)
            return len(self._names) - 1

    def list_trees(self) -> List[str]:
        with self._lock:
            return list(self._names)

    def get(self, tree: int, key: bytes) -> Optional[bytes]:
        with self._lock:
            row = self._conn.execute(
                f"SELECT v FROM {self._table(tree)} WHERE k=?", (bytes(key),)
            ).fetchone()
            return row[0] if row else None

    def len(self, tree: int) -> int:
        with self._lock:
            return self._conn.execute(
                f"SELECT COUNT(*) FROM {self._table(tree)}"
            ).fetchone()[0]

    def insert(self, tree: int, key: bytes, value: bytes) -> Optional[bytes]:
        with self._lock:
            old = self.get(tree, key)
            self._conn.execute(
                f"INSERT INTO {self._table(tree)}(k,v) VALUES(?,?) "
                "ON CONFLICT(k) DO UPDATE SET v=excluded.v",
                (bytes(key), bytes(value)),
            )
            return old

    def remove(self, tree: int, key: bytes) -> Optional[bytes]:
        with self._lock:
            old = self.get(tree, key)
            if old is not None:
                self._conn.execute(
                    f"DELETE FROM {self._table(tree)} WHERE k=?", (bytes(key),)
                )
            return old

    def clear(self, tree: int) -> None:
        with self._lock:
            self._conn.execute(f"DELETE FROM {self._table(tree)}")

    def iter_range(
        self,
        tree: int,
        start: Optional[bytes],
        end: Optional[bytes],
        reverse: bool = False,
    ) -> Iterator[Tuple[bytes, bytes]]:
        # Chunked cursor walk: re-seek by last key each chunk so concurrent
        # writes between chunks can't invalidate the iteration.
        CHUNK = 256
        cmp, order = ("<", "DESC") if reverse else (">", "ASC")
        lo, hi = start, end
        cursor_excl: Optional[bytes] = None
        while True:
            conds, params = [], []
            if lo is not None:
                conds.append("k >= ?"); params.append(lo)
            if hi is not None:
                conds.append("k < ?"); params.append(hi)
            if cursor_excl is not None:
                conds.append(f"k {cmp} ?"); params.append(cursor_excl)
            where = ("WHERE " + " AND ".join(conds)) if conds else ""
            with self._lock:
                rows = self._conn.execute(
                    f"SELECT k, v FROM {self._table(tree)} {where} "
                    f"ORDER BY k {order} LIMIT {CHUNK}",
                    params,
                ).fetchall()
            if not rows:
                return
            for k, v in rows:
                yield k, v
            cursor_excl = rows[-1][0]

    def range_scan(
        self,
        tree: int,
        start: Optional[bytes],
        end: Optional[bytes],
        limit: int,
        reverse: bool = False,
    ) -> List[Tuple[bytes, bytes]]:
        # ONE indexed LIMIT query per page — the chunked iter_range walk
        # pays a re-seek and a lock round-trip every 256 rows, which at
        # millions of rows is the listing hot path's dominant cost
        if limit <= 0:
            return []
        conds, params = [], []
        if start is not None:
            conds.append("k >= ?"); params.append(start)
        if end is not None:
            conds.append("k < ?"); params.append(end)
        where = ("WHERE " + " AND ".join(conds)) if conds else ""
        order = "DESC" if reverse else "ASC"
        with self._lock:
            return self._conn.execute(
                f"SELECT k, v FROM {self._table(tree)} {where} "
                f"ORDER BY k {order} LIMIT {int(limit)}",
                params,
            ).fetchall()

    def transaction(self, fn: Callable[[Transaction], object]):
        with self._lock:
            self._conn.execute("BEGIN IMMEDIATE")
            tx = _SqliteTx(self)
            try:
                res = fn(tx)
                self._conn.execute("COMMIT")
            except TxAbort as a:
                self._conn.execute("ROLLBACK")
                return a.value
            except BaseException:
                self._conn.execute("ROLLBACK")
                raise
        for hook in tx._on_commit:
            hook()
        return res

    def snapshot(self, path: str) -> None:
        with self._lock:
            dest = sqlite3.connect(path)
            try:
                self._conn.backup(dest)
            finally:
                dest.close()

    def close(self) -> None:
        with self._lock:
            self._conn.close()


class _SqliteTx(Transaction):
    """Runs inside BEGIN IMMEDIATE with the adapter lock held."""

    def __init__(self, db: SqliteDb):
        super().__init__()
        self.db = db

    def get(self, tree, key):
        row = self.db._conn.execute(
            f"SELECT v FROM {self.db._table(tree.idx)} WHERE k=?", (bytes(key),)
        ).fetchone()
        return row[0] if row else None

    def len(self, tree):
        return self.db._conn.execute(
            f"SELECT COUNT(*) FROM {self.db._table(tree.idx)}"
        ).fetchone()[0]

    def insert(self, tree, key, value):
        old = self.get(tree, key)
        self.db._conn.execute(
            f"INSERT INTO {self.db._table(tree.idx)}(k,v) VALUES(?,?) "
            "ON CONFLICT(k) DO UPDATE SET v=excluded.v",
            (bytes(key), bytes(value)),
        )
        return old

    def remove(self, tree, key):
        old = self.get(tree, key)
        if old is not None:
            self.db._conn.execute(
                f"DELETE FROM {self.db._table(tree.idx)} WHERE k=?", (bytes(key),)
            )
        return old

    def iter_range(self, tree, start=None, end=None, reverse=False):
        conds, params = [], []
        if start is not None:
            conds.append("k >= ?"); params.append(start)
        if end is not None:
            conds.append("k < ?"); params.append(end)
        where = ("WHERE " + " AND ".join(conds)) if conds else ""
        order = "DESC" if reverse else "ASC"
        # stream via cursor: the tx holds BEGIN IMMEDIATE + the adapter
        # lock, so a live cursor is consistent and avoids materializing
        # the whole range
        return iter(
            self.db._conn.execute(
                f"SELECT k, v FROM {self.db._table(tree.idx)} {where} "
                f"ORDER BY k {order}",
                params,
            )
        )
