"""ops — the BlockCodec device-op layer (the genuinely new layer vs reference).

The reference's block layer does integrity hashing and (in our north star)
Reed-Solomon erasure coding one block at a time on CPU
(ref src/block/block.rs:66-78 verify, src/block/repair.rs:438-490 scrub).
Here those become *batch* operations behind the `BlockCodec` interface, with:

  - cpu backend (cpu_codec.py): hashlib + numpy GF(2^8) (+ optional C++
    native kernel, native/gf256.cpp) — the correctness baseline;
  - tpu backend (tpu_codec.py): JAX — BLAKE2s as a vectorized uint32 scan
    (tpu_blake2s.py), RS(k,m) encode/decode as GF(2) bit-matrix matmuls on
    the MXU (gf256.py bitmatrix construction), shardable over a device mesh.

Both backends are bit-identical; tests/test_codec_equivalence.py enforces it.
"""

from __future__ import annotations

from .codec import BlockCodec, CodecParams


def make_codec(backend: str = "cpu", metrics=None, tracer=None,
               **kw) -> BlockCodec:
    """Codec factory — `codec.backend` in config selects this.

    `metrics`/`tracer` plumb the System-owned MetricsRegistry and Tracer
    into the codec (BlockManager passes its own): per-stage histograms,
    bytes-by-side counters, and the gate-decision event ring then show
    up on /metrics and the admin `codec info`/`codec events` commands."""
    if backend == "cpu":
        from .cpu_codec import CpuCodec
        return CpuCodec(CodecParams(**kw), metrics=metrics, tracer=tracer)
    if backend == "tpu":
        from .tpu_codec import TpuCodec
        return TpuCodec(CodecParams(**kw), metrics=metrics, tracer=tracer)
    if backend == "hybrid":
        from .hybrid_codec import HybridCodec
        # async: the daemon must come up on the CPU floor even if JAX
        # backend init hangs on a dead device tunnel; the device codec
        # attaches in the background when ready
        return HybridCodec(CodecParams(**kw), build_device="async",
                           metrics=metrics, tracer=tracer)
    raise ValueError(f"unknown codec backend {backend!r}")
