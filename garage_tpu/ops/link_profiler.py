"""LinkProfiler — stage-level attribution of every host↔device round
trip (ISSUE 16).

The hybrid gate's one number (`hybrid_link_gibs`: 0.031 GiB/s measured
against a 24 GiB/s device, BENCH_r05) says the link is slow but not
WHERE: serialization?  dlpack adoption?  XLA dispatch?  the transfer
itself?  This module is the instrument — the same exact-sum attribution
discipline PR 13 applied to requests, one level down, inside the
transport.

Every profiled round trip is partitioned into the stage taxonomy

    stage_copy  flat-buffer fill (the transport's single host copy)
    adopt       dlpack adoption / device_put of the staged buffer
    compile     dispatch that triggered an XLA compile (first call
                for a (kind, shape) — split out so cold-start cost
                never pollutes the steady-state dispatch picture)
    dispatch    XLA call launch (async; returns before the device runs)
    compute     device busy: submit-return → results ready
                (block_until_ready delta, measured at collect)
    collect     result materialization (D2H) + per-part reassembly

by CONSECUTIVE monotonic boundary stamps, so the per-stage breakdown
sums to the measured round-trip wall time exactly — there is no
unattributed residue to hide movement cost in (the PR 13 waterfall
invariant).  Stamps inside the device come from the device codec's
array API (`last_adopt_ns`, `last_ready_ns`, `last_submit_compiled` —
TpuCodec and SyntheticLinkCodec both publish them); a device that
doesn't stamp degrades gracefully: its time folds into the enclosing
stage instead of vanishing.

Producers: DeviceTransport (every batch + every link probe).
Consumers: `transport_stage_seconds{stage,kind}` histograms, windowed
`transport_stage_gibs{stage}` gauges, admin `codec info` / `codec
profile`, gate probe events, the BENCH JSON `link_stages` block and
its per-stage regression guard, and `scripts/link_profile.py`.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

STAGES = ("stage_copy", "adopt", "compile", "dispatch", "compute",
          "collect")

# µs-to-seconds span: a 1 MiB hop on a healthy PCIe link is ~100 µs;
# the metered-tunnel pathology stretches a 16 MiB probe past 500 ms
_STAGE_BUCKETS = (1e-5, 5e-5, 1e-4, 5e-4, 1e-3, 5e-3, 0.02, 0.05, 0.1,
                  0.5, 2.0, 10.0)


class LinkProfiler:
    """Always-on per-stage accumulator for host↔device round trips.

    `record()` takes one starting stamp plus an ORDERED list of
    (stage, boundary_ns) marks and attributes each inter-mark delta to
    its stage — exactness is structural, not asserted after the fact.
    Accounting is cumulative (count / seconds / bytes per stage, per
    kind); the windowed GiB/s gauge and the sweep harness diff
    snapshots.  The profiler times its own bookkeeping (`overhead_ns`)
    so the <2% overhead bound is measurable, not assumed.
    """

    def __init__(self, metrics=None):
        self._lock = threading.Lock()
        # kind -> stage -> [count, ns, bytes]: integer-ns arithmetic and
        # one dict hop per mark in the hot path (the <2% overhead bound
        # is part of the contract); the per-stage view aggregates lazily
        # at read time
        self._acc: Dict[str, Dict[str, list]] = {}
        self.batches = 0
        self._wall_ns = 0
        self.overhead_ns = 0
        # previous-render (seconds, bytes) snapshot per stage for the
        # windowed GiB/s gauge
        self._win: Dict[str, Tuple[float, int]] = {
            s: (0.0, 0) for s in STAGES}
        if metrics is not None:
            self.m_stage = metrics.histogram(
                "transport_stage_seconds",
                "Host<->device round-trip time by attribution stage "
                "(stage_copy/adopt/compile/dispatch/compute/collect; "
                "stages sum to batch wall time exactly)",
                buckets=_STAGE_BUCKETS)
            metrics.gauge(
                "transport_stage_gibs",
                "Windowed per-stage throughput of the host<->device "
                "path (bytes moved / stage seconds since the previous "
                "render; 0 when the stage was idle)",
                labeled_fn=self._gibs_window)
        else:
            self.m_stage = None

    # --- recording ----------------------------------------------------------

    def record(self, kind: str, nbytes: int, t0_ns: int,
               marks: Sequence[Tuple[str, int]],
               want_breakdown: bool = True) -> Optional[Dict[str, float]]:
        """Attribute one round trip.  `marks` are consecutive boundary
        stamps from `t0_ns`; each delta goes to the named stage, so the
        returned {stage: seconds} breakdown sums to the last mark minus
        `t0_ns` exactly (non-monotonic device stamps are clamped forward
        rather than allowed to create negative or double-counted time).
        `want_breakdown=False` lets the per-batch hot path skip building
        the return dict when no histogram sink needs it either.
        """
        t_in = time.perf_counter_ns()
        build = want_breakdown or self.m_stage is not None
        deltas: Optional[Dict[str, int]] = {} if build else None
        prev = t0_ns
        with self._lock:
            kacc = self._acc.get(kind)
            if kacc is None:
                kacc = self._acc[kind] = {}
            for stage, t in marks:
                dns = t - prev
                if dns > 0:
                    prev = t
                else:
                    dns = 0
                if deltas is not None:
                    if stage in deltas:
                        deltas[stage] += dns
                    else:
                        deltas[stage] = dns
                a = kacc.get(stage)
                if a is None:
                    a = kacc[stage] = [0, 0, 0]
                a[0] += 1
                a[1] += dns
                a[2] += nbytes
            self.batches += 1
            self._wall_ns += prev - t0_ns
        if deltas is None:
            self.overhead_ns += time.perf_counter_ns() - t_in
            return None
        breakdown = {s: dns / 1e9 for s, dns in deltas.items()}
        if self.m_stage is not None:
            for stage, sec in breakdown.items():
                self.m_stage.observe(sec, kind=kind, stage=stage)
        self.overhead_ns += time.perf_counter_ns() - t_in
        return breakdown

    # --- views --------------------------------------------------------------

    def overhead_seconds(self) -> float:
        return self.overhead_ns / 1e9

    @property
    def wall_seconds(self) -> float:
        return self._wall_ns / 1e9

    def _per_stage(self) -> Dict[str, list]:
        """Aggregate (count, ns, bytes) per stage across kinds.
        Callers hold self._lock."""
        out: Dict[str, list] = {}
        for kacc in self._acc.values():
            for stage, (c, ns, b) in kacc.items():
                a = out.get(stage)
                if a is None:
                    a = out[stage] = [0, 0, 0]
                a[0] += c
                a[1] += ns
                a[2] += b
        return out

    def snapshot(self) -> Dict[str, Tuple[int, float, int]]:
        """Immutable (count, seconds, bytes) per stage — the sweep
        harness diffs two of these to attribute one cell."""
        with self._lock:
            per = self._per_stage()
        return {s: (c, ns / 1e9, b) for s, (c, ns, b) in per.items()}

    @staticmethod
    def delta(before: Dict[str, tuple],
              after: Dict[str, tuple]) -> Dict[str, dict]:
        out = {}
        for s, (c1, sec1, b1) in after.items():
            c0, sec0, b0 = before.get(s, (0, 0.0, 0))
            if c1 - c0 or sec1 - sec0:
                out[s] = {"count": c1 - c0,
                          "seconds": round(sec1 - sec0, 9),
                          "bytes": b1 - b0}
        return out

    def summary(self, by_kind: bool = False) -> Dict[str, dict]:
        """The admin/bench view: per-stage count, cumulative seconds,
        bytes and effective GiB/s (bytes/seconds — each stage 'moves'
        the full payload, so a slow stage reads as a slow rate)."""
        with self._lock:
            per = self._per_stage()
            kinds = {k: {s: tuple(a) for s, a in st.items()}
                     for k, st in self._acc.items()} if by_kind else None
        out = {s: {"count": c, "seconds": round(ns / 1e9, 6), "bytes": b,
                   "gibs": round(b / (ns / 1e9) / 2**30, 4)
                   if ns > 0 else None}
               for s, (c, ns, b) in per.items() if c}
        if by_kind and kinds:
            out["by_kind"] = {
                k: {s: {"count": c, "seconds": round(ns / 1e9, 6),
                        "bytes": b}
                    for s, (c, ns, b) in st.items()}
                for k, st in kinds.items()}
        return out

    def _gibs_window(self):
        out = []
        with self._lock:
            per = self._per_stage()
        for s in STAGES:
            _, ns, b = per.get(s, (0, 0, 0))
            sec = ns / 1e9
            psec, pb = self._win.get(s, (0.0, 0))
            self._win[s] = (sec, b)
            ds, db = sec - psec, b - pb
            out.append(({"stage": s}, (db / ds / 2**30) if ds > 0 else 0.0))
        return out

def dominant_stage(stages: Dict[str, float]) -> Optional[str]:
    """The stage owning the most wall time of a breakdown ({stage:
    seconds} or a summary block with 'seconds' entries)."""
    if not stages:
        return None
    best, best_s = None, -1.0
    for k, v in stages.items():
        sec = v.get("seconds", 0.0) if isinstance(v, dict) else float(v)
        if k != "by_kind" and sec > best_s:
            best, best_s = k, sec
    return best


# --- controlled sweep harness (`codec profile` / scripts/link_profile) ------


def _sweep_payload(kind: str, size_bytes: int, blocks: int, k: int,
                   rng) -> tuple:
    """(payload, nblocks, nbytes) for one TransportItem of `kind`:
    `size_bytes` total split into `blocks` equal pieces (encode/scrub
    block counts rounded up to a multiple of rs_data so codeword
    grouping holds; decode ships (B, k, S) survivor shards)."""
    if kind in ("encode", "scrub"):
        blocks += (-blocks) % k
    if kind == "decode":
        ncw = max(1, blocks // k)
        s = max(64, size_bytes // (ncw * k))
        shards = rng.integers(0, 256, (ncw, k, s), dtype=np.uint8)
        present = list(range(k))
        return (shards, present, None), ncw, int(shards.nbytes)
    per = max(64, size_bytes // max(1, blocks))
    buf = rng.integers(0, 256, (blocks * per,), dtype=np.uint8).tobytes()
    blks = [buf[i * per:(i + 1) * per] for i in range(blocks)]
    if kind == "scrub":
        import hashlib

        from ..utils.data import Hash

        hashes = [Hash(hashlib.blake2s(b, digest_size=32).digest())
                  for b in blks]
        return (blks, hashes), blocks, blocks * per
    return blks, blocks, blocks * per


def run_sweep(transport, *, sizes_mib: Sequence[float] = (1, 4, 16, 64),
              shapes: Sequence[int] = (1, 16, 256),
              kinds: Sequence[str] = ("hash", "encode", "decode"),
              rounds: int = 1, warm: bool = True,
              timeout_s: float = 120.0) -> dict:
    """Controlled link sweep: sizes × batch shapes (block counts) ×
    kinds, one cell at a time through the LIVE transport, attributing
    each cell from the profiler's snapshot delta.  Returns the
    machine-readable block (`cells` + per-cell stage breakdowns +
    exact-sum verdicts); `format_sweep()` renders it for humans.

    Serial on purpose: a cell must own the queue while it runs or its
    snapshot delta would blend with foreground traffic.  The admin
    command bounds sizes accordingly — this is a measurement, not a
    load test.  `warm=True` (default) runs one unmeasured round per
    cell first so the measured rounds show the steady-state picture —
    the cold executable's cost lands in the cumulative `compile`
    summary, not in every cell.
    """
    from .transport import TransportItem  # local: transport imports us

    prof = transport.profiler
    k = max(1, transport.params.rs_data)
    rng = np.random.default_rng(16)
    cells: List[dict] = []
    for kind in kinds:
        if not transport.supports(kind):
            continue
        for size_mib in sizes_mib:
            for blocks in shapes:
                payload, nblk, nbytes = _sweep_payload(
                    kind, int(size_mib * 2**20), int(blocks), k, rng)
                stages: Dict[str, float] = {}
                wall = 0.0
                outer = 0.0
                if warm:
                    wi = TransportItem(kind, payload, nblk, nbytes)
                    transport.submit_items(kind, [wi])
                    wi.future.result(timeout=timeout_s)
                for _ in range(max(1, rounds)):
                    item = TransportItem(kind, payload, nblk, nbytes)
                    before = prof.snapshot()
                    w0 = prof.wall_seconds
                    t0 = time.monotonic()
                    transport.submit_items(kind, [item])
                    item.future.result(timeout=timeout_s)
                    outer += time.monotonic() - t0
                    wall += prof.wall_seconds - w0
                    for s, d in prof.delta(before,
                                           prof.snapshot()).items():
                        stages[s] = stages.get(s, 0.0) + d["seconds"]
                stage_sum = sum(stages.values())
                cells.append({
                    "kind": kind,
                    "size_mib": float(size_mib),
                    "blocks": int(blocks),
                    "nbytes": nbytes * max(1, rounds),
                    "wall_s": round(wall, 6),
                    "outer_s": round(outer, 6),
                    "gibs": round(nbytes * max(1, rounds)
                                  / wall / 2**30, 4) if wall > 0 else None,
                    "stages": {s: round(v, 6) for s, v in stages.items()},
                    "dominant": dominant_stage(stages),
                    # exact-sum invariant, live: the breakdown equals the
                    # profiler-measured wall within float rounding, and
                    # never exceeds the caller-observed wall
                    "sum_ok": (abs(stage_sum - wall) < 1e-6
                               and stage_sum <= outer + 1e-6),
                })
    return {
        "sizes_mib": [float(s) for s in sizes_mib],
        "shapes": [int(b) for b in shapes],
        "kinds": list(kinds),
        "rounds": int(rounds),
        "cells": cells,
        "sum_ok": all(c["sum_ok"] for c in cells),
        "overhead_seconds": round(prof.overhead_seconds(), 6),
        "summary": prof.summary(),
    }


def format_sweep(block: dict) -> str:
    """The human attribution table for one `run_sweep` block."""
    from ..utils.format_table import format_table

    rows = ["\t".join(["kind", "MiB", "blocks", "GiB/s",
                       *(f"{s}_ms" for s in STAGES), "dominant", "sum"])]
    for c in block.get("cells", []):
        st = c.get("stages", {})
        rows.append("\t".join([
            c["kind"], f"{c['size_mib']:g}", str(c["blocks"]),
            f"{c['gibs']:.3f}" if c.get("gibs") else "-",
            *(f"{st.get(s, 0.0) * 1e3:.2f}" for s in STAGES),
            c.get("dominant") or "-",
            "ok" if c.get("sum_ok") else "VIOLATED",
        ]))
    return format_table(rows)
