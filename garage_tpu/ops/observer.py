"""CodecObserver — dataplane observability for the BlockCodec layer.

The codec is the system's reason for existing, yet through round 5 it
recorded nothing into the node's MetricsRegistry or Tracer: `tpu_frac`
was a `pop_stats()` tuple only the bench could read, and a 0.0 value was
undiagnosable (VERDICT r5).  This module gives every codec instance one
observer holding:

  - per-stage duration histograms for the device pipeline
    (`codec_stage_duration_seconds{stage=,side=}`): probe, feeder_wait,
    host_staging, h2d_transfer, kernel_dispatch, sync_collect, cpu_span,
    hedge, tail_wait — the stage-by-stage attribution model of the
    degraded-read / erasure-coding literature (arXiv:2306.10528,
    arXiv:2108.02692);
  - bytes-by-side counters (`codec_bytes_total{side=}`) so tpu_frac is a
    scrapeable ratio, not a bench-polled tuple;
  - a bounded, timestamped **gate-decision event ring**: every link
    probe, gate open/hold, ramp step, fused-kernel demotion, feeder cede
    and sync failure lands here with a reason label, served by the admin
    `codec events` command — "why is tpu_frac 0.0" is one command.

The ring and the per-stage accumulators are ALWAYS ON (bounded memory,
one lock per event); the Prometheus instruments exist only when a
MetricsRegistry is plumbed in (the daemon path — BlockManager passes
`system.metrics`).  Bare-library users pay one None check.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

# pipeline stages recorded by the hybrid engine + the device codec
STAGES = (
    "probe",           # link-health probe round-trip (feeder, pre-claim)
    "feeder_wait",     # feeder claiming work from the stealing deque
    "host_staging",    # group merge + pad to the compiled lane/byte shape
    "device_submit",   # whole scrub_submit envelope (staging+h2d+dispatch)
    "h2d_transfer",    # host→device array transfer (enqueue side)
    "kernel_dispatch", # fused verify+encode dispatch (submit, no sync)
    "sync_collect",    # device→host materialization of a submission
    "cpu_span",        # one wide fused CPU call (verify + RS encode)
    "hedge",           # CPU redo of groups the device still held in flight
    "tail_wait",       # grace wait on the device before hedging the tail
    "feeder_dispatch", # one ragged foreground batch (CodecFeeder) through
                       # hash_ragged / rs_encode_ragged / rs_reconstruct_ragged
    "transport_wait",  # queue wait in the DeviceTransport's EDF heap
                       # (ops/transport.py; its staging/submit/collect
                       # reuse host_staging / device_submit / sync_collect)
)

EVENT_RING_SIZE = 256


class _StageTimer:
    __slots__ = ("_obs", "_stage", "_side", "_t0")

    def __init__(self, obs: "CodecObserver", stage: str, side: str):
        self._obs = obs
        self._stage = stage
        self._side = side

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self._obs.observe_stage(
            self._stage, self._side, time.perf_counter() - self._t0
        )
        return False


class CodecObserver:
    """One per codec instance; shared with the device codec it builds so
    kernel demotions land in the same ring as gate decisions."""

    def __init__(self, metrics=None, tracer=None,
                 ring_size: int = EVENT_RING_SIZE):
        from ..utils.timeline import Timeline

        self.tracer = tracer
        # device/transport timeline: begin/end of every pipeline stage
        # (feeder dispatch, EDF pop, per-slot staging, submit, collect)
        # in one bounded ring, exportable as Chrome-trace JSON (admin
        # `device_timeline`, scripts/device_timeline.py) — the staging
        # overlap is a picture, not an inference
        self.timeline = Timeline()
        # stage-level host<->device attribution (ops/link_profiler.py):
        # the DeviceTransport that shares this observer installs its
        # LinkProfiler here so bench attribution and admin views reach
        # the per-stage breakdown without holding a transport reference
        # across re-arms; None until a transport arms
        self.link_profiler = None
        self.events: deque = deque(maxlen=ring_size)
        self._lock = threading.Lock()
        self._seq = 0
        # always-on accumulators (admin `codec info` + bench attribution
        # read these without a registry): bytes by side, and per-stage
        # (count, seconds) keyed "stage/side"
        self.bytes_total: Dict[str, int] = {"cpu": 0, "tpu": 0}
        self._stage_acc: Dict[str, List[float]] = {}
        if metrics is not None:
            self._hist = metrics.histogram(
                "codec_stage_duration_seconds",
                "Codec pipeline stage durations by stage and side",
            )
            self._bytes_ctr = metrics.counter(
                "codec_bytes_total",
                "Block bytes processed by the codec, by side "
                "(tpu_frac = tpu / (cpu + tpu))",
            )
            self._event_ctr = metrics.counter(
                "codec_gate_events_total",
                "Gate-decision/demotion events by kind and reason",
            )
        else:
            self._hist = self._bytes_ctr = self._event_ctr = None

    # --- events ---

    def event(self, kind: str, reason: str = "", **detail: Any) -> None:
        """Append one gate-decision event (bounded ring, always on)."""
        with self._lock:
            self._seq += 1
            rec = {"seq": self._seq, "ts": round(time.time(), 3),
                   "kind": kind, "reason": reason}
            if detail:
                rec.update(detail)
            self.events.append(rec)
        if self._event_ctr is not None:
            self._event_ctr.inc(kind=kind, reason=reason)

    def events_list(self, limit: Optional[int] = None) -> List[dict]:
        """Most-recent-last snapshot of the ring."""
        with self._lock:
            out = list(self.events)
        if limit is not None and limit > 0:
            out = out[-limit:]
        return out

    # --- stages ---

    def stage(self, stage: str, side: str) -> _StageTimer:
        return _StageTimer(self, stage, side)

    def observe_stage(self, stage: str, side: str, seconds: float) -> None:
        key = f"{stage}/{side}"
        with self._lock:
            acc = self._stage_acc.get(key)
            if acc is None:
                acc = self._stage_acc[key] = [0, 0.0]
            acc[0] += 1
            acc[1] += seconds
        if self._hist is not None:
            self._hist.observe(seconds, stage=stage, side=side)

    def stage_stats(self) -> Dict[str, dict]:
        """{stage/side: {count, seconds}} — the bench JSON attribution
        block and admin `codec info` both read this."""
        with self._lock:
            return {
                k: {"count": int(c), "seconds": round(s, 6)}
                for k, (c, s) in sorted(self._stage_acc.items())
            }

    # --- bytes ---

    def add_bytes(self, side: str, n: int) -> None:
        with self._lock:
            self.bytes_total[side] = self.bytes_total.get(side, 0) + n
        if self._bytes_ctr is not None:
            self._bytes_ctr.inc(n, side=side)

    def tpu_frac(self) -> float:
        with self._lock:
            cpu = self.bytes_total.get("cpu", 0)
            tpu = self.bytes_total.get("tpu", 0)
        total = cpu + tpu
        return tpu / total if total else 0.0
