"""DeviceTransport — the zero-copy colocated host↔device submission queue.

Why this exists.  Through round 10 the device side lost every real bench
round to its own feeding path: the Pallas GF kernel runs at 110 GiB/s
and the fused device scrub at 24 GiB/s, yet ``tpu_frac`` stayed 0.0
because the hybrid gate measured the ad-hoc serialize+copy link at 0.031
GiB/s and (correctly) held.  The link was not the wire — it was the
path: every producer talked to the device on its own (the scrub feeder
packed bytes lists behind the CodecFeeder's back, the foreground ragged
batches re-packed them again), each submission paying a fresh
pad-and-copy plus an unpipelined sync.  This module is the Ragged Paged
Attention move (PAPERS.md) applied to the storage dataplane: keep the
work device-resident, feed it from ONE queue, and hand the
already-concatenated ragged buffers over with no intermediate
serialization.

One DeviceTransport per device codec owns ALL host↔device movement:

  - **Zero-copy submission.**  Blocks are written once into a reusable
    per-slot staging buffer (the single host copy — counted:
    ``transport_staged_bytes_total{copies="1"}`` and a per-block copy
    counter tests assert ≤ 1 against) and the buffer is adopted by JAX
    via dlpack when host and device share memory, ``device_put`` (the
    H2D DMA, not a host copy) otherwise.  No bytes join, no msgpack, no
    second pad pass.
  - **Double-buffered staging** bounded by ``max_device_staging_mib``:
    ``transport_staging_slots`` (default 2) slots, so batch N+1 stages
    and submits while batch N computes; oversized submissions are split
    at codeword-aligned boundaries into chunks that fit the budget
    (the staging-bound clamp), and the partial results are reassembled
    bit-identically.
  - **A single deadline-aware queue.**  The CodecFeeder is the only
    producer; foreground PUT/GET verify batches and background
    scrub/resync batches land in one earliest-deadline-first heap.
    Foreground submissions run at their request deadline (arrival time
    when none), background ones carry a slack that GROWS as the load
    governor's background_throttle_ratio drops (utils/overload.py) —
    the same demotion discipline the wire and disk layers already
    apply, now at the device door.  At equal deadlines foreground wins
    the tie.
  - **Self-measuring.**  ``probe_link`` times a real ragged submission
    through the full stage→submit→collect path, so the hybrid gate
    decides on the rate this transport actually delivers instead of
    the retired serialize+copy path's.
  - **Device-resident pool.**  When a DevicePool is attached
    (ops/device_pool.py), scrub staging consults it first: resident
    blocks ship as device page references and move ZERO link bytes
    (``pool_hit_bytes_total``), misses stage through the slot path and
    their verified lanes are adopted into the pool at collect
    (``pool_miss_bytes_total``); ``prefetch`` stages the scrub
    worker's next range ahead of need as background-class work.

Failure containment: a device failure never fails the caller — the
affected batch is recomputed inline on the CPU fallback codec
(``transport_fallback`` event) and ``_MAX_DEVICE_FAILS`` consecutive
failures close the transport so the feeder routes around it.

The device side is duck-typed ("transport device API"): ``hash_submit/
hash_collect``, ``scrub_encode_submit/scrub_collect``, ``encode_submit``,
``decode_submit`` + ``staging_geometry`` — implemented by TpuCodec and
the synthetic-link test backend; scripted fakes without the API simply
never get a transport (the legacy ragged routing still works).
"""

from __future__ import annotations

import heapq
import logging
import threading
import time
from concurrent.futures import Future
from typing import Callable, List, Optional, Sequence

import numpy as np

from ..utils.cpuprof import register_thread, unregister_thread
from ..utils.data import Hash

logger = logging.getLogger("garage_tpu.ops.transport")

KINDS = ("hash", "scrub", "encode", "decode")
CLASSES = ("fg", "bg")

# consecutive device-side failures (submit or collect) that close the
# transport: past this the device is not flaky, it is gone, and every
# further batch would pay a stage + fallback for nothing
_MAX_DEVICE_FAILS = 3

_EMPTY_DIGEST: Optional[np.ndarray] = None


def _empty_digest_words() -> np.ndarray:
    """blake2s-256(b"") as the (8,) uint32 expectation pad lanes carry so
    they verify clean and never inflate a corruption count."""
    global _EMPTY_DIGEST
    if _EMPTY_DIGEST is None:
        import hashlib

        _EMPTY_DIGEST = np.frombuffer(
            hashlib.blake2s(b"", digest_size=32).digest(), dtype="<u4"
        ).copy()
    return _EMPTY_DIGEST


class TransportClosed(RuntimeError):
    """submit_items after shutdown()/device-down — the feeder falls back
    to the inline (CPU) dispatch path."""


def _swallow_result(future: Future) -> None:
    """Prefetch futures exist only to drive adoption; a failed
    prefetch is a lost optimization, not an error (the real scrub
    batch will stage the blocks itself)."""
    try:
        future.exception()
    except Exception:  # noqa: BLE001 — cancelled futures included
        pass


class TransportItem:
    """One submission as the transport sees it — the CodecFeeder's _Item
    satisfies this protocol (payload/blocks/nbytes/future/cls/deadline);
    direct users (tests, the probe) build TransportItems."""

    __slots__ = ("kind", "payload", "blocks", "nbytes", "future", "cls",
                 "deadline", "want_parity", "prefetch")

    def __init__(self, kind: str, payload, blocks: int, nbytes: int,
                 cls: str = "fg", deadline: Optional[float] = None,
                 want_parity: bool = True, prefetch: bool = False):
        self.kind = kind
        self.payload = payload
        self.blocks = blocks
        self.nbytes = nbytes
        self.cls = cls
        self.deadline = deadline
        self.want_parity = want_parity
        # pool warm-up submission (DevicePool prefetch): results are
        # discarded, staging is attributed to pool_prefetch_bytes_total
        self.prefetch = prefetch
        self.future: Future = Future()


class _Part:
    """A k-aligned slice of one item, small enough for the staging
    budget.  Items that fit whole have a single part."""

    __slots__ = ("item", "lo", "hi", "index", "total", "sink")

    def __init__(self, item, lo: int, hi: int, index: int, total: int,
                 sink: "_Assembler"):
        self.item = item
        self.lo = lo        # block/row offset into the item's payload
        self.hi = hi
        self.index = index  # part ordinal within the item
        self.total = total
        self.sink = sink


class _Assembler:
    """Collects one item's part results in order and resolves the future
    when the last part lands (bit-identical reassembly: parts split at
    codeword boundaries, parity columns zero-extend exactly)."""

    def __init__(self, item, total: int):
        self.item = item
        self.parts: List = [None] * total
        self.done = 0
        self.lock = threading.Lock()

    def deliver(self, index: int, result) -> None:
        item = self.item
        with self.lock:
            self.parts[index] = result
            self.done += 1
            if self.done < len(self.parts):
                return
        if item.future.done():
            return
        try:
            item.future.set_result(self._combine())
        except BaseException as e:  # noqa: BLE001 — assembly must not wedge waiters
            item.future.set_exception(e)

    def fail(self, e: BaseException) -> None:
        if not self.item.future.done():
            self.item.future.set_exception(e)

    def _combine(self):
        kind, parts = self.item.kind, self.parts
        if len(parts) == 1:
            return parts[0]
        if kind == "hash":
            return [h for p in parts for h in p]
        if kind == "decode":
            return np.concatenate(parts, axis=0)
        if kind == "encode":
            return _cat_parity(parts, self.item)
        # scrub: (ok, parity|None) per part
        ok = np.concatenate([p[0] for p in parts])
        if any(p[1] is None for p in parts):
            return ok, None
        return ok, _cat_parity([p[1] for p in parts], self.item)


def _cat_parity(rows: Sequence[np.ndarray], item) -> np.ndarray:
    """Concatenate per-part parity rows, zero-extending columns to the
    item-global maxlen (a block zero-extends to maxlen, and zero data
    columns produce zero parity columns — GF-linear, so the pad is the
    exact value the unsplit encode would have produced)."""
    blocks = item.payload if item.kind == "encode" else item.payload[0]
    maxlen = max(len(b) for b in blocks)
    out = []
    for p in rows:
        if p.shape[-1] < maxlen:
            p = np.pad(p, [(0, 0), (0, 0), (0, maxlen - p.shape[-1])])
        out.append(p[..., :maxlen])
    return np.concatenate(out, axis=0)


class _Batch:
    """One staged device dispatch: parts (possibly from several items)
    of a single kind, within the staging budget."""

    __slots__ = ("kind", "parts", "nbytes", "blocks", "eff_deadline",
                 "cls", "want_parity", "ts", "staged_est",
                 "t_enq", "t_pop", "t_stage0", "t_stage1", "t_adopt1",
                 "t_submit1", "t_ready", "compiled",
                 "pool_resident", "pool_adopt", "staged_payload",
                 "prefetch")

    def __init__(self, kind: str, cls: str):
        self.kind = kind
        self.cls = cls
        self.parts: List[_Part] = []
        self.nbytes = 0        # payload bytes (obs accounting)
        self.blocks = 0
        self.eff_deadline = 0.0
        self.want_parity = False
        self.ts = 0.0
        self.staged_est = 0    # bucketed staging-buffer bytes (admission)
        # monotonic_ns stage boundary stamps feeding the device timeline
        # (obs.timeline), the per-request transport/device spans and the
        # LinkProfiler's exact-sum stage breakdown: t_stage0 ≤ t_stage1
        # (stage_copy) ≤ t_adopt1 (adopt) ≤ t_submit1 (dispatch/compile)
        # ≤ t_ready (compute) ≤ collect end.  t_adopt1/t_ready come from
        # the device codec's own stamps, clamped into the enclosing
        # transport interval
        self.t_enq = 0
        self.t_pop = 0
        self.t_stage0 = 0
        self.t_stage1 = 0
        self.t_adopt1 = 0
        self.t_submit1 = 0
        self.t_ready = 0
        self.compiled = False  # did this dispatch trigger an XLA compile
        # DevicePool bookkeeping (None = staged the legacy, pool-less
        # way): resident lanes composed device-side, miss lanes to
        # adopt at collect, and the bytes that actually crossed the
        # link (what transport_staged_bytes_total must count — pool
        # hits move zero)
        self.pool_resident: Optional[list] = None
        self.pool_adopt: Optional[list] = None
        self.staged_payload: Optional[int] = None
        self.prefetch = False


class DeviceTransport:
    """One deadline-aware, double-buffered submission queue in front of
    one device codec.  See the module docstring for the design."""

    REQUIRED = {
        "hash": "hash_submit",
        "scrub": "scrub_encode_submit",
        "encode": "encode_submit",
        "decode": "decode_submit",
    }

    _PROBE_LANE_BYTES = 128 << 10  # probe splits into 128 KiB lanes

    def __init__(self, device, params, fallback=None, observer=None,
                 metrics=None, clock: Callable[[], float] = time.monotonic,
                 pool=None):
        """device: the array-level device codec (TpuCodec / synthetic).
        params: CodecParams (staging budget + transport tunables).
        fallback: a CPU BlockCodec absorbing failed batches inline.
        pool: an ops.device_pool.DevicePool consulted while staging
        scrub batches (None = legacy staging, byte-identical to the
        pre-pool transport)."""
        self.device = device
        self.params = params
        self.fallback = fallback
        self.pool = pool
        self.clock = clock
        if observer is None:
            from .observer import CodecObserver

            observer = CodecObserver(metrics=metrics)
        self.obs = observer
        self.slots = max(1, int(getattr(params, "transport_staging_slots",
                                        2)))
        budget = int(getattr(params, "max_device_staging_mib", 4096)) << 20
        # per-chunk staging bound: `slots` staged batches must fit the
        # budget together, so each chunk gets budget/slots (floored at
        # one codeword of the configured block size so tiny budgets
        # still make progress, matching the hybrid clamp's floor)
        k = max(1, params.rs_data)
        self.budget_bytes = max(budget, k * max(1, params.block_size))
        self.chunk_bytes = max(self.budget_bytes // self.slots,
                               k * max(1, params.block_size))
        self.bg_slack_s = max(
            0.0, float(getattr(params, "transport_bg_slack_ms", 50.0))
        ) / 1000.0
        # governor hook: model/garage.py points this at
        # LoadGovernor.ratio so background batches demote under
        # foreground pressure; None = no governor (ratio 1.0)
        self.governor_ratio: Optional[Callable[[], float]] = None

        self._cond = threading.Condition()
        self._heap: list = []
        self._seq = 0
        self._closed = False
        self._device_down = False
        self._thread: Optional[threading.Thread] = None
        self._inflight: list = []        # (batch, handle, variant) FIFO
        self._inflight_bytes = 0
        self._device_fails = 0
        self._slot_bufs: List[Optional[np.ndarray]] = [None] * self.slots
        self._slot_free: List[int] = list(range(self.slots))
        self._probe_buf: Optional[np.ndarray] = None
        self._probe_staging: Optional[np.ndarray] = None
        self._probe_warmed = False
        self._probe_lock = threading.Lock()

        # always-on accounting (admin `codec info` transport block +
        # bench attribution): the copy counter is the zero-copy claim's
        # proof — staged_copies is exactly one per staged block
        self.staged_bytes = 0
        self.staged_blocks = 0
        self.staged_copies = 0
        self.dispatches = 0
        self.chunks_split = 0
        self.fallbacks = 0
        self.max_staged_bytes_seen = 0
        self._depth = {"fg": 0, "bg": 0}
        # USE-method utilization accounting: device busy = wall time with
        # ≥ 1 batch staged-or-computing (the U of the device); link busy
        # = host-side stage/submit/collect work (the U of the host↔device
        # path).  Saturation = staged bytes queued + in flight vs the
        # budget.  Cumulative seconds; the *_ratio gauges window them at
        # render time.
        self.device_busy_seconds = 0.0
        self.link_busy_seconds = 0.0
        self._busy_since: Optional[float] = None
        self._queued_est = 0  # staged_est bytes still in the EDF heap
        # wall↔monotonic offset for converting timeline stamps into the
        # wall-clock span records the waterfall stores
        self._mono_off = time.time_ns() - time.monotonic_ns()

        # stage-level link attribution (ISSUE 16): every batch and every
        # probe round trip decomposed into stage_copy/adopt/compile/
        # dispatch/compute/collect with an exact-sum guarantee.  Hung off
        # the observer too so bench/admin reach it without holding a
        # transport reference across re-arms.
        from .link_profiler import LinkProfiler

        self.profiler = LinkProfiler(metrics=metrics)
        self.obs.link_profiler = self.profiler
        self.last_probe_stages: Optional[dict] = None

        if metrics is not None:
            self.m_staged = metrics.counter(
                "transport_staged_bytes_total",
                "Block bytes staged for the device by the transport, "
                "labelled with the host copies each byte paid "
                "(the zero-copy path stages exactly one)")
            self.m_depth = metrics.gauge(
                "transport_queue_depth",
                "Batches waiting in the device transport queue, by class",
                labeled_fn=lambda: [({"class": c}, float(n))
                                    for c, n in self._depth.items()])
            self.m_inflight = metrics.gauge(
                "transport_inflight_batches",
                "Device batches staged or computing (double-buffer "
                "occupancy)",
                fn=lambda: float(len(self._inflight)))
            # USE gauges (docs/OBSERVABILITY.md "Critical path &
            # saturation"): cumulative busy seconds for Grafana rate()
            # plus self-windowed busy fractions for at-a-glance reads
            metrics.gauge(
                "transport_device_busy_seconds",
                "Cumulative wall seconds the device had >= 1 batch "
                "staged or computing (USE utilization; rate() = busy "
                "fraction)", fn=self.device_busy_now)
            metrics.gauge(
                "transport_link_busy_seconds",
                "Cumulative host-side seconds spent staging, submitting "
                "and collecting device batches (USE utilization of the "
                "host<->device path)",
                fn=lambda: self.link_busy_seconds)
            dev_win = [time.monotonic(), 0.0]
            link_win = [time.monotonic(), 0.0]
            metrics.gauge(
                "transport_device_busy_ratio",
                "Device busy fraction over the last scrape window "
                "(0 idle, 1 saturated)",
                fn=lambda: self._window_ratio(self.device_busy_now,
                                              dev_win))
            metrics.gauge(
                "transport_link_busy_ratio",
                "Host<->device link busy fraction over the last scrape "
                "window",
                fn=lambda: self._window_ratio(
                    lambda: self.link_busy_seconds, link_win))
            metrics.gauge(
                "transport_queue_saturation",
                "Staged bytes queued + in flight vs the staging budget "
                "(USE saturation; > 1 means work is waiting on the "
                "double buffer)",
                fn=lambda: ((self._queued_est + self._inflight_bytes)
                            / self.budget_bytes))
        else:
            self.m_staged = self.m_depth = self.m_inflight = None

    def device_busy_now(self) -> float:
        """Cumulative device-busy seconds including the open interval."""
        busy, since = self.device_busy_seconds, self._busy_since
        if since is not None:
            busy += max(0.0, time.monotonic() - since)
        return busy

    @staticmethod
    def _window_ratio(getter, state: list) -> float:
        """Busy fraction since the previous render of the same gauge."""
        now = time.monotonic()
        busy = getter()
        t0, b0 = state
        state[0], state[1] = now, busy
        dt = now - t0
        if dt <= 0:
            return 0.0
        return min(max((busy - b0) / dt, 0.0), 1.0)

    # --- capability probing -------------------------------------------------

    @classmethod
    def supports_device(cls, device) -> bool:
        """The device implements enough of the transport API to be worth
        pumping (the fused scrub path at minimum)."""
        return (hasattr(device, "scrub_encode_submit")
                and hasattr(device, "staging_geometry"))

    def supports(self, kind: str) -> bool:
        return hasattr(self.device, self.REQUIRED[kind])

    @property
    def alive(self) -> bool:
        return not self._closed

    # --- submission ---------------------------------------------------------

    def submit_items(self, kind: str, items: Sequence, *,
                     want_parity: bool = True) -> None:
        """Enqueue a ragged batch of submissions (the feeder's dispatch
        unit).  Items' futures are resolved by the transport worker;
        raises TransportClosed without touching any future when the
        transport is shut down (the caller then dispatches inline)."""
        if kind not in KINDS:
            raise ValueError(f"unknown transport kind {kind!r}")
        if not self.supports(kind):
            raise TransportClosed(f"device lacks {self.REQUIRED[kind]}")
        batches = self._plan(kind, items, want_parity)
        now = self.clock()
        t_ns = time.monotonic_ns()
        with self._cond:
            if self._closed:
                raise TransportClosed("device transport is shut down")
            for b in batches:
                b.ts = time.perf_counter()
                b.t_enq = t_ns
                b.eff_deadline = self._effective_deadline(b, now)
                self._seq += 1
                heapq.heappush(
                    self._heap,
                    (b.eff_deadline, 0 if b.cls == "fg" else 1,
                     self._seq, b))
                self._depth[b.cls] = self._depth.get(b.cls, 0) + 1
                self._queued_est += b.staged_est
            if self._thread is None:
                self._thread = threading.Thread(
                    target=self._run, name="codec-transport", daemon=True)
                self._thread.start()
            self._cond.notify_all()
        tl = self.obs.timeline
        # host-side latency BEFORE the transport ever saw the work: the
        # oldest contributing item's feeder submit stamp → this enqueue
        # (pairs with the LinkProfiler's in-transport stages so the full
        # host journey is attributable from one trace)
        oldest = min((getattr(p.item, "t_mono_ns", 0)
                      for b in batches for p in b.parts
                      if getattr(p.item, "t_mono_ns", 0)), default=0)
        tl.event(f"enqueue {kind}", "edf", t_ns, cat="transport",
                 cls=batches[0].cls if batches else "fg",
                 batches=len(batches),
                 nbytes=sum(b.nbytes for b in batches),
                 feeder_ms=round((t_ns - oldest) / 1e6, 3) if oldest
                 else None)
        tl.counter("transport_queue", t_ns,
                   fg=self._depth.get("fg", 0), bg=self._depth.get("bg", 0))

    def _effective_deadline(self, batch: _Batch, now: float) -> float:
        """EDF key.  Foreground: arrival time — a request's expiry
        deadline is a shedding bound, not a scheduling priority, so
        foreground stays FIFO and always ahead of contemporaneous
        background.  Background: arrival + a slack that STRETCHES as
        the governor's throttle ratio drops — at ratio 1 background
        trails foreground by bg_slack, near min_ratio it queues behind
        every foreground batch of the next seconds (the same demotion
        discipline the wire and disk layers apply).  An explicit
        background deadline is honored as an upper bound: it can move
        the batch earlier, but the class rank still breaks an exact tie
        in foreground's favor."""
        if batch.cls == "fg":
            return now
        ratio = 1.0
        if self.governor_ratio is not None:
            try:
                ratio = min(max(float(self.governor_ratio()), 0.01), 1.0)
            except Exception:  # noqa: BLE001 — a dead governor is full rate
                ratio = 1.0
        demoted = now + self.bg_slack_s / ratio
        dls = [p.item.deadline for p in batch.parts
               if p.item.deadline is not None]
        return min(demoted, *dls) if dls else demoted

    # --- batch planning (the staging-bound clamp) ---------------------------

    def _plan(self, kind: str, items: Sequence,
              want_parity: bool) -> List[_Batch]:
        """Split items into staged batches of ≤ chunk_bytes.  Oversized
        items are cut at codeword-aligned boundaries and reassembled by
        their _Assembler; co-submitted items coalesce into one dispatch
        while they fit."""
        k = max(1, self.params.rs_data)
        batches: List[_Batch] = []
        cur: Optional[_Batch] = None

        def flush():
            nonlocal cur
            if cur is not None and cur.parts:
                batches.append(cur)
            cur = None

        lanes_ml = [0, 0]  # combined (entry-padded lanes, max len) of cur

        def est_with(pl: int, ml: int) -> int:
            if kind == "decode":
                return 0  # decode parts carry their own dense est
            return self._staged_est(
                kind, lanes_ml[0] + pl, max(lanes_ml[1], ml), k)

        for it in items:
            cls = getattr(it, "cls", "fg") or "fg"
            pf = bool(getattr(it, "prefetch", False))
            wp = bool(getattr(it, "want_parity", want_parity))
            pieces = self._cut_points(kind, it, k)
            sink = _Assembler(it, len(pieces))
            if len(pieces) > 1:
                self.chunks_split += len(pieces) - 1
                self.obs.event("transport_chunk", reason="staging_bound",
                               work=kind, parts=len(pieces),
                               nbytes=it.nbytes)
            for idx, (lo, hi, nb, blk, ml) in enumerate(pieces):
                pl = (blk + ((-blk) % k)
                      if kind in ("scrub", "encode") else blk)
                est = est_with(pl, ml) if kind != "decode" else nb
                if cur is not None and (
                        cur.cls != cls
                        # a prefetch item must not coalesce with real
                        # scrub work: the batch-level flag routes byte
                        # attribution (pool_prefetch_bytes vs hit/miss),
                        # and a mixed batch would misattribute one side
                        or cur.prefetch != pf
                        or (kind == "decode"
                            and cur.staged_est + est > self.chunk_bytes)
                        or (kind != "decode"
                            and cur.parts and est > self.chunk_bytes)):
                    flush()
                    lanes_ml[0] = lanes_ml[1] = 0
                    est = est_with(pl, ml) if kind != "decode" else nb
                if cur is None:
                    cur = _Batch(kind, cls)
                    cur.prefetch = pf
                cur.parts.append(
                    _Part(it, lo, hi, idx, len(pieces), sink))
                cur.nbytes += nb
                cur.blocks += blk
                lanes_ml[0] += pl
                lanes_ml[1] = max(lanes_ml[1], ml)
                cur.staged_est = (cur.staged_est + est if kind == "decode"
                                  else est)
                cur.want_parity = cur.want_parity or wp
        flush()
        return batches

    def _staged_est(self, kind: str, nlanes: int, maxlen: int,
                    k: int) -> int:
        """Bucketed staging-buffer bytes a slice will actually occupy —
        the budget must bound REAL host memory, not payload bytes: the
        device's staging geometry rounds lanes and row width up (power-
        of-two bucketing for retrace avoidance), which can near-4x a
        payload sized just past a bucket edge."""
        if kind in ("scrub", "encode"):
            nlanes += (-nlanes) % k
        lanes, cols = self._geometry(nlanes, maxlen, kind)
        return lanes * cols

    def _cut_points(self, kind: str, it, k: int):
        """[(lo, hi, nbytes, blocks, maxlen)] covering the item, each
        within chunk_bytes of BUCKETED staging bytes (the budget bounds
        real host memory, not payload bytes — the device geometry
        rounds lanes and row width up); scrub/encode cut only at
        multiples of k so no RS codeword straddles a chunk
        (item-relative: the codec groups k consecutive blocks from the
        item's start)."""
        if kind == "decode":
            shards, _present, _rows = it.payload
            pcount = min(int(shards.shape[-2]), max(1, k))
            s4 = int(shards.shape[-1])
            s4 += (-s4) % 4  # staged at the device's 4-aligned width
            per_row = int(pcount * s4)
            step = max(1, self.chunk_bytes // max(per_row, 1))
            n = int(shards.shape[0])
            return [(lo, min(lo + step, n),
                     (min(lo + step, n) - lo) * per_row,
                     min(lo + step, n) - lo, int(shards.shape[-1]))
                    for lo in range(0, n, step)]
        blocks = it.payload if kind != "scrub" else it.payload[0]
        n = len(blocks)
        whole_ml = max((len(b) for b in blocks), default=0)
        if self._staged_est(kind, n, whole_ml, k) <= self.chunk_bytes:
            return [(0, n, it.nbytes, n, whole_ml)]
        align = k if kind in ("scrub", "encode") else 1
        out = []
        lo = nb = i = 0
        ml = 0
        while i < n:
            j = min(i + align, n)
            unit = sum(len(b) for b in blocks[i:j])
            unit_ml = max((len(b) for b in blocks[i:j]), default=0)
            if i > lo and self._staged_est(
                    kind, j - lo, max(ml, unit_ml),
                    k) > self.chunk_bytes:
                out.append((lo, i, nb, i - lo, ml))
                lo, nb, ml = i, 0, 0
            nb += unit
            ml = max(ml, unit_ml)
            i = j
        out.append((lo, n, nb, n - lo, ml))
        return out

    # --- the worker ---------------------------------------------------------

    def _admit_locked(self, batch: _Batch) -> bool:
        if len(self._inflight) >= self.slots or not self._slot_free:
            return False
        if not self._inflight:
            return True  # a lone oversized batch must not deadlock
        return (self._inflight_bytes + batch.staged_est
                <= self.budget_bytes)

    def _run(self) -> None:
        register_thread("transport-stage")
        try:
            self._run_inner()
        finally:
            unregister_thread()

    def _run_inner(self) -> None:
        while True:
            batch = None
            with self._cond:
                while True:
                    if self._heap and self._admit_locked(self._heap[0][3]):
                        batch = heapq.heappop(self._heap)[3]
                        self._depth[batch.cls] -= 1
                        self._queued_est -= batch.staged_est
                        slot = self._slot_free.pop()
                        batch.t_pop = time.monotonic_ns()
                        self.obs.observe_stage(
                            "transport_wait", "tpu",
                            time.perf_counter() - batch.ts)
                        break
                    if self._inflight:
                        break  # collect to free a slot / the budget
                    if self._closed and not self._heap:
                        return
                    self._cond.wait()
            if batch is not None:
                self.obs.timeline.event(
                    f"edf_pop {batch.kind}", "edf", batch.t_pop,
                    cat="transport", cls=batch.cls,
                    wait_ms=round((batch.t_pop - batch.t_enq) / 1e6, 3),
                    deadline=round(batch.eff_deadline, 6))
                if self._device_down:
                    # the down latch means every device submit is doomed:
                    # queued batches skip straight to the CPU fallback
                    # instead of paying a stage + dead submit each
                    with self._cond:
                        self._slot_free.append(slot)
                        self._cond.notify_all()
                    self._absorb_on_cpu(batch, RuntimeError(
                        "device transport latched down"))
                    continue
                self._stage_and_submit(batch, slot)
                with self._cond:
                    can_pipeline = (len(self._inflight) < self.slots
                                    and self._slot_free)
                if can_pipeline:
                    continue  # double-buffer: stage N+1 while N computes
            self._collect_oldest()

    def _clear_device_stamps(self) -> None:
        """Reset the device codec's per-submit profiler stamps (adopt
        boundary, ready boundary, compile flag) so a device that stamps
        only some paths never leaks a stale boundary into the next
        batch's attribution.  Devices without the attributes (scripted
        fakes) are left untouched — their time folds into the enclosing
        stage."""
        dev = self.device
        for name, v in (("last_adopt_ns", 0), ("last_ready_ns", 0),
                        ("last_submit_compiled", False)):
            if hasattr(dev, name):
                try:
                    setattr(dev, name, v)
                except Exception:  # noqa: BLE001 — read-only fakes
                    pass

    @staticmethod
    def _clamp_stamp(raw: int, lo: int, hi: int) -> int:
        """Device-provided boundary stamp forced into its enclosing
        transport interval (0/garbage → the interval's start, so the
        whole span attributes to the outer stage)."""
        return min(max(raw or lo, lo), hi)

    def _stage_and_submit(self, batch: _Batch, slot: int) -> None:
        staged = None
        try:
            batch.t_stage0 = time.monotonic_ns()
            with self.obs.stage("host_staging", "tpu"):
                staged = self._stage(batch, slot)
            batch.t_stage1 = time.monotonic_ns()
            self._clear_device_stamps()
            with self.obs.stage("device_submit", "tpu"):
                handle = self._submit(batch, staged)
            batch.t_submit1 = time.monotonic_ns()
            dev = self.device
            batch.t_adopt1 = self._clamp_stamp(
                getattr(dev, "last_adopt_ns", 0),
                batch.t_stage1, batch.t_submit1)
            batch.compiled = bool(getattr(dev, "last_submit_compiled",
                                          False))
            self.link_busy_seconds += (batch.t_submit1
                                       - batch.t_stage0) / 1e9
            tl = self.obs.timeline
            track = f"slot{slot}"
            tl.event(f"stage {batch.kind}", track, batch.t_stage0,
                     batch.t_stage1, cat="transport", cls=batch.cls,
                     blocks=batch.blocks, staged_est=batch.staged_est,
                     prefetch=batch.prefetch,
                     pool_hits=(len(batch.pool_resident)
                                if batch.pool_resident is not None
                                else None))
            tl.event(f"adopt {batch.kind}", track, batch.t_stage1,
                     batch.t_adopt1, cat="transport")
            tl.event(f"submit {batch.kind}", track, batch.t_adopt1,
                     batch.t_submit1, cat="transport",
                     compiled=batch.compiled)
            variant = getattr(self.device, "last_submit_variant", None)
            with self._cond:
                if not self._inflight and self._busy_since is None:
                    self._busy_since = time.monotonic()
                self._inflight.append((batch, handle, variant, slot))
                self._inflight_bytes += batch.staged_est
                if self._inflight_bytes > self.max_staged_bytes_seen:
                    self.max_staged_bytes_seen = self._inflight_bytes
                self._cond.notify_all()
            tl.counter("transport_inflight", batch.t_submit1,
                       batches=len(self._inflight))
            self.dispatches += 1
            if self.m_staged is not None:
                # pool-aware staging reports the bytes that actually
                # crossed the link (miss lanes only) — a full pool hit
                # keeps transport_staged_bytes_total flat by contract
                self.m_staged.inc(
                    batch.nbytes if batch.staged_payload is None
                    else batch.staged_payload, copies="1")
        except BaseException as e:  # noqa: BLE001 — device down ≠ caller down
            self._device_failed("submit", e)
            # absorb BEFORE releasing the slot: the hash fallback reads
            # the staged rows in place, and the worker thread only
            # reuses a slot buffer after this returns
            self._absorb_on_cpu(batch, e, staged=staged)
            with self._cond:
                self._slot_free.append(slot)
                self._cond.notify_all()

    def _collect_oldest(self) -> None:
        with self._cond:
            if not self._inflight:
                return
            batch, handle, variant, slot = self._inflight[0]
        t_c0 = time.monotonic_ns()
        try:
            with self.obs.stage("sync_collect", "tpu"):
                results = self._collect(batch, handle)
        except BaseException as e:  # noqa: BLE001
            self._release(batch, slot)
            note = getattr(self.device, "note_sync_failure", None)
            if note is not None and batch.kind == "scrub":
                try:
                    note(e, variant)
                except Exception:
                    logger.warning("note_sync_failure hook failed",
                                   exc_info=True)
            self._device_failed("collect", e)
            self._absorb_on_cpu(batch, e)
            return
        t_c1 = time.monotonic_ns()
        batch.t_ready = self._clamp_stamp(
            getattr(self.device, "last_ready_ns", 0),
            max(t_c0, batch.t_submit1 or t_c0), t_c1)
        self.link_busy_seconds += (t_c1 - t_c0) / 1e9
        self._release(batch, slot)
        self._device_fails = 0
        note = getattr(self.device, "note_sync_success", None)
        if note is not None and batch.kind == "scrub":
            try:
                note(variant)
            except Exception:
                logger.warning("note_sync_success hook failed",
                               exc_info=True)
        self.obs.add_bytes("tpu", batch.nbytes)
        tl = self.obs.timeline
        track = f"slot{slot}"
        if batch.t_submit1 and batch.t_ready > batch.t_submit1:
            # device-busy window: dispatch return → results ready (the
            # block_until_ready delta, observed inside _collect)
            tl.event(f"compute {batch.kind}", track, batch.t_submit1,
                     batch.t_ready, cat="transport",
                     prefetch=batch.prefetch)
        tl.event(f"collect {batch.kind}", track, batch.t_ready or t_c0,
                 t_c1, cat="transport", blocks=batch.blocks)
        if batch.t_stage0:
            self.profiler.record(
                batch.kind, batch.nbytes, batch.t_stage0,
                [("stage_copy", batch.t_stage1),
                 ("adopt", batch.t_adopt1),
                 ("compile" if batch.compiled else "dispatch",
                  batch.t_submit1),
                 ("compute", batch.t_ready),
                 ("collect", t_c1)],
                want_breakdown=False)
        self._emit_request_spans(batch, t_c1)
        for part, res in zip(batch.parts, results):
            part.sink.deliver(part.index, res)

    def _emit_request_spans(self, batch: _Batch, t_end_mono: int) -> None:
        """Attribute this batch's queue wait and device round to each
        contributing REQUEST's trace (the feeder item carries its
        submitter's TraceContext + a pre-allocated parent span id) — the
        waterfall's `transport` and `device` segments come from here."""
        tracer = self.obs.tracer
        if tracer is None:
            return
        off = self._mono_off
        seen = set()
        for part in batch.parts:
            it = part.item
            tctx = getattr(it, "tctx", None)
            if tctx is None or id(it) in seen:
                continue
            seen.add(id(it))
            parent = getattr(it, "span_id", None) or tctx.span_id
            try:
                tracer.record_span(
                    f"Transport wait {batch.kind}", tctx.trace_id,
                    parent, batch.t_enq + off, batch.t_pop + off,
                    cls=batch.cls)
                tracer.record_span(
                    f"Device {batch.kind}", tctx.trace_id, parent,
                    batch.t_stage0 + off, t_end_mono + off,
                    blocks=batch.blocks)
            except Exception:  # noqa: BLE001 — attribution must not fail work
                logger.debug("transport span emit failed", exc_info=True)

    def _release(self, batch: _Batch, slot: int) -> None:
        with self._cond:
            self._inflight.pop(0)
            self._inflight_bytes -= batch.staged_est
            self._slot_free.append(slot)
            if not self._inflight and self._busy_since is not None:
                self.device_busy_seconds += max(
                    0.0, time.monotonic() - self._busy_since)
                self._busy_since = None
            self._cond.notify_all()
        self.obs.timeline.counter(
            "transport_inflight", time.monotonic_ns(),
            batches=len(self._inflight))

    def _device_failed(self, where: str, e: BaseException) -> None:
        self._device_fails += 1
        self.obs.event("transport_error", reason=where,
                       error=f"{type(e).__name__}: {e}"[:200],
                       fails=self._device_fails)
        logger.warning("device transport %s failed (%d/%d): %r", where,
                       self._device_fails, _MAX_DEVICE_FAILS, e)
        if self._device_fails >= _MAX_DEVICE_FAILS and not self._closed:
            self.obs.event("transport_down", reason="device_failures",
                           fails=self._device_fails)
            with self._cond:
                self._closed = True
                self._device_down = True
                self._cond.notify_all()

    # --- staging (the single host copy) -------------------------------------

    def _slot_view(self, slot: int, rows: int, cols: int) -> np.ndarray:
        """A (rows, cols) uint8 view of the slot's reusable flat staging
        buffer — grown geometrically, never shrunk, so steady-state
        staging allocates nothing (pinned-memory friendly)."""
        need = rows * cols
        buf = self._slot_bufs[slot]
        if buf is None or buf.size < need:
            # exact growth, not power-of-two: the staging budget bounds
            # real allocation, and the geometry is already bucketed
            buf = np.empty((need,), dtype=np.uint8)
            self._slot_bufs[slot] = buf
        return buf[:need].reshape(rows, cols)

    def _write_blocks(self, arr: np.ndarray, lengths: np.ndarray,
                      rows: Sequence[int], blocks: Sequence[bytes]) -> None:
        """THE host copy: each block lands once in its staging row (pad
        tail zeroed in place, no full-buffer memset).  One copy per
        block, counted."""
        cols = arr.shape[1]
        for r, b in zip(rows, blocks):
            n = len(b)
            if n:
                arr[r, :n] = np.frombuffer(b, dtype=np.uint8)
            if n < cols:
                arr[r, n:] = 0
            lengths[r] = n
        self.staged_copies += len(blocks)
        self.staged_blocks += len(blocks)
        self.staged_bytes += int(sum(len(b) for b in blocks))

    @staticmethod
    def _zero_gap_rows(arr: np.ndarray, written: Sequence[int],
                       lanes: int) -> None:
        """Zero only the staging rows NOT written this batch (entry
        lane-padding gaps + geometry pad lanes) — a reused slot buffer
        holds the previous batch's bytes, but a full memset would tax
        every staged byte with a second write pass."""
        written_set = set(written)
        lo = None
        for r in range(lanes):
            if r in written_set:
                if lo is not None:
                    arr[lo:r] = 0
                    lo = None
            elif lo is None:
                lo = r
        if lo is not None:
            arr[lo:lanes] = 0

    # SIMD-friendly staging layout (the ROADMAP CPU-floor item): hash
    # rows are placed at strides that are a multiple of this, so the
    # multi-buffer CPU hash (ops/native.py get_native_blake2s_rows) can
    # consume a staged batch IN PLACE — lane pointers into the buffer,
    # no per-row bytes materialization — when a device failure absorbs
    # the batch on the CPU.  64 B = one AVX-512 vector / cache line.
    HASH_ROW_ALIGN = 64

    def _geometry(self, nlanes: int, maxlen: int, kind: str):
        geom = getattr(self.device, "staging_geometry", None)
        lanes, cols = (geom(nlanes, maxlen, kind) if geom is not None
                       else (nlanes, maxlen))
        if kind == "hash":
            # applied INSIDE _geometry so the budget estimator and the
            # actual staging agree on the bucketed row width (device
            # power-of-two widths >= 64 are already aligned: no-op)
            cols += (-cols) % self.HASH_ROW_ALIGN
        return lanes, cols

    def _stage(self, batch: _Batch, slot: int):
        kind = batch.kind
        k = max(1, self.params.rs_data)
        if kind == "hash":
            flat: List[bytes] = []
            spans = []
            for p in batch.parts:
                blocks = p.item.payload[p.lo:p.hi]
                spans.append((len(flat), len(blocks)))
                flat.extend(blocks)
            maxlen = max((len(b) for b in flat), default=0)
            lanes, cols = self._geometry(len(flat), maxlen, kind)
            arr = self._slot_view(slot, lanes, cols)
            lengths = np.zeros((lanes,), dtype=np.int32)
            self._write_blocks(arr, lengths, range(len(flat)), flat)
            if lanes > len(flat):
                arr[len(flat):] = 0
            return arr, lengths, spans
        if kind == "scrub" and self.pool is not None:
            return self._stage_scrub_pooled(batch, slot, k)
        if kind in ("scrub", "encode"):
            # entries lane-pad to k so every part starts a fresh
            # codeword (pad lanes: zero data — and, for scrub, the
            # empty-digest expectation so they verify clean)
            rows = []
            flat = []
            hashes: List[Hash] = []
            lane = 0
            spans = []
            for p in batch.parts:
                if kind == "scrub":
                    b, h = p.item.payload
                    hashes.extend(h[p.lo:p.hi])
                    b = b[p.lo:p.hi]
                else:
                    b = p.item.payload[p.lo:p.hi]
                spans.append((lane, len(b)))
                rows.extend(range(lane, lane + len(b)))
                flat.extend(b)
                lane += len(b) + ((-len(b)) % k)
            maxlen = max((len(b) for b in flat), default=0)
            lanes, cols = self._geometry(lane, maxlen, kind)
            arr = self._slot_view(slot, lanes, cols)
            lengths = np.zeros((lanes,), dtype=np.int32)
            self._write_blocks(arr, lengths, rows, flat)
            self._zero_gap_rows(arr, rows, lanes)
            if kind == "encode":
                return arr.reshape(lanes // k, k, cols), spans
            expected = np.broadcast_to(
                _empty_digest_words(), (lanes, 8)).astype(np.uint32)
            for r, h in zip(rows, hashes):
                expected[r] = np.frombuffer(bytes(h), dtype="<u4")
            return arr, lengths, expected, spans
        # decode: shards are already arrays; staging packs the batch's
        # schedule groups contiguously (one copy per codeword row).
        # Only the first k survivor rows are staged — rs_reconstruct
        # semantics use exactly k — so the device-side slice is a view,
        # not a second copy.
        groups: dict = {}
        for pi, p in enumerate(batch.parts):
            shards, present, rws = p.item.payload
            key = (tuple(present[:k]),
                   tuple(rws) if rws is not None else None,
                   min(int(shards.shape[-2]), k))
            groups.setdefault(key, []).append(
                (pi, shards[p.lo:p.hi, :k, :]))
        plans = []
        for (present, rws, pcount), members in groups.items():
            max_s = max(sh.shape[-1] for _pi, sh in members)
            # stage at a 4-aligned width so the device's uint32 view
            # needs NO second pad copy (zero columns decode to zero
            # columns, GF-linear; per-part spans trim back at collect)
            max_s += (-max_s) % 4
            total = sum(sh.shape[0] for _pi, sh in members)
            stacked = np.zeros((total, pcount, max_s), dtype=np.uint8)
            off = 0
            spans = []
            for pi, sh in members:
                stacked[off:off + sh.shape[0], :, :sh.shape[-1]] = sh
                spans.append((pi, off, sh.shape[0], sh.shape[-1]))
                off += sh.shape[0]
                self.staged_copies += sh.shape[0]
                self.staged_blocks += sh.shape[0]
                self.staged_bytes += int(sh.nbytes)
            plans.append((stacked, list(present),
                          list(rws) if rws is not None else None, spans))
        return plans

    def _stage_scrub_pooled(self, batch: _Batch, slot: int, k: int):
        """Pool-aware scrub staging: the batch keeps its FULL lane
        geometry (k-aligned parts, lane-indexed spans/lengths/expected
        — so parity grouping and collect-side slicing are unchanged),
        but only MISS lanes pay the host copy, written compactly into
        the slot's first rows; resident lanes ship as device page
        references (zero link bytes).  Returns
        (miss_arr, miss_rows, lengths, expected, spans) for
        scrub_encode_submit_resident."""
        pool = self.pool
        lane = 0
        spans = []
        entries = []  # (lane, block, hash) in batch order
        for p in batch.parts:
            b, h = p.item.payload
            hs = h[p.lo:p.hi]
            bs = b[p.lo:p.hi]
            spans.append((lane, len(bs)))
            for i in range(len(bs)):
                entries.append((lane + i, bs[i], hs[i]))
            lane += len(bs) + ((-len(bs)) % k)
        maxlen = max((len(b) for _r, b, _h in entries), default=0)
        lanes, cols = self._geometry(lane, maxlen, "scrub")
        arr = self._slot_view(slot, lanes, cols)
        lengths = np.zeros((lanes,), dtype=np.int32)
        expected = np.broadcast_to(
            _empty_digest_words(), (lanes, 8)).astype(np.uint32)
        resident: list = []   # (lane, pages, length) composed on device
        adopt: list = []      # (lane, key, length) adopted at collect
        miss_rows: List[int] = []
        hit_bytes = miss_bytes = 0
        ci = 0
        for r, blk, hh in entries:
            n = len(blk)
            lengths[r] = n
            expected[r] = np.frombuffer(bytes(hh), dtype="<u4")
            entry = pool.lookup(bytes(hh), n)
            if entry is not None:
                resident.append((r, entry.pages, n))
                hit_bytes += n
                continue
            # THE host copy, miss lanes only (tail zeroed: the slot
            # buffer is reused and the device pads pages from it)
            if n:
                arr[ci, :n] = np.frombuffer(blk, dtype=np.uint8)
            if n < cols:
                arr[ci, n:] = 0
            miss_rows.append(r)
            adopt.append((r, bytes(hh), n))
            miss_bytes += n
            ci += 1
        self.staged_copies += ci
        self.staged_blocks += ci
        self.staged_bytes += miss_bytes
        # byte attribution: every scrubbed byte is a hit or a miss; a
        # prefetch batch's staging lands in its own family so the
        # hit+miss sum stays exactly the bytes the scrub asked for
        if not batch.prefetch:
            if hit_bytes:
                pool.note_hit(hit_bytes)
            if miss_bytes:
                pool.note_miss(miss_bytes)
        elif miss_bytes:
            pool.note_miss(miss_bytes, prefetch=True)
        batch.pool_resident = resident
        batch.pool_adopt = adopt
        batch.staged_payload = miss_bytes
        return arr[:ci], miss_rows, lengths, expected, spans

    # --- device dispatch / collect ------------------------------------------

    def _submit(self, batch: _Batch, staged):
        kind = batch.kind
        dev = self.device
        if kind == "hash":
            arr, lengths, spans = staged
            return dev.hash_submit(arr, lengths), spans
        if kind == "scrub":
            if batch.pool_adopt is not None:
                miss_arr, miss_rows, lengths, expected, spans = staged
                return dev.scrub_encode_submit_resident(
                    miss_arr, miss_rows, lengths, expected,
                    batch.pool_resident), spans
            arr, lengths, expected, spans = staged
            return dev.scrub_encode_submit(arr, lengths, expected), spans
        if kind == "encode":
            groups, spans = staged
            return dev.encode_submit(groups), spans
        return [(dev.decode_submit(st, present, rws), spans)
                for st, present, rws, spans in staged]

    def _collect(self, batch: _Batch, handle) -> List:
        kind = batch.kind
        dev = self.device
        if kind == "hash":
            out, spans = handle
            total = spans[-1][0] + spans[-1][1] if spans else 0
            digs = dev.hash_collect(out, total)
            return [digs[o:o + n] for o, n in spans]
        if kind == "scrub":
            out, spans = handle
            input_ref = None
            if batch.pool_adopt is not None:
                # resident submissions return (handle, composed device
                # input) — the input ref is the adoption source
                out, input_ref = out
            ok, parity = dev.scrub_collect(out, batch.want_parity)
            pool = self.pool
            if (pool is not None and batch.pool_adopt
                    and input_ref is not None):
                # adopt VERIFIED miss lanes only: a lane that failed
                # its hash check must never become a servable page
                page = pool.page_bytes
                for r, key, n in batch.pool_adopt:
                    if not bool(ok[r]):
                        continue
                    try:
                        pages = dev.pool_adopt(input_ref, r, n, page)
                    except Exception:  # noqa: BLE001 — adoption is best-effort
                        logger.warning("pool adoption failed",
                                       exc_info=True)
                        break
                    pool.adopt(key, pages, n)
            k = max(1, self.params.rs_data)
            results = []
            for part, (o, n) in zip(batch.parts, spans):
                p_slice = None
                if (parity is not None
                        and getattr(part.item, "want_parity", True)
                        and self.params.rs_data > 0 and n):
                    blocks = part.item.payload[0][part.lo:part.hi]
                    ml = max(len(b) for b in blocks)
                    r0, nr = o // k, (n + k - 1) // k
                    p_slice = np.ascontiguousarray(
                        parity[r0:r0 + nr, :, :ml])
                results.append((ok[o:o + n], p_slice))
            return results
        if kind == "encode":
            out, spans = handle
            parity = np.asarray(dev.encode_collect(out)
                                if hasattr(dev, "encode_collect") else out)
            k = max(1, self.params.rs_data)
            results = []
            for part, (o, n) in zip(batch.parts, spans):
                blocks = part.item.payload[part.lo:part.hi]
                ml = max(len(b) for b in blocks)
                r0, nr = o // k, (n + k - 1) // k
                results.append(np.ascontiguousarray(
                    parity[r0:r0 + nr, :, :ml]))
            return results
        # decode
        results: List = [None] * len(batch.parts)
        dcoll = getattr(dev, "decode_collect", None)
        for out, spans in handle:
            dec = np.asarray(dcoll(out) if dcoll is not None else out)
            for pi, off, nrows, s in spans:
                results[pi] = np.ascontiguousarray(
                    dec[off:off + nrows, ..., :s])
        return results

    # --- CPU absorption of device failures ----------------------------------

    def _absorb_on_cpu(self, batch: _Batch, cause: BaseException,
                       staged=None) -> None:
        """A failed device batch degrades to an inline CPU computation —
        zero caller-visible errors — unless no fallback codec exists.
        A hash batch that already reached staging is consumed IN PLACE
        (`staged`): the rows sit at lane-aligned strides, so the
        multi-buffer CPU hash runs straight over the staging buffer
        instead of re-reading the original payloads."""
        cpu = self.fallback
        if cpu is None:
            for part in batch.parts:
                part.sink.fail(cause)
            return
        self.fallbacks += 1
        self.obs.event("transport_fallback", reason=batch.kind,
                       blocks=batch.blocks)
        if batch.kind == "hash" and staged is not None:
            if self._absorb_hash_staged(batch, staged):
                return
        for part in batch.parts:
            it = part.item
            try:
                if batch.kind == "hash":
                    blocks = it.payload[part.lo:part.hi]
                    res = cpu.batch_hash(blocks)
                    nbytes = sum(len(b) for b in blocks)
                elif batch.kind == "scrub":
                    b, h = it.payload
                    blocks = b[part.lo:part.hi]
                    res = cpu.scrub_encode_batch(
                        blocks, h[part.lo:part.hi],
                        getattr(it, "want_parity", True))
                    nbytes = sum(len(x) for x in blocks)
                elif batch.kind == "encode":
                    blocks = it.payload[part.lo:part.hi]
                    res = cpu.rs_encode_blocks(blocks)
                    nbytes = sum(len(b) for b in blocks)
                else:
                    shards, present, rws = it.payload
                    sub = shards[part.lo:part.hi]
                    res = cpu.rs_reconstruct(sub, present, rws)
                    nbytes = int(sub.nbytes)
                self.obs.add_bytes("cpu", nbytes)
                part.sink.deliver(part.index, res)
            except BaseException as e:  # noqa: BLE001
                part.sink.fail(e)

    def _absorb_hash_staged(self, batch: _Batch, staged) -> bool:
        """Hash a staged batch's rows in place (SIMD-friendly staging
        layout: lane-aligned strides, zero re-copies).  Returns False on
        any surprise so the caller's payload-based fallback runs."""
        try:
            arr, lengths, spans = staged
            total = spans[-1][0] + spans[-1][1] if spans else 0
            from .native import get_native_blake2s_rows

            rows_fn = get_native_blake2s_rows()
            if rows_fn is not None:
                raw = rows_fn(arr, lengths, total)
            else:
                import hashlib

                # row views of a C-contiguous matrix are zero-copy too —
                # just one lane at a time instead of 8/16
                raw = [
                    hashlib.blake2s(arr[r, :int(lengths[r])],
                                    digest_size=32).digest()
                    for r in range(total)
                ]
            digs = [Hash(d) for d in raw]
            for part, (o, n) in zip(batch.parts, spans):
                self.obs.add_bytes(
                    "cpu", int(sum(int(x) for x in lengths[o:o + n])))
                part.sink.deliver(part.index, digs[o:o + n])
            return True
        except BaseException:  # noqa: BLE001 — fall back to payloads
            logger.warning("in-place staged hash fallback failed",
                           exc_info=True)
            return False

    # --- pool prefetch / residency ------------------------------------------

    def prefetch(self, blocks: Sequence[bytes],
                 hashes: Sequence[Hash]) -> int:
        """Stage the upcoming scrub range into the device pool as
        BACKGROUND-class work: the scrub worker hints the next read-
        ahead batch and its non-resident blocks ride the staging
        double buffer (under the governor's demotion slack) while the
        current batch computes — so by the time the real scrub batch
        arrives, its lanes are pool hits.  Results are discarded; the
        staging is attributed to pool_prefetch_bytes_total.  Returns
        the bytes enqueued (0 = already resident / pool or prefetch
        disabled / transport closed)."""
        pool = self.pool
        if (pool is None or not pool.prefetch_enabled or self._closed
                or not self.supports("scrub")):
            return 0
        todo_b: List[bytes] = []
        todo_h: List[Hash] = []
        for b, h in zip(blocks, hashes):
            if not pool.contains(bytes(h)):
                todo_b.append(b)
                todo_h.append(h)
        if not todo_b:
            return 0
        nbytes = int(sum(len(b) for b in todo_b))
        it = TransportItem("scrub", (todo_b, todo_h), len(todo_b),
                           nbytes, cls="bg", want_parity=False,
                           prefetch=True)
        it.future.add_done_callback(_swallow_result)
        try:
            self.submit_items("scrub", [it], want_parity=False)
        except TransportClosed:
            return 0
        self.obs.timeline.event(
            "pool_prefetch", "edf", time.monotonic_ns(),
            cat="transport", blocks=len(todo_b), nbytes=nbytes)
        return nbytes

    def pool_covers(self, items: Sequence) -> bool:
        """Would the pool serve every block of these scrub items with
        zero link bytes?  The feeder's gate-refresh short-circuit: a
        fully-resident background batch needs no link probe because it
        will not touch the link."""
        pool = self.pool
        if pool is None:
            return False
        keys: List[bytes] = []
        for it in items:
            if getattr(it, "kind", None) != "scrub":
                return False
            _b, hs = it.payload
            keys.extend(bytes(h) for h in hs)
        return pool.contains_all(keys)

    # --- the gate's probe ---------------------------------------------------

    def probe_link(self, nbytes: int) -> float:
        """Measured round-trip rate (GiB/s) of THIS path: `nbytes`
        staged (the single host copy) and adopted/transferred through
        the device's probe op — a trivial reduction whose scalar result
        DEPENDS on the upload, so the measurement is transfer-bound
        like the retired serialize+copy probe but priced on the new
        staging path.  The first call warms the probe executable
        outside the timed region.  Raises when the device lacks
        probe_submit (the hybrid then falls back to its own probe)."""
        dev = self.device
        if not hasattr(dev, "probe_submit"):
            raise TransportClosed("device lacks probe_submit")
        with self._probe_lock:
            if self._probe_buf is None or self._probe_buf.size < nbytes:
                self._probe_buf = np.random.default_rng(0).integers(
                    0, 256, (nbytes,), dtype=np.uint8)
            src = self._probe_buf[:nbytes]
            if (self._probe_staging is None
                    or self._probe_staging.size < nbytes):
                self._probe_staging = np.empty((nbytes,), dtype=np.uint8)
            staging = self._probe_staging[:nbytes]

            def roundtrip():
                """One probed round trip, decomposed with the same
                stage taxonomy (and exact-sum guarantee) as a real
                batch: the staging-buffer refill is priced — and now
                VISIBLE — as stage_copy bytes instead of folding into
                adopt time (ISSUE 16 satellite)."""
                self._clear_device_stamps()
                t0 = time.monotonic_ns()
                staging[:] = src          # the one host copy, priced in
                t_copy = time.monotonic_ns()
                handle = dev.probe_submit(staging)
                t_sub = time.monotonic_ns()
                collect = getattr(dev, "probe_collect",
                                  lambda h: int(np.asarray(h)))
                collect(handle)
                t_end = time.monotonic_ns()
                t_adopt = self._clamp_stamp(
                    getattr(dev, "last_adopt_ns", 0), t_copy, t_sub)
                t_ready = self._clamp_stamp(
                    getattr(dev, "last_ready_ns", 0), t_sub, t_end)
                compiled = bool(getattr(dev, "last_submit_compiled",
                                        False))
                stages = self.profiler.record(
                    "probe", nbytes, t0,
                    [("stage_copy", t_copy), ("adopt", t_adopt),
                     ("compile" if compiled else "dispatch", t_sub),
                     ("compute", t_ready), ("collect", t_end)])
                return (t_end - t0) / 1e9, stages

            if not self._probe_warmed:
                # warm round: the probe executable's compile lands here,
                # recorded (as `compile`) but excluded from the rate
                roundtrip()
                self._probe_warmed = True
            dt, stages = roundtrip()
            rate = nbytes / dt / 2**30 if dt > 0 else 0.0
            self.last_probe_stages = {k: round(v, 6)
                                      for k, v in stages.items()}
            from .link_profiler import dominant_stage

            self.obs.event("transport_probe", reason="ok",
                           gibs=round(rate, 4),
                           dominant_stage=dominant_stage(stages),
                           stage_copy_bytes=nbytes,
                           stages=self.last_probe_stages)
            return rate

    # --- lifecycle / introspection ------------------------------------------

    def copies_per_block(self) -> float:
        return (self.staged_copies / self.staged_blocks
                if self.staged_blocks else 0.0)

    def stats(self) -> dict:
        with self._cond:
            return {
                "alive": not self._closed,
                "queue_depth": dict(self._depth),
                "inflight": len(self._inflight),
                "inflight_bytes": self._inflight_bytes,
                "staged_bytes": self.staged_bytes,
                "staged_blocks": self.staged_blocks,
                "staged_copies": self.staged_copies,
                "copies_per_block": round(self.copies_per_block(), 4),
                "dispatches": self.dispatches,
                "chunks_split": self.chunks_split,
                "fallbacks": self.fallbacks,
                "max_staged_bytes_seen": self.max_staged_bytes_seen,
                "staging_slots": self.slots,
                "chunk_bytes": self.chunk_bytes,
                "budget_bytes": self.budget_bytes,
                "device_busy_seconds": round(self.device_busy_now(), 6),
                "link_busy_seconds": round(self.link_busy_seconds, 6),
                "queue_saturation": round(
                    (self._queued_est + self._inflight_bytes)
                    / self.budget_bytes, 6),
                "stages": self.profiler.summary(),
                "probe_stages": self.last_probe_stages,
                "pool": (self.pool.stats() if self.pool is not None
                         else None),
            }

    def shutdown(self, timeout: float = 15.0) -> None:
        """Refuse new submissions, drain everything already queued (the
        feeder's drain contract: accepted work is never dropped)."""
        with self._cond:
            already = self._closed
            self._closed = True
            t = self._thread
            self._cond.notify_all()
        if not already:
            self.obs.event("transport_drain", reason="shutdown")
        if t is not None:
            t.join(timeout)
            if t.is_alive():
                logger.warning(
                    "device transport drain did not finish within %.1fs",
                    timeout)
