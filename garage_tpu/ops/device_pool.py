"""DevicePool — device-resident block pages under the DeviceTransport.

Why this exists.  Every device touch through PR 17 staged host→device
and DISCARDED: scrub, resync verify, and degraded decode of the same
hot blocks re-paid the link on every pass, which is why BENCH_r05
scrubbed at 0.91 GiB/s while the device kernel does 24 GiB/s.  The
link, not the ALU, is the warm-path bound — so this module treats the
device as a MEMORY: a bounded set of fixed-size device pages (the
Ragged Paged Attention layout, PAPERS.md — fixed page size, ragged
occupancy, in-place reuse) keyed by block hash, budgeted by
``[codec] pool_mib`` SEPARATELY from the staging budget
(``max_device_staging_mib`` bounds bytes in flight; the pool bounds
bytes at rest).

Layout.  A block of ``length`` bytes spans ``ceil(length / page)``
pages; the tail page is partially filled and zero-padded (ragged
occupancy — the budget charges whole pages, so ``bytes_for(length)``
is the page-rounded claim).  Pages are opaque device handles produced
by the device codec's pool API (``pool_adopt`` slices them out of an
already-submitted device batch — a device-side copy, ZERO link bytes)
and composed back into batch lanes by
``scrub_encode_submit_resident`` (again device-side).

Integration (ops/transport.py).  The transport consults the pool
while STAGING a scrub batch: a resident block's lane skips the host
copy and the H2D transfer entirely (``transport_staged_bytes_total``
stays flat; ``pool_hit_bytes_total`` takes the bytes), a miss stages
through the normal slot path and its verified lanes are adopted at
collect (``pool_miss_bytes_total``).  Every pool read still runs
through the device scrub kernel's hash verify — a corrupt page can
never return clean — but strict invalidation keeps hits USEFUL:
block delete, quarantine, rebalance-drop and overwrite all call
``invalidate`` synchronously before the operation acks
(block/manager.py), so the pool never serves a page for a block the
store no longer holds.

Eviction clock.  LRU in SCRUB-CYCLE time, not wall time: ``tick()``
advances once per scrub pass (block/repair.py), and entries untouched
for the most cycles evict first.  Wall-clock LRU would evict the
whole working set during any long idle period even though the next
pass needs exactly the same blocks; cycle LRU keeps "the blocks the
last pass touched" resident however long the pass interval is.

Thread-safety: one lock.  ``lookup``/``adopt`` run on the transport
worker thread, ``invalidate`` on event-loop and disk worker threads,
``tick`` on the scrub worker — all synchronous, all cheap (dict ops;
page frees are reference drops, the device runtime reclaims
asynchronously).
"""

from __future__ import annotations

import logging
import threading
from collections import OrderedDict
from typing import List, Optional

logger = logging.getLogger("garage_tpu.ops.device_pool")


class _PoolEntry:
    """One resident block: its device pages plus the bookkeeping the
    eviction clock needs."""

    __slots__ = ("key", "length", "pages", "tick", "page_bytes")

    def __init__(self, key: bytes, length: int, pages: List,
                 tick: int, page_bytes: int):
        self.key = key
        self.length = length
        self.pages = pages
        self.tick = tick  # scrub cycle of the last touch
        self.page_bytes = page_bytes

    @property
    def charged_bytes(self) -> int:
        return len(self.pages) * self.page_bytes


class DevicePool:
    """Bounded pool of device-resident block pages, hash-keyed."""

    def __init__(self, device, pool_bytes: int, page_bytes: int,
                 prefetch: bool = True, metrics=None, observer=None):
        self.device = device
        self.pool_bytes = max(0, int(pool_bytes))
        self.page_bytes = max(1, int(page_bytes))
        self.prefetch_enabled = bool(prefetch)
        self.obs = observer
        self._lock = threading.Lock()
        # insertion/touch order IS the eviction order: lookup moves an
        # entry to the back, so the front is always the least-recently-
        # used entry of the oldest cycle (cycle order within = touch
        # order — exactly what the scrub walk produces)
        self._entries: "OrderedDict[bytes, _PoolEntry]" = OrderedDict()
        self._resident_bytes = 0  # page-rounded (the budget currency)
        self._tick = 0
        # always-on accounting (admin `codec info` pool block + bench)
        self.hits = 0
        self.misses = 0
        self.hit_bytes = 0
        self.miss_bytes = 0
        self.prefetch_bytes = 0
        self.adopted = 0
        self.evicted_lru = 0
        self.invalidated = 0
        if metrics is not None:
            self.m_hit = metrics.counter(
                "pool_hit_bytes_total",
                "Block bytes served from device-resident pool pages "
                "(zero link bytes moved; with pool_miss_bytes_total "
                "this attributes every scrubbed byte)")
            self.m_miss = metrics.counter(
                "pool_miss_bytes_total",
                "Block bytes staged over the host-device link because "
                "no pool page held them (adopted into the pool after "
                "the batch verifies)")
            self.m_prefetch = metrics.counter(
                "pool_prefetch_bytes_total",
                "Block bytes staged ahead of need by the scrub "
                "worker's next-range prefetch hint (background-class "
                "link work overlapping the current batch's compute)")
            self.m_evict = metrics.counter(
                "pool_evict_total",
                "Pool pages released, by reason (lru = scrub-cycle "
                "eviction under the pool_mib budget, invalidate = "
                "synchronous delete/quarantine/rebalance/overwrite "
                "eviction, replace = re-adoption of a resident hash)")
            metrics.gauge(
                "pool_resident_bytes",
                "Page-rounded bytes currently held in device-resident "
                "pool pages (bounded by [codec] pool_mib)",
                fn=lambda: float(self._resident_bytes))
            metrics.gauge(
                "pool_pages",
                "Device pages currently held by the block pool",
                fn=lambda: float(self._resident_bytes // self.page_bytes))
        else:
            self.m_hit = self.m_miss = None
            self.m_prefetch = self.m_evict = None

    # --- capability probing -------------------------------------------------

    @classmethod
    def supports_device(cls, device) -> bool:
        """The device implements the pool API: resident-lane scrub
        submission plus device-side page extraction/readback."""
        return (hasattr(device, "scrub_encode_submit_resident")
                and hasattr(device, "pool_adopt")
                and hasattr(device, "pool_read"))

    # --- geometry -----------------------------------------------------------

    def pages_for(self, length: int) -> int:
        """Pages a block of `length` bytes spans (ragged occupancy: the
        tail page is partially filled)."""
        return max(1, -(-int(length) // self.page_bytes))

    def bytes_for(self, length: int) -> int:
        """Page-rounded budget charge for a block of `length` bytes."""
        return self.pages_for(length) * self.page_bytes

    # --- the scrub-cycle clock ----------------------------------------------

    def tick(self) -> int:
        """Advance the eviction clock by one scrub cycle (called at
        every scrub pass start, block/repair.py)."""
        with self._lock:
            self._tick += 1
            return self._tick

    # --- lookup / adopt -----------------------------------------------------

    def lookup(self, key: bytes, length: int) -> Optional[_PoolEntry]:
        """The entry for `key` if resident with a matching length,
        bumping it to most-recently-used in the current cycle.  A
        length mismatch (impossible for content-addressed blocks
        unless something rewrote the store behind the pool's back) is
        treated as a miss AND evicts the suspect entry — serving it
        would at best fail the device verify, at worst mask a bug."""
        key = bytes(key)
        with self._lock:
            e = self._entries.get(key)
            if e is None:
                return None
            if e.length != int(length):
                self._drop_locked(key, "invalidate")
                logger.warning(
                    "pool entry %s length %d != looked-up %d: evicted",
                    key.hex()[:16], e.length, length)
                return None
            e.tick = self._tick
            self._entries.move_to_end(key)
            return e

    def contains(self, key: bytes) -> bool:
        """Residency check with NO LRU side effect (the feeder's
        gate-refresh short-circuit and the prefetch filter)."""
        with self._lock:
            return bytes(key) in self._entries

    def contains_all(self, keys) -> bool:
        """True when every key is resident (and there is at least one):
        a batch a pool hit would fully satisfy."""
        with self._lock:
            if not self._entries:
                return False
            got_any = False
            for k in keys:
                got_any = True
                if bytes(k) not in self._entries:
                    return False
            return got_any

    def adopt(self, key: bytes, pages: List, length: int) -> bool:
        """Admit one block's device pages, evicting LRU entries until
        the budget fits.  A block bigger than the whole budget is
        refused (dropping the page refs frees them).  Re-adopting a
        resident hash replaces the old pages — the overwrite shape of
        strict invalidation."""
        key = bytes(key)
        need = len(pages) * self.page_bytes
        if need > self.pool_bytes:
            if self.obs is not None:
                self.obs.event("pool_refuse", reason="over_budget",
                               nbytes=need)
            return False
        with self._lock:
            if key in self._entries:
                self._drop_locked(key, "replace")
            while (self._resident_bytes + need > self.pool_bytes
                   and self._entries):
                old_key = next(iter(self._entries))
                self._drop_locked(old_key, "lru")
                self.evicted_lru += 1
            e = _PoolEntry(key, int(length), list(pages), self._tick,
                           self.page_bytes)
            self._entries[key] = e
            self._resident_bytes += need
            self.adopted += 1
        return True

    def _drop_locked(self, key: bytes, reason: str) -> None:
        e = self._entries.pop(key, None)
        if e is None:
            return
        self._resident_bytes -= e.charged_bytes
        e.pages = []  # the reference drop IS the device-side free
        if self.m_evict is not None:
            self.m_evict.inc(reason=reason)

    # --- strict invalidation ------------------------------------------------

    def invalidate(self, key: bytes, reason: str = "invalidate") -> bool:
        """Synchronously evict `key` — called BEFORE the store acks a
        delete/quarantine/rebalance-drop/overwrite (block/manager.py),
        so the pool can never serve a page for a block the store no
        longer holds.  Returns whether anything was resident."""
        with self._lock:
            present = bytes(key) in self._entries
            if present:
                self._drop_locked(bytes(key), "invalidate")
                self.invalidated += 1
        if present and self.obs is not None:
            self.obs.event("pool_invalidate", reason=reason,
                           hash=bytes(key).hex()[:16])
        return present

    def clear(self) -> None:
        with self._lock:
            for key in list(self._entries):
                self._drop_locked(key, "invalidate")

    # --- byte attribution (the transport's staging loop calls these) --------

    def note_hit(self, nbytes: int) -> None:
        self.hits += 1
        self.hit_bytes += int(nbytes)
        if self.m_hit is not None:
            self.m_hit.inc(int(nbytes))

    def note_miss(self, nbytes: int, prefetch: bool = False) -> None:
        """Miss accounting: a PREFETCH batch's staging is attributed to
        its own family, so pool_hit + pool_miss still equals exactly
        the bytes the scrub itself asked for."""
        if prefetch:
            self.prefetch_bytes += int(nbytes)
            if self.m_prefetch is not None:
                self.m_prefetch.inc(int(nbytes))
            return
        self.misses += 1
        self.miss_bytes += int(nbytes)
        if self.m_miss is not None:
            self.m_miss.inc(int(nbytes))

    # --- readback (tests / smoke: bit-identity proof) -----------------------

    def read(self, key: bytes) -> Optional[bytes]:
        """The resident block's bytes fetched back from its device
        pages (D2H — test/debug surface, not a data path), trimmed to
        the ragged tail.  None when not resident."""
        with self._lock:
            e = self._entries.get(bytes(key))
            if e is None:
                return None
            pages, length = list(e.pages), e.length
        return self.device.pool_read(pages, length)

    # --- introspection ------------------------------------------------------

    @property
    def resident_bytes(self) -> int:
        return self._resident_bytes

    def stats(self) -> dict:
        with self._lock:
            return {
                "pool_bytes": self.pool_bytes,
                "page_bytes": self.page_bytes,
                "resident_bytes": self._resident_bytes,
                "resident_blocks": len(self._entries),
                "resident_pages": self._resident_bytes // self.page_bytes,
                "tick": self._tick,
                "hits": self.hits,
                "misses": self.misses,
                "hit_bytes": self.hit_bytes,
                "miss_bytes": self.miss_bytes,
                "prefetch_bytes": self.prefetch_bytes,
                "adopted": self.adopted,
                "evicted_lru": self.evicted_lru,
                "invalidated": self.invalidated,
                "prefetch": self.prefetch_enabled,
            }
