"""GF(2^8) arithmetic and Reed-Solomon matrix construction.

New capability vs the reference (which replicates whole blocks; RS(k,m) is
the north-star addition per BASELINE.json).  Polynomial 0x11D (x^8+x^4+x^3+
x^2+1), the standard RS-code field shared by ISA-L/jerasure, so shards are
interoperable with common tooling.

Two execution formulations of the same code:

1. Byte domain (CPU / numpy): y = Σ_i gf_mul(G[p,i], x_i) via log/exp
   tables — `rs_encode_numpy`.
2. Bit domain (TPU / MXU): every GF(2^8) constant c is an 8×8 matrix over
   GF(2) (column j = bits of c·2^j), so the whole RS generator becomes one
   (8k × 8m) 0/1 matrix W and encoding is `parity_bits = (data_bits @ W) & 1`
   — an int8 matmul XLA tiles onto the MXU, batched over byte positions.
   `bitmatrix_of_gf_matrix` builds W; tests assert both formulations agree.

Decoding/repair: invert the surviving k×k submatrix of the extended
generator over GF(2^8) (`gf_matrix_inverse`, tiny — CPU), then the recovery
is again a (bit-)matmul with the inverted matrix.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import numpy as np

_POLY = 0x11D

# --- field tables -----------------------------------------------------------


def _build_tables() -> Tuple[np.ndarray, np.ndarray]:
    exp = np.zeros(512, dtype=np.uint8)
    log = np.zeros(256, dtype=np.int32)
    x = 1
    for i in range(255):
        exp[i] = x
        log[x] = i
        x <<= 1
        if x & 0x100:
            x ^= _POLY
    exp[255:510] = exp[0:255]  # wraparound so exp[log a + log b] needs no mod
    return exp, log


GF_EXP, GF_LOG = _build_tables()


def gf_mul(a: int, b: int) -> int:
    if a == 0 or b == 0:
        return 0
    return int(GF_EXP[int(GF_LOG[a]) + int(GF_LOG[b])])


def gf_inv(a: int) -> int:
    if a == 0:
        raise ZeroDivisionError("gf_inv(0)")
    return int(GF_EXP[255 - int(GF_LOG[a])])


def gf_mul_vec(c: int, x: np.ndarray) -> np.ndarray:
    """c · x elementwise over GF(2^8), x uint8 array."""
    if c == 0:
        return np.zeros_like(x)
    lc = int(GF_LOG[c])
    out = GF_EXP[lc + GF_LOG[x.astype(np.int32)]]
    out = np.where(x == 0, 0, out).astype(np.uint8)
    return out


# --- matrices over GF(2^8) --------------------------------------------------


def gf_matmul(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    """(r,k)·(k,c) matrix product over GF(2^8); small matrices only."""
    r, k = a.shape
    k2, c = b.shape
    assert k == k2
    out = np.zeros((r, c), dtype=np.uint8)
    for i in range(r):
        for j in range(c):
            acc = 0
            for t in range(k):
                acc ^= gf_mul(int(a[i, t]), int(b[t, j]))
            out[i, j] = acc
    return out


def gf_matrix_inverse(m: np.ndarray) -> np.ndarray:
    """Gauss-Jordan inverse of a k×k matrix over GF(2^8)."""
    k = m.shape[0]
    assert m.shape == (k, k)
    a = m.astype(np.uint8).copy()
    inv = np.eye(k, dtype=np.uint8)
    for col in range(k):
        piv = next((r for r in range(col, k) if a[r, col] != 0), None)
        if piv is None:
            raise ZeroDivisionError("singular matrix over GF(2^8)")
        if piv != col:
            a[[col, piv]] = a[[piv, col]]
            inv[[col, piv]] = inv[[piv, col]]
        pinv = gf_inv(int(a[col, col]))
        a[col] = gf_mul_vec(pinv, a[col])
        inv[col] = gf_mul_vec(pinv, inv[col])
        for r in range(k):
            if r != col and a[r, col] != 0:
                f = int(a[r, col])
                a[r] ^= gf_mul_vec(f, a[col])
                inv[r] ^= gf_mul_vec(f, inv[col])
    return inv


def rs_generator_matrix(k: int, m: int) -> np.ndarray:
    """Systematic extended generator: (k+m, k), top = I_k, bottom = Cauchy
    parity rows P[i,j] = 1/(x_i ⊕ y_j) with x_i = k+i, y_j = j.  Any k rows
    of the result are invertible (Cauchy ⊂ MDS), which is exactly the
    reconstruct-from-any-k property."""
    if k + m > 256:
        raise ValueError("k+m must be ≤ 256 for GF(2^8) RS")
    g = np.zeros((k + m, k), dtype=np.uint8)
    g[:k] = np.eye(k, dtype=np.uint8)
    for i in range(m):
        for j in range(k):
            g[k + i, j] = gf_inv((k + i) ^ j)
    return g


def rs_parity_matrix(k: int, m: int) -> np.ndarray:
    return rs_generator_matrix(k, m)[k:]


def rs_decode_matrix(k: int, m: int, present: Sequence[int]) -> np.ndarray:
    """Recovery matrix (k, k): data = D @ shards[present[:k]].

    `present` = indices (into the k+m extended codeword) of ≥k surviving
    shards; the first k are used."""
    rows = list(present)[:k]
    if len(rows) < k:
        raise ValueError(f"need ≥{k} shards, have {len(rows)}")
    g = rs_generator_matrix(k, m)
    sub = g[rows]  # (k, k): shards[rows] = sub @ data
    return gf_matrix_inverse(sub)


def rs_decode_row(k: int, m: int, present: Sequence[int],
                  row: int) -> np.ndarray:
    """One row of the recovery matrix: the k coefficients c_j with
    data[row] = Σ_j c_j ⊗ shards[present[j]].  This is the wire contract
    of partial-parallel repair (block/repair_plan.py): the coordinator
    hands survivor j its coefficient c_j, each survivor ships
    c_j ⊗ shard_j, and the coordinator only XOR-accumulates."""
    return rs_decode_matrix(k, m, present)[row]


def gf_scale_bytes(c: int, buf: bytes, limit: Optional[int] = None) -> bytes:
    """c ⊗ buf elementwise over GF(2^8), truncated to the first `limit`
    bytes — the survivor-side partial-product kernel.  c == 0 returns
    b'' (a zero contribution ships no bytes)."""
    b = buf[:limit] if limit is not None else buf
    if c == 0 or not b:
        return b""
    if c == 1:
        return bytes(b)
    return gf_mul_vec(c, np.frombuffer(b, dtype=np.uint8)).tobytes()


# --- byte-domain (CPU) kernels ---------------------------------------------


def gf_matmul_blocks(mat: np.ndarray, shards: np.ndarray) -> np.ndarray:
    """Apply (r, k) GF matrix to shards (..., k, S) → (..., r, S).

    Vectorized over byte positions with log/exp tables; the numpy CPU
    baseline the TPU path must match bit-for-bit."""
    r, k = mat.shape
    assert shards.shape[-2] == k
    out = np.zeros(shards.shape[:-2] + (r, shards.shape[-1]), dtype=np.uint8)
    for i in range(r):
        acc = np.zeros(shards.shape[:-2] + (shards.shape[-1],), dtype=np.uint8)
        for j in range(k):
            acc ^= gf_mul_vec(int(mat[i, j]), shards[..., j, :])
        out[..., i, :] = acc
    return out


# --- bit-domain (TPU) matrix construction -----------------------------------


def gf_const_bitmatrix(c: int) -> np.ndarray:
    """8×8 GF(2) matrix M_c with  bits(c·x) = M_c @ bits(x)  (column j =
    bits of c·2^j, LSB-first)."""
    mcols = []
    for j in range(8):
        prod = gf_mul(c, 1 << j)
        mcols.append([(prod >> u) & 1 for u in range(8)])
    return np.array(mcols, dtype=np.uint8).T  # (8 out-bits, 8 in-bits)


def bitmatrix_of_gf_matrix(mat: np.ndarray) -> np.ndarray:
    """Expand an (r, k) GF(2^8) matrix into the (8k, 8m=8r) 0/1 matmul
    operand W with  out_bits = in_bits @ W  (in_bits laid out as
    [..., k*8] = shard-major, LSB-first within each byte)."""
    r, k = mat.shape
    w = np.zeros((k * 8, r * 8), dtype=np.uint8)
    for i in range(r):
        for j in range(k):
            m = gf_const_bitmatrix(int(mat[i, j]))  # (8 out, 8 in)
            w[j * 8:(j + 1) * 8, i * 8:(i + 1) * 8] = m.T  # in-bits rows → out-bits cols
    return w


def unpack_bits_lsb(x: np.ndarray) -> np.ndarray:
    """uint8 (..., n) → (..., n*8) bits, LSB-first per byte."""
    return np.unpackbits(x, axis=-1, bitorder="little")


def pack_bits_lsb(b: np.ndarray) -> np.ndarray:
    return np.packbits(b, axis=-1, bitorder="little")


def rs_encode_bits_numpy(data: np.ndarray, w: np.ndarray) -> np.ndarray:
    """Reference implementation of the TPU formulation, in numpy:
    data (..., k, S) uint8 → parity (..., m, S) via bit-matmul mod 2."""
    k8, m8 = w.shape
    k, m = k8 // 8, m8 // 8
    s = data.shape[-1]
    # (..., S, k) bytes → (..., S, k*8) bits
    bits = unpack_bits_lsb(np.swapaxes(data, -1, -2).copy())
    out_bits = (bits.astype(np.int32) @ w.astype(np.int32)) & 1
    parity = pack_bits_lsb(out_bits.astype(np.uint8))  # (..., S, m)
    return np.swapaxes(parity, -1, -2).copy()
