"""Hybrid BlockCodec — adaptive host+device scrub with work stealing.

Why this exists.  The TPU codec's throughput is capped by the host→device
link: behind a constrained tunnel the sustained transfer rate can drop to
the same order as — or below — one CPU core's hashing rate, and it varies
over time (burst quotas, shared tenancy).  Statically routing all scrub
work to either backend therefore leaves throughput on the floor.  The
hybrid codec runs BOTH: the caller's thread drives the CPU codec (the
guaranteed floor — hashlib + the native GF kernel), while a feeder thread
streams groups to the device codec, keeping a bounded in-flight window.
Work distribution is a classic stealing deque — CPU pulls groups from the
left, the device from the right — so the split adapts to whatever rate
each side actually sustains, with no rate model to mistune:

  total throughput ≈ cpu_rate + min(link_rate, device_rate)

and the device is never on the critical path: at the tail of a pass the
CPU *hedges* — after a short grace period it recomputes the groups the
device still holds in flight, first writer wins, and the feeder thread is
left to drain its transfers in the background rather than joined.  A
stalled link therefore costs at most one grace period, not a sync.

The reference has no equivalent — its scrub is a strictly sequential
per-block CPU loop (ref src/block/repair.rs:438-490, block.rs:66-78
verify); this is the TPU-first replacement identified in SURVEY.md §7.

Semantics are those of BlockCodec: results are bit-identical whichever
backend processed a group (tests/test_hybrid_codec.py).
"""

from __future__ import annotations

import collections
import logging
import threading
import time
from typing import List, Optional, Sequence, Tuple

import numpy as np

from ..utils.data import Hash
from .codec import BlockCodec, CodecParams
from .cpu_codec import CpuCodec

logger = logging.getLogger("garage_tpu.ops.hybrid")

# Feeders are daemon threads (a stalled device link must never wedge
# process exit), but exiting the interpreter while one is blocked inside a
# device transfer aborts the process from C++ (PJRT raises through a dying
# runtime).  Track live feeders and give them a bounded drain at exit.
_LIVE_FEEDERS: "collections.deque[threading.Thread]" = collections.deque()
_FEEDER_EXIT_GRACE_S = 15.0


def _drain_feeders_at_exit() -> None:
    deadline = time.monotonic() + _FEEDER_EXIT_GRACE_S
    while _LIVE_FEEDERS:
        t = _LIVE_FEEDERS.popleft()
        t.join(timeout=max(0.0, deadline - time.monotonic()))


import atexit  # noqa: E402  (registration belongs right next to the state)

atexit.register(_drain_feeders_at_exit)


class HybridCodec(BlockCodec):
    """CPU floor + opportunistic device offload, per-group work stealing."""

    def __init__(self, params: CodecParams,
                 device_codec: Optional[BlockCodec] = None,
                 build_device="sync", metrics=None, tracer=None):
        """build_device selects how the device codec is constructed:
          "sync"  — build now (the caller has already probed the device
                    alive, e.g. bench.py after its subprocess probe);
          "async" — build on a background thread and attach when ready.
                    This is what the daemon config path uses: JAX backend
                    init can hang unboundedly on a dead device tunnel, and
                    a storage daemon must come up and scrub on its CPU
                    floor regardless (the device joins in when/if init
                    completes);
          False   — never build; pure CPU floor.

        metrics/tracer: the System-owned MetricsRegistry/Tracer — stage
        histograms, bytes-by-side counters, and the gate-decision event
        ring become node-visible (/metrics + admin `codec info`)."""
        super().__init__(params, metrics=metrics, tracer=tracer)
        # the inner CPU codec gets NO observer plumbing: the hybrid does
        # all byte/stage accounting itself (first-writer-wins makes the
        # inner codec's view double-count hedged groups)
        self.cpu = CpuCodec(params)
        self.tpu = device_codec
        # group = the stealing quantum; must be k-aligned so each group's
        # parity layout is self-contained (k=0: replication-only config, no
        # RS — groups need no alignment and scrub is verify-only)
        k = max(1, params.rs_data)
        g = max(params.hybrid_group_blocks, k)
        self.group_blocks = g - (g % k)
        self.window = max(1, params.hybrid_window)
        # Device submission width: the feeder MERGES consecutive deque
        # groups up to this many blocks per scrub_submit.  The device
        # blake2s runs one VPU lane per block, so its rate is a strong
        # function of batch width (measured v5e: 0.18 GiB/s at 16 lanes,
        # 1.5 at 256, 3.8 at 1024 through the XLA scan) — submitting the
        # CPU-cache-sized 16-block stealing quantum directly would waste
        # ~90% of the chip.  Decoupled from batch_blocks (host staging
        # granularity) per VERDICT r4 #1.
        self.device_batch_blocks = max(self.group_blocks,
                                       params.device_batch_blocks)
        # Staging-claim clamp (round-5 ADVICE #4): (window+1) merged
        # submissions × device_batch_blocks × block_size is host RAM +
        # device HBM held at once — 2 GiB at the defaults.  Clamp the
        # submission width so the bound never exceeds
        # max_device_staging_mib at the CONFIGURED block size (the
        # daemon plumbs config.block_size in; 1 MiB default); the event
        # makes a silently narrower device pipeline attributable.
        blk = max(1, params.block_size)
        cap = max(
            self.group_blocks,
            (params.max_device_staging_mib << 20)
            // ((self.window + 1) * blk),
        )
        if self.device_batch_blocks > cap:
            logger.warning(
                "clamping device_batch_blocks %d -> %d: "
                "(hybrid_window+1)=%d in-flight submissions of %d-byte "
                "blocks would stage %d MiB (> max_device_staging_mib=%d)",
                self.device_batch_blocks, cap, self.window + 1, blk,
                (self.window + 1) * self.device_batch_blocks * blk >> 20,
                params.max_device_staging_mib,
            )
            self.obs.event(
                "staging_clamp", reason="max_device_staging_mib",
                requested=self.device_batch_blocks, clamped=cap,
                window=self.window, block_size=blk,
            )
            self.device_batch_blocks = cap
        # CPU-side merged span while the device is actively stealing;
        # unbounded (whole contiguous segments) when the device is gated
        # or absent — the pass then degenerates to exactly the wide
        # fused CPU codec calls (VERDICT r4 #3: a held gate must cost
        # nothing vs the plain CPU path).
        self.cpu_span_blocks = max(self.group_blocks,
                                   params.hybrid_cpu_span_blocks)
        # link-health probe cache (see _probe_link)
        self._link_rate: Optional[float] = None
        self._link_ts = 0.0
        self._link_failed = False
        self._link_ttl = self._LINK_PROBE_TTL_S
        self._fail_ttl = self._LINK_PROBE_FAIL_TTL_S
        self._probe_buf: Optional[np.ndarray] = None
        self._probe_warmed = False
        self._probe_lock = threading.Lock()
        # the zero-copy device transport (ops/transport.py): armed when
        # the device codec speaks the array-level transport API; the
        # CodecFeeder routes device-side ragged batches through it, and
        # the gate probe measures IT instead of the retired
        # serialize+copy path
        self.transport = None
        # the device-resident block pool behind the transport (built by
        # _arm_transport when budgeted); BlockManager's invalidation
        # hooks and the scrub worker's cycle tick reach it here
        self.pool = None
        self._metrics = metrics
        self._governor_ratio = None
        # accounting (read by bench.py and the admin worker registry)
        self.bytes_cpu = 0
        self.bytes_tpu = 0
        # gate telemetry for the last pass: bench.py records the probe
        # rate and the gate decision next to tpu_frac so a 0.0 frac is
        # attributable (VERDICT r4 #2)
        self.last_link_gibs: Optional[float] = None
        self.last_gate: Optional[str] = None
        # per-stage breakdown of the last successful probe ({stage:
        # seconds}, ISSUE 16): attached to every probe/gate event so a
        # verdict — including a gate-shut one — names WHERE the
        # round-trip went, not just how slow it was
        self._link_stages: Optional[dict] = None
        self._stats_lock = threading.Lock()
        # NOTE: the codec-level gauges (codec_device_attached,
        # codec_link_gibs, codec_tpu_frac) are registered by
        # BlockManager against self.codec — per-instance fn= observers
        # here would pin this instance in the registry forever and go
        # stale on a codec swap (Gauge dedup keeps the FIRST observer).
        if self.tpu is None and build_device:
            if build_device == "async":
                threading.Thread(
                    target=self._build_device_thread,
                    name="codec-hybrid-devinit", daemon=True,
                ).start()
            else:
                self._build_device()
        elif self.tpu is not None:
            self._arm_transport()

    def _arm_transport(self) -> None:
        """Build the DeviceTransport over the attached device codec when
        enabled and the device speaks the array-level transport API
        (scripted test fakes without it keep the legacy ragged
        routing)."""
        if not getattr(self.params, "transport", True) or self.tpu is None:
            return
        from .transport import DeviceTransport

        if not DeviceTransport.supports_device(self.tpu):
            return
        # device-resident block pool (ops/device_pool.py): armed when
        # budgeted and the device speaks the pool API; pool_mib=0 or a
        # pool-less device keeps staging byte-identical to the legacy
        # transport
        from .device_pool import DevicePool

        pool = None
        pool_mib = int(getattr(self.params, "pool_mib", 0))
        if pool_mib > 0 and DevicePool.supports_device(self.tpu):
            pool = DevicePool(
                self.tpu,
                pool_bytes=pool_mib << 20,
                page_bytes=int(getattr(self.params, "pool_page_kib",
                                       256)) << 10,
                prefetch=bool(getattr(self.params, "pool_prefetch",
                                      True)),
                metrics=self._metrics, observer=self.obs)
        self.pool = pool
        tr = DeviceTransport(self.tpu, self.params, fallback=self.cpu,
                             observer=self.obs, metrics=self._metrics,
                             pool=pool)
        tr.governor_ratio = self._governor_ratio
        self.transport = tr  # atomic attach (feeder reads it racily)
        self.obs.event("transport_up", reason=type(self.tpu).__name__,
                       slots=tr.slots,
                       pool_mib=pool_mib if pool is not None else 0)

    def set_governor(self, ratio_fn) -> None:
        """Wire the load governor's background_throttle_ratio into the
        transport's background demotion (model/garage.py); survives a
        late async device attach."""
        self._governor_ratio = ratio_fn
        if self.transport is not None:
            self.transport.governor_ratio = ratio_fn

    def _build_device_thread(self) -> None:
        """Async-attach path: the dedicated devinit thread registers
        with the CPU profiler for its lifetime.  The SYNC path calls
        _build_device directly and keeps its caller's role."""
        from ..utils.cpuprof import register_thread, unregister_thread
        register_thread("device-init")
        try:
            self._build_device()
        finally:
            unregister_thread()

    def _build_device(self) -> None:
        try:
            from .tpu_codec import TpuCodec

            # the device codec SHARES this hybrid's observer: kernel
            # demotions land in the same event ring as gate decisions
            self.tpu = TpuCodec(self.params, observer=self.obs)  # atomic attach
            self.obs.event("device_attach", reason="ok")
            self._arm_transport()
        except Exception as e:
            logger.warning(
                "device codec unavailable; hybrid runs CPU-only",
                exc_info=True,
            )
            self.obs.event("device_attach", reason="failed",
                           error=f"{type(e).__name__}: {e}"[:200])

    def info(self) -> dict:
        d = super().info()
        with self._stats_lock:
            d.update({
                "device_attached": self.tpu is not None,
                "device_backend": (type(self.tpu).__name__
                                   if self.tpu is not None else None),
                "gate": self.last_gate,
                "link_gibs": self.last_link_gibs,
                "link_stages": (dict(self._link_stages)
                                if self._link_stages else None),
                "group_blocks": self.group_blocks,
                "device_batch_blocks": self.device_batch_blocks,
                "window": self.window,
            })
        if self.transport is not None:
            d["transport"] = self.transport.stats()
        if self.pool is not None:
            d["pool"] = self.pool.stats()
        return d

    def close(self) -> None:
        """Drain the device transport (shutdown path; idempotent)."""
        if self.transport is not None:
            self.transport.shutdown()
        if self.pool is not None:
            self.pool.clear()

    def pop_stats(self) -> Tuple[int, int]:
        with self._stats_lock:
            s = (self.bytes_cpu, self.bytes_tpu)
            self.bytes_cpu = self.bytes_tpu = 0
        return s

    def warm(self, nbytes: int) -> None:
        """Pre-compile the device executable for `nbytes`-sized blocks
        without spending link bandwidth (AOT lowering)."""
        if self.tpu is not None and hasattr(self.tpu, "warm_scrub"):
            try:
                # every POWER-OF-TWO lane bucket from the smallest batch
                # (width 1 pads into it) up to device_batch_blocks:
                # shallow-deque and pass-tail merges dispatch at any
                # intermediate bucket, not just the ramp widths — an
                # unwarmed shape means a mid-pass XLA compile (seconds on
                # a remote backend) exactly where warm() was meant to
                # prevent one.  (A doubling ramp seeded from group_blocks
                # skipped buckets when group_blocks was not a power of
                # two — advisor r4.)  Dedupe on the device's own padded
                # batch size so collapsing buckets compile once.
                seen = set()
                w = 1
                while True:
                    key = (self.tpu._batch_size(w)
                           if hasattr(self.tpu, "_batch_size") else w)
                    if key not in seen:
                        seen.add(key)
                        self.tpu.warm_scrub(w, nbytes)
                    if w >= self.device_batch_blocks:
                        break
                    w = min(w * 2, self.device_batch_blocks)
            except Exception:
                logger.warning("device warmup failed", exc_info=True)

    _LINK_PROBE_TTL_S = 15.0
    _LINK_PROBE_FAIL_TTL_S = 2.0
    _LINK_PROBE_TTL_MAX_S = 120.0
    _LINK_PROBE_BYTES = 16 << 20

    def _probe_once(self) -> Tuple[float, bool]:
        """(rate GiB/s, failed?) from one real round-trip.  Transfers a
        16 MiB buffer to the DEVICE CODEC'S device and fetches a scalar
        reduction of it — a device→host fetch of a value that DEPENDS on
        the upload is the only sync some remote backends honor (measured
        here: a tunnel whose one-shot device_put 'completed' at 0.55
        GiB/s delivered 0.02 GiB/s end-to-end)."""
        try:
            import jax
            import jax.numpy as jnp

            # derive the probed device from the device codec (advisor
            # r4: probing jax's DEFAULT device mis-measures a codec
            # living elsewhere, e.g. tests pinning a non-default device)
            dev = None
            karr = getattr(self.tpu, "_K_enc", None)
            if karr is not None:
                try:
                    dev = next(iter(karr.devices()))
                except Exception:
                    dev = None
            if self._probe_buf is None:
                self._probe_buf = np.random.default_rng(0).integers(
                    0, 256, (self._LINK_PROBE_BYTES,), dtype=np.uint8)

            def roundtrip() -> int:
                buf = (jax.device_put(self._probe_buf, dev)
                       if dev is not None else jnp.asarray(self._probe_buf))
                return int(np.asarray(jnp.sum(buf, dtype=jnp.uint32)))

            if not self._probe_warmed:
                # first call compiles the reduction (seconds on a remote
                # backend) — keep that out of the timed region or a
                # healthy link reads as gated for the whole first TTL
                roundtrip()
                self._probe_warmed = True
            t0 = time.monotonic()
            roundtrip()
            dt = time.monotonic() - t0
            return (self._LINK_PROBE_BYTES / dt / 2**30 if dt > 0 else 0.0,
                    False)
        except Exception:
            logger.warning("device link probe failed", exc_info=True)
            return 0.0, True

    def _probe_link(self) -> float:
        """Measured host→device round-trip rate (GiB/s), cached.

        With a transport armed, the probe measures the NEW path — one
        ragged submission through stage→submit→collect
        (DeviceTransport.probe_link) — not the retired serialize+copy
        round-trip, so the gate decides on the rate the feeder's
        batches will actually see.  A device codec's own `probe_link`
        hook still wins (the synthetic-link backend keeps gate
        decisions deterministic); real codecs are marked by warm_scrub;
        anything else (scripted test fakes) is treated as healthy.

        Cache policy: a FAILED probe is retried once immediately and,
        if still failing, re-probed on a doubling ladder
        (_LINK_PROBE_FAIL_TTL_S → _LINK_PROBE_TTL_MAX_S) — a
        durably-dead backend isn't hammered every pass.  A probe that
        SUCCEEDS — even below the gate threshold — caches for exactly
        _LINK_PROBE_TTL_S and resets the failure ladder, so a
        once-failed or once-slow link that recovers is re-probed (and
        the gate re-opened) within one healthy TTL.  The old policy
        doubled the TTL on below-threshold measurements too, which left
        a recovered link gated for up to _LINK_PROBE_TTL_MAX_S.  The
        flat healthy cadence costs nothing when probing is cheap (a
        transport probe or a device hook); only the LEGACY
        _probe_once path — a full 16 MiB round-trip over a possibly
        metered link — keeps the below-threshold backoff ladder."""
        hook = getattr(self.tpu, "probe_link", None)
        hook_owner = self.tpu if hook is not None else None
        tr = self.transport
        if hook is None and tr is not None and tr.alive:
            hook = tr.probe_link
            hook_owner = tr
        legacy = hook is None
        if legacy and not hasattr(self.tpu, "warm_scrub"):
            return float("inf")
        with self._probe_lock:
            now = time.monotonic()
            if self._link_rate is not None:
                ttl = (self._fail_ttl if self._link_failed
                       else self._link_ttl)
                if now - self._link_ts < ttl:
                    return self._link_rate
            if hook is not None:
                try:
                    rate, failed = float(hook(self._LINK_PROBE_BYTES)), False
                    stages = getattr(hook_owner, "last_probe_stages",
                                     None)
                    if stages:
                        self._link_stages = dict(stages)
                except Exception:
                    logger.warning("probe_link hook failed", exc_info=True)
                    rate, failed = 0.0, True
                    self._link_stages = None
            else:
                rate, failed = self._probe_once()
                if failed:
                    rate, failed = self._probe_once()
            if failed:
                self._fail_ttl = min(self._fail_ttl * 2,
                                     self._LINK_PROBE_TTL_MAX_S)
            elif legacy and rate < self.params.hybrid_min_link_gibs:
                # the probe itself spends metered link quota here:
                # back a below-threshold verdict off as before
                self._fail_ttl = self._LINK_PROBE_FAIL_TTL_S
                self._link_ttl = min(self._link_ttl * 2,
                                     self._LINK_PROBE_TTL_MAX_S)
            else:
                self._fail_ttl = self._LINK_PROBE_FAIL_TTL_S
                self._link_ttl = self._LINK_PROBE_TTL_S
            self._link_failed = failed
            self._link_rate, self._link_ts = rate, now
            return rate

    def probe_stages(self) -> Optional[dict]:
        """{stage: seconds} of the last successful probe (None when no
        decomposed probe has run — the legacy serialize+copy probe and
        scripted fakes don't stamp stages).  A CACHED verdict reuses the
        breakdown of the measurement that produced it."""
        with self._probe_lock:
            return dict(self._link_stages) if self._link_stages else None

    @staticmethod
    def _stage_detail(stages: Optional[dict]) -> dict:
        """Event-detail kwargs for a probe breakdown: the {stage:
        seconds} map plus its dominant stage (empty when unknown)."""
        if not stages:
            return {}
        from .link_profiler import dominant_stage

        return {"stages": {k: round(v, 6) for k, v in stages.items()},
                "dominant_stage": dominant_stage(stages)}

    def _ramp_widths(self) -> List[int]:
        """Device submission widths the feeder ramps through: start small
        (claims are cheap to hedge while the link's latency is unproven),
        double per successful collect up to device_batch_blocks."""
        w = max(self.group_blocks, min(64, self.device_batch_blocks))
        out = [w]
        while w < self.device_batch_blocks:
            w = min(w * 2, self.device_batch_blocks)
            out.append(w)
        return out

    # --- the hybrid engine ---

    def _run_groups(self, blocks: Sequence[bytes], hashes: Sequence[Hash],
                    compute_parity: bool, fetch_parity: bool,
                    cuts: Optional[Sequence[int]] = None):
        """Split into k-aligned groups, process them on both backends via a
        stealing deque, return per-group (ok, parity|None) in order.

        compute_parity: whether the CPU side runs the RS encode at all (the
        device kernel is fused and always encodes — one executable for both
        the verify-only and scrub paths).  fetch_parity: whether device-side
        parity is copied back to host RAM (skipping the copy spares
        device→host bandwidth for callers that discard parity).  cuts:
        extra boundaries (block indices) no group may straddle — scrub_many
        passes its batch edges so no RS codeword ever mixes two batches."""
        n = len(blocks)
        g = self.group_blocks
        starts: List[int] = []
        edges = sorted(set([0, n] + list(cuts or [])))
        for lo, hi in zip(edges, edges[1:]):
            starts.extend(range(lo, hi, g))
        groups = [
            (i, blocks[i:j], hashes[i:j])
            for i, j in zip(starts, starts[1:] + [n])
        ]
        if self.params.rs_data == 0:
            compute_parity = False  # replication-only config: verify-only
            fetch_parity = False
        results: List[Optional[Tuple[np.ndarray, Optional[np.ndarray]]]] = (
            [None] * len(groups)
        )
        # rs_data == 0 routes to CPU: the device path is the fused
        # verify+encode executable, which needs the RS matrix
        use_device = (self.tpu is not None and len(groups) > 1
                      and self.params.rs_data > 0)
        with self._stats_lock:
            self.last_gate = None if use_device else (
                "no-device" if self.tpu is None else "cpu-only")
            if not use_device:
                self.last_link_gibs = None
        if not use_device:
            self.obs.event("gate", reason=self.last_gate,
                           groups=len(groups))

        dq = collections.deque(range(len(groups)))
        lock = threading.Lock()
        done = threading.Event()
        # set when the feeder will take no (more) work — probe gate held,
        # feeder failed/ceded, or feeder finished; the CPU side then
        # merges UNBOUNDED spans (one fused call per contiguous run),
        # making a gated pass cost the same as the plain CPU codec
        gate_hold = threading.Event()
        if not use_device:
            gate_hold.set()
        remaining = [len(groups)]

        def set_result(gi, val, side, nbytes) -> bool:
            """First writer wins (the tail is hedged: CPU may redo a group
            the device still has in flight).  Byte accounting happens under
            the same lock as the winning write, so pop_stats() called right
            after the pass always sees cpu+tpu == total."""
            with lock:
                if results[gi] is not None:
                    return False
                results[gi] = val
                with self._stats_lock:
                    if side == "cpu":
                        self.bytes_cpu += nbytes
                    else:
                        self.bytes_tpu += nbytes
                self.obs.add_bytes(side, nbytes)
                remaining[0] -= 1
                if remaining[0] == 0:
                    done.set()
                return True

        cpu_t0 = time.monotonic()
        cpu_bytes_this_call = [0]

        k_align = max(1, self.params.rs_data)

        def feeder():
            # Device side: pop from the RIGHT and MERGE consecutive
            # groups into one wide submission (device_batch_blocks lanes
            # — the hash kernel's rate scales with lane count).  Because
            # only the ends of the deque are ever popped, the remaining
            # indices form one contiguous range, so right-side pops are
            # strictly descending adjacent groups; prepending each keeps
            # the merged list ascending and block-contiguous.  Parity
            # grouping is preserved iff every merged group except the
            # LAST is k-aligned (group starts then stay multiples of k),
            # so a non-aligned group — only ever a batch-segment tail —
            # is carried over to START the next merged list, where it
            # again sits last.  Keep ≤ window submissions in flight; sync
            # oldest before submitting past the window.
            inflight: collections.deque = collections.deque()
            ramp = self._ramp_widths()
            ramp_i = 0
            carry: Optional[int] = None
            try:
                # Gate on measured link health BEFORE claiming any work:
                # a sub-threshold link costs more in staging + tail-hedge
                # redo than it contributes (and learning that from the
                # first real collect can take tens of seconds).
                with self.obs.stage("probe", "tpu"):
                    rate = self._probe_link()
                stage_detail = self._stage_detail(self.probe_stages())
                with self._stats_lock:
                    self.last_link_gibs = (
                        None if rate == float("inf") else round(rate, 4))
                self.obs.event(
                    "probe",
                    reason="unmetered" if rate == float("inf") else "ok",
                    gibs=None if rate == float("inf") else round(rate, 4),
                    threshold=self.params.hybrid_min_link_gibs,
                    **stage_detail)
                if rate < self.params.hybrid_min_link_gibs:
                    with self._stats_lock:
                        self.last_gate = "hold"
                    self.obs.event(
                        "gate", reason="hold", gibs=round(rate, 4),
                        threshold=self.params.hybrid_min_link_gibs,
                        **stage_detail)
                    logger.info(
                        "hybrid feeder: link probe %.3f GiB/s below "
                        "threshold %.3f — CPU-only this pass "
                        "(dominant stage: %s)",
                        rate, self.params.hybrid_min_link_gibs,
                        stage_detail.get("dominant_stage", "unknown"))
                    return
                with self._stats_lock:
                    self.last_gate = "open"
                self.obs.event(
                    "gate", reason="open",
                    gibs=None if rate == float("inf") else round(rate, 4),
                    **stage_detail)
                while True:
                    # width ramp: early submissions are small (cheap for
                    # the tail hedge to redo if the link turns out slow);
                    # each successful collect doubles the width up to
                    # device_batch_blocks, where the device hash kernel
                    # has full lane utilization
                    target = ramp[min(ramp_i, len(ramp) - 1)]
                    merged: List[int] = []
                    nblk = 0
                    if carry is not None:
                        merged = [carry]
                        nblk = len(groups[carry][1])
                        carry = None
                    # steal at most HALF the remaining groups per
                    # submission (bounded by the device batch width):
                    # merging must not let the feeder claim the whole
                    # deque in one gulp — the CPU side would sit idle
                    # while the device serializes everything
                    t_claim = time.perf_counter()
                    with lock:
                        take_n = max(1, (len(dq) + 1) // 2)
                    while nblk < target and take_n > 0:
                        with lock:
                            if not dq:
                                break
                            gi = dq.pop()
                        take_n -= 1
                        cgi = len(groups[gi][1])
                        if merged and (cgi % k_align != 0
                                       or nblk + cgi > target):
                            carry = gi
                            break
                        merged.insert(0, gi)
                        nblk += cgi
                    self.obs.observe_stage(
                        "feeder_wait", "tpu",
                        time.perf_counter() - t_claim)
                    if not merged:
                        break
                    with self.obs.stage("host_staging", "tpu"):
                        gb: List[bytes] = []
                        gh: List[Hash] = []
                        for gi in merged:
                            _idx, b, h = groups[gi]
                            gb.extend(b)
                            gh.extend(h)
                    sub_bytes = sum(len(x) for x in gb)
                    try:
                        # whole-submit envelope; an instrumented TpuCodec
                        # additionally refines it into host_staging /
                        # h2d_transfer / kernel_dispatch internally
                        with self.obs.stage("device_submit", "tpu"):
                            ok_dev, parity_dev, _cnt = self.tpu.scrub_submit(
                                gb, gh)
                        variant = getattr(
                            self.tpu, "last_submit_variant", None)
                    except BaseException:
                        # none of `merged` was submitted: hand the whole
                        # claim back — carry (popped after merged's
                        # lowest index, so it is the SMALLEST outstanding
                        # index) must go back FIRST to keep the deque's
                        # contiguous-ascending invariant (advisor r4)
                        with lock:
                            if carry is not None:
                                dq.append(carry)
                            carry = None
                            dq.extend(merged)
                        raise
                    inflight.append(
                        (merged, sub_bytes, ok_dev, parity_dev, variant)
                    )
                    if len(inflight) > self.window:
                        t_c = time.monotonic()
                        item = inflight.popleft()
                        self._tpu_collect(item, groups, set_result,
                                          fetch_parity)
                        ramp_i += 1
                        new_target = ramp[min(ramp_i, len(ramp) - 1)]
                        if new_target != target:
                            self.obs.event("ramp", reason="widen",
                                           blocks=new_target)
                        # Give up on a pathologically slow link: feeding it
                        # costs host CPU (transfer staging ≈ one memcpy per
                        # group, a few % of a CPU verify) that the verifier
                        # could spend directly.  Staging costs ~3% of a
                        # CPU group, so ANY device rate above ~5% of the
                        # CPU's is net-positive — only below that does
                        # ceding to the CPU win.  (A 2× threshold here once
                        # dropped a link running at 18% of CPU rate, wasting
                        # its entire contribution.)
                        collect_dt = time.monotonic() - t_c
                        cpu_dt = time.monotonic() - cpu_t0
                        cpu_rate = (cpu_bytes_this_call[0] / cpu_dt
                                    if cpu_dt > 0 else 0.0)
                        item_bytes = item[1]
                        if cpu_rate > 0 and \
                                collect_dt > 20 * item_bytes / cpu_rate:
                            logger.info(
                                "hybrid feeder: link too slow (%.0f KiB/s), "
                                "ceding remaining groups to CPU",
                                item_bytes / max(collect_dt, 1e-9) / 1024,
                            )
                            self.obs.event(
                                "cede", reason="slow_collect",
                                kibs=round(item_bytes
                                           / max(collect_dt, 1e-9) / 1024),
                            )
                            break
                while inflight:
                    self._tpu_collect(inflight.popleft(), groups,
                                      set_result, fetch_parity)
            except BaseException as e:
                # Device failure must never fail a scrub: groups without a
                # result are hedge-verified on CPU below.
                logger.warning(
                    "device feeder failed; CPU absorbs its groups: %r", e
                )
                self.obs.event("feeder_error", reason=type(e).__name__,
                               error=f"{e}"[:200])
            finally:
                # A popped-but-unsubmitted carry group must not strand:
                # on ANY exit (slow-link cede, submit failure, normal end
                # with an over-target carry) hand it back to the deque so
                # the CPU loop — not the tail hedge's grace timeout —
                # picks it up.  gate_hold tells the CPU side the feeder
                # will steal no more: remaining spans go unbounded.
                if carry is not None:
                    with lock:
                        dq.append(carry)
                gate_hold.set()

        def feeder_thread():
            from ..utils.cpuprof import register_thread, unregister_thread
            register_thread("hybrid-feeder")
            try:
                feeder()
            finally:
                unregister_thread()

        if use_device:
            t = threading.Thread(target=feeder_thread,
                                 name="codec-hybrid-feeder", daemon=True)
            _LIVE_FEEDERS.append(t)
            while len(_LIVE_FEEDERS) > 8:  # drop long-finished entries
                old = _LIVE_FEEDERS.popleft()
                if old.is_alive():
                    _LIVE_FEEDERS.append(old)
                    break
            t.start()

        # CPU side: pop contiguous runs of groups from the LEFT and
        # process each run with ONE wide fused call (native multi-buffer
        # hash + pointer-gather RS amortize per-call overhead).  While
        # the device may still steal, spans are bounded at
        # cpu_span_blocks so stealing stays balanced; once the gate
        # holds (or there is no device) spans are unbounded and the pass
        # is byte-identical in call pattern to the plain CPU codec.
        while True:
            target = (self.cpu_span_blocks
                      if not gate_hold.is_set() else None)
            with lock:
                if not dq:
                    break
                span = [dq.popleft()]
                nblk = len(groups[span[-1]][1])
                while dq and (target is None or nblk < target):
                    prev_idx, prev_b, _ph = groups[span[-1]]
                    # a non-k-aligned group (a segment tail) must stay
                    # LAST in any merged run so parity-row starts remain
                    # multiples of k; block-index contiguity is a
                    # defensive invariant check
                    if (len(prev_b) % k_align != 0 or
                            groups[dq[0]][0] != prev_idx + len(prev_b)):
                        break
                    span.append(dq.popleft())
                    nblk += len(groups[span[-1]][1])
            gb: List[bytes] = []
            gh: List[Hash] = []
            for gi in span:
                gb.extend(groups[gi][1])
                gh.extend(groups[gi][2])
            with self.obs.stage("cpu_span", "cpu"):
                ok = self.cpu.batch_verify(gb, gh)
                parity_arr = None
                if compute_parity:
                    parity_arr = self.cpu.rs_encode_blocks(gb)
            self._split_merged(
                span, groups, ok,
                parity_arr if fetch_parity else None,
                set_result, "cpu")
            cpu_bytes_this_call[0] += sum(len(b) for b in gb)

        # Tail: the device still holds in-flight groups.  Waiting for a
        # metered/stalled link can dwarf the whole pass, so hedge: give the
        # device a quarter of the time the CPU would need to redo the
        # stragglers, then recompute them on CPU — first writer wins, the
        # device's late results are discarded.  The feeder thread is NOT
        # joined: it syncs its remaining transfers in the background.
        with lock:
            pending = [gi for gi, r in enumerate(results) if r is None]
        if pending:
            cpu_dt = time.monotonic() - cpu_t0
            cpu_rate = cpu_bytes_this_call[0] / cpu_dt if cpu_dt > 0 else 0.0
            pend_bytes = sum(
                len(b) for gi in pending for b in groups[gi][1]
            )
            grace = 0.25 * pend_bytes / cpu_rate if cpu_rate > 0 else 1.0
            with self.obs.stage("tail_wait", "tpu"):
                done.wait(timeout=grace)
            hedged = 0
            for gi in pending:
                with lock:
                    if results[gi] is not None:
                        continue
                _idx, gb, gh = groups[gi]
                with self.obs.stage("hedge", "cpu"):
                    val = self._cpu_group(gb, gh, compute_parity,
                                          fetch_parity)
                if set_result(gi, val, "cpu", sum(len(b) for b in gb)):
                    hedged += 1
            if hedged:
                # the hedge redoing device-claimed groups is exactly the
                # kind of silent work the round-5 heal non-repro hid —
                # make it an attributable event
                self.obs.event("tail_hedge", reason="grace_expired",
                               groups=hedged)
            done.wait()  # every slot now has a writer; returns immediately
        return results

    def _cpu_group(self, gb, gh, compute_parity, fetch_parity):
        """Verify (+ optionally encode) one group on the CPU codec.  Byte
        accounting is the caller's job (only winning writes count)."""
        ok = self.cpu.batch_verify(gb, gh)
        parity = None
        if compute_parity:
            parity = self.cpu.rs_encode_blocks(gb)
            if not fetch_parity:
                parity = None
        return ok, parity

    def _split_merged(self, merged, groups, ok_arr, parity_arr,
                      set_result, side):
        """Split one merged run's results back into per-group results —
        shared by the device collect and the CPU span path so both sides
        produce identical shapes.  Group starts within the run are
        multiples of k (every merged group but the last is k-aligned),
        so each group's parity rows are exactly [start//k, start//k +
        ceil(len/k)), trimmed to the group's own max block length (pad
        rows/columns are zero blocks → zero parity, GF-linear).
        parity_arr None = caller discards parity."""
        k = max(1, self.params.rs_data)
        off = 0
        for gi in merged:
            _idx, b, _h = groups[gi]
            ln = len(b)
            parity = None
            if parity_arr is not None:
                ml = max(len(x) for x in b)
                r0 = off // k
                nrows = (ln + k - 1) // k
                parity = parity_arr[r0:r0 + nrows, :, :ml]
            set_result(gi, (ok_arr[off:off + ln], parity), side,
                       sum(len(x) for x in b))
            off += ln

    def _tpu_collect(self, item, groups, set_result, fetch_parity):
        """Sync one merged device submission and split it per-group.

        The np.asarray here is where an async backend's kernel failures
        actually surface — long after scrub_submit returned clean — so
        the outcome is reported back to the device codec's demotion
        latch (note_sync_failure/_success, round-5 ADVICE #1)."""
        merged, _sub_bytes, ok_dev, parity_dev, variant = item
        try:
            with self.obs.stage("sync_collect", "tpu"):
                ok = np.asarray(ok_dev)
                parity_np = np.asarray(parity_dev) if fetch_parity else None
        except BaseException as e:
            self.obs.event("sync_failure", reason=type(e).__name__,
                           error=f"{e}"[:200])
            note = getattr(self.tpu, "note_sync_failure", None)
            if note is not None:
                try:
                    note(e, variant)
                except Exception:
                    logger.warning("note_sync_failure hook failed",
                                   exc_info=True)
            raise
        note = getattr(self.tpu, "note_sync_success", None)
        if note is not None:
            try:
                note(variant)
            except Exception:
                logger.warning("note_sync_success hook failed",
                               exc_info=True)
        self._split_merged(merged, groups, ok, parity_np, set_result,
                           "tpu")

    # --- ragged batch routing (the CodecFeeder's foreground path) ---

    def ragged_side(self) -> str:
        """Route for feeder ragged batches: the device only when it is
        attached AND the link probe's CACHED verdict clears the gate.
        The foreground path must never pay a cold 16 MiB probe
        round-trip — an unprobed or stale link routes to the CPU floor
        and the next scrub pass's probe re-opens the gate.  An
        unmetered backend (no probe_link hook, no warm_scrub marker —
        scripted fakes, local device) is treated as healthy, exactly
        as _probe_link does; that verdict never enters the cache, so
        it is re-derived here rather than read from _link_rate."""
        if self.tpu is None:
            return "cpu"
        if self.transport is not None and not self.transport.alive:
            # the transport latched down (repeated device failures or
            # drain): the device path is gone for ragged batches even
            # if the cached link verdict was healthy
            return "cpu"
        if (getattr(self.tpu, "probe_link", None) is None
                and not hasattr(self.tpu, "warm_scrub")):
            return "tpu"
        with self._probe_lock:
            rate, ts, failed = self._link_rate, self._link_ts, \
                self._link_failed
        if rate is None:
            return "cpu"
        if rate == float("inf"):
            return "tpu"
        if failed or time.monotonic() - ts > self._LINK_PROBE_TTL_MAX_S:
            return "cpu"
        return ("tpu" if rate >= self.params.hybrid_min_link_gibs
                else "cpu")

    def _ragged_target(self) -> BlockCodec:
        return self.tpu if self.ragged_side() == "tpu" else self.cpu

    def refresh_gate(self) -> None:
        """Run the (TTL-cached) link probe so the cached gate verdict
        exists/stays fresh.  Called by the feeder before dispatching a
        BACKGROUND batch to a still-closed gate: scrub is where the
        gate historically got its measurements (the stealing feeder
        probed every pass), and with scrub riding the feeder queue the
        probe must ride with it — background work can afford it,
        foreground never pays it cold."""
        if self.tpu is not None:
            try:
                self._probe_link()
            except Exception:  # noqa: BLE001 — a dead probe = gate stays shut
                logger.warning("gate refresh probe failed", exc_info=True)

    def scrub_ragged(self, items):
        """Feeder `scrub` kind when no transport took the batch: the CPU
        floor runs the fused serial path; a device route without the
        array API (scripted fakes) degrades to one hybrid-engine pass
        per item."""
        if self.ragged_side() == "tpu":
            t = self.tpu
            if hasattr(t, "scrub_ragged"):
                return t.scrub_ragged(items)
            return [self.scrub_encode_batch(b, h, fp) for b, h, fp in items]
        return self.cpu.scrub_ragged(items)

    def hash_ragged(self, groups):
        return self._ragged_target().hash_ragged(groups)

    def rs_encode_ragged(self, groups):
        return self._ragged_target().rs_encode_ragged(groups)

    def rs_reconstruct_ragged(self, items):
        return self._ragged_target().rs_reconstruct_ragged(items)

    # --- BlockCodec interface ---

    def batch_hash(self, blocks: Sequence[bytes]) -> List[Hash]:
        # hashing without expectations: no corruption checks to fuse, so the
        # CPU pool is already optimal for small batches; large batches split.
        return self.cpu.batch_hash(blocks)

    def batch_verify(self, blocks: Sequence[bytes], hashes: Sequence[Hash]) -> np.ndarray:
        if len(blocks) != len(hashes):
            raise ValueError(f"{len(blocks)} blocks vs {len(hashes)} hashes")
        if not blocks:
            return np.zeros((0,), dtype=bool)
        results = self._run_groups(blocks, hashes, compute_parity=False,
                                   fetch_parity=False)
        return np.concatenate([r[0] for r in results])

    @staticmethod
    def _assemble_parity(parities, maxlen: int) -> Optional[np.ndarray]:
        """Concatenate per-group parity into the canonical (ceil(B/k), m,
        maxlen) array (contract of scrub_encode_batch, shared with
        TpuCodec).  Groups are k-aligned and consecutive, so their codeword
        rows concatenate exactly as a whole-batch reshape would; shorter
        groups are zero-padded to maxlen columns (zero data → zero parity,
        GF-linear)."""
        rows = []
        for p in parities:
            if p is None:
                return None
            if p.shape[-1] < maxlen:
                p = np.pad(p, [(0, 0), (0, 0), (0, maxlen - p.shape[-1])])
            rows.append(p)
        return np.concatenate(rows, axis=0)

    def scrub_encode_batch(self, blocks: Sequence[bytes], hashes: Sequence[Hash],
                           fetch_parity: bool = True):
        """Fused verify + RS(k,m) parity across both backends.

        Same contract as TpuCodec.scrub_encode_batch: (ok (B,), parity
        (ceil(B/k), m, maxlen) | None).  With fetch_parity=False (or
        rs_data=0), parity is None — device-side parity stays on the device
        (callers that discard parity avoid paying device→host bandwidth);
        CPU-side parity is still computed, the work is identical.
        """
        if not blocks:
            return np.zeros((0,), dtype=bool), None
        results = self._run_groups(blocks, hashes, compute_parity=True,
                                   fetch_parity=fetch_parity)
        ok = np.concatenate([r[0] for r in results])
        parity = None
        if fetch_parity and self.params.rs_data > 0:
            parity = self._assemble_parity(
                [r[1] for r in results], max(len(b) for b in blocks)
            )
        return ok, parity

    def scrub_many(self, batches, fetch_parity: bool = False):
        """Fused verify+encode over MANY batches through ONE stealing deque.

        batches: sequence of (blocks, hashes) pairs (the scrub worker's
        read-ahead).  Processing all batches in one pass amortizes the
        device pipeline across batch boundaries — there is a single hedged
        tail for the whole stream instead of one per batch, which matters
        when the device link carries seconds of in-flight data.  Returns a
        list of (ok, parity|None) per input batch, parity in the canonical
        scrub_encode_batch shape computed from that batch's blocks only.
        """
        all_blocks: List[bytes] = []
        all_hashes: List[Hash] = []
        counts = []
        for blocks, hashes in batches:
            if len(blocks) != len(hashes):
                raise ValueError(f"{len(blocks)} blocks vs {len(hashes)} hashes")
            all_blocks.extend(blocks)
            all_hashes.extend(hashes)
            counts.append(len(blocks))
        if not all_blocks:
            return [(np.zeros((0,), dtype=bool), None) for _ in counts]
        # batch edges are hard cuts: no group (= RS codeword span) straddles
        # two batches, so each batch's parity is computed from its own
        # blocks only
        edges = list(np.cumsum(counts)[:-1])
        results = self._run_groups(all_blocks, all_hashes,
                                   compute_parity=True,
                                   fetch_parity=fetch_parity,
                                   cuts=[int(e) for e in edges])
        ok = np.concatenate([r[0] for r in results])
        out = []
        pos = 0
        gi = 0
        g = self.group_blocks
        for cnt in counts:
            parity = None
            ngroups = (cnt + g - 1) // g
            if fetch_parity and cnt and self.params.rs_data > 0:
                parity = self._assemble_parity(
                    [results[i][1] for i in range(gi, gi + ngroups)],
                    max(len(b) for b in all_blocks[pos:pos + cnt]),
                )
            gi += ngroups
            out.append((ok[pos:pos + cnt], parity))
            pos += cnt
        return out

    def verify_one(self, block: bytes, hash: Hash) -> bool:
        return self.cpu.verify_one(block, hash)

    def rs_encode(self, data: np.ndarray) -> np.ndarray:
        return self.cpu.rs_encode(data)

    def rs_reconstruct(self, shards: np.ndarray, present: Sequence[int],
                       rows: Optional[Sequence[int]] = None) -> np.ndarray:
        return self.cpu.rs_reconstruct(shards, present, rows)
