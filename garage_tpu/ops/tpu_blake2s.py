"""BLAKE2s-256 on TPU as a batched JAX computation.

The reference verifies block integrity with a sequential per-block blake2
hash on CPU (ref src/block/block.rs:66-78, src/util/data.rs:117).  BLAKE2 is
inherently sequential *within* a block (each 64-byte chunk's compression
feeds the next), so the TPU axis of parallelism is *across* blocks.

Layout is LANE-MAJOR: every one of the 16 state words is a (B,) uint32
vector with the batch on the minor (128-lane) dimension, and the 10 rounds
× 8 G quarter-rounds are fully unrolled with the SIGMA message schedule
resolved at trace time — zero gathers, zero rolls, pure uint32
add/xor/shift VPU ops.  (The first version kept state as (B, 4) row
vectors: minor dim 4 wastes 124 of 128 VPU lanes and the per-round SIGMA
gathers dominate; lane-major is ~an order of magnitude faster.)

All arithmetic is uint32 — native VPU ops; this is why the framework's
default block hash is BLAKE2s (32-bit) rather than the reference's blake2b
(64-bit, which TPUs emulate slowly).

Exactly RFC 7693 (sequential mode, digest 32 B, no key); verified
bit-identical to hashlib.blake2s in tests/test_codec_equivalence.py.
Variable-length lanes supported via per-lane byte lengths: lanes whose
message ended stop updating state (masked select), and the final-chunk flag
and byte counter are computed per lane.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

IV = np.array([
    0x6A09E667, 0xBB67AE85, 0x3C6EF372, 0xA54FF53A,
    0x510E527F, 0x9B05688C, 0x1F83D9AB, 0x5BE0CD19,
], dtype=np.uint32)

SIGMA = np.array([
    [0, 1, 2, 3, 4, 5, 6, 7, 8, 9, 10, 11, 12, 13, 14, 15],
    [14, 10, 4, 8, 9, 15, 13, 6, 1, 12, 0, 2, 11, 7, 5, 3],
    [11, 8, 12, 0, 5, 2, 15, 13, 10, 14, 3, 6, 7, 1, 9, 4],
    [7, 9, 3, 1, 13, 12, 11, 14, 2, 6, 5, 10, 4, 0, 15, 8],
    [9, 0, 5, 7, 2, 4, 10, 15, 14, 1, 11, 12, 6, 8, 3, 13],
    [2, 12, 6, 10, 0, 11, 8, 3, 4, 13, 7, 5, 15, 14, 1, 9],
    [12, 5, 1, 15, 14, 13, 4, 10, 0, 7, 6, 3, 9, 2, 8, 11],
    [13, 11, 7, 14, 12, 1, 3, 9, 5, 0, 15, 4, 8, 6, 2, 10],
    [6, 15, 14, 9, 11, 3, 0, 8, 12, 2, 13, 7, 1, 4, 10, 5],
    [10, 2, 8, 4, 7, 6, 1, 5, 15, 11, 9, 14, 3, 12, 13, 0],
], dtype=np.int32)

# the 8 G applications per round: (state indices a,b,c,d)
_G_IDX = [
    (0, 4, 8, 12), (1, 5, 9, 13), (2, 6, 10, 14), (3, 7, 11, 15),
    (0, 5, 10, 15), (1, 6, 11, 12), (2, 7, 8, 13), (3, 4, 9, 14),
]

# h[0] ^= 0x01010000 ^ digest_len  (param block: fanout=1, depth=1, len=32)
H0 = IV.copy()
H0[0] ^= 0x01010020


def _rotr(x: jax.Array, n: int) -> jax.Array:
    return (x >> np.uint32(n)) | (x << np.uint32(32 - n))


def compress(h: jax.Array, m: jax.Array, t: jax.Array, f: jax.Array) -> jax.Array:
    """One BLAKE2s compression, lane-major.

    h (8, B) uint32 state; m (16, B) uint32 message words (LE);
    t (B,) uint32 low byte counter (messages < 4 GiB so t_hi = 0);
    f (B,) bool final-chunk flag.  Returns the new (8, B) state.
    """
    hw = [h[i] for i in range(8)]
    mw = [m[i] for i in range(16)]
    iv = [jnp.uint32(x) for x in IV]
    v = hw + [
        jnp.broadcast_to(iv[0], t.shape),
        jnp.broadcast_to(iv[1], t.shape),
        jnp.broadcast_to(iv[2], t.shape),
        jnp.broadcast_to(iv[3], t.shape),
        iv[4] ^ t,
        jnp.broadcast_to(iv[5], t.shape),
        iv[6] ^ jnp.where(f, jnp.uint32(0xFFFFFFFF), jnp.uint32(0)),
        jnp.broadcast_to(iv[7], t.shape),
    ]
    for r in range(10):
        s = SIGMA[r]
        for g, (ia, ib, ic, id_) in enumerate(_G_IDX):
            x, y = mw[s[2 * g]], mw[s[2 * g + 1]]
            a, b, c, d = v[ia], v[ib], v[ic], v[id_]
            a = a + b + x
            d = _rotr(d ^ a, 16)
            c = c + d
            b = _rotr(b ^ c, 12)
            a = a + b + y
            d = _rotr(d ^ a, 8)
            c = c + d
            b = _rotr(b ^ c, 7)
            v[ia], v[ib], v[ic], v[id_] = a, b, c, d
    return jnp.stack([hw[i] ^ v[i] ^ v[i + 8] for i in range(8)])


def compress_rolled(h: jax.Array, m: jax.Array, t: jax.Array, f: jax.Array) -> jax.Array:
    """Same compression with the 10 rounds as a lax.scan — ~10× smaller
    compiled body.  Used on CPU (tests, 1-core CI boxes) where XLA compile
    time of the fully unrolled body is prohibitive; bit-identical to
    `compress` (asserted in tests/test_codec_equivalence.py)."""
    iv = jnp.asarray(IV)
    bsz = t.shape[0]
    v = jnp.concatenate([
        h,
        jnp.broadcast_to(iv[0:4, None], (4, bsz)),
        jnp.stack([
            iv[4] ^ t,
            jnp.broadcast_to(iv[5], t.shape),
            iv[6] ^ jnp.where(f, jnp.uint32(0xFFFFFFFF), jnp.uint32(0)),
            jnp.broadcast_to(iv[7], t.shape),
        ]),
    ])
    sigma = jnp.asarray(SIGMA)

    def round_body(v, s):
        mp = jnp.take(m, s, axis=0)  # (16, B) message words in round order
        vw = [v[i] for i in range(16)]
        for g, (ia, ib, ic, id_) in enumerate(_G_IDX):
            x, y = mp[2 * g], mp[2 * g + 1]
            a, b, c, d = vw[ia], vw[ib], vw[ic], vw[id_]
            a = a + b + x
            d = _rotr(d ^ a, 16)
            c = c + d
            b = _rotr(b ^ c, 12)
            a = a + b + y
            d = _rotr(d ^ a, 8)
            c = c + d
            b = _rotr(b ^ c, 7)
            vw[ia], vw[ib], vw[ic], vw[id_] = a, b, c, d
        return jnp.stack(vw), None

    v, _ = jax.lax.scan(round_body, v, sigma)
    return h ^ v[0:8] ^ v[8:16]


def bytes_to_words(data_u8: jax.Array) -> jax.Array:
    """uint8 (..., 4n) → uint32 (..., n), little-endian.

    Uses bitcast_convert_type (a relayout, no arithmetic): measured 33
    vs 24 GiB/s for the arithmetic shift/or formulation on v5e, and the
    byte-pack feeds every hash/GF dispatch so it is on the hot path.
    Byte order is the platform's; TPU and x86 are both little-endian,
    asserted against the arithmetic form in
    tests/test_codec_equivalence.py (a hypothetical BE platform would
    flip this flag)."""
    if _BITCAST_PACK:
        return jax.lax.bitcast_convert_type(
            data_u8.reshape(data_u8.shape[:-1] + (-1, 4)), jnp.uint32)
    b = data_u8.astype(jnp.uint32).reshape(data_u8.shape[:-1] + (-1, 4))
    return b[..., 0] | (b[..., 1] << 8) | (b[..., 2] << 16) | (b[..., 3] << 24)


_BITCAST_PACK = True


# Process-wide override for the unroll choice (None = auto by backend).
# dryrun_multichip sets this to False: its virtual CPU mesh must compile
# the small rolled body even when the axon TPU platform won the
# default-backend slot (the r01 dryrun timed out compiling the ~1100-
# primitive unrolled body through the slow tunnel).
_UNROLL_OVERRIDE: bool | None = None


def set_unroll_override(value: bool | None) -> None:
    global _UNROLL_OVERRIDE
    _UNROLL_OVERRIDE = value


def _default_unroll() -> bool:
    """Full round unroll on TPU (no gathers, fastest); rolled rounds on
    CPU, where the ~1100-primitive unrolled scan body makes XLA's 1-core
    compile pathologically slow."""
    if _UNROLL_OVERRIDE is not None:
        return _UNROLL_OVERRIDE
    return jax.default_backend() != "cpu"


def blake2s_batch(
    data_u8: jax.Array, lengths: jax.Array, unroll: bool = None
) -> jax.Array:
    """Hash B zero-padded messages.

    data_u8 (B, C*64) uint8 — messages padded with zeros to a common
    multiple-of-64 length (C ≥ 1 chunks); lengths (B,) int32 true byte
    counts.  Returns (B, 8) uint32 digests (little-endian word order).
    """
    if unroll is None:
        unroll = _default_unroll()
    compress_fn = compress if unroll else compress_rolled
    bsz, total = data_u8.shape
    assert total % 64 == 0 and total > 0
    nchunks = total // 64
    # (B, C, 16) → (C, 16, B): batch lane-major for the scan body
    msg = jnp.transpose(
        bytes_to_words(data_u8).reshape(bsz, nchunks, 16), (1, 2, 0)
    )
    lengths = lengths.astype(jnp.uint32)
    # index of each lane's final chunk: ceil(L/64)-1, clamped ≥ 0
    last = jnp.maximum(
        (lengths + jnp.uint32(63)) // jnp.uint32(64), jnp.uint32(1)
    ) - jnp.uint32(1)
    h0 = jnp.broadcast_to(jnp.asarray(H0)[:, None], (8, bsz))

    def step(h, xs):
        c, m = xs
        c32 = c.astype(jnp.uint32)
        t = jnp.minimum((c32 + 1) * jnp.uint32(64), lengths)
        f = c32 == last
        h_new = compress_fn(h, m, t, f)
        active = c32 <= last
        return jnp.where(active[None, :], h_new, h), None

    h, _ = jax.lax.scan(
        step, h0, (jnp.arange(nchunks, dtype=jnp.int32), msg)
    )
    return h.T


@functools.partial(jax.jit, static_argnames=())
def blake2s_batch_jit(data_u8: jax.Array, lengths: jax.Array) -> jax.Array:
    return blake2s_batch(data_u8, lengths)


def digests_to_bytes(h: np.ndarray) -> list:
    """(B, 8) uint32 → list of 32-byte digests."""
    le = np.asarray(h, dtype="<u4")
    return [le[i].tobytes() for i in range(le.shape[0])]
