"""BLAKE2s-256 on TPU as a batched JAX computation.

The reference verifies block integrity with a sequential per-block blake2
hash on CPU (ref src/block/block.rs:66-78, src/util/data.rs:117).  BLAKE2 is
inherently sequential *within* a block (each 64-byte chunk's compression
feeds the next), so the TPU axis of parallelism is *across* blocks: the
compression function runs on uint32 vectors of B lanes (one lane per block),
and a `lax.scan` walks the 64-byte chunks.  All arithmetic is uint32
add/xor/rotate — native VPU ops; this is why the framework's default block
hash is BLAKE2s (32-bit) rather than the reference's blake2b (64-bit, which
TPUs emulate slowly).

Exactly RFC 7693 (sequential mode, digest 32 B, no key); verified
bit-identical to hashlib.blake2s in tests/test_codec_equivalence.py.
Variable-length lanes supported via per-lane byte lengths: lanes whose
message ended stop updating state (masked select), and the final-chunk flag
and byte counter are computed per lane.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

IV = np.array([
    0x6A09E667, 0xBB67AE85, 0x3C6EF372, 0xA54FF53A,
    0x510E527F, 0x9B05688C, 0x1F83D9AB, 0x5BE0CD19,
], dtype=np.uint32)

SIGMA = np.array([
    [0, 1, 2, 3, 4, 5, 6, 7, 8, 9, 10, 11, 12, 13, 14, 15],
    [14, 10, 4, 8, 9, 15, 13, 6, 1, 12, 0, 2, 11, 7, 5, 3],
    [11, 8, 12, 0, 5, 2, 15, 13, 10, 14, 3, 6, 7, 1, 9, 4],
    [7, 9, 3, 1, 13, 12, 11, 14, 2, 6, 5, 10, 4, 0, 15, 8],
    [9, 0, 5, 7, 2, 4, 10, 15, 14, 1, 11, 12, 6, 8, 3, 13],
    [2, 12, 6, 10, 0, 11, 8, 3, 4, 13, 7, 5, 15, 14, 1, 9],
    [12, 5, 1, 15, 14, 13, 4, 10, 0, 7, 6, 3, 9, 2, 8, 11],
    [13, 11, 7, 14, 12, 1, 3, 9, 5, 0, 15, 4, 8, 6, 2, 10],
    [6, 15, 14, 9, 11, 3, 0, 8, 12, 2, 13, 7, 1, 4, 10, 5],
    [10, 2, 8, 4, 7, 6, 1, 5, 15, 11, 9, 14, 3, 12, 13, 0],
], dtype=np.int32)

# h[0] ^= 0x01010000 ^ digest_len  (param block: fanout=1, depth=1, len=32)
H0 = IV.copy()
H0[0] ^= 0x01010020


def _rotr(x: jax.Array, n: int) -> jax.Array:
    return (x >> np.uint32(n)) | (x << np.uint32(32 - n))


def _g_vec(a, b, c, d, x, y):
    """One G quarter-round applied to 4 lanes at once: a/b/c/d are (..., 4)
    uint32 rows of the 4×4 state matrix — the classic SIMD formulation of
    BLAKE2 (column step, then diagonal step after row rotation).  Wider ops
    mean ~3× fewer XLA primitives than 16 scalar-word G calls, which keeps
    the compiled graph small and feeds the VPU (..., 4)-wide vectors."""
    a = a + b + x
    d = _rotr(d ^ a, 16)
    c = c + d
    b = _rotr(b ^ c, 12)
    a = a + b + y
    d = _rotr(d ^ a, 8)
    c = c + d
    b = _rotr(b ^ c, 7)
    return a, b, c, d


# Per round: message word indices feeding the column G (x0,y0) and the
# diagonal G (x1,y1), each (10, 4) — derived from SIGMA once at import.
_SX0 = SIGMA[:, 0:8:2]
_SY0 = SIGMA[:, 1:8:2]
_SX1 = SIGMA[:, 8:16:2]
_SY1 = SIGMA[:, 9:16:2]


def compress(h: jax.Array, m: jax.Array, t: jax.Array, f: jax.Array) -> jax.Array:
    """One BLAKE2s compression, vectorized over leading batch dims.

    h (B, 8) uint32 state; m (B, 16) uint32 message words (LE);
    t (B,) uint32 low byte counter (messages < 4 GiB so t_hi = 0);
    f (B,) bool final-chunk flag.
    """
    r0 = h[..., 0:4]
    r1 = h[..., 4:8]
    r2 = jnp.broadcast_to(jnp.asarray(IV[0:4]), r0.shape)
    iv4 = jnp.asarray(IV[4:8])
    r3 = jnp.broadcast_to(iv4, r0.shape)
    tvec = jnp.stack(
        [t, jnp.zeros_like(t),
         jnp.where(f, jnp.uint32(0xFFFFFFFF), jnp.uint32(0)),
         jnp.zeros_like(t)],
        axis=-1,
    )
    r3 = r3 ^ tvec
    for r in range(10):
        r0, r1, r2, r3 = _g_vec(r0, r1, r2, r3, m[..., _SX0[r]], m[..., _SY0[r]])
        # diagonalize: rotate row i left by i, run columns, rotate back
        r1d = jnp.roll(r1, -1, axis=-1)
        r2d = jnp.roll(r2, -2, axis=-1)
        r3d = jnp.roll(r3, -3, axis=-1)
        r0, r1d, r2d, r3d = _g_vec(r0, r1d, r2d, r3d, m[..., _SX1[r]], m[..., _SY1[r]])
        r1 = jnp.roll(r1d, 1, axis=-1)
        r2 = jnp.roll(r2d, 2, axis=-1)
        r3 = jnp.roll(r3d, 3, axis=-1)
    return jnp.concatenate(
        [h[..., 0:4] ^ r0 ^ r2, h[..., 4:8] ^ r1 ^ r3], axis=-1
    )


def bytes_to_words(data_u8: jax.Array) -> jax.Array:
    """uint8 (..., 4n) → uint32 (..., n), little-endian, via explicit
    arithmetic (deterministic across platforms, unlike bitcast)."""
    b = data_u8.astype(jnp.uint32).reshape(data_u8.shape[:-1] + (-1, 4))
    return b[..., 0] | (b[..., 1] << 8) | (b[..., 2] << 16) | (b[..., 3] << 24)


def blake2s_batch(data_u8: jax.Array, lengths: jax.Array) -> jax.Array:
    """Hash B zero-padded messages.

    data_u8 (B, C*64) uint8 — messages padded with zeros to a common
    multiple-of-64 length (C ≥ 1 chunks); lengths (B,) int32 true byte
    counts.  Returns (B, 8) uint32 digests (little-endian word order).
    """
    bsz, total = data_u8.shape
    assert total % 64 == 0 and total > 0
    nchunks = total // 64
    msg = bytes_to_words(data_u8).reshape(bsz, nchunks, 16)
    lengths = lengths.astype(jnp.uint32)
    # index of each lane's final chunk: ceil(L/64)-1, clamped ≥ 0
    last = jnp.maximum(
        (lengths + jnp.uint32(63)) // jnp.uint32(64), jnp.uint32(1)
    ) - jnp.uint32(1)
    h0 = jnp.broadcast_to(jnp.asarray(H0), (bsz, 8))

    def step(h, c):
        c32 = c.astype(jnp.uint32)
        m = jax.lax.dynamic_index_in_dim(msg, c, axis=1, keepdims=False)
        t = jnp.minimum((c32 + 1) * jnp.uint32(64), lengths)
        f = c32 == last
        h_new = compress(h, m, t, f)
        active = c32 <= last
        return jnp.where(active[:, None], h_new, h), None

    h, _ = jax.lax.scan(step, h0, jnp.arange(nchunks, dtype=jnp.int32))
    return h


@functools.partial(jax.jit, static_argnames=())
def blake2s_batch_jit(data_u8: jax.Array, lengths: jax.Array) -> jax.Array:
    return blake2s_batch(data_u8, lengths)


def digests_to_bytes(h: np.ndarray) -> list:
    """(B, 8) uint32 → list of 32-byte digests."""
    le = np.asarray(h, dtype="<u4")
    return [le[i].tobytes() for i in range(le.shape[0])]
