"""BlockCodec — the batch device-op interface of the block layer.

This is the seam identified in SURVEY.md §2.5 (ref src/block/block.rs:10-115
`DataBlock`): verify/hash/compress become *batchable* operations so the
scrub/resync workers (ref block/repair.rs:438-490, block/resync.rs:361-471)
can stream thousands of blocks per step through one device dispatch instead
of hashing one block at a time.

Semantics contract (both backends must agree bit-for-bit):
  - batch_hash(blocks)   == [hash_algo(b) for b in blocks]
  - batch_verify(b, h)   == elementwise batch_hash(b) == h
  - rs_encode(data)      : (B, k, S) uint8 → (B, m, S) parity, systematic
                           Cauchy-RS over GF(2^8)/0x11D (gf256.py)
  - rs_reconstruct(shards, present): any k of the k+m shards → original data
  - compress/decompress  : zstd framing with content checksum, mirroring the
                           reference's `DataBlock::Compressed`
                           (ref block/block.rs:49-91): compress returns None
                           when compression does not shrink the block.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Sequence, Tuple

import numpy as np

from ..utils.data import Hash


@dataclasses.dataclass
class CodecParams:
    hash_algo: str = "blake2s"
    rs_data: int = 8          # k
    rs_parity: int = 4        # m
    compression_level: Optional[int] = 1
    batch_blocks: int = 256
    shard_mesh: int = 1       # devices to shard codec batches over (tpu)
    # Work-stealing quantum (hybrid backend): 16 blocks = 2 RS codewords.
    # Measured on the 1-core host + metered TPU link: small groups keep the
    # CPU side's working set cache-resident (hash + GF encode reuse the
    # same bytes) — 64-block groups ran ~35% slower end-to-end — while
    # staying coarse enough that per-group device transfer overhead stays
    # negligible.
    hybrid_group_blocks: int = 16
    # Device in-flight MERGED SUBMISSIONS (hybrid backend): each may span
    # up to device_batch_blocks blocks (the feeder merges deque groups
    # into wide submissions), so window+1 bounds in-flight claim at
    # (window+1)×device_batch_blocks blocks of host staging + device HBM.
    hybrid_window: int = 1
    # Device submission width (blocks).  DECOUPLED from batch_blocks (the
    # host staging / scrub read-batch granularity): the device blake2s
    # kernel hashes one block per VPU lane, so its rate is a strong
    # function of lane count (measured v5e XLA scan: 0.18 / 1.5 / 3.8
    # GiB/s at 16 / 256 / 1024 lanes) — quoting or submitting at the
    # 256-block staging width left most of the chip idle (VERDICT r4 #1).
    # 1024 lanes = 8 full (8, 128) vregs per state word, the Pallas
    # blake2s kernel's native tile.
    #
    # MEMORY IMPLICATION (round-5 ADVICE #4): with the hybrid backend,
    # up to (hybrid_window + 1) merged submissions are in flight at
    # once, each spanning up to device_batch_blocks blocks — so host
    # staging AND device HBM claim peak at
    #   (hybrid_window + 1) × device_batch_blocks × block_size
    # = 2 GiB at the defaults (window 1, 1024 blocks, 1 MiB blocks).
    # HybridCodec clamps the width at construction so this bound never
    # exceeds max_device_staging_mib (assuming the 1 MiB default
    # block_size; raise the cap when running bigger blocks on a box
    # with the RAM/HBM for it).
    device_batch_blocks: int = 1024
    # Upper bound (MiB) on the in-flight staging claim formula above;
    # the hybrid backend clamps device_batch_blocks to honor it and
    # logs a gate event when it does.
    max_device_staging_mib: int = 4096
    # The block size the staging clamp assumes (bytes).  BlockManager
    # plumbs the daemon's configured block_size through make(); bare
    # codecs default to the daemon default (1 MiB) — without this, a
    # 4 MiB-block config would stage 4× the promised bound unclamped.
    block_size: int = 1 << 20
    # CPU-side span width (blocks) while the device is actively claiming
    # work: the CPU merges this many deque groups per fused call (wide
    # native multi-buffer hash + pointer-gather RS amortize per-call
    # overhead) while staying fine-grained enough for work stealing to
    # balance.  When the device is gated or absent the CPU span is
    # UNBOUNDED — one fused call per contiguous segment, byte-identical
    # in cost to the plain CPU codec path (VERDICT r4 #3).
    hybrid_cpu_span_blocks: int = 128
    # --- DeviceTransport (ops/transport.py): the zero-copy colocated
    # submission queue between the CodecFeeder and the device codec.
    # transport=False restores the legacy per-call serialize+copy
    # routing (hybrid ragged batches through the device codec's
    # bytes-level API).
    transport: bool = True
    # Staging slots (double buffering): batch N+1 stages and submits
    # while batch N computes.  The per-chunk staging bound is
    # max_device_staging_mib / transport_staging_slots, so all slots
    # together never exceed the configured budget.
    transport_staging_slots: int = 2
    # Background demotion slack (ms): a background (scrub/resync) batch
    # sorts behind foreground batches arriving within this window; the
    # slack stretches by 1/background_throttle_ratio when the load
    # governor reports foreground pressure.
    transport_bg_slack_ms: float = 50.0
    # Minimum measured host→device round-trip rate for the hybrid feeder
    # to claim any work.  Staging a submission costs ~3-5% of a CPU
    # verify for the same bytes, and a claimed-but-undelivered group is
    # redone by the tail hedge — so a link below ~5% of the CPU floor
    # (~1.4 GiB/s on the 1-core host) is strictly net-negative.  The
    # probe forces a real transfer round-trip, so it is immune to the
    # enqueue-time "completion" some remote backends report.
    hybrid_min_link_gibs: float = 0.07
    # --- DevicePool (ops/device_pool.py): bounded device-resident block
    # pages under the transport.  Budgeted SEPARATELY from
    # max_device_staging_mib: the staging budget bounds bytes in
    # FLIGHT (slot buffers, reclaimed at collect), the pool budget
    # bounds bytes at REST (pages that survive across scrub passes so
    # a warm re-scrub moves zero link bytes).  0 disables the pool —
    # staging then behaves byte-identically to the pre-pool transport.
    pool_mib: int = 256
    # Fixed device page size (KiB).  A block spans ceil(len/page)
    # pages with the tail page partially filled (ragged occupancy), so
    # smaller pages waste less tail but cost more per-page handles;
    # 256 KiB ≈ 4 pages per default 1 MiB block keeps handle counts
    # trivial while bounding tail waste at < 25% for blocks ≥ 768 KiB.
    pool_page_kib: int = 256
    # Next-range prefetch: the scrub worker hints the upcoming hash
    # range and the transport stages the non-resident blocks as
    # background-class work while the current batch computes (riding
    # the staging double buffer under the governor).
    pool_prefetch: bool = True


class IncrementalHash:
    """O(1) running hash state — the update/finalize form of the
    codec's one-shot digests (the portable O(1) autoregressive-caching
    shape from PAPERS.md applied to streamed writes).

    A streamed PUT or multipart part arrives window by window; hashing
    the assembled object at the end would re-read every byte.  This
    state absorbs each window as it passes (``update``) and emits the
    digest at the end (``digest``) — BIT-IDENTICAL to the one-shot
    hash of the concatenation, for ANY chunk boundaries, because
    BLAKE2 is a sequential sponge: state after absorbing b1+b2 equals
    state after absorbing b1 then b2.  tests/test_device_pool.py
    proves the identity against blake2sum / blake2s_sum.

    The state is O(1) in stream length (one BLAKE2 block buffer plus
    the chaining value), so a 1 GiB multipart costs one pass of
    hashing total and constant memory per in-flight part."""

    __slots__ = ("_h", "nbytes")

    def __init__(self, h):
        self._h = h
        self.nbytes = 0

    def update(self, buf) -> "IncrementalHash":
        self._h.update(buf)
        self.nbytes += len(buf)
        return self

    def digest(self) -> Hash:
        """Finalize (non-destructively: hashlib copies internally) —
        the digest of everything absorbed so far."""
        return Hash(self._h.digest())

    def hexdigest(self) -> str:
        return self._h.hexdigest()

    def copy(self) -> "IncrementalHash":
        c = IncrementalHash(self._h.copy())
        c.nbytes = self.nbytes
        return c


def hash_stream() -> IncrementalHash:
    """Incremental form of the block content hash (BLAKE2s-256,
    utils.data.blake2s_sum)."""
    import hashlib

    return IncrementalHash(hashlib.blake2s(digest_size=32))


def mhash_stream() -> IncrementalHash:
    """Incremental form of the metadata hash (BLAKE2b-256,
    utils.data.blake2sum) — the streamed-PUT/multipart content digest
    (api/s3/put.py, api/s3/multipart.py)."""
    import hashlib

    return IncrementalHash(hashlib.blake2b(digest_size=32))


class BlockCodec:
    """Batch codec interface; see module docstring for the contract.

    Every codec carries a CodecObserver (`self.obs`): the gate-decision
    event ring and per-stage accumulators are always on; Prometheus
    instruments are created when the daemon plumbs its MetricsRegistry
    in (make_codec(..., metrics=system.metrics, tracer=system.tracer))."""

    def __init__(self, params: CodecParams, metrics=None, tracer=None,
                 observer=None):
        self.params = params
        if observer is None:
            from .observer import CodecObserver

            observer = CodecObserver(metrics=metrics, tracer=tracer)
        self.obs = observer

    def info(self) -> dict:
        """Operator-facing snapshot (admin `codec info`): backend,
        effective params, byte accounting, and per-stage attribution.
        JSON-safe by construction."""
        return {
            "backend": type(self).__name__,
            "params": dataclasses.asdict(self.params),
            "bytes": dict(self.obs.bytes_total),
            "tpu_frac": round(self.obs.tpu_frac(), 4),
            "stages": self.obs.stage_stats(),
            "events_recorded": len(self.obs.events),
        }

    # --- hashing ---
    def batch_hash(self, blocks: Sequence[bytes]) -> List[Hash]:
        raise NotImplementedError

    def batch_verify(self, blocks: Sequence[bytes], hashes: Sequence[Hash]) -> np.ndarray:
        if len(blocks) != len(hashes):
            raise ValueError(f"{len(blocks)} blocks vs {len(hashes)} hashes")
        got = self.batch_hash(blocks)
        return np.array([bytes(a) == bytes(b) for a, b in zip(got, hashes)], dtype=bool)

    def verify_one(self, block: bytes, hash: Hash) -> bool:
        """Single-block verify — the get/read path (ref block.rs:66-78).
        Default routes through batch_verify so both backends share one
        semantics definition; device backends override to avoid paying a
        device roundtrip for one block (their batch paths still run on
        device — the scrub/resync producers batch)."""
        return bool(self.batch_verify([block], [hash])[0])

    def gf_scale(self, coeff: int, buf: bytes,
                 limit: Optional[int] = None) -> bytes:
        """coeff ⊗ buf over GF(2^8), truncated to `limit` — the
        partial-parallel-repair kernel (survivor-side partial product and
        coordinator-side rescale, block/repair_plan.py).  CpuCodec
        overrides with the native GFNI kernel when built."""
        from . import gf256

        return gf256.gf_scale_bytes(coeff, buf, limit)

    def rs_encode_blocks(self, blocks: Sequence[bytes]) -> np.ndarray:
        """RS parity straight from a list of block buffers:
        (ceil(B/k), m, maxlen), blocks zero-extended to maxlen, the batch
        zero-padded to a whole codeword (zero data → zero parity,
        GF-linear).  This default packs into one (B, k, S) array and calls
        rs_encode; CpuCodec overrides with a pointer-gather kernel that
        skips the packing pass."""
        k = self.params.rs_data
        assert k > 0 and blocks
        pad = (-len(blocks)) % k
        maxlen = max(len(b) for b in blocks)
        arr = np.zeros((len(blocks) + pad, maxlen), dtype=np.uint8)
        for i, b in enumerate(blocks):
            arr[i, : len(b)] = np.frombuffer(b, dtype=np.uint8)
        return self.rs_encode(arr.reshape(-1, k, maxlen))

    # --- ragged batch entry points (the CodecFeeder's dispatch surface) ---
    #
    # Each takes MANY independent submissions (variable block counts and
    # sizes — "ragged" in the Ragged Paged Attention sense, PAPERS.md)
    # and runs them as ONE fused pass, returning per-submission results.
    # The base implementations amortize on the CPU backends (one
    # multi-buffer hash / one pointer-gather encode over the
    # concatenation, decode schedules shared per survivor pattern);
    # HybridCodec overrides to route whole batches to the device when
    # the gate is open.

    def ragged_side(self) -> str:
        """Which side a ragged batch dispatched NOW would run on —
        metric/event attribution for the feeder.  Backends that can
        route (hybrid) override."""
        return "cpu"

    def hash_ragged(self, groups: Sequence[Sequence[bytes]]
                    ) -> List[List[Hash]]:
        """Hash many submissions' blocks in one batch_hash pass:
        returns per-submission digest lists.  Coalescing across
        submissions is what engages the 8-way SIMD multi-buffer kernel
        for single-block foreground requests."""
        flat: List[bytes] = [b for g in groups for b in g]
        if not flat:
            return [[] for _ in groups]
        digs = self.batch_hash(flat)
        out: List[List[Hash]] = []
        i = 0
        for g in groups:
            out.append(digs[i:i + len(g)])
            i += len(g)
        return out

    def mhash_batch(self, bufs: Sequence[bytes]) -> List[Hash]:
        """Metadata (Merkle trie node/key) hashing: BLAKE2b-256, the
        table engine's `blake2sum` — bit-identical to the serial
        per-node path by construction.  Kept separate from batch_hash
        because block content hashes are BLAKE2s (the device kernel's
        algorithm) while the Merkle trie is BLAKE2b: mixing them in one
        device batch would either change the trie hash (a rolling-
        upgrade divergence: mixed-version replicas could never agree on
        node hashes for identical data) or silently fall back.  The
        hashing itself is hashlib per buffer; the feeder route buys one
        dispatch + one observability record per batch and is the single
        seam a future multi-buffer BLAKE2b kernel drops into."""
        from ..utils.data import blake2sum

        return [blake2sum(b) for b in bufs]

    def hash_stream(self) -> IncrementalHash:
        """Update/finalize form of the block content hash: absorb
        windows as they stream, finalize bit-identical to
        batch_hash([concatenation])[0]."""
        return hash_stream()

    def mhash_stream(self) -> IncrementalHash:
        """Update/finalize form of the metadata hash (blake2sum) — the
        streamed-write content digest carried per part by the S3 PUT
        and multipart handlers so a 1 GiB upload is hashed in one pass
        total, never by rehashing the assembled object."""
        return mhash_stream()

    def mhash_ragged(self, groups: Sequence[Sequence[bytes]]
                     ) -> List[List[Hash]]:
        """Per-submission metadata-hash lists in one mhash_batch pass
        (the Merkle updater/syncer's feeder entry point)."""
        flat: List[bytes] = [b for g in groups for b in g]
        if not flat:
            return [[] for _ in groups]
        digs = self.mhash_batch(flat)
        out: List[List[Hash]] = []
        i = 0
        for g in groups:
            out.append(digs[i:i + len(g)])
            i += len(g)
        return out

    def rs_encode_ragged(self, groups: Sequence[Sequence[bytes]]
                         ) -> List[np.ndarray]:
        """RS parity for many submissions in ONE pass over the
        concatenated buffers.  Each group is padded to whole codewords
        with empty blocks BEFORE concatenation so its parity rows stay
        self-contained (zero data → zero parity, GF-linear), then the
        single rs_encode_blocks call amortizes the kernel's per-call
        setup; per-group rows are split back out and column-trimmed to
        the group's own longest block.  Per-group result is identical to
        rs_encode_blocks(group)."""
        k = self.params.rs_data
        assert k > 0 and groups
        padded: List[bytes] = []
        rows_per: List[int] = []
        for g in groups:
            assert g, "empty encode submission"
            pad = (-len(g)) % k
            padded.extend(list(g))
            padded.extend([b""] * pad)
            rows_per.append((len(g) + pad) // k)
        parity = self.rs_encode_blocks(padded)
        out: List[np.ndarray] = []
        r = 0
        for g, nr in zip(groups, rows_per):
            ml = max(len(b) for b in g)
            out.append(np.ascontiguousarray(parity[r:r + nr, :, :ml]))
            r += nr
        return out

    def rs_reconstruct_ragged(self, items: Sequence[tuple]
                              ) -> List[np.ndarray]:
        """Many rs_reconstruct submissions, batched per RS SCHEDULE:
        items are (shards (B, p, S), present, rows|None) tuples;
        submissions sharing a survivor pattern (present, rows) decode
        through one matrix application (the schedule-caching idea of
        "Accelerating XOR-based Erasure Coding", PAPERS.md — a repair
        storm after a node loss repeats one loss pattern).  Shards are
        zero-padded to the key group's widest S (zero columns decode to
        zero columns, GF-linear) and results trimmed back."""
        out: List[Optional[np.ndarray]] = [None] * len(items)
        keyed: dict = {}
        for i, (shards, present, rows) in enumerate(items):
            key = (tuple(present[: self.params.rs_data]),
                   tuple(rows) if rows is not None else None,
                   int(shards.shape[1]))
            keyed.setdefault(key, []).append(i)
        for (pres, rows, p), idxs in keyed.items():
            if len(idxs) == 1:
                i = idxs[0]
                shards, present, rws = items[i]
                out[i] = self.rs_reconstruct(shards, present, rws)
                continue
            max_s = max(items[i][0].shape[-1] for i in idxs)
            total_b = sum(items[i][0].shape[0] for i in idxs)
            stacked = np.zeros((total_b, p, max_s), dtype=np.uint8)
            off = 0
            for i in idxs:
                sh = items[i][0]
                stacked[off:off + sh.shape[0], :, : sh.shape[-1]] = sh
                off += sh.shape[0]
            dec = self.rs_reconstruct(
                stacked, list(pres), list(rows) if rows is not None else None)
            off = 0
            for i in idxs:
                sh = items[i][0]
                out[i] = np.ascontiguousarray(
                    dec[off:off + sh.shape[0], :, : sh.shape[-1]])
                off += sh.shape[0]
        return out  # type: ignore[return-value]

    def scrub_ragged(self, items: Sequence[tuple]) -> List[tuple]:
        """Many scrub_encode_batch submissions (the CodecFeeder's `scrub`
        kind): items are (blocks, hashes, fetch_parity) tuples, result
        is per-item (ok, parity|None).  The base implementation runs
        them serially; HybridCodec routes the batch to one side, and a
        device-armed feeder bypasses this entirely through the
        DeviceTransport."""
        return [self.scrub_encode_batch(b, h, fp) for b, h, fp in items]

    def scrub_encode_batch(self, blocks: Sequence[bytes],
                           hashes: Sequence[Hash],
                           fetch_parity: bool = True):
        """Fused scrub step: verify + RS(k, m) parity per codeword of k
        consecutive blocks.  Returns (ok (B,), parity
        (ceil(B/k), m, maxlen) | None); short blocks zero-pad to maxlen
        (zero data → zero parity, GF-linear).  Device backends override
        with a single fused dispatch; this default serves the CPU path."""
        ok = self.batch_verify(blocks, hashes)
        parity = None
        if fetch_parity and self.params.rs_data > 0 and blocks:
            parity = self.rs_encode_blocks(blocks)
        return ok, parity

    # --- Reed-Solomon ---
    def rs_encode(self, data: np.ndarray) -> np.ndarray:
        """(B, k, S) uint8 → (B, m, S) parity shards."""
        raise NotImplementedError

    def rs_reconstruct(self, shards: np.ndarray, present: Sequence[int],
                       rows: Optional[Sequence[int]] = None) -> np.ndarray:
        """shards (B, p, S) = the surviving shards, in the order listed by
        `present` (indices into the k+m codeword, p ≥ k) → (B, k, S) data.

        `rows` restricts decoding to those data-row indices, returning
        (B, len(rows), S) — a repair that lost j of k members only pays
        for the j missing rows instead of re-deriving all k (a k/j GF
        work saving, 8× for the common single-block repair)."""
        raise NotImplementedError

    # --- compression (CPU-side on both backends) ---
    def compress(self, data: bytes) -> Optional[bytes]:
        if self.params.compression_level is None:
            return None
        from ..utils.zstd_compat import zstandard
        c = zstandard.ZstdCompressor(
            level=self.params.compression_level,
            write_checksum=True,   # ref block/block.rs:66-78 verifies via zstd checksum
            write_content_size=True,
        )
        out = c.compress(data)
        return out if len(out) < len(data) else None

    def decompress(self, data: bytes) -> bytes:
        from ..utils.zstd_compat import zstandard
        return zstandard.ZstdDecompressor().decompress(data)

    # --- sharding helpers (shape plumbing, backend-independent) ---
    def shard_block(self, block: bytes) -> Tuple[np.ndarray, int]:
        """Split one block into (k, S) zero-padded shards; returns original
        length for exact reassembly."""
        k = self.params.rs_data
        n = len(block)
        s = (n + k - 1) // k
        buf = np.zeros(k * s, dtype=np.uint8)
        buf[:n] = np.frombuffer(block, dtype=np.uint8)
        return buf.reshape(k, s), n

    def unshard_block(self, data: np.ndarray, length: int) -> bytes:
        return data.reshape(-1).tobytes()[:length]
