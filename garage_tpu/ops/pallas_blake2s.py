"""Pallas TPU kernel for batched BLAKE2s-256 — the scrub hash hot loop.

Why a hand kernel.  The XLA formulation (ops/tpu_blake2s.py) is a
lax.scan over 64-byte chunks whose carried state is an (8, B) array in
HBM: every scan step re-loads and re-stores the 16 state words plus the
chunk's message words through HBM, and XLA materializes the masked
select (`jnp.where(active, h_new, h)`) as another full state round-trip.
Measured on v5e the scan runs at 0.18 / 1.5 / 3.8 GiB/s at 16 / 256 /
1024 lanes — far below the VPU's arithmetic ceiling for the ~25
uint32 ops/byte BLAKE2s costs, i.e. the scan is bound by per-step state
traffic, not by hashing arithmetic.  This kernel keeps the 8 state words
resident in VMEM scratch across ALL chunks of a batch tile; the only
per-chunk HBM traffic is the 64 message bytes per lane, streamed by the
Pallas pipeline (double-buffered DMA overlapping compute).

Layout.  One VPU lane per message (the reference hashes blocks one at a
time on CPU — ref src/block/repair.rs:438-490, src/util/data.rs:117; the
TPU axis of parallelism is across blocks).  The batch is shaped
(R, 128) = (sublane-rows, lanes) so every one of the 16 working-state
values is a native (R, 128) uint32 vreg tile at R = 8.  The host-side
wrapper transposes the padded messages once to (C, 16, R, 128) word
layout — a single HBM-bandwidth pass that replaces the scan's per-step
gathers.

Grid = (batch_tiles, C): the chunk axis is innermost and sequential
("arbitrary" semantics), so the VMEM scratch state legally carries
between steps; h initializes at chunk 0 and flushes to the output block
at chunk C-1 (the output block index is constant along the chunk axis,
so Mosaic copies it out exactly once per batch tile).

Exactly RFC 7693 (sequential mode, digest 32 B, no key), bit-identical
to hashlib.blake2s and to ops/tpu_blake2s.blake2s_batch — asserted in
tests/test_pallas_blake2s.py (interpret mode, no TPU needed).
Variable-length lanes: per-lane byte counts give each lane its own final
chunk (t counter capped at the true length, finalization flag on the
lane's last chunk, state frozen after it) — identical masking semantics
to blake2s_batch.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from .tpu_blake2s import H0, IV, SIGMA, _G_IDX, bytes_to_words

LANE = 128


def _rotr(x, n: int):
    return (x >> jnp.uint32(n)) | (x << jnp.uint32(32 - n))


def _kernel(nchunks: int, msg_ref, len_ref, o_ref, h_ref):
    """One grid step = one 64-byte chunk for one (R, 128)-lane tile.

    msg_ref (1, 16, R, 128) u32 — this chunk's message words;
    len_ref (R, 128) u32 — true byte lengths; o_ref (8, R, 128) u32 —
    digests, written at the final chunk; h_ref (8, R, 128) u32 VMEM
    scratch — the carried state.
    """
    from jax.experimental import pallas as pl

    j = pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        for w in range(8):
            h_ref[w] = jnp.full(len_ref.shape, H0[w], jnp.uint32)

    lengths = len_ref[...]
    # index of each lane's final chunk: ceil(L/64)-1, clamped >= 0.
    # min/max spelled as where-selects: this backend's Mosaic fails to
    # legalize vector arith.maxui/minui on uint32.
    nch = (lengths + jnp.uint32(63)) // jnp.uint32(64)
    last = jnp.where(nch == 0, jnp.uint32(0), nch - jnp.uint32(1))
    cj = j.astype(jnp.uint32)
    tend = (cj + jnp.uint32(1)) * jnp.uint32(64)
    t = jnp.where(tend < lengths, tend, lengths)
    f = cj == last

    h = [h_ref[w] for w in range(8)]
    m = [msg_ref[0, w] for w in range(16)]
    v = list(h) + [
        jnp.full(lengths.shape, IV[0], jnp.uint32),
        jnp.full(lengths.shape, IV[1], jnp.uint32),
        jnp.full(lengths.shape, IV[2], jnp.uint32),
        jnp.full(lengths.shape, IV[3], jnp.uint32),
        jnp.uint32(IV[4]) ^ t,
        jnp.full(lengths.shape, IV[5], jnp.uint32),
        jnp.uint32(IV[6]) ^ jnp.where(f, jnp.uint32(0xFFFFFFFF),
                                      jnp.uint32(0)),
        jnp.full(lengths.shape, IV[7], jnp.uint32),
    ]
    for r in range(10):
        s = SIGMA[r]
        for g, (ia, ib, ic, id_) in enumerate(_G_IDX):
            x, y = m[s[2 * g]], m[s[2 * g + 1]]
            a, b, c, d = v[ia], v[ib], v[ic], v[id_]
            a = a + b + x
            d = _rotr(d ^ a, 16)
            c = c + d
            b = _rotr(b ^ c, 12)
            a = a + b + y
            d = _rotr(d ^ a, 8)
            c = c + d
            b = _rotr(b ^ c, 7)
            v[ia], v[ib], v[ic], v[id_] = a, b, c, d
    # lanes whose message already ended stop updating state
    active = cj <= last
    for w in range(8):
        h_ref[w] = jnp.where(active, h[w] ^ v[w] ^ v[w + 8], h[w])

    @pl.when(j == nchunks - 1)
    def _flush():
        for w in range(8):
            o_ref[w] = h_ref[w]


@functools.partial(jax.jit, static_argnames=("interpret",))
def blake2s_words_pallas(msg, lengths, interpret: bool = False):
    """msg (C, 16, R, 128) uint32 chunk-major message words; lengths
    (R, 128) uint32 → (8, R, 128) uint32 digests."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    nchunks, _, rows, _ = msg.shape
    # batch tile: up to 8 sublane-rows (1024 lanes) per grid step — one
    # native (8, 128) vreg per state word; bigger tiles spill
    rt = rows
    while rt > 8 or rows % rt:
        rt -= 1
    grid = (rows // rt, nchunks)
    # renamed in jax 0.5: TPUCompilerParams → CompilerParams; support both
    params_cls = getattr(pltpu, "CompilerParams", None) or \
        getattr(pltpu, "TPUCompilerParams")
    return pl.pallas_call(
        functools.partial(_kernel, nchunks),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 16, rt, LANE), lambda i, j: (j, 0, i, 0)),
            pl.BlockSpec((rt, LANE), lambda i, j: (i, 0)),
        ],
        out_specs=pl.BlockSpec((8, rt, LANE), lambda i, j: (0, i, 0)),
        out_shape=jax.ShapeDtypeStruct((8, rows, LANE), jnp.uint32),
        scratch_shapes=[pltpu.VMEM((8, rt, LANE), jnp.uint32)],
        compiler_params=params_cls(
            dimension_semantics=("arbitrary", "arbitrary"),
        ),
        interpret=interpret,
    )(msg, lengths)


def blake2s_batch_pallas(data_u8: jax.Array, lengths: jax.Array,
                         interpret: bool = False) -> jax.Array:
    """Drop-in for tpu_blake2s.blake2s_batch on lane counts divisible by
    128: data_u8 (B, C*64) uint8 zero-padded messages, lengths (B,) true
    byte counts → (B, 8) uint32 digests (little-endian word order).

    Jittable; the (B, C, 16) → (C, 16, B/128, 128) word transpose runs
    as one XLA HBM pass feeding the kernel's streaming layout.
    """
    bsz, total = data_u8.shape
    assert total % 64 == 0 and total > 0
    assert bsz % LANE == 0, bsz
    nchunks = total // 64
    rows = bsz // LANE
    msg = jnp.transpose(
        bytes_to_words(data_u8).reshape(bsz, nchunks, 16), (1, 2, 0)
    ).reshape(nchunks, 16, rows, LANE)
    lanes = lengths.astype(jnp.uint32).reshape(rows, LANE)
    h = blake2s_words_pallas(msg, lanes, interpret=interpret)
    return h.reshape(8, bsz).T
