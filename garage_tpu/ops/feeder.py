"""CodecFeeder — continuous ragged batching for the foreground data path.

Through round 5 the codec's batch entry points were fed only by the
background producers (scrub/resync read-ahead): the CLIENT-FACING hot
path — PUT block-id hashing, write-time RS encodes, degraded-read RS
decodes — called the codec one request at a time, so K concurrent users
paid K serial codec passes (docs/PUT_LATENCY.md: a put is ~88% CPU and
conc8 p50 ≈ 8 × CPU-per-put).  This module closes the gap with the
batching idiom of Ragged Paged Attention (PAPERS.md): in-flight requests
SUBMIT individually and a dispatcher coalesces them into ragged batches
(variable block counts and sizes per submission) dispatched when either
the batch fills (``max_batch_blocks``) or an SLO deadline (``slo_ms``,
armed by the OLDEST pending submission) expires — a lone put never waits
for a full batch: the deadline bounds its wait, and when the submitter
can PROVE it is alone (the ``peers`` hint from the S3 layer's in-flight
put count) the dispatch is immediate, so solo latency pays only the
thread handoff.  Conversely, when peers are expected the wait ends early
as soon as that many submissions have arrived — under K-concurrent load
the batch forms without ever sleeping the full SLO.

Why this wins even on CPU: the ragged entry points it feeds
(BlockCodec.hash_ragged / rs_encode_ragged / rs_reconstruct_ragged) run
ONE fused pass over the concatenation of every submission — the 8-way
SIMD multi-buffer BLAKE2s engages across requests (a single 1 MiB block
per request leaves 7 of 8 lanes idle), the pointer-gather GF kernel
amortizes its per-call setup, and decode submissions sharing a survivor
pattern share one cached RS schedule ("Accelerating XOR-based Erasure
Coding", PAPERS.md).  On a device-armed node the hybrid codec routes the
whole batch to the accelerator when the (cached) link probe clears the
gate, so foreground traffic inherits the scrub path's device pipeline.

Threading contract: submissions may come from the event loop or any
worker thread; each returns a concurrent.futures.Future resolved by the
dispatcher thread.  ``shutdown()`` refuses new submissions and DRAINS
everything already accepted — acked work is never dropped — and the
``*_or_direct`` conveniences fall back to an inline codec call when the
feeder is closed, so shutdown races degrade to the pre-feeder behavior
instead of erroring.
"""

from __future__ import annotations

import collections
import concurrent.futures
import logging
import threading
import time
from typing import List, Optional, Sequence

import numpy as np

from ..utils.cpuprof import register_thread, unregister_thread

logger = logging.getLogger("garage_tpu.ops.feeder")

KINDS = ("hash", "encode", "decode", "scrub", "mhash")

# histogram edges tuned to the objects being measured: waits are bounded
# by slo_ms (default 2 ms), batch sizes by max_batch_blocks
WAIT_BUCKETS = (0.0002, 0.0005, 0.001, 0.002, 0.004, 0.008, 0.016,
                0.05, 0.25)
SIZE_BUCKETS = (1.0, 2.0, 4.0, 8.0, 16.0, 32.0, 64.0, 128.0, 256.0,
                512.0, 1024.0)


class FeederClosed(RuntimeError):
    """Raised by submit_* after shutdown() — callers either drained
    already (the normal case) or fall back to a direct codec call."""


class _Item:
    __slots__ = ("kind", "payload", "blocks", "nbytes", "future", "ts",
                 "peers", "deadline", "cls", "want_parity", "tctx",
                 "span_id", "t_ns", "t_mono_ns", "t_dispatch_ns")

    def __init__(self, kind, payload, blocks, nbytes, peers=None,
                 cls="fg", want_parity=True):
        self.kind = kind
        self.payload = payload
        self.blocks = blocks
        self.nbytes = nbytes
        # scheduling class for the device transport's single queue:
        # "fg" = client-facing (PUT/GET verify, write-time encode,
        # degraded-read decode), "bg" = scrub/resync producers, demoted
        # behind foreground under governor pressure (ops/transport.py)
        self.cls = cls if cls in ("fg", "bg") else "fg"
        self.want_parity = want_parity
        # end-to-end request deadline (absolute time.monotonic), captured
        # from the submitter's task-local budget (utils/tracing): an
        # expired submission is failed typed at dispatch instead of
        # spending codec time on a request whose client already gave up
        from ..utils.tracing import current_deadline, current_trace_context

        self.deadline = current_deadline()
        # the submitter's trace identity: the dispatcher/transport run on
        # their own threads where the contextvars are gone, so the item
        # carries what they need to attribute feeder wait and device
        # compute back to the REQUEST's waterfall (utils/waterfall.py).
        # span_id is pre-allocated so transport-side child spans can
        # parent on the feeder span before it is recorded.
        self.tctx = current_trace_context()
        if self.tctx is not None:
            import os

            self.span_id = os.urandom(8).hex()
            self.t_ns = time.time_ns()
        else:
            self.span_id = None
            self.t_ns = 0
        # always-on monotonic submit stamp (t_ns is wall and traced-only):
        # the transport's enqueue timeline event diffs it to show feeder
        # wait next to the LinkProfiler's in-transport stages
        self.t_mono_ns = time.monotonic_ns()
        self.t_dispatch_ns = 0
        # how many concurrent submitters the CALLER can see (e.g. the
        # S3 layer's in-flight put count).  Three regimes: an explicit
        # peers <= 1 means PROVABLY alone — dispatch immediately, the
        # deadline would be pure added solo latency; peers > 1 means the
        # dispatcher stops waiting as soon as that many submissions have
        # arrived (the batch forms without sleeping the full SLO); None
        # means unknown concurrency (background encode/decode callers) —
        # wait out the SLO so a repair storm's submissions coalesce.
        self.peers = peers
        self.future: "concurrent.futures.Future" = concurrent.futures.Future()
        self.ts = time.perf_counter()


class CodecFeeder:
    """Deadline-bounded continuous batcher in front of one BlockCodec."""

    def __init__(self, codec, slo_ms: float = 2.0,
                 max_batch_blocks: int = 256, metrics=None, observer=None):
        self.codec = codec
        self.obs = observer if observer is not None else codec.obs
        self.slo = max(0.0, float(slo_ms)) / 1000.0
        self.max_batch_blocks = max(1, int(max_batch_blocks))
        self._cond = threading.Condition()
        self._pending: "collections.deque[_Item]" = collections.deque()
        self._pending_blocks = 0
        self._closed = False
        self._inflight = 0
        self._thread: Optional[threading.Thread] = None
        # lazy daemon worker for INLINE (CPU-side) scrub batches: a
        # multi-MiB fused verify+encode must not run on the lone
        # dispatcher thread, where it would head-of-line block every
        # foreground hash/encode/decode dispatch (pre-feeder, scrub
        # compute ran on its own asyncio.to_thread worker).  A daemon
        # thread + queue rather than a ThreadPoolExecutor: executor
        # threads are non-daemon and have no bounded join, so one
        # wedged codec call would hang both shutdown() and interpreter
        # exit.
        self._scrub_q: Optional[collections.deque] = None
        self._scrub_cond = threading.Condition()
        self._scrub_thread: Optional[threading.Thread] = None
        self._last_side: Optional[str] = None
        # always-on counters (admin `codec info` + bench self-attribution)
        self.submits = 0
        self.dispatches = 0
        self.dispatched_blocks = 0
        self.dispatch_reasons: dict = {}
        self.max_depth_seen = 0
        self.expired = 0  # submissions shed: deadline passed pre-dispatch
        if metrics is not None:
            self.m_depth = metrics.gauge(
                "codec_feeder_depth",
                "Submissions waiting in the codec feeder",
                fn=lambda: float(len(self._pending)))
            self.m_wait = metrics.histogram(
                "codec_batch_wait_seconds",
                "Submit-to-dispatch wait in the codec feeder, by kind",
                buckets=WAIT_BUCKETS)
            self.m_size = metrics.histogram(
                "codec_batch_size",
                "Blocks per dispatched feeder batch, by kind",
                buckets=SIZE_BUCKETS)
            self.m_dispatch = metrics.counter(
                "codec_batch_dispatch_total",
                "Feeder batch dispatches by kind and trigger "
                "(full = batch filled, deadline = SLO expired, "
                "peers = all expected submitters arrived, "
                "lone = no peers expected so no wait, "
                "drain = shutdown flush)")
            self.m_submit = metrics.counter(
                "codec_batch_submit_total",
                "Feeder submissions by kind")
            # USE saturation: pending blocks vs one dispatch's capacity
            # (> 1 = the dispatcher cannot drain a full batch per window;
            # docs/OBSERVABILITY.md "Critical path & saturation")
            metrics.gauge(
                "feeder_queue_saturation",
                "Pending feeder blocks / max_batch_blocks (USE "
                "saturation; > 1 means the dispatcher is the bottleneck)",
                fn=lambda: self._pending_blocks / self.max_batch_blocks)
        else:
            self.m_depth = self.m_wait = self.m_size = None
            self.m_dispatch = self.m_submit = None

    # --- submission side ---------------------------------------------------

    @property
    def closed(self) -> bool:
        return self._closed

    # In-flight foreground request tracking: the S3 put path brackets
    # each request with request_scope(), and submits carry the count as
    # the `peers` hint — that is how the dispatcher distinguishes "a
    # serial client whose submissions merely arrive back-to-back" (never
    # wait) from "K concurrent requests whose submissions will coalesce
    # if given one SLO window" (wait, but stop as soon as K arrive).

    def request_scope(self) -> "_RequestScope":
        return _RequestScope(self)

    @property
    def inflight_requests(self) -> int:
        return self._inflight

    def _submit(self, item: _Item) -> "concurrent.futures.Future":
        with self._cond:
            if self._closed:
                raise FeederClosed("codec feeder is shut down")
            self._pending.append(item)
            self._pending_blocks += item.blocks
            self.submits += 1
            if len(self._pending) > self.max_depth_seen:
                self.max_depth_seen = len(self._pending)
            if self._thread is None:
                # lazy start: bare-library users who never submit pay no
                # thread; daemon=True so a wedged codec call can't block
                # interpreter exit
                self._thread = threading.Thread(
                    target=self._run, name="codec-feeder", daemon=True)
                self._thread.start()
            self._cond.notify_all()
        if self.m_submit is not None:
            self.m_submit.inc(kind=item.kind)
        if item.tctx is not None and self.obs.tracer is not None:
            # the "Feeder <kind>" span covers submit→result on the
            # request's trace, with queue_s marking the wait portion
            # (the queue-wait/service-time split): recorded when the
            # future resolves, whichever thread does it, so both the
            # inline-CPU and transport routes attribute identically
            item.future.add_done_callback(self._emit_item_span(item))
        return item.future

    def _emit_item_span(self, item: _Item):
        def emit(_fut) -> None:
            tr = self.obs.tracer
            if tr is None:
                return
            try:
                attrs = {"kind": item.kind, "blocks": item.blocks}
                if item.t_dispatch_ns:
                    attrs["queue_s"] = round(
                        (item.t_dispatch_ns - item.t_ns) / 1e9, 6)
                tr.record_span(
                    f"Feeder {item.kind}", item.tctx.trace_id,
                    item.tctx.span_id, item.t_ns, time.time_ns(),
                    span_id=item.span_id, **attrs)
            except Exception:  # noqa: BLE001 — attribution must not fail work
                logger.debug("feeder span emit failed", exc_info=True)
        return emit

    def submit_hash(self, blocks: Sequence[bytes],
                    peers: Optional[int] = None, cls: str = "fg"):
        """BLAKE2s block-id hashing for one request's window of blocks.
        Future resolves to List[Hash] in submission order.  `peers` =
        concurrent submitters the caller can see (see _Item.peers)."""
        blocks = list(blocks)
        return self._submit(_Item(
            "hash", blocks, len(blocks), sum(len(b) for b in blocks),
            peers=peers, cls=cls))

    def submit_encode(self, blocks: Sequence[bytes],
                      peers: Optional[int] = None, cls: str = "fg"):
        """RS parity for one request's blocks (own codeword group,
        zero-padded to whole codewords — rs_encode_blocks semantics).
        Future resolves to (ceil(B/k), m, maxlen) uint8 parity."""
        blocks = list(blocks)
        return self._submit(_Item(
            "encode", blocks, len(blocks), sum(len(b) for b in blocks),
            peers=peers, cls=cls))

    def submit_decode(self, shards: np.ndarray, present: Sequence[int],
                      rows: Optional[Sequence[int]] = None,
                      peers: Optional[int] = None, cls: str = "fg"):
        """One degraded-read RS decode (rs_reconstruct semantics).
        Future resolves to the decoded (B, len(rows) or k, S) array."""
        return self._submit(_Item(
            "decode", (shards, list(present),
                       list(rows) if rows is not None else None),
            max(1, int(shards.shape[0])), int(shards.nbytes), peers=peers,
            cls=cls))

    def submit_mhash(self, bufs: Sequence[bytes],
                     peers: Optional[int] = None, cls: str = "bg"):
        """Metadata (Merkle node/key) BLAKE2b hashing for the table
        engine — the trie updater and the sync descent submit whole node
        batches here instead of hashing one node at a time.  Always
        dispatched on the CPU side (the trie hash is BLAKE2b; the device
        kernel is BLAKE2s — see BlockCodec.mhash_batch), class bg so a
        Merkle backlog drain never preempts foreground codec batches.
        Future resolves to List[Hash] in submission order."""
        bufs = list(bufs)
        return self._submit(_Item(
            "mhash", bufs, len(bufs), sum(len(b) for b in bufs),
            peers=peers, cls=cls))

    def submit_scrub(self, blocks: Sequence[bytes], hashes: Sequence,
                     want_parity: bool = True, cls: str = "bg"):
        """One scrub/resync batch (scrub_encode_batch semantics: fused
        verify + per-codeword RS parity).  Future resolves to
        (ok (B,), parity | None).  This is how the background producers
        ride the SAME feeder queue as foreground verifies — the scrub
        worker no longer talks to the device behind the feeder's back —
        entering the device transport as class "bg" (demoted behind
        foreground under governor pressure)."""
        blocks = list(blocks)
        return self._submit(_Item(
            "scrub", (blocks, list(hashes)), len(blocks),
            sum(len(b) for b in blocks), cls=cls,
            want_parity=want_parity))

    def prefetch_scrub(self, blocks: Sequence[bytes],
                       hashes: Sequence) -> int:
        """Hint the upcoming scrub range to the device pool
        (DevicePool prefetch): non-resident blocks stage as
        background-class transport work while the current batch
        computes, so the next scrub batch pool-hits.  A no-op (0)
        without an armed transport+pool — the hint is advisory and
        never an error."""
        tr = getattr(self.codec, "transport", None)
        pf = getattr(tr, "prefetch", None)
        if tr is None or pf is None or not tr.alive:
            return 0
        try:
            return int(pf(list(blocks), list(hashes)))
        except Exception:  # noqa: BLE001 — a lost hint is not an error
            logger.warning("pool prefetch hint failed", exc_info=True)
            return 0

    # sync conveniences with a closed-feeder fallback: shutdown races
    # degrade to the inline (pre-feeder) codec call, never to an error
    def hash_or_direct(self, blocks: Sequence[bytes]):
        try:
            return self.submit_hash(blocks).result()
        except FeederClosed:
            return self.codec.batch_hash(list(blocks))

    def encode_or_direct(self, blocks: Sequence[bytes]) -> np.ndarray:
        try:
            return self.submit_encode(blocks).result()
        except FeederClosed:
            return self.codec.rs_encode_blocks(list(blocks))

    def decode_or_direct(self, shards: np.ndarray, present: Sequence[int],
                         rows: Optional[Sequence[int]] = None,
                         cls: str = "fg") -> np.ndarray:
        try:
            return self.submit_decode(shards, present, rows,
                                      cls=cls).result()
        except FeederClosed:
            return self.codec.rs_reconstruct(shards, present, rows)

    async def scrub_async(self, blocks: Sequence[bytes], hashes: Sequence,
                          want_parity: bool = True):
        import asyncio

        try:
            fut = self.submit_scrub(blocks, hashes, want_parity)
        except FeederClosed:
            return await asyncio.to_thread(
                self.codec.scrub_encode_batch, list(blocks), list(hashes),
                want_parity)
        return await asyncio.wrap_future(fut)

    async def hash_async(self, blocks: Sequence[bytes],
                         peers: Optional[int] = None):
        import asyncio

        try:
            fut = self.submit_hash(blocks, peers=peers)
        except FeederClosed:
            return await asyncio.to_thread(
                self.codec.batch_hash, list(blocks))
        return await asyncio.wrap_future(fut)

    async def decode_async(self, shards: np.ndarray,
                           present: Sequence[int],
                           rows: Optional[Sequence[int]] = None,
                           cls: str = "fg"):
        """`cls="bg"` puts the decode behind foreground work in the
        queue — the repair planner submits rebuild-storm decodes there
        so a full-node heal coalesces into ragged batches without
        cutting ahead of client reads."""
        import asyncio

        try:
            fut = self.submit_decode(shards, present, rows, cls=cls)
        except FeederClosed:
            return await asyncio.to_thread(
                self.codec.rs_reconstruct, shards, present, rows)
        return await asyncio.wrap_future(fut)

    # --- dispatcher --------------------------------------------------------

    def _drain_locked(self) -> List[_Item]:
        """Pop submissions up to max_batch_blocks (at least one — a
        single oversized submission dispatches alone rather than
        deadlocking); remainder stays queued for the next batch."""
        batch: List[_Item] = []
        blocks = 0
        while self._pending and (not batch
                                 or blocks + self._pending[0].blocks
                                 <= self.max_batch_blocks):
            it = self._pending.popleft()
            self._pending_blocks -= it.blocks
            blocks += it.blocks
            batch.append(it)
        return batch

    def _run(self) -> None:
        register_thread("feeder-dispatch")
        try:
            self._run_inner()
        finally:
            unregister_thread()

    def _run_inner(self) -> None:
        while True:
            with self._cond:
                while not self._pending and not self._closed:
                    self._cond.wait()
                if not self._pending:
                    return  # closed and fully drained
                # Deadline armed by the OLDEST pending submission: work
                # that queued while the previous batch dispatched has
                # already aged past it and goes out immediately.  The
                # peers hint (the S3 layer's in-flight put count) trims
                # the wait from both ends: a PROVABLY lone submit
                # (explicit peers <= 1) dispatches at once — the
                # deadline would be pure added solo latency — and when
                # every pending submitter carries a hint, the wait ends
                # early once as many submissions as the largest peer
                # expectation have arrived.  Unhinted (peers=None)
                # submissions wait out the SLO: concurrency is unknown,
                # so the window is what coalesces a repair storm.
                deadline = self._pending[0].ts + self.slo
                reason = "deadline"
                while not self._closed:
                    if self._pending_blocks >= self.max_batch_blocks:
                        reason = "full"
                        break
                    # the peers short-circuit considers FOREGROUND
                    # submissions only: a co-pending background scrub
                    # (peers=None by design — it coalesces over the full
                    # SLO) must not force the foreground window to wait
                    # the deadline out when all its expected peers have
                    # already arrived
                    fg = [it for it in self._pending if it.cls == "fg"]
                    # a PURELY background window where every submitter
                    # hinted (the Merkle updater's mhash batches pass
                    # peers=1) may short-circuit on its own hints — the
                    # updater blocks on each batch, so sleeping the SLO
                    # out per batch is pure added drain latency.  Scrub
                    # deliberately never hints (peers=None coalesces a
                    # repair storm over the full window), so any
                    # co-pending scrub still holds the deadline.
                    pool = fg if fg else list(self._pending)
                    hints = [it.peers for it in pool]
                    if hints and None not in hints:
                        want = max(hints)
                        if want <= 1:
                            reason = "lone"
                            break
                        if len(pool) >= want:
                            reason = "peers"
                            break
                    left = deadline - time.perf_counter()
                    if left <= 0:
                        break
                    self._cond.wait(timeout=left)
                    if not self._pending:
                        break  # spurious wake after a racing drain
                if not self._pending:
                    continue
                if self._closed:
                    reason = "drain"
                batch = self._drain_locked()
            try:
                self._dispatch(batch, reason)
            except BaseException as e:  # noqa: BLE001
                # belt and braces: _dispatch already routes per-kind
                # errors into futures; anything escaping must not kill
                # the dispatcher while submissions are queued.  Futures
                # already claimed RUNNING cannot be cancel()ed — they
                # must be failed explicitly or their waiters hang.
                logger.exception("feeder dispatch loop error")
                for it in batch:
                    if not it.future.done() and not it.future.cancel():
                        it.future.set_exception(e)

    def _dispatch(self, batch: List[_Item], reason: str) -> None:
        now = time.perf_counter()
        mono = time.monotonic()
        now_ns = time.time_ns()
        by_kind: dict = {}
        for it in batch:
            # claim the future first: a caller-cancelled submission is
            # excluded from the computation entirely
            if not it.future.set_running_or_notify_cancel():
                continue
            it.t_dispatch_ns = now_ns
            if it.deadline is not None and mono >= it.deadline:
                # the submitter's request budget ran out while this sat
                # in the feeder: shed it typed instead of burning codec
                # time on an answer nobody is waiting for
                from ..utils.error import DeadlineExceeded

                self.expired += 1
                it.future.set_exception(DeadlineExceeded(
                    f"codec {it.kind} submission expired in the feeder"))
                continue
            by_kind.setdefault(it.kind, []).append(it)
            if self.m_wait is not None:
                self.m_wait.observe(now - it.ts, kind=it.kind)
        side = getattr(self.codec, "ragged_side", lambda: "cpu")()
        all_items = [it for its in by_kind.values() for it in its]
        if (side == "cpu" and all_items
                and all(it.cls == "bg" for it in all_items)
                and any(it.kind != "mhash" for it in all_items)):
            # a PURELY background batch against a closed/unprobed gate
            # pays the (TTL-cached) link probe — the old stealing feeder
            # probed every scrub pass; with scrub riding this queue the
            # probe rides along, and a healthy link re-opens the device
            # route for THIS batch.  A batch carrying any foreground
            # item never pays it: the probe can cost a full link
            # round-trip and this is the lone dispatcher thread.
            # ...unless the device pool would serve the whole batch:
            # a fully-resident scrub batch moves ZERO link bytes, so
            # probing the link first is a pure 16 MiB cold-probe tax —
            # route it to the device regardless of gate state instead
            tr = getattr(self.codec, "transport", None)
            covers = getattr(tr, "pool_covers", None)
            scrubs = [it for it in all_items if it.kind != "mhash"]
            if (tr is not None and tr.alive and tr.supports("scrub")
                    and covers is not None and covers(scrubs)):
                side = "tpu"
                self.obs.event("feeder_route", reason="pool_resident",
                               blocks=sum(it.blocks for it in scrubs))
            else:
                refresh = getattr(self.codec, "refresh_gate", None)
                if refresh is not None:
                    refresh()
                    side = self.codec.ragged_side()
        if side != self._last_side:
            # route changes are gate decisions: they land in the same
            # event ring as the scrub feeder's probe/gate events
            self.obs.event("feeder_route", reason=side,
                           prev=self._last_side or "none")
            self._last_side = side
        for kind, items in by_kind.items():
            nblocks = sum(it.blocks for it in items)
            self.dispatches += 1
            self.dispatched_blocks += nblocks
            self.dispatch_reasons[reason] = (
                self.dispatch_reasons.get(reason, 0) + 1)
            if self.m_size is not None:
                self.m_size.observe(float(nblocks), kind=kind)
            if self.m_dispatch is not None:
                self.m_dispatch.inc(kind=kind, reason=reason)
            # Device side: hand the whole ragged batch to the zero-copy
            # transport (ops/transport.py) — the feeder is the single
            # producer of its deadline-aware queue, and the transport
            # resolves the items' futures (and counts their bytes) at
            # collect.  A closed/absent transport, or one the device
            # codec cannot serve for this kind, dispatches inline below.
            if side == "tpu" and kind != "mhash":
                # mhash (Merkle BLAKE2b) is CPU-only by contract — the
                # device kernel hashes BLAKE2s and must never route a
                # trie batch (see BlockCodec.mhash_batch)
                tr = getattr(self.codec, "transport", None)
                if tr is not None and tr.alive and tr.supports(kind):
                    try:
                        tr.submit_items(kind, items)
                        self.obs.timeline.event(
                            f"handoff {kind}", "feeder",
                            time.monotonic_ns(), cat="feeder",
                            blocks=nblocks, reason=reason)
                        continue
                    except Exception:  # noqa: BLE001 — degrade inline
                        logger.warning(
                            "transport submit failed; dispatching "
                            "ragged %s batch inline", kind, exc_info=True)
            if kind == "scrub":
                # inline scrub compute runs off the dispatcher thread
                with self._scrub_cond:
                    if self._scrub_q is None:
                        self._scrub_q = collections.deque()
                        self._scrub_thread = threading.Thread(
                            target=self._scrub_worker,
                            name="codec-feeder-scrub", daemon=True)
                        self._scrub_thread.start()
                    self._scrub_q.append((items, side))
                    self._scrub_cond.notify_all()
                continue
            t_disp_mono = time.monotonic_ns()
            t_disp_ns = time.time_ns()
            # metadata hashing is CPU-side even when the gate is open —
            # its bytes must not count as device traffic
            kside = "cpu" if kind == "mhash" else side
            try:
                with self.obs.stage("feeder_dispatch", kside):
                    if kind == "hash":
                        results = self.codec.hash_ragged(
                            [it.payload for it in items])
                    elif kind == "mhash":
                        results = self.codec.mhash_ragged(
                            [it.payload for it in items])
                    elif kind == "encode":
                        results = self.codec.rs_encode_ragged(
                            [it.payload for it in items])
                    else:
                        results = self.codec.rs_reconstruct_ragged(
                            [it.payload for it in items])
                self.obs.add_bytes(kside, sum(it.nbytes for it in items))
            except BaseException as e:  # noqa: BLE001 — fan the error out
                for it in items:
                    if not it.future.done():
                        it.future.set_exception(e)
                continue
            end_ns = time.time_ns()
            self.obs.timeline.event(
                f"dispatch {kind}", "feeder", t_disp_mono,
                time.monotonic_ns(), cat="feeder", blocks=nblocks,
                reason=reason, side=kside)
            tracer = self.obs.tracer
            if tracer is not None:
                # the inline compute is a CHILD of each item's feeder
                # span: the waterfall then splits the feeder envelope
                # into queue wait (queue_s) and codec compute
                for it in items:
                    if it.tctx is not None:
                        tracer.record_span(
                            f"Codec {kind}", it.tctx.trace_id,
                            it.span_id, t_disp_ns, end_ns, side=kside,
                            blocks=nblocks)
            for it, res in zip(items, results):
                if not it.future.done():
                    it.future.set_result(res)
            if len(results) < len(items):
                # a codec returning short must not strand the tail's
                # waiters behind a silently-truncating zip
                err = RuntimeError(
                    f"ragged {kind} returned {len(results)} results "
                    f"for {len(items)} submissions")
                for it in items[len(results):]:
                    if not it.future.done():
                        it.future.set_exception(err)

    def _scrub_worker(self) -> None:
        register_thread("feeder-scrub")
        try:
            while True:
                with self._scrub_cond:
                    while not self._scrub_q:
                        self._scrub_cond.wait()
                    job = self._scrub_q.popleft()
                if job is None:
                    return
                self._dispatch_scrub_inline(*job)
        finally:
            unregister_thread()

    def _dispatch_scrub_inline(self, batch: List[_Item],
                               side: str) -> None:
        """Run one inline (non-transport) scrub batch and resolve its
        futures — on the dedicated scrub thread, so the dispatcher stays
        free for foreground batches."""
        try:
            with self.obs.stage("feeder_dispatch", side):
                results = self.codec.scrub_ragged(
                    [(it.payload[0], it.payload[1], it.want_parity)
                     for it in batch])
            self.obs.add_bytes(side, sum(it.nbytes for it in batch))
        except BaseException as e:  # noqa: BLE001 — fan the error out
            for it in batch:
                if not it.future.done():
                    it.future.set_exception(e)
            return
        for it, res in zip(batch, results):
            if not it.future.done():
                it.future.set_result(res)
        if len(results) < len(batch):
            err = RuntimeError(
                f"ragged scrub returned {len(results)} results "
                f"for {len(batch)} submissions")
            for it in batch[len(results):]:
                if not it.future.done():
                    it.future.set_exception(err)

    # --- lifecycle / introspection -----------------------------------------

    def shutdown(self, timeout: float = 15.0) -> None:
        """Refuse new submissions and drain everything already accepted.
        Idempotent; safe without a thread (nothing was ever submitted)."""
        with self._cond:
            already = self._closed
            self._closed = True
            pending = len(self._pending)
            t = self._thread
            self._cond.notify_all()
        if not already:
            self.obs.event("feeder_drain", reason="shutdown",
                           pending=pending)
        if t is not None:
            t.join(timeout)
            if t.is_alive():
                logger.warning(
                    "codec feeder drain did not finish within %.1fs", timeout)
        st = self._scrub_thread
        if st is not None:
            # the dispatcher has drained: any inline scrub jobs are
            # already queued — drain them BOUNDED (a wedged codec call
            # must not hang node shutdown; unresolved futures then fall
            # to the callers' *_or_direct/async fallbacks)
            with self._scrub_cond:
                self._scrub_q.append(None)  # sentinel: exit after drain
                self._scrub_cond.notify_all()
            st.join(timeout)
            if st.is_alive():
                logger.warning(
                    "codec feeder scrub drain did not finish within "
                    "%.1fs", timeout)

    def stats(self) -> dict:
        with self._cond:
            return {
                "depth": len(self._pending),
                "max_depth_seen": self.max_depth_seen,
                "inflight_requests": self._inflight,
                "submits": self.submits,
                "expired": self.expired,
                "dispatches": self.dispatches,
                "dispatched_blocks": self.dispatched_blocks,
                "dispatch_reasons": dict(self.dispatch_reasons),
                "slo_ms": self.slo * 1000.0,
                "max_batch_blocks": self.max_batch_blocks,
                "closed": self._closed,
            }


class _RequestScope:
    """Brackets one foreground request (`with feeder.request_scope():`)
    so the feeder's in-flight count stays honest — that count is the
    `peers` hint submitters pass, i.e. how many submissions the
    dispatcher may expect to coalesce before the SLO deadline.  Entry
    and exit are a counter bump under the feeder lock; safe across
    await points (the count, not the scope, is thread-affine-free)."""

    __slots__ = ("feeder",)

    def __init__(self, feeder: CodecFeeder):
        self.feeder = feeder

    def __enter__(self) -> CodecFeeder:
        with self.feeder._cond:
            self.feeder._inflight += 1
        return self.feeder

    def __exit__(self, *exc) -> bool:
        with self.feeder._cond:
            self.feeder._inflight -= 1
        return False
