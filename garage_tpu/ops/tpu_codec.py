"""TPU BlockCodec — JAX implementation of the batch block ops.

Design (TPU-first, per SURVEY.md §7):
  - BLAKE2s integrity hashing: vectorized uint32 scan, one lane per block
    (tpu_blake2s.py).  The scrub worker's read→verify step (ref
    block/repair.rs:438-490) becomes read→batch→one device dispatch.
  - Reed-Solomon GF(2^8) encode/reconstruct: the Cauchy generator matrix is
    expanded to a GF(2) bit-matrix W (gf256.bitmatrix_of_gf_matrix), so
    encoding is  parity_bits = (data_bits @ W) & 1  — an int8→int32 matmul
    XLA tiles onto the MXU, batched over every byte position of every shard
    group in the batch.
  - Static shapes: inputs are padded to the configured batch size and block
    size so every scrub/resync step hits the same compiled executable
    (XLA retrace avoidance); pad lanes are masked out of results.
  - Multi-chip: `sharded_fns(mesh)` returns the same ops jitted with batch
    dims sharded over a `jax.sharding.Mesh` — codec batches scale across
    chips with XLA inserting the (trivial, batch-parallel) collectives;
    a psum'd corruption count demonstrates the cross-chip reduction.

Bit-identical to CpuCodec (tests/test_codec_equivalence.py).
"""

from __future__ import annotations

import functools
import time
from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..utils.data import Hash
from . import gf256
from .codec import BlockCodec, CodecParams
from .tpu_blake2s import blake2s_batch, digests_to_bytes

# --- pure jittable kernels --------------------------------------------------


def unpack_bits(x: jax.Array) -> jax.Array:
    """uint8 (..., n) → int8 (..., n*8) bits LSB-first (matmul operand)."""
    shifts = jnp.arange(8, dtype=jnp.uint8)
    bits = (x[..., None] >> shifts) & jnp.uint8(1)
    return bits.reshape(x.shape[:-1] + (-1,)).astype(jnp.int8)


def pack_bits(b: jax.Array) -> jax.Array:
    """int32/int8 0-1 bits (..., n*8) → uint8 (..., n) LSB-first."""
    g = b.reshape(b.shape[:-1] + (-1, 8)).astype(jnp.uint8)
    weights = (jnp.uint8(1) << jnp.arange(8, dtype=jnp.uint8))
    return (g * weights).sum(axis=-1, dtype=jnp.uint32).astype(jnp.uint8)


def gf_bitmatmul(shards: jax.Array, w_bits: jax.Array) -> jax.Array:
    """Apply a GF(2^8) matrix in the bit domain.

    shards (B, k, S) uint8;  w_bits (k*8, r*8) int8 from
    gf256.bitmatrix_of_gf_matrix.  Returns (B, r, S) uint8.
    The contraction runs as int8×int8→int32 on the MXU; parity = count & 1.
    """
    bits = unpack_bits(jnp.swapaxes(shards, -1, -2))     # (B, S, k*8)
    acc = jax.lax.dot_general(
        bits, w_bits,
        dimension_numbers=(((bits.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32,
    )
    out = pack_bits(acc & 1)                             # (B, S, r)
    return jnp.swapaxes(out, -1, -2)


def gf_mask_consts(mat: np.ndarray) -> np.ndarray:
    """GF(2^8) matrix (r, k) → (r, k, 8) uint32 constants for the mask-XOR
    kernel: entry [p,i,b] = gf_mul(mat[p,i], 1<<b) replicated into all 4
    bytes of a uint32 lane."""
    r, k = mat.shape
    K = np.zeros((r, k, 8), np.uint32)
    for p in range(r):
        for i in range(k):
            for b in range(8):
                K[p, i, b] = gf256.gf_mul(int(mat[p, i]), 1 << b) * 0x01010101
    return K


def gf_apply(shards_u32: jax.Array, K: jax.Array) -> jax.Array:
    """Apply a GF(2^8) matrix via bit-mask XOR accumulation — the fast path.

    shards_u32 (B, k, S4) uint32 (4 data bytes per lane); K (r, k, 8)
    uint32 from gf_mask_consts.  Returns (B, r, S4) uint32.

    gfmul-by-constant is GF(2)-linear in the input bits:
    gfmul(c, x) = XOR_b bit_b(x) · gfmul(c, 2^b).  Each term is computed
    bytewise in uint32 lanes: ((x >> b) & 0x01010101) * 0xFF broadcasts
    bit b of every byte to a full-byte mask (no cross-byte carries), which
    then selects the constant gfmul(c, 2^b).  Pure VPU shift/and/mul/xor —
    no gathers, no MXU, ~700 vector ops total for RS(8,4).  (The earlier
    bit-matmul formulation unpacked to int8 bit-planes and ran a (…,64)×
    (64,32) MXU contraction: 16× data expansion and tiny matmul dims made
    it memory-shuffle-bound.)
    """
    r, k, _ = K.shape
    one = jnp.uint32(0x01010101)
    ff = jnp.uint32(0xFF)
    masks = []
    for i in range(k):
        x = shards_u32[:, i]
        masks.append([(((x >> jnp.uint32(b)) & one) * ff) for b in range(8)])
    outs = []
    for p in range(r):
        acc = jnp.zeros_like(shards_u32[:, 0])
        for i in range(k):
            for b in range(8):
                acc = acc ^ (masks[i][b] & K[p, i, b])
        outs.append(acc)
    return jnp.stack(outs, axis=1)


def bytes_view_u32(x_u8: jax.Array) -> jax.Array:
    """uint8 (..., 4n) → uint32 (..., n) little-endian (byte j of each lane
    = input byte 4i+j, matching pack order in u32_view_bytes).  Bitcast:
    a relayout, not arithmetic — see tpu_blake2s.bytes_to_words."""
    from .tpu_blake2s import bytes_to_words

    return bytes_to_words(x_u8)


def u32_view_bytes(x_u32: jax.Array) -> jax.Array:
    """Inverse of bytes_view_u32."""
    from .tpu_blake2s import _BITCAST_PACK

    if _BITCAST_PACK:
        out = jax.lax.bitcast_convert_type(x_u32, jnp.uint8)
        return out.reshape(x_u32.shape[:-1] + (-1,))
    parts = jnp.stack(
        [(x_u32 >> jnp.uint32(8 * j)).astype(jnp.uint8) for j in range(4)],
        axis=-1,
    )
    return parts.reshape(x_u32.shape[:-1] + (-1,))


def verify_kernel(data_u8: jax.Array, lengths: jax.Array, expected: jax.Array):
    """Batched hash + compare: returns ((B,8) digests, (B,) ok, scalar
    corrupt-count) — the scrub hot op."""
    h = blake2s_batch(data_u8, lengths)
    ok = jnp.all(h == expected, axis=-1)
    return h, ok, jnp.sum(~ok, dtype=jnp.int32)


def scrub_step_kernel(data_u8, lengths, expected, K_enc, k: int):
    """The fused scrub hot op — ONE device dispatch per batch: verify all
    B blocks AND produce RS parity for every group of k blocks (north-star
    batch producer, SURVEY.md §3.4).  data_u8 (B, S) with B % k == 0;
    returns (digests, ok, corrupt_count, parity (B//k, r, S))."""
    h, ok, bad = verify_kernel(data_u8, lengths, expected)
    u32 = bytes_view_u32(data_u8)
    groups = u32.reshape(u32.shape[0] // k, k, u32.shape[-1])
    parity = u32_view_bytes(gf_apply(groups, K_enc))
    return h, ok, bad, parity


# --- codec ------------------------------------------------------------------


# Pallas demotion policy: errors matching these markers mean the backend
# simply cannot run Mosaic kernels — retrying is pointless.  Anything
# else (tunnel UNAVAILABLE, DEADLINE_EXCEEDED, connection reset) is
# transient and only demotes after this many CONSECUTIVE failures.
PALLAS_MAX_TRANSIENT_FAILS = 5
_PALLAS_PERMANENT_MARKERS = (
    "mosaic", "not implemented", "unimplemented", "unsupported",
    "no registered", "cannot lower", "interpret mode",
)


def _pallas_error_is_permanent(e: BaseException) -> bool:
    msg = f"{type(e).__name__}: {e}".lower()
    if isinstance(e, NotImplementedError):
        return True
    return any(s in msg for s in _PALLAS_PERMANENT_MARKERS)


class TpuCodec(BlockCodec):
    def __init__(self, params: CodecParams, devices: Optional[list] = None,
                 metrics=None, tracer=None, observer=None):
        super().__init__(params, metrics=metrics, tracer=tracer,
                         observer=observer)
        if params.hash_algo != "blake2s":
            raise ValueError(
                "TpuCodec offloads blake2s only; set codec.hash_algo='blake2s' "
                f"(got {params.hash_algo!r})"
            )
        if params.rs_data > 0:
            pm = gf256.rs_parity_matrix(params.rs_data, params.rs_parity)
            self._enc_mat = pm
            self._K_enc = jnp.asarray(gf_mask_consts(pm))
        self._decode_w_cache = {}
        # Pallas GF kernels (north star): VMEM-resident mask-XOR apply,
        # one HBM read per input byte.  Built lazily per matrix; the
        # first runtime failure (a backend without Mosaic support)
        # permanently falls back to the XLA kernel.
        self._pallas_cache = {}
        self._pallas_ok = True
        self._pallas_transient_fails = 0
        # Pallas fused scrub (blake2s hash state resident in VMEM across
        # chunks + Pallas GF parity): 117 GiB/s at 1024 lanes on v5e vs
        # the XLA scan's 4.3 (scripts/blake2s_tune.py, slope-timed on
        # the real chip) — the scan was bound by per-chunk state
        # round-trips through HBM.  Separate latch from the GF kernel;
        # same permanent/transient demotion policy.
        self._pallas_fused_ok = True
        self._pallas_fused_fails = 0
        self._scrub_pallas_jit = None
        # which fused-scrub variant produced the LAST submission — the
        # caller snapshots this right after scrub_submit and passes it
        # back into note_sync_{success,failure} so sync-time failures
        # (surfacing only at np.asarray) demote the right latch
        self.last_submit_variant = "xla"
        # LinkProfiler boundary stamps (ops/link_profiler.py): the
        # transport clears these before each submit/collect and reads
        # them after, so adopt (dlpack/device_put) time and the compile
        # vs steady-state dispatch split are attributable without the
        # transport reaching into JAX.  last_submit_compiled is a
        # first-call-per-(kind, shape) proxy for "this dispatch paid an
        # XLA compile" — jit caches by shape+dtype, so a fresh shape on
        # a warm function is the compile case worth splitting out.
        self.last_adopt_ns = 0
        self.last_ready_ns = 0
        self.last_submit_compiled = False
        self._dispatched_shapes = set()
        self.mesh = None
        if params.shard_mesh > 1:
            devs = (devices or jax.devices())[: params.shard_mesh]
            if len(devs) >= params.shard_mesh:
                self.mesh = jax.sharding.Mesh(np.array(devs), ("data",))
            else:
                import logging

                logging.getLogger("garage_tpu.ops").warning(
                    "codec.shard_mesh=%d but only %d devices; running "
                    "single-device", params.shard_mesh, len(devs),
                )
        if self.mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P

            batch = NamedSharding(self.mesh, P("data"))
            repl = NamedSharding(self.mesh, P())
            self._hash_jit = jax.jit(
                blake2s_batch, in_shardings=(batch, batch), out_shardings=batch
            )
            self._verify_jit = jax.jit(
                verify_kernel,
                in_shardings=(batch, batch, batch),
                out_shardings=(batch, batch, repl),
            )
            self._gf_jit = jax.jit(
                gf_apply, in_shardings=(batch, repl), out_shardings=batch
            )
            # static k passed POSITIONALLY: pjit rejects kwargs when
            # in_shardings is given, so static_argnums — not
            # static_argnames — is the only shape that works on both the
            # sharded and single-device builds (caught by the daemon-
            # level sharded scrub test; the kwarg form compiled fine
            # single-device and exploded only on a real mesh)
            self._scrub_jit = jax.jit(
                scrub_step_kernel,
                static_argnums=(4,),
                in_shardings=(batch, batch, batch, repl),
                out_shardings=(batch, batch, repl, batch),
            )
        else:
            self._hash_jit = jax.jit(blake2s_batch)
            self._verify_jit = jax.jit(verify_kernel)
            self._gf_jit = jax.jit(gf_apply)
            self._scrub_jit = jax.jit(scrub_step_kernel, static_argnums=(4,))

    def ragged_side(self) -> str:
        """Feeder attribution: a bare TpuCodec runs every ragged batch
        on the device (routing belongs to HybridCodec)."""
        return "tpu"

    # --- the transport device API (ops/transport.py) ---
    #
    # DeviceTransport stages ragged batches ONCE into reusable host
    # buffers and hands them over through these array-level entry
    # points — no bytes-list repacking, no second pad pass.  The
    # staging buffer is adopted zero-copy via dlpack where host and
    # device share memory (CPU backend, unified hosts); elsewhere
    # device_put is the H2D DMA, which is not a host copy.  The
    # transport's slot discipline guarantees a staging buffer is never
    # rewritten while a dispatch that adopted it is still in flight.

    def staging_geometry(self, nlanes: int, maxlen: int,
                         kind: str) -> Tuple[int, int]:
        """(lanes, row_bytes) the transport must stage for a batch of
        `nlanes` blocks of up to `maxlen` bytes: the compiled-executable
        shape (power-of-two bucketing for XLA retrace avoidance, lane
        alignment to whole codewords — and codewords-per-device when
        sharded — for the fused scrub kernel's parity output)."""
        cols = self._bucket(max(maxlen, 1))
        if kind in ("scrub", "encode"):
            lanes = self._batch_size(max(nlanes, 1))
            lanes += (-lanes) % self._lane_align()
        else:
            lanes = self._batch_size(max(nlanes, 1))
        return lanes, cols

    def _to_device(self, arr: np.ndarray) -> jax.Array:
        """Adopt a staged host buffer: dlpack zero-copy when the backend
        can alias host memory, device_put (pure DMA) otherwise."""
        try:
            return jnp.from_dlpack(arr)
        except Exception:  # noqa: BLE001 — any dlpack refusal → plain put
            return jnp.asarray(arr)

    def _mark_adopt(self, kind: str, shape) -> None:
        """Stamp the adoption boundary + the compile-vs-dispatch verdict
        for the submission being built (LinkProfiler contract)."""
        self.last_adopt_ns = time.monotonic_ns()
        key = (kind, tuple(shape))
        self.last_submit_compiled = key not in self._dispatched_shapes
        self._dispatched_shapes.add(key)

    def _mark_ready(self, handle) -> None:
        """Block until the device results exist, then stamp the ready
        boundary — everything after this in a collect is pure D2H
        materialization + reassembly (`collect`), everything before it
        since submit-return is device busy (`compute`)."""
        try:
            jax.block_until_ready(handle)
        except Exception:  # noqa: BLE001 — non-jax handles sync at asarray
            pass
        self.last_ready_ns = time.monotonic_ns()

    def probe_submit(self, arr: np.ndarray):
        """The transport's link probe op: upload a staged buffer and
        return a device scalar that DEPENDS on it (the only sync some
        remote backends honor — see HybridCodec._probe_once).  Compute
        is a trivial reduction, so the measured round-trip is
        transfer-bound like the retired probe, but through the NEW
        staging/adoption path."""
        if not hasattr(self, "_probe_sum_jit"):
            self._probe_sum_jit = jax.jit(
                lambda x: jnp.sum(x, dtype=jnp.uint32))
        da = self._to_device(arr)
        self._mark_adopt("probe", arr.shape)
        return self._probe_sum_jit(da)

    def probe_collect(self, handle) -> int:
        self._mark_ready(handle)
        return int(np.asarray(handle))

    def hash_submit(self, arr: np.ndarray, lengths: np.ndarray):
        """Enqueue a staged hash batch WITHOUT synchronizing; returns
        the device digest array handle for hash_collect."""
        with self.obs.stage("h2d_transfer", "tpu"):
            da = self._to_device(arr)
            dl = jnp.asarray(lengths)
        self._mark_adopt("hash", arr.shape)
        with self.obs.stage("kernel_dispatch", "tpu"):
            return self._hash_jit(da, dl)

    def hash_collect(self, handle, n: int) -> List[Hash]:
        self._mark_ready(handle)
        h = np.asarray(handle)[:n]
        return [Hash(d) for d in digests_to_bytes(h)]

    def scrub_collect(self, out, fetch_parity: bool):
        """Materialize one scrub_encode_submit result: (ok full-lane
        bool array, parity full array | None) — per-entry trimming is
        the transport's job (it knows the lane spans)."""
        _h, ok, _bad, parity = out
        self._mark_ready((ok, parity) if fetch_parity else ok)
        ok = np.asarray(ok)
        parity_np = np.asarray(parity) if fetch_parity else None
        return ok, parity_np

    def _gf_submit(self, u32, K, mat: np.ndarray):
        """Dispatch one GF apply WITHOUT synchronizing, preferring the
        Pallas kernel with the same demotion policy as _gf_apply_np
        (a backend without Mosaic support must fall back to the XLA
        kernel, not fail the transport's batch).  Pallas failures that
        would only surface at sync time are the transport's
        note_sync_failure path."""
        pg = self._pallas_for(mat)
        if pg is not None:
            try:
                return pg(u32)
            except Exception as e:
                import logging

                if _pallas_error_is_permanent(e):
                    logging.getLogger("garage_tpu.ops").warning(
                        "pallas GF kernel unsupported on this backend "
                        "(permanent); using the XLA kernel", exc_info=True)
                    self._pallas_ok = False
                    self.obs.event("gf_demote", reason="permanent",
                                   error=f"{type(e).__name__}: {e}"[:200])
                else:
                    self._pallas_transient_fails += 1
                    if (self._pallas_transient_fails
                            >= PALLAS_MAX_TRANSIENT_FAILS):
                        self._pallas_ok = False
                        self.obs.event("gf_demote",
                                       reason="transient_limit",
                                       fails=self._pallas_transient_fails)
        return self._gf_jit(u32, K)

    def encode_submit(self, groups: np.ndarray):
        """Enqueue RS parity for staged (B, k, S) codeword groups
        without synchronizing (S must be a multiple of 4 — guaranteed
        by staging_geometry's bucketing).  Returns a lazily-viewed
        device array; np.asarray (encode_collect) is the sync."""
        assert groups.shape[-1] % 4 == 0, groups.shape
        with self.obs.stage("h2d_transfer", "tpu"):
            u32 = bytes_view_u32(self._to_device(
                groups.reshape(-1, groups.shape[-2], groups.shape[-1])))
        self._mark_adopt("encode", groups.shape)
        with self.obs.stage("kernel_dispatch", "tpu"):
            return u32_view_bytes(self._gf_submit(u32, self._K_enc,
                                                  self._enc_mat))

    def encode_collect(self, handle) -> np.ndarray:
        self._mark_ready(handle)
        return np.asarray(handle)

    def decode_submit(self, shards: np.ndarray, present: Sequence[int],
                      rows: Optional[Sequence[int]] = None):
        """Enqueue one survivor-pattern decode over staged (B, p, S)
        shards without synchronizing; shares rs_reconstruct's
        mask-constant schedule cache."""
        k, m = self.params.rs_data, self.params.rs_parity
        key = (tuple(present[:k]), tuple(rows) if rows is not None else None)
        cached = self._decode_w_cache.get(key)
        if cached is None:
            dec = gf256.rs_decode_matrix(k, m, present)
            if rows is not None:
                dec = np.ascontiguousarray(dec[list(rows)])
            cached = (jnp.asarray(gf_mask_consts(dec)), dec)
            self._decode_w_cache[key] = cached
        K, dec_mat = cached
        sub = shards[..., :k, :]
        s = sub.shape[-1]
        pad = (-s) % 4
        if pad:
            sub = np.pad(sub, [(0, 0)] * (sub.ndim - 1) + [(0, pad)])
        with self.obs.stage("h2d_transfer", "tpu"):
            u32 = bytes_view_u32(self._to_device(
                np.ascontiguousarray(sub)))
        self._mark_adopt("decode", (*sub.shape, *key[0]))
        with self.obs.stage("kernel_dispatch", "tpu"):
            return u32_view_bytes(self._gf_submit(u32, K, dec_mat))[..., :s]

    def decode_collect(self, handle) -> np.ndarray:
        """Ready-stamped decode materialization (the transport prefers
        this over a bare np.asarray so `compute` vs `collect` split
        holds for decode batches too)."""
        self._mark_ready(handle)
        return np.asarray(handle)

    # --- hashing ---
    @staticmethod
    def _bucket(n: int, quantum: int = 64) -> int:
        """Round up to a power-of-two multiple of `quantum` so variable-size
        batches land in O(log) distinct compiled shapes instead of one per
        length (XLA retrace avoidance)."""
        n = max(n, quantum)
        b = quantum
        while b < n:
            b <<= 1
        return b

    def _lane_align(self) -> int:
        """Lane-count divisor shared by _pad_group and warm_scrub: whole
        codewords (k), and — when sharded — whole codewords per device
        (k × mesh, so the fused kernel's parity output dim B//k divides
        over the mesh).  One helper so the AOT-warmed shape can never
        drift from the dispatched one."""
        k = max(1, self.params.rs_data)
        return k * (self.mesh.size if self.mesh is not None else 1)

    def _batch_size(self, n: int) -> int:
        bsz = self._bucket(n, 8)
        if self.mesh is not None:
            m = self.mesh.size
            bsz += (-bsz) % m  # batch axis must divide over the mesh
        return bsz

    def _pad_batch(self, blocks: Sequence[bytes]) -> Tuple[np.ndarray, np.ndarray]:
        maxlen = max((len(b) for b in blocks), default=0)
        padded = self._bucket(maxlen)
        bsz = self._batch_size(len(blocks))
        arr = np.zeros((bsz, padded), dtype=np.uint8)
        lengths = np.zeros((bsz,), dtype=np.int32)
        for i, b in enumerate(blocks):
            arr[i, : len(b)] = np.frombuffer(b, dtype=np.uint8)
            lengths[i] = len(b)
        return arr, lengths

    def batch_hash(self, blocks: Sequence[bytes]) -> List[Hash]:
        if not blocks:
            return []
        arr, lengths = self._pad_batch(blocks)
        h = np.asarray(self._hash_jit(jnp.asarray(arr), jnp.asarray(lengths)))
        return [Hash(d) for d in digests_to_bytes(h[: len(blocks)])]

    def verify_one(self, block: bytes, hash: Hash) -> bool:
        """Single-block verify stays on the host CPU: one block cannot
        amortize a device dispatch (the accelerator may sit behind a
        high-latency link), and hashlib.blake2s is bit-identical to the
        device kernel (tests/test_codec_equivalence.py).  Batched paths
        (scrub/resync) run on device via batch_verify/scrub_encode."""
        import hashlib

        return hashlib.blake2s(block, digest_size=32).digest() == bytes(hash)

    def batch_verify(self, blocks: Sequence[bytes], hashes: Sequence[Hash]) -> np.ndarray:
        if len(blocks) != len(hashes):
            raise ValueError(f"{len(blocks)} blocks vs {len(hashes)} hashes")
        if not blocks:
            return np.zeros((0,), dtype=bool)
        arr, lengths = self._pad_batch(blocks)
        # Pad lanes (length 0) get the empty-message digest as their
        # expectation so they pass verify and don't inflate the corrupt count.
        import hashlib

        empty = np.frombuffer(
            hashlib.blake2s(b"", digest_size=32).digest(), dtype="<u4"
        )
        expected = np.broadcast_to(empty, (arr.shape[0], 8)).copy()
        expected[: len(blocks)] = np.stack(
            [np.frombuffer(bytes(h), dtype="<u4") for h in hashes]
        )
        _, ok, _ = self._verify_jit(
            jnp.asarray(arr), jnp.asarray(lengths), jnp.asarray(expected)
        )
        return np.asarray(ok)[: len(blocks)]

    # --- Reed-Solomon ---
    def _flat_padded(self, arr: np.ndarray) -> Tuple[np.ndarray, int]:
        """Flatten leading dims to one batch axis padded for the mesh."""
        flat = np.ascontiguousarray(arr, dtype=np.uint8).reshape(
            (-1,) + arr.shape[-2:]
        )
        n = flat.shape[0]
        bsz = self._batch_size(n) if self.mesh is not None else n
        if bsz != n:
            flat = np.concatenate(
                [flat, np.zeros((bsz - n,) + flat.shape[1:], dtype=np.uint8)]
            )
        return flat, n

    def _pallas_for(self, mat: np.ndarray):
        """PallasGf for this matrix, or None (unsupported backend)."""
        if not self._pallas_ok:
            return None
        key = mat.tobytes()
        pg = self._pallas_cache.get(key)
        if pg is None:
            from .pallas_gf import PallasGf

            pg = PallasGf(mat)
            self._pallas_cache[key] = pg
        return pg

    def _gf_apply_np(self, flat: np.ndarray, K,
                     mat: Optional[np.ndarray] = None) -> np.ndarray:
        """(N, k, S) uint8 through the mask-XOR kernel; S padded to ×4 for
        the uint32 view, result truncated back.  Prefers the Pallas
        kernel when the matrix is known and the backend supports Mosaic;
        falls back to the XLA formulation."""
        s = flat.shape[-1]
        pad = (-s) % 4
        if pad:
            flat = np.pad(flat, [(0, 0)] * (flat.ndim - 1) + [(0, pad)])
        u32 = bytes_view_u32(jnp.asarray(flat))
        if mat is not None:
            pg = self._pallas_for(mat)
            if pg is not None:
                try:
                    out = np.asarray(u32_view_bytes(pg(u32)))[..., :s]
                    # reset only after the host-side materialization
                    # proved the kernel ran (same rule as the fused
                    # latch, round-5 ADVICE #1)
                    self._pallas_transient_fails = 0
                    return out
                except Exception as e:
                    import logging

                    log = logging.getLogger("garage_tpu.ops")
                    # Latch OFF only for errors that cannot heal: a
                    # backend without Mosaic support will never grow it,
                    # but a flaky tunnel (UNAVAILABLE / DEADLINE / RESET)
                    # recovers — permanently demoting the north-star
                    # kernel on one transient hiccup wasted the rest of
                    # the process lifetime (advisor r3 / VERDICT #8).
                    if _pallas_error_is_permanent(e):
                        log.warning(
                            "pallas GF kernel unsupported on this backend "
                            "(permanent); using the XLA kernel",
                            exc_info=True)
                        self._pallas_ok = False
                        self.obs.event("gf_demote", reason="permanent",
                                       error=f"{type(e).__name__}: {e}"[:200])
                    else:
                        self._pallas_transient_fails += 1
                        if (self._pallas_transient_fails
                                >= PALLAS_MAX_TRANSIENT_FAILS):
                            log.warning(
                                "pallas GF kernel failed %d consecutive "
                                "times; demoting to the XLA kernel",
                                self._pallas_transient_fails, exc_info=True)
                            self._pallas_ok = False
                            self.obs.event(
                                "gf_demote", reason="transient_limit",
                                fails=self._pallas_transient_fails)
                        else:
                            log.warning(
                                "pallas GF kernel transient failure "
                                "(%d/%d); will retry",
                                self._pallas_transient_fails,
                                PALLAS_MAX_TRANSIENT_FAILS, exc_info=True)
        out = u32_view_bytes(self._gf_jit(u32, K))
        return np.asarray(out)[..., :s]

    def rs_encode(self, data: np.ndarray) -> np.ndarray:
        assert data.shape[-2] == self.params.rs_data, data.shape
        lead = data.shape[:-2]
        flat, n = self._flat_padded(data)
        out = self._gf_apply_np(flat, self._K_enc, mat=self._enc_mat)[:n]
        return out.reshape(lead + out.shape[-2:])

    def rs_reconstruct(self, shards: np.ndarray, present: Sequence[int],
                       rows: Optional[Sequence[int]] = None) -> np.ndarray:
        k, m = self.params.rs_data, self.params.rs_parity
        key = (tuple(present[:k]), tuple(rows) if rows is not None else None)
        cached = self._decode_w_cache.get(key)
        if cached is None:
            dec = gf256.rs_decode_matrix(k, m, present)
            if rows is not None:
                dec = np.ascontiguousarray(dec[list(rows)])
            cached = (jnp.asarray(gf_mask_consts(dec)), dec)
            self._decode_w_cache[key] = cached
        K, dec_mat = cached
        lead = shards.shape[:-2]
        flat, n = self._flat_padded(shards[..., :k, :])
        out = self._gf_apply_np(flat, K, mat=dec_mat)[:n]
        return out.reshape(lead + out.shape[-2:])

    # --- fused pipelined scrub (the north-star hot path) ---

    def _pad_group(self, blocks: Sequence[bytes], hashes: Sequence[Hash]):
        """Pad a block group to the compiled lane/byte shape: (arr, lengths,
        expected) with pad lanes carrying the empty-message digest so they
        verify clean and don't inflate the corruption count."""
        import hashlib as _hl

        arr, lengths = self._pad_batch(blocks)
        k = self.params.rs_data
        # lanes align to k (whole codewords) AND, when sharded, to
        # k × mesh — the fused kernel's PARITY output has leading dim
        # B//k, and its out_sharding over the mesh needs that divisible
        # by the device count (caught by the daemon-level sharded-scrub
        # test: lanes alone being mesh-divisible is not enough)
        align = self._lane_align()
        pad_lanes = (-arr.shape[0]) % align
        if pad_lanes:
            arr = np.pad(arr, [(0, pad_lanes), (0, 0)])
            lengths = np.pad(lengths, (0, pad_lanes))
        empty = np.frombuffer(
            _hl.blake2s(b"", digest_size=32).digest(), dtype="<u4"
        )
        expected = np.broadcast_to(empty, (arr.shape[0], 8)).copy()
        expected[: len(blocks)] = np.stack(
            [np.frombuffer(bytes(h), dtype="<u4") for h in hashes]
        )
        return arr, lengths, expected

    def _scrub_pallas(self):
        """The fused scrub jit with BOTH hot ops as Pallas kernels: the
        VMEM-resident blake2s (pallas_blake2s.py) and the GF mask-XOR
        apply (pallas_gf.py) when the latter's latch is up."""
        if self._scrub_pallas_jit is None:
            from .pallas_blake2s import blake2s_batch_pallas

            pg = self._pallas_for(self._enc_mat)

            def fused(data_u8, lengths, expected, K_enc, k):
                h = blake2s_batch_pallas(data_u8, lengths)
                ok = jnp.all(h == expected, axis=-1)
                bad = jnp.sum(~ok, dtype=jnp.int32)
                u32 = bytes_view_u32(data_u8)
                groups = u32.reshape(u32.shape[0] // k, k, u32.shape[-1])
                if pg is not None:
                    parity = u32_view_bytes(pg(groups))
                else:
                    parity = u32_view_bytes(gf_apply(groups, K_enc))
                return h, ok, bad, parity

            self._scrub_pallas_jit = jax.jit(fused, static_argnums=(4,))
        return self._scrub_pallas_jit

    def _use_pallas_scrub(self, nlanes: int) -> bool:
        """The Pallas fused scrub wants whole (…,128)-lane tiles; smaller
        padded batches (deque tails) run the XLA variant instead of
        paying a 2-16x lane pad across a metered link."""
        return (self._pallas_fused_ok and self.mesh is None
                and nlanes % 128 == 0)

    def _note_fused_failure(self, e: BaseException) -> None:
        import logging

        log = logging.getLogger("garage_tpu.ops")
        if _pallas_error_is_permanent(e):
            log.warning(
                "pallas fused scrub unsupported on this backend "
                "(permanent); using the XLA kernels", exc_info=True)
            self._pallas_fused_ok = False
            self.obs.event("fused_demote", reason="permanent",
                           error=f"{type(e).__name__}: {e}"[:200])
        else:
            self._pallas_fused_fails += 1
            if self._pallas_fused_fails >= PALLAS_MAX_TRANSIENT_FAILS:
                log.warning(
                    "pallas fused scrub failed %d consecutive times; "
                    "demoting to the XLA kernels",
                    self._pallas_fused_fails, exc_info=True)
                self._pallas_fused_ok = False
                self.obs.event("fused_demote", reason="transient_limit",
                               fails=self._pallas_fused_fails,
                               error=f"{type(e).__name__}: {e}"[:200])
            else:
                log.warning(
                    "pallas fused scrub transient failure (%d/%d); "
                    "will retry", self._pallas_fused_fails,
                    PALLAS_MAX_TRANSIENT_FAILS, exc_info=True)
                self.obs.event("fused_transient",
                               reason=type(e).__name__,
                               fails=self._pallas_fused_fails)

    def note_sync_failure(self, e: BaseException,
                          variant: Optional[str] = None) -> None:
        """Sync-time kernel failure — surfacing at the caller's
        np.asarray (HybridCodec._tpu_collect), long after scrub_submit
        returned.  Routes the failure into the fused-scrub demotion
        latch when the failing submission came from the Pallas variant:
        a consistently sync-failing kernel must demote to the XLA
        fallback instead of silently losing the device side every pass
        (round-5 ADVICE #1)."""
        if (variant or self.last_submit_variant) == "pallas":
            self._note_fused_failure(e)

    def note_sync_success(self, variant: Optional[str] = None) -> None:
        """Successful host-side materialization of a submission — the
        ONLY point the fused-kernel transient-failure counter resets
        (resetting at submit time, before the kernel provably ran,
        defeated the latch: round-5 ADVICE #1)."""
        if (variant or self.last_submit_variant) == "pallas":
            self._pallas_fused_fails = 0

    def scrub_submit(self, blocks: Sequence[bytes], hashes: Sequence[Hash]):
        """Enqueue one group's fused verify+encode WITHOUT synchronizing.

        Returns (ok_dev, parity_dev, n): device arrays plus the true block
        count.  Callers keep several groups in flight to hide the
        host→device link latency (the accelerator may sit behind a
        constrained tunnel), then sync each with `np.asarray(ok_dev)[:n]`.
        """
        with self.obs.stage("host_staging", "tpu"):
            arr, lengths, expected = self._pad_group(blocks, hashes)
        _h, ok, _bad, parity = self.scrub_encode_submit(arr, lengths, expected)
        return ok, parity, len(blocks)

    def warm_scrub(self, nblocks: int, nbytes: int) -> None:
        """AOT-compile the fused scrub executable for the padded shape of an
        (nblocks × nbytes) group and populate the persistent XLA compilation
        cache — without transferring any data (device links may be
        bandwidth-metered, so warmup must not spend bytes)."""
        k = self.params.rs_data
        bsz = self._batch_size(max(nblocks, 1))
        bsz += (-bsz) % self._lane_align()
        padded = self._bucket(max(nbytes, 1))
        shapes = (
            jax.ShapeDtypeStruct((bsz, padded), jnp.uint8),
            jax.ShapeDtypeStruct((bsz,), jnp.int32),
            jax.ShapeDtypeStruct((bsz, 8), jnp.uint32),
            jax.ShapeDtypeStruct(self._K_enc.shape, self._K_enc.dtype),
        )
        if self._use_pallas_scrub(bsz):
            try:
                self._scrub_pallas().lower(*shapes, k).compile()
            except Exception as e:
                self._note_fused_failure(e)
        # ALWAYS warm the XLA variant too: it is the runtime fallback
        # when a Pallas dispatch fails transiently, and a cold fallback
        # means a multi-second mid-pass compile on a remote backend —
        # exactly what warm() exists to prevent
        self._scrub_jit.lower(*shapes, k).compile()

    def scrub_encode_submit(self, arr: np.ndarray, lengths: np.ndarray,
                            expected: np.ndarray):
        """Enqueue ONE device dispatch doing verify + RS(k,m) parity for a
        full batch; returns device arrays WITHOUT synchronizing, so callers
        can pipeline batches and hide the dispatch latency (essential when
        the accelerator sits behind a high-latency tunnel).

        Sets `last_submit_variant` ("pallas"|"xla") for the caller to
        thread into note_sync_{success,failure}: kernel failures surface
        only at sync time, and the demotion latch must attribute them to
        the variant that actually produced the arrays.  The transient-
        failure counter is NOT reset here — a submit returning is proof
        of nothing on an async backend (round-5 ADVICE #1); the reset
        happens in note_sync_success."""
        assert arr.shape[0] % self.params.rs_data == 0
        assert arr.shape[1] % 4 == 0
        with self.obs.stage("h2d_transfer", "tpu"):
            da = jnp.asarray(arr)
            dl = jnp.asarray(lengths)
            de = jnp.asarray(expected)
        self._mark_adopt("scrub", arr.shape)
        if self._use_pallas_scrub(arr.shape[0]):
            try:
                with self.obs.stage("kernel_dispatch", "tpu"):
                    out = self._scrub_pallas()(
                        da, dl, de, self._K_enc, self.params.rs_data,
                    )
                self.last_submit_variant = "pallas"
                return out
            except Exception as e:
                self._note_fused_failure(e)
        with self.obs.stage("kernel_dispatch", "tpu"):
            out = self._scrub_jit(
                da, dl, de, self._K_enc, self.params.rs_data,
            )
        self.last_submit_variant = "xla"
        return out

    # --- the DevicePool API (ops/device_pool.py) ---
    #
    # Pool-aware scrub: only MISS lanes cross the link (one compact
    # H2D upload + a device-side scatter); resident lanes are composed
    # from pool pages — device-resident jnp arrays — entirely on
    # device.  The composed batch runs the SAME fused kernel as the
    # plain path, so pool-served lanes are re-verified against their
    # expected digests on every read.

    def scrub_encode_submit_resident(self, miss_arr: np.ndarray,
                                     miss_rows, lengths: np.ndarray,
                                     expected: np.ndarray, resident):
        """Returns (scrub handle, composed device input) — the input
        ref is what pool_adopt slices verified miss lanes out of."""
        lanes = int(lengths.shape[0])
        cols = int(miss_arr.shape[1])
        assert lanes % self.params.rs_data == 0
        assert cols % 4 == 0
        with self.obs.stage("h2d_transfer", "tpu"):
            full = jnp.zeros((lanes, cols), dtype=jnp.uint8)
            if len(miss_rows):
                dm = self._to_device(
                    np.ascontiguousarray(miss_arr[:len(miss_rows)]))
                idx = jnp.asarray(np.asarray(miss_rows, dtype=np.int32))
                full = full.at[idx].set(dm)
            dl = jnp.asarray(lengths)
            de = jnp.asarray(expected)
        # device-side composition of pool-resident lanes: no host
        # bytes move here — pages are already device arrays
        for r, pages, length in resident:
            row = jnp.concatenate(list(pages))
            if int(row.shape[0]) < cols:
                row = jnp.pad(row, (0, cols - int(row.shape[0])))
            full = full.at[int(r)].set(row[:cols])
        self._mark_adopt("scrub", (lanes, cols))
        if self._use_pallas_scrub(lanes):
            try:
                with self.obs.stage("kernel_dispatch", "tpu"):
                    out = self._scrub_pallas()(
                        full, dl, de, self._K_enc, self.params.rs_data,
                    )
                self.last_submit_variant = "pallas"
                return out, full
            except Exception as e:
                self._note_fused_failure(e)
        with self.obs.stage("kernel_dispatch", "tpu"):
            out = self._scrub_jit(
                full, dl, de, self._K_enc, self.params.rs_data,
            )
        self.last_submit_variant = "xla"
        return out, full

    def pool_adopt(self, input_ref, lane: int, length: int,
                   page_bytes: int):
        """Slice one verified lane of a resident-submitted batch into
        fixed-size device pages (tail zero-padded past the ragged
        length) — device-side slicing of an already-resident array,
        ZERO link bytes."""
        npages = max(1, -(-int(length) // int(page_bytes)))
        total = npages * int(page_bytes)
        row = input_ref[int(lane)]
        if int(row.shape[0]) < total:
            row = jnp.pad(row, (0, total - int(row.shape[0])))
        pages = row[:total].reshape(npages, int(page_bytes))
        return [pages[i] for i in range(npages)]

    def pool_read(self, pages, length: int) -> bytes:
        """D2H readback of a pooled block (tests/debug only), trimmed
        to the ragged tail."""
        return np.concatenate(
            [np.asarray(p) for p in pages])[:int(length)].tobytes()

    def scrub_encode_batch(self, blocks: Sequence[bytes], hashes: Sequence[Hash],
                           fetch_parity: bool = True):
        """Synchronous fused verify+encode.  Contract shared with
        HybridCodec.scrub_encode_batch: returns (ok (B,), parity
        (ceil(B/k), m, maxlen) | None) — parity trimmed of lane/column
        padding (pad rows/columns are zero blocks → zero parity); with
        fetch_parity=False it stays on the device and None is returned."""
        ok, parity, n = self.scrub_submit(blocks, hashes)
        variant = self.last_submit_variant
        try:
            with self.obs.stage("sync_collect", "tpu"):
                ok = np.asarray(ok)[:n]
                parity_np = (np.asarray(parity) if fetch_parity else None)
        except Exception as e:
            self.note_sync_failure(e, variant)
            raise
        self.note_sync_success(variant)
        if not fetch_parity:
            return ok, None
        k = self.params.rs_data
        nrows = (n + k - 1) // k
        maxlen = max(len(b) for b in blocks)
        return ok, parity_np[:nrows, :, :maxlen]


# --- multi-chip sharded variants (dryrun_multichip + pod-scale batches) -----


def sharded_fns(mesh: "jax.sharding.Mesh", axis: str = "data"):
    """Return {verify, rs_encode} jitted with batch dims sharded over `mesh`.

    The codec batch axis is embarrassingly parallel; sharding it over the
    mesh scales scrub/encode throughput linearly over ICI-connected chips.
    `verify` additionally returns a globally psum-reduced corruption count,
    exercising a real cross-chip collective.
    """
    from jax.sharding import NamedSharding, PartitionSpec as P

    batch_sharded = NamedSharding(mesh, P(axis))
    repl = NamedSharding(mesh, P())

    def _verify(data_u8, lengths, expected):
        h, ok, bad = verify_kernel(data_u8, lengths, expected)
        return h, ok, bad

    verify = jax.jit(
        _verify,
        in_shardings=(batch_sharded, batch_sharded, batch_sharded),
        out_shardings=(batch_sharded, batch_sharded, repl),
    )

    def _encode(shards, w_bits):
        return gf_bitmatmul(shards, w_bits)

    rs_encode = jax.jit(
        _encode,
        in_shardings=(batch_sharded, repl),
        out_shardings=batch_sharded,
    )
    return {"verify": verify, "rs_encode": rs_encode}
