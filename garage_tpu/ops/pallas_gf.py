"""Pallas TPU kernel for the GF(2^8) mask-XOR matrix apply.

The north star (BASELINE.json) asks for the Reed-Solomon hot op as a
hand-written TPU kernel rather than XLA-fused jnp.  The XLA formulation
(ops/tpu_codec.py gf_apply) materializes k×8 broadcast masks per output
row across the whole batch in HBM before the XOR-reduce, so the op is
HBM-bound far below the VPU's rate.  This kernel keeps one (k, TILE)
uint32 block of the codeword resident in VMEM and produces all r output
rows from it — each input byte is read from HBM exactly once, all the
mask/select/XOR traffic happens at VMEM bandwidth.

Math (identical to gf_apply, bit-for-bit):
  gfmul(c, x) = XOR_b bit_b(x) · gfmul(c, 2^b)          (GF(2)-linearity)
applied bytewise inside uint32 lanes: m1 = (x >> b) & 0x01010101 puts
bit b of every byte in that byte's low bit; multiplying by the scalar
constant d = gfmul(c_pj, 2^b) (< 256) then yields d in every byte whose
bit was set, with no cross-byte carries (d·1 < 256) — one shift+and per
(input row, bit) and one mul+xor per output row.

The kernel is validated bit-identically against the numpy/XLA versions
in tests (interpret mode — no TPU needed for correctness), and the
device-resident rate comparison against the XLA kernel is printed by
bench.py when the chip is reachable (pallas_gibs vs device_gibs).

Tuned on the real chip (scripts/pallas_tune.py + d2h-synced slope
timing, TPU v5e, 2026-07-31).  Lessons that produced the current form,
all measured on real hardware:
  - loop order: materializing all k*8 bit-plane masks before the output
    loop (the original kernel) is a 64-vector live range that spills —
    ~23 GiB/s at the old tile=512 default;
  - tile size: 8192 u32 columns (12 rows × 32 KiB ≈ 384 KiB/step in
    VMEM) beats both 512 (grid overhead) and ≥32k (VMEM pressure);
  - mask algebra: m1 * d (scalar constant) beats mask-expand-then-AND
    (((x>>b)&one)*0xFF) & K — one fewer vector op per term.
Result: the hand kernel beats the XLA mask-XOR formulation on the same
resident data in every paired run; absolute rates vary with shared-chip
contention — best paired run 154.6 vs 130.0 GiB/s (+19%), a later
DEVICE_CAPTURE.json run under contention 109.5 vs 79.2 (+38%).  (Naive
rep-loop timing through the axon tunnel under fresh burst quota had
reported impossible numbers — 522 GiB/s > HBM roofline — because on
this remote backend block_until_ready can return at enqueue time; all
numbers above use in-dispatch fori_loop reps differenced at two rep
counts and a device→host fetch of a scalar checksum as the sync point.)
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from . import gf256

LANE = 128          # TPU lane width
SUBLANES = 8        # uint32 tile: (8, 128)


def _kernel(k: int, r: int, x_ref, consts_ref, o_ref):
    """One grid step: x_ref (k, T) uint32 codeword slab in VMEM,
    consts_ref (r, k, 8) uint32 SCALAR gf constants (< 256), o_ref
    (r, T) uint32.

    Loop order matters: each bit-plane m1 is computed once and consumed
    by all r accumulators immediately, so only r+1 T-length vectors are
    live at any point.  (Materializing all k*8 masks before the output
    loop spills out of vector registers and runs ~6x slower on v5e.)
    m1 has bytes in {0,1}; multiplying by a byte constant d < 256 yields
    d in every set byte with no cross-byte carries."""
    one = jnp.uint32(0x01010101)
    accs = [jnp.zeros_like(x_ref[0, ...]) for _ in range(r)]
    for i in range(k):
        xi = x_ref[i, ...]
        for b in range(8):
            m1 = (xi >> jnp.uint32(b)) & one
            for p in range(r):
                accs[p] = accs[p] ^ (m1 * consts_ref[p, i, b])
    for p in range(r):
        o_ref[p, ...] = accs[p]


def gf_scalar_consts(mat: np.ndarray) -> np.ndarray:
    """GF(2^8) matrix (r, k) → (r, k, 8) uint32 plain scalar constants
    gfmul(mat[p,i], 2^b) for the multiply-form kernel (contrast
    tpu_codec.gf_mask_consts, whose constants are byte-replicated for
    the mask-AND form used by the XLA path)."""
    r, k = mat.shape
    K = np.zeros((r, k, 8), np.uint32)
    for p in range(r):
        for i in range(k):
            for b in range(8):
                K[p, i, b] = gf256.gf_mul(int(mat[p, i]), 1 << b)
    return K


@functools.partial(jax.jit, static_argnames=("k", "r", "tile", "interpret"))
def _apply_flat(x, consts, k: int, r: int, tile: int, interpret: bool):
    from jax.experimental import pallas as pl

    n = x.shape[-1]
    grid = (n // tile,)
    return pl.pallas_call(
        functools.partial(_kernel, k, r),
        grid=grid,
        in_specs=[
            pl.BlockSpec((k, tile), lambda j: (0, j)),
            pl.BlockSpec((r, k, 8), lambda j: (0, 0, 0)),
        ],
        out_specs=pl.BlockSpec((r, tile), lambda j: (0, j)),
        out_shape=jax.ShapeDtypeStruct((r, n), jnp.uint32),
        interpret=interpret,
    )(x, consts)


def _kernel_batched(k: int, r: int, x_ref, consts_ref, o_ref):
    """Batched variant of _kernel: blocks carry a leading size-1
    codeword axis so the grid walks codewords DIRECTLY in the (B, k,
    S4) layout — a codeword's k rows are contiguous there, eliminating
    the fold-into-columns transpose of the flat path (which cost a full
    HBM round-trip of the batch on the fused scrub path)."""
    one = jnp.uint32(0x01010101)
    accs = [jnp.zeros_like(x_ref[0, 0, ...]) for _ in range(r)]
    for i in range(k):
        xi = x_ref[0, i, ...]
        for b in range(8):
            m1 = (xi >> jnp.uint32(b)) & one
            for p in range(r):
                accs[p] = accs[p] ^ (m1 * consts_ref[p, i, b])
    for p in range(r):
        o_ref[0, p, ...] = accs[p]


@functools.partial(jax.jit, static_argnames=("k", "r", "tile", "interpret"))
def _apply_batched(x, consts, k: int, r: int, tile: int, interpret: bool):
    from jax.experimental import pallas as pl

    b, _k, s4 = x.shape
    grid = (b, s4 // tile)
    return pl.pallas_call(
        functools.partial(_kernel_batched, k, r),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, k, tile), lambda c, j: (c, 0, j)),
            pl.BlockSpec((r, k, 8), lambda c, j: (0, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, r, tile), lambda c, j: (c, 0, j)),
        out_shape=jax.ShapeDtypeStruct((b, r, s4), jnp.uint32),
        interpret=interpret,
    )(x, consts)


class PallasGf:
    """Callable (B, k, S4) uint32 → (B, r, S4) uint32, same contract as
    tpu_codec.gf_apply, but one VMEM-resident Pallas dispatch per
    codeword slab.  `interpret=True` runs the kernel in the Pallas
    interpreter (any backend — used for CPU-side bit-identity tests)."""

    def __init__(self, mat: np.ndarray, tile: int = 8192,
                 interpret: bool = False):
        self.r, self.k = mat.shape
        self.tile = tile
        self.interpret = interpret
        self.consts = jnp.asarray(gf_scalar_consts(mat))

    def __call__(self, shards_u32: jax.Array) -> jax.Array:
        b, k, s4 = shards_u32.shape
        assert k == self.k, (k, self.k)
        # clamp the tile for small shards: padding a 1 KiB shard to the
        # 8192-column production tile would multiply the work 8-64x; a
        # power-of-two tile ≥ s4 keeps padding ≤ 2x (one jit variant per
        # clamped size — O(log) shapes)
        tile = 512
        while tile < min(self.tile, s4):
            tile <<= 1
        tile = min(tile, self.tile)
        pad = (-s4) % tile
        if pad:
            shards_u32 = jnp.pad(shards_u32, ((0, 0), (0, 0), (0, pad)))
        if s4 + pad >= 2048:
            # wide shards (the scrub/batch path): walk codewords in
            # place — their k rows are contiguous in (B, k, S4), so no
            # transpose touches HBM (the fold path's swapaxes cost a
            # full round-trip of the batch)
            out = _apply_batched(shards_u32, self.consts, self.k,
                                 self.r, tile, self.interpret)
            return out[..., :s4]
        # narrow shards: fold the batch into the column axis so tiles
        # stay full; codewords are independent, and tile-aligned
        # concatenation keeps each grid step inside one codeword
        x = jnp.swapaxes(shards_u32, 0, 1).reshape(self.k, -1)
        out = _apply_flat(x, self.consts, self.k, self.r, tile,
                          self.interpret)
        out = jnp.swapaxes(out.reshape(self.r, b, -1), 0, 1)
        return out[..., :s4]


def reference_apply(mat: np.ndarray, shards_u32: np.ndarray) -> np.ndarray:
    """numpy oracle in the uint32 domain (via the byte-domain gf256
    reference)."""
    b, k, s4 = shards_u32.shape
    as_bytes = shards_u32.view("<u4").astype("<u4").tobytes()
    arr = np.frombuffer(as_bytes, dtype=np.uint8).reshape(b, k, s4 * 4)
    out = gf256.gf_matmul_blocks(mat, arr)
    return np.frombuffer(out.tobytes(), dtype="<u4").reshape(
        b, mat.shape[0], s4)
