"""Pallas TPU kernel for the GF(2^8) mask-XOR matrix apply.

The north star (BASELINE.json) asks for the Reed-Solomon hot op as a
hand-written TPU kernel rather than XLA-fused jnp.  The XLA formulation
(ops/tpu_codec.py gf_apply) materializes k×8 broadcast masks per output
row across the whole batch in HBM before the XOR-reduce, so the op is
HBM-bound far below the VPU's rate.  This kernel keeps one (k, TILE)
uint32 block of the codeword resident in VMEM and produces all r output
rows from it — each input byte is read from HBM exactly once, all the
mask/select/XOR traffic happens at VMEM bandwidth.

Math (identical to gf_apply, bit-for-bit):
  gfmul(c, x) = XOR_b bit_b(x) · gfmul(c, 2^b)          (GF(2)-linearity)
applied bytewise inside uint32 lanes: ((x >> b) & 0x01010101) * 0xFF
broadcasts bit b of every byte to a full-byte mask with no cross-byte
carries, which then selects the constant gfmul(c_pj, 2^b).

The kernel is validated bit-identically against the numpy/XLA versions
in tests (interpret mode — no TPU needed for correctness), and the
device-resident rate comparison against the XLA kernel is printed by
bench.py when the chip is reachable (pallas_gibs vs device_gibs).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from . import gf256

LANE = 128          # TPU lane width
SUBLANES = 8        # uint32 tile: (8, 128)


def _kernel(k: int, r: int, x_ref, consts_ref, o_ref):
    """One grid step: x_ref (k, T) uint32 codeword slab in VMEM,
    consts_ref (r, k, 8) uint32 mask constants, o_ref (r, T) uint32."""
    one = jnp.uint32(0x01010101)
    ff = jnp.uint32(0xFF)
    x = x_ref[...]
    # bit-plane masks once per input row, reused by every output row
    masks = []
    for i in range(k):
        xi = x[i]
        masks.append([((xi >> jnp.uint32(b)) & one) * ff for b in range(8)])
    for p in range(r):
        acc = jnp.zeros_like(x[0])
        for i in range(k):
            for b in range(8):
                acc = acc ^ (masks[i][b] & consts_ref[p, i, b])
        o_ref[p, ...] = acc


@functools.partial(jax.jit, static_argnames=("k", "r", "tile", "interpret"))
def _apply_flat(x, consts, k: int, r: int, tile: int, interpret: bool):
    from jax.experimental import pallas as pl

    n = x.shape[-1]
    grid = (n // tile,)
    return pl.pallas_call(
        functools.partial(_kernel, k, r),
        grid=grid,
        in_specs=[
            pl.BlockSpec((k, tile), lambda j: (0, j)),
            pl.BlockSpec((r, k, 8), lambda j: (0, 0, 0)),
        ],
        out_specs=pl.BlockSpec((r, tile), lambda j: (0, j)),
        out_shape=jax.ShapeDtypeStruct((r, n), jnp.uint32),
        interpret=interpret,
    )(x, consts)


class PallasGf:
    """Callable (B, k, S4) uint32 → (B, r, S4) uint32, same contract as
    tpu_codec.gf_apply, but one VMEM-resident Pallas dispatch per
    codeword slab.  `interpret=True` runs the kernel in the Pallas
    interpreter (any backend — used for CPU-side bit-identity tests)."""

    def __init__(self, mat: np.ndarray, tile: int = 512,
                 interpret: bool = False):
        from .tpu_codec import gf_mask_consts

        self.r, self.k = mat.shape
        self.tile = tile
        self.interpret = interpret
        self.consts = jnp.asarray(gf_mask_consts(mat))

    def __call__(self, shards_u32: jax.Array) -> jax.Array:
        b, k, s4 = shards_u32.shape
        assert k == self.k, (k, self.k)
        pad = (-s4) % self.tile
        if pad:
            shards_u32 = jnp.pad(shards_u32, ((0, 0), (0, 0), (0, pad)))
        # fold the batch into the column axis: codewords are independent,
        # and tile-aligned concatenation keeps each grid step inside one
        # codeword's columns
        x = jnp.swapaxes(shards_u32, 0, 1).reshape(self.k, -1)
        out = _apply_flat(x, self.consts, self.k, self.r, self.tile,
                          self.interpret)
        out = jnp.swapaxes(out.reshape(self.r, b, -1), 0, 1)
        return out[..., :s4]


def reference_apply(mat: np.ndarray, shards_u32: np.ndarray) -> np.ndarray:
    """numpy oracle in the uint32 domain (via the byte-domain gf256
    reference)."""
    b, k, s4 = shards_u32.shape
    as_bytes = shards_u32.view("<u4").astype("<u4").tobytes()
    arr = np.frombuffer(as_bytes, dtype=np.uint8).reshape(b, k, s4 * 4)
    out = gf256.gf_matmul_blocks(mat, arr)
    return np.frombuffer(out.tobytes(), dtype="<u4").reshape(
        b, mat.shape[0], s4)
