"""CPU BlockCodec — the correctness and performance baseline.

Hashing uses hashlib (C-speed; releases the GIL for large buffers) fanned out
over a thread pool, matching the reference's multi-core scrub capability
(ref src/util/async_hash.rs offloads hashing to blocking threads).

Reed-Solomon runs through the optional C++ native kernel
(native/gf256.cpp, loaded via ctypes) when built, else vectorized numpy
log/exp-table math (gf256.gf_matmul_blocks).  Both satisfy the same contract
as the TPU backend.
"""

from __future__ import annotations

import collections
import concurrent.futures
import os
import threading
from typing import List, Optional, Sequence

import numpy as np

from ..utils.data import BLOCK_HASH_ALGOS, Hash
from . import gf256
from .codec import BlockCodec, CodecParams
from .native import (
    get_native_blake2s_multi,
    get_native_gf_matmul_blocks,
    get_native_gf_matmul_ptrs,
)


_SHARED_POOL = None
_SHARED_POOL_LOCK = threading.Lock()


def _hash_pool() -> concurrent.futures.ThreadPoolExecutor:
    """One process-wide hashing pool shared by every CpuCodec instance
    (codecs are constructed transiently; per-instance pools would leak)."""
    global _SHARED_POOL
    with _SHARED_POOL_LOCK:
        if _SHARED_POOL is None:
            from ..utils.cpuprof import register_thread
            _SHARED_POOL = concurrent.futures.ThreadPoolExecutor(
                max_workers=min(32, os.cpu_count() or 4),
                thread_name_prefix="codec-hash",
                initializer=lambda: register_thread("codec-hash"),
            )
        return _SHARED_POOL


class CpuCodec(BlockCodec):
    def __init__(self, params: CodecParams, metrics=None, tracer=None,
                 observer=None):
        super().__init__(params, metrics=metrics, tracer=tracer,
                         observer=observer)
        self._hash_fn = BLOCK_HASH_ALGOS[params.hash_algo]
        self._pool = _hash_pool()
        self._native = get_native_gf_matmul_blocks()
        # Multi-buffer SIMD hashing: 8 blocks per instruction stream.  On
        # the 1-core hosts this targets, the thread pool cannot parallelise
        # hashing at all — the SIMD lanes are the only parallelism there is.
        self._native_hash = (
            get_native_blake2s_multi() if params.hash_algo == "blake2s" else None
        )
        self._native_ptrs = get_native_gf_matmul_ptrs()
        if params.rs_data > 0:
            self._parity_mat = gf256.rs_parity_matrix(params.rs_data, params.rs_parity)
        # encode-schedule cache, keyed by (k, m, geometry): a partial
        # codeword of j < k members only needs the generator's first j
        # columns — the remaining k-j operands are implicit zeros, and
        # multiplying them is pure waste ("Accelerating XOR-based
        # Erasure Coding": drop zero operands from the schedule, cache
        # the schedule, re-run the apply).  Decode has carried the
        # equivalent cache since round 6; encode paid full-width work
        # for every lone-put partial codeword.  Bounded LRU (tiny:
        # geometries are 1..k-1, but the bound holds if k grows).
        self._enc_cache: "collections.OrderedDict" = collections.OrderedDict()
        self._enc_cache_lock = threading.Lock()
        # decode-schedule cache, keyed by survivor pattern: building the
        # recovery matrix (generator submatrix + GF inversion) costs more
        # than applying it to a single small decode, and degraded reads /
        # repair storms repeat one loss pattern for every affected
        # codeword ("Accelerating XOR-based Erasure Coding": cache the
        # schedule, re-run the apply).  TpuCodec has carried the same
        # cache since round 3; the CPU path paid the inversion per call.
        self._dec_cache: dict = {}

    def batch_hash(self, blocks: Sequence[bytes]) -> List[Hash]:
        # Below 4 blocks the 8-lane kernel wastes over half its lanes and
        # hashlib's C loop wins; at and above, the SIMD batch wins.
        if self._native_hash is not None and len(blocks) >= 4:
            return [Hash(d) for d in self._native_hash(blocks)]
        if len(blocks) <= 1:
            return [self._hash_fn(b) for b in blocks]
        return list(self._pool.map(self._hash_fn, blocks))

    def _apply(self, mat: np.ndarray, shards: np.ndarray) -> np.ndarray:
        if self._native is not None:
            return self._native(mat, shards)
        return gf256.gf_matmul_blocks(mat, shards)

    def rs_encode(self, data: np.ndarray) -> np.ndarray:
        assert data.shape[-2] == self.params.rs_data, data.shape
        return self._apply(self._parity_mat, np.ascontiguousarray(data, dtype=np.uint8))

    _ENC_CACHE_MAX = 64

    def encode_matrix(self, ncols: int) -> np.ndarray:
        """Cached encode schedule for a partial codeword of `ncols`
        members: the parity generator's first ncols columns (the other
        k-ncols operands are implicit zero shards — zero contributes
        zero over GF(2^8), so dropping the columns is exact).  Keyed by
        (k, m, geometry), bounded LRU — the encode-side twin of
        decode_matrix."""
        k, m = self.params.rs_data, self.params.rs_parity
        ncols = min(ncols, k)
        if ncols == k:
            return self._parity_mat
        key = (k, m, ncols)
        with self._enc_cache_lock:
            mat = self._enc_cache.get(key)
            if mat is not None:
                self._enc_cache.move_to_end(key)
                return mat
        mat = np.ascontiguousarray(self._parity_mat[:, :ncols])
        with self._enc_cache_lock:
            self._enc_cache[key] = mat
            while len(self._enc_cache) > self._ENC_CACHE_MAX:
                self._enc_cache.popitem(last=False)
        return mat

    def _encode_codewords(self, bufs: Sequence[bytes], mat: np.ndarray,
                          s: int) -> np.ndarray:
        """One schedule application: len(bufs)/ncols codewords of ncols
        members each, zero-extended to width s → (B, m, s) parity.
        Pointer-gather kernel when built (no packing pass), else pack +
        the blocks kernel."""
        r, ncols = mat.shape
        assert len(bufs) % ncols == 0, (len(bufs), ncols)
        if self._native_ptrs is not None:
            return self._native_ptrs(mat, list(bufs), s)
        arr = np.zeros((len(bufs), s), dtype=np.uint8)
        for i, b in enumerate(bufs):
            arr[i, : len(b)] = np.frombuffer(b, dtype=np.uint8)
        return self._apply(mat, arr.reshape(-1, ncols, s))

    def rs_encode_blocks(self, blocks: Sequence[bytes]) -> np.ndarray:
        """Schedule-aware override: full codewords run the full
        generator; a trailing partial codeword runs the cached
        column-sliced schedule instead of multiplying zero pads —
        bit-identical output (zero shards encode to zero parity), and a
        lone single-block put pays 1/k of the GF work."""
        k = self.params.rs_data
        assert k > 0 and blocks
        blocks = list(blocks)
        maxlen = max(len(b) for b in blocks)
        nfull = (len(blocks) // k) * k
        parts = []
        if nfull:
            parts.append(self._encode_codewords(
                blocks[:nfull], self._parity_mat, maxlen))
        tail = blocks[nfull:]
        if tail:
            parts.append(self._encode_codewords(
                tail, self.encode_matrix(len(tail)), maxlen))
        return parts[0] if len(parts) == 1 else np.concatenate(parts,
                                                               axis=0)

    def rs_encode_ragged(self, groups: Sequence[Sequence[bytes]]
                         ) -> List[np.ndarray]:
        """Fused ragged encode with schedule sharing: every group's full
        codewords join ONE generator application, and partial tails are
        batched per geometry through the cached sliced schedules (the
        XOR-schedule fusion of "Accelerating XOR-based Erasure Coding"
        — no zero pad blocks are materialized or multiplied at all).
        Per-group results are bit-identical to rs_encode_blocks(group)."""
        k = self.params.rs_data
        assert k > 0 and groups
        maxlen = max(len(b) for g in groups for b in g)
        full_bufs: List[bytes] = []
        tails: dict = {}
        for gi, g in enumerate(groups):
            assert g, "empty encode submission"
            nfull = (len(g) // k) * k
            full_bufs.extend(g[:nfull])
            if len(g) > nfull:
                tails.setdefault(len(g) - nfull, []).append(gi)
        full_par = (self._encode_codewords(full_bufs, self._parity_mat,
                                           maxlen)
                    if full_bufs else None)
        tail_par: dict = {}
        for ncols, gis in tails.items():
            bufs = [b for gi in gis
                    for b in groups[gi][(len(groups[gi]) // k) * k:]]
            par = self._encode_codewords(
                bufs, self.encode_matrix(ncols), maxlen)
            for j, gi in enumerate(gis):
                tail_par[gi] = par[j:j + 1]
        out: List[np.ndarray] = []
        r = 0
        for gi, g in enumerate(groups):
            nrows = len(g) // k
            ml = max(len(b) for b in g)
            rows = []
            if nrows:
                rows.append(full_par[r:r + nrows])
                r += nrows
            if gi in tail_par:
                rows.append(tail_par[gi])
            par = rows[0] if len(rows) == 1 else np.concatenate(rows,
                                                                axis=0)
            out.append(np.ascontiguousarray(par[:, :, :ml]))
        return out

    def gf_scale(self, coeff: int, buf: bytes,
                 limit: Optional[int] = None) -> bytes:
        b = buf[:limit] if limit is not None else buf
        if coeff == 0 or not b:
            return b""
        if coeff == 1:
            return bytes(b)
        if self._native is not None:
            mat = np.array([[coeff]], dtype=np.uint8)
            arr = np.ascontiguousarray(
                np.frombuffer(b, dtype=np.uint8)).reshape(1, -1)
            return self._native(mat, arr)[0].tobytes()
        return gf256.gf_scale_bytes(coeff, b)

    def decode_matrix(self, present: Sequence[int],
                      rows: Optional[Sequence[int]] = None) -> np.ndarray:
        """Cached recovery matrix for one survivor pattern (optionally
        sliced to `rows`) — the decode schedule shared by rs_reconstruct
        and the repair planner's partial-sum coefficient rows
        (block/repair_plan.py needs the row without running a decode)."""
        k, m = self.params.rs_data, self.params.rs_parity
        key = (tuple(present[:k]), tuple(rows) if rows is not None else None)
        dec = self._dec_cache.get(key)
        if dec is None:
            dec = gf256.rs_decode_matrix(k, m, present)
            if rows is not None:
                dec = np.ascontiguousarray(dec[list(rows)])
            if len(self._dec_cache) >= 512:  # bounded: loss patterns are few
                self._dec_cache.clear()
            self._dec_cache[key] = dec
        return dec

    def rs_reconstruct(self, shards: np.ndarray, present: Sequence[int],
                       rows: Optional[Sequence[int]] = None) -> np.ndarray:
        k = self.params.rs_data
        dec = self.decode_matrix(present, rows)
        return self._apply(dec, np.ascontiguousarray(shards[..., :k, :], dtype=np.uint8))
