"""ctypes loaders for the native C++ kernels in garage_tpu/native/.

Two kernels live here:
  - gf256.cpp      → libgf256.so      (AVX2 split-nibble GF(2^8) matmul)
  - blake2s_mb.cpp → libblake2smb.so  (multi-buffer BLAKE2s-256;
    16-lane AVX-512 / 8-lane AVX2, runtime-dispatched inside the kernel)

Resolved lazily on first use (not import — short CLI invocations must not
pay for a compiler run); a failed build is cached on disk against the
source mtime so it is not retried every process start.  Callers fall back
to numpy (GF) / hashlib (BLAKE2s) when a kernel is unavailable.
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import threading
from typing import Callable, List, Optional, Sequence

import numpy as np

logger = logging.getLogger("garage_tpu.ops.native")

_NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(__file__)), "native")
_BUILD_LOCK = threading.Lock()


def _load_or_build(so_name: str, src_name: str) -> Optional[ctypes.CDLL]:
    """Load native/<so_name>, building it (make) if missing or stale.

    A load failure of an existing .so (e.g. a stale binary built on another
    host — the Makefile uses -march=native) triggers one clean rebuild.
    A failed build writes a marker keyed on the source mtime so this exact
    source is never re-attempted."""
    # GARAGE_NATIVE_SUFFIX=.asan/.tsan selects the sanitizer-
    # instrumented variants built by `make asan`/`make tsan` (run the
    # tests under the matching LD_PRELOAD — see native/Makefile)
    suffix = os.environ.get("GARAGE_NATIVE_SUFFIX", "")
    # sanitizer variants are built by the PHONY asan/tsan targets (the
    # per-.so rules only exist for the plain builds), and their build
    # failures must not poison the plain build's marker (or vice versa)
    make_target = so_name
    if suffix:
        so_name = so_name.replace(".so", f"{suffix}.so")
        make_target = suffix.lstrip(".")
    so_path = os.path.join(_NATIVE_DIR, so_name)
    src_path = os.path.join(_NATIVE_DIR, src_name)
    fail_marker = os.path.join(_NATIVE_DIR,
                               f".build_failed_{src_name}{suffix}")
    with _BUILD_LOCK:
        src_mtime = os.path.getmtime(src_path)
        fresh = os.path.exists(so_path) and os.path.getmtime(so_path) >= src_mtime
        if fresh:
            try:
                return ctypes.CDLL(so_path)
            except OSError:
                pass  # stale/foreign binary: fall through to a clean rebuild
        if os.environ.get("GARAGE_TPU_NO_NATIVE_BUILD"):
            return None
        if os.path.exists(fail_marker) and os.path.getmtime(fail_marker) >= src_mtime:
            return None
        try:
            subprocess.run(
                ["make", "-C", _NATIVE_DIR, "-s", "-B", make_target],
                check=True, capture_output=True, timeout=120,
            )
            return ctypes.CDLL(so_path)
        except Exception as e:
            logger.debug("native %s build failed: %s", so_name, e)
            try:
                with open(fail_marker, "w") as f:
                    f.write(str(e))
            except OSError:
                pass
            return None


# --- GF(2^8) matmul ---

_gf_resolved = False
_gf_fn: Optional[Callable[[np.ndarray, np.ndarray], np.ndarray]] = None


def get_native_gf_matmul_blocks() -> Optional[Callable]:
    """The native GF kernel, or None (numpy fallback); builds on first call."""
    global _gf_resolved, _gf_fn
    if _gf_resolved:
        return _gf_fn
    _gf_resolved = True
    lib = _load_or_build("libgf256.so", "gf256.cpp")
    if lib is None:
        return None
    try:
        lib.gf_matmul_blocks.argtypes = [
            ctypes.POINTER(ctypes.c_uint8), ctypes.POINTER(ctypes.c_uint8),
            ctypes.POINTER(ctypes.c_uint8),
            ctypes.c_int64, ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
        ]
        lib.gf_matmul_blocks.restype = None
    except Exception as e:
        logger.debug("native gf256 symbol resolution failed: %s", e)
        return None

    def _ptr(a: np.ndarray):
        return a.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8))

    def fn(mat: np.ndarray, shards: np.ndarray) -> np.ndarray:
        r, k = mat.shape
        shards = np.ascontiguousarray(shards, dtype=np.uint8)
        lead = shards.shape[:-2]
        batch = int(np.prod(lead)) if lead else 1
        s = shards.shape[-1]
        assert shards.shape[-2] == k
        out = np.zeros(lead + (r, s), dtype=np.uint8)
        mat_c = np.ascontiguousarray(mat, dtype=np.uint8)
        lib.gf_matmul_blocks(_ptr(mat_c), _ptr(shards), _ptr(out), batch, r, k, s)
        return out

    _gf_fn = fn
    return _gf_fn


# --- GF(2^8) pointer-gather matmul (scrub/put encode hot path) ---

_gfp_resolved = False
_gfp_fn: Optional[Callable] = None


def get_native_gf_matmul_ptrs() -> Optional[Callable]:
    """fn(mat (r,k) uint8, buffers: Sequence[bytes], s) → (B, r, s) uint8,
    where consecutive groups of k buffers form one codeword, each
    zero-extended to width s.  len(buffers) must be a multiple of k.
    Only available when the GFNI kernel backs it (on AVX2-only hosts,
    packing + gf_matmul_blocks is faster than the scalar gather)."""
    global _gfp_resolved, _gfp_fn
    if _gfp_resolved:
        return _gfp_fn
    _gfp_resolved = True
    lib = _load_or_build("libgf256.so", "gf256.cpp")
    if lib is None:
        return None
    try:
        if not lib.gf_ptrs_fast():
            return None
        lib.gf_matmul_ptrs.argtypes = [
            ctypes.POINTER(ctypes.c_uint8), ctypes.POINTER(ctypes.c_char_p),
            ctypes.POINTER(ctypes.c_uint64), ctypes.POINTER(ctypes.c_uint8),
            ctypes.c_int64, ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
        ]
        lib.gf_matmul_ptrs.restype = None
    except Exception as e:
        logger.debug("native gf_matmul_ptrs unavailable: %s", e)
        return None

    def fn(mat: np.ndarray, buffers: Sequence[bytes], s: int) -> np.ndarray:
        r, k = mat.shape
        n = len(buffers)
        assert n % k == 0, (n, k)
        B = n // k
        ptrs = (ctypes.c_char_p * n)(*buffers)
        lens = (ctypes.c_uint64 * n)(*[len(b) for b in buffers])
        out = np.zeros((B, r, s), dtype=np.uint8)
        mat_c = np.ascontiguousarray(mat, dtype=np.uint8)
        lib.gf_matmul_ptrs(
            mat_c.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)), ptrs, lens,
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)), B, r, k, s)
        return out

    _gfp_fn = fn
    return _gfp_fn


# --- multi-buffer BLAKE2s-256 ---

_b2_resolved = False
_b2_fn: Optional[Callable[[Sequence[bytes]], List[bytes]]] = None


def get_native_blake2s_multi() -> Optional[Callable[[Sequence[bytes]], List[bytes]]]:
    """Batch BLAKE2s-256 over the multi-buffer SIMD kernel (16-lane
    AVX-512 or 8-lane AVX2, dispatched inside blake2s256_multi), or None
    (hashlib fallback).  Returns a callable blocks → [32-byte digests].

    The wrapper sorts the batch by length before dispatch: lanes in one
    SIMD group advance in lock-step, so grouping similar lengths minimises
    the work wasted on lanes that finish early (compressed blocks make
    lengths non-uniform).  Output order matches the input order."""
    global _b2_resolved, _b2_fn
    if _b2_resolved:
        return _b2_fn
    _b2_resolved = True
    lib = _load_or_build("libblake2smb.so", "blake2s_mb.cpp")
    if lib is None:
        return None
    try:
        if not lib.blake2s_mb_supported():
            return None  # prebuilt binary on a pre-AVX2 host
        lib.blake2s256_multi.argtypes = [
            ctypes.POINTER(ctypes.c_char_p), ctypes.POINTER(ctypes.c_uint64),
            ctypes.c_void_p, ctypes.c_int64,
        ]
        lib.blake2s256_multi.restype = None
    except Exception as e:
        logger.debug("native blake2s symbol resolution failed: %s", e)
        return None

    def fn(blocks: Sequence[bytes]) -> List[bytes]:
        n = len(blocks)
        if n == 0:
            return []
        order = sorted(range(n), key=lambda i: len(blocks[i]))
        ptrs = (ctypes.c_char_p * n)(*[blocks[i] for i in order])
        lens = (ctypes.c_uint64 * n)(*[len(blocks[i]) for i in order])
        out = (ctypes.c_uint8 * (32 * n))()
        lib.blake2s256_multi(ptrs, lens,
                             ctypes.cast(out, ctypes.c_void_p), n)
        raw = bytes(out)
        digests: List[bytes] = [b""] * n
        for pos, i in enumerate(order):
            digests[i] = raw[pos * 32:(pos + 1) * 32]
        return digests

    _b2_fn = fn
    return _b2_fn


_b2_rows_resolved = False
_b2_rows_fn: Optional[Callable] = None


def get_native_blake2s_rows() -> Optional[Callable]:
    """Strided in-place variant of the multi-buffer kernel: hash the
    first `n` rows of a C-contiguous uint8 matrix WITHOUT materializing
    per-row bytes copies — the lane pointers index straight into the
    (lane-aligned-stride) staging buffer where the rows already lie.
    This is the CPU-floor close of the SIMD-friendly staging layout:
    c_char_p only accepts bytes, so consuming a staging buffer through
    the plain wrapper costs one full copy pass per row.

    Returns fn(arr_2d, lengths, n) -> [32-byte digests], or None when
    the kernel is unavailable (callers fall back to hashlib over row
    views, which are zero-copy too, just not multi-buffer)."""
    global _b2_rows_resolved, _b2_rows_fn
    if _b2_rows_resolved:
        return _b2_rows_fn
    _b2_rows_resolved = True
    if get_native_blake2s_multi() is None:
        return None
    lib = _load_or_build("libblake2smb.so", "blake2s_mb.cpp")

    def fn(arr, lengths, n: int) -> List[bytes]:
        if n == 0:
            return []
        assert arr.dtype.itemsize == 1 and arr.flags["C_CONTIGUOUS"]
        stride = arr.strides[0]
        base = arr.ctypes.data
        order = sorted(range(n), key=lambda i: int(lengths[i]))
        ptrs = (ctypes.c_char_p * n)()
        lens = (ctypes.c_uint64 * n)()
        for pos, i in enumerate(order):
            ptrs[pos] = ctypes.cast(base + i * stride, ctypes.c_char_p)
            lens[pos] = int(lengths[i])
        out = (ctypes.c_uint8 * (32 * n))()
        lib.blake2s256_multi(ptrs, lens,
                             ctypes.cast(out, ctypes.c_void_p), n)
        raw = bytes(out)
        digests: List[bytes] = [b""] * n
        for pos, i in enumerate(order):
            digests[i] = raw[pos * 32:(pos + 1) * 32]
        return digests

    _b2_rows_fn = fn
    return _b2_rows_fn
