"""ctypes loader for the native C++ GF(2^8) kernel (native/gf256.cpp).

Resolved lazily on first use (not import — short CLI invocations must not pay
for a compiler run); a failed build is cached on disk against the source
mtime so it is not retried every process start.  Falls back to the numpy
implementation in gf256.py when unavailable.
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
from typing import Callable, Optional

import numpy as np

logger = logging.getLogger("garage_tpu.ops.native")

_NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(__file__)), "native")
_SO_PATH = os.path.join(_NATIVE_DIR, "libgf256.so")
_FAIL_MARKER = os.path.join(_NATIVE_DIR, ".build_failed")

_resolved = False
_fn: Optional[Callable[[np.ndarray, np.ndarray], np.ndarray]] = None


def _load_lib() -> Optional[ctypes.CDLL]:
    """Load the .so; a load failure (e.g. a stale binary built on another
    host — the Makefile uses -march=native) triggers one clean rebuild,
    subject to the same opt-out/fail-marker policy as _build_ok."""
    try:
        return ctypes.CDLL(_SO_PATH)
    except OSError:
        pass
    if os.environ.get("GARAGE_TPU_NO_NATIVE_BUILD"):
        return None
    src_mtime = os.path.getmtime(os.path.join(_NATIVE_DIR, "gf256.cpp"))
    if os.path.exists(_FAIL_MARKER) and os.path.getmtime(_FAIL_MARKER) >= src_mtime:
        return None
    try:
        subprocess.run(
            ["make", "-C", _NATIVE_DIR, "-s", "clean", "all"],
            check=True, capture_output=True, timeout=120,
        )
        return ctypes.CDLL(_SO_PATH)
    except Exception as e:
        logger.debug("native gf256 rebuild failed: %s", e)
        try:
            with open(_FAIL_MARKER, "w") as f:
                f.write(str(e))
        except OSError:
            pass
        return None


def _build_ok() -> bool:
    src_mtime = os.path.getmtime(os.path.join(_NATIVE_DIR, "gf256.cpp"))
    if os.path.exists(_SO_PATH) and os.path.getmtime(_SO_PATH) >= src_mtime:
        return True
    if os.environ.get("GARAGE_TPU_NO_NATIVE_BUILD"):
        return False
    if os.path.exists(_FAIL_MARKER) and os.path.getmtime(_FAIL_MARKER) >= src_mtime:
        return False  # previous build of this exact source failed; don't retry
    try:
        subprocess.run(
            ["make", "-C", _NATIVE_DIR, "-s"],
            check=True, capture_output=True, timeout=120,
        )
        return True
    except Exception as e:
        logger.debug("native gf256 build unavailable: %s", e)
        try:
            with open(_FAIL_MARKER, "w") as f:
                f.write(str(e))
        except OSError:
            pass
        return False


def _resolve() -> Optional[Callable]:
    global _resolved, _fn
    if _resolved:
        return _fn
    _resolved = True
    if not _build_ok():
        return None
    try:
        lib = _load_lib()
        if lib is None:
            return None
        lib.gf_matmul_blocks.argtypes = [
            ctypes.POINTER(ctypes.c_uint8), ctypes.POINTER(ctypes.c_uint8),
            ctypes.POINTER(ctypes.c_uint8),
            ctypes.c_int64, ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
        ]
        lib.gf_matmul_blocks.restype = None
    except Exception as e:
        logger.debug("native gf256 load failed: %s", e)
        return None

    def _ptr(a: np.ndarray):
        return a.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8))

    def fn(mat: np.ndarray, shards: np.ndarray) -> np.ndarray:
        r, k = mat.shape
        shards = np.ascontiguousarray(shards, dtype=np.uint8)
        lead = shards.shape[:-2]
        batch = int(np.prod(lead)) if lead else 1
        s = shards.shape[-1]
        assert shards.shape[-2] == k
        out = np.zeros(lead + (r, s), dtype=np.uint8)
        mat_c = np.ascontiguousarray(mat, dtype=np.uint8)
        lib.gf_matmul_blocks(_ptr(mat_c), _ptr(shards), _ptr(out), batch, r, k, s)
        return out

    _fn = fn
    return _fn


def get_native_gf_matmul_blocks() -> Optional[Callable]:
    """The native kernel, or None (numpy fallback); builds on first call."""
    return _resolve()
