"""Static website server.

Equivalent of reference src/web/web_server.rs (SURVEY.md §2.8): the Host
header maps to a bucket via `web_root_domain` (or is used verbatim as a
bucket name); objects are served through the same GetObject path with the
bucket's index/error documents and CORS rules applied
(web_server.rs:70-75+).
"""

from __future__ import annotations

import logging
from typing import Optional

from aiohttp import web

from ..api.common import host_to_bucket, request_trace, start_site
from ..api.s3.bucket_config import (
    apply_cors_headers,
    cors_request_headers,
    find_matching_cors_rule,
)
from ..utils.metrics import maybe_time

logger = logging.getLogger("garage_tpu.web")


class WebServer:
    def __init__(self, garage):
        self.garage = garage
        self.helper = garage.helper()
        self.root_domain = garage.config.web_root_domain
        self._runner: Optional[web.AppRunner] = None
        self.request_counter = 0
        self.error_counter = 0
        # own metric family slice (ref web/web_server.rs:43-67): rides the
        # shared api_* families with api="web" labels
        m = getattr(garage.system, "metrics", None)
        if m is not None:
            self._m_requests = m.counter(
                "api_request_counter", "API requests received")
            self._m_errors = m.counter(
                "api_error_counter", "API requests answered with an error")
            self._m_duration = m.histogram(
                "api_request_duration_seconds", "API request latency",
                exemplars=True)
        else:
            self._m_requests = self._m_errors = self._m_duration = None

    async def start(self, bind_addr: str) -> None:
        app = web.Application()
        app.router.add_route("*", "/{tail:.*}", self.handle_request)
        self._runner = web.AppRunner(app, access_log=None)
        await self._runner.setup()
        self._site = await start_site(self._runner, bind_addr)
        logger.info("web server listening on %s", bind_addr)

    @property
    def port(self) -> int:
        return self._site._server.sockets[0].getsockname()[1]

    async def stop(self) -> None:
        if self._runner is not None:
            await self._runner.cleanup()

    async def handle_request(self, request: web.Request) -> web.StreamResponse:
        self.request_counter += 1
        if self._m_requests is not None:
            self._m_requests.inc(api="web")
        host = request.headers.get("Host", "")
        bucket_name = host_to_bucket(host, self.root_domain) or host.split(":")[0]
        trace, _rid = request_trace(
            self.garage.system.tracer, "Web", "web", request)
        with trace, maybe_time(self._m_duration, api="web"):
            try:
                resp = await self._serve(request, bucket_name)
            except web.HTTPException:
                raise
            except ConnectionError as e:  # incl. ConnectionResetError
                logger.debug("client disconnected mid-request: %s", e)
                raise
            except Exception:
                logger.exception("web request failed")
                resp = web.Response(status=500, text="internal error")
            # EVERY error response counts, with its status (ref
            # web/web_server.rs:43-67 error_counter by status_code) — a
            # 100%-404 outage must be visible on the dashboard
            if resp.status >= 400:
                self.error_counter += 1
                if self._m_errors is not None:
                    self._m_errors.inc(api="web", status=str(resp.status))
            trace.set_attr("status", resp.status)
            return resp

    async def _serve(self, request, bucket_name: str) -> web.StreamResponse:
        bid = await self.helper.resolve_global_bucket_name(bucket_name)
        if bid is None:
            return web.Response(status=404, text="no such website")
        bucket = await self.helper.get_existing_bucket(bid)
        wc = bucket.params().website_config.value
        if wc is None:
            return web.Response(status=404, text="website not enabled on this bucket")

        index = wc.get("index_document", "index.html")
        key = request.path.lstrip("/")
        # directory-style keys resolve to the index document; a path
        # WITHOUT the trailing slash serves the object if present, else
        # 302-redirects to path/ when path/index exists — AWS website
        # semantics (ref web_server.rs:389-416 path_to_keys +
        # ImplicitRedirect)
        implicit_redirect = None
        if key == "" or key.endswith("/"):
            key = key + index
        else:
            # Location keeps the query string (the reference drops it —
            # web_server.rs:410 formats the path only — but clients lose
            # their parameters on the re-request; AWS preserves them)
            qs = request.rel_url.raw_query_string
            implicit_redirect = (
                f"{key}/{index}",
                request.rel_url.raw_path + "/" + (f"?{qs}" if qs else ""),
            )

        cors_rules = bucket.params().cors_config.value
        origin = request.headers.get("Origin")

        if request.method == "OPTIONS":
            req_method = request.headers.get(
                "Access-Control-Request-Method", "GET"
            )
            rule = find_matching_cors_rule(
                cors_rules, req_method, origin, cors_request_headers(request))
            if rule is None:
                return web.Response(status=403, text="CORS forbidden")
            hdrs = {
                "Access-Control-Allow-Methods": ", ".join(rule["allow_methods"]),
                "Access-Control-Allow-Headers": ", ".join(rule.get("allow_headers", [])) or "*",
            }
            if rule.get("max_age_seconds"):
                hdrs["Access-Control-Max-Age"] = str(rule["max_age_seconds"])
            apply_cors_headers(hdrs, rule, origin)
            return web.Response(status=200, headers=hdrs)

        if request.method not in ("GET", "HEAD"):
            return web.Response(status=405, text="method not allowed")

        # matched CORS headers must reach STREAMED responses before
        # prepare() seals them — computed here, merged by _get_object
        cors_headers: dict = {}
        if origin is not None and cors_rules:
            rule = find_matching_cors_rule(
                cors_rules, request.method, origin,
                cors_request_headers(request))
            if rule is not None:
                apply_cors_headers(cors_headers, rule, origin)

        def with_cors(r):
            if cors_headers and not r.prepared:
                for k, v in cors_headers.items():
                    r.headers[k] = v
            return r

        resp = await self._get_object(request, bid, key, cors_headers)
        if resp.status == 404 and implicit_redirect is not None:
            redir_key, redir_url = implicit_redirect
            if await self._key_exists(bid, redir_key):
                return with_cors(web.Response(
                    status=302, headers={"Location": redir_url}))
        if resp.status == 404:
            # error document, still with 404 status (web_server.rs)
            err_key = wc.get("error_document")
            if err_key:
                err_resp = await self._get_object(
                    request, bid, err_key, cors_headers)
                if err_resp.status == 200:
                    err_resp.set_status(404)
                    return with_cors(err_resp)
        return with_cors(resp)

    async def _key_exists(self, bucket_id, key: str) -> bool:
        """ref web_server.rs:212-221 check_key_exists."""
        obj = await self.garage.object_table.get(bucket_id, key)
        return obj is not None and obj.last_data_version() is not None

    async def _get_object(self, request, bucket_id, key: str,
                          cors_headers: Optional[dict] = None
                          ) -> web.StreamResponse:
        """Serve one object via the S3 read internals (no auth — websites
        are public reads, ref web_server.rs serve_file)."""
        from ..api.common import ApiError
        from ..api.s3 import get as get_ops

        class _Ctx:
            garage = self.garage
            key_name = key

            def __init__(self):
                self.request = request
                self.bucket_id = bucket_id
                # merged into streamed responses before prepare()
                self.cors_headers = cors_headers or {}

        ctx = _Ctx()
        try:
            if request.method == "HEAD":
                return await get_ops.handle_head_object(ctx)
            return await get_ops.handle_get_object(ctx)
        except ApiError as e:
            if e.status == 404:
                return web.Response(status=404, text="not found")
            return web.Response(status=e.status, text=str(e))
