"""Static website server (ref src/web/, SURVEY.md §2.8)."""

from .web_server import WebServer

__all__ = ["WebServer"]
