"""Admin RPC — server-side handler for CLI commands.

Equivalent of reference src/garage/admin/mod.rs (SURVEY.md §2.9): a netapp
endpoint handling bucket/key/layout/worker/repair/stats operations from
the CLI client (which connects with a temporary keypair + the rpc secret,
main.rs:194-263).
"""

from .handler import AdminRpcHandler

__all__ = ["AdminRpcHandler"]
