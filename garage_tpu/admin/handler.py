"""AdminRpcHandler — cluster administration over the RPC fabric.

Equivalent of reference src/garage/admin/mod.rs:37-99 + bucket.rs +
key.rs + block.rs (SURVEY.md §2.9): status, layout staging/apply, bucket
and key CRUD with permission grants, worker introspection and runtime
variables, repair launchers, and node statistics.  Commands arrive as
msgpack dicts {"cmd": ..., ...} on the "garage/admin" endpoint.
"""

from __future__ import annotations

import asyncio
import logging
import time
from typing import Any, Dict, List, Optional

from ..model.permission import BucketKeyPerm
from ..rpc.layout import LayoutParameters, NodeRole
from ..utils.crdt import now_msec
from ..utils.data import Hash, Uuid
from ..utils.error import GarageError

logger = logging.getLogger("garage_tpu.admin")

_DURATION_UNITS = {"s": 1, "m": 60, "h": 3600, "d": 86400, "w": 7 * 86400}


def _parse_duration(v) -> float:
    """'30s' / '15m' / '2h' / '1d' / '1w' or plain seconds (ref uses the
    parse_duration crate for --older-than).  Must be finite and ≥ 0 — a
    negative duration would put the cutoff in the future and abort
    in-flight uploads."""
    import math

    s = str(v).strip()
    out = None
    try:
        out = float(s)
    except ValueError:
        unit = _DURATION_UNITS.get(s[-1:])
        if unit is not None:
            try:
                out = float(s[:-1]) * unit
            except ValueError:
                pass
    if out is None or not math.isfinite(out) or out < 0:
        raise GarageError(
            f"invalid duration {v!r} (use e.g. 30s, 15m, 2h, 1d)")
    return out


class AdminRpcHandler:
    def __init__(self, garage, register_endpoint: bool = True):
        """register_endpoint=False: embed the command set without claiming
        the netapp endpoint (the HTTP admin API reuses these handlers; the
        daemon's CLI-facing instance owns the endpoint)."""
        self.garage = garage
        self.helper = garage.helper()
        if register_endpoint:
            self.endpoint = garage.system.netapp.endpoint("garage/admin")
            self.endpoint.set_handler(self._handle)

    async def _handle(self, remote, msg, body):
        cmd = msg.get("cmd")
        fn = getattr(self, f"_cmd_{cmd}", None)
        if fn is None:
            return {"err": f"unknown admin command {cmd!r}"}, None
        try:
            return {"ok": await fn(msg)}, None
        except GarageError as e:
            return {"err": str(e)}, None
        except Exception as e:  # noqa: BLE001 — report to CLI
            logger.exception("admin command %s failed", cmd)
            return {"err": f"{type(e).__name__}: {e}"}, None

    # --- status / layout ---------------------------------------------------

    async def _cmd_status(self, msg) -> Dict:
        sys = self.garage.system
        h = sys.health()
        return {
            "node_id": bytes(sys.id).hex(),
            "hostname": sys._local_status().hostname,
            "known_nodes": sys.get_known_nodes(),
            "layout_version": sys.layout.version,
            "roles": {
                nid.hex(): [r.zone, r.capacity, r.tags]
                for nid, r in sys.layout.node_roles().items()
            },
            "staged": {
                nid.hex(): ([r.zone, r.capacity, r.tags] if r else None)
                for nid, r in sys.layout.staged_roles().items()
            },
            "parameters": {
                "zone_redundancy": sys.layout.parameters.zone_redundancy,
            },
            # only a GENUINE pending change (staging resets to the active
            # parameters on apply/revert; echoing it back always would
            # make every `layout show` look like a change is pending)
            "staged_parameters": (
                {"zone_redundancy": staged_zr}
                if (staged_zr := LayoutParameters.unpack(
                    sys.layout.staging_parameters.value
                ).zone_redundancy) != sys.layout.parameters.zone_redundancy
                else None
            ),
            "health": {
                "status": h.status,
                "known_nodes": h.known_nodes,
                "connected_nodes": h.connected_nodes,
                "storage_nodes": h.storage_nodes,
                "storage_nodes_ok": h.storage_nodes_ok,
                "partitions": h.partitions,
                "partitions_quorum": h.partitions_quorum,
                "partitions_all_ok": h.partitions_all_ok,
            },
        }

    async def _cmd_connect(self, msg) -> str:
        from ..utils.data import Uuid

        addr = msg["addr"]
        # Uuid, not raw bytes: netapp's id-mismatch diagnostics call
        # hex_short() on it (a raw-bytes expected_id turned a clean
        # "peer is X, expected Y" error into an AttributeError)
        expected = Uuid(bytes.fromhex(msg["node_id"])) if msg.get("node_id") else None
        await self.garage.system.netapp.connect(addr, expected_id=expected)
        self.garage.system.peering.add_peer(addr, expected)
        return "connected"

    async def _cmd_layout_assign(self, msg) -> str:
        sys = self.garage.system
        node_hex = msg["node"]
        nid = self._resolve_node(node_hex)
        if msg.get("remove"):
            sys.layout.stage_role(nid, None)
        else:
            role = NodeRole(
                zone=msg["zone"],
                capacity=msg.get("capacity"),
                tags=list(msg.get("tags", [])),
            )
            sys.layout.stage_role(nid, role)
        sys.save_layout()
        return "staged"

    async def _cmd_layout_config(self, msg) -> str:
        """Stage layout parameters (ref cli/layout.rs LayoutConfig:
        currently zone redundancy — 'maximum' or an integer ≥ 1)."""
        zr = msg.get("zone_redundancy")
        if zr is None:
            raise GarageError("nothing to configure (need zone-redundancy)")
        if zr != "maximum":
            try:
                zr = int(zr)
            except (TypeError, ValueError):
                raise GarageError(
                    f"zone-redundancy must be 'maximum' or an integer, "
                    f"got {zr!r}")
            factor = self.garage.replication_mode.replication_factor
            if not 1 <= zr <= factor:
                # ref cli/layout.rs rejects out-of-range values at config
                # time; accepting them would silently clamp at apply
                raise GarageError(
                    f"zone-redundancy must be in [1, {factor}] "
                    f"(the replication factor), or 'maximum'")
        sys = self.garage.system
        sys.layout.stage_parameters(LayoutParameters(zone_redundancy=zr))
        sys.save_layout()
        return f"staged zone-redundancy = {zr}"

    async def _cmd_layout_apply(self, msg) -> List[str]:
        sys = self.garage.system
        version = msg.get("version")
        messages = sys.layout.apply_staged_changes(version)
        sys.save_layout()
        sys._rebuild_ring()
        await sys.broadcast_layout()
        return messages

    async def _cmd_layout_revert(self, msg) -> str:
        sys = self.garage.system
        sys.layout.revert_staged_changes(msg.get("version"))
        sys.save_layout()
        return "reverted"

    def _resolve_node(self, node_hex: str) -> bytes:
        """Accept unambiguous hex prefixes of known node ids."""
        sys = self.garage.system
        candidates = {bytes(sys.id)}
        candidates.update(bytes(n) for n in sys.peering.connected_nodes())
        candidates.update(sys.layout.node_roles().keys())
        matches = [n for n in candidates if n.hex().startswith(node_hex.lower())]
        if len(matches) != 1:
            raise GarageError(
                f"node id prefix {node_hex!r} matches {len(matches)} nodes"
            )
        return matches[0]

    # --- buckets -----------------------------------------------------------

    async def _cmd_bucket_list(self, msg) -> List[Dict]:
        out = []
        for b in await self.helper.list_buckets():
            p = b.params()
            out.append({
                "id": bytes(b.id).hex(),
                "aliases": [n for n, l in p.aliases.items.items() if l.value],
                "keys": len([1 for _k, l in p.authorized_keys.items.items() if l.value.is_any()]),
            })
        return out

    async def _cmd_bucket_info(self, msg) -> Dict:
        bid = await self._bucket_id(msg["bucket"])
        b = await self.helper.get_existing_bucket(bid)
        p = b.params()
        counters = await self.garage.object_counter.get_totals(bytes(bid))
        mpu_counters = await self.garage.mpu_counter.get_totals(bytes(bid))
        return {
            "id": bytes(bid).hex(),
            "aliases": [n for n, l in p.aliases.items.items() if l.value],
            "website": p.website_config.value,
            "quotas": p.quotas.value,
            "keys": {
                k: [l.value.allow_read, l.value.allow_write, l.value.allow_owner]
                for k, l in p.authorized_keys.items.items()
                if l.value.is_any()
            },
            "objects": counters.get("objects", 0),
            "bytes": counters.get("bytes", 0),
            "unfinished_uploads": counters.get("unfinished_uploads", 0),
            "mpu_uploads": mpu_counters.get("uploads", 0),
        }

    async def _cmd_bucket_create(self, msg) -> str:
        b = await self.helper.create_bucket(msg["name"])
        return bytes(b.id).hex()

    async def _cmd_bucket_delete(self, msg) -> str:
        bid = await self._bucket_id(msg["bucket"])
        await self.helper.delete_bucket(bid)
        return "deleted"

    async def _cmd_bucket_alias(self, msg) -> str:
        from ..model.bucket_alias_table import BucketAlias

        bid = await self._bucket_id(msg["bucket"])
        name = msg["alias"]
        existing = await self.helper.resolve_global_bucket_name(name)
        if existing is not None:
            raise GarageError(f"alias {name!r} already in use")
        b = await self.helper.get_existing_bucket(bid)
        b.params().aliases.update(name, True)
        await self.garage.bucket_table.insert(b)
        await self.garage.bucket_alias_table.insert(BucketAlias.new(name, bid))
        return "aliased"

    async def _cmd_bucket_unalias(self, msg) -> str:
        name = msg["alias"]
        alias = await self.garage.bucket_alias_table.get(name, "")
        if alias is None or alias.bucket_id() is None:
            raise GarageError(f"no such alias {name!r}")
        bid = alias.bucket_id()
        b = await self.helper.get_existing_bucket(bid)
        if self.helper.bucket_name_count(b) <= 1:
            raise GarageError("cannot remove the last alias of a bucket")
        b.params().aliases.update(name, False)
        alias.state.update(None)
        await self.garage.bucket_table.insert(b)
        await self.garage.bucket_alias_table.insert(alias)
        return "unaliased"

    async def _cmd_bucket_allow(self, msg) -> str:
        bid = await self._bucket_id(msg["bucket"])
        key = await self._find_key(msg["key"])
        cur = key.bucket_permissions(bid)
        perm = BucketKeyPerm(
            cur.allow_read or bool(msg.get("read")),
            cur.allow_write or bool(msg.get("write")),
            cur.allow_owner or bool(msg.get("owner")),
        )
        await self.helper.set_bucket_key_permissions(bid, key.key_id, perm)
        return "allowed"

    async def _cmd_bucket_deny(self, msg) -> str:
        bid = await self._bucket_id(msg["bucket"])
        key = await self._find_key(msg["key"])
        cur = key.bucket_permissions(bid)
        perm = BucketKeyPerm(
            cur.allow_read and not msg.get("read"),
            cur.allow_write and not msg.get("write"),
            cur.allow_owner and not msg.get("owner"),
        )
        await self.helper.set_bucket_key_permissions(bid, key.key_id, perm)
        return "denied"

    async def _cmd_bucket_website(self, msg) -> str:
        bid = await self._bucket_id(msg["bucket"])
        b = await self.helper.get_existing_bucket(bid)
        if msg.get("allow"):
            b.params().website_config.update({
                "index_document": msg.get("index_document", "index.html"),
                "error_document": msg.get("error_document"),
            })
        else:
            b.params().website_config.update(None)
        await self.garage.bucket_table.insert(b)
        return "updated"

    async def _cmd_bucket_set_quotas(self, msg) -> str:
        bid = await self._bucket_id(msg["bucket"])
        b = await self.helper.get_existing_bucket(bid)
        b.params().quotas.update({
            "max_size": msg.get("max_size"),
            "max_objects": msg.get("max_objects"),
        })
        await self.garage.bucket_table.insert(b)
        return "updated"

    async def _bucket_id(self, name_or_id: str) -> Uuid:
        return await self.helper.resolve_bucket(name_or_id)

    # --- keys --------------------------------------------------------------

    async def _find_key(self, pattern: str):
        """key id or unambiguous prefix or name (ref cli key search)."""
        k = await self.garage.key_table.get(pattern, "")
        if k is not None and not k.is_deleted():
            return k
        matches = [
            k for k in await self.helper.list_keys()
            if k.key_id.startswith(pattern) or k.params().name.value == pattern
        ]
        if len(matches) != 1:
            raise GarageError(f"key {pattern!r} matches {len(matches)} keys")
        return matches[0]

    async def _cmd_key_list(self, msg) -> List[Dict]:
        return [
            {"id": k.key_id, "name": k.params().name.value}
            for k in await self.helper.list_keys()
        ]

    async def _cmd_key_info(self, msg) -> Dict:
        k = await self._find_key(msg["key"])
        p = k.params()
        return {
            "id": k.key_id,
            "name": p.name.value,
            "secret": p.secret_key if msg.get("show_secret") else None,
            "allow_create_bucket": p.allow_create_bucket.value,
            "buckets": {
                bid.hex(): [l.value.allow_read, l.value.allow_write, l.value.allow_owner]
                for bid, l in p.authorized_buckets.items.items()
                if l.value.is_any()
            },
        }

    async def _cmd_key_create(self, msg) -> Dict:
        k = await self.helper.create_key(msg.get("name", "unnamed"))
        return {"id": k.key_id, "secret": k.params().secret_key}

    async def _cmd_key_delete(self, msg) -> str:
        k = await self._find_key(msg["key"])
        await self.helper.delete_key(k)
        return "deleted"

    async def _cmd_key_import(self, msg) -> str:
        from ..model.key_table import Key

        existing = await self.garage.key_table.get(msg["id"], "")
        if existing is not None and not existing.is_deleted():
            raise GarageError("key id already exists")
        k = Key.import_key(msg["id"], msg["secret"], msg.get("name", "imported"))
        await self.garage.key_table.insert(k)
        return "imported"

    async def _cmd_key_set(self, msg) -> str:
        k = await self._find_key(msg["key"])
        if "allow_create_bucket" in msg:
            k.params().allow_create_bucket.update(bool(msg["allow_create_bucket"]))
        if msg.get("name"):
            k.params().name.update(msg["name"])
        await self.garage.key_table.insert(k)
        return "updated"

    # --- cluster network health (no reference equivalent: the per-peer
    #     RPC-fabric view `garage node` ops keep asking for) ---------------

    async def _cmd_cluster_stats(self, msg) -> Dict:
        """Per-peer network health: RTT EWMA, liveness, failure streaks,
        reconnect churn, and live per-priority traffic split — the
        straggler-attribution view (a quorum PUT stalling on ONE slow
        peer shows up here as that peer's RTT/backlog, not as generic
        API latency)."""
        sys = self.garage.system
        now = time.monotonic()
        peers = []
        for nid, st in sys.peering.peers.items():
            conn = sys.netapp.conns.get(nid)
            status = sys.node_status.get(nid)
            # the shared health core (zone/up/rtt/breaker/pressure/
            # health_score/fail_slow/disk/version — same truth the
            # flight-recorder `peers` section snapshots), plus the
            # connection-level detail only this view renders
            row = sys.peer_core_row(nid, st)
            row.update({
                "hostname": status.hostname if status else None,
                "addr": st.addr,
                "connected": conn is not None and not conn._closed,
                "consecutive_failures": st.failures,
                "reconnects": st.reconnects,
                "ping_failures": st.ping_failures,
                "last_seen_secs_ago": (
                    round(now - st.last_seen, 1)
                    if st.last_seen is not None else None),
                "traffic": conn.traffic_stats() if conn is not None else None,
            })
            peers.append(row)
        # zone grouping: peers sort by zone so a zone outage reads as one
        # contiguous block, and the rollup makes it one line
        peers.sort(key=lambda p: (p["zone"] or "~", not p["up"], p["id"]))
        disk_rank = {"ok": 0, "degraded": 1, "failed": 2}
        zones: Dict[str, Dict] = {}
        for nid_b, role in sys.layout.node_roles().items():
            if role.capacity is None:
                continue  # gateways store nothing — not a zone's health
            from ..utils.data import FixedBytes32

            nid = FixedBytes32(nid_b)
            z = zones.setdefault(role.zone, {
                "nodes": 0, "up": 0, "breaker_open": 0,
                "worst_disk": "ok",
            })
            z["nodes"] += 1
            if nid == sys.id:
                z["up"] += 1
                ds = self.garage.block_manager.health.worst_state()
            else:
                if sys.peering.is_up(nid):
                    z["up"] += 1
                if sys.peering.breaker_state(nid) == "open":
                    z["breaker_open"] += 1
                status = sys.node_status.get(nid)
                ds = status.disk_state if status else None
            if ds and disk_rank.get(ds, 0) > disk_rank[z["worst_disk"]]:
                z["worst_disk"] = ds
        # local disk health: the per-root state machine + quarantine
        # counters (block/health.py) — the node-side truth behind the
        # gossiped disk_state peers see above
        mgr = self.garage.block_manager
        disk = {
            "state": mgr.health.worst_state(),
            "roots": [
                {
                    "path": r,
                    "state": s,
                    "free_bytes": mgr.health.free_bytes(r),
                }
                for r, s in mgr.health.states().items()
            ],
            "error_counts": {
                # snapshot first: note_error inserts new (op, kind) keys
                # from worker threads while this comprehension runs
                f"{op}:{kind}": n
                for (op, kind), n in dict(mgr.health.error_counts).items()
            },
            "quarantined": mgr.quarantined,
            "quarantine_errors": mgr.quarantine_errors,
        }
        return {
            "node_id": bytes(sys.id).hex(),
            "zone": sys.our_zone(),
            "version": sys.version,
            "disk": disk,
            "zones": zones,
            "peers": peers,
        }

    # --- workers / repair / stats -----------------------------------------

    async def _cmd_worker_list(self, msg) -> List[Dict]:
        out = []
        for wid, w in self.garage.bg.workers.items():
            st = w.status()
            out.append({"id": wid, "name": w.name(), **st.to_dict()})
        return out

    async def _cmd_worker_info(self, msg) -> Dict:
        """Single-worker drill-down (ref src/garage/admin/mod.rs:47-66
        WorkerInfo + cli worker info): full status incl. last error with
        its timestamp, queue depth, progress, and the runtime-tunable
        values that apply to this worker."""
        wid = int(msg["id"])
        w = self.garage.bg.workers.get(wid)
        if w is None:
            raise GarageError(f"no worker with id {wid}")
        st = w.status()
        task = self.garage.bg.tasks.get(wid)
        vars_all = self.garage.bg_vars.all()
        name_l = w.name().lower()
        # tunables whose name shares a DISTINCTIVE word with the
        # worker's name (e.g. scrub-tranquility for the scrub worker) —
        # the reference shows the worker's parameter set in `worker
        # info`.  Generic tokens are excluded: 'worker' appears in
        # every worker's name and would attach e.g.
        # resync-worker-count to all of them.
        generic = {"worker", "workers", "count", "n", "max", "min"}
        related = {
            k: v for k, v in vars_all.items()
            if any(part and part not in generic and part in name_l
                   for part in k.split("-"))
        }
        return {
            "id": wid,
            "name": w.name(),
            "alive": task is not None and not task.done(),
            **st.to_dict(),
            "last_error_time": st.last_error_time or None,
            "last_error_ago_s": (
                round(time.time() - st.last_error_time, 1)
                if st.last_error_time else None),
            "tunables": related,
        }

    async def _cmd_worker_get_var(self, msg) -> Dict:
        if msg.get("var"):
            return {msg["var"]: self.garage.bg_vars.get(msg["var"])}
        return self.garage.bg_vars.all()

    async def _cmd_worker_set_var(self, msg) -> str:
        self.garage.bg_vars.set(msg["var"], msg["value"])
        return "set"

    # --- block operations (ref garage/admin/block.rs) -----------------------

    def _parse_block_hash(self, hx: str) -> Hash:
        try:
            b = bytes.fromhex(hx)
        except ValueError:
            raise GarageError(f"invalid block hash {hx!r}")
        if len(b) != 32:
            raise GarageError(f"invalid block hash {hx!r}")
        return Hash(b)

    async def _cmd_block_list_errors(self, msg) -> List[Dict]:
        """Blocks currently in resync error backoff (ref block.rs:14-25)."""
        from ..block.resync import ErrorCounter

        resync = self.garage.block_manager.resync
        out = []
        k = b""
        while True:
            nxt = resync.errors.tree.get_gt(k)
            if nxt is None:
                break
            k, v = nxt
            ec = ErrorCounter.parse(v)
            out.append({
                "hash": k.hex(),
                "errors": ec.errors,
                "last_try_secs_ago": max(
                    0, (now_msec() - ec.last_try) // 1000),
                "next_try_in_secs": max(
                    0, (ec.next_try() - now_msec()) // 1000),
            })
        return out

    async def _cmd_block_info(self, msg) -> Dict:
        """Refcount + referencing versions/objects/uploads of one block
        (ref block.rs:27-61)."""
        g = self.garage
        h = self._parse_block_hash(msg["hash"])
        rc = g.block_manager.rc.get(h)
        found = g.block_manager.find_block(h)
        refs = await g.block_ref_table.get_range(h, limit=10000)
        versions = []
        for br in refs:
            v = await g.version_table.get(br.version, "")
            if v is None:
                versions.append({"version": bytes(br.version).hex(),
                                 "deleted": br.deleted.value})
                continue
            ent = {
                "version": bytes(br.version).hex(),
                "deleted": v.deleted.value,
                "bucket_id": bytes(v.bucket_id).hex() if v.bucket_id else None,
                "key": v.key,
            }
            if v.mpu_upload_id:
                ent["upload_id"] = bytes(v.mpu_upload_id).hex()
            versions.append(ent)
        return {
            "hash": bytes(h).hex(),
            "refcount": rc.count,
            "deletable": rc.is_deletable(),
            "present": found is not None,
            "path": found[0] if found else None,
            "versions": versions,
        }

    async def _cmd_block_retry_now(self, msg) -> str:
        """Clear backoff + requeue errored blocks (ref block.rs:63-93)."""
        resync = self.garage.block_manager.resync
        if msg.get("all"):
            if msg.get("blocks"):
                raise GarageError("--all cannot be combined with hashes")
            hashes = [e["hash"] for e in await self._cmd_block_list_errors({})]
        else:
            hashes = msg.get("blocks") or []
        for hx in hashes:
            h = self._parse_block_hash(hx)
            resync.clear_backoff(h)
            resync.put_to_resync(h, 0.0, source="admin_retry")
        return f"{len(hashes)} blocks returned in queue for a retry now"

    async def _cmd_block_purge(self, msg) -> str:
        """Drop every version/object/upload referencing the given blocks —
        LOSES DATA; the last resort for an unrecoverable block
        (ref block.rs:95-193)."""
        if not msg.get("yes"):
            raise GarageError("pass --yes to confirm the purge operation")
        from ..model.s3.object_table import (
            Object,
            ObjectVersion,
            ObjectVersionData,
        )
        from ..model.s3.version_table import Version
        from ..utils.data import gen_uuid

        g = self.garage
        obj_dels = ver_dels = mpu_dels = 0
        for hx in msg.get("blocks") or []:
            h = self._parse_block_hash(hx)
            refs = await g.block_ref_table.get_range(h, limit=10000)
            for br in refs:
                v = await g.version_table.get(br.version, "")
                if v is None:
                    continue
                bucket_id, key, ov_id = v.bucket_id, v.key, v.uuid
                if v.mpu_upload_id:
                    mpu = await g.mpu_table.get(v.mpu_upload_id, "")
                    if mpu is not None:
                        if not mpu.deleted.value:
                            mpu.deleted.set()
                            mpu.parts = {}
                            await g.mpu_table.insert(mpu)
                            mpu_dels += 1
                        bucket_id, key, ov_id = (
                            mpu.bucket_id, mpu.key, mpu.upload_id)
                    else:
                        # MPU row lost (the inconsistency purge exists to
                        # clean up): no object to delete-mark, but the
                        # version tombstone below MUST still happen or the
                        # block_ref survives (ref block.rs:115-135)
                        bucket_id = None
                obj = (await g.object_table.get(bucket_id, key)
                       if bucket_id is not None else None)
                if obj is not None:
                    complete = [ov for ov in obj.versions()
                                if ov.is_complete()]
                    if complete and complete[-1].uuid == ov_id:
                        # newest complete version holds the bad block:
                        # supersede it with a delete marker
                        dv = ObjectVersion(
                            gen_uuid(), complete[-1].timestamp + 1,
                            ["complete", ObjectVersionData.delete_marker()],
                        )
                        await g.object_table.insert(
                            Object(bucket_id, key, [dv]))
                        obj_dels += 1
                if not v.deleted.value:
                    await g.version_table.insert(
                        Version.new(v.uuid, v.bucket_id or b"", v.key,
                                    deleted=True)
                        if not v.mpu_upload_id else
                        Version(v.uuid, v.bucket_id, v.key, deleted=True,
                                mpu_upload_id=v.mpu_upload_id)
                    )
                    ver_dels += 1
        return (f"purged {len(msg.get('blocks') or [])} blocks: "
                f"{ver_dels} versions, {obj_dels} objects, "
                f"{mpu_dels} uploads deleted")

    # --- codec observability (the dataplane's "why is tpu_frac 0.0"
    #     commands; no reference equivalent — the ops/ layer is ours) ---

    async def _cmd_codec_info(self, msg) -> Dict:
        """Backend, effective params, gate state, byte split and
        per-stage attribution of the block manager's codec."""
        out = self.garage.block_manager.codec.info()
        out["heals"] = dict(self.garage.block_manager.heal_counts)
        feeder = self.garage.block_manager.feeder
        out["feeder"] = feeder.stats() if feeder is not None else None
        resync = self.garage.block_manager.resync
        if resync is not None:
            out["resync_enqueues"] = dict(resync.enqueue_counts)
        return out

    async def _cmd_codec_events(self, msg) -> List[Dict]:
        """The bounded gate-decision event ring: every probe result,
        gate open/hold, ramp step, fused-kernel demotion and sync
        failure with a reason label, most recent last."""
        limit = msg.get("limit")
        return self.garage.block_manager.codec.obs.events_list(
            int(limit) if limit else None
        )

    async def _cmd_codec_profile(self, msg) -> Dict:
        """Controlled link sweep on the live DeviceTransport: sizes x
        batch shapes x kinds, each cell decomposed into the exact-sum
        stage breakdown (ops/link_profiler.py run_sweep).  Serial and
        synchronous by design — bounded cells, run off-loop."""
        from ..ops.link_profiler import run_sweep

        codec = self.garage.block_manager.codec
        tr = getattr(codec, "transport", None)
        if tr is None or not tr.alive:
            raise GarageError("no live device transport to profile")
        sizes = msg.get("sizes_mib") or (1.0, 4.0, 16.0)
        shapes = msg.get("shapes") or (1, 16)
        kinds = msg.get("kinds") or ("hash", "encode", "decode")
        rounds = int(msg.get("rounds") or 1)
        if len(sizes) * len(shapes) * len(kinds) * rounds > 256:
            raise GarageError("sweep too large (>256 cells)")
        return await asyncio.to_thread(
            run_sweep, tr,
            sizes_mib=tuple(float(s) for s in sizes),
            shapes=tuple(int(s) for s in shapes),
            kinds=tuple(kinds),
            rounds=rounds,
        )

    async def _cmd_cpu_profile(self, msg) -> Dict:
        """The continuous CPU profiler's readout (utils/cpuprof.py):
        folded stacks covering roughly the last `seconds`, joined to
        thread roles and span segments, plus the windowed busy ratios
        and the sampler's measured self-cost.  Served from the always-on
        sampler's history — no re-sampling wait."""
        prof = getattr(self.garage, "cpuprof", None)
        if prof is None or not prof.running:
            raise GarageError("cpu profiler is not running on this node")
        seconds = float(msg.get("seconds") or 10.0)
        if not 0.0 < seconds <= 3600.0:
            raise GarageError("seconds must be in (0, 3600]")
        top = msg.get("top")
        top_k = int(top) if top else 40
        if not 0 < top_k <= 512:
            raise GarageError("top must be in (0, 512]")
        return prof.profile(seconds=seconds, top_k=top_k)

    async def _cmd_slow_ops(self, msg) -> List[Dict]:
        """Top-N slowest spans retained by the always-on slow-op log
        (works with no trace_sink configured), slowest first."""
        limit = msg.get("limit")
        return self.garage.system.tracer.slow.snapshot(
            int(limit) if limit else None
        )

    # --- critical-path attribution (docs/OBSERVABILITY.md "Critical
    #     path & saturation"; utils/waterfall.py) ----------------------

    async def _cmd_trace_spans(self, msg) -> List[Dict]:
        """Every span record THIS node holds for one trace id — the
        per-peer fetch the waterfall merge fans out (a request's remote
        handler/table/disk spans live on the nodes that ran them)."""
        wf = getattr(self.garage.system.tracer, "waterfall", None)
        if wf is None:
            return []
        return wf.spans_for_trace(str(msg["trace"]))

    async def _cmd_request_waterfall(self, msg) -> Dict:
        """The request-waterfall surface.  Without a selector: the
        per-endpoint summary + retained slowest exemplars.  With
        `trace` (an x-amz-request-id) or `endpoint`: ONE request's full
        span tree, merged across every layout node that contributed
        spans, with the critical-path segment breakdown recomputed over
        the merged tree."""
        wf = getattr(self.garage.system.tracer, "waterfall", None)
        if wf is None:
            raise GarageError("no waterfall recorder on this node")
        trace = msg.get("trace")
        endpoint = msg.get("endpoint")
        if trace is None and endpoint is None and not msg.get("pick"):
            return {
                "endpoints": wf.endpoints(),
                "retained": wf.entries(),
                "sampled": wf.sampled,
            }
        entry = wf.entry_for(trace_id=trace, endpoint=endpoint)
        if entry is None:
            raise GarageError(
                f"no retained waterfall for "
                f"{'trace ' + trace if trace else 'endpoint ' + str(endpoint)}"
            )
        return await self._merged_waterfall(entry)

    async def _merged_waterfall(self, entry: Dict) -> Dict:
        from ..utils.waterfall import (
            build_tree,
            dominant_segment,
            segment_breakdown,
        )

        tid = entry["trace_id"]
        spans = {r["span"]: dict(r) for r in entry["local_spans"]}
        nodes_contributing = 1
        endpoint_rpc = getattr(self, "endpoint", None)
        if endpoint_rpc is not None:
            import asyncio

            from ..net.frame import PRIO_NORMAL

            sys = self.garage.system
            peers = [nid for nid in sys.layout.node_roles().keys()
                     if bytes(nid) != bytes(sys.id)]

            async def fetch(nid):
                try:
                    resp = await endpoint_rpc.call(
                        nid, {"cmd": "trace_spans", "trace": tid},
                        prio=PRIO_NORMAL, timeout=5.0)
                    return resp.get("ok") or []
                except Exception:  # noqa: BLE001 — best-effort merge
                    return []

            for remote in await asyncio.gather(*[fetch(n) for n in peers]):
                if remote:
                    nodes_contributing += 1
                for r in remote:
                    spans.setdefault(r["span"], dict(r))
        records = list(spans.values())
        root = next((r for r in records if r.get("parent") is None
                     and (r.get("attrs") or {}).get("api")), None)
        if root is None:
            root = max(records,
                       key=lambda r: r["end_ns"] - r["start_ns"])
        segments = segment_breakdown(records, root)
        dom, _s = dominant_segment(segments)
        return {
            "trace_id": tid,
            "endpoint": entry["endpoint"],
            "seconds": entry["seconds"],
            "ts": entry["ts"],
            "segments": {k: round(v, 6) for k, v in sorted(
                segments.items(), key=lambda kv: -kv[1])},
            "dominant": dom,
            "span_count": len(records),
            "nodes_contributing": nodes_contributing,
            "tree": build_tree(records, root),
        }

    async def _cmd_device_timeline(self, msg) -> Dict:
        """The device/transport pipeline timeline as Chrome-trace
        (catapult) JSON — load into chrome://tracing or Perfetto; the
        per-slot tracks show staging overlapping compute (or not)."""
        limit = msg.get("limit")
        tl = self.garage.block_manager.codec.obs.timeline
        return tl.chrome_trace(int(limit) if limit else None)

    async def _cmd_exemplars(self, msg) -> List[Dict]:
        """Current-window histogram exemplars: for each exemplar-enabled
        family and label set, the max observation and the trace id that
        produced it — the bridge from a p99 bucket to `request
        waterfall --trace`."""
        from ..utils.metrics import Histogram

        out = []
        for m in self.garage.system.metrics._metrics:
            if isinstance(m, Histogram) and m.exemplars:
                for ex in m.exemplar_snapshot():
                    out.append({"family": m.name, **ex})
        return out

    # --- fleet health & SLOs (docs/OBSERVABILITY.md "Fleet health &
    #     SLOs"; utils/slo.py + utils/flightrec.py) --------------------

    async def _cmd_slo_status(self, msg) -> Dict:
        """Per-(endpoint, objective) budget table: targets, window
        event counts, fast/slow burn rates, budget remaining — the CLI
        `slo status` payload."""
        slo = getattr(self.garage, "slo", None)
        if slo is None:
            raise GarageError("no SLO tracker on this node")
        return {
            "node_id": bytes(self.garage.system.id).hex(),
            "windows": {"fast_s": slo.tun.fast_window_s,
                        "slow_s": slo.tun.slow_window_s},
            "fast_burn_threshold": slo.tun.fast_burn_threshold,
            "fast_burn_breaches": slo.fast_burn_breaches,
            "rows": slo.status(),
        }

    async def _cmd_incident_capture(self, msg) -> Dict:
        """Manual flight-recorder capture (skips the auto debounce —
        an operator asking for a snapshot always gets one).  Collectors
        run here on the loop (race-free reads of loop-owned state, at
        Prometheus-scrape cost); the expensive serialize + disk write
        runs off it — manual captures happen exactly when the node is
        degraded, and writing a large bundle inline would stall every
        in-flight request (same split as the auto path in
        utils/flightrec.py)."""
        import asyncio

        fr = getattr(self.garage, "flightrec", None)
        if fr is None:
            raise GarageError("no flight recorder on this node")
        bundle = fr.collect(msg.get("reason") or "manual",
                            trigger="manual")
        path = await asyncio.to_thread(fr.write, bundle)
        return {"path": path, "captures": fr.captures,
                "suppressed": fr.suppressed}

    async def _cmd_incident_list(self, msg) -> List[Dict]:
        """Retained incident bundles, oldest first (headers only)."""
        fr = getattr(self.garage, "flightrec", None)
        if fr is None:
            raise GarageError("no flight recorder on this node")
        return fr.bundles()

    async def _cmd_launch_repair(self, msg) -> str:
        what = msg.get("what", "tables")
        g = self.garage
        if what == "tables":
            for t in g.tables:
                if t.syncer is not None:
                    t.syncer.add_full_sync()
            return "table full sync launched"
        if what == "blocks":
            from ..block.repair import RepairWorker

            g.bg.spawn(RepairWorker(g.block_manager))
            return "block repair launched"
        if what == "scrub":
            cmd = msg.get("scrub_cmd", "start")
            if g.scrub_worker is not None:
                g.scrub_worker.send_command(cmd)
                return f"scrub {cmd} ok"
            return "no scrub worker"
        if what == "rebalance":
            from ..block.repair import RebalanceWorker

            g.bg.spawn(RebalanceWorker(g.block_manager))
            return "rebalance launched"
        if what == "versions":
            n = await self._repair_versions()
            return f"version repair: {n} orphans reaped"
        if what == "block_refs":
            n = await self._repair_block_refs()
            return f"block_ref repair: {n} orphans reaped"
        if what == "mpu":
            n = await self._repair_mpu()
            return f"mpu repair: {n} orphans reaped"
        raise GarageError(f"unknown repair {what!r}")

    async def _repair_versions(self) -> int:
        """Tombstone versions whose object no longer references them
        (ref repair/online.rs repair_versions)."""
        from ..model.s3.version_table import Version
        from ..utils.data import Hash, Uuid

        g = self.garage
        n = 0
        data = g.version_table.data
        for _k, raw in list(data.store.items(b"", None)):
            v = data.decode_entry(raw)
            if v.deleted.value:
                continue
            if v.mpu_upload_id is not None:
                mpu = await g.mpu_table.get(Uuid(v.mpu_upload_id), "")
                ok = mpu is not None and not mpu.deleted.value
            else:
                obj = await g.object_table.get(Uuid(bytes(v.bucket_id)), v.key)
                ok = obj is not None and any(
                    bytes(ov.uuid) == bytes(v.uuid)
                    and (ov.is_complete() or ov.is_uploading())
                    for ov in obj.versions()
                )
            if not ok:
                vdel = Version(
                    v.uuid, v.bucket_id, v.key, deleted=True,
                    mpu_upload_id=v.mpu_upload_id,
                )
                await g.version_table.insert(vdel)
                n += 1
        return n

    async def _repair_block_refs(self) -> int:
        """Delete block refs whose version is gone (ref online.rs)."""
        from ..model.s3.block_ref_table import BlockRef

        g = self.garage
        n = 0
        data = g.block_ref_table.data
        from ..model.parity_index_table import is_parity_ref

        for _k, raw in list(data.store.items(b"", None)):
            br = data.decode_entry(raw)
            if br.deleted.value:
                continue
            if is_parity_ref(br.version):
                # distributed-parity refs answer to the parity index
                # (tombstoned by its hook on codeword death), not to the
                # version table — reaping them here would orphan live
                # parity shards
                continue
            v = await g.version_table.get(br.version, "")
            if v is None or v.deleted.value:
                await g.block_ref_table.insert(
                    BlockRef(br.block, br.version, deleted=True)
                )
                n += 1
        return n

    async def _cmd_bucket_cleanup_uploads(self, msg) -> str:
        """Abort multipart uploads older than a duration in the given
        buckets (ref cli structs.rs CleanupIncompleteUploadsOpt +
        admin/bucket.rs handle_bucket_cleanup_incomplete_uploads).
        Aborting the object's Uploading version cascades through the
        hooks: MPU row tombstones, part versions delete, refs drop."""
        from ..model.s3.object_table import abort_uploads

        names = msg.get("buckets") or []
        if not names:
            raise GarageError("no buckets given")
        older = _parse_duration(msg.get("older_than", "1d"))
        cutoff = now_msec() - int(older * 1000)
        g = self.garage
        # resolve EVERY name before mutating anything (ref admin/bucket.rs
        # does the same): a typo in a later name must not leave earlier
        # buckets half-cleaned with no report
        resolved = []
        for name in names:
            bid = await self.helper.resolve_global_bucket_name(name)
            if bid is None:
                raise GarageError(f"bucket not found: {name}")
            resolved.append((name, bid))
        lines = []
        for name, bid in resolved:
            count = 0
            pos = ""
            while True:
                # node-side "uploading" filter: only rows with an
                # in-progress upload leave the replicas (a bucket of
                # inline objects must not cross the wire to abort 3 MPUs)
                batch = await g.object_table.get_range(
                    bid, pos, filter="uploading", limit=1000
                )
                for obj in batch:
                    count += await abort_uploads(
                        g.object_table, obj,
                        lambda v: v.timestamp < cutoff,
                    )
                if len(batch) >= 1000:
                    pos = batch[-1].key + "\x00"
                    continue
                # Short/empty filtered page is AMBIGUOUS: the coordinator
                # re-filters after the quorum merge, so matches may have
                # been dropped mid-range and an empty page has no cursor
                # to advance.  One unfiltered probe page answers "is the
                # range exhausted?" and supplies the cursor if not.
                probe = await g.object_table.get_range(
                    bid, pos, filter="any", limit=1000
                )
                if len(probe) < 1000:
                    break
                pos = probe[-1].key + "\x00"
            lines.append(f"{name}: {count} incomplete uploads aborted")
        return "\n".join(lines)

    async def _repair_mpu(self) -> int:
        """Tombstone multipart uploads whose object row no longer carries
        the matching Uploading{multipart} version — repropagates object
        deletions to the MPU table (ref repair/online.rs RepairMpu)."""
        from ..model.s3.mpu_table import MultipartUpload
        from ..utils.data import Uuid

        g = self.garage
        n = 0
        data = g.mpu_table.data
        for _k, raw in list(data.store.items(b"", None)):
            mpu = data.decode_entry(raw)
            if mpu.deleted.value:
                continue
            obj = await g.object_table.get(Uuid(mpu.bucket_id), mpu.key)
            ok = obj is not None and any(
                bytes(ov.uuid) == bytes(mpu.upload_id)
                and ov.is_uploading(check_multipart=True)
                for ov in obj.versions()
            )
            if not ok:
                await g.mpu_table.insert(MultipartUpload(
                    mpu.upload_id, mpu.timestamp, mpu.bucket_id, mpu.key,
                    deleted=True,
                ))
                n += 1
        return n

    async def _cmd_stats(self, msg) -> Dict:
        g = self.garage
        if msg.get("all"):
            # gather from every layout node, server-side fan-out (ref
            # garage/admin/mod.rs handle_stats with all_nodes=true)
            from ..net.frame import PRIO_NORMAL

            endpoint = getattr(self, "endpoint", None)
            if endpoint is None:
                raise GarageError("stats --all needs the RPC endpoint")
            import asyncio

            async def one(nid):
                if bytes(nid) == bytes(g.system.id):
                    return nid.hex(), await self._cmd_stats({})
                try:
                    resp = await endpoint.call(
                        nid, {"cmd": "stats"}, prio=PRIO_NORMAL, timeout=10.0
                    )
                    return nid.hex(), (
                        resp["ok"] if "ok" in resp
                        else {"err": resp.get("err")}
                    )
                except Exception as e:  # noqa: BLE001 — per-node report
                    return nid.hex(), {"err": f"{type(e).__name__}: {e}"}

            pairs = await asyncio.gather(
                *[one(nid) for nid in g.system.layout.node_roles().keys()]
            )
            return {"nodes": dict(pairs)}
        table_stats = {}
        for t in g.tables:
            table_stats[t.schema.TABLE_NAME] = {
                "merkle_todo": t.data.merkle_todo_len(),
                "gc_todo": t.data.gc_todo_len(),
                "insert_queue": len(t.data.insert_queue),
            }
        from .. import FEATURES, __version__

        return {
            "node_id": bytes(g.system.id).hex(),
            "garage_version": __version__,
            "features": FEATURES,
            "tables": table_stats,
            "block": {
                "rc_entries": g.block_manager.rc_len(),
                "resync_queue": g.block_resync.queue_len(),
                "resync_errors": g.block_resync.errors_len(),
                "bytes_read": g.block_manager.bytes_read,
                "bytes_written": g.block_manager.bytes_written,
                "corruptions": g.block_manager.corruptions,
                "parity_indexed": (
                    g.block_manager.parity_store.stats()["indexed_blocks"]
                    if g.block_manager.parity_store else 0
                ),
                "local_reconstructions":
                    g.block_manager.blocks_reconstructed,
                "heals": dict(g.block_manager.heal_counts),
                "resync_enqueues": dict(g.block_resync.enqueue_counts),
            },
            "codec": {
                "backend": type(g.block_manager.codec).__name__,
                "bytes": dict(g.block_manager.codec.obs.bytes_total),
                "tpu_frac": round(
                    g.block_manager.codec.obs.tpu_frac(), 4),
                "gate": getattr(g.block_manager.codec, "last_gate", None),
                "link_gibs": getattr(
                    g.block_manager.codec, "last_link_gibs", None),
            },
        }
