"""K2V client library.

Equivalent of reference src/k2v-client/ (SURVEY.md §2.10, 1398 LoC): a
standalone async client for the K2V HTTP API with SigV4 signing — item
CRUD with causality tokens, long-poll, index reads and batch operations.

Usage::

    c = K2VClient("http://127.0.0.1:3904", "mybucket", key_id, secret)
    ct = await c.insert_item("pk", "sk", b"value")
    value, ct = await c.read_item("pk", "sk")
"""

from __future__ import annotations

import base64
import json
import urllib.parse
from typing import Any, Dict, List, Optional, Tuple

import aiohttp

from .api.signature import sign_request, uri_encode

CAUSALITY_HEADER = "X-Garage-Causality-Token"


class K2VError(Exception):
    def __init__(self, status: int, body: str):
        super().__init__(f"K2V error {status}: {body[:200]}")
        self.status = status


class CausalityToken(str):
    """Opaque causality token (base64 vector clock)."""


class K2VItemValue:
    """One read result: list of concurrent values (None = tombstone
    sibling) + the causality token covering them."""

    def __init__(self, values: List[Optional[bytes]], token: CausalityToken):
        self.values = values
        self.token = token

    @property
    def value(self) -> Optional[bytes]:
        live = [v for v in self.values if v is not None]
        return live[0] if len(live) == 1 and len(self.values) == 1 else None

    def __repr__(self):  # pragma: no cover
        return f"K2VItemValue({len(self.values)} values)"


class K2VClient:
    def __init__(self, endpoint: str, bucket: str, key_id: str, secret: str,
                 region: str = "garage"):
        self.endpoint = endpoint.rstrip("/")
        self.bucket = bucket
        self.key_id = key_id
        self.secret = secret
        self.region = region

    async def _req(self, method: str, path: str, query=None, body: bytes = b"",
                   headers=None, timeout: float = 60.0):
        query = query or []
        headers = {k.lower(): v for k, v in (headers or {}).items()}
        host = self.endpoint[self.endpoint.index("://") + 3:]
        headers["host"] = host
        # `path` is already the wire form (built with quote()); sign it
        # verbatim — the server verifies against the raw wire path, and
        # unquote→re-encode round-trips differently for keys with literal %2F
        sig = sign_request(
            self.key_id, self.secret, self.region, method,
            path, query, headers, body, path_is_raw=True,
        )
        headers.update(sig)
        # wire form must equal the signed canonical form (uri_encode, not
        # urlencode's '+'-for-space), now that the server signs raw pairs;
        # encoded=True stops yarl re-normalizing what we signed
        import yarl

        qs = "&".join(f"{uri_encode(k)}={uri_encode(v)}" for k, v in query)
        url = yarl.URL(
            f"{self.endpoint}{path}" + (f"?{qs}" if qs else ""), encoded=True
        )
        async with aiohttp.ClientSession() as s:
            async with s.request(
                method, url, data=body, headers=headers,
                timeout=aiohttp.ClientTimeout(total=timeout),
            ) as r:
                return r.status, r.headers.copy(), await r.read()

    def _item_path(self, pk: str, sk: str) -> str:
        return (
            f"/{urllib.parse.quote(self.bucket, safe='')}"
            f"/{urllib.parse.quote(pk, safe='')}"
            f"/{urllib.parse.quote(sk, safe='')}"
        )

    # --- item ops ---

    async def read_item(self, pk: str, sk: str) -> Optional[K2VItemValue]:
        st, hdrs, body = await self._req(
            "GET", self._item_path(pk, sk),
            headers={"accept": "application/json"},
        )
        if st == 404:
            return None
        if st != 200:
            raise K2VError(st, body.decode(errors="replace"))
        vals = [
            base64.b64decode(v) if v is not None else None
            for v in json.loads(body)
        ]
        return K2VItemValue(vals, CausalityToken(hdrs.get(CAUSALITY_HEADER, "")))

    async def insert_item(self, pk: str, sk: str, value: bytes,
                          token: Optional[str] = None) -> None:
        headers = {}
        if token:
            headers[CAUSALITY_HEADER] = str(token)
        st, _h, body = await self._req(
            "PUT", self._item_path(pk, sk), body=value, headers=headers
        )
        if st not in (200, 204):
            raise K2VError(st, body.decode(errors="replace"))

    async def delete_item(self, pk: str, sk: str, token: Optional[str] = None) -> None:
        headers = {}
        if token:
            headers[CAUSALITY_HEADER] = str(token)
        st, _h, body = await self._req(
            "DELETE", self._item_path(pk, sk), headers=headers
        )
        if st not in (200, 204):
            raise K2VError(st, body.decode(errors="replace"))

    async def poll_item(self, pk: str, sk: str, token: str,
                        timeout: float = 300.0) -> Optional[K2VItemValue]:
        """Long-poll until the item changes past `token`; None on timeout."""
        st, hdrs, body = await self._req(
            "GET", self._item_path(pk, sk),
            query=[("causality_token", str(token)), ("timeout", str(timeout))],
            headers={"accept": "application/json"},
            timeout=timeout + 30.0,
        )
        if st == 304:
            return None
        if st != 200:
            raise K2VError(st, body.decode(errors="replace"))
        vals = [
            base64.b64decode(v) if v is not None else None
            for v in json.loads(body)
        ]
        return K2VItemValue(vals, CausalityToken(hdrs.get(CAUSALITY_HEADER, "")))

    # --- index + batches ---

    async def read_index(self, start: Optional[str] = None,
                         end: Optional[str] = None,
                         prefix: Optional[str] = None,
                         limit: Optional[int] = 1000) -> Dict[str, Any]:
        q = [("limit", str(limit))] if limit is not None else []
        for name, v in (("start", start), ("end", end), ("prefix", prefix)):
            if v is not None:
                q.append((name, v))
        st, _h, body = await self._req(
            "GET", f"/{urllib.parse.quote(self.bucket, safe='')}", query=q
        )
        if st != 200:
            raise K2VError(st, body.decode(errors="replace"))
        return json.loads(body)

    async def insert_batch(self, items: List[Tuple[str, str, Optional[bytes], Optional[str]]]) -> None:
        """items = [(pk, sk, value|None, causality_token|None)]."""
        payload = json.dumps([
            {
                "pk": pk, "sk": sk,
                "v": base64.b64encode(v).decode() if v is not None else None,
                "ct": str(ct) if ct else None,
            }
            for pk, sk, v, ct in items
        ]).encode()
        st, _h, body = await self._req(
            "POST", f"/{urllib.parse.quote(self.bucket, safe='')}", body=payload
        )
        if st not in (200, 204):
            raise K2VError(st, body.decode(errors="replace"))

    async def read_batch(self, queries: List[Dict[str, Any]]) -> List[Dict[str, Any]]:
        st, _h, body = await self._req(
            "POST", f"/{urllib.parse.quote(self.bucket, safe='')}",
            query=[("search", "")], body=json.dumps(queries).encode(),
        )
        if st != 200:
            raise K2VError(st, body.decode(errors="replace"))
        return json.loads(body)

    async def delete_batch(self, queries: List[Dict[str, Any]]) -> List[Dict[str, Any]]:
        st, _h, body = await self._req(
            "POST", f"/{urllib.parse.quote(self.bucket, safe='')}",
            query=[("delete", "")], body=json.dumps(queries).encode(),
        )
        if st != 200:
            raise K2VError(st, body.decode(errors="replace"))
        return json.loads(body)

    async def poll_range(self, pk: str, seen_marker: Optional[str] = None,
                         prefix: Optional[str] = None,
                         timeout: float = 300.0) -> Optional[Dict[str, Any]]:
        payload: Dict[str, Any] = {"timeout": timeout}
        if seen_marker:
            payload["seenMarker"] = seen_marker
        if prefix:
            payload["prefix"] = prefix
        st, _h, body = await self._req(
            "POST",
            f"/{urllib.parse.quote(self.bucket, safe='')}/{urllib.parse.quote(pk, safe='')}",
            query=[("poll_range", "")], body=json.dumps(payload).encode(),
            timeout=timeout + 30.0,
        )
        if st == 304:
            return None
        if st != 200:
            raise K2VError(st, body.decode(errors="replace"))
        return json.loads(body)

    # --- range convenience wrappers (ref k2v-cli ReadRange/DeleteRange:
    #     single-query ReadBatch/DeleteBatch) ---

    async def read_range(self, pk: str, start: Optional[str] = None,
                         end: Optional[str] = None,
                         prefix: Optional[str] = None,
                         limit: Optional[int] = None) -> Dict[str, Any]:
        q: Dict[str, Any] = {"partitionKey": pk}
        if start:
            q["start"] = start
        if end:
            q["end"] = end
        if prefix:
            q["prefix"] = prefix
        if limit:
            q["limit"] = limit
        return (await self.read_batch([q]))[0]

    async def delete_range(self, pk: str, start: Optional[str] = None,
                           end: Optional[str] = None,
                           prefix: Optional[str] = None) -> Dict[str, Any]:
        q: Dict[str, Any] = {"partitionKey": pk}
        if start:
            q["start"] = start
        if end:
            q["end"] = end
        if prefix:
            q["prefix"] = prefix
        # one call: the server walks the whole range internally (the
        # reference's DeleteRange contract)
        return (await self.delete_batch([q]))[0]


# --- CLI (equivalent of the reference's k2v-cli binary,
#     src/k2v-client/bin/k2v-cli.rs: Insert/Read/Delete/PollItem/
#     PollRange/ReadIndex/ReadRange/DeleteRange) ---


def _cli_parser():
    import argparse

    p = argparse.ArgumentParser(
        prog="python -m garage_tpu.k2v_client",
        description="K2V command-line client (ref k2v-cli)",
    )
    p.add_argument("--endpoint", required=True, help="http://host:port")
    p.add_argument("--bucket", required=True)
    p.add_argument("--key-id", required=True)
    p.add_argument("--secret", required=True)
    p.add_argument("--region", default="garage")
    sub = p.add_subparsers(dest="cmd", required=True)

    ins = sub.add_parser("insert")
    ins.add_argument("partition_key")
    ins.add_argument("sort_key")
    ins.add_argument("value", help="literal value, or @file, or - for stdin")
    ins.add_argument("--token", default=None, help="causality token")

    rd = sub.add_parser("read")
    rd.add_argument("partition_key")
    rd.add_argument("sort_key")

    de = sub.add_parser("delete")
    de.add_argument("partition_key")
    de.add_argument("sort_key")
    de.add_argument("--token", default=None)

    pi = sub.add_parser("poll-item")
    pi.add_argument("partition_key")
    pi.add_argument("sort_key")
    pi.add_argument("token")
    pi.add_argument("--timeout", type=float, default=300.0)

    pr = sub.add_parser("poll-range")
    pr.add_argument("partition_key")
    pr.add_argument("--seen-marker", default=None)
    pr.add_argument("--prefix", default=None)
    pr.add_argument("--timeout", type=float, default=300.0)

    ri = sub.add_parser("read-index")
    ri.add_argument("--start", default=None)
    ri.add_argument("--end", default=None)
    ri.add_argument("--limit", type=int, default=None)

    rr = sub.add_parser("read-range")
    rr.add_argument("partition_key")
    rr.add_argument("--start", default=None)
    rr.add_argument("--end", default=None)
    rr.add_argument("--prefix", default=None)
    rr.add_argument("--limit", type=int, default=None)

    dr = sub.add_parser("delete-range")
    dr.add_argument("partition_key")
    dr.add_argument("--start", default=None)
    dr.add_argument("--end", default=None)
    dr.add_argument("--prefix", default=None)
    return p


async def _cli_main(args) -> None:
    import sys

    c = K2VClient(args.endpoint, args.bucket, args.key_id, args.secret,
                  region=args.region)
    if args.cmd == "insert":
        v = args.value
        if v == "-":
            data = sys.stdin.buffer.read()
        elif v.startswith("@"):
            with open(v[1:], "rb") as f:
                data = f.read()
        else:
            data = v.encode()
        await c.insert_item(args.partition_key, args.sort_key, data,
                            token=args.token)
        print("ok")
    elif args.cmd == "read":
        item = await c.read_item(args.partition_key, args.sort_key)
        if item is None:
            print("(not found)")
            return
        print(f"causality token: {item.token}")
        for v in item.values:
            print("(tombstone)" if v is None else v.decode(errors="replace"))
    elif args.cmd == "delete":
        tok = args.token
        if tok is None:
            item = await c.read_item(args.partition_key, args.sort_key)
            if item is None:
                print("(not found)")
                return
            tok = item.token
        await c.delete_item(args.partition_key, args.sort_key, token=tok)
        print("deleted")
    elif args.cmd == "poll-item":
        item = await c.poll_item(args.partition_key, args.sort_key,
                                 args.token, timeout=args.timeout)
        if item is None:
            print("(timeout, no new value)")
        else:
            print(f"causality token: {item.token}")
            for v in item.values:
                print("(tombstone)" if v is None else
                      v.decode(errors="replace"))
    elif args.cmd == "poll-range":
        res = await c.poll_range(args.partition_key,
                                 seen_marker=args.seen_marker,
                                 prefix=args.prefix, timeout=args.timeout)
        print(json.dumps(res if res is not None
                         else {"timeout": True}, indent=2))
    elif args.cmd == "read-index":
        res = await c.read_index(start=args.start, end=args.end,
                                 limit=args.limit)
        print(json.dumps(res, indent=2))
    elif args.cmd == "read-range":
        res = await c.read_range(args.partition_key, start=args.start,
                                 end=args.end, prefix=args.prefix,
                                 limit=args.limit)
        print(json.dumps(res, indent=2))
    elif args.cmd == "delete-range":
        res = await c.delete_range(args.partition_key, start=args.start,
                                   end=args.end, prefix=args.prefix)
        print(json.dumps(res, indent=2))


if __name__ == "__main__":  # pragma: no cover — exercised via subprocess
    import asyncio as _asyncio

    _asyncio.run(_cli_main(_cli_parser().parse_args()))
