"""K2V client library.

Equivalent of reference src/k2v-client/ (SURVEY.md §2.10, 1398 LoC): a
standalone async client for the K2V HTTP API with SigV4 signing — item
CRUD with causality tokens, long-poll, index reads and batch operations.

Usage::

    c = K2VClient("http://127.0.0.1:3904", "mybucket", key_id, secret)
    ct = await c.insert_item("pk", "sk", b"value")
    value, ct = await c.read_item("pk", "sk")
"""

from __future__ import annotations

import base64
import json
import urllib.parse
from typing import Any, Dict, List, Optional, Tuple

import aiohttp

from .api.signature import sign_request, uri_encode

CAUSALITY_HEADER = "X-Garage-Causality-Token"


class K2VError(Exception):
    def __init__(self, status: int, body: str):
        super().__init__(f"K2V error {status}: {body[:200]}")
        self.status = status


class CausalityToken(str):
    """Opaque causality token (base64 vector clock)."""


class K2VItemValue:
    """One read result: list of concurrent values (None = tombstone
    sibling) + the causality token covering them."""

    def __init__(self, values: List[Optional[bytes]], token: CausalityToken):
        self.values = values
        self.token = token

    @property
    def value(self) -> Optional[bytes]:
        live = [v for v in self.values if v is not None]
        return live[0] if len(live) == 1 and len(self.values) == 1 else None

    def __repr__(self):  # pragma: no cover
        return f"K2VItemValue({len(self.values)} values)"


class K2VClient:
    def __init__(self, endpoint: str, bucket: str, key_id: str, secret: str,
                 region: str = "garage"):
        self.endpoint = endpoint.rstrip("/")
        self.bucket = bucket
        self.key_id = key_id
        self.secret = secret
        self.region = region

    async def _req(self, method: str, path: str, query=None, body: bytes = b"",
                   headers=None, timeout: float = 60.0):
        query = query or []
        headers = {k.lower(): v for k, v in (headers or {}).items()}
        host = self.endpoint[self.endpoint.index("://") + 3:]
        headers["host"] = host
        # `path` is already the wire form (built with quote()); sign it
        # verbatim — the server verifies against the raw wire path, and
        # unquote→re-encode round-trips differently for keys with literal %2F
        sig = sign_request(
            self.key_id, self.secret, self.region, method,
            path, query, headers, body, path_is_raw=True,
        )
        headers.update(sig)
        # wire form must equal the signed canonical form (uri_encode, not
        # urlencode's '+'-for-space), now that the server signs raw pairs;
        # encoded=True stops yarl re-normalizing what we signed
        import yarl

        qs = "&".join(f"{uri_encode(k)}={uri_encode(v)}" for k, v in query)
        url = yarl.URL(
            f"{self.endpoint}{path}" + (f"?{qs}" if qs else ""), encoded=True
        )
        async with aiohttp.ClientSession() as s:
            async with s.request(
                method, url, data=body, headers=headers,
                timeout=aiohttp.ClientTimeout(total=timeout),
            ) as r:
                return r.status, r.headers.copy(), await r.read()

    def _item_path(self, pk: str, sk: str) -> str:
        return (
            f"/{urllib.parse.quote(self.bucket, safe='')}"
            f"/{urllib.parse.quote(pk, safe='')}"
            f"/{urllib.parse.quote(sk, safe='')}"
        )

    # --- item ops ---

    async def read_item(self, pk: str, sk: str) -> Optional[K2VItemValue]:
        st, hdrs, body = await self._req(
            "GET", self._item_path(pk, sk),
            headers={"accept": "application/json"},
        )
        if st == 404:
            return None
        if st != 200:
            raise K2VError(st, body.decode(errors="replace"))
        vals = [
            base64.b64decode(v) if v is not None else None
            for v in json.loads(body)
        ]
        return K2VItemValue(vals, CausalityToken(hdrs.get(CAUSALITY_HEADER, "")))

    async def insert_item(self, pk: str, sk: str, value: bytes,
                          token: Optional[str] = None) -> None:
        headers = {}
        if token:
            headers[CAUSALITY_HEADER] = str(token)
        st, _h, body = await self._req(
            "PUT", self._item_path(pk, sk), body=value, headers=headers
        )
        if st not in (200, 204):
            raise K2VError(st, body.decode(errors="replace"))

    async def delete_item(self, pk: str, sk: str, token: Optional[str] = None) -> None:
        headers = {}
        if token:
            headers[CAUSALITY_HEADER] = str(token)
        st, _h, body = await self._req(
            "DELETE", self._item_path(pk, sk), headers=headers
        )
        if st not in (200, 204):
            raise K2VError(st, body.decode(errors="replace"))

    async def poll_item(self, pk: str, sk: str, token: str,
                        timeout: float = 300.0) -> Optional[K2VItemValue]:
        """Long-poll until the item changes past `token`; None on timeout."""
        st, hdrs, body = await self._req(
            "GET", self._item_path(pk, sk),
            query=[("causality_token", str(token)), ("timeout", str(timeout))],
            headers={"accept": "application/json"},
            timeout=timeout + 30.0,
        )
        if st == 304:
            return None
        if st != 200:
            raise K2VError(st, body.decode(errors="replace"))
        vals = [
            base64.b64decode(v) if v is not None else None
            for v in json.loads(body)
        ]
        return K2VItemValue(vals, CausalityToken(hdrs.get(CAUSALITY_HEADER, "")))

    # --- index + batches ---

    async def read_index(self, start: Optional[str] = None,
                         end: Optional[str] = None,
                         prefix: Optional[str] = None,
                         limit: int = 1000) -> Dict[str, Any]:
        q = [("limit", str(limit))]
        for name, v in (("start", start), ("end", end), ("prefix", prefix)):
            if v is not None:
                q.append((name, v))
        st, _h, body = await self._req(
            "GET", f"/{urllib.parse.quote(self.bucket, safe='')}", query=q
        )
        if st != 200:
            raise K2VError(st, body.decode(errors="replace"))
        return json.loads(body)

    async def insert_batch(self, items: List[Tuple[str, str, Optional[bytes], Optional[str]]]) -> None:
        """items = [(pk, sk, value|None, causality_token|None)]."""
        payload = json.dumps([
            {
                "pk": pk, "sk": sk,
                "v": base64.b64encode(v).decode() if v is not None else None,
                "ct": str(ct) if ct else None,
            }
            for pk, sk, v, ct in items
        ]).encode()
        st, _h, body = await self._req(
            "POST", f"/{urllib.parse.quote(self.bucket, safe='')}", body=payload
        )
        if st not in (200, 204):
            raise K2VError(st, body.decode(errors="replace"))

    async def read_batch(self, queries: List[Dict[str, Any]]) -> List[Dict[str, Any]]:
        st, _h, body = await self._req(
            "POST", f"/{urllib.parse.quote(self.bucket, safe='')}",
            query=[("search", "")], body=json.dumps(queries).encode(),
        )
        if st != 200:
            raise K2VError(st, body.decode(errors="replace"))
        return json.loads(body)

    async def delete_batch(self, queries: List[Dict[str, Any]]) -> List[Dict[str, Any]]:
        st, _h, body = await self._req(
            "POST", f"/{urllib.parse.quote(self.bucket, safe='')}",
            query=[("delete", "")], body=json.dumps(queries).encode(),
        )
        if st != 200:
            raise K2VError(st, body.decode(errors="replace"))
        return json.loads(body)

    async def poll_range(self, pk: str, seen_marker: Optional[str] = None,
                         prefix: Optional[str] = None,
                         timeout: float = 300.0) -> Optional[Dict[str, Any]]:
        payload: Dict[str, Any] = {"timeout": timeout}
        if seen_marker:
            payload["seenMarker"] = seen_marker
        if prefix:
            payload["prefix"] = prefix
        st, _h, body = await self._req(
            "POST",
            f"/{urllib.parse.quote(self.bucket, safe='')}/{urllib.parse.quote(pk, safe='')}",
            query=[("poll_range", "")], body=json.dumps(payload).encode(),
            timeout=timeout + 30.0,
        )
        if st == 304:
            return None
        if st != 200:
            raise K2VError(st, body.decode(errors="replace"))
        return json.loads(body)
