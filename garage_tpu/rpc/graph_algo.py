"""Flow graphs for the layout optimizer.

Equivalent of reference src/rpc/graph_algo.rs: a generic directed flow graph
with `compute_maximal_flow` (Dinic: BFS level graph + DFS blocking flow,
graph_algo.rs:175) and `optimize_flow_with_cost` (negative-cycle
cancellation on the residual graph, graph_algo.rs:269).  Vertices are
arbitrary hashable handles.
"""

from __future__ import annotations

from collections import deque
from typing import Dict, Hashable, List, Optional, Tuple

Vertex = Hashable


class _Edge:
    __slots__ = ("dst", "cap", "flow", "cost", "rev")

    def __init__(self, dst: int, cap: int, cost: int, rev: int):
        self.dst = dst
        self.cap = cap
        self.flow = 0
        self.cost = cost
        self.rev = rev  # index of reverse edge in adj[dst]


class Graph:
    """Flow network over hashable vertex handles (ref graph_algo.rs:46)."""

    def __init__(self):
        self._id: Dict[Vertex, int] = {}
        self._vertex: List[Vertex] = []
        self.adj: List[List[_Edge]] = []

    def vertex_id(self, v: Vertex) -> int:
        i = self._id.get(v)
        if i is None:
            i = len(self._vertex)
            self._id[v] = i
            self._vertex.append(v)
            self.adj.append([])
        return i

    def add_edge(self, u: Vertex, v: Vertex, cap: int, cost: int = 0) -> None:
        ui, vi = self.vertex_id(u), self.vertex_id(v)
        self.adj[ui].append(_Edge(vi, cap, cost, len(self.adj[vi])))
        self.adj[vi].append(_Edge(ui, 0, -cost, len(self.adj[ui]) - 1))

    # --- max flow (Dinic) ---

    def compute_maximal_flow(self, source: Vertex, sink: Vertex) -> int:
        s, t = self.vertex_id(source), self.vertex_id(sink)
        total = 0
        n = len(self.adj)
        while True:
            level = [-1] * n
            level[s] = 0
            q = deque([s])
            while q:
                u = q.popleft()
                for e in self.adj[u]:
                    if e.cap - e.flow > 0 and level[e.dst] < 0:
                        level[e.dst] = level[u] + 1
                        q.append(e.dst)
            if level[t] < 0:
                return total
            it = [0] * n

            def dfs(u: int, pushed: int) -> int:
                if u == t:
                    return pushed
                while it[u] < len(self.adj[u]):
                    e = self.adj[u][it[u]]
                    if e.cap - e.flow > 0 and level[e.dst] == level[u] + 1:
                        got = dfs(e.dst, min(pushed, e.cap - e.flow))
                        if got > 0:
                            e.flow += got
                            self.adj[e.dst][e.rev].flow -= got
                            return got
                    it[u] += 1
                return 0

            while True:
                pushed = dfs(s, 1 << 60)
                if pushed == 0:
                    break
                total += pushed

    # --- cost optimization (negative cycle cancellation) ---

    def optimize_flow_with_cost(self, max_rounds: int = 1000) -> None:
        """Cancel negative-cost cycles in the residual graph until none
        remain (ref graph_algo.rs:269): keeps the flow value, minimizes
        total cost.  Bellman-Ford finds a vertex on a negative cycle; we
        walk predecessors to extract it."""
        n = len(self.adj)
        for _ in range(max_rounds):
            cycle = self._find_negative_cycle(n)
            if cycle is None:
                return
            bottleneck = min(e.cap - e.flow for e in cycle)
            for e in cycle:
                e.flow += bottleneck
                self.adj[e.dst][e.rev].flow -= bottleneck

    def _find_negative_cycle(self, n: int) -> Optional[List[_Edge]]:
        INF = 1 << 60
        dist = [0] * n  # all-zero init finds cycles reachable from anywhere
        pred: List[Optional[Tuple[int, _Edge]]] = [None] * n
        x = -1
        for _ in range(n):
            x = -1
            for u in range(n):
                for e in self.adj[u]:
                    if e.cap - e.flow > 0 and dist[u] + e.cost < dist[e.dst]:
                        dist[e.dst] = max(dist[u] + e.cost, -INF)
                        pred[e.dst] = (u, e)
                        x = e.dst
            if x == -1:
                return None
        # x is on or downstream of a negative cycle; walk back n steps
        for _ in range(n):
            x = pred[x][0]  # type: ignore[index]
        cycle: List[_Edge] = []
        v = x
        while True:
            u, e = pred[v]  # type: ignore[misc]
            cycle.append(e)
            v = u
            if v == x:
                break
        cycle.reverse()
        return cycle

    # --- inspection ---

    def positive_flow_edges(self) -> List[Tuple[Vertex, Vertex, int]]:
        out = []
        for u, edges in enumerate(self.adj):
            for e in edges:
                if e.flow > 0 and e.cap > 0:
                    out.append((self._vertex[u], self._vertex[e.dst], e.flow))
        return out

    def flow_cost(self) -> int:
        return sum(
            e.flow * e.cost for edges in self.adj for e in edges if e.flow > 0
        )
