"""RpcHelper — the quorum fan-out engine.

Equivalent of reference src/rpc/rpc_helper.rs:37-435: `try_call_many` has
two modes (rpc_helper.rs:263-390):

  - **reads** (interrupt_after_quorum): requests go out in *latency order*
    (self first, then lowest ping EWMA) with only `quorum` requests in
    flight; a failure launches the next candidate; outstanding requests are
    cancelled the moment quorum is reached — 1 network RTT in the common
    case, no wasted traffic.
  - **writes** (all-sent): requests go to every replica at once; the call
    returns at quorum; stragglers keep running in a background drain task
    so all replicas converge without delaying the caller.
"""

from __future__ import annotations

import asyncio
import logging
from contextlib import nullcontext
from dataclasses import dataclass
from typing import Any, Callable, List, Optional, Sequence, Tuple

from ..utils.data import FixedBytes32
from ..utils.error import QuorumError, RpcError
from ..net.frame import PRIO_NORMAL
from ..net.netapp import Endpoint, NetApp
from ..net.peering import FullMeshPeering

logger = logging.getLogger("garage_tpu.rpc.helper")

NodeID = FixedBytes32


@dataclass
class RequestStrategy:
    """(ref rpc_helper.rs:37-53)"""

    rs_quorum: int = 1
    rs_interrupt_after_quorum: bool = False  # reads: stop once quorum is in
    rs_priority: int = PRIO_NORMAL
    rs_timeout: Optional[float] = 30.0


class RpcHelper:
    def __init__(self, netapp: NetApp, peering: FullMeshPeering, metrics=None,
                 tracer=None):
        self.netapp = netapp
        self.peering = peering
        self.our_id = netapp.id
        self._drain_tasks: set = set()
        self.tracer = tracer
        # per-RPC counters + latency histogram (ref rpc/metrics.rs:38)
        if metrics is not None:
            self.m_requests = metrics.counter(
                "rpc_request_counter", "Number of RPC requests emitted")
            self.m_errors = metrics.counter(
                "rpc_error_counter", "Number of failed RPC requests")
            self.m_timeouts = metrics.counter(
                "rpc_timeout_counter", "Number of RPC timeouts")
            self.m_duration = metrics.histogram(
                "rpc_duration_seconds", "Duration of RPCs")
        else:
            self.m_requests = self.m_errors = None
            self.m_timeouts = self.m_duration = None

    def _instrument(self, endpoint_path: str, coro_fn):
        """Wrap one RPC call with counters + duration (the reference's
        RecordDuration + per-call metrics, rpc_helper.rs:238-260)."""
        if self.m_requests is None:
            return coro_fn

        async def timed(*a, **kw):
            import time as _time

            from ..utils.error import error_code

            self.m_requests.inc(endpoint=endpoint_path)
            t0 = _time.perf_counter()
            try:
                return await coro_fn(*a, **kw)
            except asyncio.TimeoutError:
                self.m_timeouts.inc(endpoint=endpoint_path)
                self.m_errors.inc(endpoint=endpoint_path, error="Timeout")
                raise
            except Exception as e:
                # the error label is the structured wire code (satellite:
                # K_ERR/K_RESP carry a code, so remote domain errors keep
                # their type here instead of collapsing into one bucket)
                self.m_errors.inc(
                    endpoint=endpoint_path, error=error_code(e))
                raise
            finally:
                self.m_duration.observe(
                    _time.perf_counter() - t0, endpoint=endpoint_path
                )

        return timed

    # --- ordering (ref rpc_helper.rs:392-435) ---

    def request_order(self, nodes: Sequence[NodeID]) -> List[NodeID]:
        """Self first, then ascending ping latency, unknown-latency last."""

        def key(n: NodeID):
            if n == self.our_id:
                return (0, 0.0)
            lat = self.peering.latency(n)
            if lat is None:
                return (2, 0.0)
            return (1, lat)

        return sorted(nodes, key=key)

    # --- single + many (ref rpc_helper.rs:121-172) ---

    async def call(
        self,
        endpoint: Endpoint,
        node: NodeID,
        msg: Any,
        prio: int = PRIO_NORMAL,
        timeout: Optional[float] = 30.0,
    ) -> Any:
        fn = self._instrument(
            endpoint.path,
            lambda: endpoint.call(node, msg, prio=prio, timeout=timeout),
        )
        return await fn()

    async def call_many(
        self,
        endpoint: Endpoint,
        nodes: Sequence[NodeID],
        msg: Any,
        prio: int = PRIO_NORMAL,
        timeout: Optional[float] = 30.0,
    ) -> List[Tuple[NodeID, Any]]:
        """Call all nodes; per-node result or exception (never raises)."""

        async def one(n):
            try:
                return n, await endpoint.call(n, msg, prio=prio, timeout=timeout)
            except Exception as e:
                return n, e

        return list(await asyncio.gather(*[one(n) for n in nodes]))

    async def broadcast(
        self,
        endpoint: Endpoint,
        msg: Any,
        prio: int = PRIO_NORMAL,
        timeout: Optional[float] = 30.0,
    ) -> List[Tuple[NodeID, Any]]:
        nodes = list(self.peering.connected_nodes())
        return await self.call_many(endpoint, nodes, msg, prio, timeout)

    # --- quorum calls (ref rpc_helper.rs:223-390) ---

    async def try_call_many(
        self,
        endpoint: Endpoint,
        nodes: Sequence[NodeID],
        msg: Any,
        strategy: RequestStrategy,
        make_call: Optional[Callable[[NodeID], Any]] = None,
    ) -> List[Any]:
        """Returns the first `quorum` successful responses, or raises
        QuorumError with the collected errors."""
        quorum = strategy.rs_quorum
        nodes = list(nodes)
        if len(nodes) < quorum:
            raise QuorumError(quorum, 0, [f"only {len(nodes)} candidate nodes"])

        def _raw(n: NodeID):
            if make_call is not None:
                return make_call(n)
            return endpoint.call(
                n, msg, prio=strategy.rs_priority, timeout=strategy.rs_timeout
            )

        timed = self._instrument(endpoint.path, lambda n: _raw(n))

        def call_node(n: NodeID):
            return timed(n)

        # quorum-call span with the reference's attributes
        # (rpc/rpc_helper.rs:238-260: to, quorum, strategy); attrs are only
        # built when tracing is on
        tr = self.tracer
        span = tr.span(
            f"RPC {endpoint.path}",
            to=",".join(bytes(n).hex()[:8] for n in nodes),
            quorum=quorum,
            strategy=("interrupt_after_quorum"
                      if strategy.rs_interrupt_after_quorum else "all_sent"),
        ) if tr is not None and tr.enabled else nullcontext()
        with span:
            if strategy.rs_interrupt_after_quorum:
                return await self._quorum_read(nodes, call_node, quorum)
            return await self._quorum_write(nodes, call_node, quorum)

    async def _quorum_read(self, nodes, call_node, quorum) -> List[Any]:
        ordered = self.request_order(nodes)
        in_flight: dict = {}
        successes: List[Any] = []
        errors: List[Any] = []
        next_i = 0
        try:
            while len(successes) < quorum:
                # keep exactly enough requests in flight to reach quorum
                want = quorum - len(successes)
                while len(in_flight) < want and next_i < len(ordered):
                    n = ordered[next_i]
                    next_i += 1
                    in_flight[asyncio.ensure_future(call_node(n))] = n
                if not in_flight:
                    raise QuorumError(quorum, len(successes), errors)
                done, _ = await asyncio.wait(
                    in_flight.keys(), return_when=asyncio.FIRST_COMPLETED
                )
                for fut in done:
                    in_flight.pop(fut)
                    try:
                        successes.append(fut.result())
                    except Exception as e:
                        errors.append(e)
            return successes
        finally:
            for fut in in_flight:
                fut.cancel()

    async def _quorum_write(self, nodes, call_node, quorum) -> List[Any]:
        futs = {asyncio.ensure_future(call_node(n)): n for n in nodes}
        pending = set(futs.keys())
        successes: List[Any] = []
        errors: List[Any] = []
        while pending and len(successes) < quorum:
            done, pending = await asyncio.wait(
                pending, return_when=asyncio.FIRST_COMPLETED
            )
            for fut in done:
                try:
                    successes.append(fut.result())
                except Exception as e:
                    errors.append(e)
        if len(successes) < quorum:
            raise QuorumError(quorum, len(successes), errors)
        if pending:
            # drain stragglers in the background (ref rpc_helper.rs:348-382)
            drain = asyncio.ensure_future(self._drain(pending))
            self._drain_tasks.add(drain)
            drain.add_done_callback(self._drain_tasks.discard)
        return successes

    @staticmethod
    async def _drain(pending):
        results = await asyncio.gather(*pending, return_exceptions=True)
        for r in results:
            if isinstance(r, Exception):
                logger.debug("background write straggler failed: %s", r)
