"""RpcHelper — the quorum fan-out engine.

Equivalent of reference src/rpc/rpc_helper.rs:37-435: `try_call_many` has
two modes (rpc_helper.rs:263-390):

  - **reads** (interrupt_after_quorum): requests go out in *latency order*
    (self first, then lowest ping EWMA) with only `quorum` requests in
    flight; a failure launches the next candidate; outstanding requests are
    cancelled the moment quorum is reached — 1 network RTT in the common
    case, no wasted traffic.
  - **writes** (all-sent): requests go to every replica at once; the call
    returns at quorum; stragglers keep running in a background drain task
    so all replicas converge without delaying the caller.

Degraded-mode resilience (this repo's addition; docs/ROBUSTNESS.md): every
per-node dispatch runs through one policy gate that layers

  1. **adaptive per-peer timeouts** — clamped ``base + k·rtt`` from the
     peering RTT EWMA, static strategy timeout as fallback and ceiling, so
     a blackholed peer (accepts, never responds) costs ~2 s, not 30;
  2. **bounded retries with full-jitter backoff** for idempotent calls
     (``rs_idempotent``), transport errors only, under a per-fan-out
     budget — a node yields exactly ONE outcome no matter how many
     attempts, so quorum math never double-counts;
  3. **read hedging** — when a quorum-read wave is slower than the
     endpoint's observed latency quantile (reuse rpc_duration_seconds),
     the next latency-ordered candidate launches speculatively and the
     loser is cancelled at quorum;
  4. the **per-peer circuit breaker** (net/peering.py) — open peers sort
     last in request_order and fast-fail (PeerUnavailable) instead of
     burning a timeout; call outcomes feed the breaker back.
"""

from __future__ import annotations

import asyncio
import logging
import random
import time
from contextlib import nullcontext
from dataclasses import dataclass
from typing import Any, Callable, List, Optional, Sequence, Tuple

from ..utils.data import FixedBytes32
from ..utils.error import (
    DeadlineExceeded,
    PeerUnavailable,
    QuorumError,
    RpcError,
    ZoneQuorumError,
    error_code,
)
from ..utils.tracing import clamp_to_budget, deadline_expired, remaining_budget
from ..net.frame import PRIO_NORMAL
from ..net.netapp import Endpoint, NetApp
from ..net.peering import FullMeshPeering
from ..net.resilience import (
    ResilienceTunables,
    adaptive_timeout,
    full_jitter_backoff,
    is_transport_error,
)

logger = logging.getLogger("garage_tpu.rpc.helper")

NodeID = FixedBytes32


@dataclass
class RequestStrategy:
    """(ref rpc_helper.rs:37-53, extended with the resilience knobs)"""

    rs_quorum: int = 1
    rs_interrupt_after_quorum: bool = False  # reads: stop once quorum is in
    rs_priority: int = PRIO_NORMAL
    rs_timeout: Optional[float] = 30.0       # static fallback + ceiling
    # resilience (defaults resolve against the helper's tunables):
    rs_idempotent: bool = False         # safe to retry (reads/probes only)
    rs_retries: Optional[int] = None    # None → tunables.retry_max if idempotent
    rs_adaptive_timeout: bool = True    # per-peer base + k·rtt clamp
    rs_hedge: bool = True               # speculative next-candidate on slow wave
    rs_hedge_delay: Optional[float] = None  # None → latency-quantile derived
    # writes only: the acked replica set must span at least this many
    # distinct zones (0/1 = availability-first, no topology check).
    # Callers resolve it via System.write_zone_requirement so it is
    # never larger than the candidate set can actually span.
    rs_required_zones: int = 0


class _RetryBudget:
    """Caps TOTAL retries across one logical fan-out: per-node retry
    loops stay bounded even when every replica flaps at once (N nodes ×
    retry_max would otherwise multiply tail latency under correlated
    failure — the regime retries exist to escape, not amplify)."""

    __slots__ = ("left",)

    def __init__(self, total: int):
        self.left = total

    def take(self) -> bool:
        if self.left <= 0:
            return False
        self.left -= 1
        return True


class RpcHelper:
    def __init__(self, netapp: NetApp, peering: FullMeshPeering, metrics=None,
                 tracer=None, tunables: Optional[ResilienceTunables] = None):
        self.netapp = netapp
        self.peering = peering
        self.our_id = netapp.id
        self.tunables = tunables or peering.tunables
        self._drain_tasks: set = set()
        self._rng = random.Random()
        self.tracer = tracer
        # topology source (set by System once the layout is known): zone
        # of a peer / of this node, from the COMMITTED layout.  Defaults
        # answer None, which keeps every zone feature inert — bare
        # RpcHelper uses (tests, CLI clients) behave exactly as before.
        self.zone_of: Callable[[NodeID], Optional[str]] = lambda _n: None
        self.local_zone: Callable[[], Optional[str]] = lambda: None
        # fleet-health sources (set by System once built): gossiped
        # load-governor pressure and the fail-slow verdict per peer.
        # Defaults keep every health feature inert — bare RpcHelper uses
        # (tests, CLI clients) rank exactly as before.
        self.pressure_of: Callable[[NodeID], float] = lambda _n: 0.0
        self.fail_slow_of: Callable[[NodeID], bool] = lambda _n: False
        # per-peer service-time feed for the fail-slow scorer
        # (utils/health_score.py): called with (node, endpoint, seconds)
        # for every COMPLETED call — the same timings that land in
        # rpc_duration_seconds, with the peer dimension the histogram
        # lacks
        self.health_note: Optional[
            Callable[[NodeID, str, float], None]] = None
        # per-RPC counters + latency histogram (ref rpc/metrics.rs:38)
        if metrics is not None:
            self.m_requests = metrics.counter(
                "rpc_request_counter", "Number of RPC requests emitted")
            self.m_errors = metrics.counter(
                "rpc_error_counter", "Number of failed RPC requests")
            self.m_timeouts = metrics.counter(
                "rpc_timeout_counter", "Number of RPC timeouts")
            self.m_duration = metrics.histogram(
                "rpc_duration_seconds", "Duration of RPCs")
            self.m_retries = metrics.counter(
                "rpc_retry_total",
                "RPC attempts retried after a retryable failure")
            self.m_hedges = metrics.counter(
                "rpc_hedge_total",
                "Speculative (hedged) quorum-read requests launched")
            self.m_adaptive = metrics.histogram(
                "rpc_adaptive_timeout_seconds",
                "Adaptive per-peer timeout chosen for outgoing RPCs")
            self.m_zone_requorum = metrics.counter(
                "rpc_zone_requorum_total",
                "Quorum writes that waited past their numeric quorum "
                "for acks to span the required zones")
            self.m_zone_errors = metrics.counter(
                "rpc_zone_quorum_error_total",
                "Quorum writes failed because the acked replica set "
                "never spanned the required zones (ZoneQuorumError)")
            self.m_deadline = metrics.counter(
                "rpc_deadline_exceeded_total",
                "RPC dispatches shed or aborted because the request's "
                "end-to-end deadline budget ran out")
        else:
            self.m_requests = self.m_errors = None
            self.m_timeouts = self.m_duration = None
            self.m_retries = self.m_hedges = self.m_adaptive = None
            self.m_zone_requorum = self.m_zone_errors = None
            self.m_deadline = None

    def set_zone_source(self, zone_of: Callable[[NodeID], Optional[str]],
                        local_zone: Callable[[], Optional[str]]) -> None:
        """Thread the committed layout's topology in (System calls this
        once at construction; the callables read live state, so a layout
        change needs no re-wiring)."""
        self.zone_of = zone_of
        self.local_zone = local_zone

    def set_health_source(
        self,
        pressure_of: Callable[[NodeID], float],
        fail_slow_of: Callable[[NodeID], bool],
        note: Optional[Callable[[NodeID, str, float], None]] = None,
    ) -> None:
        """Thread the fleet-health plane in (System wires this next to
        the zone source): gossiped pressure + fail-slow verdicts feed
        peer_rank, and every completed call's service time feeds the
        comparative scorer via `note`."""
        self.pressure_of = pressure_of
        self.fail_slow_of = fail_slow_of
        self.health_note = note

    def _feed_health(self, node: NodeID, endpoint_path: str,
                     seconds: float) -> None:
        if self.health_note is None or node == self.our_id:
            return
        try:
            self.health_note(node, endpoint_path, seconds)
        except Exception:  # noqa: BLE001 — scoring must never break calls
            pass

    def _instrument(self, endpoint_path: str, coro_fn):
        """Wrap one RPC attempt with counters + duration (the reference's
        RecordDuration + per-call metrics, rpc_helper.rs:238-260)."""
        if self.m_requests is None:
            return coro_fn

        async def timed(*a, **kw):
            import time as _time

            self.m_requests.inc(endpoint=endpoint_path)
            t0 = _time.perf_counter()
            try:
                return await coro_fn(*a, **kw)
            except Exception as e:
                # the error label is the structured wire code (satellite:
                # K_ERR/K_RESP carry a code, so remote domain errors keep
                # their type here instead of collapsing into one bucket).
                # Timeout flavor matched by CODE, not class: the netapp
                # layer raises the typed TimeoutError_ (an RpcError), which
                # a bare `except asyncio.TimeoutError` would never see
                code = error_code(e)
                if code == "Timeout":
                    self.m_timeouts.inc(endpoint=endpoint_path)
                elif code == "DeadlineExceeded" and self.m_deadline is not None:
                    self.m_deadline.inc(endpoint=endpoint_path)
                self.m_errors.inc(endpoint=endpoint_path, error=code)
                raise
            finally:
                self.m_duration.observe(
                    _time.perf_counter() - t0, endpoint=endpoint_path
                )

        return timed

    # --- resilience primitives (shared with block/manager.py streaming) ---

    def timeout_for(self, node: NodeID, static: Optional[float],
                    adaptive: bool = True) -> Optional[float]:
        """Per-peer timeout: clamped base + k·rtt from the ping EWMA,
        static fallback for self/unknown peers, static as the ceiling."""
        if not adaptive or node == self.our_id:
            return static
        t = adaptive_timeout(self.peering.latency(node), static, self.tunables)
        if t is not None and t != static and self.m_adaptive is not None:
            self.m_adaptive.observe(t)
        return t

    def peer_allows(self, node: NodeID) -> bool:
        """Circuit-breaker gate (self-calls always pass).  A True answer
        may consume the half-open probe slot — report the call's outcome
        via note_result."""
        if node == self.our_id:
            return True
        return self.peering.breaker_allows(node)

    def note_result(self, node: NodeID, err: Optional[BaseException]) -> None:
        """Feed a call outcome back to the peer's breaker.  Only transport
        errors count against the peer; a domain error (NoSuchBlock from a
        live handler) proves the path works."""
        if node == self.our_id:
            return
        if err is None:
            self.peering.record_rpc_success(node)
        elif isinstance(err, (asyncio.CancelledError, DeadlineExceeded)):
            # no verdict about the peer: a cancelled call never finished,
            # and a deadline expiry indicts the caller's budget — either
            # way, only release a consumed half-open probe slot
            self.peering.breaker_release(node)
        elif is_transport_error(err):
            self.peering.record_rpc_failure(node)
        else:
            self.peering.record_rpc_success(node)

    def _hedge_delay(self, endpoint_path: str,
                     strategy: RequestStrategy) -> Optional[float]:
        """How long a quorum-read wave may run before the next candidate
        launches speculatively: the endpoint's observed latency quantile
        (needs hedge_min_samples history), or the caller's explicit
        rs_hedge_delay.  None disables hedging for this call."""
        if not strategy.rs_hedge:
            return None
        if strategy.rs_hedge_delay is not None:
            return max(strategy.rs_hedge_delay, 0.001)
        if self.m_duration is None:
            return None
        d = self.m_duration.quantile(
            self.tunables.hedge_quantile,
            min_count=self.tunables.hedge_min_samples,
            endpoint=endpoint_path,
        )
        return max(d, 0.001) if d is not None else None

    async def _call_policied(
        self,
        endpoint_path: str,
        node: NodeID,
        raw_call: Callable[[Optional[float]], Any],
        strategy: RequestStrategy,
        budget: Optional[_RetryBudget] = None,
    ) -> Any:
        """ONE logical call to one node: breaker gate → adaptive timeout →
        instrumented attempt → bounded full-jitter retries (idempotent +
        transport errors only).  Exactly one outcome per node regardless
        of attempts, so quorum accounting upstream stays per-node."""
        retries = strategy.rs_retries
        if retries is None:
            retries = self.tunables.retry_max if strategy.rs_idempotent else 0
        attempt = 0
        while True:
            # deadline gate FIRST (before peer_allows, which may consume
            # the breaker's half-open probe slot): work whose client has
            # already timed out is shed here, before any bytes move
            rem = remaining_budget()
            if rem is not None and rem <= self.tunables.deadline_floor:
                if self.m_deadline is not None:
                    self.m_deadline.inc(endpoint=endpoint_path)
                raise DeadlineExceeded(
                    f"budget exhausted ({rem * 1000:.1f} ms left) before "
                    f"dispatch of {endpoint_path}")
            if not self.peer_allows(node):
                # fast-fail: no timeout burned, next candidate launches now
                raise PeerUnavailable(
                    f"breaker open for {bytes(node).hex()[:8]}")
            timeout = self.timeout_for(
                node, strategy.rs_timeout, strategy.rs_adaptive_timeout)
            # per-hop timeout clamped to the remaining request budget: a
            # hop may finish early or fail early, never outlive its client
            clamped = clamp_to_budget(timeout)
            budget_bound = clamped is not None and (
                timeout is None or clamped < timeout)

            async def attempt_once(_t=clamped, _bb=budget_bound):
                try:
                    return await raw_call(_t)
                except (TimeoutError, asyncio.TimeoutError, RpcError) as e:
                    # a timeout caused by the BUDGET clamp (not the peer
                    # being slow relative to its own allowance) is the
                    # request's deadline expiring: reclassify typed so it
                    # neither feeds the breaker nor earns a retry, and the
                    # API layer renders 503 instead of 500
                    if (_bb and deadline_expired()
                            and error_code(e) == "Timeout"):
                        raise DeadlineExceeded(
                            f"request budget expired during "
                            f"{endpoint_path}") from e
                    raise

            fn = self._instrument(endpoint_path, attempt_once)
            t_attempt = time.perf_counter()
            try:
                result = await fn()
            except asyncio.CancelledError:
                self.note_result(node, asyncio.CancelledError())
                raise
            except Exception as e:
                self.note_result(node, e)
                # a COMPLETED answer (domain error: the peer served the
                # call, we just disliked the verdict) still measures the
                # peer's service time; transport failures and budget
                # expiries measure nothing about the peer
                if (not is_transport_error(e)
                        and not isinstance(e, DeadlineExceeded)):
                    self._feed_health(node, endpoint_path,
                                      time.perf_counter() - t_attempt)
                retryable = (
                    attempt < retries
                    and not isinstance(e, PeerUnavailable)
                    and is_transport_error(e)
                    and (budget is None or budget.take())
                )
                if not retryable:
                    raise
                if self.m_retries is not None:
                    self.m_retries.inc(
                        endpoint=endpoint_path, reason=error_code(e))
                await asyncio.sleep(
                    full_jitter_backoff(attempt, self.tunables, self._rng))
                attempt += 1
                continue
            else:
                self.note_result(node, None)
                self._feed_health(node, endpoint_path,
                                  time.perf_counter() - t_attempt)
                return result

    # --- ordering (ref rpc_helper.rs:392-435) ---

    def request_order(self, nodes: Sequence[NodeID]) -> List[NodeID]:
        """Self first, then LOCAL-ZONE peers, then cross-zone peers —
        each zone band ordered by ascending ping latency with
        unknown-latency peers after measured ones — and open-breaker
        peers last (they fast-fail, but a candidate that will not answer
        should never order into the first quorum wave).

        The zone band implements the survivor-selection policy from the
        degraded-read literature (PAPERS.md "Boosting the Performance of
        Degraded Reads"): serve from the nearest/healthiest survivors —
        here, the local failure domain — and only cross a zone boundary
        when the local copies are dark (breaker-open/timeout walks the
        ordered list into the next zone).  Peers with no known zone (no
        committed layout, gateway-only tests) rank with the local band,
        which reproduces the pre-zone ordering exactly."""
        return sorted(nodes, key=self.peer_rank)

    def pressure_bucket(self, n: NodeID) -> int:
        """Gossiped load-governor pressure quantized into coarse bands
        (0 = < 0.5 relaxed, 1 = < 1.0 warm, 2 = saturated): ranking on
        the raw float would reorder candidates on every gossip tick,
        defeating RTT ordering within a band — the bucket only demotes
        peers that are MEANINGFULLY hotter."""
        try:
            p = float(self.pressure_of(n))
        except Exception:  # noqa: BLE001 — a dead source is pressure 0
            return 0
        return 0 if p < 0.5 else (1 if p < 1.0 else 2)

    def peer_rank(self, n: NodeID) -> tuple:
        """The candidate-ordering key request_order sorts by — exposed
        so planners can rank non-node resources by their best holder
        (block/repair_plan.py ranks codeword pieces with it).  The key
        is (breaker/fail-slow/zone band, [zone within fail-slow,]
        pressure bucket, measured-latency flag, RTT):

          band 0 = self, 1 = local/unknown zone, 2 = cross-zone,
          3 = FAIL-SLOW (up, pings fine, breaker closed — but a
          sustained factor slower than its siblings for the same
          endpoint class; utils/health_score.py), 4 = breaker open.

        Fail-slow demotes after breaker-open and before RTT, per the
        degraded-reads paper's healthy-survivor-first rule; within the
        fail-slow band, zone then pressure then RTT still order the
        flagged peers (if every candidate is flagged, the least-bad one
        serves).  The pressure bucket is the load-aware half of the
        same paper: a pressured-but-reachable peer yields to an idle
        sibling in the same zone band, but never to a farther zone.
        With no health source wired every bucket is 0 and the ordering
        is exactly the pre-fleet-health (zone, RTT) one."""
        if n == self.our_id:
            return (0, 0, 0, 0.0)
        if self.peering.breaker_state(n) == "open":
            return (4, 0, 0, 0.0)
        lz = self.local_zone()
        nz = self.zone_of(n)
        zband = 1 if (lz is None or nz is None or nz == lz) else 2
        pbucket = self.pressure_bucket(n)
        lat = self.peering.latency(n)
        measured = 1 if lat is None else 0
        try:
            flagged = bool(self.fail_slow_of(n))
        except Exception:  # noqa: BLE001 — a dead source is healthy
            flagged = False
        if flagged:
            return (3, zband, pbucket, measured, lat or 0.0)
        return (zband, pbucket, measured, lat or 0.0)

    # --- single + many (ref rpc_helper.rs:121-172) ---

    async def call(
        self,
        endpoint: Endpoint,
        node: NodeID,
        msg: Any,
        prio: int = PRIO_NORMAL,
        timeout: Optional[float] = 30.0,
        body: Any = None,
        idempotent: bool = False,
    ) -> Any:
        """One-node call through the full resilience gate (adaptive
        timeout, breaker fast-fail, retries when idempotent).  Calls
        carrying a streaming body never retry (the iterator is consumed
        by the first attempt) and keep the STATIC timeout: the timeout
        covers the whole body transfer, which is bandwidth-bound — an
        RTT-derived clamp would false-fail big transfers on slow links
        and feed those timeouts into the breaker."""
        strategy = RequestStrategy(
            rs_priority=prio,
            rs_timeout=timeout,
            rs_idempotent=idempotent and body is None,
            rs_adaptive_timeout=body is None,
        )
        return await self._call_policied(
            endpoint.path,
            node,
            lambda t: endpoint.call(node, msg, prio=prio, timeout=t,
                                    body=body),
            strategy,
        )

    async def call_many(
        self,
        endpoint: Endpoint,
        nodes: Sequence[NodeID],
        msg: Any,
        prio: int = PRIO_NORMAL,
        timeout: Optional[float] = 30.0,
    ) -> List[Tuple[NodeID, Any]]:
        """Call all nodes; per-node result or exception (never raises)."""

        async def one(n):
            try:
                return n, await self.call(endpoint, n, msg, prio, timeout)
            except Exception as e:
                return n, e

        return list(await asyncio.gather(*[one(n) for n in nodes]))

    async def broadcast(
        self,
        endpoint: Endpoint,
        msg: Any,
        prio: int = PRIO_NORMAL,
        timeout: Optional[float] = 30.0,
    ) -> List[Tuple[NodeID, Any]]:
        nodes = list(self.peering.connected_nodes())
        return await self.call_many(endpoint, nodes, msg, prio, timeout)

    # --- quorum calls (ref rpc_helper.rs:223-390) ---

    async def try_call_many(
        self,
        endpoint: Endpoint,
        nodes: Sequence[NodeID],
        msg: Any,
        strategy: RequestStrategy,
        make_call: Optional[Callable[[NodeID, Optional[float]], Any]] = None,
    ) -> List[Any]:
        """Returns the first `quorum` successful responses, or raises
        QuorumError with the collected errors.  ``make_call(node,
        timeout)`` overrides the default dispatch; the resolved adaptive
        timeout is handed in so custom senders inherit it."""
        quorum = strategy.rs_quorum
        nodes = list(nodes)
        if len(nodes) < quorum:
            raise QuorumError(quorum, 0, [f"only {len(nodes)} candidate nodes"])

        budget = _RetryBudget(self.tunables.retry_max * max(quorum, 1))

        def _raw(n: NodeID, t: Optional[float]):
            if make_call is not None:
                return make_call(n, t)
            return endpoint.call(n, msg, prio=strategy.rs_priority, timeout=t)

        def call_node(n: NodeID):
            return self._call_policied(
                endpoint.path, n, lambda t, _n=n: _raw(_n, t), strategy,
                budget=budget,
            )

        # quorum-call span with the reference's attributes
        # (rpc/rpc_helper.rs:238-260: to, quorum, strategy) — created
        # whether or not an exporter is configured: the request
        # waterfall's `rpc` segment comes from exactly this span
        tr = self.tracer
        span = tr.span(
            f"RPC {endpoint.path}",
            to=",".join(bytes(n).hex()[:8] for n in nodes),
            quorum=quorum,
            strategy=("interrupt_after_quorum"
                      if strategy.rs_interrupt_after_quorum else "all_sent"),
        ) if tr is not None else nullcontext()
        with span:
            if strategy.rs_interrupt_after_quorum:
                return await self._quorum_read(
                    nodes, call_node, quorum,
                    self._hedge_delay(endpoint.path, strategy), endpoint.path)
            return await self._quorum_write(
                nodes, call_node, quorum,
                required_zones=strategy.rs_required_zones,
                endpoint_path=endpoint.path)

    @staticmethod
    def _quorum_fail(quorum: int, successes: int, errors: list):
        """The no-quorum exception: typed DeadlineExceeded when the
        request's budget is what killed it (every per-node failure was a
        budget expiry, or the budget is gone outright) — the API layer
        then answers the defined 503 + Retry-After instead of an
        anonymous 500 QuorumError, and the client backs off instead of
        instantly re-queueing against a saturated cluster."""
        if deadline_expired() or (
                errors and all(isinstance(e, DeadlineExceeded)
                               for e in errors)):
            raise DeadlineExceeded(
                f"request budget exhausted before quorum "
                f"({successes}/{quorum} ok)")
        raise QuorumError(quorum, successes, errors)

    async def _quorum_read(self, nodes, call_node, quorum,
                           hedge_delay=None, endpoint_path="") -> List[Any]:
        ordered = self.request_order(nodes)
        in_flight: dict = {}
        responded: set = set()   # nodes whose outcome already counted
        successes: List[Any] = []
        errors: List[Any] = []
        next_i = 0
        try:
            while len(successes) < quorum:
                # keep exactly enough requests in flight to reach quorum
                # (hedges may temporarily exceed this)
                want = quorum - len(successes)
                while len(in_flight) < want and next_i < len(ordered):
                    n = ordered[next_i]
                    next_i += 1
                    in_flight[asyncio.ensure_future(call_node(n))] = n
                if not in_flight:
                    self._quorum_fail(quorum, len(successes), errors)
                # hedging: if the wave is slower than the endpoint's
                # latency quantile AND an unsent candidate remains, launch
                # it speculatively instead of waiting for a failure
                can_hedge = hedge_delay is not None and next_i < len(ordered)
                done, _ = await asyncio.wait(
                    in_flight.keys(),
                    return_when=asyncio.FIRST_COMPLETED,
                    timeout=hedge_delay if can_hedge else None,
                )
                if not done:
                    n = ordered[next_i]
                    next_i += 1
                    in_flight[asyncio.ensure_future(call_node(n))] = n
                    if self.m_hedges is not None:
                        self.m_hedges.inc(endpoint=endpoint_path)
                    continue
                for fut in done:
                    node = in_flight.pop(fut)
                    if bytes(node) in responded:
                        # a node contributes at most ONE outcome to quorum
                        # math.  Hedge/retry can't double-launch a node by
                        # construction (next_i is monotonic; retries stay
                        # inside one future) — this guards CALLERS passing
                        # duplicate candidates, where two futures for one
                        # node would otherwise both count.  Retrieve the
                        # discarded outcome so it never logs as an
                        # unretrieved task exception
                        try:
                            fut.exception()
                        except asyncio.CancelledError:
                            pass
                        continue
                    responded.add(bytes(node))
                    try:
                        successes.append(fut.result())
                    except Exception as e:
                        errors.append(e)
            return successes
        finally:
            if in_flight:
                # cancel losers AND await them in the background so their
                # CancelledError/late results are consumed ("Task
                # exception was never retrieved" leak otherwise); awaited
                # at shutdown via RpcHelper.shutdown
                for fut in in_flight:
                    fut.cancel()
                self._spawn_drain(list(in_flight.keys()))

    async def _quorum_write(self, nodes, call_node, quorum,
                            required_zones: int = 0,
                            endpoint_path: str = "") -> List[Any]:
        futs = {asyncio.ensure_future(call_node(n)): n for n in nodes}
        pending = set(futs.keys())
        successes: List[Any] = []
        errors: List[Any] = []
        ok_nodes: List[NodeID] = []

        def acked_zones() -> set:
            return {z for z in (self.zone_of(n) for n in ok_nodes)
                    if z is not None}

        def zones_ok() -> bool:
            # 0/1 required zones: any ack spans them (and a candidate
            # set with UNKNOWN zones can never prove a violation)
            return required_zones <= 1 or len(acked_zones()) >= required_zones

        requorumed = False
        while pending and (len(successes) < quorum or not zones_ok()):
            if len(successes) >= quorum and not requorumed:
                # numeric quorum is in but the acked copies sit in too
                # few failure domains: keep waiting on the stragglers
                # instead of acking a write that would not survive the
                # zones it landed in
                requorumed = True
                if self.m_zone_requorum is not None:
                    self.m_zone_requorum.inc(endpoint=endpoint_path)
            done, pending = await asyncio.wait(
                pending, return_when=asyncio.FIRST_COMPLETED
            )
            for fut in done:
                try:
                    successes.append(fut.result())
                    ok_nodes.append(futs[fut])
                except Exception as e:
                    errors.append(e)
        if len(successes) < quorum:
            self._quorum_fail(quorum, len(successes), errors)
        if not zones_ok():
            # every candidate has answered; the acks never left
            # len(acked_zones()) zones — a whole zone is dark and the
            # layout demands more (hard integer zone_redundancy)
            if self.m_zone_errors is not None:
                self.m_zone_errors.inc(endpoint=endpoint_path)
            zs = ", ".join(sorted(acked_zones())) or "none"
            raise ZoneQuorumError(
                f"write acked by {len(successes)} nodes but only "
                f"{len(acked_zones())} zone(s) [{zs}]; layout requires "
                f"{required_zones} — a failure domain is unreachable"
            )
        if pending:
            # drain stragglers in the background (ref rpc_helper.rs:348-382)
            self._spawn_drain(pending)
        return successes

    def _spawn_drain(self, pending) -> None:
        drain = asyncio.ensure_future(self._drain(pending))
        self._drain_tasks.add(drain)
        drain.add_done_callback(self._drain_tasks.discard)

    async def shutdown(self, timeout: float = 5.0) -> None:
        """Await background drains (write stragglers, cancelled read
        losers) so no task outlives the transport it talks through; after
        `timeout`, survivors are cancelled and awaited."""
        tasks = [t for t in self._drain_tasks if not t.done()]
        if not tasks:
            return
        _done, pending = await asyncio.wait(tasks, timeout=timeout)
        for t in pending:
            t.cancel()
        if pending:
            await asyncio.gather(*pending, return_exceptions=True)

    @staticmethod
    async def _drain(pending):
        results = await asyncio.gather(*pending, return_exceptions=True)
        for r in results:
            if isinstance(r, Exception):
                logger.debug("background straggler failed: %s", r)
