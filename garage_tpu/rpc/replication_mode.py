"""Replication modes → (factor, read quorum, write quorum).

Equivalent of reference src/rpc/replication_mode.rs:1-57.
"""

from __future__ import annotations

from dataclasses import dataclass

from ..utils.error import GarageError


@dataclass(frozen=True)
class ReplicationMode:
    name: str
    replication_factor: int
    read_quorum: int
    write_quorum: int

    @property
    def is_read_after_write_consistent(self) -> bool:
        return self.read_quorum + self.write_quorum > self.replication_factor


_MODES = {
    "none": ReplicationMode("none", 1, 1, 1),
    "1": ReplicationMode("1", 1, 1, 1),
    "2": ReplicationMode("2", 2, 1, 2),
    "2-dangerous": ReplicationMode("2-dangerous", 2, 1, 1),
    "3": ReplicationMode("3", 3, 2, 2),
    "3-degraded": ReplicationMode("3-degraded", 3, 1, 2),
    "3-dangerous": ReplicationMode("3-dangerous", 3, 1, 1),
}


def parse_replication_mode(s: str) -> ReplicationMode:
    mode = _MODES.get(str(s))
    if mode is None:
        raise GarageError(
            f"invalid replication_mode {s!r}; one of {sorted(_MODES)}"
        )
    return mode
