"""External peer discovery: Consul and Kubernetes.

Equivalent of reference src/rpc/consul.rs (230 LoC) and
src/rpc/kubernetes.rs (114 LoC): beyond bootstrap peers, a node can
register itself in an external catalog and learn its peers from it on
every discovery tick (ref rpc/system.rs:726-808).

Consul speaks the same wire format as the reference — the
`fr-deuxfleurs-garagehq-pubkey` service-meta key and both the `catalog`
(catalog/register) and `agent` (agent/service/register) publication APIs
(consul.rs:14,130-220) — so a mixed cluster's members can find each
other through one Consul.

Kubernetes uses the reference's CRD (group `deuxfleurs.fr`, kind
GarageNode, named by the node's hex pubkey, labelled
`garage.deuxfleurs.fr/service=<service_name>`, kubernetes.rs:14-26,45-114)
via the in-cluster API (service-account token + CA), no client library
needed.
"""

from __future__ import annotations

import logging
import os
import ssl
from typing import Dict, List, Optional, Tuple

logger = logging.getLogger("garage_tpu.rpc.discovery")

META_PREFIX = "fr-deuxfleurs-garagehq"   # ref consul.rs:14 (wire compat)
K8S_GROUP = "deuxfleurs.fr"              # ref kubernetes.rs K8S_GROUP
K8S_VERSION = "v1"
K8S_PLURAL = "garagenodes"
SA_DIR = "/var/run/secrets/kubernetes.io/serviceaccount"


def _split_addr(rpc_public_addr: str) -> Tuple[str, int]:
    """'host:port' → (host, port), with IPv6 brackets stripped so the
    published Address parses as a bare IP on the reference side too
    (consul.rs stores an IpAddr)."""
    host, _, port = rpc_public_addr.rpartition(":")
    return host.strip("[]"), int(port)


def _decode_pubkey(pubkey_hex: str) -> Optional[bytes]:
    try:
        pubkey = bytes.fromhex(pubkey_hex)
    except ValueError:
        return None
    return pubkey if len(pubkey) == 32 else None


class ConsulDiscovery:
    """Register in / query from a Consul catalog (ref consul.rs:76-220)."""

    def __init__(self, cfg: "ConsulDiscoveryConfig"):
        self.cfg = cfg
        self._session = None

    def _ssl(self):
        if not self.cfg.consul_http_addr.startswith("https"):
            return None
        if self.cfg.tls_skip_verify:
            ctx = ssl.create_default_context()
            ctx.check_hostname = False
            ctx.verify_mode = ssl.CERT_NONE
            return ctx
        ctx = ssl.create_default_context(cafile=self.cfg.ca_cert)
        if self.cfg.client_cert and self.cfg.client_key:
            ctx.load_cert_chain(self.cfg.client_cert, self.cfg.client_key)
        return ctx

    async def _ensure_session(self):
        if self._session is None:
            import aiohttp

            headers = {}
            if self.cfg.token:
                headers["x-consul-token"] = self.cfg.token
            self._session = aiohttp.ClientSession(
                headers=headers,
                timeout=aiohttp.ClientTimeout(total=10.0),
                connector=aiohttp.TCPConnector(ssl=self._ssl()),
            )
        return self._session

    async def get_nodes(self) -> List[Tuple[bytes, str]]:
        """→ [(node_id, "ip:port")] from the service catalog
        (ref consul.rs:130-159)."""
        s = await self._ensure_session()
        url = (f"{self.cfg.consul_http_addr}/v1/catalog/service/"
               f"{self.cfg.service_name}")
        async with s.get(url) as r:
            r.raise_for_status()
            entries = await r.json()
        out = []
        for ent in entries:
            pubkey_hex = (ent.get("ServiceMeta") or {}).get(
                f"{META_PREFIX}-pubkey"
            )
            addr = ent.get("ServiceAddress") or ent.get("Address")
            port = ent.get("ServicePort")
            if not (pubkey_hex and addr and port):
                logger.warning("skipping invalid Consul node spec: %r", ent)
                continue
            pubkey = _decode_pubkey(pubkey_hex)
            if pubkey is None:
                logger.warning("invalid pubkey in Consul meta: %r", pubkey_hex)
                continue
            out.append((pubkey, f"{addr}:{port}"))
        return out

    async def publish(self, node_id: bytes, hostname: str,
                      rpc_public_addr: str) -> None:
        """Register this node (ref consul.rs:163-220; same JSON bodies)."""
        s = await self._ensure_session()
        ip, port = _split_addr(rpc_public_addr)
        node = f"garage:{bytes(node_id)[:8].hex()}"
        tags = ["advertised-by-garage", hostname] + list(self.cfg.tags)
        meta = dict(self.cfg.meta)
        meta[f"{META_PREFIX}-pubkey"] = bytes(node_id).hex()
        meta[f"{META_PREFIX}-hostname"] = hostname
        if self.cfg.api == "catalog":
            url = f"{self.cfg.consul_http_addr}/v1/catalog/register"
            body = {
                "Node": node,
                "Address": ip,
                "Service": {
                    "ID": node,
                    "Service": self.cfg.service_name,
                    "Tags": tags,
                    "Meta": meta,
                    "Address": ip,
                    "Port": port,
                },
            }
        else:  # agent API
            url = (f"{self.cfg.consul_http_addr}"
                   "/v1/agent/service/register?replace-existing-checks")
            body = {
                "ID": node,
                "Name": self.cfg.service_name,
                "Tags": tags,
                "Address": ip,
                "Port": port,
                "Meta": meta,
            }
        async with s.put(url, json=body) as r:
            r.raise_for_status()

    async def close(self) -> None:
        if self._session is not None:
            await self._session.close()
            self._session = None


class KubernetesDiscovery:
    """GarageNode CRD registration/query via the in-cluster API
    (ref kubernetes.rs:26-114).  `api_base`/`token`/`ca` default to the
    pod's service account and are overridable for tests."""

    def __init__(self, cfg: "KubernetesDiscoveryConfig",
                 api_base: Optional[str] = None,
                 token: Optional[str] = None,
                 ca_cert: Optional[str] = None):
        self.cfg = cfg
        if api_base is None:
            host = os.environ.get("KUBERNETES_SERVICE_HOST")
            port = os.environ.get("KUBERNETES_SERVICE_PORT", "443")
            if not host:
                raise RuntimeError(
                    "not in a Kubernetes pod (KUBERNETES_SERVICE_HOST unset) "
                    "and no api_base override given"
                )
            api_base = f"https://{host}:{port}"
        self.api_base = api_base.rstrip("/")
        if token is None and os.path.exists(f"{SA_DIR}/token"):
            with open(f"{SA_DIR}/token") as f:
                token = f.read().strip()
        self.token = token
        if ca_cert is None and os.path.exists(f"{SA_DIR}/ca.crt"):
            ca_cert = f"{SA_DIR}/ca.crt"
        self.ca_cert = ca_cert
        self._session = None

    def _crd_url(self, name: str = "") -> str:
        base = (f"{self.api_base}/apis/{K8S_GROUP}/{K8S_VERSION}"
                f"/namespaces/{self.cfg.namespace}/{K8S_PLURAL}")
        return f"{base}/{name}" if name else base

    async def _ensure_session(self):
        if self._session is None:
            import aiohttp

            headers = {}
            if self.token:
                headers["Authorization"] = f"Bearer {self.token}"
            sslctx = None
            if self.api_base.startswith("https"):
                sslctx = ssl.create_default_context(cafile=self.ca_cert)
            self._session = aiohttp.ClientSession(
                headers=headers,
                timeout=aiohttp.ClientTimeout(total=10.0),
                connector=aiohttp.TCPConnector(ssl=sslctx),
            )
        return self._session

    async def ensure_crd(self) -> None:
        """Create the GarageNode CRD if absent (ref kubernetes.rs:32-43);
        skipped when cfg.skip_crd (RBAC may forbid CRD management)."""
        if self.cfg.skip_crd:
            return
        s = await self._ensure_session()
        name = f"{K8S_PLURAL}.{K8S_GROUP}"
        url = (f"{self.api_base}/apis/apiextensions.k8s.io/v1"
               f"/customresourcedefinitions")
        async with s.get(f"{url}/{name}") as r:
            if r.status == 200:
                return
        crd = {
            "apiVersion": "apiextensions.k8s.io/v1",
            "kind": "CustomResourceDefinition",
            "metadata": {"name": name},
            "spec": {
                "group": K8S_GROUP,
                "names": {"kind": "GarageNode", "plural": K8S_PLURAL,
                          "singular": "garagenode"},
                "scope": "Namespaced",
                "versions": [{
                    "name": K8S_VERSION, "served": True, "storage": True,
                    "schema": {"openAPIV3Schema": {
                        "type": "object",
                        "properties": {"spec": {
                            "type": "object",
                            "properties": {
                                "hostname": {"type": "string"},
                                "address": {"type": "string"},
                                "port": {"type": "integer"},
                            },
                        }},
                    }},
                }],
            },
        }
        async with s.post(url, json=crd) as r:
            if r.status not in (200, 201, 409):
                logger.warning("GarageNode CRD create failed: %s", r.status)

    async def get_nodes(self) -> List[Tuple[bytes, str]]:
        """→ [(node_id, "ip:port")] from GarageNode objects labelled with
        our service name (ref kubernetes.rs:45-74)."""
        s = await self._ensure_session()
        sel = f"garage.{K8S_GROUP}/service={self.cfg.service_name}"
        async with s.get(self._crd_url(), params={"labelSelector": sel}) as r:
            r.raise_for_status()
            items = (await r.json()).get("items", [])
        out = []
        for node in items:
            name = node.get("metadata", {}).get("name", "")
            spec = node.get("spec", {})
            pubkey = _decode_pubkey(name)
            if pubkey is None:
                continue
            if spec.get("address") and spec.get("port"):
                out.append((pubkey, f"{spec['address']}:{spec['port']}"))
        return out

    async def publish(self, node_id: bytes, hostname: str,
                      rpc_public_addr: str) -> None:
        """Create-or-replace our GarageNode object (ref kubernetes.rs:76-114)."""
        s = await self._ensure_session()
        ip, port = _split_addr(rpc_public_addr)
        name = bytes(node_id).hex()
        obj = {
            "apiVersion": f"{K8S_GROUP}/{K8S_VERSION}",
            "kind": "GarageNode",
            "metadata": {
                "name": name,
                "labels": {
                    f"garage.{K8S_GROUP}/service": self.cfg.service_name,
                },
            },
            "spec": {"hostname": hostname, "address": ip, "port": port},
        }
        async with s.get(self._crd_url(name)) as r:
            if r.status == 200:
                old = await r.json()
                obj["metadata"]["resourceVersion"] = (
                    old["metadata"]["resourceVersion"]
                )
                async with s.put(self._crd_url(name), json=obj) as r2:
                    r2.raise_for_status()
                return
        async with s.post(self._crd_url(), json=obj) as r:
            r.raise_for_status()

    async def close(self) -> None:
        if self._session is not None:
            await self._session.close()
            self._session = None
