"""System — cluster membership manager.

Equivalent of reference src/rpc/system.rs: persisted node key + cluster
layout + peer list, status gossip every STATUS_EXCHANGE_INTERVAL, discovery
loop every DISCOVERY_INTERVAL (bootstrap peers + persisted peers), layout
push/pull & CRDT merge (system.rs:652-701), and `ClusterHealth` computed by
partition quorum counting (system.rs:468-527).
"""

from __future__ import annotations

import asyncio
import dataclasses
import logging
import os
import socket
import time
from typing import Callable, Dict, List, Optional

from ..net import FullMeshPeering, NetApp
from ..net.frame import PRIO_HIGH
from ..net.netapp import load_or_gen_node_key
from ..utils.config import Config
from ..utils.data import FixedBytes32
from ..utils.migrate import Migrated
from ..utils.persister import Persister
from .layout import N_PARTITIONS, ClusterLayout
from .replication_mode import ReplicationMode, parse_replication_mode
from .ring import Ring
from .rpc_helper import RpcHelper

logger = logging.getLogger("garage_tpu.rpc.system")

STATUS_EXCHANGE_INTERVAL = 10.0   # ref system.rs:44-50
DISCOVERY_INTERVAL = 60.0

SYSTEM_ENDPOINT = "garage/system"


class PersistedPeers(Migrated):
    """[(node_id, addr)] remembered across restarts (ref system.rs:88-89)."""

    VERSION_MARKER = b"GT01peers"

    def __init__(self, peers: Optional[List[List]] = None):
        self.peers = peers or []  # [ [id_bytes, addr_str], ... ]

    def fields(self):
        return self.peers

    @classmethod
    def from_fields(cls, body):
        return cls([[bytes(i), str(a)] for i, a in body])


@dataclasses.dataclass
class NodeStatus:
    """Gossiped per-node status (ref system.rs NodeStatus)."""

    hostname: str = "?"
    replication_factor: int = 0
    layout_version: int = 0
    layout_staging_hash: bytes = b""
    data_avail: Optional[int] = None   # bytes free on data disk
    data_total: Optional[int] = None
    meta_avail: Optional[int] = None
    meta_total: Optional[int] = None
    # worst per-root disk health ("ok"/"degraded"/"failed"; None = peer
    # predates the field): peers learn a node went read-only from gossip,
    # not from their next rejected rpc_put_block (block/health.py)
    disk_state: Optional[str] = None
    # software version the node runs (also exchanged in the transport
    # handshake): the rolling-upgrade drill's skew signal — `cluster
    # stats` shows which zones still run the old build.  None = peer
    # predates the field
    version: Optional[str] = None
    # current load-governor pressure (utils/overload.py; 0 idle, >= 1
    # saturated, clamped to 2).  Gateways fold the max pressure of the
    # layout nodes a request must touch into ADMISSION (shed at the
    # front door on behalf of the hot node — docs/ROBUSTNESS.md
    # "Multi-tenant fairness & noisy neighbors").  None = peer predates
    # the field / governor unwired
    governor_pressure: Optional[float] = None
    # this node's comparative fail-slow view of ITS peers (utils/
    # health_score.py): {peer hex16 prefix: score} plus the flagged
    # subset.  Riding gossip means every node learns about a straggler
    # from the nodes that actually call it — a gateway that never
    # talks to a storage node directly still demotes it in repair /
    # read ranking.  None = peer predates the fields / scorer dark
    health_scores: Optional[Dict[str, float]] = None
    fail_slow: Optional[List[str]] = None
    # graceful-drain state of the node's API front door ("draining"
    # while a SIGTERM'd gateway sheds new requests and finishes its
    # in-flight set, "drained" once it stopped serving).  Riding gossip
    # means sibling gateways and pool clients learn to absorb the load
    # BEFORE the socket closes.  None = not draining / peer predates
    # the field
    drain: Optional[str] = None

    def pack(self):
        return dataclasses.asdict(self)

    @classmethod
    def unpack(cls, d):
        return cls(**{k: d.get(k) for k in (
            "hostname", "replication_factor", "layout_version",
            "layout_staging_hash", "data_avail", "data_total",
            "meta_avail", "meta_total", "disk_state", "version",
            "governor_pressure", "health_scores", "fail_slow",
            "drain",
        )})


@dataclasses.dataclass
class ClusterHealth:
    """(ref system.rs:468-527)"""

    status: str                 # healthy | degraded | unavailable
    known_nodes: int
    connected_nodes: int
    storage_nodes: int
    storage_nodes_ok: int
    partitions: int
    partitions_quorum: int
    partitions_all_ok: int


class System:
    """Membership + layout + ring + rpc helper — the node's cluster brain
    (ref rpc/system.rs:84-123)."""

    def __init__(self, config: Config, replication_mode: Optional[ReplicationMode] = None):
        self.config = config
        self.replication_mode = replication_mode or parse_replication_mode(
            config.replication_mode
        )
        os.makedirs(config.metadata_dir, exist_ok=True)
        self.node_key = load_or_gen_node_key(
            os.path.join(config.metadata_dir, "node_key")
        )
        # the version advertised in the transport handshake and status
        # gossip; node_version overrides for mixed-version drills
        from .. import __version__ as _pkg_version

        self.version = getattr(config, "node_version", None) or _pkg_version
        self.netapp = NetApp(self.node_key, config.rpc_secret,
                             version=self.version)
        self.id = self.netapp.id
        # per-node metrics registry: every layer records into it and the
        # admin /metrics endpoint renders it (ref util/metrics.rs + the
        # per-layer metric structs).  Built BEFORE peering/netapp wiring
        # so the transport's per-peer instruments register into it.
        from ..utils.metrics import MetricsRegistry
        from ..utils.tracing import init_tracing

        self.metrics = MetricsRegistry()
        self.netapp.set_metrics(self.metrics)
        # [rpc] resilience tunables drive the per-peer circuit breakers
        # (peering) and adaptive timeouts / retries / hedging (RpcHelper)
        self.peering = FullMeshPeering(self.netapp, metrics=self.metrics,
                                       tunables=config.rpc)
        # per-peer metric series only for peers with a dialable address;
        # throwaway CLI connections aggregate under peer="transient"
        # (unbounded label growth otherwise)
        self.netapp.peer_durable_fn = lambda nid: (
            (st := self.peering.peers.get(nid)) is not None
            and st.addr is not None
        )
        # tracer next to the metrics registry: spans export to
        # admin.trace_sink when configured, no-op otherwise (ref
        # garage/tracing_setup.rs:13-37)
        self.tracer = init_tracing(
            getattr(config, "admin_trace_sink", None), bytes(self.id)
        )
        # the transport parents incoming-request handler spans on the
        # caller's propagated context (cross-node traces)
        self.netapp.tracer = self.tracer
        # request-waterfall recorder (utils/waterfall.py): every
        # finished span lands in its bounded ring; request roots are
        # sampled into per-endpoint critical-path breakdowns + retained
        # slowest trees (admin `request waterfall`), and
        # request_critical_path_seconds{endpoint,segment} derives here
        from ..utils.waterfall import WaterfallRecorder

        self.tracer.waterfall = WaterfallRecorder(metrics=self.metrics)
        # tracer self-observability: exporter health + the always-on
        # slow-op log's high-water mark are scrapeable, so "is tracing
        # even working" never needs a collector to answer
        self.metrics.gauge(
            "tracer_spans_exported_total", "Spans delivered to the "
            "OTLP collector", fn=lambda: float(self.tracer.exported))
        self.metrics.gauge(
            "tracer_spans_dropped_total", "Spans dropped (no/slow "
            "collector)", fn=lambda: float(self.tracer.dropped))
        self.metrics.gauge(
            "tracer_slow_op_max_seconds", "Slowest operation retained "
            "in the slow-op log", fn=lambda: self.tracer.slow.max_seconds())
        self.rpc = RpcHelper(self.netapp, self.peering, metrics=self.metrics,
                             tracer=self.tracer, tunables=config.rpc)

        # --- fleet health: comparative fail-slow scorer (utils/
        # health_score.py).  Fed per-peer service times by RpcHelper's
        # call path and the peering ping loop; its verdicts ride
        # NodeStatus gossip, demote flagged peers in peer_rank, and
        # trigger the incident flight recorder (model/garage.py hooks
        # on_change) ---
        from ..utils.health_score import FailSlowScorer, HealthTunables

        self.health_scorer = FailSlowScorer(
            getattr(config, "health", None) or HealthTunables())
        self.rpc.set_health_source(
            self.peer_pressure, self.peer_fail_slow,
            note=self._health_note)
        self.peering.rtt_note = (
            lambda nid, rtt: self._health_note(nid, "ping", rtt))
        # merged (local + fresh gossip) health view, cached briefly:
        # peer_rank consults it per candidate, so a rank must be a dict
        # lookup, not a gossip-table scan
        self._health_view_cache: tuple = (-1e9, {})
        self.metrics.gauge(
            "peer_health_score",
            "Comparative fail-slow score per peer (worst ratio of the "
            "peer's per-endpoint-class service time to the cluster "
            "lower-median; local + fresh gossiped views merged, max "
            "wins)",
            labeled_fn=lambda: [
                ({"peer": p}, v[0])
                for p, v in sorted(self._health_view().items())
            ],
        )
        self.metrics.gauge(
            "peer_fail_slow",
            "1 while the peer is flagged fail-slow (up, pings fine, "
            "breaker closed — but a sustained factor slower than its "
            "siblings; demoted in read/repair ranking)",
            labeled_fn=lambda: [
                ({"peer": p}, 1.0 if v[1] else 0.0)
                for p, v in sorted(self._health_view().items())
            ],
        )
        # hooks run once per status-gossip round (and whenever a drill
        # pushes a round by hand): the flight recorder's disk/cluster
        # degradation watches live here (model/garage.py registers them)
        self.status_tick_hooks: List[Callable[[], None]] = []

        # node disk gauges, observed at scrape time (ref
        # rpc/system_metrics.rs:77 statvfs-fed data/meta avail gauges);
        # one statvfs sweep serves all four gauges per scrape, and an
        # UNKNOWN value raises so the sample is omitted (Gauge.render
        # swallows observer errors) rather than read as a full disk
        self._disk_cache: tuple = (0.0, {})

        def _disk(key):
            def observe():
                return float(self._disk_stats()[key])  # KeyError → omitted
            return observe

        self.metrics.gauge("cluster_local_data_avail_bytes",
                           "Free bytes on the data disk", fn=_disk("data_avail"))
        self.metrics.gauge("cluster_local_data_total_bytes",
                           "Size of the data disk", fn=_disk("data_total"))
        self.metrics.gauge("cluster_local_meta_avail_bytes",
                           "Free bytes on the metadata disk",
                           fn=_disk("meta_avail"))
        self.metrics.gauge("cluster_local_meta_total_bytes",
                           "Size of the metadata disk", fn=_disk("meta_total"))

        self._layout_persister: Persister = Persister(
            config.metadata_dir, "cluster_layout", ClusterLayout
        )
        loaded = self._layout_persister.load()
        self.layout: ClusterLayout = (
            loaded if loaded is not None
            else ClusterLayout(self.replication_mode.replication_factor)
        )
        self.ring = Ring(self.layout)
        self._ring_callbacks: List[Callable[[Ring], None]] = []
        # committed-layout topology cache, rebuilt with the ring: feeds
        # zone-aware request ordering and the write-quorum zone check
        self._zone_map: Dict[bytes, str] = self.layout.zone_map()
        self.rpc.set_zone_source(self.zone_of, self.our_zone)
        # zone-aware fail-slow baseline: a peer is judged against its
        # same-zone siblings when enough exist, so WAN distance (a
        # healthy zone two hops away) never reads as gray failure
        self.health_scorer.zone_of = lambda p: self._zone_map.get(bytes(p))
        # info-style join metric: peer → zone per the committed layout
        # (value always 1), so Grafana can aggregate peer_up /
        # peer_breaker_state by failure domain.  labeled_fn renders the
        # LIVE map — members removed from the layout drop out with it.
        self.metrics.gauge(
            "peer_zone_info",
            "Committed-layout zone per cluster member (constant 1; "
            "join key for per-zone aggregations)",
            labeled_fn=lambda: [
                ({"peer": bytes(nid).hex()[:16], "zone": z}, 1.0)
                for nid, z in self._zone_map.items()
            ],
        )

        self._peers_persister: Persister = Persister(
            config.metadata_dir, "peer_list", PersistedPeers
        )
        saved = self._peers_persister.load()
        if saved:
            for nid, addr in saved.peers:
                self.peering.add_peer(addr, FixedBytes32(nid))

        # set by BlockManager: () -> "ok"|"degraded"|"failed" (worst
        # data-root health, gossiped in NodeStatus so peers' `cluster
        # stats` show a remote node going read-only)
        self.disk_state_fn: Optional[Callable[[], str]] = None
        # set by Garage: () -> load-governor pressure in [0, 2], gossiped
        # in NodeStatus so gateways can shed at the front door on behalf
        # of a saturated storage node (cluster-aware admission)
        self.governor_pressure_fn: Optional[Callable[[], float]] = None
        # set by the API front door while gracefully draining (ISSUE 19):
        # None (serving) -> "draining" (shedding new, finishing
        # in-flight) -> "drained" (stopped).  Gossiped in NodeStatus so
        # sibling gateways absorb load before this node's socket closes
        self.drain_state: Optional[str] = None
        self.metrics.gauge(
            "gateway_drain_state",
            "Graceful-drain state of this node's API front door "
            "(0 serving, 1 draining, 2 drained)",
            fn=lambda: {None: 0.0, "draining": 1.0,
                        "drained": 2.0}.get(self.drain_state, 0.0))

        self.node_status: Dict[FixedBytes32, NodeStatus] = {}
        # when each peer's status last arrived (monotonic): gossiped
        # pressure EXPIRES — a node that advertised hot and then died
        # must not keep gateways shedding its buckets forever
        self._status_at: Dict[FixedBytes32, float] = {}
        # the gossiped pressure map, scrapeable at any node: which peers
        # currently advertise foreground saturation (feeds the Grafana
        # "gossiped pressure map" panel and the noisy-neighbor drill)
        self.metrics.gauge(
            "cluster_peer_pressure",
            "Last load-governor pressure each peer gossiped in its "
            "NodeStatus (0 idle, >= 1 saturated; stale gossip reads 0)",
            labeled_fn=lambda: [
                ({"peer": bytes(nid).hex()[:16]},
                 self.peer_pressure(nid))
                for nid, st in self.node_status.items()
                if st.governor_pressure is not None
            ],
        )
        self._discovery = None  # external (consul/k8s) backends, built lazily
        self._tasks: List[asyncio.Task] = []
        self._stopped = asyncio.Event()

        self.endpoint = self.netapp.endpoint(SYSTEM_ENDPOINT)
        self.endpoint.set_handler(self._handle)

    # --- ring watching ---

    def on_ring_change(self, cb: Callable[[Ring], None]):
        self._ring_callbacks.append(cb)

    def _rebuild_ring(self):
        old_members = set(self._zone_map)
        self.ring = Ring(self.layout)
        self._zone_map = self.layout.zone_map()
        # when partitions last moved (monotonic): repair paths use this
        # to decide whether an empty quorum read may be BLIND (new
        # replicas not yet synced) and worth a cluster sweep — see
        # model/parity_repair._sweep_index_entries
        self.ring_changed_at = time.monotonic()
        # peers REMOVED from the committed layout are gone for good:
        # drop their peer-book entries, breaker state and per-peer
        # metric series, or `peer_up`/`peer_rtt_ewma_seconds`/
        # `peer_breaker_state` keep reporting a node that no longer
        # exists (and its breaker would greet a re-added node with
        # stale failure history)
        for nid in old_members - set(self._zone_map):
            fb = FixedBytes32(nid)
            if fb == self.id:
                continue
            self.peering.forget_peer(fb)
            self.netapp.forget_peer_series(fb)
            self.node_status.pop(fb, None)
            self._status_at.pop(fb, None)
            # scorer digests + fail-slow verdict go with the peer (a
            # re-added node inherits no slowness history), and the
            # merged view drops its series on the next rebuild
            self.health_scorer.forget(nid)
            self._health_view_cache = (-1e9, {})
        for cb in self._ring_callbacks:
            try:
                cb(self.ring)
            except Exception:
                logger.exception("ring-change callback failed")

    # --- topology (zone failure domains; docs/ROBUSTNESS.md) ---

    def zone_of(self, node) -> Optional[str]:
        """Zone of a node per the COMMITTED layout (None when the node
        carries no role — e.g. a pure gateway client)."""
        return self._zone_map.get(bytes(node))

    def our_zone(self) -> Optional[str]:
        return self._zone_map.get(bytes(self.id))

    def write_zone_requirement(self, nodes) -> int:
        """How many distinct zones a write to `nodes` must span: the
        layout's HARD (integer) zone_redundancy, capped at the zones the
        candidate set can actually reach — so the check can always be
        satisfied when every candidate acks, and a dark zone surfaces as
        the typed ZoneQuorumError instead of an impossible bar.  0 under
        "maximum" (availability-first) or when topology is unknown."""
        hard = self.layout.hard_zone_redundancy()
        if not hard or hard <= 1:
            return 0
        zones = {self._zone_map[bytes(n)] for n in nodes
                 if bytes(n) in self._zone_map}
        if not zones:
            return 0
        return min(hard, len(zones))

    # --- layout operations ---

    async def update_cluster_layout(self, other: ClusterLayout):
        """CRDT-merge a layout received from a peer or the CLI; on change,
        persist, rebuild ring, and push to peers
        (ref system.rs:652-701 handle_advertise_cluster_layout)."""
        changed = self.layout.merge(other)
        if changed:
            self._layout_persister.save(self.layout)
            self._rebuild_ring()
            await self._push_layout()

    async def _push_layout(self):
        msg = {"t": "advertise_layout", "layout": self.layout.encode()}
        await self.rpc.broadcast(self.endpoint, msg, prio=PRIO_HIGH, timeout=10.0)

    def save_layout(self):
        """Persist the current (possibly staged) layout (admin path)."""
        self._layout_persister.save(self.layout)

    async def broadcast_layout(self):
        await self._push_layout()

    # --- status gossip ---

    def _local_status(self) -> NodeStatus:
        st = NodeStatus(
            hostname=socket.gethostname(),
            replication_factor=self.replication_mode.replication_factor,
            layout_version=self.layout.version,
            layout_staging_hash=bytes(self.layout.staging_hash()),
            version=self.version,
        )
        disk = self._disk_stats()
        st.meta_avail = disk.get("meta_avail")
        st.meta_total = disk.get("meta_total")
        st.data_avail = disk.get("data_avail")
        st.data_total = disk.get("data_total")
        if self.disk_state_fn is not None:
            try:
                st.disk_state = self.disk_state_fn()
            except Exception:  # noqa: BLE001 — gossip must never break
                logger.exception("disk_state_fn failed")
        if self.governor_pressure_fn is not None:
            try:
                st.governor_pressure = round(
                    float(self.governor_pressure_fn()), 4)
            except Exception:  # noqa: BLE001 — gossip must never break
                logger.exception("governor_pressure_fn failed")
        try:
            scores = self.health_scorer.scores()
            if scores:
                st.health_scores = {
                    p: v["score"] for p, v in scores.items()
                    if v["score"] is not None
                }
                st.fail_slow = [
                    p for p, v in scores.items() if v["fail_slow"]
                ]
        except Exception:  # noqa: BLE001 — gossip must never break
            logger.exception("health scorer snapshot failed")
        st.drain = self.drain_state
        return st

    def _disk_stats(self) -> dict:
        """statvfs snapshot shared by the Prometheus gauges AND the
        gossiped NodeStatus (one implementation — they must not diverge),
        cached briefly so one scrape's four gauges do a single sweep.
        Multi-data-dir nodes sum avail/total across DISTINCT filesystems
        (ref rpc/system.rs update_disk_usage dedups by fsid).  Missing
        keys mean 'unknown' — gauge observers let the KeyError propagate
        so the sample is omitted."""
        now = time.monotonic()
        ts, vals = self._disk_cache
        if vals and now - ts < 1.0:
            return vals
        vals = {}
        try:
            sv = os.statvfs(self.config.metadata_dir)
            vals["meta_avail"] = sv.f_bavail * sv.f_frsize
            vals["meta_total"] = sv.f_blocks * sv.f_frsize
        except OSError:
            pass
        if self.config.data_dir:
            avail = total = 0
            seen_fs = set()
            ok = False
            for d in self.config.data_dir:
                try:
                    sv = os.statvfs(d["path"])
                except OSError:
                    continue
                ok = True
                if sv.f_fsid in seen_fs:
                    continue  # same filesystem mounted twice: count once
                seen_fs.add(sv.f_fsid)
                avail += sv.f_bavail * sv.f_frsize
                total += sv.f_blocks * sv.f_frsize
            if ok:
                vals["data_avail"] = avail
                vals["data_total"] = total
        self._disk_cache = (now, vals)
        return vals

    def _peer_book(self) -> list:
        """[(id, addr)] of every dialable peer this node knows, plus
        itself — the payload of peer-list gossip."""
        out = []
        my_addr = self.config.rpc_public_addr
        if my_addr:
            out.append([bytes(self.id), my_addr])
        for nid, (addr, _up, _lat) in self.peering.peer_info().items():
            if addr:
                out.append([bytes(nid), addr])
        return out

    async def advertise_status(self):
        """One status-gossip round: broadcast our NodeStatus (disk
        health, governor pressure, layout version) plus the peer book.
        The exchange loop calls this on its interval; drills/tests call
        it directly to push fresh pressure without waiting 10 s."""
        # Peer-list gossip rides the status broadcast: an operator
        # who runs `connect` against ONE node (the star bootstrap
        # every real deployment starts as) must converge to a full
        # mesh — without address exchange, nodes only ever know
        # the peers someone explicitly dialed for them, and a
        # partition heals only by operator action (observed: star
        # survivors couldn't reach table quorums after node loss).
        # ref: netapp's FullMeshPeeringStrategy PeerList exchange.
        # Status-tick hooks first (flight-recorder degradation watches,
        # registered by model/garage.py): they observe state transitions
        # on the gossip cadence, and a drill pushing a round by hand
        # evaluates them immediately
        for hook in self.status_tick_hooks:
            try:
                hook()
            except Exception:  # noqa: BLE001 — watches never break gossip
                logger.exception("status tick hook failed")
        msg = {
            "t": "advertise_status",
            "status": self._local_status().pack(),
            "peers": self._peer_book(),
        }
        await self.rpc.broadcast(self.endpoint, msg, prio=PRIO_HIGH,
                                 timeout=10.0)

    async def _status_exchange_loop(self):
        while not self._stopped.is_set():
            try:
                await self.advertise_status()
            except Exception as e:
                logger.debug("status exchange failed: %s", e)
            await asyncio.sleep(STATUS_EXCHANGE_INTERVAL)

    def _external_discovery(self):
        """Lazily construct the configured external discovery backends
        (ref system.rs:336-360: consul/kubernetes are optional features)."""
        if self._discovery is None:
            self._discovery = []
            if self.config.consul_discovery is not None:
                from .discovery import ConsulDiscovery

                self._discovery.append(
                    ConsulDiscovery(self.config.consul_discovery)
                )
            if self.config.kubernetes_discovery is not None:
                try:
                    from .discovery import KubernetesDiscovery

                    k8s = KubernetesDiscovery(
                        self.config.kubernetes_discovery
                    )
                    self._k8s_crd_pending = not (
                        self.config.kubernetes_discovery.skip_crd
                    )
                    self._discovery.append(k8s)
                except Exception as e:  # not in a pod: log once, disable
                    logger.warning("kubernetes discovery disabled: %s", e)
        return self._discovery

    async def _external_discovery_tick(self):
        """Publish ourselves + learn peers from Consul/Kubernetes (ref
        system.rs:726-808: runs every discovery tick, errors only warn)."""
        public = self.config.rpc_public_addr
        for d in self._external_discovery():
            # publish and query fail independently (ref system.rs:726-808):
            # a node with a read-only catalog token must still learn peers
            try:
                if getattr(self, "_k8s_crd_pending", False) and hasattr(
                    d, "ensure_crd"
                ):
                    await d.ensure_crd()
                    self._k8s_crd_pending = False
                if public:
                    await d.publish(
                        bytes(self.id), socket.gethostname(), public
                    )
            except Exception as e:
                logger.warning("discovery publish via %s failed: %s",
                               type(d).__name__, e)
            try:
                for node_id, addr in await d.get_nodes():
                    if node_id != bytes(self.id):
                        self.peering.add_peer(addr, FixedBytes32(node_id))
            except Exception as e:
                logger.warning("discovery query via %s failed: %s",
                               type(d).__name__, e)

    async def _discovery_loop(self):
        while not self._stopped.is_set():
            for addr in self.config.bootstrap_peers:
                self.peering.add_peer(addr)
            await self._external_discovery_tick()
            await self.peering._tick()
            # persist known peers for next restart
            peers = [
                [bytes(nid), st.addr]
                for nid, st in self.peering.peers.items()
                if st.addr
            ]
            try:
                self._peers_persister.save(PersistedPeers(peers))
            except OSError as e:
                logger.debug("peer list save failed: %s", e)
            # pull layout from a peer if ours is older than advertised
            await asyncio.sleep(DISCOVERY_INTERVAL)

    # --- rpc handler ---

    async def _handle(self, remote, msg, body):
        t = msg.get("t")
        if t == "pull_layout":
            return {"layout": self.layout.encode()}, None
        if t == "advertise_layout":
            other = ClusterLayout.decode(bytes(msg["layout"]))
            await self.update_cluster_layout(other)
            return {"ok": True}, None
        if t == "advertise_status":
            st = NodeStatus.unpack(msg["status"])
            prev = self.node_status.get(FixedBytes32(remote))
            self.node_status[FixedBytes32(remote)] = st
            self._status_at[FixedBytes32(remote)] = time.monotonic()
            # fresh gossip may carry new fail-slow verdicts: rebuild
            # the merged health view on the next read (drills assert
            # flag propagation within a bounded number of exchanges).
            # Only when the health fields actually CHANGED — at fleet
            # scale every node receives N-1 statuses per interval, and
            # unconditionally nuking the ~1 s cache would make
            # peer_rank re-score per gossip message
            if (prev is None
                    or prev.health_scores != st.health_scores
                    or prev.fail_slow != st.fail_slow):
                self._health_view_cache = (-1e9, {})
            # a peer with a newer layout triggers a pull
            if st.layout_version > self.layout.version:
                asyncio.get_running_loop().create_task(self._pull_layout(remote))
            # peer-list gossip: learn every (id, addr) the sender knows;
            # the peering tick dials the ones we aren't connected to
            for pair in msg.get("peers", []) or []:
                try:
                    nid, addr = bytes(pair[0]), str(pair[1])
                    if len(nid) == 32 and nid != bytes(self.id):
                        self.peering.add_peer(addr, FixedBytes32(nid))
                except Exception:  # noqa: BLE001 — gossip is best-effort
                    continue
            return {"ok": True}, None
        if t == "ping":
            return {"pong": True, "id": bytes(self.id)}, None
        raise ValueError(f"unknown system message {t!r}")

    async def _pull_layout(self, node):
        try:
            resp = await self.endpoint.call(
                FixedBytes32(node), {"t": "pull_layout"}, prio=PRIO_HIGH, timeout=10.0
            )
            await self.update_cluster_layout(
                ClusterLayout.decode(bytes(resp["layout"]))
            )
        except Exception as e:
            logger.debug("layout pull from %s failed: %s", bytes(node).hex()[:8], e)

    # --- health (ref system.rs:468-527) ---

    def health(self) -> ClusterHealth:
        roles = self.layout.node_roles()
        storage_nodes = [
            nid for nid, r in roles.items() if r.capacity is not None
        ]
        storage_ok = [n for n in storage_nodes if self.peering.is_up(FixedBytes32(n))]
        partitions = N_PARTITIONS if self.ring.ready else 0
        quorum = self.replication_mode.write_quorum
        p_quorum = p_all = 0
        for p in range(partitions):
            nodes = self.ring.partition_nodes(p)
            up = sum(1 for n in nodes if self.peering.is_up(n))
            if up == len(nodes):
                p_all += 1
            if up >= quorum:
                p_quorum += 1
        if partitions and p_quorum == partitions and len(storage_ok) == len(storage_nodes):
            status = "healthy"
        elif partitions and p_quorum == partitions:
            status = "degraded"
        else:
            status = "unavailable"
        return ClusterHealth(
            status=status,
            known_nodes=len(self.peering.peers) + 1,
            connected_nodes=len(self.peering.connected_nodes()) + 1,
            storage_nodes=len(storage_nodes),
            storage_nodes_ok=len(storage_ok),
            partitions=partitions,
            partitions_quorum=p_quorum,
            partitions_all_ok=p_all,
        )

    def peer_version(self, nid) -> Optional[str]:
        """Software version `nid` last gossiped (status exchange), ours
        for self, None when unknown — the capability gate mixed-version
        rollouts key on (e.g. block/repair_plan.py sends the `ppr`
        partial-product RPC only to peers new enough to answer it, and
        falls back to whole-shard fetch otherwise)."""
        if bytes(nid) == bytes(self.id):
            return self.version
        st = self.node_status.get(FixedBytes32(bytes(nid)))
        return st.version if st is not None else None

    # gossiped pressure older than this reads as 0: a hot node that
    # crashed (or partitioned away) must stop shedding its buckets at
    # every gateway within a few missed exchange rounds
    PRESSURE_TTL = 3 * STATUS_EXCHANGE_INTERVAL

    def peer_pressure(self, nid) -> float:
        """Load-governor pressure `nid` last gossiped (0.0 when unknown
        or STALE — a silent peer is not presumed hot, and a dead one
        must not stay hot forever).  Cluster-aware admission folds the
        max over a request's placement nodes into the admit decision
        (api/admission.py RemotePressureProbe); the degraded-read
        planner can rank survivors by the same signal."""
        if bytes(nid) == bytes(self.id):
            if self.governor_pressure_fn is not None:
                try:
                    return float(self.governor_pressure_fn())
                except Exception:  # noqa: BLE001
                    return 0.0
            return 0.0
        fb = FixedBytes32(bytes(nid))
        st = self.node_status.get(fb)
        if st is None or st.governor_pressure is None:
            return 0.0
        at = self._status_at.get(fb)
        if at is None or time.monotonic() - at > self.PRESSURE_TTL:
            return 0.0
        return float(st.governor_pressure)

    # --- fleet health (fail-slow detection; utils/health_score.py) ---

    def _health_note(self, node, cls: str, seconds: float) -> None:
        """Per-peer service-time tap (RpcHelper call outcomes + peering
        ping RTTs).  DURABLE peers only: a throwaway CLI connection must
        not grow scorer digests or `peer_health_score` series."""
        st = self.peering.peers.get(FixedBytes32(bytes(node)))
        if st is None or st.addr is None:
            return
        try:
            self.health_scorer.note(bytes(node), cls, seconds)
        except Exception:  # noqa: BLE001 — scoring never breaks calls
            logger.debug("health note failed", exc_info=True)

    def _health_view(self) -> Dict[str, list]:
        """{peer hex16: [score, flagged]} — the LOCAL scorer's verdicts
        merged with every fresh gossiped report (max score wins; any
        fresh reporter's flag flags).  Gossip staleness uses the same
        TTL as pressure: a reporter that died must not keep a peer
        demoted forever.  Cached ~1 s — peer_rank consults this per
        candidate."""
        now = time.monotonic()
        ts, view = self._health_view_cache
        if now - ts < 1.0:
            return view
        view = {}
        try:
            for p, v in self.health_scorer.scores().items():
                view[p] = [v["score"] if v["score"] is not None else 0.0,
                           bool(v["fail_slow"])]
        except Exception:  # noqa: BLE001
            logger.debug("health scorer read failed", exc_info=True)
        me = bytes(self.id).hex()[:16]
        for nid, st in self.node_status.items():
            at = self._status_at.get(nid)
            if at is None or now - at > self.PRESSURE_TTL:
                continue
            for p, s in (st.health_scores or {}).items():
                if p == me:
                    continue  # peers' view of US never demotes a third
                cur = view.setdefault(p, [0.0, False])
                if s is not None and float(s) > cur[0]:
                    cur[0] = float(s)
            for p in (st.fail_slow or []):
                if p == me:
                    continue
                view.setdefault(p, [0.0, False])[1] = True
        self._health_view_cache = (now, view)
        return view

    def peer_core_row(self, nid, st) -> dict:
        """The shared per-peer health core — admin ``cluster stats``
        rows and the flight recorder's ``peers`` section both build on
        it, so a new gossiped field lands in both views at once.
        ``st`` is the peering PeerState for ``nid``."""
        status = self.node_status.get(nid)
        return {
            "id": bytes(nid).hex(),
            "zone": self.zone_of(nid),
            "up": st.is_up,
            "rtt_ewma_ms": (round(st.latency * 1000.0, 3)
                            if st.latency is not None else None),
            "breaker": self.peering.breaker_state(nid),
            "pressure": self.peer_pressure(nid),
            "health_score": self.peer_health_score(nid),
            "fail_slow": self.peer_fail_slow(nid),
            "disk_state": status.disk_state if status else None,
            "version": (self.netapp.peer_versions.get(nid)
                        or (status.version if status else None)),
            "drain": status.drain if status else None,
        }

    def peer_health_score(self, nid) -> Optional[float]:
        """Merged comparative health score for `nid` (None = nobody can
        judge it yet)."""
        v = self._health_view().get(bytes(nid).hex()[:16])
        return v[0] if v is not None else None

    def peer_fail_slow(self, nid) -> bool:
        """Is `nid` fail-slow, per the local scorer OR any fresh
        gossiped reporter?  Consumed by RpcHelper.peer_rank (read
        ordering) and through it by RepairPlanner survivor ranking —
        demotion only, never exclusion: a flagged peer still serves
        when the healthy candidates are exhausted."""
        if bytes(nid) == bytes(self.id):
            return False
        v = self._health_view().get(bytes(nid).hex()[:16])
        return bool(v is not None and v[1])

    def get_known_nodes(self) -> List[dict]:
        """Peer list for status displays (ids as hex, JSON-safe)."""
        out = [{
            "id": bytes(self.id).hex(),
            "addr": self.config.rpc_public_addr or self.config.rpc_bind_addr,
            "is_up": True,
            "last_seen_secs_ago": 0,
            "hostname": self._local_status().hostname,
        }]
        now = time.monotonic()
        for nid, st in self.peering.peers.items():
            status = self.node_status.get(nid)
            out.append({
                "id": bytes(nid).hex(),
                "addr": st.addr,
                "is_up": st.is_up,
                "last_seen_secs_ago": (
                    int(now - st.last_seen) if st.last_seen else None
                ),
                "hostname": status.hostname if status else None,
            })
        return out

    # --- lifecycle (ref system.rs:391-400) ---

    async def run(self):
        await self.netapp.listen(self.config.rpc_bind_addr)
        self.peering.start()
        self.tracer.start()  # flush loop; no-op unless trace_sink configured
        loop = asyncio.get_running_loop()
        self._tasks = [
            loop.create_task(self._status_exchange_loop()),
            loop.create_task(self._discovery_loop()),
        ]

    async def shutdown(self):
        self._stopped.set()
        # drain quorum-write stragglers / cancelled read losers while the
        # transport is still alive (they are talking through it)
        await self.rpc.shutdown(timeout=5.0)
        for t in self._tasks:
            t.cancel()
        for d in (self._discovery or []):
            try:
                await d.close()
            except Exception:
                pass
        await self.peering.stop()
        await self.netapp.shutdown()
