"""Ring — the partition table derived from the cluster layout.

Equivalent of reference src/rpc/ring.rs: 2^8 partitions keyed by the top
bits of the 256-bit item hash (PARTITION_BITS=8, ring.rs:20); each ring
entry lists the nodes of that partition (ring.rs:53-61); `get_nodes(hash,
n)` returns the first n replicas (ring.rs:131-153).
"""

from __future__ import annotations

from typing import List, Optional, Tuple

from ..utils.data import FixedBytes32, Hash
from .layout import N_PARTITIONS, ClusterLayout

PARTITION_BITS = 8
assert N_PARTITIONS == 1 << PARTITION_BITS


def partition_of(h: bytes) -> int:
    """Top PARTITION_BITS bits of the hash → partition index."""
    return h[0]


def partition_prefix(partition: int) -> bytes:
    """First bytes of the hash range covered by this partition — used as
    the boundary key for per-partition Merkle trees and sync ranges."""
    return bytes([partition])


def partition_range(partition: int) -> Tuple[Hash, Optional[Hash]]:
    """[first_hash, last_hash) of the partition (ref ring.rs partition
    boundaries); end is None for the last partition."""
    first = Hash(bytes([partition]) + b"\x00" * 31)
    if partition == N_PARTITIONS - 1:
        return first, None
    return first, Hash(bytes([partition + 1]) + b"\x00" * 31)


class Ring:
    """Immutable view: layout version → partition → replica nodes
    (ref ring.rs:64-97)."""

    def __init__(self, layout: ClusterLayout):
        self.layout = layout
        self.replication_factor = layout.replication_factor
        f = layout.replication_factor
        self._partitions: List[List[bytes]] = []
        if (
            layout.ring_assignment_data
            and len(layout.ring_assignment_data) == N_PARTITIONS * f
        ):
            for p in range(N_PARTITIONS):
                nodes = layout.partition_nodes(p)
                # Rotate each partition's replica list by the partition
                # index: the optimizer balances the replica SETS but not
                # their order, and with full-factor writes order never
                # mattered — every set member stores a copy.  Under
                # data_replication_mode with a smaller data factor the
                # FIRST entries are load-bearing (they are the only
                # storage nodes), and unrotated lists concentrated every
                # primary on a couple of nodes (measured: 3 equal nodes →
                # 256/256 primaries on one).  The rotation is a pure
                # function of (partition, set) so every node agrees.
                if nodes:
                    r = p % len(nodes)
                    nodes = nodes[r:] + nodes[:r]
                self._partitions.append(nodes)
        else:
            self._partitions = [[] for _ in range(N_PARTITIONS)]

    def digest(self) -> bytes:
        """16-byte digest of the effective partition assignment — stable
        across re-decodes of the same layout, changed by any assignment
        change.  Used by the layout-sweep marker (block/repair.py) to
        detect ring changes a node missed while down."""
        import hashlib

        h = hashlib.blake2s(digest_size=16)
        for nodes in self._partitions:
            for n in nodes:
                h.update(bytes(n))
            h.update(b"|")
        return h.digest()

    @property
    def ready(self) -> bool:
        return bool(self._partitions[0])

    def partition_of(self, position: bytes) -> int:
        return partition_of(position)

    def get_nodes(self, position: bytes, n: int) -> List[FixedBytes32]:
        """Replica nodes for the item at `position` (ref ring.rs:131-153)."""
        nodes = self._partitions[partition_of(position)]
        if not nodes:
            return []
        if n > len(nodes):
            n = len(nodes)
        return [FixedBytes32(x) for x in nodes[:n]]

    def partitions(self) -> List[Tuple[int, Hash]]:
        """All (partition index, first hash) pairs (ref ring.rs partitions)."""
        return [
            (p, partition_range(p)[0]) for p in range(N_PARTITIONS)
        ]

    def partition_nodes(self, partition: int) -> List[FixedBytes32]:
        return [FixedBytes32(x) for x in self._partitions[partition]]
