"""rpc — cluster membership, partition ring, layout optimizer, quorum calls.

Equivalent of reference src/rpc (SURVEY.md §2.3): `System` (membership,
gossip, discovery, health), `Ring` (2^8-partition table), `ClusterLayout`
(CRDT'd staged role assignment with flow-optimized partition placement),
`RpcHelper` (quorum fan-out with interrupt-after-quorum reads and
latency-ordered sends), and `graph_algo` (max-flow / min-cost flow used by
the layout optimizer).
"""
