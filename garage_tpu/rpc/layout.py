"""Cluster layout: CRDT'd role assignment + flow-optimized partition placement.

Equivalent of reference src/rpc/layout.rs (SURVEY.md §2.3): node roles
(zone, capacity, tags) are staged through an LwwMap and applied with an
explicit version bump (`apply_staged_changes`, ref layout.rs:307); the
partition→nodes assignment is computed by **dichotomy on the partition size
combined with max-flow feasibility** (ref layout.rs:592-680,783), then
**movement vs the previous layout is minimized** by cost-optimizing the
flow (edges that keep a replica where it was cost 0, moves cost 1; ref
layout.rs:819-980 + graph_algo negative-cycle cancellation).

Zone redundancy: every partition must span at least
min(zone_redundancy, n_zones) distinct zones; enforced in the flow graph by
capping each partition→zone edge at factor − zr + 1 replicas.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Tuple, Union

from ..utils.crdt import Lww, LwwMap
from ..utils.data import Hash, blake2sum
from ..utils.error import LayoutError
from ..utils.migrate import Migrated, pack
from .graph_algo import Graph

N_PARTITIONS = 256  # 2^PARTITION_BITS, ref rpc/ring.rs:20


@dataclasses.dataclass
class NodeRole:
    """A node's assigned role (ref layout.rs NodeRole)."""

    zone: str
    capacity: Optional[int]  # None = gateway (no data stored)
    tags: List[str] = dataclasses.field(default_factory=list)

    def pack(self):
        return [self.zone, self.capacity, list(self.tags)]

    @classmethod
    def unpack(cls, v):
        if v is None:
            return None
        return cls(zone=v[0], capacity=v[1], tags=list(v[2]))

    def capacity_string(self) -> str:
        return "gateway" if self.capacity is None else str(self.capacity)


ZoneRedundancy = Union[int, str]  # int or "maximum"


@dataclasses.dataclass
class LayoutParameters:
    zone_redundancy: ZoneRedundancy = "maximum"

    def pack(self):
        return [self.zone_redundancy]

    @classmethod
    def unpack(cls, v):
        return cls(zone_redundancy=v[0])


class ClusterLayout(Migrated):
    """The full cluster layout (ref layout.rs:88-129 v09 struct)."""

    VERSION_MARKER = b"GT01layout"

    def __init__(self, replication_factor: int = 3):
        self.version = 0
        self.replication_factor = replication_factor
        self.parameters = LayoutParameters()
        self.roles: LwwMap = LwwMap()            # node_id bytes -> NodeRole | None
        self.staging_parameters: Lww = Lww(LayoutParameters().pack(), ts=0)
        self.staging_roles: LwwMap = LwwMap()
        self.node_id_vec: List[bytes] = []
        self.ring_assignment_data: List[int] = []  # flat: partition p replica r
                                                   # -> index into node_id_vec

    # --- serialization ---

    def fields(self):
        return {
            "version": self.version,
            "replication_factor": self.replication_factor,
            "parameters": self.parameters.pack(),
            "roles": [[k, e.pack()] for k, e in self.roles.sorted_items()],
            "staging_parameters": self.staging_parameters.pack(),
            "staging_roles": [
                [k, e.pack()] for k, e in self.staging_roles.sorted_items()
            ],
            "node_id_vec": list(self.node_id_vec),
            "ring_assignment_data": list(self.ring_assignment_data),
        }

    @classmethod
    def from_fields(cls, d):
        lay = cls(replication_factor=d["replication_factor"])
        lay.version = d["version"]
        lay.parameters = LayoutParameters.unpack(d["parameters"])
        lay.roles = LwwMap({k: Lww(e[1], ts=e[0]) for k, e in d["roles"]})
        sp = d["staging_parameters"]
        lay.staging_parameters = Lww(sp[1], ts=sp[0])
        lay.staging_roles = LwwMap({k: Lww(e[1], ts=e[0]) for k, e in d["staging_roles"]})
        lay.node_id_vec = [bytes(x) for x in d["node_id_vec"]]
        lay.ring_assignment_data = list(d["ring_assignment_data"])
        return lay

    # --- inspection ---

    def node_roles(self) -> Dict[bytes, NodeRole]:
        """Current (applied) roles, removed nodes excluded."""
        out = {}
        for k, e in self.roles.items.items():
            role = NodeRole.unpack(e.value)
            if role is not None:
                out[bytes(k)] = role
        return out

    def zone_map(self) -> Dict[bytes, str]:
        """node id → zone for every node in the COMMITTED layout (the
        routing/quorum layers' source of topology truth)."""
        return {nid: r.zone for nid, r in self.node_roles().items()}

    def hard_zone_redundancy(self) -> Optional[int]:
        """The integer zone_redundancy when the layout DEMANDS zone
        spread from write quorums; None for "maximum", which asks
        placement for the widest spread but keeps writes
        availability-first when a whole zone is dark."""
        zr = self.parameters.zone_redundancy
        if isinstance(zr, int):
            return min(zr, self.replication_factor)
        return None

    def staged_roles(self) -> Dict[bytes, Optional[NodeRole]]:
        return {
            bytes(k): NodeRole.unpack(e.value)
            for k, e in self.staging_roles.items.items()
        }

    def staging_hash(self) -> Hash:
        return blake2sum(
            pack([
                [[k, e.pack()] for k, e in self.staging_roles.sorted_items()],
                self.staging_parameters.pack(),
            ])
        )

    def partition_nodes(self, partition: int) -> List[bytes]:
        f = self.replication_factor
        idxs = self.ring_assignment_data[partition * f : (partition + 1) * f]
        return [self.node_id_vec[i] for i in idxs]

    def all_nodes(self) -> List[bytes]:
        return [bytes(k) for k in self.node_roles()]

    # --- staging (ref layout.rs:307-335) ---

    def stage_role(self, node_id: bytes, role: Optional[NodeRole]):
        self.staging_roles.update(bytes(node_id), role.pack() if role else None)

    def stage_parameters(self, params: LayoutParameters):
        self.staging_parameters.update(params.pack())

    def apply_staged_changes(self, version: Optional[int] = None) -> List[str]:
        expected = self.version + 1
        if version is not None and version != expected:
            raise LayoutError(
                f"expected version {expected} to apply staged changes, got {version}"
            )
        self.roles.merge(self.staging_roles)
        # drop removed nodes from the map entirely once applied
        self.parameters = LayoutParameters.unpack(self.staging_parameters.value)
        msgs = self.calculate_partition_assignment()
        self.staging_roles = LwwMap()
        self.staging_parameters = Lww(self.parameters.pack(), ts=0)
        self.version = expected
        return msgs

    def revert_staged_changes(self, version: Optional[int] = None):
        expected = self.version + 1
        if version is not None and version != expected:
            raise LayoutError(
                f"expected version {expected} to revert staged changes, got {version}"
            )
        self.staging_roles = LwwMap()
        self.staging_parameters = Lww(self.parameters.pack(), ts=0)
        self.version = expected

    # --- CRDT merge (ref layout.rs:286-305) ---

    def merge(self, other: "ClusterLayout") -> bool:
        if other.version > self.version:
            self.version = other.version
            self.replication_factor = other.replication_factor
            self.parameters = other.parameters
            self.roles = other.roles
            self.staging_parameters = other.staging_parameters
            self.staging_roles = other.staging_roles
            self.node_id_vec = list(other.node_id_vec)
            self.ring_assignment_data = list(other.ring_assignment_data)
            return True
        if other.version == self.version:
            before = self.staging_hash()
            self.staging_roles.merge(other.staging_roles)
            self.staging_parameters.merge(other.staging_parameters)
            return self.staging_hash() != before
        return False

    # --- validation (ref layout.rs:467) ---

    def check(self) -> List[str]:
        errors = []
        f = self.replication_factor
        if self.version > 0 and self.ring_assignment_data:
            if len(self.ring_assignment_data) != N_PARTITIONS * f:
                errors.append(
                    f"ring_assignment_data has {len(self.ring_assignment_data)} "
                    f"entries, expected {N_PARTITIONS * f}"
                )
            else:
                roles = self.node_roles()
                for p in range(N_PARTITIONS):
                    nodes = self.partition_nodes(p)
                    if len(set(nodes)) != f:
                        errors.append(f"partition {p} has duplicate nodes")
                        break
                    for n in nodes:
                        role = roles.get(n)
                        if role is None or role.capacity is None:
                            errors.append(
                                f"partition {p} assigned to non-storage node"
                            )
                            break
        return errors

    # --- partition assignment (ref layout.rs:592-680) ---

    def _storage_nodes(self) -> Dict[bytes, NodeRole]:
        return {
            nid: role
            for nid, role in self.node_roles().items()
            if role.capacity is not None
        }

    def effective_zone_redundancy(self) -> int:
        zones = {r.zone for r in self._storage_nodes().values()}
        zr = self.parameters.zone_redundancy
        if zr == "maximum":
            return min(self.replication_factor, max(len(zones), 1))
        return min(int(zr), self.replication_factor)

    def calculate_partition_assignment(
        self, n_partitions: int = N_PARTITIONS
    ) -> List[str]:
        f = self.replication_factor
        storage = self._storage_nodes()
        if len(storage) < f:
            raise LayoutError(
                f"not enough storage nodes: {len(storage)} < replication factor {f}"
            )
        zr = self.effective_zone_redundancy()
        zones = sorted({r.zone for r in storage.values()})
        if len(zones) < zr:
            raise LayoutError(
                f"not enough zones: {len(zones)} < zone redundancy {zr}"
            )

        # previous assignment, for movement minimization
        old_nodes_of: List[set] = [set() for _ in range(n_partitions)]
        if self.ring_assignment_data and self.node_id_vec:
            old_f = (
                len(self.ring_assignment_data) // n_partitions
                if len(self.ring_assignment_data) % n_partitions == 0
                else 0
            )
            for p in range(n_partitions if old_f else 0):
                for i in self.ring_assignment_data[p * old_f : (p + 1) * old_f]:
                    if i < len(self.node_id_vec):
                        old_nodes_of[p].add(self.node_id_vec[i])

        s_opt = compute_optimal_partition_size(storage, f, zr, n_partitions)

        g = _assignment_graph(storage, f, zr, n_partitions, s_opt, old_nodes_of)
        flow = g.compute_maximal_flow("src", "sink")
        assert flow == n_partitions * f, (flow, n_partitions * f)
        g.optimize_flow_with_cost()

        new_node_id_vec = sorted(storage.keys())
        idx_of = {nid: i for i, nid in enumerate(new_node_id_vec)}
        assignment: List[List[int]] = [[] for _ in range(n_partitions)]
        usage: Dict[bytes, int] = {nid: 0 for nid in storage}
        for u, v, fl in g.positive_flow_edges():
            if isinstance(u, tuple) and u[0] == "pz" and isinstance(v, tuple) and v[0] == "n":
                p, nid = u[1], v[1]
                assignment[p].append(idx_of[nid])
                usage[nid] += 1
        moved = 0
        for p in range(n_partitions):
            assert len(assignment[p]) == f, (p, assignment[p])
            assignment[p].sort()
            new_set = {new_node_id_vec[i] for i in assignment[p]}
            moved += len(new_set - old_nodes_of[p])
        had_old = any(old_nodes_of[p] for p in range(n_partitions))

        self.node_id_vec = new_node_id_vec
        self.ring_assignment_data = [i for p in assignment for i in p]

        msgs = [
            f"partition size: {s_opt}",
            f"zone redundancy: {zr} (zones: {', '.join(zones)})",
        ]
        if had_old:
            msgs.append(f"{moved} partition replicas moved")
        for nid in new_node_id_vec:
            cap = storage[nid].capacity
            msgs.append(
                f"  node {nid.hex()[:16]} zone={storage[nid].zone} "
                f"usage={usage[nid]}/{cap // s_opt} partitions "
                f"({usage[nid] * s_opt * 100 // max(cap, 1)}% of capacity)"
            )
        return msgs


def _assignment_graph(
    storage: Dict[bytes, NodeRole],
    f: int,
    zr: int,
    n_partitions: int,
    partition_size: int,
    old_nodes_of: List[set],
) -> Graph:
    """Flow network (ref layout.rs:819-980):
      src → ("p", p)                     cap f
      ("p", p) → ("pz", p, zone)         cap f − zr + 1   (zone redundancy)
      ("pz", p, z) → ("n", node)         cap 1, cost 0 if node held p else 1
      ("n", node) → sink                 cap ⌊capacity / partition_size⌋
    """
    g = Graph()
    by_zone: Dict[str, List[bytes]] = {}
    for nid, role in storage.items():
        by_zone.setdefault(role.zone, []).append(nid)
    for p in range(n_partitions):
        g.add_edge("src", ("p", p), f)
        for z, nids in by_zone.items():
            g.add_edge(("p", p), ("pz", p, z), f - zr + 1)
            for nid in nids:
                cost = 0 if nid in old_nodes_of[p] else 1
                g.add_edge(("pz", p, z), ("n", nid), 1, cost)
    for nid, role in storage.items():
        g.add_edge(("n", nid), "sink", role.capacity // partition_size)
    return g


def _feasible(
    storage: Dict[bytes, NodeRole],
    f: int,
    zr: int,
    n_partitions: int,
    partition_size: int,
) -> bool:
    if partition_size <= 0:
        return True
    g = _assignment_graph(
        storage, f, zr, n_partitions, partition_size, [set()] * n_partitions
    )
    return g.compute_maximal_flow("src", "sink") == n_partitions * f


def compute_optimal_partition_size(
    storage: Dict[bytes, NodeRole],
    f: int,
    zr: int,
    n_partitions: int = N_PARTITIONS,
) -> int:
    """Largest partition size with a feasible assignment, by dichotomy
    (ref layout.rs:783 compute_optimal_partition_size)."""
    if not _feasible(storage, f, zr, n_partitions, 1):
        raise LayoutError(
            "layout infeasible even at partition size 1: not enough "
            "capacity/zones for the requested replication"
        )
    lo, hi = 1, max(r.capacity for r in storage.values()) + 1
    while lo < hi:
        mid = (lo + hi + 1) // 2
        if _feasible(storage, f, zr, n_partitions, mid):
            lo = mid
        else:
            hi = mid - 1
        if lo == hi:
            break
    return lo
