"""Command-line interface.

Equivalent of reference src/garage/main.rs + cli/ (SURVEY.md §2.9):
`server` runs the daemon; every other command connects to a running node
over the RPC fabric with a temporary keypair + the rpc secret from the
config file (main.rs:194-263) and drives the AdminRpcHandler.

Usage:  python -m garage_tpu <command> [args]  (-c/--config garage.toml)
"""

from __future__ import annotations

import argparse
import asyncio
import json
import logging
import os
import sys
from typing import Any, Dict, Optional

from .utils.format_table import format_table


def _build_parser() -> argparse.ArgumentParser:
    from . import FEATURES, __version__

    p = argparse.ArgumentParser(prog="garage_tpu")
    p.add_argument(
        "-V", "--version", action="version",
        version=f"garage_tpu {__version__} [features: {', '.join(FEATURES)}]",
    )
    p.add_argument("-c", "--config", default=os.environ.get(
        "GARAGE_TPU_CONFIG", "./garage.toml"
    ))
    p.add_argument("--rpc-host", default=None,
                   help="node RPC address (default: rpc_bind_addr from config)")
    sub = p.add_subparsers(dest="command", required=True)

    sub.add_parser("server", help="run the storage daemon")
    sub.add_parser("node-id", help="print this node's id")

    cdb = sub.add_parser(
        "convert-db",
        help="offline copy of metadata between db engines "
             "(ref cli/convert_db.rs)",
    )
    cdb.add_argument("-i", dest="input_path", required=True)
    cdb.add_argument("-a", dest="input_engine", required=True,
                     help="sqlite | native | memory")
    cdb.add_argument("-o", dest="output_path", required=True)
    cdb.add_argument("-b", dest="output_engine", required=True)

    sub.add_parser(
        "offline-repair-counters",
        help="rebuild index counters from local table rows; daemon must be "
             "stopped (ref repair/offline.rs:11-47 + index_counter.rs:252+)",
    )
    sub.add_parser("status", help="cluster status")
    pst = sub.add_parser("stats", help="node statistics")
    pst.add_argument("-a", "--all-nodes", action="store_true",
                     help="gather statistics from all cluster nodes")

    pc = sub.add_parser("connect", help="connect to a peer (id@host:port)")
    pc.add_argument("peer")

    pcl = sub.add_parser(
        "cluster",
        help="cluster-level network observability (per-peer health)")
    cls_ = pcl.add_subparsers(dest="cluster_cmd", required=True)
    cst = cls_.add_parser(
        "stats",
        help="per-peer RTT / liveness / reconnect churn / per-priority "
             "traffic split")
    cst.add_argument("--json", action="store_true",
                     help="raw JSON instead of the table rendering")

    pl = sub.add_parser("layout", help="cluster layout operations")
    ls = pl.add_subparsers(dest="layout_cmd", required=True)
    la = ls.add_parser("assign")
    la.add_argument("node")
    la.add_argument("-z", "--zone", required=True)
    la.add_argument("-c", "--capacity", default=None,
                    help="storage capacity (e.g. 10G); omit for gateway")
    la.add_argument("-t", "--tags", default="")
    lr = ls.add_parser("remove")
    lr.add_argument("node")
    ls.add_parser("show")
    lcf = ls.add_parser("config", help="stage layout parameters")
    lcf.add_argument("-z", "--zone-redundancy", default=None,
                     help="'maximum' or an integer >= 1")
    lap = ls.add_parser("apply")
    lap.add_argument("--version", type=int, default=None)
    lrv = ls.add_parser("revert")
    lrv.add_argument("--version", type=int, default=None)

    pb = sub.add_parser("bucket", help="bucket operations")
    bs = pb.add_subparsers(dest="bucket_cmd", required=True)
    bs.add_parser("list")
    for name, extra in [
        ("info", []), ("create", []), ("delete", []),
    ]:
        x = bs.add_parser(name)
        x.add_argument("bucket")
    ba = bs.add_parser("alias")
    ba.add_argument("bucket")
    ba.add_argument("alias")
    bu = bs.add_parser("unalias")
    bu.add_argument("alias")
    for name in ("allow", "deny"):
        x = bs.add_parser(name)
        x.add_argument("bucket")
        x.add_argument("--key", required=True)
        x.add_argument("--read", action="store_true")
        x.add_argument("--write", action="store_true")
        x.add_argument("--owner", action="store_true")
    bw = bs.add_parser("website")
    bw.add_argument("bucket")
    bw.add_argument("--allow", action="store_true")
    bw.add_argument("--deny", action="store_true")
    bw.add_argument("--index-document", default="index.html")
    bw.add_argument("--error-document", default=None)
    bq = bs.add_parser("set-quotas")
    bq.add_argument("bucket")
    bq.add_argument("--max-size", default=None)
    bq.add_argument("--max-objects", type=int, default=None)
    bcu = bs.add_parser(
        "cleanup-incomplete-uploads",
        help="abort multipart uploads older than --older-than",
    )
    bcu.add_argument("buckets", nargs="+")
    bcu.add_argument("--older-than", default="1d",
                     help="e.g. 30s, 15m, 2h, 1d, 1w (default 1d)")

    pk = sub.add_parser("key", help="API key operations")
    ks = pk.add_subparsers(dest="key_cmd", required=True)
    ks.add_parser("list")
    ki = ks.add_parser("info")
    ki.add_argument("key")
    ki.add_argument("--show-secret", action="store_true")
    kc = ks.add_parser("create")
    kc.add_argument("name", nargs="?", default="unnamed")
    kd = ks.add_parser("delete")
    kd.add_argument("key")
    km = ks.add_parser("import")
    km.add_argument("id")
    km.add_argument("secret")
    km.add_argument("--name", default="imported")
    kset = ks.add_parser("set")
    kset.add_argument("key")
    kset.add_argument("--allow-create-bucket", dest="acb", action="store_true")
    kset.add_argument("--deny-create-bucket", dest="dcb", action="store_true")
    kset.add_argument("--name", default=None)

    pr = sub.add_parser("repair", help="launch repair operations")
    pr.add_argument("what", choices=[
        "tables", "blocks", "versions", "block_refs", "mpu", "rebalance",
        "scrub",
    ])
    pr.add_argument("--cmd", default="start",
                    choices=["start", "pause", "resume", "cancel"])

    pw = sub.add_parser("worker", help="background worker operations")
    ws = pw.add_subparsers(dest="worker_cmd", required=True)
    ws.add_parser("list")
    wi = ws.add_parser("info", help="single-worker drill-down")
    wi.add_argument("id", type=int)
    wg = ws.add_parser("get")
    wg.add_argument("var", nargs="?", default=None)
    wst = ws.add_parser("set")
    wst.add_argument("var")
    wst.add_argument("value")

    pb = sub.add_parser(
        "block", help="block-level operations (ref garage/admin/block.rs)")
    bls = pb.add_subparsers(dest="block_cmd", required=True)
    bls.add_parser("list-errors", help="blocks in resync error backoff")
    bi = bls.add_parser("info", help="refcount + referencing versions")
    bi.add_argument("hash")
    brt = bls.add_parser("retry-now", help="clear backoff and requeue")
    brt.add_argument("hashes", nargs="*")
    brt.add_argument("--all", action="store_true",
                     help="retry every errored block")
    bp = bls.add_parser(
        "purge",
        help="DELETE every object/version referencing these blocks "
             "(unrecoverable-block last resort)")
    bp.add_argument("hashes", nargs="+")
    bp.add_argument("--yes", action="store_true")

    pcx = sub.add_parser(
        "codec",
        help="dataplane codec observability (gate decisions, stage "
             "attribution, heal sources)")
    cxs = pcx.add_subparsers(dest="codec_cmd", required=True)
    cxs.add_parser("info", help="backend, gate state, bytes-by-side, "
                                "per-stage attribution, heal counters")
    cev = cxs.add_parser(
        "events",
        help="the gate-decision event ring: why the device side did or "
             "did not take work (probe rates, gate holds, demotions)")
    cev.add_argument("-n", "--limit", type=int, default=50)
    cpr = cxs.add_parser(
        "profile",
        help="controlled link sweep on the LIVE transport: sizes x "
             "batch shapes x kinds, each cell decomposed into "
             "stage_copy/adopt/compile/dispatch/compute/collect "
             "(exact-sum attribution; ops/link_profiler.py)")
    cpr.add_argument("--sizes-mib", type=float, nargs="+",
                     default=[1.0, 4.0, 16.0],
                     help="payload sizes per cell (MiB)")
    cpr.add_argument("--shapes", type=int, nargs="+", default=[1, 16],
                     help="blocks per batch (the batch shapes axis)")
    cpr.add_argument("--kinds", nargs="+",
                     default=["hash", "encode", "decode"],
                     choices=["hash", "encode", "decode", "scrub"])
    cpr.add_argument("--rounds", type=int, default=1)
    cpr.add_argument("--json", action="store_true",
                     help="emit the machine-readable block instead of "
                          "the table")

    pcpu = sub.add_parser(
        "cpu",
        help="continuous CPU profiler (always-on thread-stack sampling "
             "joined to roles + waterfall segments; utils/cpuprof.py)")
    cus = pcpu.add_subparsers(dest="cpu_cmd", required=True)
    cup = cus.add_parser(
        "profile",
        help="folded stacks from roughly the last --seconds, hottest "
             "first, with per-role busy ratios and the sampler's "
             "measured self-cost (<2%% budget)")
    cup.add_argument("--seconds", type=float, default=10.0,
                     help="history window to fold (served instantly "
                          "from the always-on sampler)")
    cup.add_argument("--top", type=int, default=40,
                     help="top-K stacks to return")
    cup.add_argument("--fold", action="store_true",
                     help="emit raw collapsed-stack lines "
                          "(flamegraph.pl-compatible)")
    cup.add_argument("--json", action="store_true",
                     help="emit the machine-readable profile block")

    pso = sub.add_parser(
        "slow-ops",
        help="top-N slowest operations retained by the always-on "
             "slow-op log (no trace_sink needed)")
    pso.add_argument("-n", "--limit", type=int, default=20)

    prq = sub.add_parser(
        "request",
        help="per-request critical-path attribution (waterfalls)")
    rqs = prq.add_subparsers(dest="request_cmd", required=True)
    rw = rqs.add_parser(
        "waterfall",
        help="retained slowest request span trees per endpoint; with "
             "--trace/--endpoint, one request's cross-node waterfall "
             "with its critical-path segment breakdown")
    rw.add_argument("--trace", default=None,
                    help="an x-amz-request-id (== trace id)")
    rw.add_argument("--endpoint", default=None,
                    help="e.g. PutObject: show its slowest retained "
                         "request")
    rw.add_argument("--json", action="store_true")
    rqs.add_parser(
        "exemplars",
        help="current-window histogram exemplars: the trace id behind "
             "each family's max observation")

    psl = sub.add_parser(
        "slo",
        help="per-endpoint SLO budgets & burn rates (utils/slo.py)")
    sls = psl.add_subparsers(dest="slo_cmd", required=True)
    slst = sls.add_parser(
        "status",
        help="budget table: per-endpoint availability + latency "
             "objectives, fast/slow burn rates, budget remaining")
    slst.add_argument("--json", action="store_true")

    pin = sub.add_parser(
        "incident",
        help="incident flight recorder (one-call diagnostic bundles)")
    ins = pin.add_subparsers(dest="incident_cmd", required=True)
    icap = ins.add_parser(
        "capture",
        help="write a bundle NOW (metrics, waterfalls, timeline, "
             "breaker/disk/governor/peer state, event rings)")
    icap.add_argument("--reason", default="manual")
    ins.add_parser("list", help="retained bundles on the node")

    ptl = sub.add_parser(
        "timeline",
        help="device/transport pipeline timeline as Chrome-trace JSON "
             "(load into chrome://tracing or Perfetto)")
    ptl.add_argument("-o", "--out", default=None,
                     help="write the catapult JSON here (default: "
                          "stdout)")
    ptl.add_argument("-n", "--limit", type=int, default=None,
                     help="most recent N events only")
    return p


class AdminClient:
    """CLI-side RPC client: temp keypair + rpc secret (ref main.rs:194-263)."""

    def __init__(self, config_path: str, rpc_host: Optional[str]):
        from .utils.config import read_config

        self.config = read_config(config_path)
        addr = rpc_host or self.config.rpc_bind_addr
        host, port = addr.rsplit(":", 1)
        if host in ("0.0.0.0", "::"):
            host = "127.0.0.1"
        self.addr = f"{host}:{port}"

    async def call(self, msg: Dict[str, Any]) -> Any:
        from .net import NetApp
        from .net.netapp import gen_node_key

        netapp = NetApp(gen_node_key(), self.config.rpc_secret)
        endpoint = netapp.endpoint("garage/admin")
        try:
            conn = await netapp.connect(self.addr)
            resp = await endpoint.call(conn.remote_id, msg, timeout=60.0)
            if resp.get("err"):
                print(f"error: {resp['err']}", file=sys.stderr)
                sys.exit(1)
            return resp.get("ok")
        finally:
            await netapp.shutdown()


def _perm_str(p) -> str:
    return "".join(c if f else "-" for c, f in zip("RWO", p))


def _fmt_bytes(n: float) -> str:
    for unit in ("B", "KiB", "MiB", "GiB", "TiB"):
        if n < 1024 or unit == "TiB":
            return f"{n:.0f}{unit}" if unit == "B" else f"{n:.1f}{unit}"
        n /= 1024
    return f"{n:.1f}TiB"


async def _amain(args) -> None:
    if args.command == "server":
        from .server import run_server

        await run_server(args.config)
        return

    if args.command == "convert-db":
        from .db import open_db

        src = open_db(args.input_engine, args.input_path)
        dst = open_db(args.output_engine, args.output_path)
        n_trees = n_rows = 0
        for name in src.list_trees():
            st = src.open_tree(name)
            dt = dst.open_tree(name)
            if not dt.is_empty():
                print(f"error: output tree {name!r} is not empty", file=sys.stderr)
                sys.exit(1)
            # batch rows per commit: one insert per row would pay a full
            # commit (and read-old-value) each (ref cli/convert_db.rs uses
            # the engines' bulk import)
            batch = []
            for kv in st.items():
                batch.append(kv)
                if len(batch) >= 2000:
                    rows = batch
                    dst.transaction(lambda tx, rows=rows: [
                        tx.insert(dt, k, v) for k, v in rows
                    ])
                    n_rows += len(batch)
                    batch = []
            if batch:
                rows = batch
                dst.transaction(lambda tx, rows=rows: [
                    tx.insert(dt, k, v) for k, v in rows
                ])
                n_rows += len(batch)
            n_trees += 1
        src.close()
        dst.close()
        print(f"converted {n_trees} trees / {n_rows} rows "
              f"({args.input_engine} -> {args.output_engine})")
        return

    if args.command == "offline-repair-counters":
        from .model import Garage
        from .utils.config import read_config

        g = Garage(read_config(args.config))  # no listen: offline
        jobs = [
            (g.object_counter, g.object_table,
             lambda e: (bytes(e.bucket_id), "")),
            (g.mpu_counter, g.mpu_table,
             lambda e: (bytes(e.bucket_id), "")),
            (g.k2v_counter, g.k2v_item_table,
             lambda e: (bytes(e.bucket_id), e.partition_key_str)),
        ]
        for counter, table, key_fn in jobs:
            z, n = counter.offline_recount_all(table, key_fn)
            print(f"{counter.table.schema.TABLE_NAME}: zeroed {z}, "
                  f"recounted {n} entries")
        await g.shutdown()
        return

    if args.command == "node-id":
        from .utils.config import read_config
        from .net.netapp import load_or_gen_node_key, node_id_of

        cfg = read_config(args.config)
        key = load_or_gen_node_key(os.path.join(cfg.metadata_dir, "node_key"))
        nid = node_id_of(key).hex()
        addr = cfg.rpc_public_addr or cfg.rpc_bind_addr
        print(f"{nid}@{addr}")
        return

    client = AdminClient(args.config, args.rpc_host)

    if args.command == "status":
        st = await client.call({"cmd": "status"})
        h = st["health"]
        print(f"==== Node: {st['node_id'][:16]}… ({st['hostname']}) ====")
        print(f"Cluster health: {h['status']}  "
              f"(nodes {h['connected_nodes']}/{h['known_nodes']} connected, "
              f"partitions {h['partitions_all_ok']}/{h['partitions']} all-ok, "
              f"{h['partitions_quorum']}/{h['partitions']} quorum)")
        print(f"Layout version: {st['layout_version']}")
        rows = ["ID\tZONE\tCAPACITY\tTAGS\tSTATUS"]
        known = {n["id"]: n for n in st["known_nodes"]}
        for nid, (zone, cap, tags) in sorted(st["roles"].items()):
            k = known.get(nid, {})
            up = "up" if k.get("is_up") else "down"
            cap_s = str(cap) if cap is not None else "gateway"
            rows.append(f"{nid[:16]}…\t{zone}\t{cap_s}\t{','.join(tags)}\t{up}")
        print(format_table(rows))
        if any(v is not None for v in st["staged"].values()) or st["staged"]:
            staged = {k: v for k, v in st["staged"].items()}
            if staged:
                print("\n==== Staged changes ====")
                for nid, r in staged.items():
                    print(f"  {nid[:16]}… → {r}")
                print("Use `layout apply` to activate.")
        return

    if args.command == "stats":
        msg = {"cmd": "stats"}
        if getattr(args, "all_nodes", False):
            msg["all"] = True
        print(json.dumps(await client.call(msg), indent=2))
        return

    if args.command == "connect":
        if "@" in args.peer:
            nid, addr = args.peer.split("@", 1)
        else:
            nid, addr = None, args.peer
        print(await client.call({"cmd": "connect", "addr": addr, "node_id": nid}))
        return

    if args.command == "cluster":
        if args.cluster_cmd == "stats":
            st = await client.call({"cmd": "cluster_stats"})
            if args.json:
                print(json.dumps(st, indent=2))
                return
            me = (f" zone={st['zone']}" if st.get("zone") else "") + (
                f" v{st['version']}" if st.get("version") else "")
            print(f"==== Node: {st['node_id'][:16]}…{me} — peer health "
                  f"(grouped by zone) ====")
            rows = ["ZONE\tPEER\tADDR\tUP\tBRK\tHEALTH\tDISK\tVER\tRTT"
                    "\tFAILS\tRECONN\tTX\tRX\tBG TX%"]
            for p in st["peers"]:
                tr = p.get("traffic") or {}
                tx = sum(v["tx_bytes"] for v in tr.values())
                rx = sum(v["rx_bytes"] for v in tr.values())
                bg = tr.get("background", {}).get("tx_bytes", 0)
                rtt = p["rtt_ewma_ms"]
                brk = p.get("breaker")
                hs = p.get("health_score")
                # the fail-slow column: a peer can be up with its
                # breaker closed and still be SLOW! — the gray-failure
                # case the comparative scorer exists for
                health = ("SLOW!" if p.get("fail_slow")
                          else f"{hs:.1f}x" if hs is not None else "-")
                rows.append("\t".join([
                    p.get("zone") or "-",
                    f"{p['id'][:16]}…",
                    p["addr"] or "-",
                    "up" if p["up"] else "DOWN",
                    {"closed": "-", "half_open": "half",
                     "open": "OPEN"}.get(brk, brk or "-"),
                    health,
                    p.get("disk_state") or "-",
                    p.get("version") or "-",
                    f"{rtt}ms" if rtt is not None else "-",
                    str(p["consecutive_failures"]),
                    str(p["reconnects"]),
                    _fmt_bytes(tx) if tr else "-",
                    _fmt_bytes(rx) if tr else "-",
                    f"{100.0 * bg / tx:.0f}%" if tx else "-",
                ]))
            print(format_table(rows))
            zones = st.get("zones") or {}
            if zones:
                # one line per failure domain: a zone outage is legible
                # at a glance (nodes down + breakers open + sick disks
                # all concentrate on one row)
                print("\n==== Zones ====")
                for zname in sorted(zones):
                    z = zones[zname]
                    print(f"  {zname}: {z['up']}/{z['nodes']} up, "
                          f"worst disk {z['worst_disk']}, "
                          f"{z['breaker_open']} breaker(s) open")
            disk = st.get("disk")
            if disk:
                print(f"\n==== Local disk health: {disk['state']} "
                      f"(quarantined {disk['quarantined']}, "
                      f"quarantine errors {disk['quarantine_errors']}) ====")
                drows = ["ROOT\tSTATE\tFREE"]
                for r in disk["roots"]:
                    free = r["free_bytes"]
                    drows.append("\t".join([
                        r["path"], r["state"],
                        _fmt_bytes(free) if free is not None else "?",
                    ]))
                print(format_table(drows))
                if disk["error_counts"]:
                    print("disk errors: " + ", ".join(
                        f"{k}={v}" for k, v in
                        sorted(disk["error_counts"].items())))
        return

    if args.command == "layout":
        lc = args.layout_cmd
        if lc == "assign":
            from .utils.config import parse_capacity

            cap = parse_capacity(args.capacity) if args.capacity else None
            tags = [t for t in args.tags.split(",") if t]
            print(await client.call({
                "cmd": "layout_assign", "node": args.node,
                "zone": args.zone, "capacity": cap, "tags": tags,
            }))
        elif lc == "remove":
            print(await client.call({
                "cmd": "layout_assign", "node": args.node, "remove": True,
            }))
        elif lc == "config":
            print(await client.call({
                "cmd": "layout_config",
                "zone_redundancy": args.zone_redundancy,
            }))
        elif lc == "show":
            st = await client.call({"cmd": "status"})
            print(json.dumps({
                "roles": st["roles"], "staged": st["staged"],
                "version": st["layout_version"],
                "parameters": st.get("parameters"),
                "staged_parameters": st.get("staged_parameters"),
            }, indent=2))
        elif lc == "apply":
            for m in await client.call({"cmd": "layout_apply", "version": args.version}):
                print(m)
        elif lc == "revert":
            print(await client.call({"cmd": "layout_revert", "version": args.version}))
        return

    if args.command == "bucket":
        bc = args.bucket_cmd
        if bc == "list":
            rows = ["ID\tALIASES\tKEYS"]
            for b in await client.call({"cmd": "bucket_list"}):
                rows.append(f"{b['id'][:16]}…\t{','.join(b['aliases'])}\t{b['keys']}")
            print(format_table(rows))
        elif bc == "info":
            print(json.dumps(await client.call(
                {"cmd": "bucket_info", "bucket": args.bucket}), indent=2))
        elif bc == "create":
            print(await client.call({"cmd": "bucket_create", "name": args.bucket}))
        elif bc == "delete":
            print(await client.call({"cmd": "bucket_delete", "bucket": args.bucket}))
        elif bc == "alias":
            print(await client.call({
                "cmd": "bucket_alias", "bucket": args.bucket, "alias": args.alias,
            }))
        elif bc == "unalias":
            print(await client.call({"cmd": "bucket_unalias", "alias": args.alias}))
        elif bc in ("allow", "deny"):
            print(await client.call({
                "cmd": f"bucket_{bc}", "bucket": args.bucket, "key": args.key,
                "read": args.read, "write": args.write, "owner": args.owner,
            }))
        elif bc == "website":
            print(await client.call({
                "cmd": "bucket_website", "bucket": args.bucket,
                "allow": args.allow and not args.deny,
                "index_document": args.index_document,
                "error_document": args.error_document,
            }))
        elif bc == "set-quotas":
            from .utils.config import parse_capacity

            print(await client.call({
                "cmd": "bucket_set_quotas", "bucket": args.bucket,
                "max_size": parse_capacity(args.max_size) if args.max_size else None,
                "max_objects": args.max_objects,
            }))
        elif bc == "cleanup-incomplete-uploads":
            print(await client.call({
                "cmd": "bucket_cleanup_uploads", "buckets": args.buckets,
                "older_than": args.older_than,
            }))
        return

    if args.command == "key":
        kc = args.key_cmd
        if kc == "list":
            rows = ["ID\tNAME"]
            for k in await client.call({"cmd": "key_list"}):
                rows.append(f"{k['id']}\t{k['name']}")
            print(format_table(rows))
        elif kc == "info":
            print(json.dumps(await client.call({
                "cmd": "key_info", "key": args.key,
                "show_secret": args.show_secret,
            }), indent=2))
        elif kc == "create":
            k = await client.call({"cmd": "key_create", "name": args.name})
            print(f"Key ID:     {k['id']}\nSecret key: {k['secret']}")
        elif kc == "delete":
            print(await client.call({"cmd": "key_delete", "key": args.key}))
        elif kc == "import":
            print(await client.call({
                "cmd": "key_import", "id": args.id, "secret": args.secret,
                "name": args.name,
            }))
        elif kc == "set":
            msg = {"cmd": "key_set", "key": args.key}
            if args.acb:
                msg["allow_create_bucket"] = True
            if args.dcb:
                msg["allow_create_bucket"] = False
            if args.name:
                msg["name"] = args.name
            print(await client.call(msg))
        return

    if args.command == "repair":
        print(await client.call({
            "cmd": "launch_repair", "what": args.what, "scrub_cmd": args.cmd,
        }))
        return

    if args.command == "worker":
        wc = args.worker_cmd
        if wc == "list":
            rows = ["ID\tNAME\tSTATE\tERRORS\tQUEUE\tPROGRESS"]
            for w in await client.call({"cmd": "worker_list"}):
                rows.append(
                    f"{w['id']}\t{w['name']}\t{w['state']}\t{w['errors']}"
                    f"\t{w['queue_length'] if w['queue_length'] is not None else '-'}"
                    f"\t{w['progress'] or '-'}"
                )
            print(format_table(rows))
        elif wc == "info":
            w = await client.call({"cmd": "worker_info", "id": args.id})
            rows = ["FIELD\tVALUE"]
            order = ["id", "name", "alive", "state", "errors",
                     "consecutive_errors", "last_error",
                     "last_error_ago_s", "tranquility", "progress",
                     "queue_length", "persistent_errors"]
            for k in order:
                v = w.get(k)
                rows.append(f"{k}\t{v if v is not None else '-'}")
            for line in w.get("freeform") or []:
                rows.append(f"note\t{line}")
            for k, v in (w.get("tunables") or {}).items():
                rows.append(f"tunable:{k}\t{v}")
            print(format_table(rows))
        elif wc == "get":
            print(json.dumps(await client.call(
                {"cmd": "worker_get_var", "var": args.var}), indent=2))
        elif wc == "set":
            v: Any = args.value
            try:
                v = int(args.value)
            except ValueError:
                pass
            print(await client.call({
                "cmd": "worker_set_var", "var": args.var, "value": v,
            }))
        return

    if args.command == "block":
        bc = args.block_cmd
        if bc == "list-errors":
            rows = ["HASH\tERRORS\tLAST TRY\tNEXT TRY"]
            for e in await client.call({"cmd": "block_list_errors"}):
                # full hash: this listing feeds retry-now/purge arguments
                rows.append(
                    f"{e['hash']}\t{e['errors']}"
                    f"\t{e['last_try_secs_ago']}s ago"
                    f"\tin {e['next_try_in_secs']}s"
                )
            print(format_table(rows))
        elif bc == "info":
            print(json.dumps(await client.call(
                {"cmd": "block_info", "hash": args.hash}), indent=2))
        elif bc == "retry-now":
            print(await client.call({
                "cmd": "block_retry_now", "all": args.all,
                "blocks": args.hashes,
            }))
        elif bc == "purge":
            print(await client.call({
                "cmd": "block_purge", "yes": args.yes,
                "blocks": args.hashes,
            }))
        return

    if args.command == "codec":
        if args.codec_cmd == "info":
            print(json.dumps(await client.call({"cmd": "codec_info"}),
                             indent=2))
        elif args.codec_cmd == "events":
            rows = ["SEQ\tKIND\tREASON\tDETAIL"]
            for e in await client.call(
                {"cmd": "codec_events", "limit": args.limit}
            ):
                detail = ", ".join(
                    f"{k}={v}" for k, v in e.items()
                    if k not in ("seq", "ts", "kind", "reason")
                )
                rows.append(f"{e['seq']}\t{e['kind']}\t"
                            f"{e.get('reason') or '-'}\t{detail or '-'}")
            print(format_table(rows))
        elif args.codec_cmd == "profile":
            block = await client.call({
                "cmd": "codec_profile",
                "sizes_mib": args.sizes_mib,
                "shapes": args.shapes,
                "kinds": args.kinds,
                "rounds": args.rounds,
            })
            if args.json:
                print(json.dumps(block, indent=2))
            else:
                from garage_tpu.ops.link_profiler import format_sweep
                print(format_sweep(block))
        return

    if args.command == "cpu":
        prof = await client.call({
            "cmd": "cpu_profile",
            "seconds": args.seconds,
            "top": args.top,
        })
        if args.json:
            print(json.dumps(prof, indent=2))
        elif args.fold:
            # raw collapsed-stack lines — pipe straight into
            # flamegraph.pl / speedscope
            for rec in prof["top"]:
                print(f"{rec['stack']} {rec['count']}")
        else:
            busy = " ".join(f"{r}={v:.0%}" for r, v in
                            prof.get("busy_ratio", {}).items())
            print(f"==== CPU profile (last ~{prof['seconds']}s, "
                  f"{prof['samples']} samples @ {prof['hz']}Hz, "
                  f"sampler overhead "
                  f"{prof['overhead_ratio'] * 100:.2f}%) ====")
            if busy:
                print(f"busy: {busy}")
            rows = ["SHARE\tROLE\tSEGMENT\tHOTTEST FRAME\tSTACK"]
            for rec in prof["top"]:
                stack = rec["stack"].split(";", 2)[-1]
                if len(stack) > 72:
                    stack = "…" + stack[-71:]
                rows.append(f"{rec['share'] * 100:5.1f}%\t{rec['role']}"
                            f"\t{rec['segment']}\t{rec['leaf']}\t{stack}")
            print(format_table(rows))
        return

    if args.command == "slow-ops":
        rows = ["SECONDS\tOP\tTRACE\tATTRS"]
        for o in await client.call({"cmd": "slow_ops",
                                    "limit": args.limit}):
            attrs = ", ".join(f"{k}={v}" for k, v in
                              (o.get("attrs") or {}).items())
            # the trace id keys straight into `request waterfall --trace`
            trace = o.get("trace")
            rows.append(f"{o['seconds']:.3f}\t{o['name']}"
                        f"\t{trace[:16] + '…' if trace else '-'}"
                        f"\t{attrs or '-'}")
        print(format_table(rows))
        return

    if args.command == "request":
        if args.request_cmd == "exemplars":
            rows = ["FAMILY\tLABELS\tSECONDS\tTRACE"]
            for e in await client.call({"cmd": "exemplars"}):
                labels = ",".join(f"{k}={v}" for k, v in
                                  (e.get("labels") or {}).items())
                rows.append(f"{e['family']}\t{labels or '-'}"
                            f"\t{e['value']:.4f}\t{e['trace_id']}")
            print(format_table(rows))
            return
        msg = {"cmd": "request_waterfall"}
        if args.trace:
            msg["trace"] = args.trace
        if args.endpoint:
            msg["endpoint"] = args.endpoint
        wf = await client.call(msg)
        if args.json:
            print(json.dumps(wf, indent=2))
            return
        if "tree" not in wf:
            print("==== Sampled endpoints ====")
            rows = ["ENDPOINT\tSAMPLED\tMEAN\tDOMINANT\tRETAINED"]
            for e in wf["endpoints"]:
                rows.append(f"{e['endpoint']}\t{e['sampled']}"
                            f"\t{e['mean_ms']:.1f}ms\t{e['dominant']}"
                            f"\t{e['retained']}")
            print(format_table(rows))
            print("\n==== Retained waterfalls (slowest first; "
                  "`request waterfall --trace <id>` for the tree) ====")
            rows = ["ENDPOINT\tSECONDS\tDOMINANT\tTRACE\tSEGMENTS"]
            for e in wf["retained"]:
                segs = " ".join(
                    f"{k}={v * 1000:.1f}ms"
                    for k, v in list(e["segments"].items())[:4])
                rows.append(f"{e['endpoint']}\t{e['seconds']:.4f}"
                            f"\t{e['dominant']}\t{e['trace_id']}\t{segs}")
            print(format_table(rows))
            return
        total_ms = wf["seconds"] * 1000.0
        print(f"==== {wf['endpoint']} — trace {wf['trace_id']} — "
              f"{total_ms:.1f} ms across {wf['nodes_contributing']} "
              f"node(s), {wf['span_count']} spans ====")
        print("critical path: " + ", ".join(
            f"{k} {v * 1000:.1f}ms ({v / wf['seconds'] * 100:.0f}%)"
            for k, v in wf["segments"].items())
            + f"  → dominant: {wf['dominant']}")
        t0 = wf["tree"]["start_ns"]

        def render(node, depth):
            off = (node["start_ns"] - t0) / 1e6
            dur = node["seconds"] * 1000.0
            bar_w = 32
            lo = (0 if total_ms <= 0
                  else int(bar_w * off / total_ms))
            hi = (bar_w if total_ms <= 0 else
                  max(lo + 1, int(bar_w * (off + dur) / total_ms)))
            bar = " " * lo + "█" * min(bar_w - lo, hi - lo)
            print(f"  {off:8.1f}ms {dur:8.1f}ms |{bar:<{bar_w}}| "
                  f"{'  ' * depth}{node['name']} [{node['segment']}]")
            for c in node["children"]:
                render(c, depth + 1)

        render(wf["tree"], 0)
        return

    if args.command == "slo":
        st = await client.call({"cmd": "slo_status"})
        if args.json:
            print(json.dumps(st, indent=2))
            return
        w = st["windows"]
        print(f"==== SLO budgets — node {st['node_id'][:16]}… "
              f"(fast {w['fast_s']:.0f}s / slow {w['slow_s']:.0f}s; "
              f"fast-burn threshold {st['fast_burn_threshold']}x, "
              f"{st['fast_burn_breaches']} breach(es)) ====")
        rows = ["ENDPOINT\tSLO\tTARGET\tEVENTS\tBAD\tBURN fast\t"
                "BURN slow\tBUDGET LEFT"]
        for r in st["rows"]:
            rows.append("\t".join([
                r["endpoint"], r["slo"], r["target"],
                str(r["events"]), str(r["bad"]),
                f"{r['burn_fast']:.2f}x", f"{r['burn_slow']:.2f}x",
                f"{r['budget_remaining'] * 100:.1f}%",
            ]))
        print(format_table(rows))
        return

    if args.command == "incident":
        if args.incident_cmd == "capture":
            out = await client.call({"cmd": "incident_capture",
                                     "reason": args.reason})
            print(f"bundle written: {out['path']}")
        else:
            rows = ["CAPTURED\tTRIGGER\tREASON\tSECTIONS\tPATH"]
            for b in await client.call({"cmd": "incident_list"}):
                ts = b.get("captured_at")
                rows.append("\t".join([
                    f"{ts:.0f}" if ts else "-",
                    b.get("trigger") or "-",
                    b.get("reason") or "-",
                    str(len(b.get("sections") or [])),
                    b["path"],
                ]))
            print(format_table(rows))
        return

    if args.command == "timeline":
        msg = {"cmd": "device_timeline"}
        if args.limit:
            msg["limit"] = args.limit
        chrome = await client.call(msg)
        body = json.dumps(chrome)
        if args.out:
            with open(args.out, "w") as f:
                f.write(body)
            n = sum(1 for e in chrome["traceEvents"]
                    if e.get("ph") != "M")
            print(f"wrote {n} events to {args.out} "
                  f"(open in chrome://tracing or https://ui.perfetto.dev)")
        else:
            print(body)
        return


def main() -> None:
    # log↔trace correlation: every record emitted inside a request
    # scope carries the request's trace id (== x-amz-request-id), so
    # flight-recorder bundles and `request waterfall --trace` key
    # straight into the log stream; "-" outside any request
    from .utils.tracing import install_log_trace_ids

    install_log_trace_ids()
    logging.basicConfig(
        level=os.environ.get("GARAGE_TPU_LOG", "INFO"),
        format="%(asctime)s %(levelname).1s %(name)s [%(trace_id)s]: "
               "%(message)s",
    )
    args = _build_parser().parse_args()
    try:
        asyncio.run(_amain(args))
    except KeyboardInterrupt:
        pass


if __name__ == "__main__":
    main()
