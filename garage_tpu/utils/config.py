"""Daemon configuration.

Equivalent of reference src/util/config.rs:14-298: a single TOML file with
secrets loadable from separate files (refusing world-readable secret files,
config.rs:280-287), human-friendly capacities ("10G", config.rs:300-340) and
compression levels, plus env-var secret overrides (ref garage/main.rs:69-85:
GARAGE_RPC_SECRET etc. → here GARAGE_TPU_RPC_SECRET).

TPU-first additions under ``[codec]``: backend selection (cpu|tpu), block
hash algorithm (blake2s is the device-friendly default), Reed-Solomon (k, m)
data/parity split, and device batch sizing.
"""

from __future__ import annotations

import dataclasses
import os
import re
import stat
try:
    import tomllib
except ImportError:  # Python < 3.11: the API-identical backport
    import tomli as tomllib
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from ..net.resilience import ResilienceTunables
from ..ops.codec import CodecParams as _CodecParams
from .health_score import HealthTunables
from .overload import OverloadTunables
from .slo import SloTunables

_CODEC_DEFAULTS = _CodecParams()


class ConfigError(Exception):
    pass


_CAPACITY_RE = re.compile(r"^\s*([0-9]+(?:\.[0-9]+)?)\s*([kKmMgGtT]?)(i?)[bB]?\s*$")
_CAPACITY_MULT = {"": 1, "k": 10**3, "m": 10**6, "g": 10**9, "t": 10**12}
_CAPACITY_MULT_IEC = {"k": 2**10, "m": 2**20, "g": 2**30, "t": 2**40}


def parse_capacity(v: Any) -> int:
    """'10G' / '100M' / int → bytes (ref util/config.rs:300-340, SI units);
    IEC suffixes ('1GiB') are honored as binary multiples."""
    if isinstance(v, int):
        return v
    m = _CAPACITY_RE.match(str(v))
    if not m:
        raise ConfigError(f"invalid capacity: {v!r}")
    unit = m.group(2).lower()
    if m.group(3):
        if not unit:
            raise ConfigError(f"invalid capacity: {v!r}")
        return int(float(m.group(1)) * _CAPACITY_MULT_IEC[unit])
    return int(float(m.group(1)) * _CAPACITY_MULT[unit])


def secret_from_file(path: str) -> str:
    """Read a secret file, refusing world-readable perms
    (ref util/config.rs:268-298)."""
    st = os.stat(path)
    if st.st_mode & (stat.S_IRWXG | stat.S_IRWXO) and not os.environ.get(
        "GARAGE_TPU_ALLOW_WORLD_READABLE_SECRETS"
    ):
        raise ConfigError(
            f"secret file {path} is group/world-accessible "
            f"(mode {oct(st.st_mode & 0o777)}); chmod 0600 it"
        )
    with open(path, "r") as f:
        return f.read().strip()


@dataclass
class CodecConfig:
    """TPU block-codec settings (new vs reference — the BlockCodec seam)."""
    # Default is the production path: CPU floor + opportunistic device
    # stealing.  The device codec builds on a BACKGROUND thread
    # (make_codec passes build_device="async"), so a daemon on a host
    # with no/dead accelerator boots instantly on the CPU floor and the
    # TPU joins in if/when its backend initializes — a tpu-native
    # framework whose stock config never touched the TPU would undercut
    # its own thesis.  Set "cpu" to pin the floor, "tpu" to require the
    # device.
    backend: str = "hybrid"         # hybrid (cpu + device stealing) | cpu | tpu
    hash_algo: str = "blake2s"      # blake2s (TPU-offloadable) | blake2b | sha256
    rs_data: int = 8                # Reed-Solomon k (0 = replication only, no RS)
    rs_parity: int = 4              # Reed-Solomon m
    batch_blocks: int = 256         # blocks per device batch (scrub/resync producers)
    shard_mesh: int = 1             # devices to shard codec batches over
    # hybrid backend work-stealing quantum; single source of truth is the
    # CodecParams default (codec.py: cache-resident CPU-side groups)
    hybrid_group_blocks: int = _CODEC_DEFAULTS.hybrid_group_blocks
    # persist scrub-time RS parity sidecars enabling zero-network local
    # reconstruction of corrupted/lost blocks (the decode-repair half of
    # the BlockCodec north star).  Opt-in: costs ~m/k extra disk (+50%
    # at the default 8/4), refreshed and garbage-collected per scrub pass
    store_parity: bool = False
    # RS-encode on the PutObject path (BASELINE config #3): freshly
    # written blocks join write-time codewords whose parity is encoded
    # off the write path and persisted immediately — no unprotected
    # window until the first scrub pass.  Effective only with
    # store_parity; partial codewords (small objects, flush timeouts)
    # encode against implicit zero shards.
    parity_on_write: bool = True
    # Cross-node parity (requires store_parity + parity_on_write): each
    # codeword's parity shards are stored as ordinary ring-placed blocks
    # on OTHER nodes and indexed in a replicated table, so RS survives
    # whole-NODE loss, not just local corruption.  Pair with
    # data_replication_mode = "none" for the erasure-coded storage class
    # (1 + m/k × storage tolerating m codeword-node losses).
    parity_distribute: bool = False
    hybrid_window: int = 1          # hybrid backend: device in-flight groups
    # Device submission width (blocks) for the hybrid feeder.  MEMORY
    # IMPLICATION (round-5 ADVICE #4): host staging + device HBM hold up
    # to (hybrid_window + 1) × device_batch_blocks × block_size at once
    # — 2 GiB at the defaults (window 1 × 1024 blocks × 1 MiB).  The
    # codec clamps this width at construction so the bound never
    # exceeds max_device_staging_mib; see ops/codec.py CodecParams for
    # the full derivation.
    device_batch_blocks: int = _CODEC_DEFAULTS.device_batch_blocks
    # Cap (MiB) on the in-flight staging claim above.  Raise it only on
    # hosts with the RAM/HBM headroom for wider windows.
    max_device_staging_mib: int = _CODEC_DEFAULTS.max_device_staging_mib
    # --- continuous-batching feeder for the FOREGROUND data path
    # (ops/feeder.py): in-flight PUT block-id hashing, write-time RS
    # encodes and degraded-read decodes submit individually and are
    # coalesced into ragged codec batches, dispatched when the batch
    # fills or the SLO deadline expires.  K concurrent puts then pay
    # ~one batched codec pass instead of K serial ones; a lone put
    # waits at most feeder_slo_ms (the solo-latency regression bound).
    feeder: bool = True
    feeder_slo_ms: float = 2.0
    feeder_max_batch_blocks: int = 256
    # --- zero-copy device transport (ops/transport.py): one
    # deadline-aware submission queue from the feeder to the device —
    # staged once into reusable buffers (≤1 host copy per block),
    # double-buffered within max_device_staging_mib, foreground ahead
    # of governor-demoted background.  transport=false restores the
    # legacy per-call serialize+copy routing.
    transport: bool = _CODEC_DEFAULTS.transport
    transport_staging_slots: int = _CODEC_DEFAULTS.transport_staging_slots
    transport_bg_slack_ms: float = _CODEC_DEFAULTS.transport_bg_slack_ms
    # --- device-resident block pool (ops/device_pool.py): bounded
    # fixed-size device pages keyed by block hash, consulted by the
    # transport before staging — a warm re-scrub of a resident working
    # set moves zero link bytes.  Budgeted SEPARATELY from
    # max_device_staging_mib (staging bounds bytes in flight; the pool
    # bounds bytes at rest).  pool_mib=0 disables (staging then
    # behaves byte-identically to the pre-pool transport);
    # pool_prefetch gates the scrub worker's next-range hint.
    pool_mib: int = _CODEC_DEFAULTS.pool_mib
    pool_page_kib: int = _CODEC_DEFAULTS.pool_page_kib
    pool_prefetch: bool = _CODEC_DEFAULTS.pool_prefetch
    # --- repair-bandwidth-optimal degraded reads (block/repair_plan.py):
    # exact-k survivor selection ranked by RTT EWMA / breaker state /
    # zone locality, hedged ranked replacements, and partial-parallel
    # repair (survivors ship GF-scaled partial sums via the `ppr` block
    # RPC instead of whole shards).  repair_planner=False restores the
    # legacy sweep-everything gather; repair_ppr=False keeps exact-k
    # planning but fetches whole shards.  repair_hedge_ms > 0 pins the
    # stalled-fetch hedge delay; 0 derives it from the block endpoint's
    # observed latency quantile.
    repair_planner: bool = True
    repair_ppr: bool = True
    repair_hedge_ms: float = 0.0
    # tree-aggregated PPR (`ppr_tree` block RPC): survivors forward
    # GF-scaled partials along a fanout-shaped aggregation tree so the
    # coordinator ingests ONE stream regardless of k.  repair_tree=False
    # keeps flat PPR; repair_tree_fanout bounds each interior node's
    # child count.
    repair_tree: bool = True
    repair_tree_fanout: int = 4

    def make(self, compression_level: Optional[int] = 1,
             metrics=None, tracer=None, block_size: Optional[int] = None):
        """Build the configured BlockCodec (`backend` selects the impl).

        metrics/tracer plumb the System's MetricsRegistry/Tracer into
        the codec: per-stage histograms, bytes-by-side counters, and the
        gate-decision event ring (admin `codec info` / `codec events`).
        block_size feeds the staging clamp so the memory bound holds at
        the daemon's configured block size, not the 1 MiB default."""
        from ..ops import make_codec
        from ..ops.codec import CodecParams as _CP

        return make_codec(
            self.backend,
            metrics=metrics,
            tracer=tracer,
            block_size=block_size or _CP.block_size,
            hash_algo=self.hash_algo,
            rs_data=self.rs_data,
            rs_parity=self.rs_parity,
            batch_blocks=self.batch_blocks,
            compression_level=compression_level,
            shard_mesh=self.shard_mesh,
            hybrid_group_blocks=self.hybrid_group_blocks,
            hybrid_window=self.hybrid_window,
            device_batch_blocks=self.device_batch_blocks,
            max_device_staging_mib=self.max_device_staging_mib,
            transport=self.transport,
            transport_staging_slots=self.transport_staging_slots,
            transport_bg_slack_ms=self.transport_bg_slack_ms,
            pool_mib=self.pool_mib,
            pool_page_kib=self.pool_page_kib,
            pool_prefetch=self.pool_prefetch,
        )


@dataclass
class TableTunables:
    """[table] — metadata-plane scaling knobs (docs/OBSERVABILITY.md
    "Metadata plane"): batched Merkle digestion, batched anti-entropy
    descent and bucket-sharded listing fan-out.  Every knob has a
    `<= 1` escape hatch that restores the serial/per-node behavior
    (the bench's paired A/B baseline)."""

    # todo items drained per batched Merkle pass (table/merkle.py):
    # shared trie path nodes are rewritten and re-hashed ONCE per batch
    # instead of once per item, and node hashes ride the codec feeder as
    # one ragged batch.  <= 1 = legacy one-transaction-per-item updates.
    merkle_batch: int = 256
    # Merkle nodes fetched per anti-entropy RPC round (table/sync.py):
    # the syncer ships whole subtree frontiers breadth-wise, collapsing
    # cold-node convergence from O(nodes) round-trips to O(depth).
    # <= 1 = legacy one-node-per-round descent.
    sync_batch_nodes: int = 512
    # concurrent sub-range scans a large ListObjects enumeration fans
    # out across once its first page comes back full (api/s3/list.py):
    # disjoint key sub-ranges prefetch in parallel and are consumed in
    # order, so deep listings stop paying one quorum round-trip per
    # serial page.  <= 1 = serial single-cursor walk.
    list_shards: int = 4
    # rows per server-side range_scan page when a filtered read_range
    # has to keep scanning past rejected rows
    scan_page: int = 1024


@dataclass
class ConsulDiscoveryConfig:
    """[consul_discovery] (ref util/config.rs:185-210, rpc/consul.rs)."""
    consul_http_addr: str = ""
    service_name: str = ""
    api: str = "catalog"            # catalog | agent
    token: Optional[str] = None
    tags: List[str] = field(default_factory=list)
    meta: Dict[str, str] = field(default_factory=dict)
    ca_cert: Optional[str] = None
    client_cert: Optional[str] = None
    client_key: Optional[str] = None
    tls_skip_verify: bool = False


@dataclass
class KubernetesDiscoveryConfig:
    """[kubernetes_discovery] (ref util/config.rs, rpc/kubernetes.rs)."""
    namespace: str = ""
    service_name: str = ""
    skip_crd: bool = False


@dataclass
class Config:
    """Top-level config (ref util/config.rs:14-107)."""
    metadata_dir: str = "./meta"
    data_dir: List[Dict[str, Any]] = field(default_factory=list)  # [{path, capacity?, read_only?}]
    block_size: int = 1024 * 1024       # ref config.rs:234-236 default 1 MiB
    replication_mode: str = "3"         # ref rpc/replication_mode.rs
    # Block placement may use a DIFFERENT mode than the metadata tables
    # (None = same): meta "3" + data "none" + codec.parity_distribute is
    # the erasure-coded storage class — see CodecConfig.parity_distribute.
    data_replication_mode: Optional[str] = None
    compression_level: Optional[int] = 1  # zstd level; None = off (ref config.rs:342-394)
    rpc_bind_addr: str = "0.0.0.0:3901"
    rpc_public_addr: Optional[str] = None
    rpc_secret: Optional[str] = None
    bootstrap_peers: List[str] = field(default_factory=list)
    db_engine: str = "sqlite"           # sqlite | native | memory (ref model/garage.rs:114-213)
    # disabled by default, matching the reference (ref util/config.rs:
    # 20-25 "disabled by default" for both): commits reach the OS on
    # ack (kill -9-safe, tests/test_db_torture.py); fsync=true narrows
    # the power-loss window at ~0.6 ms per metadata commit (measured,
    # docs/DATAPLANE_PROFILE.md — it was 22% of data-plane CPU)
    metadata_fsync: bool = False
    data_fsync: bool = False
    # --- disk-fault robustness (docs/ROBUSTNESS.md "Disk faults &
    # degraded mode"): per-data-root ok → degraded(read-only) → failed
    # state machine in block/health.py ---
    # free-bytes watermark: a root with less free space preflights every
    # block write into a typed StorageFull rejection (write quorums
    # route around the node; reads keep flowing)
    data_free_space_watermark: int = 128 * 1024 * 1024
    # consecutive read/write disk errors on one root that flip it
    # read-only (degraded); 4× this latches "failed"
    disk_error_threshold: int = 8
    # cooldown before a degraded root admits one half-open probe write
    disk_error_cooldown: float = 30.0
    # startup-janitor quarantine bound: .corrupted files beyond either
    # budget are purged oldest-first at boot
    quarantine_max_files: int = 128
    quarantine_max_bytes: int = 256 * 1024 * 1024
    # version tag advertised in the handshake + status gossip (None =
    # the package version); overridable for mixed-version drills
    node_version: Optional[str] = None
    # layout-change rebalance mover: data streamed per second ceiling
    # (MiB/s) so a zone drain cannot starve foreground traffic
    rebalance_rate_mib: float = 64.0
    # fleet rebuild scheduler (block/rebuild.py): repaired bytes per
    # second ceiling for a full-node-loss storm, further scaled by the
    # LoadGovernor throttle ratio
    rebuild_rate_mib: float = 256.0
    s3_api_bind_addr: Optional[str] = "0.0.0.0:3900"
    s3_region: str = "garage"
    root_domain: Optional[str] = None
    web_bind_addr: Optional[str] = None
    web_root_domain: Optional[str] = None
    admin_api_bind_addr: Optional[str] = None
    admin_metrics_token: Optional[str] = None
    admin_token: Optional[str] = None
    admin_trace_sink: Optional[str] = None  # OTLP/HTTP collector endpoint
    k2v_api_bind_addr: Optional[str] = None
    codec: CodecConfig = field(default_factory=CodecConfig)
    # [table] — metadata-plane scaling: batched Merkle hashing, batched
    # sync descent, bucket-sharded listing fan-out
    table: TableTunables = field(default_factory=TableTunables)
    # [rpc] — degraded-mode resilience tunables (adaptive timeouts,
    # retry/backoff, read hedging, per-peer circuit breaker, the
    # static block-transfer timeout, and the end-to-end request
    # deadline budget); see docs/ROBUSTNESS.md
    rpc: ResilienceTunables = field(default_factory=ResilienceTunables)
    # [api] — overload protection at the front door: admission-gate
    # watermarks (max in-flight requests/bytes → 503 SlowDown past
    # them) and the background load governor's thresholds; see
    # docs/ROBUSTNESS.md "Overload & brownout"
    api: OverloadTunables = field(default_factory=OverloadTunables)
    # [health] — fail-slow peer detection: the comparative scorer's
    # factor/window/hysteresis knobs (docs/OBSERVABILITY.md "Fleet
    # health & SLOs")
    health: HealthTunables = field(default_factory=HealthTunables)
    # [slo] — per-endpoint availability + latency-threshold objectives
    # tracked as multi-window burn rates; [[slo.objective]] tables
    # override the defaults per endpoint
    slo: SloTunables = field(default_factory=SloTunables)
    # incident flight recorder (utils/flightrec.py): bundles kept on
    # disk (oldest deleted first) and the auto-trigger debounce window
    incident_max_bundles: int = 16
    incident_debounce_secs: float = 60.0
    # [cpu] sample_hz — continuous thread-stack sampler rate
    # (utils/cpuprof.py); default is co-prime with the 10/25/50/100 ms
    # periodic workers so sampling can't phase-lock onto them
    cpuprof_hz: float = 29.0
    consul_discovery: Optional[ConsulDiscoveryConfig] = None
    kubernetes_discovery: Optional[KubernetesDiscoveryConfig] = None
    # raw parsed TOML for anything not modeled
    raw: Dict[str, Any] = field(default_factory=dict, repr=False)


_SECRET_ENV = {
    "rpc_secret": "GARAGE_TPU_RPC_SECRET",
    "admin_token": "GARAGE_TPU_ADMIN_TOKEN",
    "admin_metrics_token": "GARAGE_TPU_METRICS_TOKEN",
}


def read_config(path: str) -> Config:
    """Load + validate a TOML config file (ref util/config.rs:239-266)."""
    with open(path, "rb") as f:
        raw = tomllib.load(f)
    return config_from_dict(raw)


def config_from_dict(raw: Dict[str, Any]) -> Config:
    cfg = Config(raw=raw)
    for key in (
        "metadata_dir", "block_size", "replication_mode",
        "data_replication_mode", "compression_level",
        "rpc_bind_addr", "rpc_public_addr", "rpc_secret", "bootstrap_peers",
        "db_engine", "metadata_fsync", "data_fsync", "root_domain",
        "disk_error_threshold", "disk_error_cooldown",
        "node_version", "rebalance_rate_mib", "rebuild_rate_mib",
    ):
        if key in raw:
            setattr(cfg, key, raw[key])
    if "block_size" in raw:
        cfg.block_size = parse_capacity(raw["block_size"])
    # human-friendly capacities for the disk-health knobs ("100M", "1G")
    for key in ("data_free_space_watermark", "quarantine_max_bytes"):
        if key in raw:
            setattr(cfg, key, parse_capacity(raw[key]))
    if "quarantine_max_files" in raw:
        v = raw["quarantine_max_files"]
        # a file COUNT: capacity suffixes ("1K" → 1000) would be
        # silently misread as counts, so only a plain integer is legal
        if isinstance(v, bool) or not isinstance(v, int) or v < 0:
            raise ConfigError(
                "quarantine_max_files must be a non-negative integer")
        cfg.quarantine_max_files = v
    if cfg.disk_error_threshold < 1:
        raise ConfigError("disk_error_threshold must be >= 1")
    if cfg.rebalance_rate_mib <= 0:
        raise ConfigError("rebalance_rate_mib must be > 0")
    if cfg.rebuild_rate_mib <= 0:
        raise ConfigError("rebuild_rate_mib must be > 0")
    cfg.replication_mode = str(cfg.replication_mode)

    dd = raw.get("data_dir", "./data")
    if isinstance(dd, str):
        cfg.data_dir = [{"path": dd}]
    elif isinstance(dd, list):
        cfg.data_dir = [
            {**d, "capacity": parse_capacity(d["capacity"])} if "capacity" in d else dict(d)
            for d in dd
        ]
    else:
        raise ConfigError("data_dir must be a string or list of tables")

    s3 = raw.get("s3_api", {})
    cfg.s3_api_bind_addr = s3.get("api_bind_addr", cfg.s3_api_bind_addr)
    cfg.s3_region = s3.get("s3_region", cfg.s3_region)
    cfg.root_domain = s3.get("root_domain", cfg.root_domain)

    web = raw.get("s3_web", {})
    cfg.web_bind_addr = web.get("bind_addr", cfg.web_bind_addr)
    cfg.web_root_domain = web.get("root_domain", cfg.web_root_domain)

    admin = raw.get("admin", {})
    cfg.admin_api_bind_addr = admin.get("api_bind_addr", cfg.admin_api_bind_addr)
    cfg.admin_trace_sink = admin.get("trace_sink", cfg.admin_trace_sink)
    cfg.admin_metrics_token = admin.get("metrics_token", cfg.admin_metrics_token)
    cfg.admin_token = admin.get("admin_token", cfg.admin_token)

    k2v = raw.get("k2v_api", {})
    cfg.k2v_api_bind_addr = k2v.get("api_bind_addr", cfg.k2v_api_bind_addr)

    for section, cls, attr, required in (
        ("consul_discovery", ConsulDiscoveryConfig, "consul_discovery",
         ("consul_http_addr", "service_name")),
        ("kubernetes_discovery", KubernetesDiscoveryConfig,
         "kubernetes_discovery", ("namespace", "service_name")),
    ):
        sec = raw.get(section)
        if sec is not None:
            known = {f.name for f in dataclasses.fields(cls)}
            bad = set(sec) - known
            if bad:
                raise ConfigError(f"unknown [{section}] keys: {sorted(bad)}")
            parsed = cls(**sec)
            missing = [k for k in required if not getattr(parsed, k)]
            if missing:
                raise ConfigError(f"[{section}] requires {missing}")
            setattr(cfg, attr, parsed)
    if cfg.consul_discovery is not None and cfg.consul_discovery.api not in (
        "catalog", "agent"
    ):
        raise ConfigError("consul_discovery.api must be catalog|agent")

    rpc = raw.get("rpc", {})
    known = {f.name for f in dataclasses.fields(ResilienceTunables)}
    bad = set(rpc) - known
    if bad:
        raise ConfigError(f"unknown [rpc] keys: {sorted(bad)}")
    cfg.rpc = ResilienceTunables(**rpc)
    if cfg.rpc.retry_max < 0:
        raise ConfigError("rpc.retry_max must be >= 0")
    if not 0.0 < cfg.rpc.hedge_quantile < 1.0:
        raise ConfigError("rpc.hedge_quantile must be in (0, 1)")
    if cfg.rpc.breaker_failure_threshold < 1:
        raise ConfigError("rpc.breaker_failure_threshold must be >= 1")
    if cfg.rpc.deadline_floor < 0:
        raise ConfigError("rpc.deadline_floor must be >= 0")

    api = dict(raw.get("api", {}))
    known = {f.name for f in dataclasses.fields(OverloadTunables)}
    bad = set(api) - known
    if bad:
        raise ConfigError(f"unknown [api] keys: {sorted(bad)}")
    # human-friendly capacities for the byte-sized QoS knobs
    for key in ("max_inflight_bytes", "wdrr_quantum_bytes",
                "wdrr_request_cost", "streaming_body_estimate"):
        if key in api:
            api[key] = parse_capacity(api[key])
    cfg.api = OverloadTunables(**api)
    if cfg.api.max_inflight < 0:
        raise ConfigError("api.max_inflight must be >= 0 (0 = unlimited)")
    if cfg.api.max_inflight_bytes < 0:
        raise ConfigError("api.max_inflight_bytes must be >= 0")
    if not 0.0 < cfg.api.governor_min_ratio <= 1.0:
        raise ConfigError("api.governor_min_ratio must be in (0, 1]")
    if not 0.0 <= cfg.api.governor_low < cfg.api.governor_high:
        raise ConfigError("api.governor_low must be in [0, governor_high)")
    if cfg.api.tenant_queue_len < 1:
        raise ConfigError("api.tenant_queue_len must be >= 1")
    if cfg.api.tenant_queue_wait < 0:
        raise ConfigError("api.tenant_queue_wait must be >= 0")
    if cfg.api.wdrr_quantum_bytes < 1:
        raise ConfigError("api.wdrr_quantum_bytes must be >= 1")
    if cfg.api.wdrr_request_cost < 0:
        raise ConfigError("api.wdrr_request_cost must be >= 0")
    if cfg.api.max_tracked_tenants < 1:
        raise ConfigError("api.max_tracked_tenants must be >= 1")
    if cfg.api.remote_pressure_shed < 0:
        raise ConfigError(
            "api.remote_pressure_shed must be >= 0 (0 = disabled)")
    if cfg.api.codel_target < 0:
        raise ConfigError("api.codel_target must be >= 0 (0 = static)")
    if cfg.api.codel_interval <= 0:
        raise ConfigError("api.codel_interval must be > 0")
    if cfg.api.streaming_body_estimate < 0:
        raise ConfigError("api.streaming_body_estimate must be >= 0")
    if cfg.api.drain_timeout < 0:
        raise ConfigError("api.drain_timeout must be >= 0")
    if cfg.api.longpoll_max_parked < 0:
        raise ConfigError(
            "api.longpoll_max_parked must be >= 0 (0 = 4x max_inflight)")
    if "retry_after_max" not in api:
        # pre-existing configs may carry retry_after > the new cap's
        # default: an upgrade must not refuse to boot — widen the
        # derived ceiling instead of raising
        cfg.api.retry_after_max = max(cfg.api.retry_after_max,
                                      int(cfg.api.retry_after), 1)
    if cfg.api.retry_after_max < max(int(cfg.api.retry_after), 1):
        raise ConfigError(
            "api.retry_after_max must be >= api.retry_after (and >= 1)")

    health = raw.get("health", {})
    known = {f.name for f in dataclasses.fields(HealthTunables)}
    bad = set(health) - known
    if bad:
        raise ConfigError(f"unknown [health] keys: {sorted(bad)}")
    cfg.health = HealthTunables(**health)
    if cfg.health.fail_slow_factor <= 1.0:
        raise ConfigError("health.fail_slow_factor must be > 1")
    if not 1.0 <= cfg.health.clear_factor <= cfg.health.fail_slow_factor:
        raise ConfigError(
            "health.clear_factor must be in [1, fail_slow_factor] "
            "(the hysteresis band)")
    if cfg.health.window_s < 0:
        raise ConfigError("health.window_s must be >= 0")
    if cfg.health.min_samples < 1 or cfg.health.min_baseline_peers < 1:
        raise ConfigError(
            "health.min_samples and health.min_baseline_peers must be >= 1")
    if cfg.health.sample_ttl_s <= 0:
        raise ConfigError("health.sample_ttl_s must be > 0")

    slo = dict(raw.get("slo", {}))
    # TOML [[slo.objective]] array-of-tables → the objectives list
    if "objective" in slo:
        slo["objectives"] = list(slo.pop("objective") or [])
    known = {f.name for f in dataclasses.fields(SloTunables)}
    bad = set(slo) - known
    if bad:
        raise ConfigError(f"unknown [slo] keys: {sorted(bad)}")
    cfg.slo = SloTunables(**slo)
    if not 0 < cfg.slo.bucket_s <= cfg.slo.fast_window_s \
            <= cfg.slo.slow_window_s:
        raise ConfigError(
            "[slo] needs 0 < bucket_s <= fast_window_s <= slow_window_s")
    if not 0.0 < cfg.slo.default_availability < 1.0:
        raise ConfigError("slo.default_availability must be in (0, 1)")
    if cfg.slo.default_latency_ms <= 0:
        raise ConfigError("slo.default_latency_ms must be > 0")
    if cfg.slo.fast_burn_threshold <= 0 or cfg.slo.min_events < 1:
        raise ConfigError(
            "slo.fast_burn_threshold must be > 0 and slo.min_events >= 1")
    if cfg.slo.max_endpoints < 1:
        raise ConfigError("slo.max_endpoints must be >= 1")
    for o in cfg.slo.objectives:
        if not isinstance(o, dict) or not o.get("endpoint"):
            raise ConfigError(
                "[[slo.objective]] entries need an `endpoint` key")
        extra = set(o) - {"endpoint", "availability", "latency_ms"}
        if extra:
            raise ConfigError(
                f"unknown [[slo.objective]] keys: {sorted(extra)}")
        av = o.get("availability")
        if av is not None and not 0.0 < float(av) < 1.0:
            raise ConfigError("objective availability must be in (0, 1)")
        lm = o.get("latency_ms")
        if lm is not None and float(lm) <= 0:
            raise ConfigError("objective latency_ms must be > 0")

    incident = raw.get("incident", {})
    bad = set(incident) - {"max_bundles", "debounce_secs"}
    if bad:
        raise ConfigError(f"unknown [incident] keys: {sorted(bad)}")
    if "max_bundles" in incident:
        v = incident["max_bundles"]
        if isinstance(v, bool) or not isinstance(v, int) or v < 1:
            raise ConfigError("incident.max_bundles must be an integer >= 1")
        cfg.incident_max_bundles = v
    if "debounce_secs" in incident:
        v = float(incident["debounce_secs"])
        if v < 0:
            raise ConfigError("incident.debounce_secs must be >= 0")
        cfg.incident_debounce_secs = v

    cpu = raw.get("cpu", {})
    bad = set(cpu) - {"sample_hz"}
    if bad:
        raise ConfigError(f"unknown [cpu] keys: {sorted(bad)}")
    if "sample_hz" in cpu:
        v = float(cpu["sample_hz"])
        if not 0.0 < v <= 1000.0:
            raise ConfigError("cpu.sample_hz must be in (0, 1000]")
        cfg.cpuprof_hz = v

    table = raw.get("table", {})
    known = {f.name for f in dataclasses.fields(TableTunables)}
    bad = set(table) - known
    if bad:
        raise ConfigError(f"unknown [table] keys: {sorted(bad)}")
    cfg.table = TableTunables(**table)
    for key in ("merkle_batch", "sync_batch_nodes", "list_shards",
                "scan_page"):
        v = getattr(cfg.table, key)
        if isinstance(v, bool) or not isinstance(v, int) or v < 1:
            raise ConfigError(f"table.{key} must be a positive integer")
    if cfg.table.list_shards > 64:
        raise ConfigError("table.list_shards must be <= 64")
    if cfg.table.sync_batch_nodes > 65536:
        raise ConfigError("table.sync_batch_nodes must be <= 65536")

    codec = raw.get("codec", {})
    known = {f.name for f in dataclasses.fields(CodecConfig)}
    bad = set(codec) - known
    if bad:
        raise ConfigError(f"unknown [codec] keys: {sorted(bad)}")
    cfg.codec = CodecConfig(**codec)
    if cfg.codec.backend not in ("cpu", "tpu", "hybrid"):
        raise ConfigError(
            f"codec.backend must be cpu|tpu|hybrid, got {cfg.codec.backend!r}"
        )
    if (cfg.codec.rs_data == 0) != (cfg.codec.rs_parity == 0):
        raise ConfigError("codec.rs_data and codec.rs_parity must both be 0 or both be >0")
    if cfg.codec.feeder_slo_ms < 0:
        raise ConfigError("codec.feeder_slo_ms must be >= 0")
    if cfg.codec.feeder_max_batch_blocks < 1:
        raise ConfigError("codec.feeder_max_batch_blocks must be >= 1")
    if cfg.codec.repair_hedge_ms < 0:
        raise ConfigError("codec.repair_hedge_ms must be >= 0")
    if cfg.codec.repair_tree_fanout < 1:
        raise ConfigError("codec.repair_tree_fanout must be >= 1")
    if cfg.codec.transport_staging_slots < 1:
        raise ConfigError("codec.transport_staging_slots must be >= 1")
    if cfg.codec.transport_bg_slack_ms < 0:
        raise ConfigError("codec.transport_bg_slack_ms must be >= 0")
    if cfg.codec.pool_mib < 0:
        raise ConfigError("codec.pool_mib must be >= 0 (0 disables the pool)")
    if cfg.codec.pool_page_kib < 1:
        raise ConfigError("codec.pool_page_kib must be >= 1")

    # secrets: env overrides > `<key>_file` in TOML > inline value
    for key, env in _SECRET_ENV.items():
        if os.environ.get(env):
            setattr(cfg, key, os.environ[env])
        elif raw.get(f"{key}_file"):
            setattr(cfg, key, secret_from_file(raw[f"{key}_file"]))
        elif key in ("admin_token", "admin_metrics_token") and admin.get(f"{key}_file"):
            setattr(cfg, key, secret_from_file(admin[f"{key}_file"]))
    return cfg
