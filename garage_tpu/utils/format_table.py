"""Column-aligned table printer for CLI output.

Equivalent of reference src/format-table/lib.rs (49 LoC): rows are
tab-separated strings; columns are padded to the widest cell.
"""

from __future__ import annotations

from typing import List


def format_table(rows: List[str]) -> str:
    cells = [r.split("\t") for r in rows]
    if not cells:
        return ""
    ncols = max(len(r) for r in cells)
    widths = [0] * ncols
    for r in cells:
        for i, c in enumerate(r):
            widths[i] = max(widths[i], len(c))
    out = []
    for r in cells:
        out.append(
            "  ".join(c.ljust(widths[i]) for i, c in enumerate(r)).rstrip()
        )
    return "\n".join(out)
