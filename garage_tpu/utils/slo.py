"""SLO engine — declarative per-endpoint objectives, multi-window burn
rates, error budgets.

Every robustness and performance claim this repo makes ("zero client
errors through the drill", "p99 held within 3x") has so far been judged
ad hoc, per script.  This module gives the judgement a standing
definition: a per-endpoint **availability** objective (fraction of
requests that must not fail server-side — 5xx, sheds, deadline
expiries) and a **latency-threshold** objective (fraction of successful
requests that must finish under a bound), both tracked as *burn rates*
over a fast and a slow window (the multi-window multi-burn-rate
alerting shape from the SRE literature):

    burn = (bad fraction observed in window) / (1 - target)

burn 1.0 means the error budget is being spent exactly at the rate
that exhausts it over the budget window; burn >= ``fast_burn_threshold``
over the fast window is a page-now signal — and here, the trigger that
snapshots an incident flight-recorder bundle (utils/flightrec.py)
while the evidence still exists.

The tracker is fed directly by the API front doors (S3 + K2V) at
request completion — sheds included, so admission verdicts burn the
availability budget like any other server-side failure — and keeps its
own time-bucketed ring per endpoint (cumulative Prometheus histograms
cannot answer "what happened in the last 5 minutes" process-side
without snapshot diffing).  Injectable clock; everything bounded
(buckets per endpoint, endpoints tracked).

Exported: ``slo_error_budget_remaining{endpoint,slo}`` and
``slo_burn_rate{endpoint,slo,window}``; read side is admin
``slo_status`` / CLI ``slo status`` and the per-phase ``slo_report``
block bench.py embeds.
"""

from __future__ import annotations

import time
from collections import deque
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

__all__ = ["SloTunables", "SloTracker"]


@dataclass
class SloTunables:
    """``[slo]`` — objectives + windows
    (docs/OBSERVABILITY.md "Fleet health & SLOs")."""

    # burn-rate windows: fast (page-now) and slow (budget) — the slow
    # window doubles as the budget-remaining horizon
    fast_window_s: float = 300.0
    slow_window_s: float = 3600.0
    # ring bucket width; ring length = slow_window_s / bucket_s
    bucket_s: float = 10.0
    # fast-window burn at/above this triggers the incident capture
    # (14 ~ the classic "2% of a 30d budget in 1h" page threshold)
    fast_burn_threshold: float = 14.0
    # events needed in the fast window before a breach verdict (one
    # failed request in an idle second must not page)
    min_events: int = 10
    # objectives applied to every endpoint not explicitly listed
    default_availability: float = 0.999
    default_latency_ms: float = 2000.0
    # per-endpoint overrides: [{endpoint, availability?, latency_ms?}]
    # (TOML: [[slo.objective]] tables)
    objectives: List[dict] = field(default_factory=list)
    # distinct endpoints tracked (cardinality bound; extras share one
    # "~overflow" series like the tenant tracker)
    max_endpoints: int = 64


class _Ring:
    """Per-endpoint time-bucketed counters:
    [bucket_start, total, err, slow]."""

    __slots__ = ("buckets",)

    def __init__(self, maxlen: int):
        self.buckets: deque = deque(maxlen=maxlen)

    def add(self, start: float, err: bool, slow: bool) -> None:
        b = self.buckets[-1] if self.buckets else None
        if b is None or b[0] != start:
            b = [start, 0, 0, 0]
            self.buckets.append(b)
        b[1] += 1
        b[2] += 1 if err else 0
        b[3] += 1 if slow else 0

    def window(self, now: float, window_s: float) -> tuple:
        """(total, err, slow) over buckets younger than window_s."""
        cut = now - window_s
        total = err = slow = 0
        for start, t, e, s in self.buckets:
            if start > cut:
                total += t
                err += e
                slow += s
        return total, err, slow


class SloTracker:
    """One per node.  ``note(endpoint, seconds, ok)`` at every request
    completion; reads are burn rates / budgets per (endpoint, slo)."""

    def __init__(self, tun: Optional[SloTunables] = None, metrics=None,
                 clock: Callable[[], float] = time.monotonic,
                 on_fast_burn: Optional[Callable[[str, str, float], None]] = None):
        self.tun = tun or SloTunables()
        self.clock = clock
        self.on_fast_burn = on_fast_burn
        self._rings: Dict[str, _Ring] = {}
        self._maxlen = max(2, int(self.tun.slow_window_s
                                  / max(self.tun.bucket_s, 0.001)) + 2)
        # per-endpoint objectives resolved once (config is immutable);
        # _resolved memoizes the {availability, latency_s} dicts so the
        # per-request note() path allocates nothing (bounded: callers
        # pass ring keys, capped at max_endpoints, plus the overrides)
        self._overrides = {
            str(o.get("endpoint")): o for o in self.tun.objectives
            if o.get("endpoint")
        }
        self._resolved: Dict[str, dict] = {}
        # last breach-check bucket per endpoint (one check per bucket,
        # not per request) and last breach signalled (re-arms when the
        # burn drops back under the threshold)
        self._checked_bucket: Dict[str, float] = {}
        self._breached: Dict[str, set] = {}
        self.fast_burn_breaches = 0
        if metrics is not None:
            metrics.gauge(
                "slo_error_budget_remaining",
                "Fraction of the slow-window error budget left per "
                "endpoint and objective (1 = untouched, <= 0 = spent)",
                labeled_fn=self._budget_samples)
            metrics.gauge(
                "slo_burn_rate",
                "Error-budget burn rate per endpoint, objective and "
                "window (1 = spending exactly the budget; >> 1 = "
                "budget-exhausting incident)",
                labeled_fn=self._burn_samples)

    # --- objectives ------------------------------------------------------

    def objective(self, endpoint: str) -> dict:
        obj = self._resolved.get(endpoint)
        if obj is None:
            o = self._overrides.get(endpoint, {})
            obj = self._resolved[endpoint] = {
                "availability": float(
                    o.get("availability", self.tun.default_availability)),
                "latency_s": float(
                    o.get("latency_ms",
                          self.tun.default_latency_ms)) / 1000.0,
            }
        return obj

    # --- ingest ----------------------------------------------------------

    def note(self, endpoint: str, seconds: float, ok: bool,
             client_paced: bool = False) -> None:
        """One finished request: `ok` False = server-side failure (5xx,
        shed, deadline) burning availability; a SLOW success (duration
        past the endpoint's latency threshold) burns the latency SLO.
        `client_paced` requests (long-polls, streamed transfers whose
        duration is the client's drain pace — the front doors derive it
        from the admission token's CoDel exclusion) still count toward
        availability but never mark slow: a healthy big-object or
        long-poll workload must not burn the latency budget."""
        ring = self._rings.get(endpoint)
        if ring is None:
            if len(self._rings) >= self.tun.max_endpoints:
                endpoint = "~overflow"
                ring = self._rings.get(endpoint)
            if ring is None:
                ring = self._rings[endpoint] = _Ring(self._maxlen)
        now = self.clock()
        start = now - (now % max(self.tun.bucket_s, 0.001))
        slow = (ok and not client_paced
                and seconds > self.objective(endpoint)["latency_s"])
        ring.add(start, err=not ok, slow=slow)
        self._maybe_breach(endpoint, ring, now, start,
                           bad_avail=not ok, bad_slow=slow)

    def _maybe_breach(self, endpoint: str, ring: _Ring, now: float,
                      bucket: float, bad_avail: bool = False,
                      bad_slow: bool = False) -> None:
        """Healthy traffic re-evaluates once per bucket (the window scan
        must not run per request), but a BAD event whose OWN objective
        is not yet latched re-evaluates immediately: an error burst
        confined to a single bucket — then silence — must still fire
        the breach (and its incident capture) at the moment the budget
        burns, not if and when a later bucket's first event happens to
        look back.  Once the burning objective is latched, its bad
        events fall back to the per-bucket cadence (the scan is O(ring)
        and errors are the common case mid-incident — an availability
        storm must not pay the scan per failure for its whole
        duration)."""
        if self.on_fast_burn is None:
            return
        latched = self._breached.get(endpoint, ())
        recheck = ((bad_avail and "availability" not in latched)
                   or (bad_slow and "latency" not in latched))
        if self._checked_bucket.get(endpoint) == bucket and not recheck:
            return
        self._checked_bucket[endpoint] = bucket
        total, err, slow = ring.window(now, self.tun.fast_window_s)
        if total < self.tun.min_events:
            return
        obj = self.objective(endpoint)
        breached = self._breached.setdefault(endpoint, set())
        for slo, n_bad, target in (("availability", err,
                                    obj["availability"]),
                                   ("latency", slow,
                                    obj["availability"])):
            budget = max(1.0 - target, 1e-9)
            burn = (n_bad / total) / budget
            if burn >= self.tun.fast_burn_threshold:
                if slo not in breached:
                    breached.add(slo)
                    self.fast_burn_breaches += 1
                    try:
                        self.on_fast_burn(endpoint, slo, burn)
                    except Exception:  # noqa: BLE001 — never break serving
                        pass
            else:
                breached.discard(slo)  # re-arm once the burn subsides

    # --- read side -------------------------------------------------------

    def burn_rate(self, endpoint: str, slo: str, window_s: float) -> float:
        ring = self._rings.get(endpoint)
        if ring is None:
            return 0.0
        total, err, slow = ring.window(self.clock(), window_s)
        if total == 0:
            return 0.0
        obj = self.objective(endpoint)
        bad = err if slo == "availability" else slow
        return (bad / total) / max(1.0 - obj["availability"], 1e-9)

    def budget_remaining(self, endpoint: str, slo: str) -> float:
        """1 - (bad events / allowed bad events) over the slow window;
        1.0 with no traffic, negative when the budget is overspent."""
        ring = self._rings.get(endpoint)
        if ring is None:
            return 1.0
        total, err, slow = ring.window(self.clock(), self.tun.slow_window_s)
        if total == 0:
            return 1.0
        obj = self.objective(endpoint)
        allowed = total * max(1.0 - obj["availability"], 1e-9)
        bad = err if slo == "availability" else slow
        return round(1.0 - bad / allowed, 6)

    def _budget_samples(self):
        return [
            ({"endpoint": ep, "slo": slo},
             self.budget_remaining(ep, slo))
            for ep in sorted(self._rings)
            for slo in ("availability", "latency")
        ]

    def _burn_samples(self):
        out = []
        for ep in sorted(self._rings):
            for slo in ("availability", "latency"):
                out.append((
                    {"endpoint": ep, "slo": slo, "window": "fast"},
                    round(self.burn_rate(ep, slo, self.tun.fast_window_s), 6)))
                out.append((
                    {"endpoint": ep, "slo": slo, "window": "slow"},
                    round(self.burn_rate(ep, slo, self.tun.slow_window_s), 6)))
        return out

    def report(self) -> Dict[str, dict]:
        """Raw per-endpoint window counts — bench.py aggregates these
        across cluster nodes into its per-phase ``slo_report`` (burn
        rates must be recomputed over the MERGED counts, not averaged)."""
        now = self.clock()
        out: Dict[str, dict] = {}
        for ep, ring in self._rings.items():
            obj = self.objective(ep)
            ft, fe, fs = ring.window(now, self.tun.fast_window_s)
            st, se, ss = ring.window(now, self.tun.slow_window_s)
            out[ep] = {
                "availability_target": obj["availability"],
                "latency_target_ms": round(obj["latency_s"] * 1000.0, 1),
                "fast": {"total": ft, "err": fe, "slow": fs},
                "slow": {"total": st, "err": se, "slow": ss},
            }
        return out

    def status(self) -> List[dict]:
        """Budget-table rows for admin ``slo_status`` / CLI
        ``slo status`` — one row per (endpoint, objective)."""
        rows = []
        for ep in sorted(self._rings):
            obj = self.objective(ep)
            ring = self._rings[ep]
            total, err, slow = ring.window(self.clock(),
                                           self.tun.slow_window_s)
            for slo, bad, target_str in (
                    ("availability", err, f"{obj['availability']:.4f}"),
                    ("latency", slow,
                     f"p<{obj['latency_s'] * 1000:.0f}ms")):
                fast = self.burn_rate(ep, slo, self.tun.fast_window_s)
                slow_b = self.burn_rate(ep, slo, self.tun.slow_window_s)
                rows.append({
                    "endpoint": ep,
                    "slo": slo,
                    "target": target_str,
                    "events": total,
                    "bad": bad,
                    "burn_fast": round(fast, 3),
                    "burn_slow": round(slow_b, 3),
                    "budget_remaining": self.budget_remaining(ep, slo),
                    "worst_window": ("fast" if fast >= slow_b else "slow"),
                })
        return rows
