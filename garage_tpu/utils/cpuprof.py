"""Continuous CPU profiler — always-on thread-stack sampling joined to
the waterfall segment taxonomy.

The scrub headline is CPU-bound with the device idle (ROADMAP
"device-armed round"), and while the PR 13 waterfalls name the dominant
*segment* of a request and the PR 16 link profiler names the dominant
*stage* of a device round trip, nothing could name the *function*
burning the CPU.  This module is that layer:

  - a daemon **sampler thread** walks ``sys._current_frames()`` at a
    configurable rate (default ~29 Hz — deliberately co-prime with
    common 10/25/50/100 ms periodic work so the sampler never phase-
    locks onto a timer loop and over- or under-counts it);
  - every sampled stack is folded into a bounded **stack trie** keyed
    by ``role → segment → frame…``, so memory stays O(max_nodes) no
    matter how long the process runs; when the trie fills, the coldest
    leaves are evicted by folding their counts into their parents
    (total sample counts are conserved — an evicted stack becomes a
    truncated stack, never a lost one);
  - a **thread registry** joins each sample to the attribution layer:
    long-lived threads register a role (``feeder-dispatch``,
    ``transport-stage``, ``incident-write``, …) with a default segment
    from the waterfall taxonomy, and event-loop threads register their
    loop so samples landing on the loop are joined to the *task-local
    span* that was running (``note_span_enter``/``note_span_exit`` are
    called by ``tracing.Span.__enter__/__exit__`` and keep a per-task
    segment stack the sampler can read from a foreign thread — the
    C-accelerated ``asyncio.Task`` on this interpreter exposes no
    ``_context``, so the join is explicit instead of introspective);
  - samples whose leaf frame is a known **waiter** (lock/queue/selector
    waits) count as idle: they feed the per-role busy-ratio window but
    never pollute the flamegraph with parked threads.

Output surfaces: ``cpu_profile_samples_total{role,segment}`` and
windowed ``cpu_busy_ratio{role}`` metrics, collapsed-stack
(flamegraph.pl-compatible) folded lines via ``folded()`` /
``recent_folded()``, and a bounded history ring of per-interval deltas
the flight recorder snapshots into incident bundles.

Everything the sampler does is guarded: a failure to classify one
thread skips that thread, never the sweep; the sweep itself times its
own cost and exports it (``cpu_profiler_overhead_ratio``) so the <2%
overhead budget is measured, not asserted.
"""

from __future__ import annotations

import asyncio
import os
import sys
import threading
import time
import weakref
from collections import Counter as _Counter
from collections import deque
from typing import Dict, Iterable, List, Optional, Tuple

from .waterfall import SEGMENTS, segment_of

# --- thread registry -------------------------------------------------------

# Default segment for each registered role.  Roles are a SMALL FIXED
# population (they label metric series); anything unregistered samples
# as role "other".
ROLE_SEGMENTS = {
    "feeder-dispatch": "feeder",
    "feeder-scrub": "codec",
    "hybrid-feeder": "feeder",
    "device-init": "device",
    "transport-stage": "transport",
    "incident-write": "disk",
    "merkle": "codec",
    "codec-hash": "codec",
    "aio-worker": "other",
    "event-loop": "api",
    "sampler": "other",
    "main": "other",
    "other": "other",
}

_reg_lock = threading.Lock()
_thread_roles: Dict[int, Tuple[str, str]] = {}   # ident -> (role, segment)
_loops: Dict[int, object] = {}                   # ident -> asyncio loop


def register_thread(role: str, segment: Optional[str] = None,
                    ident: Optional[int] = None) -> None:
    """Register the calling thread (or ``ident``) under ``role``.  Call
    from the first line of a long-lived thread's run function; pair
    with :func:`unregister_thread` in its ``finally``."""
    seg = segment or ROLE_SEGMENTS.get(role, "other")
    if seg not in SEGMENTS:
        seg = "other"
    if ident is None:
        ident = threading.get_ident()
    with _reg_lock:
        _thread_roles[ident] = (role, seg)


def unregister_thread(ident: Optional[int] = None) -> None:
    if ident is None:
        ident = threading.get_ident()
    with _reg_lock:
        _thread_roles.pop(ident, None)
        _loops.pop(ident, None)


def register_loop(role: str = "event-loop",
                  loop: Optional[object] = None) -> None:
    """Register the calling thread as an event-loop thread.  Samples on
    it are joined to the running task's span segment instead of the
    role's static default."""
    if loop is None:
        loop = asyncio.get_running_loop()
    ident = threading.get_ident()
    register_thread(role, ident=ident)
    with _reg_lock:
        _loops[ident] = loop


def thread_role(ident: int) -> Tuple[str, str]:
    with _reg_lock:
        rec = _thread_roles.get(ident)
    if rec is not None:
        return rec
    if ident == threading.main_thread().ident:
        return ("main", "other")
    return ("other", "other")


def registered_threads() -> Dict[int, Tuple[str, str]]:
    with _reg_lock:
        return dict(_thread_roles)


# --- task-local span join (the tracing hook) -------------------------------

# task -> stack of active segment names.  Weak keys: a task destroyed
# mid-span (loop torn down) drops its entry with it.  Written only from
# the task's own thread (span enter/exit run inside the task); read by
# the sampler thread under the GIL, guarded.
_task_segments: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()
_join_enabled = False


def enable_span_join(on: bool = True) -> None:
    """Profilers flip this on at start so un-profiled processes pay
    nothing per span."""
    global _join_enabled
    _join_enabled = on


def note_span_enter(name: str) -> None:
    """Called by ``tracing.Span.__enter__``: record the span's segment
    on the current task's stack so a foreign sampler thread can tag
    event-loop samples with the segment that was actually running."""
    if not _join_enabled:
        return
    try:
        task = asyncio.current_task()
    except RuntimeError:
        return
    if task is None:
        return
    stack = _task_segments.get(task)
    if stack is None:
        stack = []
        _task_segments[task] = stack
    stack.append(segment_of(name))


def note_span_exit() -> None:
    if not _join_enabled:
        return
    try:
        task = asyncio.current_task()
    except RuntimeError:
        return
    if task is None:
        return
    stack = _task_segments.get(task)
    if stack:
        stack.pop()
        if not stack:
            try:
                del _task_segments[task]
            except KeyError:
                pass


def _loop_segment(loop: object) -> Optional[str]:
    """Best-effort: the segment of the span running on ``loop``'s
    current task, read from the sampler thread.  Races with the loop
    are benign under the GIL; any failure means 'unknown'."""
    try:
        task = asyncio.tasks._current_tasks.get(loop)  # noqa: SLF001
        if task is None:
            return None
        stack = _task_segments.get(task)
        if stack:
            return stack[-1]
    except Exception:  # noqa: BLE001 — sampler must never break the node
        return None
    return None


# --- idle classification ---------------------------------------------------

# A sample whose LEAF frame is one of these well-known waiters is a
# parked thread, not CPU work: count it for the busy-ratio denominator
# but keep it out of the flamegraph.
_IDLE_LEAVES = {
    ("threading.py", "wait"),
    ("threading.py", "_wait_for_tstate_lock"),
    ("selectors.py", "select"),
    ("selectors.py", "poll"),
    ("queue.py", "get"),
    ("socket.py", "accept"),
    ("socket.py", "recv"),
    ("socket.py", "recv_into"),
    ("ssl.py", "recv"),
    ("ssl.py", "read"),
    ("subprocess.py", "wait"),
    ("subprocess.py", "_try_wait"),
    ("connection.py", "poll"),
    # a ThreadPoolExecutor worker parked on its (C-implemented)
    # SimpleQueue.get has no Python frame inside the get — the leaf IS
    # _worker, so a whole idle pool would read as busy without this
    ("thread.py", "_worker"),
}


def _is_idle_leaf(frame) -> bool:
    code = frame.f_code
    base = os.path.basename(code.co_filename)
    if (base, code.co_name) not in _IDLE_LEAVES:
        return False
    # GIL-handoff nuance: a foreign sampler acquires the GIL mostly at
    # VOLUNTARY release points, and an event loop with ready callbacks
    # voluntarily releases inside selector.select(timeout=0) every
    # iteration — so a BUSY loop would sample as parked-in-select.  A
    # zero timeout means "poll, there is work queued": that is loop
    # overhead, not idleness.  (Blocking waits pass None or > 0.)
    if base == "selectors.py":
        try:
            if frame.f_locals.get("timeout") == 0:
                return False
        except Exception:  # noqa: BLE001
            return True
    return True


# --- frame labelling -------------------------------------------------------

_label_cache: Dict[int, str] = {}
_LABEL_CACHE_MAX = 8192


def _frame_label(code) -> str:
    """``module.function`` label for one frame, collapsed-stack safe
    (no ``;`` or whitespace).  Memoized per code object."""
    label = _label_cache.get(id(code))
    if label is not None:
        return label
    base = os.path.basename(code.co_filename)
    if base == "__init__.py":
        base = os.path.basename(os.path.dirname(code.co_filename)) or base
    if base.endswith(".py"):
        base = base[:-3]
    label = f"{base}.{code.co_name}".replace(";", ":").replace(" ", "_")
    if len(_label_cache) >= _LABEL_CACHE_MAX:
        _label_cache.clear()
    _label_cache[id(code)] = label
    return label


# --- bounded stack trie ----------------------------------------------------


class _Node:
    __slots__ = ("count", "children")

    def __init__(self):
        self.count = 0          # samples whose stack ENDS here
        self.children: Dict[str, "_Node"] = {}


class StackTrie:
    """Bounded trie of folded stacks.  ``add()`` inserts a root-first
    path; when the node budget is exhausted the path is truncated at
    the deepest existing prefix (counted there, tallied as truncated),
    and the coldest leaves are evicted by folding their counts into
    their parents.  Total counts are conserved across both."""

    def __init__(self, max_nodes: int = 8192):
        self.max_nodes = max(16, int(max_nodes))
        self.root = _Node()
        self.nodes = 0
        self.total = 0
        self.truncated = 0
        self.evicted_nodes = 0

    def add(self, path: Iterable[str], n: int = 1) -> None:
        # evict BEFORE walking: evicting mid-walk could remove the very
        # node the walk is holding and detach the rest of the insertion
        if self.nodes >= self.max_nodes:
            self._evict()
        node = self.root
        for depth, part in enumerate(path):
            child = node.children.get(part)
            if child is None:
                # role/segment nodes (depth 0-1) bypass the budget:
                # they are a small fixed population and truncating them
                # would fold whole roles into the unattributed root
                if depth >= 2 and self.nodes >= self.max_nodes:
                    self.truncated += n
                    break
                child = _Node()
                node.children[part] = child
                self.nodes += 1
            node = child
        node.count += n
        self.total += n

    def _evict(self) -> None:
        """Fold the coldest leaves into their parents until the trie is
        back under 3/4 budget.  A leaf's count moves to its parent —
        the stack gets shorter, the samples stay.  Role/segment nodes
        (depth ≤ 1) are never evicted."""
        target = self.max_nodes * 3 // 4
        while self.nodes > target:
            leaves: List[Tuple[int, _Node, str, _Node]] = []
            stack = [(self.root, None, None, 0)]
            while stack:
                node, parent, key, depth = stack.pop()
                if not node.children and parent is not None and depth > 2:
                    leaves.append((node.count, parent, key, node))
                else:
                    for k, c in node.children.items():
                        stack.append((c, node, k, depth + 1))
            if not leaves:
                break
            leaves.sort(key=lambda rec: rec[0])
            evicted_any = False
            for count, parent, key, _node in leaves[:max(
                    1, self.nodes - target)]:
                if key not in parent.children:
                    continue
                parent.count += count
                del parent.children[key]
                self.nodes -= 1
                self.evicted_nodes += 1
                evicted_any = True
                if self.nodes <= target:
                    break
            if not evicted_any:
                break

    def folded(self) -> _Counter:
        """``{"a;b;c": count}`` for every path with samples."""
        out: _Counter = _Counter()
        stack: List[Tuple[_Node, Tuple[str, ...]]] = [(self.root, ())]
        while stack:
            node, path = stack.pop()
            if node.count and path:
                out[";".join(path)] += node.count
            for k, c in node.children.items():
                stack.append((c, path + (k,)))
        return out


# --- the profiler ----------------------------------------------------------

DEFAULT_HZ = 29.0
MAX_STACK_DEPTH = 48


class CpuProfiler:
    """Always-on sampling profiler.  ``start()`` spawns the daemon
    sampler; tests drive :meth:`sample_once` directly with synthetic
    frames and a fake clock for determinism."""

    def __init__(self, metrics=None, hz: float = DEFAULT_HZ,
                 max_nodes: int = 8192, window_s: float = 60.0,
                 history_s: float = 30.0, flush_s: float = 5.0,
                 clock=time.monotonic):
        self.hz = max(0.1, float(hz))
        self.interval = 1.0 / self.hz
        self.window_s = float(window_s)
        self.history_s = float(history_s)
        self.flush_s = max(0.5, float(flush_s))
        self.clock = clock
        self.trie = StackTrie(max_nodes=max_nodes)
        self.samples = 0
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # per-second busy/total buckets per role, for cpu_busy_ratio
        self._busy: deque = deque()      # (sec, {role: [busy, total]})
        # sampler self-cost buckets, for the overhead ratio
        self._cost: deque = deque()      # (sec, spent_s, wall_s)
        # history ring of folded deltas for the flight recorder
        self._history: deque = deque()   # (t, Counter)
        self._last_fold: _Counter = _Counter()
        self._last_flush = clock()
        self._metrics_samples = None
        if metrics is not None:
            self._register_metrics(metrics)

    # -- metrics --

    def _register_metrics(self, metrics) -> None:
        self._metrics_samples = metrics.counter(
            "cpu_profile_samples_total",
            "CPU profile samples by thread role and joined segment")
        self._metrics_evicted = metrics.counter(
            "cpu_profile_truncated_samples_total",
            "Samples truncated by the stack-trie node budget")
        metrics.gauge(
            "cpu_busy_ratio",
            "Fraction of profiler samples on-CPU per thread role "
            "(rolling window)",
            labeled_fn=lambda: [({"role": r}, v)
                                for r, v in sorted(
                                    self.busy_ratio().items())])
        metrics.gauge("cpu_profiler_overhead_ratio",
                      "Sampler self-cost as a fraction of wall time "
                      "(rolling window)", fn=self.overhead_ratio)
        metrics.gauge("cpu_profile_trie_nodes",
                      "Live nodes in the profiler's bounded stack trie",
                      fn=lambda: float(self.trie.nodes))

    # -- lifecycle --

    def start(self) -> "CpuProfiler":
        if self._thread is not None:
            return self
        enable_span_join(True)
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name="cpu-sampler", daemon=True)
        self._thread.start()
        return self

    def stop(self, timeout: float = 2.0) -> None:
        t = self._thread
        if t is None:
            return
        self._stop.set()
        t.join(timeout=timeout)
        self._thread = None

    @property
    def running(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    def _run(self) -> None:
        register_thread("sampler")
        try:
            while not self._stop.is_set():
                t0 = self.clock()
                try:
                    self.sample_once(now=t0)
                except Exception:  # noqa: BLE001 — never kill the node
                    pass
                spent = self.clock() - t0
                self._note_cost(t0, spent)
                # sleep the REMAINDER of the interval so a slow sweep
                # doesn't compound the sampling rate error
                self._stop.wait(max(0.0, self.interval - spent))
        finally:
            unregister_thread()

    # -- sampling --

    def sample_once(self, now: Optional[float] = None,
                    frames: Optional[Dict[int, object]] = None) -> int:
        """One sweep over every thread's current frame.  ``frames``
        lets tests inject synthetic stacks; production reads
        ``sys._current_frames()``."""
        if now is None:
            now = self.clock()
        if frames is None:
            frames = sys._current_frames()  # noqa: SLF001
        # never sample the sweeping thread itself: it is awake only
        # while sweeping, so it would always observe itself busy
        # (self-observation bias); its true cost is the measured
        # cpu_profiler_overhead_ratio
        self_ident = threading.get_ident()
        with _reg_lock:
            loops = dict(_loops)
        busy_seen = 0
        sec = int(now)
        with self._lock:
            bucket = self._busy[-1][1] if (self._busy
                                           and self._busy[-1][0] == sec) \
                else None
            if bucket is None:
                bucket = {}
                self._busy.append((sec, bucket))
                self._trim(now)
            for ident, frame in frames.items():
                if ident == self_ident:
                    continue
                try:
                    role, seg = thread_role(ident)
                    loop = loops.get(ident)
                    if loop is not None:
                        seg = _loop_segment(loop) or seg
                    idle = _is_idle_leaf(frame)
                    rec = bucket.setdefault(role, [0, 0])
                    rec[1] += 1
                    if idle:
                        continue
                    rec[0] += 1
                    busy_seen += 1
                    path = self._fold_path(role, seg, frame)
                    before_trunc = self.trie.truncated
                    self.trie.add(path)
                    self.samples += 1
                    if self._metrics_samples is not None:
                        self._metrics_samples.inc(
                            1, role=role, segment=seg)
                        if self.trie.truncated != before_trunc:
                            self._metrics_evicted.inc(1)
                except Exception:  # noqa: BLE001 — skip thread, not sweep
                    continue
            if now - self._last_flush >= self.flush_s:
                self._flush_history(now)
        return busy_seen

    @staticmethod
    def _fold_path(role: str, seg: str, frame) -> List[str]:
        rev = []
        f = frame
        while f is not None and len(rev) < MAX_STACK_DEPTH:
            rev.append(_frame_label(f.f_code))
            f = f.f_back
        rev.reverse()            # outermost first (flamegraph order)
        return [role, seg] + rev

    def _note_cost(self, t0: float, spent: float) -> None:
        sec = int(t0)
        with self._lock:
            if self._cost and self._cost[-1][0] == sec:
                last = self._cost[-1]
                self._cost[-1] = (sec, last[1] + spent, last[2])
            else:
                self._cost.append((sec, spent, 1.0))

    def _trim(self, now: float) -> None:
        horizon = now - self.window_s
        while self._busy and self._busy[0][0] < horizon:
            self._busy.popleft()
        while self._cost and self._cost[0][0] < horizon:
            self._cost.popleft()
        hist_horizon = now - self.history_s
        while self._history and self._history[0][0] < hist_horizon:
            self._history.popleft()

    def _flush_history(self, now: float) -> None:
        cur = self.trie.folded()
        delta = cur - self._last_fold
        if delta:
            self._history.append((now, delta))
        self._last_fold = cur
        self._last_flush = now

    # -- readouts --

    def busy_ratio(self) -> Dict[str, float]:
        """Per-role on-CPU fraction over the rolling window."""
        agg: Dict[str, List[int]] = {}
        with self._lock:
            for _sec, bucket in self._busy:
                for role, (busy, total) in bucket.items():
                    rec = agg.setdefault(role, [0, 0])
                    rec[0] += busy
                    rec[1] += total
        return {r: (b / t if t else 0.0) for r, (b, t) in agg.items()}

    def overhead_ratio(self) -> float:
        """Sampler self-cost / wall over the rolling window: the
        measured answer to the <2% overhead budget."""
        with self._lock:
            if not self._cost:
                return 0.0
            spent = sum(s for _sec, s, _n in self._cost)
            wall = max(1.0, self._cost[-1][0] - self._cost[0][0] + 1)
        return spent / wall

    def folded_counter(self) -> _Counter:
        with self._lock:
            return self.trie.folded()

    def folded(self, top_k: Optional[int] = None) -> List[str]:
        """flamegraph.pl-compatible collapsed lines, hottest first."""
        counts = self.folded_counter()
        items = counts.most_common(top_k) if top_k else sorted(
            counts.items(), key=lambda kv: (-kv[1], kv[0]))
        return [f"{stack} {count}" for stack, count in items]

    def recent_folded(self, seconds: float,
                      top_k: Optional[int] = None) -> List[str]:
        """Collapsed lines covering roughly the last ``seconds``:
        flushed history deltas inside the window plus the live
        not-yet-flushed delta.  Served instantly (no re-sampling wait)
        — the sampler is always on."""
        now = self.clock()
        merged: _Counter = _Counter()
        with self._lock:
            for t, delta in self._history:
                if t >= now - seconds:
                    merged.update(delta)
            merged.update(self.trie.folded() - self._last_fold)
        items = merged.most_common(top_k) if top_k else sorted(
            merged.items(), key=lambda kv: (-kv[1], kv[0]))
        return [f"{stack} {count}" for stack, count in items]

    def profile(self, seconds: Optional[float] = 10.0,
                top_k: Optional[int] = 40) -> Dict[str, object]:
        """The machine-readable profile block served by the admin
        command and embedded in incident bundles / BENCH JSON.
        ``seconds=None`` folds the CUMULATIVE trie (everything since
        start — what a bench phase embeds) instead of the bounded
        history window."""
        if seconds is None:
            lines = self.folded(top_k=None)
        else:
            lines = self.recent_folded(seconds, top_k=None)
        total = sum(int(ln.rsplit(" ", 1)[1]) for ln in lines) or 1
        top = []
        for ln in (lines[:top_k] if top_k else lines):
            stack, count = ln.rsplit(" ", 1)
            parts = stack.split(";")
            top.append({
                "stack": stack,
                "role": parts[0] if parts else "other",
                "segment": parts[1] if len(parts) > 1 else "other",
                "leaf": parts[-1] if parts else "",
                "count": int(count),
                "share": round(int(count) / total, 4),
            })
        return {
            "seconds": round(float(seconds), 3) if seconds else None,
            "hz": self.hz,
            "samples": total if lines else 0,
            "busy_ratio": {r: round(v, 4)
                           for r, v in sorted(self.busy_ratio().items())},
            "overhead_ratio": round(self.overhead_ratio(), 5),
            "trie_nodes": self.trie.nodes,
            "truncated_samples": self.trie.truncated,
            "top": top,
        }

    def flight_recorder_section(self, seconds: float = 30.0,
                                top_k: int = 60) -> Dict[str, object]:
        """Collector payload for incident bundles: the last N seconds
        of folded stacks plus the windowed ratios."""
        return self.profile(seconds=seconds, top_k=top_k)


# --- module-level convenience ---------------------------------------------

_default: Optional[CpuProfiler] = None


def install(metrics=None, **kw) -> CpuProfiler:
    """Create/start the process-wide profiler (idempotent)."""
    global _default
    if _default is None:
        _default = CpuProfiler(metrics=metrics, **kw).start()
    return _default


def get() -> Optional[CpuProfiler]:
    return _default


def uninstall() -> None:
    global _default
    if _default is not None:
        _default.stop()
        _default = None
    enable_span_join(False)
