"""Request waterfalls — critical-path attribution for client requests.

Through PR 12 the system could say *that* a request was slow
(`api_request_duration_seconds`, the slow-op log) but never *where the
time went*: queue wait vs signature vs table quorum vs block RPC vs
feeder wait vs device compute.  This module is the attribution layer on
top of the PR 1–2 span plumbing:

  - a **segment taxonomy** mapping every span name the system emits to
    one of a small, stable set of segments (admission / queue /
    signature / table / rpc / feeder / codec / transport / device /
    disk / api / other);
  - **critical-path segment math**: a timeline sweep over one request's
    span tree that attributes every instant of the root span's duration
    to the DEEPEST span covering it (ties to the latest-started), so
    parallel fan-outs (a quorum write's concurrent RPCs) are never
    double-counted and the per-segment seconds sum to the request
    duration EXACTLY;
  - a bounded, always-on **WaterfallRecorder**: every finished span
    lands in a recent-span ring (the cross-node fetch window for the
    admin `request waterfall` merge); when a request ROOT span
    finishes, the request is sampled (top-N-slowest candidates always,
    plus every `sample_every`-th request), its breakdown computed, the
    dominant segment observed into
    `request_critical_path_seconds{endpoint,segment}` (with the trace
    id as the exemplar), and the slowest trees per endpoint retained as
    p99 exemplars — the trace id printed next to a histogram bucket is
    directly linkable to a retained waterfall.

Everything is bounded: the ring, the per-endpoint heap, the endpoint
map, the spans stored per retained tree.  The recorder never touches
the network — cross-node merging is the admin layer's job
(`admin/handler.py _cmd_request_waterfall` fans out `trace_spans`).
"""

from __future__ import annotations

import heapq
import threading
import time
from collections import deque
from typing import Dict, List, Optional, Tuple

# The segment taxonomy.  Every span name the system emits maps into one
# of these; the admin smoke asserts a live request's dominant segment is
# one of them.  docs/OBSERVABILITY.md "Critical path & saturation"
# documents what each covers.
SEGMENTS = (
    "admission",   # front-door gate: WDRR queue wait + verdict
    "queue",       # generic queue-wait split (a span's queue_s prefix)
    "signature",   # SigV4 verification
    "table",       # metadata table quorum ops
    "rpc",         # RPC fabric: quorum calls + remote handler time
    "feeder",      # codec feeder: submit-to-result envelope
    "codec",       # CPU-side ragged codec compute
    "transport",   # device transport EDF queue wait
    "device",      # device stage + compute + collect
    "disk",        # local block store I/O
    "api",         # request handler self-time (parse, stream, respond)
    "other",       # anything unmapped (kept visible, never hidden)
)

# longest-prefix-first mapping from span names (see the span creation
# sites: api/common.request_trace, rpc_helper, netapp, table, manager,
# feeder, transport)
_PREFIX_SEGMENTS = (
    ("admission", "admission"),
    ("signature", "signature"),
    ("Table ", "table"),
    ("RPC handler ", "rpc"),
    ("RPC ", "rpc"),
    ("Block ", "disk"),
    ("Feeder ", "feeder"),
    ("Codec ", "codec"),
    ("Transport ", "transport"),
    ("Device ", "device"),
    ("Scrub ", "codec"),
    ("S3 ", "api"),
    ("K2V ", "api"),
    ("Web ", "api"),
)


def segment_of(name: str) -> str:
    for prefix, seg in _PREFIX_SEGMENTS:
        if name.startswith(prefix):
            return seg
    return "other"


# --- critical-path segment math -------------------------------------------


def _depths(records: List[dict], root: dict) -> Dict[str, int]:
    """span_id -> tree depth (root = 0).  A span whose parent is not in
    the set (e.g. a remote handler span fetched without its local rpc
    parent) counts as a direct child of the root — it still attributes
    deeper than the root, which is what the sweep needs."""
    by_id = {r["span"]: r for r in records}
    depths: Dict[str, int] = {root["span"]: 0}

    def depth(sid: str) -> int:
        d = depths.get(sid)
        if d is not None:
            return d
        seen = []
        cur = sid
        while cur is not None and cur not in depths:
            seen.append(cur)
            if len(seen) > 128:  # cycle/chain guard
                break
            r = by_id.get(cur)
            cur = r.get("parent") if r is not None else None
        base = depths.get(cur, 0) if cur is not None else 0
        for i, s in enumerate(reversed(seen)):
            depths[s] = base + i + 1
        return depths.get(sid, 1)

    for r in records:
        depth(r["span"])
    return depths


def segment_breakdown(records: List[dict],
                      root: dict) -> Dict[str, float]:
    """{segment: seconds} attributing the root span's whole duration.

    Timeline sweep: every elementary interval between span boundaries is
    attributed to the segment of the DEEPEST span covering it (ties to
    the latest-started, then the later-recorded).  Parallel siblings
    therefore never double-count, and the values sum to the root
    duration exactly.  A span carrying a `queue_s` attribute splits: its
    first `queue_s` seconds attribute to the `queue` segment at depth
    just below its children (a child span covering the queue window
    still wins)."""
    t0, t1 = int(root["start_ns"]), int(root["end_ns"])
    if t1 <= t0:
        return {}
    depths = _depths(records, root)
    # (start, end, sortkey, segment) intervals; sortkey orders "deepest
    # wins" with start-time and insertion tiebreaks
    ivals: List[Tuple[int, int, tuple, str]] = []
    for i, r in enumerate(records):
        s = max(int(r["start_ns"]), t0)
        e = min(int(r["end_ns"]), t1)
        if e <= s:
            continue
        d = depths.get(r["span"], 1)
        seg = segment_of(r["name"])
        qs = (r.get("attrs") or {}).get("queue_s")
        if isinstance(qs, (int, float)) and qs > 0:
            qe = min(s + int(float(qs) * 1e9), e)
            if qe > s:
                # queue window: deeper than the span itself (the +0.5),
                # shallower than its children (at d+1)
                ivals.append((s, qe, (d + 0.5, s, i), "queue"))
            ivals.append((s, e, (d, s, i), seg))
        else:
            ivals.append((s, e, (d, s, i), seg))
    if not ivals:
        return {segment_of(root["name"]): (t1 - t0) / 1e9}
    # incremental sweep: this runs inside Span.__exit__ on the event
    # loop for sampled requests, so the cost must track the ACTIVE
    # overlap (tree depth + parallel siblings, typically < 10), not
    # intervals².  Boundary events add/remove intervals from an active
    # map; each elementary interval takes max() over the active set.
    events: List[Tuple[int, int, int]] = []  # (boundary, +1/-1, ival idx)
    for i, (s, e, _key, _seg) in enumerate(ivals):
        events.append((s, 1, i))
        events.append((e, -1, i))
    events.sort(key=lambda ev: (ev[0], ev[1]))  # removals before adds
    out: Dict[str, float] = {}
    active: Dict[int, None] = {}
    root_seg = segment_of(root["name"])
    prev = t0
    for bound, kind, idx in events:
        b = min(max(bound, t0), t1)
        if b > prev:
            if active:
                _key, seg = max(ivals[i][2:] for i in active)
            else:
                seg = root_seg
            out[seg] = out.get(seg, 0.0) + (b - prev) / 1e9
            prev = b
        if kind == 1:
            active[idx] = None
        else:
            active.pop(idx, None)
    if t1 > prev:
        out[root_seg] = out.get(root_seg, 0.0) + (t1 - prev) / 1e9
    return out


def dominant_segment(segments: Dict[str, float]) -> Tuple[str, float]:
    if not segments:
        return "other", 0.0
    seg = max(segments, key=lambda s: segments[s])
    return seg, segments[seg]


def build_tree(records: List[dict], root: dict,
               max_spans: int = 512) -> dict:
    """Nested {name, span, start_ns, end_ns, seconds, segment, attrs,
    children} tree for rendering; children sorted by start time.  Spans
    whose parent is absent attach under the root."""
    by_parent: Dict[Optional[str], List[dict]] = {}
    ids = {r["span"] for r in records} | {root["span"]}
    for r in records[:max_spans]:
        if r["span"] == root["span"]:
            continue
        parent = r.get("parent")
        if parent not in ids:
            parent = root["span"]
        by_parent.setdefault(parent, []).append(r)

    def node(r: dict) -> dict:
        kids = sorted(by_parent.get(r["span"], []),
                      key=lambda c: c["start_ns"])
        return {
            "name": r["name"],
            "span": r["span"],
            "start_ns": int(r["start_ns"]),
            "end_ns": int(r["end_ns"]),
            "seconds": round((int(r["end_ns"]) - int(r["start_ns"])) / 1e9,
                             6),
            "segment": segment_of(r["name"]),
            "attrs": {k: v for k, v in (r.get("attrs") or {}).items()
                      if isinstance(v, (str, int, float, bool))},
            "children": [node(c) for c in kids],
        }

    return node(root)


# --- the recorder ----------------------------------------------------------


class WaterfallRecorder:
    """Always-on, bounded request-waterfall retention for one node."""

    KEEP = 4             # slowest trees retained per endpoint
    RING = 4096          # recent finished spans (cross-node fetch window)
    MAX_ENDPOINTS = 64   # endpoint label cardinality bound
    MAX_TRACE_SPANS = 256
    SAMPLE_EVERY = 8     # non-candidate requests sampled 1-in-N

    def __init__(self, metrics=None, keep: int = KEEP, ring: int = RING,
                 sample_every: int = SAMPLE_EVERY):
        self._ring: deque = deque(maxlen=ring)
        self._lock = threading.Lock()
        self._keep = max(1, keep)
        self._sample_every = max(1, sample_every)
        # endpoint -> min-heap of (seconds, seq, entry)
        self._top: Dict[str, list] = {}
        # endpoint -> {"count": sampled requests, "seconds": total root
        # seconds, "segments": {segment: seconds}} — the bench phases
        # read deltas of this for their per-phase breakdown
        self._totals: Dict[str, dict] = {}
        self._seq = 0
        self.sampled = 0
        self.finalized = 0
        if metrics is not None:
            self.m_cp = metrics.histogram(
                "request_critical_path_seconds",
                "Self-time of the dominant critical-path segment per "
                "sampled request, by endpoint and segment",
                exemplars=True)
            # no _total suffix: that suffix is counter-reserved in the
            # Prometheus conventions and this renders as a gauge
            metrics.gauge(
                "request_waterfall_sampled",
                "Requests whose span tree was sampled for critical-path "
                "attribution", fn=lambda: float(self.sampled))
        else:
            self.m_cp = None

    # --- ingest (called by Tracer._record for every finished span) ---

    def note(self, rec: dict) -> None:
        with self._lock:
            self._ring.append(rec)
        # request roots: minted by api/common.request_trace (no parent,
        # an `api` attr).  Background roots (resync loops, scrub) have
        # no api attr and just age out of the ring.
        if rec.get("parent") is None and (rec.get("attrs") or {}).get("api"):
            self._finalize(rec)

    def _endpoint_of(self, rec: dict) -> str:
        attrs = rec.get("attrs") or {}
        ep = attrs.get("endpoint") or rec["name"]
        with self._lock:
            # the cap INCLUDES the overflow bucket: one slot is held in
            # reserve so forged endpoint floods pool instead of growing
            if (ep not in self._totals
                    and len(self._totals) >= self.MAX_ENDPOINTS - 1
                    and ep != "~overflow"):
                return "~overflow"
        return str(ep)

    def _finalize(self, root: dict) -> None:
        self.finalized += 1
        dur_s = (int(root["end_ns"]) - int(root["start_ns"])) / 1e9
        if dur_s <= 0:
            return
        endpoint = self._endpoint_of(root)
        tid = root["trace"]
        with self._lock:
            self._seq += 1
            heap = self._top.setdefault(endpoint, [])
            qualifies = len(heap) < self._keep or dur_s > heap[0][0]
            if not qualifies and self._seq % self._sample_every != 0:
                return
            spans = [r for r in self._ring if r["trace"] == tid]
        spans = spans[-self.MAX_TRACE_SPANS:]
        segments = segment_breakdown(spans, root)
        dom, dom_s = dominant_segment(segments)
        entry = {
            "trace_id": tid,
            "endpoint": endpoint,
            "seconds": round(dur_s, 6),
            "ts": round(time.time(), 3),
            "segments": {k: round(v, 6) for k, v in sorted(
                segments.items(), key=lambda kv: -kv[1])},
            "dominant": dom,
            "span_count": len(spans),
            # the local span records, retained with the tree so the
            # waterfall survives ring eviction (admin merges remote
            # spans in at fetch time)
            "local_spans": spans,
        }
        with self._lock:
            self.sampled += 1
            tot = self._totals.setdefault(
                endpoint, {"count": 0, "seconds": 0.0, "segments": {}})
            tot["count"] += 1
            tot["seconds"] += dur_s
            for seg, s in segments.items():
                tot["segments"][seg] = tot["segments"].get(seg, 0.0) + s
            if qualifies:
                if len(heap) >= self._keep:
                    heapq.heapreplace(heap, (dur_s, self._seq, entry))
                else:
                    heapq.heappush(heap, (dur_s, self._seq, entry))
        if self.m_cp is not None:
            self.m_cp.observe(dom_s, trace_exemplar=tid,
                              endpoint=endpoint, segment=dom)

    # --- read side -------------------------------------------------------

    def endpoints(self) -> List[dict]:
        """Per-endpoint summary: sampled count, mean duration, dominant
        segment of the cumulative breakdown, retained exemplar count."""
        out = []
        with self._lock:
            for ep, tot in sorted(self._totals.items()):
                dom, _s = dominant_segment(tot["segments"])
                out.append({
                    "endpoint": ep,
                    "sampled": tot["count"],
                    "mean_ms": round(
                        tot["seconds"] / tot["count"] * 1000.0, 3)
                    if tot["count"] else 0.0,
                    "dominant": dom,
                    "retained": len(self._top.get(ep, [])),
                })
        return out

    def entries(self, endpoint: Optional[str] = None) -> List[dict]:
        """Retained waterfall entries (without the raw spans), slowest
        first."""
        with self._lock:
            heaps = ([self._top.get(endpoint, [])] if endpoint
                     else list(self._top.values()))
            items = [e for h in heaps for _d, _s, e in h]
        items.sort(key=lambda e: -e["seconds"])
        return [{k: v for k, v in e.items() if k != "local_spans"}
                for e in items]

    def entry_for(self, trace_id: Optional[str] = None,
                  endpoint: Optional[str] = None) -> Optional[dict]:
        """One retained entry WITH its local spans: by trace id, or the
        slowest retained for `endpoint`, or the slowest overall."""
        with self._lock:
            candidates = [e for h in self._top.values() for _d, _s, e in h]
        if trace_id is not None:
            for e in candidates:
                if e["trace_id"] == trace_id:
                    return e
            return None
        if endpoint is not None:
            candidates = [e for e in candidates if e["endpoint"] == endpoint]
        return max(candidates, key=lambda e: e["seconds"], default=None)

    def spans_for_trace(self, trace_id: str) -> List[dict]:
        """Every span record this node holds for `trace_id`: the recent
        ring plus any retained entry's spans (the cross-node fetch the
        admin waterfall merge calls on peers)."""
        with self._lock:
            out = {r["span"]: r for r in self._ring
                   if r["trace"] == trace_id}
            for h in self._top.values():
                for _d, _s, e in h:
                    if e["trace_id"] == trace_id:
                        for r in e["local_spans"]:
                            out.setdefault(r["span"], r)
        return list(out.values())

    def totals(self) -> Dict[str, dict]:
        """Cumulative per-endpoint sampled breakdown (bench reads phase
        deltas of this)."""
        with self._lock:
            return {
                ep: {
                    "count": tot["count"],
                    "seconds": round(tot["seconds"], 6),
                    "segments": {k: round(v, 6)
                                 for k, v in tot["segments"].items()},
                }
                for ep, tot in self._totals.items()
            }
