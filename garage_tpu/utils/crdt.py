"""Conflict-free replicated data types.

Equivalent of reference src/util/crdt/*: the `Crdt` merge trait
(crdt/crdt.rs:19-27) and its instances — `Lww` (lww.rs:41-44), `LwwMap`
(lww_map.rs), `Map` (map.rs), `Bool` (bool.rs, or-merge), `Deletable`
(deletable.rs), and `AutoCrdt` max-merge for totally ordered values
(crdt.rs:43-58).

Merge must be commutative, associative and idempotent; all replicated
metadata in the framework is a CRDT so replicas converge without
coordination.  Values are kept as plain Python data (msgpack-encodable);
each CRDT knows how to (de)serialize itself to primitive structures.
"""

from __future__ import annotations

import time
from typing import Any, Callable, Dict, Generic, List, Optional, Tuple, TypeVar

T = TypeVar("T")


def now_msec() -> int:
    """Wall-clock ms — LWW timestamps (ref util/time.rs:4-16)."""
    return int(time.time() * 1000)


def _value_order_key(v: Any) -> bytes:
    """Deterministic total order over arbitrary msgpack-able values, used to
    tie-break LWW merges at equal timestamps.  Python's `>` is partial (dicts
    aren't orderable), so we compare canonical msgpack encodings — stable
    across replicas, which is all convergence needs."""
    import msgpack

    try:
        return msgpack.packb(v, use_bin_type=True)
    except Exception:
        return repr(v).encode()


def _tie_break_gt(a: Any, b: Any) -> bool:
    """a > b under a total order that never raises."""
    try:
        return bool(a > b)
    except TypeError:
        return _value_order_key(a) > _value_order_key(b)


class Crdt:
    """Base merge trait (ref util/crdt/crdt.rs:19-27)."""

    def merge(self, other: "Crdt") -> None:
        raise NotImplementedError

    # --- serialization to msgpack-friendly primitives ---
    def pack(self) -> Any:
        raise NotImplementedError

    @classmethod
    def unpack(cls, v: Any) -> "Crdt":
        raise NotImplementedError


def merge_auto(a: T, b: T) -> T:
    """AutoCrdt: max-merge for totally ordered values (ref crdt.rs:43-58)."""
    return b if b > a else a


class Lww(Crdt, Generic[T]):
    """Last-writer-wins register (ref util/crdt/lww.rs).

    Timestamp is wall-clock ms; `update` bumps to max(now, ts+1) so a node
    always supersedes its own previous value (lww.rs:75-80).  Ties merge the
    payload if it is itself a CRDT, else take the larger value (lww.rs:41-44
    merges payloads on equal ts).
    """

    __slots__ = ("ts", "value")

    def __init__(self, value: T, ts: Optional[int] = None):
        self.ts = now_msec() if ts is None else ts
        self.value = value

    def update(self, value: T) -> None:
        self.ts = max(now_msec(), self.ts + 1)
        self.value = value

    def merge(self, other: "Lww[T]") -> None:
        if other.ts > self.ts:
            self.ts = other.ts
            self.value = other.value
        elif other.ts == self.ts and other.value != self.value:
            if isinstance(self.value, Crdt):
                self.value.merge(other.value)  # type: ignore[arg-type]
            elif other.value is not None and (
                self.value is None or _tie_break_gt(other.value, self.value)
            ):
                self.value = other.value

    def pack(self) -> Any:
        return [self.ts, self.value.pack() if isinstance(self.value, Crdt) else self.value]

    @classmethod
    def unpack(cls, v: Any, value_unpack: Optional[Callable[[Any], Any]] = None) -> "Lww":
        ts, val = v
        if value_unpack is not None:
            val = value_unpack(val)
        return cls(val, ts=ts)

    def __eq__(self, other: object) -> bool:
        return isinstance(other, Lww) and (self.ts, self.value) == (other.ts, other.value)

    def __repr__(self) -> str:  # pragma: no cover
        return f"Lww(ts={self.ts}, value={self.value!r})"


class LwwMap(Crdt, Generic[T]):
    """Map of independently-LWW values keyed by hashable keys
    (ref util/crdt/lww_map.rs; reference stores a sorted Vec, we use dict —
    iteration is sorted on demand)."""

    __slots__ = ("items",)

    def __init__(self, items: Optional[Dict[Any, Lww[T]]] = None):
        self.items: Dict[Any, Lww[T]] = items or {}

    def get(self, k: Any) -> Optional[T]:
        e = self.items.get(k)
        return e.value if e is not None else None

    def get_ts(self, k: Any) -> int:
        e = self.items.get(k)
        return e.ts if e is not None else 0

    def update(self, k: Any, v: T) -> None:
        e = self.items.get(k)
        if e is None:
            self.items[k] = Lww(v)
        else:
            e.update(v)

    def update_in_place(self, k: Any, v: T, ts: int) -> None:
        self.items[k] = Lww(v, ts=ts)

    def merge(self, other: "LwwMap[T]") -> None:
        for k, lww in other.items.items():
            mine = self.items.get(k)
            if mine is None:
                self.items[k] = Lww(lww.value, ts=lww.ts)
            else:
                mine.merge(lww)

    def sorted_items(self) -> List[Tuple[Any, Lww[T]]]:
        return sorted(self.items.items(), key=lambda kv: kv[0])

    def pack(self) -> Any:
        return [[k, e.pack()] for k, e in self.sorted_items()]

    @classmethod
    def unpack(cls, v: Any, value_unpack: Optional[Callable[[Any], Any]] = None) -> "LwwMap":
        return cls({k: Lww.unpack(e, value_unpack) for k, e in v})

    def __eq__(self, other: object) -> bool:
        return isinstance(other, LwwMap) and self.items == other.items

    def __len__(self) -> int:
        return len(self.items)


class CrdtMap(Crdt):
    """Map whose values are themselves CRDTs, merged pointwise
    (ref util/crdt/map.rs)."""

    __slots__ = ("items",)

    def __init__(self, items: Optional[Dict[Any, Crdt]] = None):
        self.items: Dict[Any, Crdt] = items or {}

    def put(self, k: Any, v: Crdt) -> None:
        mine = self.items.get(k)
        if mine is None:
            self.items[k] = v
        else:
            mine.merge(v)

    def merge(self, other: "CrdtMap") -> None:
        for k, v in other.items.items():
            self.put(k, v)

    def pack(self) -> Any:
        return [[k, e.pack()] for k, e in sorted(self.items.items(), key=lambda kv: kv[0])]

    def __eq__(self, other: object) -> bool:
        return isinstance(other, CrdtMap) and self.items == other.items


class CrdtBool(Crdt):
    """Or-merge boolean: once true, always true (ref util/crdt/bool.rs)."""

    __slots__ = ("value",)

    def __init__(self, value: bool = False):
        self.value = value

    def set(self) -> None:
        self.value = True

    def merge(self, other: "CrdtBool") -> None:
        self.value = self.value or other.value

    def pack(self) -> Any:
        return self.value

    @classmethod
    def unpack(cls, v: Any) -> "CrdtBool":
        return cls(bool(v))

    def __eq__(self, other: object) -> bool:
        return isinstance(other, CrdtBool) and self.value == other.value


class Deletable(Crdt, Generic[T]):
    """Present(value) | Deleted — deletion wins over any concurrent value
    (ref util/crdt/deletable.rs)."""

    __slots__ = ("value", "deleted")

    def __init__(self, value: Optional[T] = None, deleted: bool = False):
        self.value = value
        self.deleted = deleted

    @classmethod
    def present(cls, value: T) -> "Deletable[T]":
        return cls(value=value)

    @classmethod
    def delete(cls) -> "Deletable[T]":
        return cls(deleted=True)

    def is_deleted(self) -> bool:
        return self.deleted

    def get(self) -> Optional[T]:
        return None if self.deleted else self.value

    def merge(self, other: "Deletable[T]") -> None:
        if other.deleted:
            self.deleted, self.value = True, None
        elif not self.deleted:
            if isinstance(self.value, Crdt) and other.value is not None:
                self.value.merge(other.value)  # type: ignore[arg-type]
            elif other.value is not None and (
                self.value is None or _tie_break_gt(other.value, self.value)
            ):
                self.value = other.value

    def pack(self) -> Any:
        if self.deleted:
            return None
        return [self.value.pack() if isinstance(self.value, Crdt) else self.value]

    @classmethod
    def unpack(cls, v: Any, value_unpack: Optional[Callable[[Any], Any]] = None) -> "Deletable":
        if v is None:
            return cls.delete()
        val = v[0]
        if value_unpack is not None:
            val = value_unpack(val)
        return cls.present(val)

    def __eq__(self, other: object) -> bool:
        return (
            isinstance(other, Deletable)
            and (self.deleted, self.value) == (other.deleted, other.value)
        )
