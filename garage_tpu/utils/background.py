"""Background worker runtime.

Equivalent of reference src/util/background/: the `Worker` trait with
work/wait_for_work phases (background/worker.rs:41-59), the processor loop
with exponential error backoff 1.5^n capped at ~1h and a worker-info registry
(worker.rs:61-232), `BackgroundRunner` (background/mod.rs:16-60), and the
runtime-tunable `BgVars` (background/vars.rs:8-45).

TPU-native difference: the reference runs workers on a tokio runtime; here
workers are asyncio tasks.  Batch-producing workers (scrub/resync) additionally
talk to the codec's device executor, which runs JAX dispatch on a dedicated
thread so the event loop never blocks on TPU work.
"""

from __future__ import annotations

import asyncio
import enum
import logging
import time
from typing import Any, Callable, Dict, List, Optional

logger = logging.getLogger("garage_tpu.background")


class WorkerState(enum.Enum):
    # ref util/background/worker.rs:19-39
    BUSY = "busy"
    THROTTLED = "throttled"
    IDLE = "idle"
    DONE = "done"


class WorkerStatus:
    """Operator-visible worker state (ref worker.rs WorkerStatus/WorkerInfo)."""

    def __init__(self) -> None:
        self.state = WorkerState.IDLE
        self.iterations = 0
        self.errors = 0
        self.consecutive_errors = 0
        self.last_error: Optional[str] = None
        self.last_error_time: float = 0.0
        self.tranquility: Optional[int] = None
        self.progress: Optional[str] = None
        self.queue_length: Optional[int] = None
        self.persistent_errors: Optional[int] = None
        self.freeform: List[str] = []

    def to_dict(self) -> Dict[str, Any]:
        return {
            "state": self.state.value,
            "iterations": self.iterations,
            "errors": self.errors,
            "consecutive_errors": self.consecutive_errors,
            "last_error": self.last_error,
            "tranquility": self.tranquility,
            "progress": self.progress,
            "queue_length": self.queue_length,
            "persistent_errors": self.persistent_errors,
            "freeform": self.freeform,
        }


class LoopSafeEvent:
    """asyncio.Event whose set() is safe from ANY thread.

    asyncio.Event.set() from a foreign thread flips the flag but wakes
    nothing — a worker parked in wait_for_work() sleeps through the
    notification until something else stirs the loop.  That is exactly
    the idle gap the batched table paths expose: a Merkle/insert-queue
    refill committed from a worker thread (batched passes run under
    asyncio.to_thread) must wake the drainer NOW.  The waiting side
    captures its loop; set() routes through call_soon_threadsafe when
    called off-loop.  A set() before the first wait() only flips the
    flag, which wait() observes before sleeping — no wakeup is lost."""

    def __init__(self) -> None:
        self._ev = asyncio.Event()
        self._loop: Optional[asyncio.AbstractEventLoop] = None

    def clear(self) -> None:
        self._ev.clear()

    def is_set(self) -> bool:
        return self._ev.is_set()

    async def wait(self) -> bool:
        self._loop = asyncio.get_running_loop()
        return await self._ev.wait()

    def set(self) -> None:
        loop = self._loop
        if loop is not None and not loop.is_closed():
            try:
                running = asyncio.get_running_loop()
            except RuntimeError:
                running = None
            if running is not loop:
                try:
                    loop.call_soon_threadsafe(self._ev.set)
                    return
                except RuntimeError:
                    pass  # loop shut down between checks: flag-only set
        self._ev.set()


class Worker:
    """Subclass and implement `work` (one step, returns a WorkerState) and
    optionally `wait_for_work` (ref background/worker.rs:41-59)."""

    def name(self) -> str:
        return type(self).__name__

    def status(self) -> WorkerStatus:
        st = getattr(self, "_status", None)
        if st is None:
            st = self._status = WorkerStatus()
        return st

    async def work(self) -> WorkerState:
        raise NotImplementedError

    async def wait_for_work(self) -> None:
        """Called when work() returned IDLE; return when there may be work."""
        await asyncio.sleep(1.0)


# ref background/worker.rs:28-33: backoff base 1.5^n, 10 errors before warn
_ERROR_RETRY_BASE = 1.0
# 120 s, not the reference's ~1 h scale: a worker stuck at an hour-long
# retry cap cannot participate in self-healing after a transient outage
# (a dead peer during one queue sweep is enough to saturate the cap)
_ERROR_RETRY_MAX = 120.0


class BackgroundRunner:
    """Spawns and tracks workers (ref background/mod.rs:16-60 +
    worker.rs:61-175 WorkerProcessor)."""

    def __init__(self) -> None:
        self.workers: Dict[int, Worker] = {}
        self.tasks: Dict[int, asyncio.Task] = {}
        self._next_id = 0
        self.stopping = asyncio.Event()
        # load governor (utils/overload.py), set by the model layer:
        # when foreground pressure is high, BUSY workers are duty-cycled
        # (sleep proportional to their last slice) so resync/scrub/GC/
        # sync sweeps cede CPU and wire to client traffic, and resume
        # full rate when pressure clears.  None = never throttle.
        self.governor = None

    def spawn(self, worker: Worker) -> int:
        wid = self._next_id
        self._next_id += 1
        self.workers[wid] = worker
        self.tasks[wid] = asyncio.get_running_loop().create_task(
            self._run_worker(wid, worker), name=f"worker-{wid}-{worker.name()}"
        )
        return wid

    def reap(self, wid: int) -> bool:
        """Drop a COMPLETED worker's registry entries.  Recurring one-shot
        spawns (the automatic layout sweep respawns on every ring change)
        would otherwise accumulate dead workers/tasks for the daemon's
        lifetime.  Refuses while the task is still running."""
        t = self.tasks.get(wid)
        if t is not None and not t.done():
            return False
        self.tasks.pop(wid, None)
        self.workers.pop(wid, None)
        return True

    async def _run_worker(self, wid: int, worker: Worker) -> None:
        status = worker.status()
        while not self.stopping.is_set():
            try:
                t0 = time.monotonic()
                state = await worker.work()
                status.iterations += 1
                status.consecutive_errors = 0
                status.state = state
                if state == WorkerState.BUSY and self.governor is not None:
                    # dynamic background yielding: at throttle ratio r the
                    # worker runs r of the wall clock (sleep after each
                    # slice), so foreground overload visibly pushes
                    # background bytes/s down — and full rate resumes as
                    # soon as the governor's pressure clears
                    pause = self.governor.bg_pause(time.monotonic() - t0)
                    if pause > 0:
                        status.state = WorkerState.THROTTLED
                        await self._sleep_or_stop(pause)
            except asyncio.CancelledError:
                raise
            except Exception as e:
                status.errors += 1
                status.consecutive_errors += 1
                status.last_error = f"{type(e).__name__}: {e}"
                status.last_error_time = time.time()
                log = logger.warning if status.consecutive_errors >= 10 else logger.debug
                log("worker %s (%d) error: %s", worker.name(), wid, e, exc_info=True)
                # ref worker.rs:161-167: exponential backoff 1.5^n
                delay = min(
                    _ERROR_RETRY_BASE * (1.5 ** min(status.consecutive_errors, 20)),
                    _ERROR_RETRY_MAX,
                )
                await self._sleep_or_stop(delay)
                continue
            if state == WorkerState.DONE:
                logger.info("worker %s (%d) done", worker.name(), wid)
                return
            if state == WorkerState.IDLE:
                wait = asyncio.ensure_future(worker.wait_for_work())
                stop = asyncio.ensure_future(self.stopping.wait())
                done, pending = await asyncio.wait(
                    {wait, stop}, return_when=asyncio.FIRST_COMPLETED
                )
                for p in pending:
                    p.cancel()
                    await asyncio.gather(p, return_exceptions=True)
                for d in done:
                    # a raising wait_for_work must not hot-spin the loop
                    if d is wait and d.exception() is not None:
                        logger.warning(
                            "worker %s (%d) wait_for_work error: %s",
                            worker.name(), wid, d.exception(),
                        )
                        await self._sleep_or_stop(1.0)

    async def _sleep_or_stop(self, delay: float) -> None:
        try:
            await asyncio.wait_for(self.stopping.wait(), timeout=delay)
        except asyncio.TimeoutError:
            pass

    def worker_info(self) -> Dict[int, Dict[str, Any]]:
        """Registry for `garage worker list` (ref worker.rs:189-232)."""
        return {
            wid: {"name": w.name(), **w.status().to_dict()}
            for wid, w in self.workers.items()
        }

    def observe_gauges(self, registry) -> None:
        """Mirror every worker's status into labelled Prometheus gauges —
        called at scrape time by the admin /metrics handler (the same
        pattern as Table.observe_gauges).  Clear-then-set: a reaped
        worker's series must disappear, not freeze.

        One series per (id, name) pair; state is a 0/1 family over the
        four WorkerState values so `worker_state{state="busy"} == 1`
        selects busy workers without string-valued metrics."""
        g_state = registry.gauge(
            "worker_state", "1 for the worker's current state, 0 otherwise")
        g_iter = registry.gauge(
            "worker_iterations", "Completed work() iterations")
        g_err = registry.gauge("worker_errors", "Total worker errors")
        g_cerr = registry.gauge(
            "worker_consecutive_errors",
            "Consecutive errors (drives the retry backoff)")
        g_queue = registry.gauge(
            "worker_queue_length",
            "Backlog the worker is draining (todo/queue/backlog depth)")
        g_perr = registry.gauge(
            "worker_persistent_errors",
            "Entries parked in the worker's error/backoff set")
        for g in (g_state, g_iter, g_err, g_cerr, g_queue, g_perr):
            g.clear()
        for wid, w in self.workers.items():
            st = w.status()
            lbl = {"id": str(wid), "name": w.name()}
            for s in WorkerState:
                g_state.set(
                    1.0 if st.state == s else 0.0, state=s.value, **lbl)
            g_iter.set(float(st.iterations), **lbl)
            g_err.set(float(st.errors), **lbl)
            g_cerr.set(float(st.consecutive_errors), **lbl)
            if st.queue_length is not None:
                g_queue.set(float(st.queue_length), **lbl)
            if st.persistent_errors is not None:
                g_perr.set(float(st.persistent_errors), **lbl)

    async def shutdown(self, timeout: float = 8.0) -> None:
        """Signal stop; hard-cancel after deadline (ref worker.rs:100-113
        8s exit deadline)."""
        self.stopping.set()
        tasks = [t for t in self.tasks.values() if not t.done()]
        if tasks:
            done, pending = await asyncio.wait(tasks, timeout=timeout)
            for t in pending:
                t.cancel()
            await asyncio.gather(*pending, return_exceptions=True)


class BgVars:
    """Runtime-tunable named variables, settable from the CLI
    (ref util/background/vars.rs:8-45)."""

    def __init__(self) -> None:
        self._vars: Dict[str, tuple] = {}  # name -> (getter, setter|None)

    def register_rw(self, name: str, getter: Callable[[], Any], setter: Callable[[Any], None]) -> None:
        self._vars[name] = (getter, setter)

    def register_ro(self, name: str, getter: Callable[[], Any]) -> None:
        self._vars[name] = (getter, None)

    def get(self, name: str) -> Any:
        return self._vars[name][0]()

    def set(self, name: str, value: Any) -> None:
        g, s = self._vars[name]
        if s is None:
            raise KeyError(f"variable {name} is read-only")
        s(value)

    def all(self) -> Dict[str, Any]:
        return {k: g() for k, (g, _) in sorted(self._vars.items())}
