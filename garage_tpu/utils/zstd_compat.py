"""zstandard import shim — the real wheel when present, a gated fallback
when not.

Every compression call site imports `zstandard` through this module.
With the wheel installed the name IS the wheel (zero behavior change,
real zstd frames on the wire).  Without it — containers this repo grows
in do not always ship the wheel, and installing one is off-limits — a
minimal API-compatible fallback backed by zlib keeps the block layer
functional: `ZstdCompressor(level, write_checksum, write_content_size)`,
`ZstdDecompressor().decompress/.decompressobj()`, and `ZstdError` on
corrupt frames (zlib's adler32 trailer provides the checksum-verify
property block.rs:66-78 relies on).

Fallback frames carry a private magic and are only readable by the same
fallback — NOT zstd on the wire.  That is acceptable because a cluster
without the wheel is a test/dev cluster; a mixed wheel/fallback cluster
should run with compression_level = None.
"""

from __future__ import annotations

try:
    import zstandard  # noqa: F401  (the real wheel: re-exported as-is)

    HAVE_ZSTD = True
except ImportError:
    import types
    import zlib

    HAVE_ZSTD = False
    _MAGIC = b"GTZF"

    class ZstdError(Exception):
        pass

    class _Compressor:
        def __init__(self, level: int = 1, write_checksum: bool = True,
                     write_content_size: bool = True, **_kw):
            # zlib levels top out at 9; zstd levels can be higher
            self._level = max(1, min(int(level), 9))

        def compress(self, data: bytes) -> bytes:
            return _MAGIC + zlib.compress(data, self._level)

    class _DecompressObj:
        """Incremental decompressor matching the zstandard
        decompressobj() surface the streaming read path uses."""

        def __init__(self):
            self._hdr = b""
            self._z = zlib.decompressobj()

        def decompress(self, chunk: bytes) -> bytes:
            if len(self._hdr) < len(_MAGIC):
                need = len(_MAGIC) - len(self._hdr)
                self._hdr += bytes(chunk[:need])
                chunk = chunk[need:]
                if len(self._hdr) == len(_MAGIC) and self._hdr != _MAGIC:
                    raise ZstdError("bad fallback frame magic")
                if not chunk:
                    return b""
            try:
                return self._z.decompress(chunk)
            except zlib.error as e:
                raise ZstdError(str(e)) from None

    class _Decompressor:
        def decompress(self, data: bytes) -> bytes:
            if data[: len(_MAGIC)] != _MAGIC:
                raise ZstdError("bad fallback frame magic")
            try:
                return zlib.decompress(data[len(_MAGIC):])
            except zlib.error as e:
                raise ZstdError(str(e)) from None

        def decompressobj(self) -> _DecompressObj:
            return _DecompressObj()

    zstandard = types.SimpleNamespace(
        ZstdError=ZstdError,
        ZstdCompressor=_Compressor,
        ZstdDecompressor=_Decompressor,
        __fallback__=True,
    )
