"""Fail-slow peer detection — comparative per-peer health scoring.

A node that is *down* is easy: pings fail, the circuit breaker opens,
request_order sorts it last.  A node that is *up but slow* — NIC
negotiated 100 Mb/s, a dying disk dragging every RPC handler, one VM on
a noisy host — passes every liveness check while silently dragging each
quorum it sits in (the "gray failure" / fail-slow literature; the
degraded-reads paper's least-loaded-survivor scheduling is the same
observation from the repair side).  Nothing absolute can catch it: its
latency may be perfectly "normal" for a WAN peer.  What gives it away
is COMPARISON — the same endpoint class, served by its siblings, is a
factor cheaper.

This module holds the pure scorer:

  - every completed RPC feeds a per-(peer, endpoint-class) service-time
    EWMA digest (``note``) — the plumbing is the RpcHelper call path
    that already times ``rpc_duration_seconds``, plus the peering ping
    loop that feeds ``peer_rtt_ewma_seconds`` (class ``ping``);
  - a peer's **health score** is the worst ratio, across classes, of
    its digest to the *lower median* of the OTHER peers' digests for
    the same class (lower median biases toward flagging when half the
    comparison set is itself sick, and a peer judged against only its
    own traffic can never be flagged);
  - a peer is flagged **fail-slow** when its score sits at or above
    ``fail_slow_factor`` continuously for ``window_s`` seconds, and
    unflagged when it sits at or below ``clear_factor`` for the same
    window (hysteresis: the band between the two factors changes
    nothing, so a peer oscillating around the threshold does not flap).

Consumers: the score and flag ride ``NodeStatus`` gossip
(rpc/system.py), render as ``peer_health_score{peer}`` /
``peer_fail_slow{peer}``, demote flagged peers in
``RpcHelper.peer_rank`` (after breaker-open, before RTT), and feed
``RepairPlanner`` survivor ranking.  Flag transitions trigger the
incident flight recorder (utils/flightrec.py).

Deliberately dependency-free with an injectable clock, like the
CircuitBreaker next door in net/resilience.py: every transition
unit-tests without sleeping.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Tuple

__all__ = ["HealthTunables", "FailSlowScorer"]

# EWMA step per observation; ~10 samples to cross most of a step change,
# small enough that one straggling call does not flag a healthy peer
EWMA_ALPHA = 0.2

# medians below this floor (seconds) are clamped before the ratio: at
# loopback microsecond medians, scheduler jitter alone is a 10x "blowup"
MEDIAN_FLOOR_S = 1e-4


@dataclass
class HealthTunables:
    """``[health]`` — fail-slow detection knobs
    (docs/OBSERVABILITY.md "Fleet health & SLOs")."""

    # score at/above this, sustained for window_s, flags the peer
    fail_slow_factor: float = 3.0
    # score at/below this, sustained for window_s, clears the flag
    # (the band between the factors is hysteresis: no transitions)
    clear_factor: float = 1.5
    # how long a verdict must hold continuously before it takes effect
    window_s: float = 30.0
    # per-(peer, class) observations needed before the digest is judged
    min_samples: int = 8
    # OTHER peers with a judgeable digest in the same class needed
    # before a comparison is trusted (1 = compare against a single
    # sibling — small clusters; raise it where one bad baseline peer
    # could mis-flag a healthy one)
    min_baseline_peers: int = 1
    # digests idle longer than this drop out of the comparison set (a
    # peer we stopped calling must neither flag nor anchor the median)
    sample_ttl_s: float = 300.0


class _Digest:
    """Per-(peer, class) service-time EWMA."""

    __slots__ = ("ewma", "count", "last_at")

    def __init__(self):
        self.ewma = 0.0
        self.count = 0
        self.last_at = 0.0

    def note(self, seconds: float, now: float) -> None:
        self.count += 1
        self.last_at = now
        if self.count == 1:
            self.ewma = seconds
        else:
            self.ewma += EWMA_ALPHA * (seconds - self.ewma)


class _PeerVerdict:
    """Flag state machine for one peer (sustained-window hysteresis)."""

    __slots__ = ("score", "flagged", "above_since", "below_since",
                 "flagged_at")

    def __init__(self):
        self.score: Optional[float] = None
        self.flagged = False
        self.above_since: Optional[float] = None
        self.below_since: Optional[float] = None
        self.flagged_at: Optional[float] = None


def _lower_median(vals: List[float]) -> float:
    """Median biased LOW on even counts: when half the comparison set is
    itself slow, the baseline stays anchored to the healthy half."""
    s = sorted(vals)
    return s[(len(s) - 1) // 2]


class FailSlowScorer:
    """Comparative fail-slow scorer for one node's view of its peers.

    ``note`` is called from the RPC hot path (dict lookup + float math);
    the flag evaluation (``update``) runs on the status-gossip cadence
    and on demand from the metric observers.  ``on_change(peer_hex,
    flagged, score)`` fires on every flag transition — the incident
    flight recorder's trigger."""

    def __init__(self, tun: Optional[HealthTunables] = None,
                 clock: Callable[[], float] = time.monotonic,
                 on_change: Optional[Callable[[str, bool, float], None]] = None):
        self.tun = tun or HealthTunables()
        self.clock = clock
        self.on_change = on_change
        # zone lookup (peer_bytes -> zone name or None), wired by the
        # owning System from the cluster layout.  On a geo-distributed
        # cluster a healthy peer two WAN hops away is a FACTOR slower
        # than a loopback sibling on every class — that is distance, not
        # gray failure.  When the peer's zone holds enough baseline
        # peers, comparison is restricted to same-zone siblings (who pay
        # the same RTTs); otherwise it falls back to the full set.
        self.zone_of: Optional[Callable[[bytes], Optional[str]]] = None
        # (peer_bytes, class) -> digest
        self._digests: Dict[Tuple[bytes, str], _Digest] = {}
        self._verdicts: Dict[bytes, _PeerVerdict] = {}
        self.transitions = 0  # lifetime flag flips (debug/metrics)
        # the scorer is read from flight-recorder capture threads (the
        # metrics collector renders the health gauges off-loop) while
        # the event loop keeps feeding note()/update(): every state
        # mutation or multi-item read holds this.  Reentrant because
        # update() -> on_change -> (an inline, loop-less capture) can
        # come back through scores() on the same thread
        self._lock = threading.RLock()

    @staticmethod
    def _label(peer: bytes) -> str:
        return bytes(peer).hex()[:16]

    # --- ingest ----------------------------------------------------------

    def note(self, peer: bytes, cls: str, seconds: float) -> None:
        """One completed call to `peer` on endpoint-class `cls`."""
        peer = bytes(peer)
        key = (peer, cls)
        with self._lock:
            d = self._digests.get(key)
            if d is None:
                d = self._digests[key] = _Digest()
            d.note(float(seconds), self.clock())

    def forget(self, peer: bytes) -> None:
        """Drop a peer removed from the layout (same contract as
        peering.forget_peer: a re-added node inherits no history)."""
        peer = bytes(peer)
        with self._lock:
            for key in [k for k in self._digests if k[0] == peer]:
                del self._digests[key]
            self._verdicts.pop(peer, None)

    # --- scoring ---------------------------------------------------------

    def _fresh_digests(self, now: float) -> Dict[Tuple[bytes, str], _Digest]:
        ttl = self.tun.sample_ttl_s
        gone = [k for k, d in self._digests.items()
                if now - d.last_at > ttl]
        for k in gone:
            del self._digests[k]
        return self._digests

    def score(self, peer: bytes) -> Optional[float]:
        """The peer's current comparative score (worst class ratio), or
        None when no class has enough data to judge."""
        with self._lock:
            return self._score(bytes(peer),
                               self._fresh_digests(self.clock()))

    def _score(self, peer: bytes,
               digests: Dict[Tuple[bytes, str], _Digest]) -> Optional[float]:
        tun = self.tun
        worst: Optional[float] = None
        by_class: Dict[str, List[Tuple[bytes, float]]] = {}
        for (p, cls), d in digests.items():
            if d.count >= tun.min_samples:
                by_class.setdefault(cls, []).append((p, d.ewma))
        zone = self.zone_of(peer) if self.zone_of is not None else None
        for cls, rows in by_class.items():
            mine = next((e for p, e in rows if p == peer), None)
            if mine is None:
                continue
            others = [e for p, e in rows if p != peer]
            if zone is not None:
                # prefer same-zone siblings as the baseline: a WAN-far
                # zone must not look fail-slow against loopback peers
                same_zone = [e for p, e in rows
                             if p != peer and self.zone_of(p) == zone]
                if len(same_zone) >= tun.min_baseline_peers:
                    others = same_zone
            if len(others) < tun.min_baseline_peers:
                continue
            ratio = mine / max(_lower_median(others), MEDIAN_FLOOR_S)
            if worst is None or ratio > worst:
                worst = ratio
        return worst

    # --- verdicts (sustained-window hysteresis) --------------------------

    def update(self, now: Optional[float] = None) -> None:
        """Re-evaluate every peer's flag.  Called on the status-exchange
        cadence (rpc/system.py) and before any scores() read."""
        now = self.clock() if now is None else now
        with self._lock:
            self._update_locked(now)

    def _update_locked(self, now: float) -> None:
        digests = self._fresh_digests(now)
        peers = {p for p, _cls in digests}
        tun = self.tun
        for peer in peers:
            v = self._verdicts.get(peer)
            if v is None:
                v = self._verdicts[peer] = _PeerVerdict()
            v.score = self._score(peer, digests)
            if v.score is None:
                # not judgeable: clear timers, keep the current flag
                # (a flagged peer we stopped calling ages out via the
                # digest TTL sweep below, not via an absent verdict)
                v.above_since = v.below_since = None
                continue
            if v.score >= tun.fail_slow_factor:
                v.below_since = None
                if v.above_since is None:
                    v.above_since = now
                if not v.flagged and now - v.above_since >= tun.window_s:
                    v.flagged = True
                    v.flagged_at = now
                    self.transitions += 1
                    self._emit(peer, True, v.score)
            elif v.score <= tun.clear_factor:
                v.above_since = None
                if v.below_since is None:
                    v.below_since = now
                if v.flagged and now - v.below_since >= tun.window_s:
                    v.flagged = False
                    v.flagged_at = None
                    self.transitions += 1
                    self._emit(peer, False, v.score)
            else:
                # hysteresis band: neither timer runs
                v.above_since = v.below_since = None
        # peers whose every digest aged out: clear a stale flag (the
        # peer is not being called at all — unreachable is the
        # breaker's business, not ours)
        for peer in [p for p in self._verdicts if p not in peers]:
            v = self._verdicts.pop(peer)
            if v.flagged:
                self.transitions += 1
                self._emit(peer, False, v.score or 0.0)

    def _emit(self, peer: bytes, flagged: bool, score: float) -> None:
        if self.on_change is None:
            return
        try:
            self.on_change(self._label(peer), flagged, round(score, 3))
        except Exception:  # noqa: BLE001 — observers must never break scoring
            pass

    # --- read side -------------------------------------------------------

    def fail_slow(self, peer: bytes) -> bool:
        v = self._verdicts.get(bytes(peer))
        return bool(v is not None and v.flagged)

    def scores(self, update: bool = True) -> Dict[str, dict]:
        """{peer_hex16: {"score": float, "fail_slow": bool}} for every
        currently judgeable peer — the gossip payload and the metric
        observer's source."""
        with self._lock:
            if update:
                self.update()
            out: Dict[str, dict] = {}
            for peer, v in self._verdicts.items():
                if v.score is None and not v.flagged:
                    continue
                out[self._label(peer)] = {
                    "score": (round(v.score, 3)
                              if v.score is not None else None),
                    "fail_slow": v.flagged,
                }
            return out
