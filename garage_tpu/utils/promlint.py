"""Strict Prometheus text-exposition lint.

The registry in utils/metrics.py IS this project's exporter — there is no
client library between the metric objects and the scrape body, so a
rendering bug (duplicate `# TYPE` from a name collision, an unescaped
label value, a histogram whose cumulative counts go backwards) ships
straight to Prometheus, which rejects the whole scrape at ingest time and
takes every metric on the node dark at once.  This lint runs as a unit
test against a fully-populated registry and inside the smoke script
against a live node's /metrics body.

Checks (a strict subset of the text-exposition format Prometheus
actually enforces, plus this repo's own rendering conventions):

  - metric and label names match the Prometheus grammar
  - no duplicate `# TYPE`/`# HELP` for a family; TYPE precedes samples
  - samples belong to their declared family (histograms may only append
    `_bucket`/`_sum`/`_count`)
  - no duplicate sample (same name + same label set)
  - label values use only the three legal escapes (\\\\, \\", \\n) and
    contain no raw newline/quote
  - non-`le` labels are emitted in sorted order and `le` comes last on
    `_bucket` lines (what metrics.py renders; a violation means a bypass
    of the registry)
  - histogram `le` values are strictly increasing, cumulative bucket
    values are non-decreasing, the `+Inf` bucket exists and equals
    `_count` for the same label set
  - sample values parse as floats
"""

from __future__ import annotations

import math
import re
from typing import Dict, List, Optional, Tuple

_METRIC_NAME = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_NAME = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")
_SAMPLE = re.compile(
    r"^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)"
    r"(?:\{(?P<labels>.*)\})?"
    r" (?P<value>\S+)(?: (?P<ts>-?\d+))?$"
)
_TYPES = {"counter", "gauge", "histogram", "summary", "untyped"}


def _parse_labels(raw: str) -> Optional[List[Tuple[str, str]]]:
    """Parse the inside of {...}; None on malformed input.  Hand-rolled
    scanner because escapes make a regex split unsound."""
    out: List[Tuple[str, str]] = []
    i, n = 0, len(raw)
    while i < n:
        j = raw.find("=", i)
        if j < 0:
            return None
        name = raw[i:j]
        if j + 1 >= n or raw[j + 1] != '"':
            return None
        i = j + 2
        val = []
        while i < n:
            c = raw[i]
            if c == "\\":
                if i + 1 >= n or raw[i + 1] not in ('\\', '"', 'n'):
                    return None  # illegal escape
                val.append(raw[i:i + 2])
                i += 2
                continue
            if c == '"':
                break
            if c == "\n":
                return None
            val.append(c)
            i += 1
        else:
            return None  # unterminated value
        out.append((name, "".join(val)))
        i += 1  # past closing quote
        if i < n:
            if raw[i] != ",":
                return None
            i += 1
    return out


def _unescape(v: str) -> str:
    return (v.replace("\\\\", "\x00").replace('\\"', '"')
            .replace("\\n", "\n").replace("\x00", "\\"))


def _family_of(name: str, types: Dict[str, str]) -> Optional[str]:
    if name in types:
        return name
    for suffix in ("_bucket", "_sum", "_count"):
        base = name[: -len(suffix)] if name.endswith(suffix) else None
        if base and types.get(base) in ("histogram", "summary"):
            return base
    return None


def lint_exposition(text: str) -> List[str]:
    """→ list of human-readable violations (empty = clean)."""
    errors: List[str] = []
    types: Dict[str, str] = {}
    helps: set = set()
    seen_samples: set = set()
    # (family, non-le label tuple) -> [(le_float, cum_value)]
    buckets: Dict[tuple, List[Tuple[float, float]]] = {}
    counts: Dict[tuple, float] = {}

    if text and not text.endswith("\n"):
        errors.append("exposition does not end with a newline")

    for ln, line in enumerate(text.splitlines(), 1):
        if not line:
            continue
        if line.startswith("# TYPE "):
            parts = line.split(" ", 3)
            if len(parts) != 4 or parts[3] not in _TYPES:
                errors.append(f"line {ln}: malformed TYPE line: {line!r}")
                continue
            fam = parts[2]
            if fam in types:
                errors.append(f"line {ln}: duplicate # TYPE for {fam!r}")
            if not _METRIC_NAME.match(fam):
                errors.append(f"line {ln}: invalid family name {fam!r}")
            types[fam] = parts[3]
            continue
        if line.startswith("# HELP "):
            parts = line.split(" ", 3)
            fam = parts[2] if len(parts) >= 3 else ""
            if fam in helps:
                errors.append(f"line {ln}: duplicate # HELP for {fam!r}")
            helps.add(fam)
            continue
        if line.startswith("#"):
            continue  # free comment

        m = _SAMPLE.match(line)
        if m is None:
            errors.append(f"line {ln}: unparsable sample: {line!r}")
            continue
        name = m.group("name")
        try:
            value = float(m.group("value"))
        except ValueError:
            errors.append(f"line {ln}: non-numeric value on {name}")
            continue
        fam = _family_of(name, types)
        if fam is None:
            errors.append(
                f"line {ln}: sample {name!r} has no preceding # TYPE")
            continue

        raw_labels = m.group("labels")
        labels = _parse_labels(raw_labels) if raw_labels is not None else []
        if labels is None:
            errors.append(f"line {ln}: malformed/ill-escaped labels on {name}")
            continue
        for k, _v in labels:
            if not _LABEL_NAME.match(k):
                errors.append(f"line {ln}: invalid label name {k!r} on {name}")
        non_le = [k for k, _ in labels if k != "le"]
        if non_le != sorted(non_le):
            errors.append(
                f"line {ln}: labels not sorted on {name}: {non_le}")
        if any(k == "le" for k, _ in labels) and labels[-1][0] != "le":
            errors.append(f"line {ln}: 'le' is not the last label on {name}")

        key = (name, tuple(labels))
        if key in seen_samples:
            errors.append(
                f"line {ln}: duplicate sample {name}{{{raw_labels or ''}}}")
        seen_samples.add(key)

        if types.get(fam) == "histogram":
            base_labels = tuple((k, v) for k, v in labels if k != "le")
            if name == fam + "_bucket":
                le_raw = dict(labels).get("le")
                if le_raw is None:
                    errors.append(f"line {ln}: _bucket without le on {name}")
                    continue
                le = (math.inf if _unescape(le_raw) == "+Inf"
                      else float(_unescape(le_raw)))
                buckets.setdefault((fam, base_labels), []).append((le, value))
            elif name == fam + "_count":
                counts[(fam, base_labels)] = value

    for (fam, base), series in buckets.items():
        lbl = "{" + ",".join(f'{k}="{v}"' for k, v in base) + "}"
        les = [le for le, _ in series]
        if les != sorted(les) or len(set(les)) != len(les):
            errors.append(f"{fam}{lbl}: le values not strictly increasing")
        vals = [v for _, v in series]
        if any(b < a for a, b in zip(vals, vals[1:])):
            errors.append(f"{fam}{lbl}: cumulative bucket counts decrease")
        if not les or les[-1] != math.inf:
            errors.append(f"{fam}{lbl}: missing +Inf bucket")
        elif (fam, base) in counts and vals[-1] != counts[(fam, base)]:
            errors.append(f"{fam}{lbl}: +Inf bucket != _count")

    return errors
