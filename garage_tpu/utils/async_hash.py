"""Off-thread hashing for streaming request bodies.

Equivalent of reference src/util/async_hash.rs: one-shot helpers that
push a CPU-heavy hash onto a worker thread (async_blake2sum /
async_sha256sum, async_hash.rs:12-25), and a streaming `AsyncHasher`
fed chunk by chunk through a bounded channel whose consumer runs on a
dedicated thread (async_hash.rs:29-56).

Why it matters even on one core: hashlib releases the GIL for large
buffers, but calling `update()` inline still parks the *event loop
thread* for milliseconds per block — every other request on the node
stalls behind it.  Off-thread, the loop keeps multiplexing while the
hash runs.  The bounded feed queue (capacity 1, like the reference's
mpsc::channel(1)) backpressures the producer so a slow hasher can't
buffer the whole body in RAM.

Usage note: the S3 put path intentionally does NOT use AsyncHasher —
measured on this host, the dedicated thread pair costs ~2 ms per
request in spawns, so `read_and_put_blocks` advances md5+sha256+content
hash in one combined `asyncio.to_thread` hop per block (first block
inline: single-block objects are the p50 latency case).  AsyncHasher
remains for callers that need true streaming overlap of several
digests over one pass.
"""

from __future__ import annotations

import asyncio
import queue
import threading
from typing import Optional

from .data import BLOCK_HASH_ALGOS, Hash


async def async_block_hash(data: bytes, algo: str = "blake2s") -> Hash:
    """One-shot block hash on a worker thread (ref async_hash.rs:21-25)."""
    return await asyncio.to_thread(BLOCK_HASH_ALGOS[algo], data)


class AsyncHasher:
    """Streaming hasher whose digest state advances on its own thread.

    usage:
        h = AsyncHasher(hashlib.md5())
        try:
            await h.update(chunk)          # backpressured hand-off
            digest = await h.hexdigest()   # joins the thread
        finally:
            await h.aclose()               # no-op if finalized; releases
                                           # the thread on error paths

    The worker thread starts LAZILY on the first large update: small
    bodies (inline objects) hash directly on the caller — sub-threshold
    updates cost less inline than a thread hand-off would.
    """

    # below this, updating inline is cheaper than the thread hand-off
    INLINE_THRESHOLD = 128 * 1024

    def __init__(self, hasher, feed_capacity: int = 1):
        self._h = hasher
        self._capacity = feed_capacity
        self._q: Optional[queue.Queue] = None
        self._thread: Optional[threading.Thread] = None
        self._finished = False

    def _run(self) -> None:
        from .cpuprof import register_thread, unregister_thread
        register_thread("merkle")
        try:
            while True:
                blk = self._q.get()
                if blk is None:
                    return
                self._h.update(blk)
        finally:
            unregister_thread()

    async def update(self, data: bytes) -> None:
        if self._finished:
            raise RuntimeError("AsyncHasher already finalized")
        if self._thread is None:
            if len(data) < self.INLINE_THRESHOLD:
                self._h.update(data)
                return
            self._q = queue.Queue(maxsize=self._capacity)
            self._thread = threading.Thread(
                target=self._run, name="async-hasher", daemon=True
            )
            self._thread.start()
        # q.put blocks when the hasher lags → run it off-loop so the event
        # loop never parks; that block IS the backpressure
        await asyncio.to_thread(self._q.put, data)

    async def _finalize(self) -> None:
        if not self._finished:
            self._finished = True
            if self._thread is not None:
                await asyncio.to_thread(self._q.put, None)
                await asyncio.to_thread(self._thread.join)
                self._thread = None

    async def aclose(self) -> None:
        """Release the worker thread; safe to call any time, including
        after digest().  MUST run on error paths or every aborted request
        leaks a thread parked on the feed queue."""
        await self._finalize()

    async def digest(self) -> bytes:
        await self._finalize()
        return self._h.digest()

    async def hexdigest(self) -> str:
        await self._finalize()
        return self._h.hexdigest()
