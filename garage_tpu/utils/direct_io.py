"""O_DIRECT bulk file reads — the scrub/staging read path.

Why.  On this class of host (virtio disk, 1 CPU core) a BUFFERED
sequential read is kernel-CPU-bound, not device-bound: measured 0.2-0.3
GiB/s at ~92% CPU (the page-cache copy burns the core), while O_DIRECT
reads the same files at 2.9 GiB/s at ~7% CPU.  For the scrub pipeline
the difference is structural — with buffered reads, read and verify
cannot overlap on one core and sustained throughput collapses to the
harmonic mean of disk and codec (BENCH_r04's 0.24 GiB/s); with O_DIRECT
the core belongs to the codec and sustained approaches the codec rate.

Bypassing the page cache is also the RIGHT semantic for scrub: a scrub
pass touches every block exactly once and must not evict the working
set the GET path depends on.  The reference's scrub reads buffered
(ref src/block/repair.rs:438-490 via block.rs read); this is a
deliberate improvement, not a parity item.

Alignment contract: O_DIRECT requires sector-aligned offsets, lengths
and destination buffers.  The destination is an anonymous mmap
(page-aligned — satisfies any sector size) sized to the file rounded up
to a page, read with ONE preadv per file from offset 0 (the kernel
splits internally; the short read at EOF may return an unaligned COUNT,
which POSIX/ext4 allow).  Data is copied out of the mmap exactly once.
Any OSError — O_DIRECT unsupported (tmpfs/overlay), mid-file EINVAL —
falls back to a buffered read of the remainder, so this is never less
available than open()/read().
"""

from __future__ import annotations

import mmap
import os
import threading
from typing import List, Optional, Tuple

_PAGE = 4096
_CHUNK = 32 << 20  # preadv request size: one huge request measured ~40%
                   # slower on first touch; ≥4 MiB requests are equal

# Per-thread destination buffer, grown geometrically and REUSED: the
# first O_DIRECT read into fresh anonymous pages pays ~65k page-pin
# faults per 256 MiB (~40% of the read time); warm pages make every
# subsequent read run at device speed.  Scrub worker threads read
# block-sized files repeatedly, so the cache converges immediately.
_local = threading.local()


def _dest(cap: int) -> mmap.mmap:
    buf = getattr(_local, "buf", None)
    if buf is None or len(buf) < cap:
        grow = max(cap, 2 * len(buf) if buf is not None else cap)
        buf = mmap.mmap(-1, grow)
        _local.buf = buf
    return buf


def _read_direct_raw(path: str) -> Optional[Tuple[memoryview, int]]:
    """(view of a page-aligned per-thread buffer, valid byte count) via
    O_DIRECT, or None if the open wants the buffered fallback.  The
    view is only valid until this THREAD's next _read_direct_raw call —
    callers copy out (once) before returning."""
    flags = os.O_RDONLY | getattr(os, "O_DIRECT", 0)
    try:
        fd = os.open(path, flags)
    except OSError:
        return None
    try:
        size = os.fstat(fd).st_size
        cap = max((size + _PAGE - 1) & ~(_PAGE - 1), _PAGE)
        dest = _dest(cap)
        mv = memoryview(dest)
        off = 0
        while off < size:
            try:
                n = os.preadv(fd, [mv[off:min(off + _CHUNK, cap)]], off)
            except OSError:
                # mid-file refusal: finish buffered into the same dest
                rest = _read_buffered_from(path, off, size - off)
                mv[off:off + len(rest)] = rest
                off += len(rest)
                break
            if n <= 0:
                break
            off += n
        return mv, off
    finally:
        os.close(fd)


def _read_buffered_from(path: str, offset: int, length: int) -> bytes:
    with open(path, "rb") as f:
        f.seek(offset)
        return f.read(length)


def read_file_direct(path: str) -> bytes:
    """Whole-file read via O_DIRECT with buffered fallback; exactly one
    copy out of the aligned buffer."""
    raw = _read_direct_raw(path)
    if raw is None:
        with open(path, "rb") as f:
            return f.read()
    mv, n = raw
    return bytes(mv[:n])


def read_file_direct_blocks(path: str, block_size: int) -> List[bytes]:
    """Read a file and split it into block_size pieces with one copy per
    block and NO intermediate whole-file bytes — the bulk-bench/staging
    shape (the production block store keeps one FILE per block and uses
    read_file_direct)."""
    raw = _read_direct_raw(path)
    if raw is None:
        with open(path, "rb") as f:
            data = f.read()
        return [data[i:i + block_size]
                for i in range(0, len(data), block_size)]
    mv, n = raw
    return [bytes(mv[i:min(i + block_size, n)])
            for i in range(0, n, block_size)]


def write_file_direct(path: str, data: bytes, fsync: bool = False) -> None:
    """Write a file via O_DIRECT for the sector-aligned prefix and a
    buffered write for the tail; falls back to a plain buffered write
    when O_DIRECT is unavailable.

    Why for block WRITES (the PutObject hot path): a buffered 1 MiB
    write costs ~0.4 ms of pure CPU in the page-cache copy and degrades
    to 7-8 ms under dirty-page throttling when puts are sustained,
    while the O_DIRECT write costs ~0.1 ms CPU with the transfer in
    kernel DMA (GIL released) — on a 1-core host, concurrent puts then
    overlap their writes instead of serializing on the copy.  It is
    also durability-positive: the aligned bulk is on media when the
    call returns, where the reference's data_fsync=false default leaves
    the whole block in cache (ref src/block/manager.rs:689-784).
    """
    n = len(data)
    aligned = n & ~(_PAGE - 1)
    flags = (os.O_WRONLY | os.O_CREAT | os.O_TRUNC
             | getattr(os, "O_DIRECT", 0))
    fd = -1
    if aligned:
        try:
            fd = os.open(path, flags, 0o644)
        except OSError:
            fd = -1
    if fd >= 0:
        try:
            buf = _dest(aligned)
            mv = memoryview(buf)
            mv[:aligned] = data[:aligned]
            off = 0
            while off < aligned:
                w = os.pwritev(
                    fd, [mv[off:min(off + _CHUNK, aligned)]], off)
                if w <= 0:
                    raise OSError("short O_DIRECT write")
                off += w
        except OSError:
            os.close(fd)
            fd = -1  # fall through to the fully-buffered path
        else:
            os.close(fd)
            if aligned < n or fsync:
                with open(path, "r+b") as f:
                    f.seek(aligned)
                    f.write(data[aligned:])
                    if fsync:
                        f.flush()
                        os.fsync(f.fileno())
            return
    with open(path, "wb") as f:
        f.write(data)
        if fsync:
            f.flush()
            os.fsync(f.fileno())


def try_read_direct(path: str) -> Optional[bytes]:
    """read_file_direct with the scrub worker's error contract: a
    vanished/unreadable file is None, not an exception."""
    try:
        return read_file_direct(path)
    except OSError:
        return None
