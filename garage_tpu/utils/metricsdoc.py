"""Metrics-docs lint: every family on /metrics must have a doc row.

The registry is this project's exporter and docs/OBSERVABILITY.md is
its contract with operators — a family that ships without a row there
is a dashboard nobody can read and a playbook nobody can follow.  This
check closes the loop the same way promlint does for the exposition
format: it runs as a unit test against a fully-populated node's render
and against every live node's /metrics in scripts/test_smoke.sh, so an
undocumented family fails CI the day it is introduced.

A family is "documented" when its exact name appears anywhere in the
doc as a backticked token (the convention every metrics table already
follows).  Genuinely internal families go on the explicit allowlist
below — with a reason — instead of silently rotting undocumented.
"""

from __future__ import annotations

import re
from typing import Iterable, List, Set

# Families deliberately kept out of the operator doc.  Keep this SHORT
# and reasoned: the default for a new family is a doc row, not a listing
# here.
ALLOWLIST: Set[str] = {
    # per-test scratch families some suites register on throwaway
    # registries; never rendered by a daemon
    "test_metric",
}

_TYPE_LINE = re.compile(r"^# TYPE ([a-zA-Z_:][a-zA-Z0-9_:]*) ")
_TICKED = re.compile(r"`([a-zA-Z_:][a-zA-Z0-9_:]*)`")


def families_in_exposition(text: str) -> Set[str]:
    """Family names declared by `# TYPE` lines in a scrape body."""
    out = set()
    for line in text.splitlines():
        m = _TYPE_LINE.match(line)
        if m:
            out.add(m.group(1))
    return out


def documented_families(doc_text: str) -> Set[str]:
    """Every backticked identifier in the doc — superset of the family
    names, which is exactly what we need for membership tests."""
    return set(_TICKED.findall(doc_text))


def undocumented_families(exposition: str, doc_text: str,
                          allow: Iterable[str] = ()) -> List[str]:
    """Families present on /metrics but absent from the doc (and not
    allowlisted) — empty means the contract holds."""
    fams = families_in_exposition(exposition)
    doc = documented_families(doc_text)
    extra = set(allow) | ALLOWLIST
    return sorted(f for f in fams if f not in doc and f not in extra)
