"""Overload protection — admission tunables + the background load governor.

The cluster has well-defined behavior up to saturation (quorums, retries,
breakers, disk routing) but historically none PAST it: the S3 front door
accepted unbounded concurrent work and background producers (resync,
rebalance, scrub, repair storms) competed with foreground PUT/GET at
static wire priorities only.  The metastable-failure literature (see
PAPERS.md discussion in docs/ROBUSTNESS.md "Overload & brownout") says
the difference between a latency blip and a cluster-wide collapse is
shedding doomed work EARLY and letting background load cede capacity
while the foreground is hot.  This module holds the two pure pieces:

  - ``OverloadTunables`` — the ``[api]`` config section: admission-gate
    watermarks (max in-flight requests / body bytes) and the governor's
    thresholds.  Dependency-free so net/, api/ and block/ can all import
    it.
  - ``LoadGovernor`` — one per node.  It aggregates live pressure
    signals the node already produces (admission-gate occupancy, codec
    feeder depth, the netapp queue-wait EWMA, disk health) into a single
    smoothed ``background_throttle_ratio`` in [min_ratio, 1.0]:
    1.0 = background runs at full rate, min_ratio = background nearly
    parked.  Consumers: BackgroundRunner (duty-cycles worker
    iterations), RebalanceMover (scales rebalance_rate_mib), and the
    RepairPlanner (clamps repair-storm fetch concurrency).

The governor is deliberately memory-light: pressure is recomputed from
the signal callbacks on demand and smoothed with a time-constant EWMA
(injectable clock, so transitions unit-test without sleeping).
"""

from __future__ import annotations

import math
import time
from dataclasses import dataclass
from typing import Callable, List, Optional, Tuple

__all__ = ["OverloadTunables", "LoadGovernor"]


@dataclass
class OverloadTunables:
    """``[api]`` tunables (docs/ROBUSTNESS.md "Overload & brownout").

    Admission gate: a request is shed (503 SlowDown + Retry-After)
    when the node already has ``max_inflight`` requests in flight or
    ``max_inflight_bytes`` of declared request-body bytes committed —
    never queued: queueing past saturation only converts overload into
    timeout storms.  0 disables the corresponding watermark."""

    # admission gate watermarks
    max_inflight: int = 256
    max_inflight_bytes: int = 1 << 30          # 1 GiB of declared bodies
    # base client back-off seconds on a shed; the Retry-After actually
    # sent is DERIVED from live load (governor pressure / queue depth,
    # AdmissionGate.retry_after_hint) between retry_after and
    # retry_after_max
    retry_after: int = 1
    retry_after_max: int = 30
    # --- multi-tenant fair queueing (WDRR; docs/ROBUSTNESS.md
    # "Multi-tenant fairness & noisy neighbors") ---
    # bound on each tenant's admission queue (requests waiting for a
    # slot); past it that tenant sheds — never the whole gate
    tenant_queue_len: int = 32
    # max seconds a request may wait queued before shedding typed
    tenant_queue_wait: float = 1.0
    # WDRR byte deficit added per scheduling visit, and the per-request
    # base cost (so byte-free GETs still consume deficit)
    wdrr_quantum_bytes: int = 256 * 1024
    wdrr_request_cost: int = 64 * 1024
    # cap on distinct tenants tracked (metric-cardinality bound; the
    # overflow shares one "~overflow" bucket)
    max_tracked_tenants: int = 1024
    # --- cluster-aware admission ---
    # gossiped governor_pressure of a layout node the request must touch
    # at/above this sheds at the front door (verdict remote_pressure);
    # 0 disables.  Pressure is clamped to [0, 2]; 1 means saturated.
    remote_pressure_shed: float = 1.5
    # --- CoDel-style adaptive watermark ---
    # admitted-request sojourn target (seconds): latency persistently
    # above it for codel_interval tightens the effective in-flight
    # limit; persistently below relaxes back toward max_inflight.
    # 0 disables (static watermark).
    codel_target: float = 0.5
    codel_interval: float = 2.0
    # --- byte accounting for Content-Length-less (chunked) bodies ---
    # conservative bytes charged at admission for a streaming body with
    # no declared length, reconciled to actual bytes as it streams
    streaming_body_estimate: int = 16 * 1024 * 1024
    # bound on the separate long-poll pool (parked K2V polls).  Past it
    # a poll keeps holding its admission slot instead of parking, so
    # total poll concurrency stays bounded by the gate as before this
    # pool existed.  0 = derive 4 x max_inflight.
    longpoll_max_parked: int = 0
    # --- graceful drain (docs/ROBUSTNESS.md "Geo-WAN & gateway
    # failover") ---
    # max seconds a SIGTERM'd API server waits for its in-flight set
    # to finish while shedding new requests typed, before closing the
    # socket regardless
    drain_timeout: float = 10.0
    # --- load governor ---
    # pressure <= governor_low → background at full rate (ratio 1.0);
    # pressure >= governor_high → background at governor_min_ratio;
    # linear in between.  Pressure is the max over the live signals,
    # each normalized to [0, 1+].
    governor_low: float = 0.45
    governor_high: float = 0.85
    governor_min_ratio: float = 0.05
    # EWMA time constant (seconds) smoothing the ratio so one bursty
    # scrape or a single slow frame does not whipsaw the workers
    governor_tau: float = 2.0
    # queue-wait seconds that count as pressure 1.0 (the HOL-blocking
    # signal net_queue_wait_seconds measures; 50 ms of frames waiting
    # for the wire means the node is badly backed up)
    governor_queue_wait_full: float = 0.05
    # codec feeder depth (pending submissions) that counts as pressure
    # 1.0 — the foreground data path is saturated past this backlog
    governor_feeder_depth_full: int = 64


class LoadGovernor:
    """Aggregates pressure signals → one smoothed background throttle
    ratio.  Signals are callables returning pressure in [0, 1+] (values
    above 1 are clamped); registration order is only cosmetic."""

    def __init__(self, tun: Optional[OverloadTunables] = None,
                 metrics=None, clock: Callable[[], float] = time.monotonic):
        self.tun = tun or OverloadTunables()
        self.clock = clock
        self._signals: List[Tuple[str, Callable[[], float]]] = []
        # netapp queue-wait EWMA (fed by the connection write loops via
        # note_queue_wait; decays on read so a past burst ages out even
        # with no new frames flowing)
        self._qwait_ewma = 0.0
        self._qwait_at = clock()
        self._ratio = 1.0
        self._ratio_at = clock()
        if metrics is not None:
            metrics.gauge(
                "background_throttle_ratio",
                "Background work rate multiplier chosen by the load "
                "governor (1 = full rate, near 0 = foreground pressure "
                "has background work parked)",
                fn=self.ratio)
            metrics.gauge(
                "governor_pressure",
                "Current max foreground-pressure signal feeding the "
                "load governor (0 idle, >= 1 saturated)",
                fn=self.pressure)

    # --- signal wiring ---------------------------------------------------

    def add_signal(self, name: str, fn: Callable[[], float]) -> None:
        self._signals.append((name, fn))

    def remove_signal(self, name: str) -> None:
        """Drop a signal by name (chaos drills inject synthetic pressure
        and must be able to heal it)."""
        self._signals = [(n, f) for n, f in self._signals if n != name]

    def note_queue_wait(self, seconds: float) -> None:
        """Fed by netapp's write loop with each frame's queue wait; a
        cheap EWMA (alpha keyed to governor_tau via inter-sample time)."""
        now = self.clock()
        dt = max(now - self._qwait_at, 1e-6)
        self._qwait_at = now
        alpha = 1.0 - math.exp(-dt / max(self.tun.governor_tau, 1e-3))
        self._qwait_ewma += alpha * (seconds - self._qwait_ewma)

    def _qwait_pressure(self) -> float:
        # decay toward zero while no frames flow (no samples ≠ pressure)
        idle = self.clock() - self._qwait_at
        decay = math.exp(-idle / max(self.tun.governor_tau, 1e-3))
        full = max(self.tun.governor_queue_wait_full, 1e-6)
        return (self._qwait_ewma * decay) / full

    # --- outputs ---------------------------------------------------------

    def pressure(self) -> float:
        """Max over the live signals, clamped to [0, 2] (values above 1
        all mean 'saturated'; the cap keeps a broken signal from feeding
        absurd numbers into the smoothing)."""
        p = self._qwait_pressure()
        for _name, fn in self._signals:
            try:
                p = max(p, float(fn()))
            except Exception:  # noqa: BLE001 — a dead signal is 0, not a crash
                continue
        return min(max(p, 0.0), 2.0)

    def signals(self) -> dict:
        """Per-signal snapshot for the admin API / debugging."""
        out = {"queue_wait": round(self._qwait_pressure(), 4)}
        for name, fn in self._signals:
            try:
                out[name] = round(float(fn()), 4)
            except Exception:  # noqa: BLE001
                out[name] = None
        return out

    def _target(self, p: float) -> float:
        lo, hi = self.tun.governor_low, self.tun.governor_high
        if p <= lo:
            return 1.0
        if p >= hi:
            return self.tun.governor_min_ratio
        frac = (p - lo) / max(hi - lo, 1e-6)
        return 1.0 - frac * (1.0 - self.tun.governor_min_ratio)

    def ratio(self) -> float:
        """The smoothed background rate multiplier in
        [governor_min_ratio, 1.0].  Reading advances the smoothing
        toward the instantaneous target with time constant
        governor_tau — callers (workers between iterations, the metrics
        scrape) sample often enough that no separate tick loop is
        needed."""
        now = self.clock()
        dt = max(now - self._ratio_at, 0.0)
        self._ratio_at = now
        target = self._target(self.pressure())
        alpha = 1.0 - math.exp(-dt / max(self.tun.governor_tau, 1e-3))
        self._ratio += alpha * (target - self._ratio)
        # snap when close so "recovered" is an exact 1.0, not 0.9999…
        if abs(self._ratio - target) < 1e-3:
            self._ratio = target
        return self._ratio

    def bg_pause(self, worked_s: float, cap: float = 2.0) -> float:
        """How long a background worker should sleep after a work slice
        that took `worked_s`: duty-cycle control — at ratio r the worker
        runs r of the time (sleep = worked · (1-r)/r), capped so a long
        slice cannot park a worker for minutes.  0 at full rate."""
        r = self.ratio()
        if r >= 0.999:
            return 0.0
        r = max(r, self.tun.governor_min_ratio, 1e-3)
        return min(worked_s * (1.0 - r) / r, cap)
