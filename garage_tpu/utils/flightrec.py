"""Incident flight recorder — one-call diagnostic bundles.

When an incident happens the evidence is ephemeral: the retained
waterfalls rotate, the device timeline ring wraps, breaker and governor
state heal themselves, gossip forgets.  By the time a human looks, the
system has already recovered — or the interesting 30 seconds have been
overwritten.  The flight recorder closes that gap: a single ``capture``
call walks a registry of *collectors* (metrics snapshot, retained
waterfalls, device timeline, breaker/disk/governor/gate state, the
gossiped peer table, recent event rings, SLO budgets) and writes ONE
JSON bundle to ``<metadata_dir>/incidents/``.

Triggered three ways:

  - **automatically** (``trigger``) on fast-burn SLO breaches
    (utils/slo.py), fail-slow flag transitions (utils/health_score.py)
    and disk/cluster state degradation — debounced so a breach that
    fires every bucket produces ONE bundle per ``debounce_s``, and the
    retention bound (``max_bundles``, oldest deleted first) means an
    incident storm can never fill the metadata disk;
  - **manually** via admin ``incident_capture`` / CLI ``incident
    capture`` / ``scripts/incident_dump.py`` (manual captures skip the
    debounce — an operator asking for a snapshot always gets one);
  - from tests, with injectable clocks.

Collector failures are recorded IN the bundle (``{"error": ...}``),
never raised: a capture triggered by a sick subsystem must not die of
the same sickness it is documenting.
"""

from __future__ import annotations

import asyncio
import itertools
import json
import logging
import os
import threading
import time
from typing import Any, Callable, Dict, List, Optional

logger = logging.getLogger("garage_tpu.flightrec")

SCHEMA = "garage_tpu.incident/1"

# bundle filenames: incident-<epoch_ms>-<seq>-<reason>.json (reason
# slugged; seq = process-monotonic same-millisecond disambiguator)
_SLUG_OK = "abcdefghijklmnopqrstuvwxyz0123456789_-"


def _slug(reason: str) -> str:
    s = "".join(c if c in _SLUG_OK else "-" for c in str(reason).lower())
    return s[:48] or "incident"


class FlightRecorder:
    """One per node (model/garage.py wires the collectors)."""

    def __init__(self, dir_path: str, node_id: str = "",
                 max_bundles: int = 16, debounce_s: float = 60.0,
                 metrics=None,
                 clock: Callable[[], float] = time.time,
                 mono: Callable[[], float] = time.monotonic):
        self.dir = dir_path
        self.node_id = node_id
        self.max_bundles = max(1, int(max_bundles))
        self.debounce_s = float(debounce_s)
        self.clock = clock
        self.mono = mono
        self.collectors: Dict[str, Callable[[], Any]] = {}
        self._last_auto_at: Optional[float] = None  # monotonic
        self._capturing = False
        self._seq = itertools.count()  # same-ms filename disambiguator
        self.captures = 0
        self.suppressed = 0
        if metrics is not None:
            self._m_captures = metrics.counter(
                "incident_capture_total",
                "Flight-recorder bundles written, by trigger")
            self._m_suppressed = metrics.counter(
                "incident_suppressed_total",
                "Auto incident triggers suppressed by the debounce "
                "window (a bundle for the same storm already exists)")
            metrics.gauge(
                "incident_bundles_retained",
                "Incident bundles currently on disk (bounded by the "
                "retention limit, oldest deleted first)",
                fn=lambda: float(len(self._bundle_files())))
        else:
            self._m_captures = self._m_suppressed = None

    # --- collector registry ----------------------------------------------

    def add_collector(self, name: str, fn: Callable[[], Any]) -> None:
        self.collectors[name] = fn

    # --- capture ----------------------------------------------------------

    def trigger(self, reason: str, detail: Optional[dict] = None
                ) -> Optional[str]:
        """The AUTO path: debounced.  Returns the bundle path, or None
        when suppressed (a bundle for the current storm already exists)
        or deferred (see below).

        Auto triggers fire from hot paths — a fast-burn breach fires
        inside a request handler, a fail-slow flip inside peer-rank's
        health view.  Under a running event loop the collectors run
        inline (the caller IS the loop, so loop-owned state is read
        race-free, at Prometheus-scrape cost) but the expensive
        serialize + disk write happens on a short-lived worker thread
        (the node is already degraded; stalling every in-flight request
        to write the evidence would deepen the incident being
        documented).  Without a loop (tests, scripts) the whole capture
        runs inline and the path is returned."""
        now = self.mono()
        if self._capturing or (
                self._last_auto_at is not None
                and now - self._last_auto_at < self.debounce_s):
            # _capturing: a collector observed a NEW transition while a
            # capture was already assembling (e.g. the metrics render's
            # health-score sweep flips a fail-slow flag) — the bundle
            # being written documents that same storm; without the
            # guard the nested trigger would assemble a second full
            # bundle inline, inside its own collector
            self.suppressed += 1
            if self._m_suppressed is not None:
                self._m_suppressed.inc()
            logger.debug("incident trigger %r suppressed (debounce)", reason)
            return None
        self._last_auto_at = now
        try:
            asyncio.get_running_loop()
        except RuntimeError:
            try:
                return self.capture(reason, detail=detail, trigger="auto")
            except Exception:
                # a failed WRITE must not consume the debounce window:
                # the incident most likely to break the write (a dying
                # metadata disk) is the one that must keep retrying on
                # its next trigger instead of ending with zero bundles.
                # Compare-and-swap: only roll back OUR stamp — a newer
                # trigger's window must not be clobbered
                if self._last_auto_at == now:
                    self._last_auto_at = None
                raise
        # under a running loop: collect HERE (the caller's thread IS
        # the loop, so collectors see loop-owned dicts race-free; the
        # walk is scrape-grade — metrics.render runs on the loop at
        # every Prometheus scrape already) and push only the expensive
        # serialize + disk write to a worker thread
        bundle = self.collect(reason, detail=detail, trigger="auto")
        threading.Thread(
            target=self._write_logged, args=(bundle, now),
            name="incident-write", daemon=True).start()
        return None

    def _write_logged(self, bundle: dict, stamp: float) -> None:
        from .cpuprof import register_thread, unregister_thread
        register_thread("incident-write")
        try:
            self.write(bundle)
        except Exception:  # noqa: BLE001 — an unwritable dir, already logged
            # roll back the debounce window, but only OUR stamp (a slow
            # failing write must not clobber a newer trigger's window)
            if self._last_auto_at == stamp:
                self._last_auto_at = None
            logger.exception("deferred incident write failed (%s)",
                             bundle.get("reason"))
        finally:
            unregister_thread()

    def capture(self, reason: str, detail: Optional[dict] = None,
                trigger: str = "manual") -> str:
        """Assemble + write one bundle; returns its path.  Never raises
        on collector failure (recorded per-section instead); only an
        unwritable incidents directory propagates.  Collectors run on
        the CALLING thread — call from the event loop (or split it
        yourself: ``collect`` on the loop, ``write`` off it, as the
        admin handler and the auto trigger do) so they see loop-owned
        state race-free."""
        return self.write(self.collect(reason, detail=detail,
                                       trigger=trigger))

    def collect(self, reason: str, detail: Optional[dict] = None,
                trigger: str = "manual") -> dict:
        """Walk the collectors into a bundle dict (no I/O).  A failing
        collector is recorded as its section's ``{"error": ...}``."""
        sections: Dict[str, Any] = {}
        self._capturing = True
        try:
            for name, fn in self.collectors.items():
                try:
                    sections[name] = fn()
                except Exception as e:  # noqa: BLE001 — document the sickness
                    sections[name] = {"error": f"{type(e).__name__}: {e}"}
        finally:
            self._capturing = False
        ts = self.clock()
        return {
            "schema": SCHEMA,
            "captured_at": round(ts, 3),
            "node_id": self.node_id,
            "trigger": trigger,
            "reason": str(reason),
            "detail": detail or {},
            # header copy of the section names: every scalar a listing
            # needs sits BEFORE the (potentially MBs-large) sections
            # payload, so bundles() can parse a bounded prefix
            "section_list": sorted(sections),
            "sections": sections,
        }

    def write(self, bundle: dict) -> str:
        """Serialize + write a collected bundle (the expensive half —
        safe on a worker thread: touches no shared mutable state beyond
        the counters).  The filename carries a process-monotonic
        sequence so two captures in the same wall-clock millisecond
        (concurrent manual requests) never overwrite each other."""
        os.makedirs(self.dir, exist_ok=True)
        name = (f"incident-{int(bundle['captured_at'] * 1000)}-"
                f"{next(self._seq):03d}-{_slug(bundle['reason'])}.json")
        path = os.path.join(self.dir, name)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(bundle, f, default=_json_default)
        os.replace(tmp, path)
        self.captures += 1
        if self._m_captures is not None:
            self._m_captures.inc(trigger=bundle["trigger"])
        self._enforce_retention()
        logger.info("incident bundle written: %s (%s)", path,
                    bundle["reason"])
        return path

    # --- retention / listing ----------------------------------------------

    def _bundle_files(self) -> List[str]:
        try:
            names = os.listdir(self.dir)
        except OSError:
            return []
        return sorted(
            os.path.join(self.dir, n) for n in names
            if n.startswith("incident-") and n.endswith(".json")
        )

    def _enforce_retention(self) -> None:
        files = self._bundle_files()
        for p in files[:max(0, len(files) - self.max_bundles)]:
            try:
                os.remove(p)
            except OSError:
                pass

    def bundles(self) -> List[dict]:
        """[{path, reason, trigger, captured_at, sections}] newest last
        — the admin ``incident_list`` payload (headers only, never the
        full sections: a listing must stay cheap).  Only a bounded
        prefix of each file is read and parsed: ``capture`` writes all
        the scalar header fields (and a ``section_list`` copy of the
        section names) before the large ``sections`` payload, so the
        prefix is cut at the ``"sections"`` key and re-closed; anything
        that defeats the cut (hand-edited bundle) falls back to a full
        parse."""
        out = []
        for p in self._bundle_files():
            row = {"path": p, "reason": None}
            head = None
            try:
                with open(p) as f:
                    prefix = f.read(16384)
                cut = prefix.find('"sections"')
                if cut != -1:
                    try:
                        head = json.loads(
                            prefix[:cut].rstrip().rstrip(",") + "}")
                    except ValueError:
                        head = None
                if head is None:
                    with open(p) as f:
                        head = json.load(f)
            except (OSError, ValueError):
                out.append(row)
                continue
            row.update({
                "reason": head.get("reason"),
                "trigger": head.get("trigger"),
                "captured_at": head.get("captured_at"),
                "sections": (head.get("section_list")
                             or sorted((head.get("sections") or {}).keys())),
            })
            out.append(row)
        return out


def _json_default(o):
    """Bundles hold whatever the collectors return: bytes become hex,
    everything else its repr — a capture must never die of a non-JSON
    value."""
    if isinstance(o, (bytes, bytearray)):
        return bytes(o).hex()
    return repr(o)
