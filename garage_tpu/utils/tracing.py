"""Distributed tracing — spans + OTLP/HTTP export.

Equivalent of the reference's OpenTelemetry integration: OTLP exporter
configured from `admin.trace_sink` (ref garage/tracing_setup.rs:13-37,
util/config.rs:173-175), spans created per API request with a fresh
trace id (ref api/generic_server.rs:187-200), per table op (ref
table/table.rs:105-110), per RPC quorum call with quorum attributes (ref
rpc/rpc_helper.rs:238-260), and per block write/read/resync (ref
block/manager.rs:492-501, resync.rs:286-303).

No OTel SDK dependency: spans are plain records, parenting rides a
contextvar (task-local, so concurrent requests don't cross wires), and
the exporter speaks the OTLP/HTTP **JSON** encoding directly
(opentelemetry-proto's canonical JSON mapping), batched on a background
task.  A dead or slow collector drops batches after a short timeout —
tracing must never hold up the data path.

When no trace_sink is configured the tracer is disabled for EXPORT but
spans are still REAL (ids + contextvar parenting): they feed the
always-on top-N slow-op log AND the request-waterfall recorder
(utils/waterfall.py) — so "what were the slowest block ops this node
ever ran" and "where did that slow PUT's time go" are both answerable
on a node that never configured a collector.  Only the export buffer is
skipped without a sink; the per-span cost is one 8-byte id + a record
dict, paid so the critical-path attribution layer is never dark.
"""

from __future__ import annotations

import asyncio
import contextvars
import dataclasses
import heapq
import logging
import os
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

from .cpuprof import note_span_enter, note_span_exit  # noqa: E402

logger = logging.getLogger("garage_tpu.tracing")

FLUSH_INTERVAL = 3.0      # seconds between export batches
EXPORT_TIMEOUT = 3.0      # ref tracing_setup.rs with_timeout(3s)
MAX_BUFFER = 4096         # spans held while the collector is unreachable
MAX_BATCH = 512
SLOW_LOG_SIZE = 64        # top-N slowest spans retained, always on
SLOW_LOG_MIN_S = 0.010    # ignore sub-10ms ops entirely (noise floor)


class SlowOpLog:
    """Top-N slowest operations by duration — bounded, always on.

    Fed by every span exit (real spans and the no-sink lite spans
    alike).  The read side is the admin `slow-ops` command.  The O(1)
    fast path (compare against the current minimum before locking)
    keeps the hot-path cost of a fast op at one float compare."""

    def __init__(self, size: int = SLOW_LOG_SIZE):
        self._size = size
        self._heap: list = []   # (dur_s, seq, record) min-heap
        self._lock = threading.Lock()
        self._seq = 0

    def note(self, name: str, dur_s: float, attrs: Dict[str, Any],
             trace_id: Optional[str] = None) -> None:
        if dur_s < SLOW_LOG_MIN_S:
            return
        heap = self._heap
        if len(heap) >= self._size and dur_s <= heap[0][0]:
            return  # fast path: racy read is fine, the bar only rises
        rec = {
            "name": name,
            "seconds": round(dur_s, 6),
            "ts": round(time.time(), 3),
            "attrs": {k: v for k, v in attrs.items()
                      if isinstance(v, (str, int, float, bool))},
        }
        if trace_id:
            # the histogram-exemplar / waterfall link: a slow-op row's
            # trace id keys straight into `request waterfall --trace`
            rec["trace"] = trace_id
        with self._lock:
            self._seq += 1
            if len(heap) < self._size:
                heapq.heappush(heap, (dur_s, self._seq, rec))
            elif dur_s > heap[0][0]:
                heapq.heapreplace(heap, (dur_s, self._seq, rec))

    def snapshot(self, limit: Optional[int] = None) -> List[dict]:
        """Slowest-first list of retained records."""
        with self._lock:
            items = sorted(self._heap, key=lambda t: -t[0])
        out = [rec for _d, _s, rec in items]
        return out[:limit] if limit else out

    def max_seconds(self) -> float:
        with self._lock:
            return max((d for d, _s, _r in self._heap), default=0.0)

_current_span: contextvars.ContextVar[Optional["Span"]] = contextvars.ContextVar(
    "garage_tpu_current_span", default=None
)

# --- end-to-end request deadlines (docs/ROBUSTNESS.md "Overload &
# brownout") -----------------------------------------------------------
#
# The API front door arms a deadline for each client request; every
# nested RPC carries the REMAINING budget in its request header (`dl`,
# net/netapp.py — relative seconds, never an absolute timestamp: peer
# clocks are not comparable), the receiving node re-arms its own
# task-local deadline from it, and each layer clamps its work to what is
# left.  Work whose client has already timed out is shed at the earliest
# seam it reaches (RpcHelper dispatch, the netapp out-queue, the codec
# feeder) with the typed DeadlineExceeded instead of burning capacity on
# an answer nobody is waiting for.  Task-local like the span context, so
# concurrent requests cannot cross budgets.

_deadline: contextvars.ContextVar[Optional[float]] = contextvars.ContextVar(
    "garage_tpu_deadline", default=None  # absolute time.monotonic() seconds
)


def arm_deadline(budget_s: Optional[float]):
    """Install a deadline `budget_s` seconds from now for the current
    task; returns the reset token.  When a deadline is already armed the
    TIGHTER of the two wins — a nested hop can shrink the budget, never
    extend it.  budget_s None installs nothing (token still valid) —
    deadline DISABLING is the caller's decision; a budget <= 0 arms an
    already-expired deadline (a hop that received zero budget must
    fast-fail, not run uncapped)."""
    cur = _deadline.get()
    if budget_s is None:
        return _deadline.set(cur)
    new = time.monotonic() + budget_s
    return _deadline.set(new if cur is None else min(cur, new))


def disarm_deadline(token) -> None:
    _deadline.reset(token)


def refresh_deadline(budget_s: Optional[float]) -> None:
    """Progress-based deadline renewal: reset the CURRENT task's armed
    deadline to `budget_s` from now.  The deadline exists to shed work
    whose client has departed — a client actively streaming body bytes
    (or draining response bytes) is demonstrably alive, so the streaming
    handlers renew the budget on every unit of observed progress; the
    budget then bounds time-since-last-progress, not total transfer time
    (a multi-GiB PUT must not be killed at the 30 s mark mid-stream).
    No-op when no deadline is armed (deadlines disabled) or budget_s is
    None.  Unlike arm_deadline this may EXTEND — only the layer that
    observes client progress is entitled to do that."""
    if budget_s is None or _deadline.get() is None:
        return
    _deadline.set(time.monotonic() + budget_s)


def current_deadline() -> Optional[float]:
    """The task's absolute (time.monotonic) deadline, or None."""
    return _deadline.get()


def remaining_budget() -> Optional[float]:
    """Seconds of budget left for the current task's request; negative
    once expired, None when no deadline is armed."""
    d = _deadline.get()
    return None if d is None else d - time.monotonic()


def deadline_expired() -> bool:
    d = _deadline.get()
    return d is not None and time.monotonic() >= d


def clamp_to_budget(timeout: Optional[float]) -> Optional[float]:
    """A per-hop timeout never longer than the remaining request budget.
    None timeout + armed deadline → the budget itself (an untimed call
    must still end with its client)."""
    rem = remaining_budget()
    if rem is None:
        return timeout
    rem = max(rem, 0.001)
    return rem if timeout is None else min(timeout, rem)


class deadline_scope:
    """``with deadline_scope(budget_s):`` — arm for the block, restore on
    exit.  The API servers bracket each client request with one."""

    __slots__ = ("budget", "_token")

    def __init__(self, budget_s: Optional[float]):
        self.budget = budget_s

    def __enter__(self):
        self._token = arm_deadline(self.budget)
        return self

    def __exit__(self, *exc) -> bool:
        disarm_deadline(self._token)
        return False

# Trace context extracted from an INCOMING RPC frame: set by the netapp
# handler task so server-side spans parent on the caller's span even when
# this node's tracer is export-disabled (the context is still forwarded
# to further hops).  Task-local like _current_span.
_remote_ctx: contextvars.ContextVar[Optional["TraceContext"]] = (
    contextvars.ContextVar("garage_tpu_remote_trace_ctx", default=None)
)


@dataclasses.dataclass(frozen=True)
class TraceContext:
    """Wire-portable span identity: what one node needs to parent its
    spans on a caller running on another node.  Carried in the msgpack
    request header of K_REQ frames (net/netapp.py) — the equivalent of
    W3C traceparent for the cluster-internal RPC fabric.

    `priority` is the caller's frame priority; the receiving node's
    further hops never run MORE urgent than it
    (netapp.call_streaming demotes via inherited_priority), so work
    spawned by a background request stays background."""

    trace_id: str
    span_id: str
    priority: int = 1  # PRIO_NORMAL; plain int to avoid a net/ import

    def pack(self) -> Dict[str, Any]:
        """Header-embeddable dict (short keys: rides every K_REQ)."""
        return {"t": self.trace_id, "s": self.span_id, "p": self.priority}

    @classmethod
    def unpack(cls, d: Any) -> Optional["TraceContext"]:
        """Parse a header dict; None on anything malformed — a bad peer
        must never be able to break request dispatch via the trace
        header."""
        try:
            t, s = str(d["t"]), str(d["s"])
            if not t or not s or len(t) > 64 or len(s) > 32:
                return None
            int(t, 16), int(s, 16)  # hex ids only
            p = min(max(int(d.get("p", 1)), 0), 3)  # clamp to PRIO range
            return cls(t, s, p)
        except (TypeError, KeyError, ValueError):
            return None


def current_trace_id() -> Optional[str]:
    """The current task's trace id (local span first, then the remote
    context) — the histogram-exemplar hook (utils/metrics.py) reads it
    so a p99 bucket can name the exact request that landed there."""
    span = _current_span.get()
    if span is not None:
        return span.trace_id
    ctx = _remote_ctx.get()
    return ctx.trace_id if ctx is not None else None


def install_log_trace_ids() -> None:
    """Log↔trace correlation: inject the task-local trace id into every
    log record as ``record.trace_id`` ("-" outside any request scope),
    so a log line emitted while serving a request carries the SAME key
    as the retained waterfall (`request waterfall --trace`), the
    histogram exemplar, and the x-amz-request-id the client holds —
    grep the incident bundle's trace id through the logs and every line
    of that request lines up.

    Implemented as a record FACTORY, not a Filter: factories apply to
    every logger/handler at once (one formatter change in cli.main
    turns it on), and a filter attached to the root handler would miss
    records from handlers added later.  Idempotent; never raises into
    the logging call."""
    old = logging.getLogRecordFactory()
    if getattr(old, "_garage_tpu_trace", False):
        return

    def factory(*args, **kwargs):
        record = old(*args, **kwargs)
        try:
            record.trace_id = current_trace_id() or "-"
        except Exception:  # noqa: BLE001 — logging must never break
            record.trace_id = "-"
        return record

    factory._garage_tpu_trace = True
    logging.setLogRecordFactory(factory)


def current_trace_context() -> Optional[TraceContext]:
    """The context to INJECT into an outgoing RPC: the current local
    span's identity, or (when this node created no span of its own, e.g.
    tracer export-disabled mid-chain) the remote context it received."""
    span = _current_span.get()
    if span is not None:
        return TraceContext(span.trace_id, span.span_id)
    return _remote_ctx.get()


def inherited_priority() -> Optional[int]:
    """The incoming request's frame priority, when this task is serving
    one — the floor (in urgency terms: the CEILING) for further hops.
    Deliberately reads only the REMOTE context: local spans default to
    PRIO_NORMAL and must not demote explicit high-priority calls."""
    ctx = _remote_ctx.get()
    return ctx.priority if ctx is not None else None


def set_remote_context(ctx: Optional[TraceContext]):
    """Install an extracted incoming context for the current task; returns
    the reset token."""
    return _remote_ctx.set(ctx)


def reset_remote_context(token) -> None:
    _remote_ctx.reset(token)


class Span:
    __slots__ = ("trace_id", "span_id", "parent_id", "name", "start_ns",
                 "end_ns", "attrs", "error", "_tracer", "_token")

    def __init__(self, tracer: "Tracer", name: str, trace_id: str,
                 parent_id: Optional[str], attrs: Dict[str, Any],
                 start_ns: Optional[int] = None):
        self._tracer = tracer
        self.name = name
        self.trace_id = trace_id
        self.span_id = os.urandom(8).hex()
        self.parent_id = parent_id
        self.attrs = attrs
        # start_ns override: the API front door backdates the request
        # root to intake time so admission — which runs BEFORE the trace
        # is minted — still lands inside the root's interval and the
        # waterfall's segments sum to the duration the client saw
        self.start_ns = start_ns if start_ns is not None else time.time_ns()
        self.end_ns = 0
        self.error: Optional[str] = None
        self._token = None

    def set_attr(self, key: str, value: Any) -> None:
        self.attrs[key] = value

    def mark_service_start(self) -> None:
        """Queue-wait/service-time split: everything from the span's
        start to THIS call was queue wait — the waterfall sweep
        attributes that prefix to the `queue` segment, the rest to the
        span's own segment."""
        self.attrs["queue_s"] = round(
            (time.time_ns() - self.start_ns) / 1e9, 6)

    def to_record(self) -> Dict[str, Any]:
        """The plain-dict shape the waterfall recorder stores and the
        admin `trace_spans` command ships across nodes."""
        return {
            "trace": self.trace_id,
            "span": self.span_id,
            "parent": self.parent_id,
            "name": self.name,
            "start_ns": self.start_ns,
            "end_ns": self.end_ns,
            "attrs": {k: v for k, v in self.attrs.items()
                      if isinstance(v, (str, int, float, bool))},
        }

    def __enter__(self) -> "Span":
        self._token = _current_span.set(self)
        # CPU-profiler join: record this span's segment on the current
        # task's stack so the sampler thread can tag event-loop samples
        # with what was actually running (no-op unless a profiler is
        # installed — see utils/cpuprof.enable_span_join)
        note_span_enter(self.name)
        return self

    def __exit__(self, exc_type, exc, _tb) -> bool:
        self.end_ns = time.time_ns()
        if exc is not None:
            self.error = f"{exc_type.__name__}: {exc}"
        note_span_exit()
        _current_span.reset(self._token)
        self._tracer._record(self)
        self._tracer.slow.note(
            self.name, (self.end_ns - self.start_ns) / 1e9, self.attrs,
            trace_id=self.trace_id,
        )
        return False


class Tracer:
    """Per-node tracer; lives on System next to the metrics registry."""

    def __init__(self, service_instance: str = "",
                 exporter: Optional["OtlpHttpExporter"] = None):
        self.exporter = exporter
        self.enabled = exporter is not None
        self.service_instance = service_instance
        self._buf: deque = deque(maxlen=MAX_BUFFER)
        self.dropped = 0
        self.exported = 0
        # always-on top-N slow-op retention — populated by every span
        # exit whether or not a collector is configured
        self.slow = SlowOpLog()
        # request-waterfall recorder (utils/waterfall.py), attached by
        # System next to the metrics registry; every finished span's
        # record lands there so per-request critical-path attribution
        # works with no collector configured
        self.waterfall = None
        self._task: Optional[asyncio.Task] = None

    # --- span creation ---

    def span(self, name: str, /, **attrs):
        """Child span of the context's current span; when there is none,
        of the REMOTE context extracted from an incoming RPC frame; a new
        trace root otherwise.  Always a real span (ids + contextvar):
        without an exporter it skips only the export buffer, still
        feeding the slow-op log and the waterfall recorder."""
        parent = _current_span.get()
        if parent is not None:
            return Span(self, name, parent.trace_id, parent.span_id, attrs)
        rctx = _remote_ctx.get()
        if rctx is not None:
            return Span(self, name, rctx.trace_id, rctx.span_id, attrs)
        return Span(self, name, os.urandom(16).hex(), None, attrs)

    def span_from_context(self, name: str, ctx: Optional[TraceContext],
                          /, **attrs):
        """Span parented on an EXPLICIT cross-node context (the netapp
        server side wraps request handlers in one).  Falls back to a
        plain span when the caller sent no context."""
        if ctx is None:
            return self.span(name, **attrs)
        return Span(self, name, ctx.trace_id, ctx.span_id, attrs)

    def new_trace(self, name: str, /, trace_id: Optional[str] = None,
                  start_ns: Optional[int] = None, **attrs):
        """Root span with a fresh trace id — one per API request (ref
        generic_server.rs:187-200 gen_trace_id).  `trace_id` lets the
        API layer supply the id it returns to the client
        (x-amz-request-id == trace id, so a support ticket quoting the
        request id IS the trace lookup key); `start_ns` backdates the
        root to request intake so pre-trace work (admission) lands
        inside it."""
        return Span(self, name, trace_id or os.urandom(16).hex(), None,
                    attrs, start_ns=start_ns)

    def record_span(self, name: str, trace_id: str,
                    parent_id: Optional[str], start_ns: int, end_ns: int,
                    span_id: Optional[str] = None, **attrs) -> str:
        """Record an ALREADY-FINISHED span with explicit timestamps —
        the cross-thread path: the codec feeder and device transport
        resolve a request's work on their own worker threads, where the
        submitter's contextvars are gone, so they carry the submitter's
        TraceContext on the work item and attribute the wait/compute
        back to its trace here.  `span_id` lets the caller pre-allocate
        the id so children recorded elsewhere can parent on it.
        Returns the span id."""
        s = Span(self, name, trace_id, parent_id, attrs, start_ns=start_ns)
        if span_id is not None:
            s.span_id = span_id
        s.end_ns = end_ns
        self._record(s)
        self.slow.note(name, (end_ns - start_ns) / 1e9, attrs,
                       trace_id=trace_id)
        return s.span_id

    def _record(self, span: Span) -> None:
        if self.enabled:
            if len(self._buf) == self._buf.maxlen:
                self.dropped += 1
            self._buf.append(span)
        wf = self.waterfall
        if wf is not None:
            try:
                wf.note(span.to_record())
            except Exception:  # noqa: BLE001 — attribution must never
                logger.debug("waterfall note failed", exc_info=True)

    # --- export loop ---

    def start(self) -> None:
        if self.enabled and self._task is None:
            self._task = asyncio.get_running_loop().create_task(
                self._flush_loop(), name="tracing-flush"
            )

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None
        await self.flush()

    async def _flush_loop(self) -> None:
        while True:
            await asyncio.sleep(FLUSH_INTERVAL)
            try:
                await self.flush()
            except Exception:
                logger.debug("trace flush failed", exc_info=True)

    async def flush(self) -> None:
        if self.exporter is None:
            return
        while self._buf:
            batch = []
            while self._buf and len(batch) < MAX_BATCH:
                batch.append(self._buf.popleft())
            try:
                ok = await self.exporter.export(batch, self.service_instance)
            except asyncio.CancelledError:
                # stop() cancelling a flush mid-export: put the batch back
                # so the final flush (or the drop accounting) sees it
                self._buf.extendleft(reversed(batch))
                raise
            if ok:
                self.exported += len(batch)
            else:
                self.dropped += len(batch)
                return  # collector unreachable: don't spin on the backlog


def _otlp_value(v: Any) -> Dict[str, Any]:
    if isinstance(v, bool):
        return {"boolValue": v}
    if isinstance(v, int):
        return {"intValue": str(v)}
    if isinstance(v, float):
        return {"doubleValue": v}
    return {"stringValue": str(v)}


def spans_to_otlp(spans: List[Span], service_instance: str) -> Dict[str, Any]:
    """OTLP/HTTP JSON payload (the proto3 canonical JSON mapping of
    ExportTraceServiceRequest)."""
    out = []
    for s in spans:
        rec: Dict[str, Any] = {
            "traceId": s.trace_id,
            "spanId": s.span_id,
            "name": s.name,
            "kind": 1,  # SPAN_KIND_INTERNAL
            "startTimeUnixNano": str(s.start_ns),
            "endTimeUnixNano": str(s.end_ns),
            "attributes": [
                {"key": k, "value": _otlp_value(v)} for k, v in s.attrs.items()
            ],
            "status": (
                {"code": 2, "message": s.error} if s.error else {"code": 1}
            ),
        }
        if s.parent_id:
            rec["parentSpanId"] = s.parent_id
        out.append(rec)
    return {
        "resourceSpans": [{
            "resource": {"attributes": [
                {"key": "service.name", "value": {"stringValue": "garage_tpu"}},
                {"key": "service.instance.id",
                 "value": {"stringValue": service_instance}},
            ]},
            "scopeSpans": [{
                "scope": {"name": "garage_tpu"},
                "spans": out,
            }],
        }]
    }


class OtlpHttpExporter:
    """POSTs OTLP JSON to `<endpoint>/v1/traces` (3 s timeout, failures
    dropped — tracing never blocks the node)."""

    def __init__(self, endpoint: str):
        self.url = endpoint.rstrip("/") + "/v1/traces"
        self._session = None

    async def export(self, spans: List[Span], service_instance: str) -> bool:
        import aiohttp

        if self._session is None:
            self._session = aiohttp.ClientSession(
                timeout=aiohttp.ClientTimeout(total=EXPORT_TIMEOUT)
            )
        try:
            payload = spans_to_otlp(spans, service_instance)
            async with self._session.post(self.url, json=payload) as r:
                return r.status in (200, 202)
        except Exception as e:
            logger.debug("OTLP export to %s failed: %s", self.url, e)
            return False

    async def close(self) -> None:
        if self._session is not None:
            await self._session.close()
            self._session = None


def init_tracing(trace_sink: Optional[str], node_id: bytes) -> Tracer:
    """Build the node's tracer from `admin.trace_sink` (ref
    tracing_setup.rs:13-37: service.instance.id = first 8 id bytes)."""
    instance = bytes(node_id)[:8].hex()
    if not trace_sink:
        return Tracer(instance, None)
    return Tracer(instance, OtlpHttpExporter(trace_sink))
