"""Atomic single-value persistence to a metadata file.

Equivalent of reference src/util/persister.rs:10-120: `Persister` saves one
versioned (`Migrated`) value via write-tmp + fsync + rename, and
`PersisterShared` adds an in-RAM cache.  Used for cluster layout, peer lists,
scrub checkpoints and lifecycle progress (ref rpc/system.rs:88-89,
block/repair.rs:185-229).
"""

from __future__ import annotations

import os
import threading
from typing import Generic, Optional, Type, TypeVar

from .migrate import Migrated

T = TypeVar("T", bound=Migrated)


class Persister(Generic[T]):
    def __init__(self, directory: str, name: str, typ: Type[T]):
        self.path = os.path.join(directory, name)
        self.typ = typ
        os.makedirs(directory, exist_ok=True)

    def load(self) -> Optional[T]:
        try:
            with open(self.path, "rb") as f:
                return self.typ.decode(f.read())  # type: ignore[return-value]
        except FileNotFoundError:
            return None

    def save(self, value: T) -> None:
        tmp = self.path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(value.encode())
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self.path)
        # fsync the directory so the rename is durable (ref persister.rs:60-76)
        dfd = os.open(os.path.dirname(self.path) or ".", os.O_RDONLY)
        try:
            os.fsync(dfd)
        finally:
            os.close(dfd)


class PersisterShared(Persister[T]):
    """Persister + RwLock'd in-memory copy (ref persister.rs:88-120)."""

    def __init__(self, directory: str, name: str, typ: Type[T], default: T):
        super().__init__(directory, name, typ)
        self._lock = threading.Lock()
        loaded = self.load()
        self._value: T = loaded if loaded is not None else default

    def get(self) -> T:
        with self._lock:
            return self._value

    def set(self, value: T) -> None:
        with self._lock:
            self._value = value
            self.save(value)
