"""Timeline — a bounded ring of timestamped events, exportable as
Chrome-trace (catapult) JSON.

The device/transport pipeline's overlap claims (double-buffered staging,
EDF foreground-first dispatch) were only ever *inferred* from counters;
this ring records the actual begin/end of every stage — enqueue, EDF
pop, per-slot staging, device submit, collect — so `chrome://tracing`
(or Perfetto) renders the pipeline as it ran and "did slot 1 stage while
slot 0 computed" is a picture, not an argument.

Always on, bounded (`maxlen` events, each a small dict), one lock.
Producers are the codec feeder and the device transport via the shared
CodecObserver (`obs.timeline`); consumers are the admin
`device_timeline` command, the HTTP `/v1/timeline` endpoint and
`scripts/device_timeline.py`.

Chrome-trace mapping: duration events are phase "X" (ts + dur, µs),
instants are "i", counters are "C".  Tracks ("tid") are stable small
integers assigned per track name, with thread_name metadata events so
the UI shows "slot0", "edf", "feeder" instead of numbers.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Dict, List, Optional

DEFAULT_SIZE = 8192


class Timeline:
    def __init__(self, size: int = DEFAULT_SIZE):
        self._ring: deque = deque(maxlen=size)
        self._lock = threading.Lock()
        self._tracks: Dict[str, int] = {}
        self.dropped = 0  # events evicted by the ring bound

    def _track(self, name: str) -> int:
        tid = self._tracks.get(name)
        if tid is None:
            tid = self._tracks[name] = len(self._tracks) + 1
        return tid

    def event(self, name: str, track: str, start_ns: int,
              end_ns: Optional[int] = None, cat: str = "transport",
              **args) -> None:
        """Duration event [start_ns, end_ns] (monotonic_ns), or an
        instant when end_ns is None."""
        ev = {
            "name": name,
            "cat": cat,
            "ph": "X" if end_ns is not None else "i",
            "ts": start_ns // 1000,  # chrome wants µs
            "pid": 1,
        }
        if end_ns is not None:
            ev["dur"] = max(0, (end_ns - start_ns) // 1000)
        else:
            ev["s"] = "t"  # instant scope: thread
        if args:
            ev["args"] = args
        with self._lock:
            ev["tid"] = self._track(track)
            if len(self._ring) == self._ring.maxlen:
                self.dropped += 1
            self._ring.append(ev)

    def counter(self, name: str, start_ns: int, **values) -> None:
        """Counter sample (stacked area in the trace viewer) — queue
        depths, slot occupancy."""
        ev = {"name": name, "cat": "counter", "ph": "C",
              "ts": start_ns // 1000, "pid": 1,
              "args": {k: float(v) for k, v in values.items()}}
        with self._lock:
            ev["tid"] = self._track("counters")
            if len(self._ring) == self._ring.maxlen:
                self.dropped += 1
            self._ring.append(ev)

    def snapshot(self, limit: Optional[int] = None) -> List[dict]:
        with self._lock:
            out = list(self._ring)
        if limit is not None and limit > 0:
            out = out[-limit:]
        return out

    def chrome_trace(self, limit: Optional[int] = None) -> dict:
        """The catapult JSON object: sorted traceEvents plus
        process/thread metadata so tracks render with their names."""
        events = sorted(self.snapshot(limit), key=lambda e: e["ts"])
        with self._lock:
            tracks = dict(self._tracks)
        meta: List[dict] = [{
            "name": "process_name", "ph": "M", "pid": 1, "tid": 0,
            "args": {"name": "garage_tpu device pipeline"},
        }]
        for name, tid in sorted(tracks.items(), key=lambda kv: kv[1]):
            meta.append({"name": "thread_name", "ph": "M", "pid": 1,
                         "tid": tid, "args": {"name": name}})
        return {
            "traceEvents": meta + events,
            "displayTimeUnit": "ms",
            "otherData": {
                "source": "garage_tpu",
                "captured_at": round(time.time(), 3),
                "dropped_events": self.dropped,
            },
        }


def overlapping_slot_windows(chrome: dict) -> int:
    """Count pairs of phase-X events on DISTINCT slot tracks whose time
    windows overlap — the smoke/test assertion that the double buffer
    actually overlapped staging with compute (≥ 1 pair means two slots
    were concurrently occupied)."""
    slots: Dict[int, list] = {}
    names = {}
    for ev in chrome.get("traceEvents", []):
        if ev.get("ph") == "M" and ev.get("name") == "thread_name":
            names[ev["tid"]] = ev["args"]["name"]
    for ev in chrome.get("traceEvents", []):
        if ev.get("ph") != "X":
            continue
        track = names.get(ev.get("tid"), "")
        if not str(track).startswith("slot"):
            continue
        slots.setdefault(ev["tid"], []).append(
            (ev["ts"], ev["ts"] + ev.get("dur", 0)))
    pairs = 0
    tids = sorted(slots)
    for i, a in enumerate(tids):
        for b in tids[i + 1:]:
            for s0, e0 in slots[a]:
                if any(s0 < e1 and s1 < e0 for s1, e1 in slots[b]):
                    pairs += 1
                    break
    return pairs
