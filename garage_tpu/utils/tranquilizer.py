"""Adaptive worker throttling.

Equivalent of reference src/util/tranquilizer.rs:21-77: after each unit of
work taking `t` seconds, sleep `tranquility × avg(last 30 observations)` so a
worker consumes ~1/(1+tranquility) of one core / one disk.  Used by scrub and
resync workers (ref block/repair.rs:466-468, block/resync.rs:526-553).
"""

from __future__ import annotations

import asyncio
import time
from collections import deque

from .background import WorkerState

MAX_OBSERVATIONS = 30  # ref util/tranquilizer.rs:30


class Tranquilizer:
    def __init__(self) -> None:
        self._obs: deque = deque(maxlen=MAX_OBSERVATIONS)
        self._last_start: float = time.monotonic()

    def reset(self) -> None:
        self._last_start = time.monotonic()

    def observe(self) -> float:
        dt = time.monotonic() - self._last_start
        self._obs.append(dt)
        return sum(self._obs) / len(self._obs)

    async def tranquilize(self, tranquility: int) -> None:
        avg = self.observe()
        if tranquility > 0:
            await asyncio.sleep(avg * tranquility)
        self.reset()

    async def tranquilize_worker(self, tranquility: int) -> WorkerState:
        """Sleep then report Busy/Throttled (ref tranquilizer.rs:60-69)."""
        await self.tranquilize(tranquility)
        return WorkerState.THROTTLED if tranquility > 0 else WorkerState.BUSY
