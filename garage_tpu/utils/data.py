"""Core data types and hash functions.

Equivalent of reference src/util/data.rs: the 32-byte `FixedBytes32` type
(used as both node/object UUIDs and content hashes, data.rs:9), plus the
hash functions `sha256sum` (data.rs:106), `blake2sum` (data.rs:117, blake2b-256)
and `fasthash` (data.rs:131, xxh3 in the reference; here blake2b-8byte keyed —
stdlib, non-cryptographic use only) and `gen_uuid` (data.rs:140).

TPU-first addition: `blake2s_sum` — BLAKE2s-256 is the framework's default
*block* hash because its 32-bit compression function maps onto the TPU VPU
(uint32 add/xor/rotate), unlike blake2b's 64-bit arithmetic.  The metadata
plane keeps blake2b for parity with the reference's semantics.  Both are
exact RFC 7693 and the TPU implementation (ops/tpu_blake2s.py) is verified
bit-identical against hashlib.
"""

from __future__ import annotations

import hashlib
import os
from typing import Union

ZERO_UUID: bytes = b"\x00" * 32


class FixedBytes32(bytes):
    """An exactly-32-byte value: UUIDs and hashes (ref util/data.rs:9-104).

    Subclass of ``bytes`` so it is hashable, comparable and serializable
    everywhere a plain byte string works.
    """

    __slots__ = ()

    def __new__(cls, value: Union[bytes, bytearray, memoryview, str]) -> "FixedBytes32":
        if isinstance(value, str):
            value = bytes.fromhex(value)
        b = bytes(value)
        if len(b) != 32:
            raise ValueError(f"FixedBytes32 requires exactly 32 bytes, got {len(b)}")
        return super().__new__(cls, b)

    def hex_short(self) -> str:
        """First 16 hex chars — display form (ref util/data.rs hex_::<16>)."""
        return self.hex()[:16]

    def as_int_prefix(self, nbytes: int = 2) -> int:
        """Big-endian integer of the first `nbytes` (ring partition lookup)."""
        return int.from_bytes(self[:nbytes], "big")

    def __repr__(self) -> str:  # pragma: no cover
        return f"FixedBytes32({self.hex()[:16]}…)"


# In the reference Uuid and Hash are both aliases of FixedBytes32 (data.rs:96-104).
Uuid = FixedBytes32
Hash = FixedBytes32


def sha256sum(data: bytes) -> Hash:
    """SHA-256 (ref util/data.rs:106-112): S3 content sha / SigV4."""
    return Hash(hashlib.sha256(data).digest())


def blake2sum(data: bytes) -> Hash:
    """BLAKE2b-256 (ref util/data.rs:117-125): metadata/merkle hash."""
    return Hash(hashlib.blake2b(data, digest_size=32).digest())


def blake2s_sum(data: bytes) -> Hash:
    """BLAKE2s-256: the TPU-native block content hash (framework default)."""
    return Hash(hashlib.blake2s(data, digest_size=32).digest())


def md5sum(data: bytes) -> bytes:
    """MD5 — S3 ETag compatibility only."""
    return hashlib.md5(data, usedforsecurity=False).digest()


def fasthash(data: bytes) -> int:
    """64-bit non-cryptographic hash (ref util/data.rs:131 uses xxh3;
    we use an 8-byte blake2b, stdlib and stable across processes)."""
    return int.from_bytes(hashlib.blake2b(data, digest_size=8).digest(), "big")


def gen_uuid() -> Uuid:
    """Random 32-byte UUID (ref util/data.rs:140-142)."""
    return Uuid(os.urandom(32))


BLOCK_HASH_ALGOS = {
    "blake2s": blake2s_sum,  # TPU-offloadable (default)
    "blake2b": blake2sum,    # reference-compatible
    "sha256": sha256sum,
}


def block_hash(data: bytes, algo: str = "blake2s") -> Hash:
    """Content hash of a data block under the configured algorithm."""
    return BLOCK_HASH_ALGOS[algo](data)
