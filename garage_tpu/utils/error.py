"""Framework error model (ref src/util/error.rs)."""

from __future__ import annotations

import asyncio


class GarageError(Exception):
    """Base error (ref util/error.rs Error enum)."""


class RpcError(GarageError):
    """Remote call failed (ref util/error.rs Error::RemoteError)."""


class QuorumError(RpcError):
    """Quorum not reached (ref util/error.rs Error::Quorum)."""

    def __init__(self, needed: int, got: int, errors: list):
        self.needed, self.got, self.errors = needed, got, list(errors)
        super().__init__(
            f"quorum not reached: {got}/{needed} ok; errors: "
            + "; ".join(str(e) for e in self.errors[:4])
        )


class TimeoutError_(RpcError):
    pass


class ZoneQuorumError(RpcError):
    """Write reached its NUMERIC quorum but the acked replica set does
    not span the number of distinct zones the layout demands (hard
    integer ``zone_redundancy``) and every remaining candidate has
    answered or failed — a whole failure domain is dark.  Typed (and
    wire-coded) so clients and operators can tell "cluster too slow /
    too many nodes down" (QuorumError) from "a zone is gone and the
    layout refuses to ack writes that would not survive losing the
    zones we just wrote to"."""


class DeadlineExceeded(RpcError):
    """The request's end-to-end deadline budget ran out: the client has
    (or is about to have) timed out, so no layer should spend another
    cycle on the request.  Raised locally when a hop's remaining budget
    is below the dispatch floor, by the netapp out-queue when a queued
    request frame expires before reaching the wire, and by the codec
    feeder for expired submissions; wire-coded so a remote hop's verdict
    round-trips typed.  Deliberately NOT a transport error: the peer did
    nothing wrong — it must never feed the circuit breaker or earn a
    retry.  The API layer renders it 503 (the S3 throttle status, with
    Retry-After) so clients back off instead of re-queueing instantly."""

    status = 503          # API rendering (api/common error_response)
    code = "DeadlineExceeded"


class PeerUnavailable(RpcError):
    """Call refused locally: the peer's circuit breaker is open, so
    dispatching would only burn a timeout.  Raised before any bytes hit
    the wire — quorum fan-outs treat it like any other per-node error
    (next candidate launches immediately), and it is deliberately NOT
    retryable (the breaker's cooldown governs when the peer gets its
    next chance)."""


class StorageError(GarageError):
    """Local disk storage failed or the data root is in error-streak
    degraded (read-only) mode.  Carries a structured wire code so a
    write quorum treats the rejection as THIS node's answer (route
    around it, no retry, no breaker feed) — the peer is alive, its disk
    is not."""


class StorageFull(StorageError):
    """Write refused by the free-space watermark preflight (or the disk
    returned ENOSPC): the data root is read-only until space recovers.
    Typed separately from StorageError so operators can tell 'disk
    full' from 'disk dying' in one label."""


class CorruptData(GarageError):
    """Block content does not match its hash (ref util/error.rs CorruptData)."""

    def __init__(self, expected_hash):
        self.expected_hash = expected_hash
        if isinstance(expected_hash, (bytes, bytearray)):
            msg = f"corrupt data for block {bytes(expected_hash).hex()[:16]}"
        else:
            msg = str(expected_hash)
        super().__init__(msg)


class NoSuchBlock(GarageError):
    pass


class DbError(GarageError):
    pass


class LayoutError(GarageError):
    """Invalid cluster layout operation (ref util/error.rs Message variants)."""


# --- wire error codes ------------------------------------------------------
#
# RPC error frames carry a structured code next to the message so (a) the
# client-side error counter can label failures by TYPE, not by unbounded
# message text, and (b) remote domain errors round-trip their class — a
# handler raising NoSuchBlock surfaces as NoSuchBlock at the caller, not
# as an anonymous RpcError string.
#
# Only classes constructible from a single message string participate;
# QuorumError (3-arg) is deliberately absent and falls back to RpcError.

_WIRE_CLASSES = {
    cls.__name__: cls
    for cls in (
        GarageError, RpcError, TimeoutError_, CorruptData, NoSuchBlock,
        DbError, LayoutError, StorageError, StorageFull, ZoneQuorumError,
        DeadlineExceeded,
    )
}
# every timeout flavor emits ONE code, so it must also reconstruct
_WIRE_CLASSES["Timeout"] = TimeoutError_


def error_code(e: BaseException) -> str:
    """Stable wire/metric label for an exception: the class name for
    domain errors, 'Timeout' for EVERY timeout flavor (builtin,
    asyncio — a distinct class until py3.11 — and TimeoutError_), and
    'Internal' for everything else (unbounded foreign types must not
    explode label cardinality).  An error reconstructed from the wire
    keeps its ORIGINAL code, so a remote 'Internal' forwarded across
    hops stays 'Internal' instead of relabeling as the carrier class."""
    rc = getattr(e, "remote_code", None)
    if rc:
        return str(rc)
    if isinstance(e, (TimeoutError, asyncio.TimeoutError, TimeoutError_)):
        return "Timeout"
    if isinstance(e, GarageError):
        return type(e).__name__
    return "Internal"


def remote_error(code, msg) -> GarageError:
    """Reconstruct a remote failure from its wire (code, message) pair.
    Unknown or absent codes degrade to RpcError with the code kept in
    the message."""
    msg = str(msg if msg is not None else "remote error")
    cls = _WIRE_CLASSES.get(code or "")
    if cls is not None:
        err = cls(msg)
    elif code in (None, "", "Internal"):
        err = RpcError(msg)
    else:
        err = RpcError(f"[{code}] {msg}")
    if code:
        err.remote_code = str(code)
    return err
