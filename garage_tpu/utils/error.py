"""Framework error model (ref src/util/error.rs)."""

from __future__ import annotations


class GarageError(Exception):
    """Base error (ref util/error.rs Error enum)."""


class RpcError(GarageError):
    """Remote call failed (ref util/error.rs Error::RemoteError)."""


class QuorumError(RpcError):
    """Quorum not reached (ref util/error.rs Error::Quorum)."""

    def __init__(self, needed: int, got: int, errors: list):
        self.needed, self.got, self.errors = needed, got, list(errors)
        super().__init__(
            f"quorum not reached: {got}/{needed} ok; errors: "
            + "; ".join(str(e) for e in self.errors[:4])
        )


class TimeoutError_(RpcError):
    pass


class CorruptData(GarageError):
    """Block content does not match its hash (ref util/error.rs CorruptData)."""

    def __init__(self, expected_hash):
        self.expected_hash = expected_hash
        if isinstance(expected_hash, (bytes, bytearray)):
            msg = f"corrupt data for block {bytes(expected_hash).hex()[:16]}"
        else:
            msg = str(expected_hash)
        super().__init__(msg)


class NoSuchBlock(GarageError):
    pass


class DbError(GarageError):
    pass


class LayoutError(GarageError):
    """Invalid cluster layout operation (ref util/error.rs Message variants)."""
