"""Lightweight metrics registry — counters, gauges, histograms with
Prometheus text exposition.

Equivalent of the reference's OpenTelemetry metric helpers
(ref util/metrics.rs:8-63) plus the per-layer metric structs
(rpc/metrics.rs:38, table/metrics.rs, block/metrics.rs:7-127,
api/generic_server.rs:63-95).  The reference pushes through the OTel
SDK to its Prometheus exporter; here the registry IS the exporter: every
`System` owns one (`system.metrics`), all layers record into it, and the
admin API renders it (`/metrics`).  No OTel dependency — the sample
model (monotonic counters, label sets, cumulative histogram buckets)
follows the Prometheus exposition format directly.
"""

from __future__ import annotations

import bisect
import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple

# latency buckets (seconds), roughly the OTel default boundaries
DEFAULT_BUCKETS = (
    0.001, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0
)

# histogram exemplars: the max observation per label set is remembered
# for one window, with the trace id that produced it — the link from "a
# p99 bucket grew" to the exact request waterfall to pull
EXEMPLAR_WINDOW_S = 60.0

_trace_id_fn = None


def _current_trace_id():
    """Lazy bridge to utils.tracing.current_trace_id (metrics must not
    import the tracing module at import time — the registry is used by
    bare-library code that never touches spans)."""
    global _trace_id_fn
    if _trace_id_fn is None:
        try:
            from .tracing import current_trace_id as fn
        except Exception:  # pragma: no cover — broken install
            fn = lambda: None  # noqa: E731
        _trace_id_fn = fn
    return _trace_id_fn()


def _fmt_labels(labels: Tuple[Tuple[str, str], ...]) -> str:
    if not labels:
        return ""
    inner = ",".join(f'{k}="{_escape(v)}"' for k, v in labels)
    return "{" + inner + "}"


def _escape(v: str) -> str:
    return str(v).replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


class Counter:
    """Monotonic counter, optionally labelled."""

    def __init__(self, name: str, help: str = ""):
        self.name = name
        self.help = help
        self._vals: Dict[Tuple[Tuple[str, str], ...], float] = {}

    def inc(self, n: float = 1.0, **labels) -> None:
        key = tuple(sorted(labels.items()))
        self._vals[key] = self._vals.get(key, 0.0) + n

    def get(self, **labels) -> float:
        return self._vals.get(tuple(sorted(labels.items())), 0.0)

    def drop_label(self, name: str, value: str) -> None:
        """Remove every series carrying label ``name == value``.  Used
        when a labelled POPULATION member leaves for good (a peer removed
        from the committed layout): its series would otherwise report a
        frozen count forever, indistinguishable from a live-but-quiet
        node."""
        gone = [k for k in self._vals
                if any(ln == name and lv == value for ln, lv in k)]
        for k in gone:
            del self._vals[k]

    def render(self) -> List[str]:
        out = [f"# HELP {self.name} {self.help}", f"# TYPE {self.name} counter"]
        for key, v in sorted(self._vals.items()):
            out.append(f"{self.name}{_fmt_labels(key)} {_num(v)}")
        if not self._vals:
            out.append(f"{self.name} 0")
        return out


class Gauge:
    """Point-in-time value: set directly or observed via a callback at
    render time (the reference's ValueObserver pattern).

    `labeled_fn` is the labelled flavor of `fn`: a callback returning
    an iterable of ({label: value}, sample) pairs, evaluated at render
    time.  It exists for small FIXED populations whose truth lives in
    one object (per-data-root disk state): unlike clear-then-set
    observers it needs no scrape-side refresh hook, so any render() —
    admin /metrics, a test, the CLI — sees current values."""

    def __init__(self, name: str, help: str = "",
                 fn: Optional[Callable[[], float]] = None,
                 labeled_fn: Optional[Callable[[], object]] = None):
        self.name = name
        self.help = help
        self.fn = fn
        self.labeled_fn = labeled_fn
        self._vals: Dict[Tuple[Tuple[str, str], ...], float] = {}

    def set(self, v: float, **labels) -> None:
        self._vals[tuple(sorted(labels.items()))] = v

    def clear(self) -> None:
        """Drop all labelled samples.  Scrape-time observers that mirror a
        MUTABLE population (per-worker, per-peer) clear + repopulate so a
        reaped worker's series disappears instead of freezing at its last
        value."""
        self._vals.clear()

    def render(self) -> List[str]:
        out = [f"# HELP {self.name} {self.help}", f"# TYPE {self.name} gauge"]
        if self.fn is not None:
            try:
                out.append(f"{self.name} {_num(self.fn())}")
            except Exception:  # noqa: BLE001 — observers must never break scrape
                pass
        if self.labeled_fn is not None:
            try:
                samples = sorted(
                    (tuple(sorted(labels.items())), v)
                    for labels, v in self.labeled_fn()
                )
            except Exception:  # noqa: BLE001 — observers must never break scrape
                samples = []
            for key, v in samples:
                out.append(f"{self.name}{_fmt_labels(key)} {_num(v)}")
        for key, v in sorted(self._vals.items()):
            out.append(f"{self.name}{_fmt_labels(key)} {_num(v)}")
        return out


class Histogram:
    """Cumulative-bucket histogram (Prometheus semantics).

    With ``exemplars=True`` each label set remembers the trace id of its
    max-value observation per EXEMPLAR_WINDOW_S window (the trace id is
    read from the task-local tracing context unless the caller passes
    ``trace_exemplar=``).  The plain text render stays untouched
    (promlint-clean); ``render(openmetrics=True)`` appends the
    OpenMetrics ``# {trace_id="…"} value ts`` exemplar on the holding
    bucket line, and ``exemplar_snapshot()`` serves them to the admin
    CLI."""

    def __init__(self, name: str, help: str = "",
                 buckets: Sequence[float] = DEFAULT_BUCKETS,
                 exemplars: bool = False):
        self.name = name
        self.help = help
        self.buckets = tuple(buckets)
        self.exemplars = bool(exemplars)
        # labels -> [bucket counts..., +inf count, sum, count]
        self._vals: Dict[Tuple[Tuple[str, str], ...], list] = {}
        # labels -> [window_start, value, bucket_index, trace_id, ts]
        self._exemplars: Dict[Tuple[Tuple[str, str], ...], list] = {}

    def observe(self, v: float, trace_exemplar: Optional[str] = None,
                **labels) -> None:
        key = tuple(sorted(labels.items()))
        slot = self._vals.get(key)
        if slot is None:
            slot = [0] * (len(self.buckets) + 1) + [0.0, 0]
            self._vals[key] = slot
        i = bisect.bisect_left(self.buckets, v)
        slot[i] += 1
        slot[-2] += v
        slot[-1] += 1
        if self.exemplars:
            tid = (trace_exemplar if trace_exemplar is not None
                   else _current_trace_id())
            if tid:
                now = time.time()
                ex = self._exemplars.get(key)
                if (ex is None or now - ex[0] > EXEMPLAR_WINDOW_S
                        or v >= ex[1]):
                    start = (now if ex is None
                             or now - ex[0] > EXEMPLAR_WINDOW_S else ex[0])
                    self._exemplars[key] = [start, v, i, tid, now]

    def exemplar_snapshot(self) -> list:
        """[{labels, value, trace_id, ts}] — current-window max-bucket
        exemplars for every label set."""
        out = []
        for key, (_w, v, _i, tid, ts) in sorted(self._exemplars.items()):
            out.append({"labels": dict(key), "value": round(v, 6),
                        "trace_id": tid, "ts": round(ts, 3)})
        return out

    def time(self, **labels):
        """Context manager recording elapsed seconds (the reference's
        RecordDuration combinator, util/metrics.rs:8-57)."""
        return _Timer(self, labels)

    def quantile(self, q: float, min_count: int = 1,
                 **labels) -> Optional[float]:
        """Estimate the q-quantile for one label set by linear
        interpolation inside the holding bucket (the same estimate
        Prometheus' histogram_quantile() makes at query time — the hedge
        trigger reuses it process-side).  None when the label set has
        fewer than min_count observations; observations in the +Inf
        bucket clamp to the last finite bucket edge."""
        slot = self._vals.get(tuple(sorted(labels.items())))
        if slot is None or slot[-1] < min_count:
            return None
        target = q * slot[-1]
        cum = 0.0
        lo = 0.0
        for i, edge in enumerate(self.buckets):
            c = slot[i]
            if cum + c >= target:
                if c <= 0:
                    return lo
                return lo + (edge - lo) * (target - cum) / c
            cum += c
            lo = edge
        return self.buckets[-1]

    def render(self, openmetrics: bool = False) -> List[str]:
        out = [f"# HELP {self.name} {self.help}",
               f"# TYPE {self.name} histogram"]
        for key, slot in sorted(self._vals.items()):
            ex = self._exemplars.get(key) if openmetrics else None
            cum = 0
            for i, b in enumerate(self.buckets):
                cum += slot[i]
                lab = key + (("le", _num(b)),)
                line = f"{self.name}_bucket{_fmt_labels(lab)} {cum}"
                if ex is not None and ex[2] == i:
                    line += (f' # {{trace_id="{ex[3]}"}} {_num(ex[1])} '
                             f"{ex[4]:.3f}")
                out.append(line)
            cum += slot[len(self.buckets)]
            lab = key + (("le", "+Inf"),)
            line = f"{self.name}_bucket{_fmt_labels(lab)} {cum}"
            if ex is not None and ex[2] == len(self.buckets):
                line += (f' # {{trace_id="{ex[3]}"}} {_num(ex[1])} '
                         f"{ex[4]:.3f}")
            out.append(line)
            out.append(f"{self.name}_sum{_fmt_labels(key)} {_num(slot[-2])}")
            out.append(f"{self.name}_count{_fmt_labels(key)} {slot[-1]}")
        return out


class _Timer:
    def __init__(self, hist: Histogram, labels: dict):
        self.hist = hist
        self.labels = labels

    def __enter__(self):
        self.t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self.hist.observe(time.perf_counter() - self.t0, **self.labels)
        return False


def _num(v) -> str:
    f = float(v)
    return str(int(f)) if f.is_integer() else repr(f)


class _NullTimer:
    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_TIMER = _NullTimer()


def maybe_time(hist: Optional[Histogram], **labels):
    """Histogram timer, or a no-op context when the layer runs without a
    metrics registry (tests, bare library use) — keeps hot paths free of
    per-call conditionals."""
    return hist.time(**labels) if hist is not None else _NULL_TIMER


class MetricsRegistry:
    """One per System; layers create their metrics through it and the
    admin endpoint renders everything.

    Families are deduplicated BY NAME: two components asking for the same
    family name share one metric object (several Table instances all
    record into `table_merge_duration_seconds` with their own labels),
    and the exposition never emits duplicate `# TYPE` blocks — Prometheus
    rejects those at scrape time.  Asking for an existing name with a
    different metric type is a programming error and raises."""

    def __init__(self):
        self._metrics: List[object] = []
        self._by_name: Dict[str, object] = {}

    def _get_or_create(self, cls, name: str, *args):
        m = self._by_name.get(name)
        if m is not None:
            if not isinstance(m, cls):
                raise ValueError(
                    f"metric {name!r} already registered as "
                    f"{type(m).__name__}, requested {cls.__name__}"
                )
            return m
        m = cls(name, *args)
        self._by_name[name] = m
        self._metrics.append(m)
        return m

    def counter(self, name: str, help: str = "") -> Counter:
        return self._get_or_create(Counter, name, help)

    def gauge(self, name: str, help: str = "",
              fn: Optional[Callable[[], float]] = None,
              labeled_fn: Optional[Callable[[], object]] = None) -> Gauge:
        """A second registration of an existing gauge may not pass a
        DIFFERENT observer callback: the first registration's `fn` used to
        win silently, which turned a double-construction bug (two
        components observing through dead instances) into wrong metrics
        instead of a crash.  Per-instance values must use labelled
        `set()`; re-requesting an existing gauge without an observer
        stays valid (that is the sharing path).  The same rule covers
        `labeled_fn` (render-time labelled observers)."""
        m = self._by_name.get(name)
        if m is not None and fn is not None and getattr(m, "fn", None) is not fn:
            raise ValueError(
                f"gauge {name!r} already registered"
                + (" with a different observer callback"
                   if getattr(m, "fn", None) is not None else "")
                + "; a second fn= observer would be silently ignored"
            )
        if (m is not None and labeled_fn is not None
                and getattr(m, "labeled_fn", None) is not labeled_fn):
            raise ValueError(
                f"gauge {name!r} already registered; a second labeled_fn= "
                f"observer would be silently ignored"
            )
        return self._get_or_create(Gauge, name, help, fn, labeled_fn)

    def histogram(self, name: str, help: str = "",
                  buckets: Sequence[float] = DEFAULT_BUCKETS,
                  exemplars: bool = False) -> Histogram:
        return self._get_or_create(Histogram, name, help, buckets, exemplars)

    def render(self, openmetrics: bool = False) -> str:
        """Prometheus text exposition; ``openmetrics=True`` additionally
        appends histogram exemplars (`# {trace_id=…} v ts` bucket-line
        suffixes) — serve that flavor only to scrapers that negotiated
        the OpenMetrics content type (the plain format's parsers reject
        the suffix)."""
        lines: List[str] = []
        for m in self._metrics:
            if openmetrics and isinstance(m, Histogram):
                lines.extend(m.render(openmetrics=True))
            else:
                lines.extend(m.render())
        return "\n".join(lines) + "\n"
