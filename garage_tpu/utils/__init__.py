"""L1 foundation utilities (ref: src/util)."""
