"""Versioned on-disk/on-wire serialization.

Equivalent of reference src/util/migrate.rs:4-37: every persisted value is
encoded as ``VERSION_MARKER + msgpack(body)``.  Decoding tries the current
version's marker first; on mismatch it decodes as the previous version and
migrates forward recursively, so any historical byte string remains readable
(`Migrate::decode`, migrate.rs:22-33).

Usage::

    class ThingV1(Migrated):
        VERSION_MARKER = b"G1thing"
        @classmethod
        def from_fields(cls, d): ...
        def fields(self): ...

    class ThingV2(Migrated):
        VERSION_MARKER = b"G2thing"
        PREVIOUS = ThingV1
        @classmethod
        def migrate(cls, old: ThingV1) -> "ThingV2": ...
"""

from __future__ import annotations

from typing import Any, ClassVar, Optional, Type

import msgpack


def pack(obj: Any) -> bytes:
    """msgpack encode (ref util/encode.rs nonversioned_encode)."""
    return msgpack.packb(obj, use_bin_type=True)


def unpack(data: bytes) -> Any:
    return msgpack.unpackb(data, raw=False, strict_map_key=False)


class DecodeError(Exception):
    pass


class Migrated:
    """A value with a versioned serialized form (ref util/migrate.rs:4-43)."""

    VERSION_MARKER: ClassVar[bytes] = b""
    PREVIOUS: ClassVar[Optional[Type["Migrated"]]] = None

    # --- subclass interface ---
    def fields(self) -> Any:
        """Return msgpack-encodable body."""
        raise NotImplementedError

    @classmethod
    def from_fields(cls, body: Any) -> "Migrated":
        raise NotImplementedError

    @classmethod
    def migrate(cls, old: "Migrated") -> "Migrated":
        """Convert an instance of PREVIOUS into this version."""
        raise NotImplementedError

    # --- engine (ref migrate.rs:22-37) ---
    def encode(self) -> bytes:
        assert self.VERSION_MARKER, f"{type(self).__name__} has no VERSION_MARKER"
        return self.VERSION_MARKER + pack(self.fields())

    @classmethod
    def decode(cls, data: bytes) -> "Migrated":
        if cls.VERSION_MARKER and data.startswith(cls.VERSION_MARKER):
            try:
                return cls.from_fields(unpack(data[len(cls.VERSION_MARKER):]))
            except Exception as e:
                raise DecodeError(
                    f"{cls.__name__}: marker matched but body undecodable: {e}"
                ) from e
        if cls.PREVIOUS is not None:
            old = cls.PREVIOUS.decode(data)
            return cls.migrate(old)
        raise DecodeError(
            f"{cls.__name__}: unrecognized version marker {data[:16]!r}"
        )
