"""Test/bench harnesses that ship in-tree: fault injection and WAN
emulation.  These are not daemon code paths — they drive the product
from outside — but they live in the package so tests, bench.py, and
operator tooling share one implementation (the reference keeps its
equivalents in tests/common/ and external mknet configs)."""
